"""Hierarchical KV page tiering: HBM → host DRAM → disk.

The radix prefix cache (prefix_cache.py) lives entirely in HBM, so
under fleet pressure eviction is the common case and the hit rate
collapses exactly when sharing matters most.  This module adds two
colder tiers BEHIND the cache without touching its hot path:

- **host-DRAM spill**: when the cache evicts a rider-free leaf, the
  engine gathers that page's KV rows out of the pool (one jitted
  ``dynamic_slice`` — the result aliases nothing, so the pool page is
  released immediately) and hands the device blocks to this store.  A
  dedicated COPIER THREAD performs the device→host download off the
  drive tick, stamps a sha256 over the payload, and parks it in a
  byte-bounded LRU dict.  The handoff queue is bounded: a slow host
  path drops spills (counted) instead of wedging the tick.
- **disk**: at graceful drain the session dumps every warm page (still
  resident or already spilled) into a sidecar directory next to the
  warm-state snapshot; the v2 snapshot carries per-page refs (key,
  file, sha256), so a restart — or an autoscaler scale-up booting from
  a sibling's snapshot — promotes real KV bytes instead of replaying
  prefill per chain.

**Promotion** happens in ``submit_request``/``rewarm``: after the radix
cache inserts new pages for a prompt, the engine asks this store for
the longest promotable run of them, verifies each payload's sha256,
and scatters it back into the pool (one jitted ``dynamic_update_slice``
per page).  Promotion is pure byte movement — a promoted page serves
EXACTLY what the resident page would have — which is the whole
eval-harness contract: a tier must never change an answer.

**Degrade ladder** (typed, counted, evented — never a crash, never
wrong KV): checksum mismatch → :class:`TierIntegrityError`; tier I/O
error → :class:`TierIOError` (disk reads retry under a small
``RetryPolicy`` first); promotion past the deadline →
:class:`TierTimeoutError`.  Every rung drops the tier entry and the
engine recomputes the page from its token chain via the existing
prefill path (``reval_kvtier_recomputes_total``).

Keys are sha256 over the ENTIRE root→page token chain, not the page's
own tokens: a page's KV depends on its full attention prefix, so two
pages with identical tokens under different prefixes must never alias.

Single-owner on the driver side (lookup/fetch/promote run on the
engine's driver thread, like the runtime); the copier thread is the one
concurrent writer, and every shared field is guarded by ``_cv``
(audited — analysis/lockcheck.py).  This module stays jax-free: device
blocks pass through opaquely and the only device→host transfer is the
copier's marked download (the hostsync pass keeps it honest).
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass

import numpy as np

try:                # registers "bfloat16"/"float8_*" with np.dtype —
    import ml_dtypes  # noqa: F401 — disk entries round-trip raw bytes
except ImportError:  # pragma: no cover — jax always ships ml_dtypes
    pass

from ...env import env_flag, env_float, env_int
from ...obs import metrics as obs_metrics
from ...obs.logging import log_event
from ...resilience.retry import RetryPolicy

__all__ = ["TieredPageStore", "TierEntry", "TierError",
           "TierIntegrityError", "TierIOError", "TierTimeoutError",
           "chain_key"]


class TierError(Exception):
    """Base of the typed degrade ladder; ``reason`` names the rung in
    ``kvtier.degrade`` events and per-rung counters."""

    reason = "error"


class TierIntegrityError(TierError):
    """The payload's sha256 no longer matches the checksum stamped at
    spill — bit rot, a torn write, or injected corruption.  Serving it
    would be WRONG KV; the only correct move is recompute."""

    reason = "integrity"


class TierIOError(TierError):
    """The tier could not produce the payload at all (dead disk file,
    exhausted host mapping, injected fail-tier fault)."""

    reason = "io"


class TierTimeoutError(TierError):
    """The fetch outlived the promotion deadline — recompute is faster
    than waiting on a wedged host path."""

    reason = "timeout"


def chain_key(tokens) -> str:
    """sha256 over the full root→page token chain (int32 bytes).  The
    chain — not the page's own tokens — is the identity: KV rows encode
    attention over the ENTIRE prefix."""
    return hashlib.sha256(
        np.array(list(tokens), np.int32).tobytes()).hexdigest()


def _payload_checksum(payload) -> str:
    h = hashlib.sha256()
    for arr in payload:
        h.update(arr.tobytes())
    return h.hexdigest()


@dataclass
class TierEntry:
    """One spilled page.  ``payload`` is the host copy (a list of numpy
    blocks in pool order: k per layer, v per layer, then scales for an
    int8 pool) or None for a disk-only entry hydrated from a snapshot;
    ``checksum`` is sha256 over the concatenated payload bytes, stamped
    at spill and verified at every promotion."""

    key: str
    checksum: str
    nbytes: int
    payload: list | None = None
    path: str | None = None
    tier: str = "host"                 # "host" | "disk"


class TieredPageStore:
    """See module docstring.  ``stats`` is a zero-arg callable returning
    the engine's live ``EngineStats`` (engines swap their stats object
    between bench passes — same convention as the prefix cache);
    ``chaos`` an optional :class:`~reval_tpu.resilience.TierChaos`."""

    def __init__(self, page_size: int, *, host_mb: int | None = None,
                 queue_cap: int | None = None,
                 timeout_s: float | None = None, stats=None, chaos=None,
                 start_copier: bool = True):
        self.page = int(page_size)
        self.host_bound = (env_int("REVAL_TPU_KVTIER_HOST_MB", 256)
                           if host_mb is None else int(host_mb)) << 20
        self.queue_cap = (env_int("REVAL_TPU_KVTIER_QUEUE", 64)
                          if queue_cap is None else int(queue_cap))
        self.timeout_s = (env_float("REVAL_TPU_KVTIER_TIMEOUT_S", 5.0)
                          if timeout_s is None else float(timeout_s))
        self._stats = stats if stats is not None else lambda: None
        self.chaos = chaos
        #: disk reads get a second chance before the I/O rung fires —
        #: transient NFS/page-cache hiccups are not a reason to recompute
        self._disk_retry = RetryPolicy(max_attempts=2, base_delay=0.02,
                                       max_delay=0.1,
                                       retryable=lambda e: isinstance(
                                           e, OSError))
        # ONE lock for the whole store: the Condition doubles as the
        # mutex (the copier waits on it, the driver notifies through it)
        self._cv = threading.Condition()
        # key → entry, LRU order (move_to_end on touch); the copier
        # inserts, the driver looks up/fetches/drops
        self._entries: OrderedDict[str, TierEntry] = OrderedDict()  # guarded-by: _cv
        self._queue: deque = deque()    # guarded-by: _cv
        self._stop = False              # guarded-by: _cv
        # gauges: single-writer-under-lock, lock-free scalar reads are
        # deliberate (counters()/_publish_gauges read a point value)
        self.host_bytes = 0             # guarded-by: _cv (writes)
        self.host_pages = 0             # guarded-by: _cv (writes)
        self.disk_pages = 0             # guarded-by: _cv (writes)
        self.queue_depth = 0            # guarded-by: _cv (writes)
        self._copier: threading.Thread | None = None
        if start_copier:
            self._copier = threading.Thread(target=self._copy_loop,
                                            daemon=True,
                                            name="kvtier-copier")
            self._copier.start()

    # -- spill (driver side: enqueue only, never block) ---------------------
    def spill(self, tokens, blocks) -> bool:
        """Hand one evicted page's device blocks to the copier.  Bounded
        backpressure: a full queue DROPS the spill (counted) — the drive
        tick must never wait on the host path."""
        key = chain_key(tokens)
        stats = self._stats()
        with self._cv:
            if self._stop:
                return False
            if key in self._entries:
                self._entries.move_to_end(key)
                return False            # already warm in a colder tier
            if len(self._queue) >= self.queue_cap:
                if stats is not None:
                    stats.kvtier_spill_drops += 1
                return False
            self._queue.append((key, blocks))
            depth = self.queue_depth = len(self._queue)
            self._cv.notify()
        if stats is not None:
            stats.registry.gauge(obs_metrics.KVTIER_QUEUE_DEPTH).set(depth)
        return True

    # -- the copier thread --------------------------------------------------
    def _download(self, blocks) -> list[np.ndarray]:  # hot-path
        """The ONE device→host transfer of the spill path — on the
        copier thread, never the drive tick."""
        # host-sync: the copier's deliberate page download; this thread
        # exists so the drive tick never pays this transfer
        return [np.asarray(b) for b in blocks]

    def _copy_loop(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._stop:
                    self._cv.wait(0.2)
                if not self._queue:
                    if self._stop:
                        return
                    continue
                key, blocks = self._queue.popleft()
                depth = self.queue_depth = len(self._queue)
            stats = self._stats()
            try:
                payload = self._download(blocks)
                entry = TierEntry(key=key,
                                  checksum=_payload_checksum(payload),
                                  nbytes=sum(a.nbytes for a in payload),
                                  payload=payload, tier="host")
            except Exception as exc:    # noqa: BLE001 — a failed copy
                # loses warmth, never correctness (the page was evicted
                # either way); counted + evented, the loop keeps draining
                log_event("kvtier.spill_error", level="warning",
                          key=key[:12], exc=exc)
                if stats is not None:
                    stats.kvtier_spill_errors += 1
                continue
            with self._cv:
                if self._stop:
                    return
                self._entries[key] = entry
                self._entries.move_to_end(key)
                self.host_bytes += entry.nbytes
                self.host_pages += 1
                evicted = self._enforce_host_bound_locked()
            if stats is not None:
                stats.kvtier_spills += 1
                if evicted:
                    stats.kvtier_host_evictions += evicted
                reg = stats.registry
                reg.gauge(obs_metrics.KVTIER_QUEUE_DEPTH).set(depth)
                self._publish_gauges(reg)

    def _enforce_host_bound_locked(self) -> int:  # lock-held: _cv
        """LRU-drop host payloads past the byte bound; disk-backed
        entries demote to path-only (their bytes live on disk), bare
        host entries drop entirely.  Returns payloads evicted."""
        evicted = 0
        while self.host_bytes > self.host_bound:
            victim = None
            for key, entry in self._entries.items():
                if entry.payload is not None:
                    victim = (key, entry)
                    break
            if victim is None:
                break
            key, entry = victim
            self.host_bytes -= entry.nbytes
            self.host_pages -= 1
            evicted += 1
            if entry.path is not None:
                entry.payload = None
                entry.tier = "disk"
                self._entries.move_to_end(key)
            else:
                del self._entries[key]
        return evicted

    def _publish_gauges(self, reg) -> None:
        reg.gauge(obs_metrics.KVTIER_HOST_PAGES).set(self.host_pages)
        reg.gauge(obs_metrics.KVTIER_HOST_BYTES).set(self.host_bytes)
        reg.gauge(obs_metrics.KVTIER_DISK_PAGES).set(self.disk_pages)

    # -- promotion (driver side) --------------------------------------------
    def lookup(self, tokens) -> TierEntry | None:
        """The tier entry covering ``tokens`` (a full root→page chain),
        or None.  Touches LRU; never blocks on the copier beyond the
        dict lock."""
        key = chain_key(tokens)
        with self._cv:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
        return entry

    def fetch(self, entry: TierEntry) -> list[np.ndarray]:
        """The verified payload for one promotion, or a typed
        :class:`TierError`.  Applies the chaos schedule, enforces the
        promotion deadline, and ALWAYS re-verifies the sha256 stamped at
        spill — a tier must never serve bytes it cannot prove."""
        t0 = time.monotonic()
        mode = self.chaos.draw(entry.key) if self.chaos is not None else None
        if mode == "fail":
            raise TierIOError(f"chaos: injected {entry.tier}-tier I/O "
                              f"failure for page {entry.key[:12]}")
        if mode == "stall":
            self.chaos.sleep(self.chaos.stall_s)
        payload = entry.payload
        if payload is None:
            if entry.path is None:
                raise TierIOError(f"page {entry.key[:12]} has neither a "
                                  f"host payload nor a disk file")
            try:
                payload = self._disk_retry.call(
                    lambda: _read_page_file(entry.path),
                    label=f"kvtier:{entry.key[:12]}")
            except Exception as exc:
                raise TierIOError(f"disk tier read failed for page "
                                  f"{entry.key[:12]}: {exc}") from exc
        if mode == "corrupt":
            payload = [a.copy() for a in payload]
            flat = payload[0].view(np.uint8).reshape(-1)
            flat[0] ^= 0xFF
        if _payload_checksum(payload) != entry.checksum:
            raise TierIntegrityError(f"checksum mismatch on page "
                                     f"{entry.key[:12]} ({entry.tier} tier)")
        if time.monotonic() - t0 > self.timeout_s:
            raise TierTimeoutError(f"promotion of page {entry.key[:12]} "
                                   f"outlived the {self.timeout_s}s deadline")
        return payload

    def drop(self, key: str) -> None:
        """Degrade-ladder removal: the entry failed its promotion, so it
        must never be offered again (recompute re-spills a good copy on
        the next eviction)."""
        with self._cv:
            entry = self._entries.pop(key, None)
            if entry is None:
                return
            if entry.payload is not None:
                self.host_bytes -= entry.nbytes
                self.host_pages -= 1
            else:
                self.disk_pages -= 1

    # -- disk tier (snapshot v2 sidecar) ------------------------------------
    def put_host(self, tokens, payload: list[np.ndarray]) -> TierEntry:
        """Driver-side synchronous insert (the drain path dumps resident
        pages through here — no copier race on a quiescent engine)."""
        key = chain_key(tokens)
        entry = TierEntry(key=key, checksum=_payload_checksum(payload),
                          nbytes=sum(a.nbytes for a in payload),
                          payload=payload, tier="host")
        with self._cv:
            old = self._entries.pop(key, None)
            if old is not None and old.payload is not None:
                self.host_bytes -= old.nbytes
                self.host_pages -= 1
            elif old is not None:
                self.disk_pages -= 1
            self._entries[key] = entry
            self.host_bytes += entry.nbytes
            self.host_pages += 1
            self._enforce_host_bound_locked()
        return entry

    def write_disk(self, dir_path: str) -> list[dict]:
        """Write every host-resident payload as one page file under
        ``dir_path`` and return snapshot refs (key/file/sha256/bytes).
        A page that fails to write is skipped with a ``kvtier.disk_error``
        warning — the drain finishes regardless."""
        os.makedirs(dir_path, exist_ok=True)
        with self._cv:
            entries = [e for e in self._entries.values()
                       if e.payload is not None]
        refs: list[dict] = []
        for entry in entries:
            fname = f"{entry.key}.kvpage"
            try:
                _write_page_file(os.path.join(dir_path, fname),
                                 entry.payload, entry.checksum)
            except OSError as exc:
                log_event("kvtier.disk_error", level="warning",
                          where="write", key=entry.key[:12], exc=exc)
                continue
            entry.path = os.path.join(dir_path, fname)
            refs.append({"key": entry.key, "file": fname,
                         "sha256": entry.checksum,
                         "nbytes": entry.nbytes})
        return refs

    def attach_disk(self, refs: list[dict], dir_path: str) -> int:
        """Hydrate disk-tier entries from snapshot refs (payload stays
        on disk until promotion).  Garbage refs are skipped — a bad
        snapshot degrades to the chain-replay path, never a wedged
        boot.  Returns entries attached."""
        attached = 0
        for ref in refs or []:
            if not isinstance(ref, dict):
                continue
            key, fname = ref.get("key"), ref.get("file")
            sha, nbytes = ref.get("sha256"), ref.get("nbytes")
            if not (isinstance(key, str) and isinstance(fname, str)
                    and isinstance(sha, str)):
                continue
            path = os.path.join(dir_path, os.path.basename(fname))
            entry = TierEntry(key=key, checksum=sha,
                              nbytes=int(nbytes or 0), payload=None,
                              path=path, tier="disk")
            with self._cv:
                if key in self._entries:
                    continue
                self._entries[key] = entry
                self.disk_pages += 1
            attached += 1
        stats = self._stats()
        if stats is not None:
            self._publish_gauges(stats.registry)
        return attached

    # -- lifecycle / gauges --------------------------------------------------
    def drain(self, timeout_s: float = 5.0) -> bool:
        """Wait for the copier to finish the queued spills (tests and
        the drain path); True when the queue emptied in time."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._cv:
                if not self._queue:
                    return True
            time.sleep(0.005)
        return False

    def close(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        if self._copier is not None:
            self._copier.join(timeout=5)
            self._copier = None
        with self._cv:
            self._entries.clear()
            self._queue.clear()
            self.host_bytes = self.host_pages = self.disk_pages = 0
            self.queue_depth = 0

    def counters(self) -> dict:
        """Gauge snapshot (counters live on the engine's EngineStats —
        same split as the prefix cache)."""
        with self._cv:
            return {"host_pages": self.host_pages,
                    "host_bytes": self.host_bytes,
                    "disk_pages": self.disk_pages,
                    "queue_depth": self.queue_depth}


def default_tiering_enabled(flag: bool | None) -> bool:
    """The master switch: an explicit ctor value wins, else
    ``REVAL_TPU_KVTIER`` (default on — spill/promote only ever run at
    eviction and insert, so the resident hot path is unchanged)."""
    return env_flag("REVAL_TPU_KVTIER", True) if flag is None else bool(flag)


# -- page files (the disk tier's on-disk shape) ------------------------------
#
# One page per file: a JSON header (block shapes/dtypes + the spill-time
# sha256) length-prefixed before the concatenated raw array bytes.  Raw
# bytes, not npz: bfloat16 round-trips exactly (ml_dtypes names the
# dtype) and verification hashes the SAME bytes the host tier hashed.

_PAGE_MAGIC = b"RVKV"


def _write_page_file(path: str, payload: list[np.ndarray],
                     checksum: str) -> None:
    header = json.dumps({
        "sha256": checksum,
        "blocks": [{"shape": list(a.shape), "dtype": str(a.dtype)}
                   for a in payload]}).encode()
    tmp = f"{path}.tmp"
    with open(tmp, "wb") as f:
        f.write(_PAGE_MAGIC)
        f.write(len(header).to_bytes(4, "little"))
        f.write(header)
        for arr in payload:
            f.write(arr.tobytes())
    os.replace(tmp, path)


def _read_page_file(path: str) -> list[np.ndarray]:
    with open(path, "rb") as f:
        magic = f.read(4)
        if magic != _PAGE_MAGIC:
            raise OSError(f"{path}: not a kv page file")
        n = int.from_bytes(f.read(4), "little")
        try:
            header = json.loads(f.read(n))
            blocks = header["blocks"]
        except Exception as exc:
            raise OSError(f"{path}: corrupt page header: {exc}") from exc
        out = []
        for spec in blocks:
            dtype = np.dtype(spec["dtype"])
            shape = tuple(int(d) for d in spec["shape"])
            want = dtype.itemsize * int(np.prod(shape))
            raw = f.read(want)
            if len(raw) != want:
                raise OSError(f"{path}: truncated page payload")
            out.append(np.frombuffer(raw, dtype=dtype).reshape(shape))
    return out
