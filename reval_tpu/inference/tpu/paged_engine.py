"""PagedTPUEngine: continuous batching over a paged KV cache.

The throughput engine (SURVEY.md §7 steps 4-5).  Where ``TPUEngine`` runs
static batches — every sequence in a batch prefills together and the batch
ends when its *slowest* member stops — this engine keeps a fixed set of
decode slots fed from an admission queue:

- the **native scheduler** (reval_tpu.runtime, C++) owns pages and slots:
  FCFS admission with a one-page decode watermark, lazy page allocation as
  sequences grow, recompute-style preemption on pool exhaustion;
- **prefill** runs per admitted sequence through the contiguous
  left-padded path (already MXU-shaped), bucketed to a power-of-two page
  count, then commits its KV into the allocated pages (models/paged.py);
- **decode** runs all slots every step through the Pallas paged-attention
  kernel, a jitted ``lax.scan`` chunk at a time; finished sequences free
  their slot at the next chunk boundary and a waiting request takes it.

The result: short answers ([ANSWER] NO, 2 tokens) stop occupying a slot
the moment they finish instead of padding out to the batch's longest
member — exactly the fan-out shape of DREval probe prompts.

Sharding: tensor parallelism only (params + KV heads over ``tp``); data
parallelism for paged decode is one engine replica per host/dp-group
(fleet replicate mode), because the page pool is batch-global state.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ...models import ModelConfig, init_kv_cache, load_checkpoint, prefill
from ...models.paged import commit_prefill, init_paged_cache, paged_decode_step
from ...runtime import PagedRuntime
from .engine import EngineStats, truncate_at_stop
from .sampling import sample_token
from .tokenizer import HFTokenizer

__all__ = ["PagedTPUEngine"]

CHUNK = 8  # decode steps per host sync (stop-string check cadence)


def _pow2_pages(n: int) -> int:
    b = 1
    while b < n:
        b *= 2
    return b


@dataclass
class _Request:
    index: int                   # position in the caller's prompt list
    ids: list[int]
    max_new: int
    generated: list[int] = field(default_factory=list)
    done: bool = False


class PagedTPUEngine:
    def __init__(self, params, cfg: ModelConfig, tokenizer, *,
                 max_slots: int = 8, page_size: int = 128,
                 max_seq_len: int = 8192, num_pages: int | None = None,
                 mesh=None, seed: int = 0):
        assert max_seq_len % page_size == 0
        self.cfg = cfg
        self.tokenizer = tokenizer
        self.max_slots = max_slots
        self.page_size = page_size
        self.max_pages_per_seq = max_seq_len // page_size
        # default pool: every slot can reach max_seq_len (no oversubscription;
        # pass a smaller num_pages to trade HBM for preemption risk)
        self.num_pages = (num_pages if num_pages is not None
                          else 1 + max_slots * self.max_pages_per_seq)
        self.mesh = mesh
        self.stats = EngineStats()
        self._key = jax.random.PRNGKey(seed)
        self.params = params
        dtype = params["embed"].dtype
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ...parallel import shard_params
            from ...parallel.sharding import paged_cache_spec

            self.params = shard_params(params, cfg, mesh)
            self._cache_sharding = NamedSharding(mesh, paged_cache_spec(cfg, mesh))
            self._replicated = NamedSharding(mesh, P())
        else:
            self._cache_sharding = None
            self._replicated = None
        self.rt = PagedRuntime(self.num_pages, page_size, max_slots,
                               self.max_pages_per_seq)
        self.cache = init_paged_cache(cfg, self.num_pages, page_size, dtype=dtype)
        if self._cache_sharding is not None:
            self.cache = type(self.cache)(
                *(jax.device_put(c, self._cache_sharding) for c in self.cache))
        self._jit_prefill = jax.jit(partial(prefill, cfg=cfg))
        self._jit_commit = jax.jit(commit_prefill, donate_argnums=(0,))
        self._jit_chunk = jax.jit(
            partial(self._decode_chunk, cfg=cfg), static_argnames=("steps",),
            donate_argnames=("cache",))

    @classmethod
    def from_pretrained(cls, model_path: str, *, dtype: str = "bfloat16",
                        tp_size: int = 1, max_slots: int = 8,
                        page_size: int = 128, max_seq_len: int = 8192,
                        num_pages: int | None = None, tokenizer=None,
                        seed: int = 0,
                        local_devices_only: bool = False) -> "PagedTPUEngine":
        params, cfg = load_checkpoint(model_path, dtype=dtype)
        if tokenizer is None:
            tokenizer = HFTokenizer(model_path)
        mesh = None
        if tp_size > 1:
            from ...parallel import make_mesh

            devices = jax.local_devices() if local_devices_only else None
            mesh = make_mesh(tp=tp_size, devices=devices)
        return cls(params, cfg, tokenizer, max_slots=max_slots,
                   page_size=page_size, max_seq_len=max_seq_len,
                   num_pages=num_pages, mesh=mesh, seed=seed)

    def close(self) -> None:
        if self.rt is not None:
            self.rt.close()
            self.rt = None

    # -- jitted pieces -----------------------------------------------------
    @staticmethod
    def _decode_chunk(params, first_token, block_tables, seq_lens, cache,
                      temperature, key, *, cfg: ModelConfig, steps: int):
        """``steps`` paged decode iterations for the whole slot batch."""

        def body(carry, _):
            token, cache, lens, key = carry
            logits, cache = paged_decode_step(params, cfg, token, block_tables,
                                              lens, cache)
            key, sub = jax.random.split(key)
            nxt = sample_token(logits, temperature, sub)
            return (nxt[:, None], cache, lens + 1, key), nxt

        (last, cache, _, _), toks = jax.lax.scan(
            body, (first_token, cache, seq_lens, key), None, length=steps)
        return toks.T, cache, last

    def _next_key(self) -> jax.Array:
        self._key, sub = jax.random.split(self._key)
        return sub

    # -- generation --------------------------------------------------------
    def generate(self, prompts: list[str], *, max_new_tokens: int = 256,
                 temperature: float = 0.0, stop: list[str] | None = None) -> list[str]:
        if not prompts:
            return []
        stop = stop or []
        max_len = self.max_pages_per_seq * self.page_size
        limit = max_len - max_new_tokens - 1
        reqs: dict[int, _Request] = {}
        for i, prompt in enumerate(prompts):
            ids = self.tokenizer.encode(prompt)
            if len(ids) > limit:
                ids = ids[-limit:]      # clip from the left, keep the tail
            seq_id = self.rt.submit(len(ids), max_new_tokens)
            reqs[seq_id] = _Request(index=i, ids=ids, max_new=max_new_tokens)

        active: dict[int, int] = {}          # slot -> seq_id
        slot_token = np.zeros((self.max_slots, 1), np.int32)
        temp = jnp.float32(temperature)
        while True:
            for seq_id, slot in self.rt.admit():
                req = reqs[seq_id]
                req.generated = []           # recompute after preemption too
                first = self._prefill_into_pages(req, seq_id, temp)
                req.generated.append(first)
                slot_token[slot] = first
                active[slot] = seq_id
                if self._finished(req, stop):
                    self._retire(req, seq_id, slot, active)
            if not active:
                if any(not r.done for r in reqs.values()):
                    raise RuntimeError(
                        "paged scheduler deadlock: nothing running or admissible")
                break

            # every active sequence must have pages for the whole chunk
            # BEFORE the decode writes into them
            steps = min(CHUNK, min(reqs[s].max_new - len(reqs[s].generated)
                                   for s in active.values()))
            self._reserve_chunk(active, reqs, steps)
            if not active:
                continue                     # everyone got preempted

            tables = np.zeros((self.max_slots, self.max_pages_per_seq), np.int32)
            lens = np.ones(self.max_slots, np.int32)   # idle slots: trash pos 1
            for slot, seq_id in active.items():
                tables[slot] = self.rt.block_table(seq_id)
                req = reqs[seq_id]
                # materialised tokens = prompt + generated minus the pending
                # input token (written during the chunk's first step)
                lens[slot] = len(req.ids) + len(req.generated) - 1
            t0 = time.perf_counter()
            toks, self.cache, last = self._jit_chunk(
                self.params, self._dev(jnp.asarray(slot_token)),
                self._dev(jnp.asarray(tables)), self._dev(jnp.asarray(lens)),
                self.cache, temp, self._next_key(), steps=steps)
            toks_host = np.asarray(toks)
            slot_token = np.array(last)      # copy: host-mutated on admission
            self.stats.decode_seconds += time.perf_counter() - t0
            self.stats.generated_tokens += steps * len(active)

            for slot, seq_id in list(active.items()):
                req = reqs[seq_id]
                req.generated.extend(int(t) for t in toks_host[slot])
                if self._finished(req, stop):
                    self._retire(req, seq_id, slot, active)

        out: list[str] = [""] * len(prompts)
        for req in reqs.values():
            ids = req.generated
            if self.tokenizer.eos_id in ids:
                ids = ids[: ids.index(self.tokenizer.eos_id)]
            out[req.index] = truncate_at_stop(self.tokenizer.decode(ids), stop)
        self.stats.prompts += len(prompts)
        return out

    # -- host-side helpers -------------------------------------------------
    def _dev(self, arr):
        if self._replicated is not None:
            return jax.device_put(arr, self._replicated)
        return arr

    def _finished(self, req: _Request, stop: list[str]) -> bool:
        if len(req.generated) >= req.max_new:
            return True
        if self.tokenizer.eos_id in req.generated:
            return True
        if not stop:
            return False
        text = self.tokenizer.decode(req.generated)
        return any(s in text for s in stop)

    def _retire(self, req: _Request, seq_id: int, slot: int,
                active: dict[int, int]) -> None:
        req.done = True
        self.rt.release(seq_id)
        active.pop(slot, None)

    def _reserve_chunk(self, active: dict[int, int],
                       reqs: dict[int, _Request], steps: int) -> None:
        """Pre-allocate pages so a chunk of ``steps`` writes cannot land
        outside a sequence's block table; preempt on pool exhaustion."""
        for slot, seq_id in list(active.items()):
            while slot in active:            # we may become a victim ourselves
                if self.rt.advance(seq_id, steps) is not None:
                    break
                victim = self.rt.preempt_last()
                if victim is None:
                    raise RuntimeError("page pool exhausted with nothing to preempt")
                reqs[victim].generated = []  # recompute on re-admission
                vslot = next(s for s, q in active.items() if q == victim)
                active.pop(vslot)

    def _prefill_into_pages(self, req: _Request, seq_id: int,
                            temperature: jnp.ndarray) -> int:
        """Prefill one admitted sequence, commit its KV into its pages,
        return the first sampled token."""
        n_pages_bucket = _pow2_pages(
            (len(req.ids) + self.page_size - 1) // self.page_size)
        t = n_pages_bucket * self.page_size
        tokens = np.full((1, t), self.tokenizer.pad_id, np.int32)
        tokens[0, t - len(req.ids):] = req.ids
        pad_len = jnp.asarray([t - len(req.ids)], jnp.int32)
        table = self.rt.block_table(seq_id)[:n_pages_bucket][None, :]
        t0 = time.perf_counter()
        kv = init_kv_cache(self.cfg, 1, t, dtype=self.params["embed"].dtype)
        logits, kv = self._jit_prefill(self.params, tokens=self._dev(jnp.asarray(tokens)),
                                       pad_len=self._dev(pad_len), cache=kv)
        self.cache = self._jit_commit(self.cache, kv, self._dev(pad_len),
                                      self._dev(jnp.asarray(table)))
        first = sample_token(logits[:, -1, :], temperature, self._next_key())
        first_host = int(np.asarray(first)[0])
        self.stats.prefill_seconds += time.perf_counter() - t0
        self.stats.prefill_tokens += len(req.ids)
        return first_host
