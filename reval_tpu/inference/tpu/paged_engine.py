"""PagedTPUEngine: continuous batching over a paged KV cache.

The throughput engine (SURVEY.md §7 steps 4-5).  Where ``TPUEngine`` runs
static batches — every sequence in a batch prefills together and the batch
ends when its *slowest* member stops — this engine keeps a fixed set of
decode slots fed from an admission queue:

- the **native scheduler** (reval_tpu.runtime, C++) owns pages and slots:
  FCFS admission with a one-page decode watermark, lazy page allocation as
  sequences grow, recompute-style preemption on pool exhaustion;
- under a ``ragged``/``ragged_xla`` backend the drive loop is **true
  continuous batching** (``_tick_ragged``): every tick dispatches ONE
  jitted program (``paged.ragged_step``) over the whole slot set, each
  row carrying its own ``(ctx_len, q_len)`` — still-prefilling rows feed
  a prompt window, decoding rows a single query, spec-verify rows a
  draft window — so a long prefill admits mid-decode without stalling
  anyone and nothing waits at a wave boundary;
- the incumbent split-dispatch mode remains the default elsewhere:
  **prefill** per admitted wave through the contiguous left-padded path
  (bucketed to a power-of-two page count, then KV commit), **decode**
  for all slots through the Pallas paged-attention kernel, a jitted
  ``lax.scan`` chunk at a time; finished sequences free their slot at
  the next chunk boundary and a waiting request takes it.

The result: short answers ([ANSWER] NO, 2 tokens) stop occupying a slot
the moment they finish instead of padding out to the batch's longest
member — exactly the fan-out shape of DREval probe prompts.

Prefix reuse is a **persistent radix prefix cache** (prefix_cache.py):
every page-aligned prompt prefix prefilled is kept in refcounted pool
pages ACROSS generate() calls and entry points, so fleet repeats, fused
multi-template batches, and single-prompt serve requests all skip the
cached part of their prompt and prefill only the suffix — against a
context gathered per sequence from the pool (models/paged.py).  LRU
eviction of rider-free nodes yields pages back under pool pressure,
before any running sequence is preempted.

Sharding: tensor parallelism only (params + KV heads over ``tp``); data
parallelism for paged decode is one engine replica per host/dp-group
(fleet replicate mode), because the page pool is batch-global state.
"""

from __future__ import annotations

import time
import zlib
from dataclasses import dataclass, field
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ...analysis.jitcheck import deliberate_fetch, drive_guard, tracked_jit
from ...decoding import GrammarSet, NgramIndex
from ...decoding import propose as propose_drafts
from ...env import env_flag, env_int
from ...models import (
    ModelConfig,
    init_kv_cache,
    load_checkpoint,
    prefill,
)
from ...obs import metrics as obs_metrics
from ...obs.flightrec import FlightRecorder
from ...obs.logging import log_event
from ...models.paged import (
    commit_prefill,
    commit_verify,
    gather_tier_page,
    init_paged_cache,
    paged_decode_step,
    paged_ragged_step,
    prefill_with_paged_context,
    promote_tier_page,
)
from ...runtime import PagedRuntime
from .engine import (
    EngineStats,
    StopScanner,
    bump_template_stats,
    finalize_ids,
    finalize_text,
    pow2_bucket,
    profile_trace,
    restore_template_stats,
)
from .kv_tiers import TierError, TieredPageStore, default_tiering_enabled
from .prefix_cache import RadixPrefixCache
from .sampling import filter_logits, sample_token_rows
from .tokenizer import HFTokenizer

__all__ = ["PagedTPUEngine"]

PAGE_SIZE = 128  # KV pool page size (tokens); the engine's default

# Decode steps per host sync (stop-string check cadence).  Historically a
# constant 32; now an autotunable knob the kernel-CI leaderboard's chunk
# axis measures (tools/kernelbench.py) and its serving-config pick exports
# — read ONCE at import so every jitted chunk program binds one cadence
# per process (a mid-run flip would recompile every decode variant).
CHUNK = max(1, env_int("REVAL_TPU_DECODE_CHUNK", 32))

# First chunk after an admission wave is short: freshly admitted DREval
# probes often answer in a handful of tokens ([ANSWER] NO [/ANSWER]), and a
# short first chunk retires them ~CHUNK steps earlier.  Steady-state chunks
# run at full CHUNK — per-chunk host work (RPC dispatch + the token
# download) measured ~100 ms on the tunneled v5e, so fine-grained chunks
# halve decode throughput (PERF.md).
FIRST_CHUNK = min(8, CHUNK)


def _floor_pow2(n: int) -> int:
    return 1 << (max(1, n).bit_length() - 1)


def patch_state_tables(state, tables):
    """Overwrite the packed drive state's table columns (the first
    ``tables.shape[1]`` of them) in place — the chunk pipeline's
    flush-free page-crossing path.  Module-level so the TPU lowering
    tier exports THIS function, not a reconstruction
    (tests/test_tpu_lowering.py)."""
    return state.at[:, :tables.shape[1]].set(tables)

# Prompt tokens one ragged drive tick feeds per row (the continuous-
# batching path's per-tick prefill quantum).  Bounds the [B, W] window
# forward's activation footprint the same way PREFILL_BYTE_BUDGET bounds
# the incumbent wave, and — because a long prompt feeds across ticks —
# keeps already-decoding rows stepping while a long prefill admits
# mid-decode (they ride the same wave, one token per tick, instead of
# stalling behind a monolithic prefill dispatch).
RAGGED_FEED = max(1, env_int("REVAL_TPU_RAGGED_FEED", 256))

# Cap on the transient KV block a prefill call materialises ([L, rows, T,
# H_kv, D] before committing to pages) — large admissions prefill in
# sub-batches instead.  A BYTE budget, not a token count: per-token KV is
# L × H_kv × D × 2 (k+v) × dtype bytes, which spans ~190 KB (1.3b) to
# ~512 KB (6.7b) — a fixed token cap tuned on the small model OOMs the
# big one next to its page pool.  768 MB leaves room for the 6.7b pool +
# int8 weights on a 16 GB chip; prefill is MXU-bound, so the smaller row
# batches cost little.
PREFILL_BYTE_BUDGET = 768_000_000


@dataclass
class _Request:
    index: int                   # position in the caller's prompt list
    ids: list[int]
    max_new: int
    scanner: StopScanner
    generated: list[int] = field(default_factory=list)
    done: bool = False
    #: lifecycle stamps (perf_counter): construction defaults to "now",
    #: but the serving session passes its own submit time so queue wait
    #: spent in the session inbox is part of the request's latency.
    #: Admission keeps the FIRST stamp across preemption re-admissions.
    t_submit: float = field(default_factory=time.perf_counter)
    t_admit: float | None = None
    t_first: float | None = None
    t_done: float | None = None
    temp: float = 0.0            # per-request sampling temperature
    top_k: int = 0               # per-request top-k filter (0 = off)
    top_p: float = 1.0           # per-request nucleus filter (1 = off)
    notify: object = None        # optional callable(req): progress hook
    #: raw uint32[2] PRNG key; token ``p`` samples from fold_in(key, p),
    #: so the stream survives preemption, chunk re-partitioning, and
    #: dp placement unchanged
    key: np.ndarray = None
    #: radix prefix-cache node this request rides (pinned until release)
    node: object = None
    #: grammar name constraining this request (None = unconstrained) and
    #: the row's current automaton state in the engine's GrammarSet
    #: tables (0 = FREE) — engine-local state ids, resolved at submit
    grammar: str | None = None
    gstate: int = 0
    #: prompt-lookup index (decoding/draft.py), built lazily at the
    #: first speculative round and extended as tokens are accepted
    ngram: object = None
    #: the drafter faulted for this request: spec.wedge degrade — the
    #: row rides plain decode (or bonus-only verify) until it retires
    spec_wedged: bool = False
    #: ragged continuous batching only: prompt tokens already committed
    #: by feed windows, and the coverage the row's CURRENT admission must
    #: reach before it decodes.  ``fed_target`` snapshots
    #: ``len(prefill_ids)`` at (re-)admission — the live value grows with
    #: every generated token, and chasing it would keep the row feeding
    #: one token per tick forever; ``fed`` starts at the cached-prefix
    #: coverage
    fed: int = 0
    fed_target: int = 0

    @property
    def prefill_ids(self) -> list[int]:
        """Tokens a (re-)admission prefill must cover: the prompt plus any
        already-generated tokens (non-empty after a preemption — resume
        semantics, so sampled tokens are never resampled)."""
        return self.ids + self.generated


@dataclass
class _DriveState:
    """Device/host loop state that survives across drive ticks.

    Owning it in a dataclass (rather than `_drive` locals) lets the
    continuous-batching session (serving/session.py) interleave NEW
    request admission between decode chunks: each `_drive_tick` call is
    one admission + prefill + chunk round against whatever `reqs`
    currently holds — exactly vLLM's engine-step contract."""

    active: dict[int, int]       # slot -> seq_id
    slot_token: np.ndarray       # [B, 1] pending input token per slot
    slot_temp: np.ndarray        # [B] per-slot sampling temperature
    slot_topk: np.ndarray = None  # [B] per-slot top-k (0 = off)
    slot_topp: np.ndarray = None  # [B] per-slot top-p (1 = off)
    #: packed [B, span+6] int32 device array: block tables first (span
    #: columns — patch_state_tables depends on the tables-first layout),
    #: then seq_lens, the pending input token, the per-request PRNG key
    #: (2 bitcast words), the generated-token position, and the row's
    #: grammar-automaton state (0 = unconstrained)
    dev_state: object = None
    dev_samp: object = None      # [B, 3] float32 (temp, top_p, top_k)
    dirty: bool = True
    span: int = 0
    since_admit: int = 0
    #: in-flight decode chunk awaiting its host half:
    #: (toks device array, steps, ((slot, seq_id), ...) snapshot, t0)
    pending: tuple | None = None
    t_mark: float = 0.0          # last fetch end (decode-wall accounting)
    #: ticks to skip before re-flushing the pipeline for a speculative
    #: attempt after a dry one (see the spec gate in ``_tick``)
    spec_backoff: int = 0


class PagedTPUEngine:
    # mesh: axes=()
    def __init__(self, params, cfg: ModelConfig, tokenizer, *,
                 max_slots: int = 8, page_size: int = PAGE_SIZE,
                 max_seq_len: int = 8192, num_pages: int | None = None,
                 mesh=None, seed: int = 0, prefix_sharing: bool = True,
                 kv_dtype: str = "",
                 memory_utilization: float | None = None,
                 pipeline: bool | None = None,
                 speculative: bool | None = None,
                 kv_tiering: bool | None = None,
                 tier_chaos=None):
        """``memory_utilization``: when set (and ``num_pages`` is not),
        size the page pool from the device's reported HBM — the
        equivalent of the ``gpu_memory_utilization`` the reference
        passes to vLLM (reference inference.py:93): pool budget =
        ``memory_utilization × HBM − weights − 1 GiB workspace``.
        Preemption makes oversubscription safe, so the pool takes the
        whole budget.  Devices that don't report memory (the CPU test
        backend) fall back to the full per-slot reservation.

        ``pipeline``: one-deep chunk pipelining — a steady-state drive
        tick dispatches the next decode chunk (whose loop state is
        device-resident) BEFORE fetching the previous chunk's tokens,
        hiding the per-chunk host cost (~100 ms of RPC dispatch + token
        download on the tunneled v5e) behind device compute.  Output is
        bit-identical; sequences that hit a stop string may compute one
        discarded extra chunk.  Default on; ``None`` reads
        ``REVAL_TPU_PIPELINE`` (set ``0`` to disable, e.g. for A/B).

        ``speculative``: the self-drafting verify path
        (reval_tpu/decoding/).  ``None`` (default) reads
        ``REVAL_TPU_SPEC`` as the master switch but engages only for
        greedy rows that carry a ``grammar=`` constraint; ``True``
        additionally enables n-gram prompt-lookup drafting for
        grammar-less greedy rows (the determinism matrix's spec cells
        and the bench A/B set this); ``False`` — like
        ``REVAL_TPU_SPEC=0`` — restores plain decode byte-for-byte.

        ``kv_tiering``: hierarchical KV page tiers behind the radix
        prefix cache (kv_tiers.py) — evicted pages spill to host DRAM
        off the drive tick and promote back bit-identically instead of
        being recomputed; the warm snapshot's disk sidecar rides the
        same store.  ``None`` reads ``REVAL_TPU_KVTIER`` (default on);
        only meaningful with ``prefix_sharing``.  ``tier_chaos``: an
        optional :class:`~reval_tpu.resilience.TierChaos` fault
        schedule applied at promotion (``serve --tier-chaos`` wires
        it)."""
        assert max_seq_len % page_size == 0
        self.cfg = cfg
        self.tokenizer = tokenizer
        self.max_slots = max_slots
        self.page_size = page_size
        self.prefix_sharing = prefix_sharing
        if pipeline is None:
            pipeline = env_flag("REVAL_TPU_PIPELINE", True)
        self.pipeline = bool(pipeline)
        # -- ragged continuous batching (ops/pallas_attention.py) ----------
        # One ragged wave per drive tick serves any mix of prefill-feed,
        # decode, and spec-verify rows through ONE jit dispatch
        # (paged.ragged_step) instead of the incumbent prefill-wave /
        # decode-chunk / verify-chunk split.  Opt-in via
        # REVAL_TPU_PAGED_BACKEND=ragged (Pallas kernel) or ragged_xla
        # (gather-free XLA reference — exportable, bit-compatible).
        from ...ops.pallas_attention import resolved_paged_backend

        self.ragged = resolved_paged_backend() in ("ragged", "ragged_xla")
        if self.ragged and mesh is not None:
            # the ragged kernel has no shard_map wrapper yet — a
            # tp-sharded mesh rides the incumbent split dispatch
            self.ragged = False
            log_event("engine.ragged_fallback", level="warning",
                      reason="tp_mesh", mesh=str(mesh))
        # -- speculative + constrained decoding (reval_tpu/decoding/) ------
        self.spec_enabled = (env_flag("REVAL_TPU_SPEC", True)
                             if speculative is None else bool(speculative))
        #: explicit opt-in: draft grammar-less greedy rows too (n-gram)
        self.spec_eager = speculative is True
        self.spec_k = max(1, env_int("REVAL_TPU_SPEC_K", 8))
        self.spec_ngram = max(0, env_int("REVAL_TPU_SPEC_NGRAM", 3))
        #: per-engine combined token-constraint tables (state 0 = FREE);
        #: single-owner like the engine (driver thread compiles/walks)
        self._grammars = GrammarSet(tokenizer, cfg.vocab_size)
        self._gtab = None               # device (mask, next) upload
        self._gtab_version = -1         # GrammarSet.version it mirrors
        self.max_pages_per_seq = max_seq_len // page_size
        if memory_utilization is not None and not (0.0 < memory_utilization <= 1.0):
            # a tiny/negative value would silently clamp to the minimum
            # pool and preempt constantly; >1 oversubscribes HBM
            raise ValueError(
                f"memory_utilization must be in (0, 1], got {memory_utilization}")
        if num_pages is None and memory_utilization is not None:
            num_pages = self._pages_for_budget(
                params, cfg, mesh, page_size, kv_dtype, memory_utilization,
                max_slots, self.max_pages_per_seq)
        # default pool: every slot can reach max_seq_len (no oversubscription;
        # pass a smaller num_pages to trade HBM for preemption risk)
        self.num_pages = (num_pages if num_pages is not None
                          else 1 + max_slots * self.max_pages_per_seq)
        self.mesh = mesh
        self.stats = EngineStats()
        #: decode-loop progress stamp (monotonic): the serving watchdog
        #: reads it to tell "slow but stepping" from "wedged"
        self.heartbeat = time.monotonic()
        #: always-on per-step ring buffer feeding postmortem bundles
        #: (obs/flightrec.py; REVAL_TPU_FLIGHTREC=0 disables — the A/B)
        self.flightrec = FlightRecorder()
        self._pinned_sample = 0     # decimated pinned-pages gauge (tree walk)
        self._key = jax.random.PRNGKey(seed)
        self.params = params
        dtype = params["embed"].dtype
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ...parallel import shard_params
            from ...parallel.sharding import paged_cache_spec, resolve_moe_impl

            cfg = self.cfg = resolve_moe_impl(cfg, mesh)
            self.params = shard_params(params, cfg, mesh)
            self._cache_sharding = NamedSharding(mesh, paged_cache_spec(cfg, mesh))
            self._replicated = NamedSharding(mesh, P())
        else:
            self._cache_sharding = None
            self._replicated = None
        self.rt = PagedRuntime(self.num_pages, page_size, max_slots,
                               self.max_pages_per_seq)
        self.cache = init_paged_cache(cfg, self.num_pages, page_size,
                                      dtype=dtype, kv_dtype=kv_dtype)
        cache_out_shardings = None
        if self._cache_sharding is not None:
            # pool arrays are [rows, H_kv, D]; int8 scale arrays [rows, H_kv]
            # shard the same H_kv axis one rank down
            from jax.sharding import NamedSharding

            scale_sharding = NamedSharding(
                self.mesh, type(self._cache_sharding.spec)(
                    *self._cache_sharding.spec[:2]))
            self.cache = jax.tree.map(
                lambda c: jax.device_put(
                    c, self._cache_sharding if c.ndim == 3 else scale_sharding),
                self.cache)
            # pin the cache-RETURNING entries to the same placement:
            # without out_shardings XLA's propagation is free to pick a
            # different pool layout (found via the shardcheck guard on a
            # kv-indivisible tp mesh: commit came back H_kv-sharded over
            # a declared-replicated pool), and every later chunk then
            # re-gathers the pool against the attention shard_map's
            # declared specs — a silent per-chunk all-gather
            cache_out_shardings = jax.tree.map(
                lambda c: (self._cache_sharding if c.ndim == 3
                           else scale_sharding),
                self.cache)
        # Compile-variant budgets (warmup=N): the worst-case count of
        # legitimate shape buckets per entry at the flagship bench shape
        # (rows/token pow2 buckets for prefill, steps x filtered x span
        # buckets for the chunk).  The jitcheck tracker flags variant
        # N+1 as a post-warmup recompile (reval_jit_cache_misses_total +
        # a jit.recompile log event); the static jit pass cross-checks
        # these literals against the annotations.
        reg = lambda: self.stats.registry  # noqa: E731 — see TrackedJit
        # jit-entry: paged.prefill bucketed=(rows, tokens) warmup=24
        self._jit_prefill = tracked_jit(
            "paged.prefill",
            jax.jit(partial(prefill, cfg=cfg, logits_mode="last")),
            registry=reg, warmup=24)
        # jit-entry: paged.prefill_pctx bucketed=(rows, tokens, ctx_pages) warmup=24
        self._jit_prefill_pctx = tracked_jit(
            "paged.prefill_pctx",
            jax.jit(partial(prefill_with_paged_context, cfg=cfg,
                            logits_mode="last")),
            registry=reg, warmup=24)
        # jit-entry: paged.commit bucketed=(rows, tokens) warmup=24
        self._jit_commit = tracked_jit(
            "paged.commit",
            jax.jit(commit_prefill, donate_argnums=(0,),
                    **({"out_shardings": cache_out_shardings}
                       if cache_out_shardings is not None else {})),
            registry=reg, warmup=24)
        # persistent radix prefix cache: page-aligned prompt prefixes live
        # in refcounted pool pages ACROSS generate() calls and entry
        # points (fleet repeats, serve-mode requests).  The watermark
        # keeps one free page per slot so cached-but-idle prefixes never
        # starve decode admission; under deeper pressure the engine
        # evicts LRU nodes before preempting running sequences.
        # hierarchical KV tiering (kv_tiers.py): evicted prefix-cache
        # pages spill to host DRAM (copier thread, off the drive tick)
        # and promote back into the pool bit-identically at the next
        # acquire; the warm snapshot's disk sidecar attaches here too
        self.kv_tiering = (default_tiering_enabled(kv_tiering)
                           and prefix_sharing)
        self.kv_tiers = (TieredPageStore(page_size,
                                         stats=lambda: self.stats,
                                         chaos=tier_chaos)
                         if self.kv_tiering else None)
        self.prefix_cache = (RadixPrefixCache(
            self.rt, page_size, watermark=max_slots,
            stats=lambda: self.stats,
            spill=self._spill_node if self.kv_tiers is not None else None)
                             if prefix_sharing else None)
        # jit-entry: paged.decode_chunk static=(steps, filtered, grammared) bucketed=(span, gstates) warmup=64
        self._jit_chunk = tracked_jit(
            "paged.decode_chunk",
            jax.jit(
                partial(self._decode_chunk, cfg=cfg, mesh=mesh),
                static_argnames=("steps", "filtered", "grammared"),
                donate_argnames=("cache",),
                **({"out_shardings": (None, cache_out_shardings, None)}
                   if cache_out_shardings is not None else {})),
            registry=reg, warmup=64)
        # speculative verify: score a whole draft window (pending token +
        # K drafts) in ONE forward against per-row gathered pool context,
        # commit its KV at the exact flat positions plain decode would
        # write, and emit masked greedy targets + the accepted-prefix
        # length (decoding/ — the engine half of ROADMAP item 2)
        # jit-entry: paged.verify_chunk static=(grammared) bucketed=(span, ctx_pages, gstates, window) warmup=24
        self._jit_verify = tracked_jit(
            "paged.verify_chunk",
            jax.jit(
                partial(self._verify_chunk, cfg=cfg),
                static_argnames=("grammared",),
                donate_argnames=("cache",),
                **({"out_shardings": (None, cache_out_shardings)}
                   if cache_out_shardings is not None else {})),
            registry=reg, warmup=24)
        # in-place update of the packed state's table columns (the first
        # ``span`` columns) — lets a page-boundary crossing ride the
        # chunk pipeline instead of flushing it (tables are host-known;
        # lens/token/pos keep flowing device-side untouched)
        # jit-entry: paged.patch_tables bucketed=(span) warmup=16
        self._jit_patch = tracked_jit(
            "paged.patch_tables", jax.jit(patch_state_tables),
            registry=reg, warmup=16)
        # ragged unified step: ONE dispatch per drive tick computes a
        # whole mixed wave — per-row (ctx_len, q_len) descriptors ride
        # the packed state, the window tokens commit + attend through
        # the ragged paged-attention kernel, and an optional plain-decode
        # scan tail (steps > 1) amortises host cadence exactly like the
        # incumbent chunk.  Only dispatched when the resolved backend is
        # ragged/ragged_xla; registered unconditionally so the jit/AOT
        # registries see one stable entry set.
        # jit-entry: paged.ragged_step static=(steps, filtered, grammared) bucketed=(span, window, gstates) warmup=64
        self._jit_ragged = tracked_jit(
            "paged.ragged_step",
            jax.jit(
                partial(self._ragged_step, cfg=cfg, mesh=mesh),
                static_argnames=("steps", "filtered", "grammared"),
                donate_argnames=("cache",),
                **({"out_shardings": (None, cache_out_shardings)}
                   if cache_out_shardings is not None else {})),
            registry=reg, warmup=64)
        # KV-tier page movement (kv_tiers.py): one page's rows out of
        # the pool (spill read — a non-aliasing slice, so the pool page
        # is releasable the moment dispatch returns) and back in
        # (promotion write — leading-dim in-place scatter on the donated
        # pool).  Fixed shapes per engine: one variant each, plus one
        # spare for a resharded pool.
        # jit-entry: paged.kvtier_gather warmup=2
        self._jit_tier_gather = tracked_jit(
            "paged.kvtier_gather", jax.jit(gather_tier_page),
            registry=reg, warmup=2)
        # jit-entry: paged.kvtier_promote warmup=2
        self._jit_tier_promote = tracked_jit(
            "paged.kvtier_promote",
            jax.jit(promote_tier_page, donate_argnums=(0,),
                    **({"out_shardings": cache_out_shardings}
                       if cache_out_shardings is not None else {})),
            registry=reg, warmup=2)
        #: per-template request counts: crc32 of the first prompt PAGE's
        #: token ids — the token-space analog of the router's char-window
        #: affinity key (same intent, DIFFERENT domain: the two hashes
        #: are not joinable).  Rides the warm-state snapshot so a
        #: restarted replica still reports its template mix
        #: (single-owner, like the runtime: one driver thread mutates it)
        self._template_stats: dict[int, int] = {}
        # persistent AOT executable cache (aot_cache.py): when
        # REVAL_TPU_AOT_CACHE_DIR is set, every tracked jit variant this
        # engine compiles is serialized to disk and the next process
        # boot dispatches the deserialized executable instead of paying
        # the trace+lower again.  Off (None) → the trackers above serve
        # calls exactly as before.
        from .aot_cache import AotJit, cache_from_env, kernel_export_skip
        from ...ops.pallas_attention import resolved_kernel_knobs

        # the receipt/AOT config context: built UNCONDITIONALLY (the
        # reproducibility receipt on every response needs it whether or
        # not the executable cache is armed), snapshotted here because
        # the trace-time knobs bind per process exactly like the
        # executables they key
        kernel_backend = resolved_paged_backend()
        self._receipt_ctx = {
            "engine": "paged", "model": str(cfg),
            "weights_dtype": str(dtype), "kv_dtype": kv_dtype or "bf16",
            "page_size": page_size, "max_slots": max_slots,
            "max_seq_len": max_seq_len,
            "mesh": str(mesh) if mesh is not None else "none",
            "platform": jax.default_backend(),
            "kernel_backend": kernel_backend,
            # trace-time kernel knobs (dot formulation, interpret
            # mode): same backend label, different traced program
            **resolved_kernel_knobs()}
        self._aot_cache = cache_from_env(registry=reg)
        if self._aot_cache is not None:
            ctx = self._receipt_ctx
            # the decode chunk embeds the paged-attention kernel: on a
            # pallas backend its export needs Mosaic lowering support —
            # the canary names the environment gap (unsupported, counted)
            # instead of raising a doomed export per variant
            chunk_canary = (kernel_export_skip
                            if kernel_backend not in ("xla", "ragged_xla")
                            else None)
            # donate= re-applies the original jits' buffer donation to
            # deserialized executables (serialization drops it; the
            # commit/chunk programs update the KV pool in place through
            # that aliasing — positional index at the call site)
            self._jit_prefill = AotJit(self._jit_prefill, self._aot_cache, ctx)
            self._jit_prefill_pctx = AotJit(self._jit_prefill_pctx,
                                            self._aot_cache, ctx)
            self._jit_commit = AotJit(self._jit_commit, self._aot_cache, ctx,
                                      donate=(0,))
            self._jit_chunk = AotJit(self._jit_chunk, self._aot_cache, ctx,
                                     static=("steps", "filtered",
                                             "grammared"),
                                     canary=chunk_canary, donate=(2,))
            # the verify forward rides the prefill path (gather + plain
            # XLA attention) — no Mosaic kernel, so no canary needed
            self._jit_verify = AotJit(self._jit_verify, self._aot_cache, ctx,
                                      static=("grammared",), donate=(7,))
            # the ragged step embeds the ragged attention kernel: the
            # Pallas form needs Mosaic export support (canary), the
            # ragged_xla reference exports anywhere
            self._jit_ragged = AotJit(self._jit_ragged, self._aot_cache, ctx,
                                      static=("steps", "filtered",
                                              "grammared"),
                                      canary=chunk_canary, donate=(3,))
            self._jit_patch = AotJit(self._jit_patch, self._aot_cache, ctx)
            self._jit_tier_gather = AotJit(self._jit_tier_gather,
                                           self._aot_cache, ctx)
            self._jit_tier_promote = AotJit(self._jit_tier_promote,
                                            self._aot_cache, ctx,
                                            donate=(0,))
        # runtime mesh discipline (analysis/shardcheck.py): on a mesh,
        # the chunk/commit entries carry the KV pool — assert its actual
        # sharding still matches paged_cache_spec after every dispatch
        # (a silently-resharded pool is a mesh-size× step-time cliff).
        # Wrapped OUTERMOST so the AOT dispatch path is checked too.
        if self._cache_sharding is not None:
            from ...analysis.shardcheck import ShardGuard

            self._jit_chunk = ShardGuard(
                "paged.decode_chunk", self._jit_chunk, registry=reg,
                in_checks={2: self._cache_sharding},
                out_checks={1: self._cache_sharding})
            self._jit_commit = ShardGuard(
                "paged.commit", self._jit_commit, registry=reg,
                in_checks={0: self._cache_sharding},
                out_checks={0: self._cache_sharding})
            self._jit_verify = ShardGuard(
                "paged.verify_chunk", self._jit_verify, registry=reg,
                in_checks={7: self._cache_sharding},
                out_checks={1: self._cache_sharding})
            self._jit_tier_promote = ShardGuard(
                "paged.kvtier_promote", self._jit_tier_promote,
                registry=reg, in_checks={0: self._cache_sharding},
                out_checks={0: self._cache_sharding})
        self._jit_trackers = (self._jit_prefill, self._jit_prefill_pctx,
                              self._jit_commit, self._jit_chunk,
                              self._jit_verify, self._jit_ragged,
                              self._jit_patch,
                              self._jit_tier_gather,
                              self._jit_tier_promote)

    @staticmethod
    def _pages_for_budget(params, cfg, mesh, page_size: int, kv_dtype: str,
                          utilization: float, max_slots: int,
                          max_pages_per_seq: int) -> int | None:
        """Pages the HBM budget affords per device, or None (no memory
        stats → caller keeps the deterministic full-reservation default).

        All quantities are PER DEVICE: under a tp mesh both the weights
        and the pool's kv-head axis are sharded ``mesh.size`` ways.
        """
        try:
            stats = jax.local_devices()[0].memory_stats() or {}
        except Exception:
            stats = {}
        hbm = stats.get("bytes_limit")
        if not hbm:
            return None
        shards = mesh.size if mesh is not None else 1
        weight_bytes = sum(
            x.nbytes for x in jax.tree_util.tree_leaves(params)) // shards
        store = 1 if kv_dtype == "int8" else jnp.dtype(
            params["embed"].dtype).itemsize
        h_kv_local = max(1, cfg.num_kv_heads // shards)
        per_token = 2 * cfg.num_layers * h_kv_local * cfg.head_dim * store
        if kv_dtype == "int8":
            per_token += 2 * cfg.num_layers * h_kv_local * 4   # f32 scales
        budget = int(utilization * hbm) - weight_bytes - (1 << 30)
        pages = budget // (page_size * per_token)
        # never above what the slots can address (pages past
        # 1 + slots*max_pages_per_seq are unreachable HBM), never below a
        # working minimum: one page per slot plus the trash page
        # (preemption handles workloads larger than the pool)
        pages = min(int(pages), 1 + max_slots * max_pages_per_seq)
        return max(pages, max_slots + 1)

    @classmethod
    def from_pretrained(cls, model_path: str, *, dtype: str = "bfloat16",
                        tp_size: int = 1, max_slots: int = 8,
                        page_size: int = PAGE_SIZE, max_seq_len: int = 8192,
                        num_pages: int | None = None, tokenizer=None,
                        seed: int = 0, kv_dtype: str = "",
                        local_devices_only: bool = False,
                        memory_utilization: float | None = None,
                        pipeline: bool | None = None,
                        kv_tiering: bool | None = None,
                        tier_chaos=None,
                        ) -> "PagedTPUEngine":
        mesh = None
        if tp_size > 1:
            from ...parallel import make_mesh

            devices = jax.local_devices() if local_devices_only else None
            mesh = make_mesh(tp=tp_size, devices=devices)
        if mesh is not None and dtype != "int8":
            # shard-direct load: each device reads only its slice of the
            # checkpoint — incl. int4, whose group scales quantize
            # shard-locally (34B+ would blow host RAM through the
            # full-tree path; only int8's whole-tensor amax keeps it)
            from ...models import load_checkpoint_sharded

            params, cfg = load_checkpoint_sharded(model_path, mesh, dtype=dtype)
        else:
            params, cfg = load_checkpoint(model_path, dtype=dtype)
        if tokenizer is None:
            tokenizer = HFTokenizer(model_path)
        return cls(params, cfg, tokenizer, max_slots=max_slots,
                   page_size=page_size, max_seq_len=max_seq_len,
                   num_pages=num_pages, mesh=mesh, seed=seed,
                   kv_dtype=kv_dtype, pipeline=pipeline,
                   memory_utilization=memory_utilization,
                   kv_tiering=kv_tiering, tier_chaos=tier_chaos)

    def close(self) -> None:
        if self.kv_tiers is not None:
            self.kv_tiers.close()
            self.kv_tiers = None
        if self.prefix_cache is not None:
            self.prefix_cache.clear()
            self.prefix_cache = None
        if self.rt is not None:
            self.rt.close()
            self.rt = None
        # drop the page pool so its HBM is reclaimable immediately — a
        # multi-GB pool lingering until GC makes the next engine's
        # allocation fail on a 16 GB chip
        self.cache = None

    # -- jitted pieces -----------------------------------------------------
    @staticmethod
    def _decode_chunk(params, state, cache, sampling, gtables=None,
                      *, cfg: ModelConfig, steps: int, filtered: bool = False,
                      grammared: bool = False, mesh=None):
        """``steps`` paged decode iterations for the whole slot batch.

        ``state`` packs the whole per-chunk loop state into ONE int32
        array ``[B, span + 6]`` — block tables, seq_lens, the pending
        input token, the per-request PRNG key (2 bitcast words), the
        generated-token position, and the grammar-automaton state — so a
        steady-state chunk needs no host→device uploads at all: the
        previous chunk's returned state feeds the next call as a
        device-resident array.  Per-upload RPC latency on the tunneled
        TPU measured ~100 ms/chunk of avoidable host work (PERF.md),
        which is why this is packed rather than six arrays.  Sampling
        keys fold the request key with the generated position
        (``sample_token_rows``), making every request's sample stream
        schedule-independent.

        ``grammared`` (static) compiles the token-constraint mask into
        the step: ``gtables`` is ``(mask [S, V] bool, next [S, V]
        int32)`` (decoding/grammar.py; state 0 = unconstrained rows —
        its all-True row makes the mask a bit-exact no-op for them),
        each row's state advances through the table on its own sampled
        token, so a constrained row can never emit an out-of-grammar
        token mid-chunk.  The default program carries no tables and is
        byte-identical to the pre-grammar chunk.
        """
        span = state.shape[1] - 6
        block_tables = state[:, :span]
        seq_lens = state[:, span]
        first_token = state[:, span + 1:span + 2]
        keys = jax.lax.bitcast_convert_type(state[:, span + 2:span + 4],
                                            jnp.uint32)
        gen_pos = state[:, span + 4]
        gstate0 = state[:, span + 5]

        temperature = sampling[:, 0]

        def body(carry, _):
            token, cache, lens, pos, gstate = carry
            logits, cache = paged_decode_step(params, cfg, token, block_tables,
                                              lens, cache, mesh=mesh)
            if grammared:   # static: default chunks carry no mask gather
                gmask, _ = gtables
                logits = jnp.where(gmask[gstate], logits, -1e30)
            if filtered:    # static: default chunks carry no [B, V] sort
                logits = filter_logits(logits, sampling[:, 2].astype(jnp.int32),
                                       sampling[:, 1], temperature)
            row_keys = jax.vmap(jax.random.fold_in)(keys, pos)
            nxt = sample_token_rows(logits, temperature, row_keys)
            if grammared:
                _, gnext = gtables
                gstate = gnext[gstate, nxt]
            return (nxt[:, None], cache, lens + 1, pos + 1, gstate), nxt

        (last, cache, lens, pos, gstate), toks = jax.lax.scan(
            body, (first_token, cache, seq_lens, gen_pos, gstate0),
            None, length=steps)
        new_state = jnp.concatenate(
            [block_tables, lens[:, None], last,
             jax.lax.bitcast_convert_type(keys, jnp.int32), pos[:, None],
             gstate[:, None]],
            axis=1)
        return toks.T, cache, new_state

    @staticmethod
    def _verify_chunk(params, tables, ctx_tables, lens, tokens, ndraft,
                      gstate, cache, kvbuf, gmask=None, gnext=None,
                      *, cfg: ModelConfig, grammared: bool = False):
        """Score one draft window per slot in ONE forward (the
        speculative verify step — the engine half of ROADMAP item 2).

        ``tokens`` [B, W]: column 0 is the row's pending input token,
        columns 1..W-1 its drafts (padded with the pending token past
        ``ndraft[b]`` — padding can never be accepted because the cap
        rides the accept rule).  ``lens`` [B] is each row's materialised
        length: the window occupies absolute positions [len, len+W), its
        context is the row's own pool pages gathered via ``ctx_tables``
        (the block tables' leading columns), and its KV commits through
        :func:`~reval_tpu.models.paged.commit_verify` at exactly the
        flat positions plain decode would write — which is what makes a
        later plain chunk read bit-compatible state.

        The accept contract: ``targets[b, j]`` is the grammar-masked
        greedy argmax after consuming window columns 0..j, computed by
        the SAME ``jnp.argmax`` over the same f32 logits (and the same
        ``-1e30`` mask constant) the decode chunk uses; draft ``j+1`` is
        accepted iff it equals ``targets[b, j]`` and every earlier draft
        was accepted.  Accepted tokens are therefore the tokens plain
        greedy decode would have emitted — the bit-identity the
        determinism observatory's spec cells certify.

        Returns ``(out [B, W+2] int32, cache)``: targets, the accepted
        draft count, and the row's automaton state after consuming the
        accepted tokens + bonus (one packed array = one host fetch).
        """
        b, w = tokens.shape
        logits, kv = prefill_with_paged_context(
            params, cfg, tokens, jnp.zeros(b, jnp.int32), ctx_tables,
            lens, cache, kvbuf, logits_mode="all")
        cache = commit_verify(cache, kv, tables, lens)
        if grammared:
            # automaton states after consuming window columns 0..j:
            # column 0 (the pending token) is already folded into
            # ``gstate``; drafts advance it one table lookup at a time
            def walk(s, tok_col):
                ns = gnext[s, tok_col]
                return ns, ns

            _, tail = jax.lax.scan(walk, gstate, tokens.T[1:])
            s_after = jnp.concatenate([gstate[None], tail], axis=0).T  # [B,W]
            logits = jnp.where(gmask[s_after], logits, -1e30)
        else:
            s_after = jnp.zeros_like(tokens)
        targets = jnp.argmax(logits, axis=-1).astype(jnp.int32)       # [B,W]
        pos = jnp.arange(1, w, dtype=jnp.int32)[None, :]
        ok = (tokens[:, 1:] == targets[:, :-1]) & (pos <= ndraft[:, None])
        accepted = jnp.cumprod(ok.astype(jnp.int32), axis=1).sum(axis=1)
        if grammared:
            sa = jnp.take_along_axis(s_after, accepted[:, None], axis=1)[:, 0]
            bonus = jnp.take_along_axis(targets, accepted[:, None],
                                        axis=1)[:, 0]
            new_gs = gnext[sa, bonus]
        else:
            new_gs = gstate
        out = jnp.concatenate(
            [targets, accepted[:, None], new_gs[:, None]], axis=1)
        return out, cache

    @staticmethod
    def _ragged_step(params, state, tokens, cache, sampling, gtables=None,
                     *, cfg: ModelConfig, steps: int, filtered: bool = False,
                     grammared: bool = False, mesh=None):
        """ONE ragged wave over the whole slot batch: a mixed window
        forward (prefill-feed, decode, and spec-verify rows together)
        followed by an optional plain-decode scan tail.

        ``state`` packs the per-row ragged descriptors into one int32
        array ``[B, span + 7]`` — block tables (``span`` columns), the
        committed context length ``ctx``, the window length ``q_len``,
        the draft count ``ndraft``, the per-request PRNG key (2 bitcast
        words), the generated-token position, and the grammar-automaton
        state.  ``tokens`` [B, W] is row ``b``'s window: a decode row is
        its pending token (``q_len=1``), a verify row pending + drafts
        (``q_len = 1 + ndraft``), a feed row the next ``q_len`` prompt
        tokens; columns past ``q_len`` are padding (their KV lands in
        the trash page, their logits are never read).

        The per-column greedy targets use the SAME masked ``jnp.argmax``
        contract as :meth:`_verify_chunk` (same f32 logits, same
        ``-1e30`` mask constant), the accept rule is identical, and the
        emission column generalises the verify bonus: column ``q_len - 1
        - ndraft + accepted`` is the row's next-token position whether
        the row decoded (col 0), fed its final prompt chunk (its last
        real column — the first-token sample the incumbent prefill
        emits), or verified a draft window (the bonus column).  Sampled
        rows sample that column with ``fold_in(key, pos)`` exactly like
        the decode chunk, so greedy streams stay schedule-independent.

        ``steps > 1`` (pure-decode ticks only) appends ``steps - 1``
        plain decode iterations — the exact :meth:`_decode_chunk` body,
        whose attention rides the ragged kernel at ``q_len = 1`` under
        the ragged backends.

        Returns ``(out [B, W + steps + 1] int32, cache)``: the window
        targets, the accepted draft count, the phase-A emission, and the
        scan-tail tokens — one packed array, one host fetch per tick.
        """
        span = state.shape[1] - 7
        block_tables = state[:, :span]
        ctx = state[:, span]
        qlen = state[:, span + 1]
        ndraft = state[:, span + 2]
        keys = jax.lax.bitcast_convert_type(state[:, span + 3:span + 5],
                                            jnp.uint32)
        pos = state[:, span + 5]
        gstate0 = state[:, span + 6]
        b, w = tokens.shape
        temperature = sampling[:, 0]

        logits, cache = paged_ragged_step(params, cfg, tokens, block_tables,
                                          ctx, qlen, cache, mesh=mesh)
        if grammared:
            gmask, gnext = gtables
            # automaton states after consuming window columns 0..j:
            # column 0 (pending/prompt) is already folded into
            # ``gstate0``; only DRAFT columns (1..ndraft) advance — feed
            # rows' prompt tokens never walk the automaton (the grammar
            # constrains the answer, not the prompt)
            def walk(s, col):
                tok, j = col
                ns = jnp.where(j <= ndraft, gnext[s, tok], s)
                return ns, ns

            _, tail = jax.lax.scan(
                walk, gstate0,
                (tokens.T[1:], jnp.arange(1, w, dtype=jnp.int32)))
            s_after = jnp.concatenate([gstate0[None], tail], axis=0).T
            logits = jnp.where(gmask[s_after], logits, -1e30)
        targets = jnp.argmax(logits, axis=-1).astype(jnp.int32)       # [B,W]
        j = jnp.arange(1, w, dtype=jnp.int32)[None, :]
        ok = (tokens[:, 1:] == targets[:, :-1]) & (j <= ndraft[:, None])
        accepted = jnp.cumprod(ok.astype(jnp.int32), axis=1).sum(axis=1)
        # the row's next-token column: 0 for decode, the last real
        # column for a feed, the bonus column for a verify window
        base = jnp.clip(qlen - 1 - ndraft + accepted, 0, w - 1)
        emit = jnp.take_along_axis(logits, base[:, None, None],
                                   axis=1)[:, 0]                      # [B,V]
        if filtered:    # static: default waves carry no [B, V] sort
            emit = filter_logits(emit, sampling[:, 2].astype(jnp.int32),
                                 sampling[:, 1], temperature)
        row_keys = jax.vmap(jax.random.fold_in)(keys, pos)
        nxt = sample_token_rows(emit, temperature, row_keys)
        if grammared:
            s_base = jnp.take_along_axis(s_after, base[:, None], axis=1)[:, 0]
            gstate = gnext[s_base, nxt]
        else:
            gstate = gstate0
        # tokens that stick this wave: q_len for a feed, 1 for decode,
        # 1 + accepted for verify (the host rolls rejected tails back)
        lens = ctx + qlen - ndraft + accepted

        def body(carry, _):
            token, cache, lens_c, pos_c, gs = carry
            logits2, cache = paged_decode_step(params, cfg, token,
                                               block_tables, lens_c, cache,
                                               mesh=mesh)
            if grammared:   # static: default waves carry no mask gather
                logits2 = jnp.where(gmask[gs], logits2, -1e30)
            if filtered:
                logits2 = filter_logits(logits2,
                                        sampling[:, 2].astype(jnp.int32),
                                        sampling[:, 1], temperature)
            rk = jax.vmap(jax.random.fold_in)(keys, pos_c)
            nxt2 = sample_token_rows(logits2, temperature, rk)
            if grammared:
                gs = gnext[gs, nxt2]
            return (nxt2[:, None], cache, lens_c + 1, pos_c + 1, gs), nxt2

        if steps > 1:   # static: feed/verify ticks compile no scan tail
            (_, cache, _, _, _), toks = jax.lax.scan(
                body, (nxt[:, None], cache, lens, pos + 1, gstate),
                None, length=steps - 1)
            tail = toks.T
        else:
            tail = jnp.zeros((b, 0), jnp.int32)
        out = jnp.concatenate(
            [targets, accepted[:, None], nxt[:, None], tail], axis=1)
        return out, cache

    def _next_key(self) -> jax.Array:
        self._key, sub = jax.random.split(self._key)
        return sub

    def request_keys(self, n: int) -> np.ndarray:
        """[n, 2] uint32 per-request PRNG keys for one call: request ``i``
        gets ``fold_in(call_key, i)``; one call-level key advance keeps
        repeated calls (consistency-task repeats) sampling differently
        while requests within a call are schedule-independent."""
        base = self._next_key()
        return np.asarray(jax.vmap(jax.random.fold_in, in_axes=(None, 0))(
            base, jnp.arange(n)), dtype=np.uint32)

    def encode_clipped(self, prompt: str, max_new_tokens: int) -> list[int]:
        """Tokenise one prompt, left-clipping so prompt + generation fits
        ``max_seq_len`` (the in-process ``generate`` path and the serving
        session both use it; the rule itself lives in
        :func:`clip_prompt_ids`).  Raises ValueError when the token
        budget alone exceeds the sequence capacity."""
        from .engine import clip_prompt_ids

        return clip_prompt_ids(self.tokenizer, prompt, max_new_tokens,
                               self.max_pages_per_seq * self.page_size)

    # -- generation --------------------------------------------------------
    def generate(self, prompts: list[str], *, max_new_tokens: int = 256,
                 temperature: float = 0.0, stop: list[str] | None = None,
                 top_k: int = 0, top_p: float = 1.0,
                 on_progress=None, return_ids: bool = False,
                 grammar=None):
        """``on_progress(index, text)``: streaming hook, called at every
        decode-chunk boundary with the prompt's index and its finalised
        text so far (stop/EOS truncation already applied).  The text
        normally extends the previous call's, but BPE detokenisation is
        not strictly prefix-stable at chunk edges — consumers should
        diff defensively.  Costs one detokenisation of the generated ids
        per chunk per live request — only paid when a callback is
        installed.

        ``return_ids``: also return the raw generated token streams
        (``finalize_ids`` semantics — EOS-cut, pre-stop) as a second
        list; the determinism matrix compares these, because ids outside
        the byte range (EOS, vocab padding) decode to nothing and their
        divergence is invisible in text.

        ``grammar``: a decoding/grammar.py shape name (or a per-prompt
        list of names/None — the fleet's fused multi-task batches mix
        shapes): each named prompt decodes under its token-constraint
        automaton (out-of-grammar tokens masked) and, when speculation
        is enabled, drafts its forced/looked-up continuations for the
        batched verify step."""
        if not prompts:
            return ([], []) if return_ids else []
        stop = stop or []
        grammars = self._grammar_list(grammar, len(prompts))
        encoded = [self.encode_clipped(p, max_new_tokens) for p in prompts]

        reqs: dict[int, _Request] = {}
        notify = None
        if on_progress is not None:
            def notify(req, _stop=stop):
                on_progress(req.index,
                            finalize_text(self.tokenizer, req.generated,
                                          _stop))
        keys = self.request_keys(len(encoded))
        try:
            for i, ids in enumerate(encoded):
                # every prompt — single serve-mode requests included —
                # consults the persistent prefix cache; prompts later in
                # the list hit pages inserted by earlier ones (that is
                # what fuses multi-template fleet batches without a
                # whole-batch LCP)
                seq_id, node = self.submit_request(ids, max_new_tokens,
                                                   grammar=grammars[i])
                reqs[seq_id] = _Request(index=i, ids=ids, max_new=max_new_tokens,
                                        scanner=StopScanner(self.tokenizer, stop),
                                        temp=float(temperature),
                                        top_k=int(top_k), top_p=float(top_p),
                                        notify=notify, key=keys[i], node=node,
                                        grammar=grammars[i],
                                        gstate=(self.grammar_state(grammars[i])
                                                if grammars[i] else 0))

            with profile_trace():
                self._drive(reqs)
        except Exception:
            # never leave requests queued/running in the native scheduler —
            # the next generate() would be handed stale seq ids (and their
            # prefix nodes pinned forever)
            for seq_id, req in reqs.items():
                if not req.done:
                    self.release_request(seq_id, req)
            raise

        out: list[str] = [""] * len(prompts)
        out_ids: list[list[int]] = [[] for _ in prompts]
        for req in reqs.values():
            out[req.index] = finalize_text(self.tokenizer, req.generated, stop)
            out_ids[req.index] = finalize_ids(self.tokenizer, req.generated)
        self.stats.prompts += len(prompts)
        if return_ids:
            return out, out_ids
        return out

    @staticmethod
    def _grammar_list(grammar, n: int) -> list:
        """Normalise a ``grammar=`` argument (None | name | per-prompt
        list) to one entry per prompt."""
        if grammar is None or isinstance(grammar, str):
            return [grammar] * n
        grammars = list(grammar)
        if len(grammars) != n:
            raise ValueError(f"grammar list has {len(grammars)} entries "
                             f"for {n} prompts")
        return grammars

    def grammar_state(self, name: str) -> int:
        """Compile (idempotent) a grammar name into this engine's
        combined constraint tables and return its start state — the id a
        request's ``gstate`` begins at.  Raises ``ValueError`` for
        unknown names (the serving layer maps that to a 400)."""
        return self._grammars.start_state(name)

    def spec_counters(self) -> dict:
        """Speculative-decoding counter snapshot (accept rate, drafted/
        accepted/rolled-back tokens, wedges) — the bench ``speculative``
        block and the fleet trailer render this dict
        (:meth:`EngineStats.spec_counters`)."""
        return self.stats.spec_counters()

    def receipt_context(self) -> dict:
        """The reproducibility-receipt config context (obs/receipts.py):
        the AOT cache's fingerprint axes extended with the serving knobs
        it never needed — speculative decoding on/off + K, KV-tier
        enablement, and the decode-chunk cadence.  Snapshotted at build
        like the trace-time knobs it rides with; per-request axes
        (grammar, sampling) travel on the receipt body instead, so two
        identically-configured replicas fingerprint identically."""
        return dict(self._receipt_ctx,
                    spec=self.spec_enabled, spec_eager=self.spec_eager,
                    spec_k=self.spec_k, kv_tiering=self.kv_tiering,
                    ragged=self.ragged, decode_chunk=CHUNK)

    def submit_request(self, ids: list[int], max_new_tokens: int,
                       grammar: str | None = None) -> tuple[int, object]:
        """Hand one tokenised request to the native scheduler, riding the
        persistent prefix cache.

        The ONE entry point every driver uses (``generate()``, the dp
        work-stealing loop, the serving session) so the cache lifecycle
        lives in one place: look up the longest cached page-aligned
        prefix, prefill any newly inserted pages once, submit the request
        against the node's refcounted pages.  Returns ``(seq_id, node)``;
        the node is pinned until :meth:`release_request`.

        ``grammar`` (optional) validates + compiles the request's
        constraint automaton up front — an unknown name fails HERE, in
        the submitting thread, never in the drive loop — and counts the
        request into ``reval_grammar_requests_total``.  The caller still
        stamps the compiled start state onto its ``_Request.gstate``
        (via :meth:`grammar_state` — state ids are engine-local).
        """
        # per-template accounting: crc32 of the first prompt page's
        # token ids (token-space analog of the router's affinity key,
        # not the same hash) — the warm-state snapshot carries the
        # replica's template mix across a restart
        if grammar:
            self.grammar_state(grammar)     # ValueError on unknown names
            self.stats.grammar_requests += 1
        tag = zlib.crc32(np.asarray(ids[:self.page_size],
                                    np.int32).tobytes())
        bump_template_stats(self._template_stats, tag)
        node = None
        if self.prefix_cache is not None:
            node, new_from = self.prefix_cache.acquire(ids)
            if node is not None and new_from < node.tok_len:
                try:
                    # colder tiers first: promote any spilled pages of
                    # the chain bit-identically; whatever they don't
                    # cover recomputes through prefill as before
                    start = self._promote_from_tier(ids, node, new_from)
                    if start < node.tok_len:
                        self._prefill_prefix_pages(ids, node, start)
                except Exception:
                    # the new nodes hold uncommitted (garbage) KV: they
                    # must not survive to serve a later rider — and the
                    # credited hit never materialises
                    self.stats.prefix_hit_tokens -= new_from
                    self.prefix_cache.drop_tail(node, new_from)
                    raise
            if node is not None:
                try:
                    seq_id = self.rt.submit_prefixed(node.prefix_id,
                                                     len(ids), max_new_tokens)
                except ValueError:
                    # oversized request etc. — surface through the plain
                    # submit below so every path errors identically.  The
                    # request will prefill its FULL prompt, so the hit
                    # acquire() credited must be taken back
                    self.stats.prefix_hit_tokens -= new_from
                    self.prefix_cache.unpin(node)
                    node = None
        if node is None:
            seq_id = self.rt.submit(len(ids), max_new_tokens)
        return seq_id, node

    def release_request(self, seq_id: int, req: _Request) -> None:
        """Finish one request: free its scheduler sequence and unpin its
        prefix node (the cached pages stay — that is the point)."""
        self.rt.release(seq_id)
        if req.node is not None and self.prefix_cache is not None:
            self.prefix_cache.unpin(req.node)
            req.node = None

    def _prefill_prefix_pages(self, ids: list[int], node, new_from: int
                              ) -> None:
        """Prefill tokens ``[new_from, node.tok_len)`` into the node
        chain's newly inserted pages — ONCE; every current and future
        rider of these pages reuses the committed KV.  A non-zero
        ``new_from`` extends an existing cached prefix, so the new tokens
        attend the parent pages as gathered context.

        Runs at batch 1 per insert, but fetch-free: every call is async
        dispatch (upload + two jit calls, no host readback — measured
        bare dispatch RTT 0.026 ms, PERF round 4), so a cold batch's
        inserts queue on the device stream without host round-trips
        between them.  The cost vs a batched prefill is batch-1 MXU
        occupancy on work done once per distinct prefix — the same shape
        the old whole-batch template reserve used."""
        p = self.page_size
        tables_all = self.rt.block_table(node.prefix_id)
        n_start, n_end = new_from // p, node.tok_len // p
        n_pg = pow2_bucket(n_end - n_start)
        t = n_pg * p
        tokens = np.full((1, t), self.tokenizer.pad_id, np.int32)
        own = ids[new_from:node.tok_len]
        tokens[0, t - len(own):] = own
        pad = np.asarray([t - len(own)], np.int32)
        tables = np.zeros((1, n_pg), np.int32)
        tables[0, :n_end - n_start] = tables_all[n_start:n_end]
        t0 = time.perf_counter()
        kv = init_kv_cache(self.cfg, 1, t, dtype=self.params["embed"].dtype)
        dev_pad = self._dev(jnp.asarray(pad))
        if n_start == 0:
            _, kv = self._jit_prefill(self.params,
                                      tokens=self._dev(jnp.asarray(tokens)),
                                      pad_len=dev_pad, cache=kv)
        else:
            ctx_pg = pow2_bucket(n_start)
            ctx_tables = np.zeros((1, ctx_pg), np.int32)
            ctx_tables[0, :n_start] = tables_all[:n_start]
            _, kv = self._jit_prefill_pctx(
                self.params, tokens=self._dev(jnp.asarray(tokens)),
                pad_len=dev_pad,
                ctx_tables=self._dev(jnp.asarray(ctx_tables)),
                ctx_len=self._dev(jnp.asarray([new_from], jnp.int32)),
                paged=self.cache, cache=kv)
        self.cache = self._jit_commit(self.cache, kv, dev_pad,
                                      self._dev(jnp.asarray(tables)))
        self.stats.prefill_seconds += time.perf_counter() - t0
        self.stats.prefill_tokens += len(own)

    # -- hierarchical KV tiering (kv_tiers.py) -----------------------------
    def _chain_tokens(self, node) -> list[int]:
        """The full root→node token chain — the tier store's page
        identity (a page's KV depends on its entire attention prefix)."""
        keys = []
        while node is not None:
            keys.append(node.key)
            node = node.parent
        return [t for key in reversed(keys) for t in key]

    def _spill_node(self, node) -> None:
        """Prefix-cache eviction hook: dispatch the page's device-side
        gather (non-aliasing — the pool page is free to be reused the
        moment this returns) and hand the blocks to the copier.  Runs
        on the driver thread mid-eviction, so it must never raise and
        never block: a failed spill loses tier warmth, not the
        eviction."""
        try:
            tables = self.rt.block_table(node.prefix_id)
            page = int(tables[node.depth_pages - 1])
            blocks = self._jit_tier_gather(
                self.cache,
                self._dev(jnp.asarray([page], jnp.int32)))
            self.kv_tiers.spill(self._chain_tokens(node), blocks)
        except Exception as exc:  # noqa: BLE001 — see docstring
            self.stats.kvtier_spill_errors += 1
            log_event("kvtier.spill_error", level="warning", exc=exc)

    def _promote_from_tier(self, ids: list[int], node, new_from: int
                           ) -> int:
        """Promote the longest run of the chain's newly inserted pages
        (tokens ``[new_from, node.tok_len)``) available in a colder
        tier, sha256-verified, back into the pool.  Returns the token
        offset prefill must still cover from — every rung of the
        degrade ladder lands here as a counted + evented fallback to
        recompute, never a crash, never wrong KV."""
        if self.kv_tiers is None or new_from >= node.tok_len:
            return new_from
        p = self.page_size
        tables_all = self.rt.block_table(node.prefix_id)
        start = new_from
        for i in range(new_from // p, node.tok_len // p):
            entry = self.kv_tiers.lookup(ids[:(i + 1) * p])
            if entry is None:
                break
            from_disk = entry.payload is None
            t0 = time.perf_counter()
            try:
                blocks = self.kv_tiers.fetch(entry)
                self.cache = self._jit_tier_promote(
                    self.cache,
                    self._dev(jnp.asarray([int(tables_all[i])], jnp.int32)),
                    tuple(blocks))
            except Exception as exc:  # noqa: BLE001 — ladder floor:
                # anything a tier throws degrades to recompute
                reason = (exc.reason if isinstance(exc, TierError)
                          else "error")
                self.kv_tiers.drop(entry.key)
                self.stats.kvtier_recomputes += 1
                if reason == "integrity":
                    self.stats.kvtier_integrity_failures += 1
                    log_event("kvtier.integrity_failure", level="warning",
                              key=entry.key[:12], tier=entry.tier)
                log_event("kvtier.degrade", level="warning",
                          reason=reason, key=entry.key[:12],
                          tier=entry.tier, exc=exc)
                break
            self.stats.kvtier_promotions += 1
            if from_disk:
                self.stats.kvtier_disk_promotions += 1
            self.stats.registry.histogram(
                obs_metrics.KVTIER_PROMOTE_SECONDS).observe(
                time.perf_counter() - t0)
            start = (i + 1) * p
        return start

    # engine-local: the KV tier store is paged-pool machinery (page
    # granular spill/promote) — the session/bench probe it via hasattr
    def kv_tier_counters(self) -> dict:
        """The bench/watch ``kv_tier`` block: the EngineStats counter
        side plus the store's live gauges."""
        if self.kv_tiers is None:
            return {}
        return {**self.stats.kvtier_counters(),
                **self.kv_tiers.counters()}

    # engine-local: disk-tier drain hook (snapshot v2 sidecar) — only a
    # paged pool has pages to dump; the session probes it via hasattr
    def dump_tier_pages(self, dir_path: str) -> list[dict]:
        """Write every warm page — still resident in the pool or
        already spilled to host DRAM — into the snapshot sidecar
        directory; returns the per-page refs the v2 snapshot carries.
        Resident pages are read out synchronously (the engine is
        draining: no copier race, no tick to protect)."""
        if self.kv_tiers is None:
            return []
        if self.prefix_cache is not None:
            stack = list(self.prefix_cache.children.values())
            while stack:
                node = stack.pop()
                stack.extend(node.children.values())
                try:
                    tables = self.rt.block_table(node.prefix_id)
                    page = int(tables[node.depth_pages - 1])
                    blocks = self._jit_tier_gather(
                        self.cache,
                        self._dev(jnp.asarray([page], jnp.int32)))
                    # host-sync: drain-path download of resident pages —
                    # the engine is quiescing, there is no tick to stall
                    payload = [np.asarray(b) for b in blocks]
                    self.kv_tiers.put_host(self._chain_tokens(node),
                                           payload)
                except Exception as exc:  # noqa: BLE001 — a page that
                    # won't read still has its token chain in the v2
                    # doc; the restart recomputes it
                    self.stats.kvtier_spill_errors += 1
                    log_event("kvtier.spill_error", level="warning",
                              where="drain", exc=exc)
        self.kv_tiers.drain(timeout_s=5.0)
        return self.kv_tiers.write_disk(dir_path)

    # engine-local: disk-tier boot hook (snapshot v2 sidecar) — pairs
    # with dump_tier_pages; the session probes it via hasattr
    def attach_tier_refs(self, refs: list[dict], dir_path: str) -> int:
        """Hydrate disk-tier entries from a v2 snapshot's page refs so
        the following :meth:`rewarm` promotes real KV bytes instead of
        replaying prefill per chain.  Returns entries attached."""
        if self.kv_tiers is None:
            return 0
        return self.kv_tiers.attach_disk(refs, dir_path)

    def prefix_cache_counters(self) -> dict:
        """Prefix-cache gauge snapshot (hit/eviction COUNTERS live on
        ``stats``; same shape as the dp engine's aggregate)."""
        return (self.prefix_cache.counters()
                if self.prefix_cache is not None else {})

    def jit_counters(self) -> dict:
        """Compile-variant snapshot of the tracked jit entry points —
        the bench ``jit`` block and the PERF.md per-path compile-count
        baseline.  Summed from the trackers themselves (reset-proof
        against bench's ``EngineStats`` swaps); the same totals ride
        ``/metrics`` as ``reval_jit_compiles_total`` /
        ``reval_jit_cache_misses_total``."""
        return {"compiles": sum(t.variants for t in self._jit_trackers),
                "cache_misses": sum(t.misses for t in self._jit_trackers),
                "entries": {t.name: t.variants for t in self._jit_trackers},
                # total dispatches per entry (warmup included) — the
                # bench ragged block's dispatches-per-tick numerator and
                # the one-dispatch-per-tick contract's observable
                "calls": {t.name: t.calls for t in self._jit_trackers}}

    def aot_counters(self) -> dict:
        """AOT executable-cache snapshot — the bench ``restart`` block
        and the drill's "zero compilations of already-cached entries"
        assertion.  ``fresh_compiles`` counts the XLA compiles THIS
        process actually paid across the wrapped entries (0 on a fully
        warm restart)."""
        if self._aot_cache is None:
            return {"enabled": False}
        return {"enabled": True,
                "fresh_compiles": sum(
                    getattr(t, "fresh_compiles", 0)
                    for t in self._jit_trackers),
                **self._aot_cache.counters()}

    # -- warm-restart state (serving/snapshot.py rides these) --------------
    def warm_state(self) -> dict:
        """The engine half of a warm-state snapshot: every cached
        prefix chain as its full token list (leaf-to-root concatenated
        page keys — what a restarted engine must replay through prefill)
        plus the per-template affinity stats the fleet router's
        placement view keys on."""
        chains: list[list[int]] = []
        if self.prefix_cache is not None:
            stack = [(n, []) for n in self.prefix_cache.children.values()]
            while stack:
                node, prefix = stack.pop()
                chain = prefix + list(node.key)
                if node.children:
                    stack.extend((c, chain)
                                 for c in node.children.values())
                else:
                    chains.append(chain)
        return {"prefix_chains": chains,
                "template_stats": {str(k): v
                                   for k, v in self._template_stats.items()}}

    def rewarm(self, state: dict) -> int:
        """Replay a snapshot's prefix chains through REAL prefill so the
        radix cache (and its committed KV pages) is warm before
        ``/readyz`` flips.  Single-owner: run from the thread that owns
        the engine (the session driver does, before its drive loop).
        Each chain degrades independently — a chain the pool cannot hold
        (or that fails mid-prefill) is skipped, never fatal.  Returns
        chains replayed."""
        warmed = 0
        for chain in state.get("prefix_chains") or []:
            if (not isinstance(chain, list) or not chain
                    or len(chain) % self.page_size
                    or self.prefix_cache is None):
                continue
            try:
                # one token past the final page so acquire() covers every
                # page of the chain (its cap is (len-1) // page_size)
                ids = [int(t) for t in chain] + [self.tokenizer.pad_id]
                node, new_from = self.prefix_cache.acquire(ids)
                if node is None:
                    continue
                if new_from < node.tok_len:
                    try:
                        # the disk tier attached at boot serves real KV
                        # bytes here; only uncovered pages re-prefill
                        start = self._promote_from_tier(ids, node,
                                                        new_from)
                        if start < node.tok_len:
                            self._prefill_prefix_pages(ids, node, start)
                    except Exception:
                        # same rollback as submit_request: the new nodes
                        # hold uncommitted (garbage) KV — left alive they
                        # would serve a later rider silently wrong, and
                        # the pin (which rode down to the dropped tail)
                        # would keep the chain unevictable forever
                        self.stats.prefix_hit_tokens -= new_from
                        self.prefix_cache.drop_tail(node, new_from)
                        raise
                self.prefix_cache.unpin(node)
                warmed += 1
            except Exception:   # noqa: BLE001 — a cold chain beats a
                # wedged boot; the remaining chains still replay
                continue
            finally:
                # stamp PER CHAIN: an on-chip replay can compile per
                # prefill bucket (minutes), and a submission arriving
                # mid-warmup makes the session busy — a stale heartbeat
                # would trip the sticky watchdog and wedge the very boot
                # this replay exists to speed up
                self.heartbeat = time.monotonic()
        self.heartbeat = time.monotonic()
        restore_template_stats(self._template_stats,
                               state.get("template_stats"))
        return warmed

    def new_drive_state(self) -> _DriveState:
        return _DriveState(active={},
                           slot_token=np.zeros((self.max_slots, 1), np.int32),
                           slot_temp=np.zeros(self.max_slots, np.float32),
                           slot_topk=np.zeros(self.max_slots, np.int32),
                           slot_topp=np.ones(self.max_slots, np.float32))

    def _drive(self, reqs: dict[int, _Request]) -> None:
        """Blocking admission/prefill/decode loop until every request is
        done (the ``generate()`` path).  The continuous-batching session
        calls ``_drive_tick`` directly so it can inject new requests
        between chunks."""
        st = self.new_drive_state()
        while any(not r.done for r in reqs.values()):
            self._drive_tick(reqs, st)
        # `done` is only ever set while processing a fetched chunk, so the
        # loop cannot exit with one in flight; drain as a safety net
        self._process_pending(reqs, st)

    def _drive_tick(self, reqs: dict[int, _Request],  # hot-path
                    st: _DriveState) -> None:
        """One engine step (see :meth:`_tick`), timed into the
        ``reval_engine_step_seconds`` histogram — the per-step half of
        the measurement loop (FlashInfer-Bench's point: scheduler and
        kernel work only compound when the engine itself measures)."""
        t0 = time.perf_counter()
        try:
            # REVAL_TPU_JITCHECK: device->host transfer guard over the
            # whole tick, so an implicit sync anywhere in the drive loop
            # (helpers included) raises loudly at test time; the one
            # intended fetch is marked deliberate_fetch() in
            # _process_chunk.  A free nullcontext when the sanitizer is
            # off.
            with drive_guard():
                if self.ragged:
                    self._tick_ragged(reqs, st)
                else:
                    self._tick(reqs, st)
        finally:
            dt = time.perf_counter() - t0
            free = self.rt.free_pages if self.rt is not None else 0
            self.stats.registry.histogram(obs_metrics.ENGINE_STEP).observe(dt)
            self.stats.registry.gauge(obs_metrics.FREE_PAGES).set(free)
            fr = self.flightrec
            if fr.enabled:
                pc = self.prefix_cache
                if pc is not None and not (fr.total & 63):
                    # pinned_pages walks the radix tree: sample it every
                    # 64 ticks, not per record (the rest is O(1) reads)
                    self._pinned_sample = pc.pinned_pages
                fr.record(
                    len(st.active),
                    self.rt.num_waiting if self.rt is not None else 0,
                    free,
                    pc.cached_pages if pc is not None else 0,
                    self._pinned_sample,
                    self.kv_tiers.queue_depth
                    if self.kv_tiers is not None else 0,
                    self.stats.prefix_hit_tokens,
                    self.stats.spec_accepted_tokens,
                    st.pending[1] if st.pending is not None else 0,
                    dt,
                    time.monotonic() - self.heartbeat,
                    tuple(st.active.values()))

    def _tick(self, reqs: dict[int, _Request], st: _DriveState) -> None:  # hot-path
        """ONE admission + prefill + decode-chunk round over ``reqs`` —
        the split-dispatch drive tick (``_tick_ragged`` replaces it
        whenever the resolved backend is ``ragged``/``ragged_xla``).

        Loop state (tables, lens, pending token, per-slot temperature)
        lives ON DEVICE between chunks as the packed array `_decode_chunk`
        returns; it is rebuilt and re-uploaded only when the slot
        population changes (admission, retirement, preemption) or the
        table span bucket grows.  A clean steady-state chunk therefore
        costs one jit dispatch and one token download — everything else
        rides device-resident state.

        Raises RuntimeError when nothing is running *and* nothing could be
        admitted while undone requests remain (scheduler deadlock — e.g. a
        request larger than the whole pool).
        """
        self.heartbeat = time.monotonic()
        admitted = self.rt.admit()
        if (not admitted and self.rt.num_waiting
                and self.rt.num_running < self.max_slots
                and self.prefix_cache is not None):
            # a free slot exists but the pool is too full to admit — the
            # cache must yield before decode starves (cached-but-idle
            # prefixes lose to admission, same as they lose to preemption)
            while self.prefix_cache.evict_lru(1):
                admitted = self.rt.admit()
                if admitted:
                    break
        if admitted:
            # flush BEFORE prefilling: the admission prefill would
            # otherwise run (and wait behind the in-flight chunk on the
            # device stream) inside the pending chunk's dispatch→fetch
            # interval, double-charging its wall into both
            # prefill_seconds and decode_seconds
            self._process_pending(reqs, st)
            st.dirty = True
            st.since_admit = 0
            t_admit = time.perf_counter()
            firsts = self._prefill_admitted(admitted, reqs)
            t_first = time.perf_counter()
            for seq_id, slot in admitted:
                req = reqs[seq_id]
                # first admission only: a preemption resume keeps the
                # original stamps (the request's latency, not the slot's)
                if req.t_admit is None:
                    req.t_admit = t_admit
                # append, not reset: after a preemption the kept tokens
                # were replayed by the resume prefill and stand
                req.generated.append(firsts[slot])
                if req.grammar is not None:
                    req.gstate = self._grammars.walk(req.gstate,
                                                     [firsts[slot]])
                if req.t_first is None:
                    req.t_first = t_first
                st.slot_token[slot] = firsts[slot]
                st.slot_temp[slot] = req.temp
                st.slot_topk[slot] = req.top_k
                st.slot_topp[slot] = req.top_p
                st.active[slot] = seq_id
                if self._finished(req, [firsts[slot]]):
                    self._retire(req, seq_id, slot, st.active)
                    st.dirty = True
                if req.notify is not None:
                    req.notify(req)
        if not st.active:
            if any(not r.done for r in reqs.values()):
                # lint: allow(hotpath) — the deadlock raise is the tick's
                # terminal path; the steady-state loop never reaches it
                log_event("engine.deadlock", level="error",
                          waiting=self.rt.num_waiting,
                          free_pages=self.rt.free_pages)
                raise RuntimeError(
                    "paged scheduler deadlock: nothing running or admissible")
            return

        # ---- speculative verify rounds (decoding/) -------------------
        # When every active row is greedy and some row can draft, serve
        # the tick with ONE batched verify forward instead of a decode
        # chunk.  Any in-flight chunk flushes first: drafting reads the
        # rows' ground-truth tails, and the verify writes into pages the
        # chunk may still target.  A round that finds no drafts falls
        # through to the plain chunk path below (pending already None).
        #
        # The flush is only paid when it is likely to buy something: a
        # chunk-in-flight tick attempts speculation when a row looks
        # draft-promising (forced automaton state / indexed n-gram hit —
        # a slightly stale read, it gates scheduling only) or the dry
        # backoff expired.  Without the gate, a chronically draft-less
        # constrained workload (e.g. `line` bodies with n-gram lookup
        # off) would flush the one-deep pipeline EVERY tick and
        # reintroduce the per-chunk host serialization it exists to
        # hide; without the backoff retry, repetition arriving inside
        # the in-flight chunk (invisible to the probe) could starve
        # speculation forever.
        if self._spec_candidate(reqs, st):
            # the tail regime (≤ one steady chunk of budget left) always
            # attempts: the budget flush gate below is about to quiesce
            # the pipeline for these rows anyway, and REval's tiny
            # answers live entirely in this regime
            attempt = (st.pending is None or st.spec_backoff <= 0
                       or self._chunk_budget(reqs, st) <= CHUNK
                       or any(self._spec_eligible(reqs[s])
                              and self._spec_promising(reqs[s])
                              for s in st.active.values()))
            if not attempt:
                st.spec_backoff -= 1
            else:
                if st.pending is not None:
                    self._process_pending(reqs, st)
                if not st.active:
                    return              # the flush retired the last runner
                if self._spec_round(reqs, st):
                    st.spec_backoff = 0
                    return
                st.spec_backoff = self.SPEC_RETRY_BACKOFF

        # ---- one-deep chunk pipeline flush gates ---------------------
        # A steady tick dispatches the NEXT chunk before fetching the
        # PREVIOUS one (see ``pipeline`` in __init__).  Any condition
        # whose host logic needs the in-flight chunk's tokens — or that
        # would free/reallocate pages the in-flight chunk still writes —
        # fetches it first:
        #   dirty       slot population or tables changed (admission,
        #               retirement, preemption, span growth)
        #   budget 0    the in-flight steps consume some slot's whole
        #               remaining budget: ground truth needed
        #   page cross  the coming chunk would allocate pages, and
        #               allocation can preempt — in-flight writes must
        #               land before any page is freed for reuse
        if st.pending is not None and st.dirty:
            self._process_pending(reqs, st)
        if st.pending is not None and self._chunk_budget(reqs, st) <= 0:
            self._process_pending(reqs, st)
        if st.pending is not None:
            # A crossing that merely ALLOCATES can ride the pipeline
            # (tables are host-known — the reserve below patches the
            # device copy in place).  Flush only when the pool is short
            # enough that the reserve could preempt: in-flight writes
            # must land before any page is freed for reuse.  (Span
            # bucket growth is handled at the dispatch path, which
            # flushes and rebuilds when it detects the shape change.)
            need = self._pages_needed_next(st, self._next_chunk_steps(reqs, st))
            if need and self.rt.free_pages < need:
                self._process_pending(reqs, st)
        if not st.active:
            return                    # a flush retired the last runner

        steps = self._next_chunk_steps(reqs, st)
        st.since_admit += 1

        # every active sequence must have pages for the whole chunk
        # BEFORE the decode writes into them
        before = dict(st.active)
        grew = self._reserve_chunk(st.active, reqs, steps)
        preempted = st.active != before
        if preempted:
            st.dirty = True                 # a preemption emptied slots
        if grew:
            if st.pending is not None and not preempted:
                # pipelined crossing: the gate above guaranteed enough
                # free pages, so this reserve only allocated — patch the
                # new table entries into the device state in place
                self._patch_dev_tables(st)
            else:
                st.dirty = True             # table copy stale: repack
        if st.pending is not None and st.dirty:
            # unreachable by construction — the page-cross gate above
            # flushes before any reserve that could preempt; kept as a
            # correctness backstop.  Must run before the
            # everyone-preempted return below: a stale chunk surviving
            # into re-admission could append pre-preemption tokens after
            # the resume token.
            self._process_pending(reqs, st)
        if not st.active:
            return                          # everyone got preempted

        lens, new_span = self._lens_and_span(reqs, st, steps)
        if new_span != st.span and st.pending is not None:
            # span bucket growth changes the packed state's SHAPE — a
            # full repack is unavoidable and it needs the in-flight
            # chunk's tokens: quiesce, then rebuild from ground truth
            self._process_pending(reqs, st)
            if not st.active:
                return
            lens, new_span = self._lens_and_span(reqs, st, steps)
        if new_span != st.span:
            st.span = new_span
            st.dirty = True
        if st.dirty or st.dev_state is None:
            tables = np.zeros((self.max_slots, st.span), np.int32)
            keyarr = np.zeros((self.max_slots, 2), np.uint32)
            posarr = np.zeros(self.max_slots, np.int32)
            gstates = np.zeros(self.max_slots, np.int32)
            for slot, seq_id in st.active.items():
                tables[slot] = self.rt.block_table(seq_id)[:st.span]
                keyarr[slot] = reqs[seq_id].key
                posarr[slot] = len(reqs[seq_id].generated)
                gstates[slot] = reqs[seq_id].gstate
            packed = np.concatenate(
                [tables, lens[:, None], st.slot_token.astype(np.int32),
                 keyarr.view(np.int32), posarr[:, None],
                 gstates[:, None]], axis=1)
            st.dev_state = self._dev(jnp.asarray(packed))
            samp = np.stack([st.slot_temp, st.slot_topp,
                             st.slot_topk.astype(np.float32)], axis=1)
            st.dev_samp = self._dev(jnp.asarray(samp))
            st.dirty = False
        t0 = time.perf_counter()
        with jax.profiler.TraceAnnotation("reval.paged_decode_chunk"):
            # filtering can never change an argmax, so greedy rows
            # (temp 0) don't justify the filtered program's per-step
            # [B, V] sort even when they carry top_k/top_p values
            rows = list(st.active)
            filtered = bool(((st.slot_topk[rows] > 0)
                             | (st.slot_topp[rows] < 1.0))
                            [st.slot_temp[rows] > 0].any())
            # grammar masking compiles in only when a constrained row is
            # live (stable across steady-state chunks: the active set
            # only changes through st.dirty); the default program stays
            # byte-identical to the pre-grammar chunk
            grammared = any(reqs[s].grammar is not None
                            for s in st.active.values())
            gtables = self._grammar_tables() if grammared else None
            toks, self.cache, st.dev_state = self._jit_chunk(
                self.params, st.dev_state, self.cache, st.dev_samp,
                gtables, steps=steps, filtered=filtered,
                grammared=grammared)
        chunk = (toks, steps, tuple(st.active.items()), t0)
        prev, st.pending = st.pending, None
        if self.pipeline:
            # park this chunk; fetch the previous one BEHIND it — the
            # download RTT rides under this chunk's device time
            st.pending = chunk
            if prev is not None:
                self.stats.pipelined_chunks += 1
                self._process_chunk(reqs, st, prev)
        else:
            self._process_chunk(reqs, st, chunk)

    # -- ragged continuous batching (one wave, one dispatch) ---------------
    def _tick_ragged(self, reqs: dict[int, _Request],  # hot-path
                     st: _DriveState) -> None:
        """ONE continuous-batching round: admission, then a single
        ``paged.ragged_step`` dispatch serving every active row — rows
        still feeding their prompt ride the same wave as rows decoding
        and rows verifying draft windows, so a long prefill admits
        mid-decode without stalling running rows (it feeds
        ``RAGGED_FEED`` tokens per tick while they keep stepping).

        No prefill-wave/decode-chunk split, no pow2 context bucketing,
        no one-deep chunk pipeline: ``st.pending`` stays ``None`` and
        every tick fetches its own packed output (the flight recorder's
        in-flight field is therefore always 0 in ragged mode — the
        step-cadence contract the mock engine mirrors).  Compile
        variants stay bounded by the pow2 (span, window) buckets plus
        the static (steps, filtered, grammared) axes.

        Raises RuntimeError on scheduler deadlock, same contract as
        :meth:`_tick`.
        """
        self.heartbeat = time.monotonic()
        admitted = self.rt.admit()
        if (not admitted and self.rt.num_waiting
                and self.rt.num_running < self.max_slots
                and self.prefix_cache is not None):
            # same admission-starvation valve as _tick: cached-but-idle
            # prefixes yield before decode starves
            while self.prefix_cache.evict_lru(1):
                admitted = self.rt.admit()
                if admitted:
                    break
        if admitted:
            st.since_admit = 0
            t_admit = time.perf_counter()
            for seq_id, slot in admitted:
                req = reqs[seq_id]
                # first admission only: a preemption resume keeps the
                # original stamps (the request's latency, not the slot's)
                if req.t_admit is None:
                    req.t_admit = t_admit
                # feed resumes past the cached-prefix pages (their KV is
                # committed); a preemption resume re-feeds
                # prompt+generated the same way the incumbent re-prefills.
                # Clamped below the full prompt: even a fully-cached
                # prompt must feed ≥1 token — the wave has no other
                # source of first-token logits
                req.fed_target = len(req.prefill_ids)
                req.fed = min(self.rt.prefix_pages(seq_id) * self.page_size,
                              req.fed_target - 1)
                st.slot_temp[slot] = req.temp
                st.slot_topk[slot] = req.top_k
                st.slot_topp[slot] = req.top_p
                st.active[slot] = seq_id
        if not st.active:
            if any(not r.done for r in reqs.values()):
                # lint: allow(hotpath) — terminal path, never steady state
                log_event("engine.deadlock", level="error",
                          waiting=self.rt.num_waiting,
                          free_pages=self.rt.free_pages)
                raise RuntimeError(
                    "paged scheduler deadlock: nothing running or admissible")
            return

        # ---- plan the wave: per-row (kind, q_len, drafts) ------------
        plan: dict[int, tuple[str, int, list | None]] = {}
        feeding = verifying = False
        for slot, seq_id in st.active.items():
            req = reqs[seq_id]
            if req.fed < req.fed_target:
                plan[slot] = ("feed",
                              min(req.fed_target - req.fed, RAGGED_FEED),
                              None)
                feeding = True
            else:
                plan[slot] = ("decode", 1, None)
        if self.spec_enabled and all(reqs[s].temp == 0
                                     for s in st.active.values()):
            # greedy batches only (the accept contract is a greedy
            # contract — same eligibility as _spec_round); feed rows
            # keep feeding, draftable decode rows widen to a verify
            # window on the SAME wave
            for slot, seq_id in st.active.items():
                if plan[slot][0] != "decode":
                    continue
                req = reqs[seq_id]
                k = min(self.spec_k, req.max_new - len(req.generated) - 1)
                d = self._draft_for(req, k)
                if d:
                    plan[slot] = ("verify", 1 + len(d), d)
                    verifying = True
        steps = (self._next_chunk_steps(reqs, st)
                 if not (feeding or verifying) else 1)
        st.since_admit += 1
        w = pow2_bucket(max(q for _, q, _ in plan.values()))

        # ---- page reservation (may preempt; exact bookkeeping) -------
        for slot, seq_id in list(st.active.items()):
            if plan[slot][0] == "feed":
                # feed KV lands in pages the admission already allocated
                # for the prompt; the emitted token (final window only)
                # stays pending — nothing to advance
                continue
            need = plan[slot][1] + steps - 1
            while slot in st.active:     # we may become a victim ourselves
                if self.rt.advance(seq_id, need) is not None:
                    break
                if (self.prefix_cache is not None
                        and self.prefix_cache.evict_lru(1)):
                    continue
                victim = max(st.active.values())
                vreq = reqs[victim]
                # mid-feed victims land on prompt_len-1 (no pending
                # sampled token yet) — the runtime's valid lower bound
                kept = len(vreq.ids) + len(vreq.generated) - 1
                # lint: allow(hotpath) — preemption is the rare
                # pool-exhaustion path, never the steady-state tick
                log_event("engine.preempt", level="warning", seq_id=victim,
                          kept_tokens=kept, free_pages=self.rt.free_pages)
                self.rt.preempt(victim, kept)
                vslot = next(s for s, q in st.active.items() if q == victim)
                st.active.pop(vslot)
        plan = {s: p for s, p in plan.items() if s in st.active}
        if not st.active:
            return                          # everyone got preempted

        # ---- pack the wave ------------------------------------------
        b = self.max_slots
        lens = np.ones(b, np.int32)          # idle slots: trash pos 1
        for slot, seq_id in st.active.items():
            req = reqs[seq_id]
            lens[slot] = (req.fed if plan[slot][0] == "feed"
                          else len(req.ids) + len(req.generated) - 1)
        span = min(pow2_bucket(int((lens.max() + w + steps
                                    + self.page_size - 1) // self.page_size)),
                   self.max_pages_per_seq)
        tokens = np.zeros((b, w), np.int32)
        state = np.zeros((b, span + 7), np.int32)
        keyarr = np.zeros((b, 2), np.uint32)
        grammared = False
        for slot, seq_id in st.active.items():
            req = reqs[seq_id]
            kind, qlen, drafts = plan[slot]
            state[slot, :span] = self.rt.block_table(seq_id)[:span]
            state[slot, span] = lens[slot]
            state[slot, span + 1] = qlen
            state[slot, span + 5] = len(req.generated)
            state[slot, span + 6] = req.gstate
            keyarr[slot] = req.key
            grammared |= req.grammar is not None
            if kind == "feed":
                tokens[slot, :qlen] = req.prefill_ids[req.fed:req.fed + qlen]
            else:
                pending = int(st.slot_token[slot, 0])
                tokens[slot, 0] = pending
                if drafts:
                    state[slot, span + 2] = len(drafts)
                    # pad past the drafts with the pending token: padding
                    # can never be accepted (the accept rule caps at ndraft)
                    tokens[slot, 1:] = (drafts
                                        + [pending] * (w - 1 - len(drafts))
                                        )[:w - 1]
        state[:, span + 3:span + 5] = keyarr.view(np.int32)
        rows = list(st.active)
        filtered = bool(((st.slot_topk[rows] > 0)
                         | (st.slot_topp[rows] < 1.0))
                        [st.slot_temp[rows] > 0].any())
        gtables = self._grammar_tables() if grammared else None
        samp = np.stack([st.slot_temp, st.slot_topp,
                         st.slot_topk.astype(np.float32)], axis=1)

        # ---- the tick's ONE dispatch --------------------------------
        t0 = time.perf_counter()
        with jax.profiler.TraceAnnotation("reval.paged_ragged_step"):
            out_dev, self.cache = self._jit_ragged(
                self.params, self._dev(jnp.asarray(state)),
                self._dev(jnp.asarray(tokens)), self.cache,
                self._dev(jnp.asarray(samp)), gtables,
                steps=steps, filtered=filtered, grammared=grammared)
        with deliberate_fetch():
            # host-sync: the ragged tick's ONE deliberate fetch — the
            # packed wave output gates every host decision that follows
            out = np.asarray(out_dev)
        self.heartbeat = time.monotonic()
        now = time.perf_counter()
        wall = now - max(t0, st.t_mark)
        st.t_mark = now
        if all(k == "feed" for k, _, _ in plan.values()):
            self.stats.prefill_seconds += wall
            self.stats.registry.histogram(
                obs_metrics.PREFILL_BATCH).observe(wall)
        else:
            self.stats.decode_seconds += wall
            self.stats.registry.histogram(
                obs_metrics.DECODE_CHUNK).observe(wall)
            self.stats.decode_chunks += 1
            self.stats.decode_steps += steps
        if verifying:
            self.stats.spec_rounds += 1
        # wave occupancy: useful = the real (q_len + trailing chunk
        # steps) work each row asked for; padded = the b*(w+steps-1)
        # rectangle the one dispatch actually computed
        self.stats.ragged_ticks += 1
        self.stats.ragged_useful_tokens += sum(
            qlen + steps - 1 for _, qlen, _ in plan.values())
        self.stats.ragged_padded_tokens += len(plan) * (w + steps - 1)

        # ---- host half: accept, append, retire, notify ---------------
        for slot, seq_id in list(st.active.items()):
            req = reqs[seq_id]
            kind, qlen, drafts = plan[slot]
            if kind == "feed":
                req.fed += qlen
                self.stats.prefill_tokens += qlen
                if req.fed < req.fed_target:
                    continue                # mid-feed: nothing emitted yet
                first = int(out[slot, w + 1])
                # append, not reset: after a preemption the kept tokens
                # were re-fed and stand
                req.generated.append(first)
                if req.grammar is not None:
                    req.gstate = self._grammars.walk(req.gstate, [first])
                if req.t_first is None:
                    req.t_first = time.perf_counter()
                st.slot_token[slot] = first
                self.stats.generated_tokens += 1
                if self._finished(req, [first]):
                    self._retire(req, seq_id, slot, st.active)
                if req.notify is not None:
                    req.notify(req)
                continue
            if kind == "verify":
                nd = len(drafts)
                acc = min(int(out[slot, w]), nd)
                take = min(acc + 1, req.max_new - len(req.generated))
                new_toks = [int(t) for t in out[slot, :take]]
                used = max(0, take - 1)     # drafts that landed
                self.stats.spec_drafted_tokens += nd
                self.stats.spec_accepted_tokens += min(acc, used)
                self.stats.spec_rolled_back_tokens += nd - min(acc, used)
                self.stats.generated_tokens += take
                self.stats.registry.histogram(
                    obs_metrics.SPEC_ACCEPTED_PER_ROUND).observe(float(acc))
                req.generated.extend(new_toks)
                st.slot_token[slot] = new_toks[-1]
                if req.grammar is not None:
                    req.gstate = self._grammars.walk(req.gstate, new_toks)
                if take < qlen:
                    # exact page bookkeeping: return the rejected tail's
                    # reservation (pages past the covering count free)
                    self.rt.rollback(seq_id, int(lens[slot]) + take)
                if self._finished(req, new_toks):
                    self._retire(req, seq_id, slot, st.active)
                if req.notify is not None:
                    req.notify(req)
                continue
            chunk_ids = [int(t) for t in out[slot, w + 1:w + 1 + steps]]
            self.stats.generated_tokens += steps
            req.generated.extend(chunk_ids)
            if req.grammar is not None:
                req.gstate = self._grammars.walk(req.gstate, chunk_ids)
            st.slot_token[slot] = chunk_ids[-1]
            if self._finished(req, chunk_ids):
                self._retire(req, seq_id, slot, st.active)
            if req.notify is not None:
                req.notify(req)
        if feeding:
            # the first pure-decode tick after a feed completes keeps the
            # short-first-chunk admission semantics
            st.since_admit = 0

    # -- speculative verify path (reval_tpu/decoding/; ROADMAP item 2) -----
    def _grammar_tables(self):
        """Device upload of the combined constraint tables, rebuilt when
        the GrammarSet grew (state count pow2-padded so the compiled
        shape set stays bounded; pad rows behave FREE, unreachable)."""
        gs = self._grammars
        if self._gtab is None or self._gtab_version != gs.version:
            s = pow2_bucket(gs.n_states, 8)
            mask = np.ones((s, gs.vocab_size), np.bool_)
            nxt = np.zeros((s, gs.vocab_size), np.int32)
            mask[:gs.n_states] = gs.mask
            nxt[:gs.n_states] = gs.next
            self._gtab = (self._dev(jnp.asarray(mask)),
                          self._dev(jnp.asarray(nxt)))
            self._gtab_version = gs.version
        return self._gtab

    def _spec_eligible(self, req: _Request) -> bool:
        return (not req.spec_wedged and req.temp == 0
                and (req.grammar is not None or self.spec_eager))

    #: plain-chunk ticks a dry speculative attempt sits out before the
    #: next one may flush the pipeline again (see the spec gate in
    #: ``_tick``): a chronically draft-less workload keeps ~2/3 of its
    #: chunks pipelined instead of flushing every tick, while a workload
    #: that BECOMES draftable re-engages within a couple of chunks
    SPEC_RETRY_BACKOFF = 2

    def _spec_candidate(self, reqs: dict[int, _Request],
                        st: _DriveState) -> bool:
        """Cheap per-tick eligibility: speculation on and every active
        row greedy (the accept contract is a greedy contract — sampled
        rows ride plain chunks), with at least one row that may draft.
        Whether a flush is worth attempting rides the gate in ``_tick``
        (free with no chunk in flight; probe- or backoff-gated with
        one)."""
        if not self.spec_enabled or not st.active:
            return False
        rows = [reqs[s] for s in st.active.values()]
        if any(r.temp > 0 for r in rows):
            return False
        return any(self._spec_eligible(r) for r in rows)

    def _ngram_index(self, req: _Request):  # hot-path
        """The row's prompt-lookup index, synced to its PROCESSED tokens
        (incremental — each token is indexed once; an in-flight chunk's
        tokens land at the next sync).  None when n-gram drafting is
        off."""
        if not self.spec_ngram:
            return None
        idx = req.ngram
        if idx is None:
            idx = req.ngram = NgramIndex(self.spec_ngram)
        stream = req.prefill_ids
        if len(idx.toks) < len(stream):
            idx.extend(stream[len(idx.toks):])
        return idx

    def _spec_promising(self, req: _Request) -> bool:  # hot-path
        """Could this row plausibly draft?  Reads state one in-flight
        chunk stale at worst (see :meth:`_spec_candidate`)."""
        if (req.grammar is not None and req.gstate != 0
                and int(self._grammars.forced[req.gstate]) >= 0):
            return True
        idx = self._ngram_index(req)
        return idx is not None and idx.match(idx.toks) is not None

    def _draft_for(self, req: _Request, k: int) -> list[int]:  # hot-path
        """Up to ``k`` drafts for one row (grammar forcing + n-gram
        prompt lookup).  ANY drafter fault wedges only this request —
        spec.wedge degrade: it rides plain decode from here on, the
        batch keeps speculating."""
        if k <= 0 or not self._spec_eligible(req):
            return []
        try:
            gs = self._grammars if req.grammar is not None else None
            drafts, forced = propose_drafts(self._ngram_index(req), k, gs,
                                            req.gstate)
            self.stats.grammar_forced_tokens += forced
            return drafts
        except Exception as exc:   # noqa: BLE001 — any drafter fault
            req.spec_wedged = True
            self.stats.spec_wedges += 1
            # lint: allow(hotpath) — the wedge event is the rare
            # once-per-request degrade path, never the steady state
            log_event("spec.wedge", level="warning", error=repr(exc),
                      grammar=req.grammar)
            return []

    def _spec_round(self, reqs: dict[int, _Request],  # hot-path
                    st: _DriveState) -> bool:
        """One speculative verify round over the active slots: draft,
        reserve pages for the whole window, dispatch ONE batched verify
        forward, then host-side accept/rollback with exact page
        bookkeeping.  Returns False (caller falls back to a plain
        chunk) when no row produced drafts or the window cannot fit the
        smallest remaining budget; True = this tick is served.

        Every greedy row advances ≥1 token per round (the bonus target
        IS the plain greedy next token), so draft-less rows ride along
        rather than stall.  Rejected drafts roll the runtime length
        back (``PagedRuntime.rollback``) so their reserved pages free —
        the same exact-bookkeeping contract as the PR-10 rewarm
        rollback; their stale KV sits past the accepted length, masked
        by attention and overwritten in place by the next write there.
        """
        budget = self._chunk_budget(reqs, st)      # st.pending is None here
        # pow2-floored window (the _next_chunk_steps idiom): an unpadded
        # min(K+1, budget) would compile a fresh verify variant for every
        # shrinking budget tail (w = 9, 8, 7, ... near max_new) — flooring
        # keeps the compiled window set at {2, 4, 8, ...} and never
        # reserves past the smallest remaining budget
        w = _floor_pow2(min(self.spec_k + 1, budget))
        if w < 2:
            return False
        drafts = {slot: self._draft_for(reqs[seq_id], w - 1)
                  for slot, seq_id in st.active.items()}
        if not any(drafts.values()):
            return False
        before = dict(st.active)
        self._reserve_chunk(st.active, reqs, w)
        if st.active != before:
            st.dirty = True
        if not st.active:
            return True                            # everyone got preempted
        lens, span = self._lens_and_span(reqs, st, w)
        b = self.max_slots
        tokens = np.zeros((b, w), np.int32)
        ndraft = np.zeros(b, np.int32)
        gstates = np.zeros(b, np.int32)
        tables = np.zeros((b, span), np.int32)
        ctx_pages = 1
        grammared = False
        for slot, seq_id in st.active.items():
            req = reqs[seq_id]
            d = drafts.get(slot) or []
            pending = int(st.slot_token[slot, 0])
            tokens[slot, 0] = pending
            # pad past the drafts with the pending token: padding can
            # never be accepted (the accept rule caps at ndraft)
            tokens[slot, 1:] = (d + [pending] * (w - 1 - len(d)))[: w - 1]
            ndraft[slot] = len(d)
            gstates[slot] = req.gstate
            grammared |= req.grammar is not None
            tables[slot] = self.rt.block_table(seq_id)[:span]
            ctx_pages = max(ctx_pages,
                            -(-int(lens[slot]) // self.page_size))
        ctx_pages = min(pow2_bucket(ctx_pages), self.max_pages_per_seq)
        kvbuf = init_kv_cache(self.cfg, b, w,
                              dtype=self.params["embed"].dtype)
        gmask, gnext = self._grammar_tables() if grammared else (None, None)
        t0 = time.perf_counter()
        with jax.profiler.TraceAnnotation("reval.paged_verify_chunk"):
            out_dev, self.cache = self._jit_verify(
                self.params, self._dev(jnp.asarray(tables)),
                self._dev(jnp.asarray(tables[:, :ctx_pages])),
                self._dev(jnp.asarray(lens)),
                self._dev(jnp.asarray(tokens)),
                self._dev(jnp.asarray(ndraft)),
                self._dev(jnp.asarray(gstates)),
                self.cache, kvbuf, gmask, gnext, grammared=grammared)
        with deliberate_fetch():
            # host-sync: the verify round's ONE deliberate fetch — the
            # accept verdicts gate every host decision that follows
            out = np.asarray(out_dev)
        self.heartbeat = time.monotonic()
        now = time.perf_counter()
        wall = now - max(t0, st.t_mark)
        st.t_mark = now
        self.stats.decode_seconds += wall
        self.stats.registry.histogram(obs_metrics.DECODE_CHUNK).observe(wall)
        self.stats.decode_steps += 1               # ONE weight pass
        self.stats.spec_rounds += 1
        hist = self.stats.registry.histogram(
            obs_metrics.SPEC_ACCEPTED_PER_ROUND)
        for slot, seq_id in list(st.active.items()):
            req = reqs[seq_id]
            nd = int(ndraft[slot])
            acc = min(int(out[slot, w]), nd)
            take = min(acc + 1, req.max_new - len(req.generated))
            new_toks = [int(t) for t in out[slot, :take]]
            used = max(0, take - 1)                # drafts that landed
            self.stats.spec_drafted_tokens += nd
            self.stats.spec_accepted_tokens += min(acc, used)
            self.stats.spec_rolled_back_tokens += nd - min(acc, used)
            self.stats.generated_tokens += take
            hist.observe(float(acc))
            req.generated.extend(new_toks)
            st.slot_token[slot] = new_toks[-1]
            if req.grammar is not None:
                req.gstate = self._grammars.walk(req.gstate, new_toks)
            if take < w:
                # exact page bookkeeping: return the rejected tail's
                # reservation (pages past the covering count free)
                self.rt.rollback(seq_id, int(lens[slot]) + take)
            if self._finished(req, new_toks):
                self._retire(req, seq_id, slot, st.active)
            if req.notify is not None:
                req.notify(req)
        st.dirty = True          # lens moved per-row: repack before any
        #                          plain chunk rides the packed state
        return True

    def _next_chunk_steps(self, reqs: dict[int, _Request],
                          st: _DriveState) -> int:
        """Steps the NEXT dispatched chunk will run: the admission-aware
        cap (short first chunk after an admission wave, full CHUNK at
        steady state) floored to a power of two within the remaining
        token budget.  The ONE definition shared by the page-cross gate
        and the dispatch path — they used to duplicate it, coupled only
        by the unasserted invariant that a pending chunk implies
        ``since_admit >= 1``; a drift would let the gate underestimate
        pages and reintroduce a preempting reserve under an in-flight
        chunk (ADVICE r5)."""
        cap = FIRST_CHUNK if st.since_admit == 0 else CHUNK
        return _floor_pow2(min(cap, self._chunk_budget(reqs, st)))

    def _chunk_budget(self, reqs: dict[int, _Request],
                      st: _DriveState) -> int:
        """Smallest remaining new-token budget over the running slots,
        counting tokens an in-flight chunk will deliver as spent."""
        pend = dict(st.pending[2]) if st.pending is not None else {}
        psteps = st.pending[1] if st.pending is not None else 0
        return min(reqs[s].max_new - len(reqs[s].generated)
                   - (psteps if pend.get(slot) == s else 0)
                   for slot, s in st.active.items())

    def _lens_and_span(self, reqs: dict[int, _Request], st: _DriveState,
                       steps: int) -> tuple[np.ndarray, int]:
        """Per-slot materialised lengths (prompt + generated, counting
        any in-flight chunk's tokens, minus the pending input token) and
        the pow2 table-span bucket a ``steps`` chunk needs.  The
        attention kernel walks every table column it is given — the span
        slices the tables to the pages this chunk can actually touch,
        bucketed so the compiled shape set stays small."""
        pend_rows = dict(st.pending[2]) if st.pending is not None else {}
        pend_steps = st.pending[1] if st.pending is not None else 0
        lens = np.ones(self.max_slots, np.int32)   # idle slots: trash pos 1
        for slot, seq_id in st.active.items():
            req = reqs[seq_id]
            lens[slot] = (len(req.ids) + len(req.generated) - 1
                          + (pend_steps if pend_rows.get(slot) == seq_id
                             else 0))
        span = pow2_bucket(
            int((lens.max() + steps + self.page_size - 1) // self.page_size))
        return lens, min(span, self.max_pages_per_seq)

    def _pages_needed_next(self, st: _DriveState, steps: int) -> int:
        """Pages ``_reserve_chunk`` would have to allocate for a chunk of
        ``steps`` (0 ⇒ the reserve provably cannot preempt).  Counts full
        page-count deltas, so any page_size — even smaller than the
        chunk — is handled.  Conservative when a rollback left a
        sequence holding spare pages (the runtime then allocates fewer
        than this estimate, never more)."""
        p = self.page_size
        need = 0
        for seq_id in st.active.values():
            ln = self.rt.seq_len(seq_id)
            need += (ln + steps + p - 1) // p - (ln + p - 1) // p
        return need

    def _patch_dev_tables(self, st: _DriveState) -> None:
        """Write the runtime's current block tables over the device
        state's table columns without a fetch — the counterpart of a
        full repack for the allocation-only crossing case.  Chained on
        the in-flight chunk's output, so device ordering stays
        dispatch-order."""
        tables = np.zeros((self.max_slots, st.span), np.int32)
        for slot, seq_id in st.active.items():
            tables[slot] = self.rt.block_table(seq_id)[:st.span]
        st.dev_state = self._jit_patch(st.dev_state,
                                       self._dev(jnp.asarray(tables)))
        self.stats.patched_tables += 1

    def _process_pending(self, reqs: dict[int, _Request],  # hot-path
                         st: _DriveState) -> None:
        chunk, st.pending = st.pending, None
        if chunk is not None:
            self._process_chunk(reqs, st, chunk)

    def _process_chunk(self, reqs: dict[int, _Request],  # hot-path
                       st: _DriveState, chunk: tuple) -> None:
        """Host half of a dispatched chunk: fetch tokens, append,
        stop-scan, retire, notify.  In pipelined mode this runs one chunk
        behind dispatch; a sequence retired here may have one further
        chunk in flight whose tokens are then discarded — the same
        truncation semantics as in-chunk stop overrun, one chunk later.
        Its pages stay allocated until this retire runs, so the in-flight
        writes always land in still-owned pages."""
        toks_dev, steps, rows, t0 = chunk
        with deliberate_fetch():
            # host-sync: the chunk's ONE deliberate fetch — stop scanning
            # and retirement need ground-truth tokens (everything else in
            # the tick rides device-resident state)
            toks_host = np.asarray(toks_dev)
        # the fetch returned: the device demonstrably made progress
        self.heartbeat = time.monotonic()
        now = time.perf_counter()
        # union-of-intervals: overlapped dispatch→fetch spans must not
        # double-count decode wall time
        span = now - max(t0, st.t_mark)
        self.stats.decode_seconds += span
        self.stats.registry.histogram(obs_metrics.DECODE_CHUNK).observe(span)
        st.t_mark = now
        # generated_tokens counts DELIVERED work: rows whose sequence
        # retired while this chunk was in flight computed `steps` tokens
        # that are discarded below, and folding them in would inflate
        # the pipelined tok/s (and bench.py's tokens_per_sec, derived as
        # generated_tokens / decode_seconds) relative to delivered
        # output.  In-chunk overrun past a stop string still counts —
        # the row was live when the chunk was cut.
        delivered = sum(1 for slot, seq_id in rows
                        if st.active.get(slot) == seq_id)
        self.stats.generated_tokens += steps * delivered
        self.stats.decode_chunks += 1
        self.stats.decode_steps += steps

        for slot, seq_id in rows:
            if st.active.get(slot) != seq_id:
                continue       # retired while this chunk was in flight
            req = reqs[seq_id]
            chunk_ids = [int(t) for t in toks_host[slot]]
            req.generated.extend(chunk_ids)
            if req.grammar is not None:
                # host mirror of the in-chunk table walk: the drafter
                # and the next repack read req.gstate
                req.gstate = self._grammars.walk(req.gstate, chunk_ids)
            st.slot_token[slot] = chunk_ids[-1]
            if self._finished(req, chunk_ids):
                self._retire(req, seq_id, slot, st.active)
                st.dirty = True
            if req.notify is not None:
                req.notify(req)
        if not st.active and st.pending is not None:
            # the last running sequence just retired with its successor
            # chunk still in flight: drain NOW.  A serving session can
            # otherwise idle for minutes before its next tick reaches a
            # flush gate, and that whole gap would be charged to
            # decode_seconds when the stale chunk is finally fetched
            # (dp_paged's per-call drive would leak the buffer outright).
            self._process_pending(reqs, st)

    # -- host-side helpers -------------------------------------------------
    def _dev(self, arr):
        if self._replicated is not None:
            return jax.device_put(arr, self._replicated)
        return arr

    def _finished(self, req: _Request, new_ids: list[int]) -> bool:
        return (len(req.generated) >= req.max_new
                or req.scanner.hit_new(new_ids))

    def _retire(self, req: _Request, seq_id: int, slot: int,
                active: dict[int, int]) -> None:
        req.done = True
        req.t_done = time.perf_counter()
        self.stats.observe_request(req)
        self.release_request(seq_id, req)
        active.pop(slot, None)

    def _reserve_chunk(self, active: dict[int, int],
                       reqs: dict[int, _Request], steps: int) -> bool:
        """Pre-allocate pages so a chunk of ``steps`` writes cannot land
        outside a sequence's block table; preempt on pool exhaustion.
        Returns True when any sequence's block table gained a page (the
        device-resident table copy is then stale and must re-upload)."""
        grew = False
        for slot, seq_id in list(active.items()):
            while slot in active:            # we may become a victim ourselves
                target = self.rt.advance(seq_id, steps)
                if target is not None:
                    p = self.page_size
                    if (target + p - 1) // p != (target - steps + p - 1) // p:
                        grew = True
                    break
                # pool exhausted: cached-but-idle prefixes go first —
                # evicting an LRU rider-free node costs a future prefill,
                # preempting a running sequence costs a recompute NOW
                if (self.prefix_cache is not None
                        and self.prefix_cache.evict_lru(1)):
                    continue
                # youngest running sequence is the victim; WE report how many
                # tokens its pages really hold — a victim whose advance()
                # already reserved this chunk must not fold those phantom
                # (never-executed) steps into its resume prompt
                victim = max(active.values())
                vreq = reqs[victim]
                log_event("engine.preempt", level="warning", seq_id=victim,
                          kept_tokens=len(vreq.ids) + len(vreq.generated) - 1,
                          free_pages=self.rt.free_pages)
                self.rt.preempt(victim, len(vreq.ids) + len(vreq.generated) - 1)
                # generated tokens are KEPT: the runtime folded them into the
                # victim's prompt_len, so re-admission prefills prompt+generated
                # and decoding resumes (no resampling at temperature > 0)
                vslot = next(s for s, q in active.items() if q == victim)
                active.pop(vslot)
        return grew

    def _prefill_admitted(self, admitted: list[tuple[int, int]],
                          reqs: dict[int, _Request]) -> dict[int, int]:
        """Prefill all just-admitted sequences, batched by prompt bucket.

        Split-dispatch mode only — a ``ragged``/``ragged_xla`` backend
        never calls this: ``_tick_ragged`` feeds admitted prompts as
        ragged windows of the shared wave instead, with no pow2 prompt
        bucketing and no separate prefill program.

        Sequences sharing a page bucket prefill as ONE left-padded batch
        (padded to a power-of-two row count to bound compile variants;
        dummy rows are all-padding and commit to the trash page) and their
        KV lands in the paged cache with a single scatter.  Returns
        slot → first sampled token.
        """
        # group by (prefix-page bucket, own-page bucket): rows of one group
        # share compiled shapes but each rides its OWN cached prefix (the
        # tables and ctx lengths are per-row operands) — this is what lets
        # one admission wave mix several templates.  prefix_pages is
        # per-sequence: a rider whose cached prefix died before admission
        # (detached by the runtime) lands in the 0-bucket and prefills its
        # FULL prompt, and a resumed preemption victim prefills
        # prompt+generated, which may land in a larger bucket
        by_bucket: dict[tuple[int, int], list[tuple[int, int]]] = {}
        for seq_id, slot in admitted:
            req = reqs[seq_id]
            npre = self.rt.prefix_pages(seq_id)
            ctx_pg = pow2_bucket(npre) if npre else 0
            own = len(req.prefill_ids) - npre * self.page_size
            n_pg = pow2_bucket((own + self.page_size - 1) // self.page_size)
            by_bucket.setdefault((ctx_pg, n_pg), []).append((seq_id, slot))

        per_token_kv = (self.cfg.num_layers * self.cfg.num_kv_heads *
                        self.cfg.head_dim * 2 *
                        jnp.dtype(self.params["embed"].dtype).itemsize)
        token_budget = max(self.page_size, PREFILL_BYTE_BUDGET // per_token_kv)
        firsts: dict[int, int] = {}
        t0 = time.perf_counter()
        # One-deep overlap (mirrors the decode chunk pipeline): harvest
        # group i's sampled tokens AFTER dispatching group i+1, so the
        # per-group host RTT rides behind the next group's device time.
        # Device-side memory stays bounded: programs execute in dispatch
        # order, so group i's transient KV block is consumed by its
        # commit before group i+1's prefill runs — at most one extra
        # block is allocated-but-not-yet-live, covered by the 1 GiB
        # workspace reserve in _pages_for_budget.
        pend = None
        for (ctx_pg, n_pg), full_group in by_bucket.items():
            t = n_pg * self.page_size
            step = max(1, token_budget // t)
            for start in range(0, len(full_group), step):
                g = full_group[start:start + step]
                first_dev = self._prefill_group(g, ctx_pg, n_pg, t, reqs)
                if self.pipeline:
                    if pend is not None:
                        self._harvest_first(*pend, firsts)
                    pend = (g, first_dev)
                else:
                    self._harvest_first(g, first_dev, firsts)
        if pend is not None:
            self._harvest_first(*pend, firsts)
        wall = time.perf_counter() - t0
        self.stats.prefill_seconds += wall
        self.stats.registry.histogram(obs_metrics.PREFILL_BATCH).observe(wall)
        return firsts

    @staticmethod
    def _harvest_first(group, first_dev, firsts: dict[int, int]) -> None:
        with deliberate_fetch():
            # host-sync: the prefill wave's ONE deliberate fetch per
            # group — the first sampled tokens; the pipelined caller
            # overlaps this fetch with the next group's dispatch
            first_host = np.asarray(first_dev)
        for row, (_, slot) in enumerate(group):
            firsts[slot] = int(first_host[row])

    def _prefill_group(self, group, ctx_pg: int, n_pg: int, t: int,
                       reqs: dict[int, _Request]):
        """Dispatch one bucketed prefill+commit+sample; returns the
        device array of first sampled tokens WITHOUT fetching (the
        caller overlaps the fetch with the next group's dispatch).

        ``ctx_pg`` > 0 rows each attend their OWN cached prefix, gathered
        from pool pages via per-row context tables (prefix lengths vary
        within the bucket; trash-page padding is masked by ``ctx_len``).
        """
        rows = pow2_bucket(len(group))
        tokens = np.full((rows, t), self.tokenizer.pad_id, np.int32)
        pad_len = np.full(rows, t, np.int32)        # dummy rows: all pad
        tables = np.zeros((rows, n_pg), np.int32)   # dummy rows: trash
        ctx_tables = np.zeros((rows, max(ctx_pg, 1)), np.int32)
        ctx_len = np.zeros(rows, np.int32)
        temps = np.zeros(rows, np.float32)          # dummy rows: greedy
        topks = np.zeros(rows, np.int32)
        topps = np.ones(rows, np.float32)
        keys = np.zeros((rows, 2), np.uint32)
        poss = np.zeros(rows, np.int32)
        gstates = np.zeros(rows, np.int32)          # dummy rows: FREE
        for row, (seq_id, _) in enumerate(group):
            req = reqs[seq_id]
            gstates[row] = req.gstate
            npre = self.rt.prefix_pages(seq_id)
            skip = npre * self.page_size
            ids = req.prefill_ids[skip:]            # own (suffix) tokens
            tokens[row, t - len(ids):] = ids
            pad_len[row] = t - len(ids)
            temps[row] = req.temp
            topks[row] = req.top_k
            topps[row] = req.top_p
            keys[row] = req.key
            poss[row] = len(req.generated)   # resume continues the stream
            table = self.rt.block_table(seq_id)
            ctx_tables[row, :npre] = table[:npre]
            ctx_len[row] = skip
            # own pages sit after the shared-prefix pages in the table
            own = table[npre:npre + n_pg]
            tables[row, : len(own)] = own
            self.stats.prefill_tokens += len(ids)
        kv = init_kv_cache(self.cfg, rows, t,
                           dtype=self.params["embed"].dtype)
        dev_pad = self._dev(jnp.asarray(pad_len))
        with jax.profiler.TraceAnnotation("reval.paged_prefill"):
            if ctx_pg:
                logits, kv = self._jit_prefill_pctx(
                    self.params, tokens=self._dev(jnp.asarray(tokens)),
                    pad_len=dev_pad,
                    ctx_tables=self._dev(jnp.asarray(ctx_tables)),
                    ctx_len=self._dev(jnp.asarray(ctx_len)),
                    paged=self.cache, cache=kv)
            else:
                logits, kv = self._jit_prefill(
                    self.params, tokens=self._dev(jnp.asarray(tokens)),
                    pad_len=dev_pad, cache=kv)
            self.cache = self._jit_commit(self.cache, kv, dev_pad,
                                          self._dev(jnp.asarray(tables)))
        row_keys = jax.vmap(jax.random.fold_in)(
            self._dev(jnp.asarray(keys)), self._dev(jnp.asarray(poss)))
        first_logits = logits[:, 0, :]
        if (gstates != 0).any():
            # the FIRST sampled token rides prefill logits, not the
            # chunk: constrained rows must be masked here too or the
            # answer's opening token could fall outside the grammar
            # (same -1e30 constant as the chunk/verify masks)
            gmask, _ = self._grammar_tables()
            first_logits = jnp.where(gmask[self._dev(jnp.asarray(gstates))],
                                     first_logits, -1e30)
        if (topks > 0).any() or (topps < 1.0).any():
            first_logits = filter_logits(first_logits,
                                         self._dev(jnp.asarray(topks)),
                                         self._dev(jnp.asarray(topps)),
                                         self._dev(jnp.asarray(temps)))
        return sample_token_rows(first_logits,
                                 self._dev(jnp.asarray(temps)), row_keys)
