"""The in-tree TPU inference engine (JAX/XLA/Pallas).

Replaces the reference's vLLM/CUDA arms (inference.py:75-131) with
in-process generation: HF safetensors checkpoints loaded into pjit-sharded
JAX pytrees, jitted prefill + decode with an on-device KV cache, and
batched scheduling of whole prompt sets.
"""

from .backend import TPUBackend

__all__ = ["TPUBackend"]
