"""TPUBackend: the InferenceBackend facade over the JAX engine.

Construction wires tokenizer + model + engine; ``infer_many`` feeds the
whole prompt set through batched generation.
"""

from __future__ import annotations

from ..base import InferenceBackend

__all__ = ["TPUBackend"]


class TPUBackend(InferenceBackend):
    def __init__(self, model_id: str, model_path: str | None = None, temp: float = 0.8,
                 prompt_type: str = "direct", dtype: str = "bfloat16",
                 num_chips: int = 1, dp_size: int = 1, pp_size: int = 1,
                 sp_size: int = 1, batch_size: int = 8,
                 max_seq_len: int = 8192, local_devices_only: bool = False,
                 engine: str | None = None, kv_dtype: str = "",
                 memory_utilization: float | None = None,
                 kv_tiering: bool | None = None, tier_chaos=None,
                 **kwargs):
        """``engine``: "paged" (continuous batching over the paged KV
        cache + native scheduler) or "static" (rectangular batches; the
        dp/sp/pp sharding paths live here).  Default (None) auto-selects:
        paged, unless pp_size/sp_size>1 demand the static engine.
        Explicitly requesting "paged" together with pp/sp is an error
        rather than a silent engine swap.

        ``pp_size``: >1 selects the pipeline-parallel static engine
        (GPipe prefill + token-ring decode over pp stages, composed with
        ``num_chips``-wide tp per stage) for layer stacks that exceed a
        tp-sharded chip's HBM.

        ``sp_size``: >1 adds sequence parallelism on the static engine —
        ring-attention prefill with the sequence (and KV cache) sharded
        over sp, for prompts past one chip's attention working set.

        ``dtype``: "bfloat16" (default), "float32", "int8", or "int4" —
        weight-only int8 quantization (models/quant.py): bf16 compute,
        halved weight HBM reads, ~2× params per chip (6.7b-class models
        fit a single 16 GB v5e).

        ``kv_dtype``: "" (KV pages stored in the activation dtype) or
        "int8" — quantized page pool with per-(token, head) scales
        (models/paged.py): half the pool HBM and attention read
        traffic.

        ``memory_utilization``: size the paged KV pool from the device's
        reported HBM (pool = util × HBM − weights − workspace) — the
        reference's ``gpu_memory_utilization`` vLLM kwarg (reference
        inference.py:93).  None (default) reserves max_seq_len per slot;
        paged engines only.

        ``kv_tiering``: hierarchical KV page tiering behind the paged
        prefix cache (inference/tpu/kv_tiers.py; default None reads
        ``REVAL_TPU_KVTIER``); ``tier_chaos`` a seeded
        :class:`~reval_tpu.resilience.TierChaos` promotion-fault
        injector (paged engines only — loud error otherwise)."""
        super().__init__(model_id, temp=temp, prompt_type=prompt_type)
        if not model_path:
            raise ValueError(
                "TPU backend needs model_path (a HuggingFace checkpoint directory "
                "containing config.json + *.safetensors)"
            )
        if sp_size > 1 and pp_size > 1:
            raise ValueError("sp_size and pp_size cannot combine yet — "
                             "pick sequence OR pipeline parallelism")
        if dp_size > 1 and pp_size > 1:
            raise ValueError(
                "dp_size and pp_size cannot combine yet — the pipelined "
                "engine has no dp axis, so dp_size>1 would silently run at "
                "1/dp throughput; drop one of the two")
        if engine == "paged" and (sp_size > 1 or pp_size > 1):
            raise ValueError(
                "sequence/pipeline parallelism runs on the static engine "
                "(the paged scheduler has no sp/pp path) — drop the "
                "explicit engine='paged' or the sp_size/pp_size")
        import jax

        cross_process = (not local_devices_only and jax.process_count() > 1)
        if engine == "paged" and cross_process:
            raise ValueError(
                "multihost 'global' mode (mesh over every host's chips) "
                "runs on the static engine — the paged scheduler's "
                "host-side state is per-process.  Drop engine='paged', or "
                "use multihost 'replicate' for per-host paged engines")
        if engine is None:
            engine = ("static" if (sp_size > 1 or pp_size > 1 or cross_process)
                      else "paged")
        if tier_chaos is not None and engine != "paged":
            raise ValueError(
                "tier_chaos injects KV-tier promotion faults, a paged-"
                "pool feature (inference/tpu/kv_tiers.py) — drop "
                "tier_chaos or use engine='paged'")
        if pp_size > 1:
            # pipeline parallelism implies the static engine (the paged
            # scheduler has no pp path); kv_dtype is a paged-pool feature
            if kv_dtype:
                raise ValueError("kv_dtype requires the paged engine, "
                                 "which has no pipeline-parallel path — "
                                 "drop kv_dtype or pp_size")
            if memory_utilization is not None:
                raise ValueError("memory_utilization requires the paged "
                                 "engine, which has no pipeline-parallel "
                                 "path — drop memory_utilization or pp_size")
            from .pp_engine import PipelinedTPUEngine

            self.engine = PipelinedTPUEngine.from_pretrained(
                model_path, dtype=dtype, pp_size=pp_size, tp_size=num_chips,
                batch_size=batch_size, max_seq_len=max_seq_len,
                local_devices_only=local_devices_only,
            )
        elif engine == "paged" and dp_size == 1:
            from .paged_engine import PagedTPUEngine

            self.engine = PagedTPUEngine.from_pretrained(
                model_path, dtype=dtype, tp_size=num_chips,
                max_slots=batch_size, max_seq_len=max_seq_len,
                local_devices_only=local_devices_only, kv_dtype=kv_dtype,
                memory_utilization=memory_utilization,
                kv_tiering=kv_tiering, tier_chaos=tier_chaos,
            )
        elif engine == "paged":
            # dp>1 with continuous batching: one paged replica per device
            # group (v5e-8 flagship shape: dp=2 × tp=4); replicas pull
            # prompts from one shared work queue at chunk boundaries
            # (demand-driven balancing, see dp_paged.py)
            from .dp_paged import DataParallelPagedEngine

            self.engine = DataParallelPagedEngine.from_pretrained(
                model_path, dtype=dtype, dp_size=dp_size, tp_size=num_chips,
                max_slots=batch_size, max_seq_len=max_seq_len,
                local_devices_only=local_devices_only, kv_dtype=kv_dtype,
                memory_utilization=memory_utilization,
                kv_tiering=kv_tiering, tier_chaos=tier_chaos,
            )
        else:
            # the static engine shards one rectangular batch over a
            # dp×sp×tp mesh — one jit program over all chips
            if kv_dtype:
                raise ValueError(
                    "kv_dtype is a paged-pool feature; the static engine's "
                    "contiguous cache does not support it — drop kv_dtype "
                    "or use engine='paged'")
            if memory_utilization is not None:
                raise ValueError(
                    "memory_utilization sizes the paged KV pool; the static "
                    "engine reserves its contiguous cache per batch row — "
                    "drop memory_utilization or use engine='paged'")
            from .engine import TPUEngine

            self.engine = TPUEngine.from_pretrained(
                model_path, dtype=dtype, tp_size=num_chips, dp_size=dp_size,
                sp_size=sp_size, batch_size=batch_size, max_seq_len=max_seq_len,
                local_devices_only=local_devices_only,
            )

    def infer_one(self, prompt: str) -> str:
        return self.infer_many([prompt])[0]

    def set_task_grammar(self, grammar: str | None) -> None:
        """Constrain subsequent :meth:`infer_many` calls to one answer
        shape (reval_tpu/decoding/) — the fleet sets this per task and
        clears it after (``FleetRunner.task_grammar``).  Raises up front
        when the selected engine has no constrained-decode path (static/
        pp), so a grammar run can never silently score unconstrained
        generations."""
        if grammar and not hasattr(self.engine, "spec_counters"):
            raise ValueError(
                "grammar-constrained decoding requires a paged engine "
                "(engine='paged'); the static/pp engines have no masked "
                "decode path")
        self._task_grammar = grammar or None

    def infer_many(self, prompts) -> list[str]:
        kwargs = {}
        grammar = getattr(self, "_task_grammar", None)
        if grammar:
            kwargs["grammar"] = grammar
        return self.engine.generate(
            list(prompts),
            max_new_tokens=self.config.max_new_tokens,
            temperature=self.temp,
            stop=self.config.stop,
            **kwargs,
        )

    def close(self) -> None:
        if self.engine is not None and hasattr(self.engine, "close"):
            self.engine.close()
        self.engine = None
