"""Token sampling under jit.

Greedy and temperature sampling are computed unconditionally and selected
with ``where`` — both are trivial next to the model step, and it keeps the
decode step free of data-dependent control flow (XLA requirement).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["sample_token", "sample_token_rows"]


def _gumbel_select(logits: jnp.ndarray, temperature: jnp.ndarray,
                   uniform: jnp.ndarray) -> jnp.ndarray:
    """Shared core: greedy/temperature switch + Gumbel-max over
    ``logits / temperature`` given pre-drawn uniform noise [B, V].
    ``temperature <= 0`` means greedy (argmax); scalar or per-row [B]."""
    greedy = jnp.argmax(logits, axis=-1)
    temp = jnp.maximum(temperature, 1e-6)
    if temp.ndim == 1:
        temp = temp[:, None]
    gumbel = -jnp.log(-jnp.log(uniform))
    sampled = jnp.argmax(logits / temp + gumbel, axis=-1)
    return jnp.where(temperature > 0, sampled, greedy)


def sample_token(logits: jnp.ndarray, temperature: jnp.ndarray, key: jax.Array) -> jnp.ndarray:
    """logits [B, V] float32 → token ids [B]; one key for the whole batch
    (the static engine's per-chunk stream)."""
    uniform = jax.random.uniform(key, logits.shape, minval=1e-20, maxval=1.0)
    return _gumbel_select(logits, temperature, uniform)


def sample_token_rows(logits: jnp.ndarray, temperature: jnp.ndarray,
                      keys: jnp.ndarray) -> jnp.ndarray:
    """Per-row keyed sampling: logits [B, V], keys [B, 2] raw uint32
    (legacy PRNG key data), temperature scalar or [B].

    Each row draws from its OWN stream, so a sampled sequence is a pure
    function of (request key, token position) — independent of batch
    composition, decode-chunk schedule, preemption, and dp-replica
    placement.  The paged engine keys each request as
    ``fold_in(call_key, request_index)`` and folds the per-token position
    inside the decode chunk; the reference gets no such guarantee from
    vLLM (seeding there is per-engine-step), so reproducibility under
    continuous batching is strictly better here.
    """
    uniform = jax.vmap(
        lambda k, row: jax.random.uniform(k, row.shape, minval=1e-20,
                                          maxval=1.0))(keys, logits)
    return _gumbel_select(logits, temperature, uniform)
