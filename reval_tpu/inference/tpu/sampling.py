"""Token sampling under jit.

Greedy and temperature sampling are computed unconditionally and selected
with ``where`` — both are trivial next to the model step, and it keeps the
decode step free of data-dependent control flow (XLA requirement).

Top-k / nucleus (top-p) filtering is available as :func:`filter_logits`.
Engines keep it OUT of the compiled program unless some request in the
batch asks for it (a static jit flag): the filter needs a [B, V] sort
every step, and the defaults (top_p=1, top_k=off) must cost nothing.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["filter_logits", "sample_token", "sample_token_rows"]

_NEG_INF = -1e30


def filter_logits(logits: jnp.ndarray, top_k: jnp.ndarray,
                  top_p: jnp.ndarray,
                  temperature: jnp.ndarray | None = None) -> jnp.ndarray:
    """Mask logits outside the top-k set and the top-p nucleus.

    logits [B, V]; top_k [B] int32 (<=0 = off); top_p [B] float32
    (>=1 = off); temperature [B] or scalar (the nucleus is computed over
    the TEMPERATURE-SCALED distribution — vLLM/HF order: temperature,
    then top-k, then top-p over the renormalized survivors; the token
    crossing the ``top_p`` threshold is kept).  Returns the ORIGINAL
    logits with masked entries at -1e30, so downstream sampling divides
    by temperature exactly once.
    """
    v = logits.shape[-1]
    scaled = logits
    if temperature is not None:
        temp = jnp.maximum(jnp.asarray(temperature, logits.dtype), 1e-6)
        if temp.ndim == 1:
            temp = temp[:, None]
        scaled = logits / temp
    order = jnp.argsort(-scaled, axis=-1)              # descending
    sorted_scaled = jnp.take_along_axis(scaled, order, axis=-1)
    # rank of each vocab entry in the sorted order: scatter iota
    ranks = jnp.zeros_like(order).at[
        jnp.arange(logits.shape[0])[:, None], order].set(
        jnp.arange(v, dtype=order.dtype)[None, :])
    k = jnp.where(top_k <= 0, v, top_k).astype(jnp.int32)
    keep = ranks < k[:, None]
    # nucleus mass over the distribution RENORMALIZED after top-k: mask
    # the beyond-k sorted tail before the softmax
    kept_sorted = jnp.where(jnp.arange(v)[None, :] < k[:, None],
                            sorted_scaled, _NEG_INF)
    probs = jax.nn.softmax(kept_sorted, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    p = jnp.where(top_p >= 1.0, jnp.inf, top_p)
    # keep sorted positions whose PREVIOUS cumulative mass is < p (the
    # crossing token stays); position 0 always stays
    sorted_keep = jnp.concatenate(
        [jnp.ones_like(cum[:, :1], bool), cum[:, :-1] < p[:, None]], axis=-1)
    keep &= jnp.take_along_axis(sorted_keep, ranks, axis=-1)
    return jnp.where(keep, logits, _NEG_INF)


def _gumbel_select(logits: jnp.ndarray, temperature: jnp.ndarray,
                   uniform: jnp.ndarray) -> jnp.ndarray:
    """Shared core: greedy/temperature switch + Gumbel-max over
    ``logits / temperature`` given pre-drawn uniform noise [B, V].
    ``temperature <= 0`` means greedy (argmax); scalar or per-row [B]."""
    greedy = jnp.argmax(logits, axis=-1)
    temp = jnp.maximum(temperature, 1e-6)
    if temp.ndim == 1:
        temp = temp[:, None]
    gumbel = -jnp.log(-jnp.log(uniform))
    sampled = jnp.argmax(logits / temp + gumbel, axis=-1)
    return jnp.where(temperature > 0, sampled, greedy)


def sample_token(logits: jnp.ndarray, temperature: jnp.ndarray, key: jax.Array) -> jnp.ndarray:
    """logits [B, V] float32 → token ids [B]; one key for the whole batch
    (the static engine's per-chunk stream)."""
    uniform = jax.random.uniform(key, logits.shape, minval=1e-20, maxval=1.0)
    return _gumbel_select(logits, temperature, uniform)


def sample_token_rows(logits: jnp.ndarray, temperature: jnp.ndarray,
                      keys: jnp.ndarray) -> jnp.ndarray:
    """Per-row keyed sampling: logits [B, V], keys [B, 2] raw uint32
    (legacy PRNG key data), temperature scalar or [B].

    Each row draws from its OWN stream, so a sampled sequence is a pure
    function of (request key, token position) — independent of batch
    composition, decode-chunk schedule, preemption, and dp-replica
    placement.  The paged engine keys each request as
    ``fold_in(call_key, request_index)`` and folds the per-token position
    inside the decode chunk; the reference gets no such guarantee from
    vLLM (seeding there is per-engine-step), so reproducibility under
    continuous batching is strictly better here.
    """
    uniform = jax.vmap(
        lambda k, row: jax.random.uniform(k, row.shape, minval=1e-20,
                                          maxval=1.0))(keys, logits)
    return _gumbel_select(logits, temperature, uniform)
