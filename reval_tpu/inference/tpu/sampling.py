"""Token sampling under jit.

Greedy and temperature sampling are computed unconditionally and selected
with ``where`` — both are trivial next to the model step, and it keeps the
decode step free of data-dependent control flow (XLA requirement).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["sample_token"]


def sample_token(logits: jnp.ndarray, temperature: jnp.ndarray, key: jax.Array) -> jnp.ndarray:
    """logits [B, V] float32 → token ids [B].

    ``temperature <= 0`` means greedy (argmax); otherwise categorical over
    ``logits / temperature`` via the Gumbel trick.  ``temperature`` may be
    a scalar or a per-row [B] vector — the paged engine batches requests
    with different sampling temperatures into one decode step (continuous
    cross-request batching, vLLM api_server semantics).
    """
    greedy = jnp.argmax(logits, axis=-1)
    temp = jnp.maximum(temperature, 1e-6)
    if temp.ndim == 1:
        temp = temp[:, None]
    gumbel = -jnp.log(-jnp.log(
        jax.random.uniform(key, logits.shape, minval=1e-20, maxval=1.0)))
    sampled = jnp.argmax(logits / temp + gumbel, axis=-1)
    return jnp.where(temperature > 0, sampled, greedy)
