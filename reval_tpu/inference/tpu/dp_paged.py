"""Data-parallel continuous batching: N paged-engine replicas, one process.

The paged engine's page pool and native scheduler are deliberately
per-replica state (a global pool would serialise every replica's admission
on one lock and put all block tables behind one host thread), so data
parallelism for continuous batching is replica-per-device-group: a v5e-8
runs the flagship models as ``dp=2 × tp=4`` — two independent paged
engines, each sharded over its own 4 chips.

This mirrors how the reference scales: vLLM's continuous batching is
per-process, and ``batch_run.py`` runs several GPU processes side by side
(reference batch_run.py:20-28).  Here the replicas share one Python
process — JAX dispatch releases the GIL while device work runs, so a
thread per replica keeps every device group busy concurrently — and one
model load (weights are device_put per replica group).

Load balance (round-3, VERDICT round-2 weak item 5): prompts are NOT
statically sharded.  They sit in one shared LPT-ordered work queue
(longest prompt first), and every replica's driver thread pulls from it
at decode-chunk boundaries whenever it has a free slot — demand-driven
work stealing, so a replica whose requests stop early (the DREval
fan-out shape: many 2-token "[ANSWER] NO" rows) immediately takes work a
busier replica would otherwise serialise.  Imbalance is bounded by one
request's runtime instead of the worst static shard.

Prefix reuse: each replica owns a persistent radix prefix cache (the page
pool is per-replica state, so cached KV cannot cross replicas).  Every
pulled prompt rides its replica's cache via ``submit_request`` — the
first pull of a template prefills it once per replica, later pulls (and
later CALLS: fleet repeats, serve traffic) hit the cached pages.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor, wait as futures_wait

import jax

from ...models import load_checkpoint
from ...parallel import make_mesh
from .engine import EngineStats, StopScanner, finalize_ids, finalize_text
from .paged_engine import PagedTPUEngine, _Request
from .tokenizer import HFTokenizer

__all__ = ["DataParallelPagedEngine"]


class DataParallelPagedEngine:
    # Engine-surface gaps (enginezoo pass; ROADMAP item 3 erases them):
    # not-supported: submit_request — replicas own the request lifecycle (work stealing)
    # not-supported: release_request — replicas own request teardown
    # not-supported: new_drive_state — per-replica drive loops (MultiSession)
    # not-supported: encode_clipped — per-replica tokenize budgets
    # not-supported: request_keys — per-replica PRNG keys
    # not-supported: warm_state — snapshot/restore is per-replica (.r<i> suffixes)
    # not-supported: rewarm — restore is per-replica (see warm_state)
    # not-supported: grammar_state — automaton state ids are replica-local (work stealing resolves per pull)
    def __init__(self, params, cfg, tokenizer, *, dp_size: int,
                 tp_size: int = 1, max_slots: int = 8, page_size: int = 128,
                 max_seq_len: int = 8192, num_pages: int | None = None,
                 seed: int = 0, prefix_sharing: bool = True, devices=None,
                 kv_dtype: str = "",
                 memory_utilization: float | None = None,
                 speculative: bool | None = None,
                 kv_tiering: bool | None = None, tier_chaos=None):
        devices = list(devices if devices is not None else jax.devices())
        need = dp_size * tp_size
        if len(devices) < need:
            raise ValueError(f"dp={dp_size} × tp={tp_size} needs {need} "
                             f"devices, have {len(devices)}")
        self.dp_size = dp_size
        self.tokenizer = tokenizer
        self.prefix_sharing = prefix_sharing
        self.replicas: list[PagedTPUEngine] = []
        for r in range(dp_size):
            group = devices[r * tp_size:(r + 1) * tp_size]
            # a tp=1 mesh still pins the replica's params/cache to its device
            mesh = make_mesh(tp=tp_size, devices=group)
            self.replicas.append(PagedTPUEngine(
                params, cfg, tokenizer, max_slots=max_slots,
                page_size=page_size, max_seq_len=max_seq_len,
                num_pages=num_pages, mesh=mesh, seed=seed + r,
                prefix_sharing=prefix_sharing, kv_dtype=kv_dtype,
                memory_utilization=memory_utilization,
                speculative=speculative,
                # one store per replica (its own copier, its own bound);
                # the chaos schedule is shared — it keys on chain hashes,
                # so placement does not move the faults
                kv_tiering=kv_tiering, tier_chaos=tier_chaos))
        self._pool = ThreadPoolExecutor(max_workers=dp_size,
                                        thread_name_prefix="dp-paged")

    @classmethod
    def from_pretrained(cls, model_path: str, *, dtype: str = "bfloat16",
                        dp_size: int = 2, tp_size: int = 1,
                        max_slots: int = 8, page_size: int = 128,
                        max_seq_len: int = 8192, num_pages: int | None = None,
                        tokenizer=None, seed: int = 0, kv_dtype: str = "",
                        local_devices_only: bool = False,
                        memory_utilization: float | None = None,
                        kv_tiering: bool | None = None,
                        tier_chaos=None,
                        ) -> "DataParallelPagedEngine":
        params, cfg = load_checkpoint(model_path, dtype=dtype)
        if tokenizer is None:
            tokenizer = HFTokenizer(model_path)
        devices = jax.local_devices() if local_devices_only else None
        return cls(params, cfg, tokenizer, dp_size=dp_size, tp_size=tp_size,
                   max_slots=max_slots, page_size=page_size,
                   max_seq_len=max_seq_len, num_pages=num_pages, seed=seed,
                   devices=devices, kv_dtype=kv_dtype,
                   memory_utilization=memory_utilization,
                   kv_tiering=kv_tiering, tier_chaos=tier_chaos)

    @property
    def stats(self) -> EngineStats:
        """Aggregated over replicas by registry merge — counters sum,
        histogram buckets add, gauges take last — so a metric added to
        ``EngineStats`` can never be silently dropped here again.
        (Seconds are summed device-time, not wall-clock — divide by dp
        for a wall estimate under full overlap.)"""
        agg = EngineStats()
        for rep in self.replicas:
            agg.merge(rep.stats)
        return agg

    def receipt_context(self) -> dict:
        """Replica 0's serving-config receipt context with the
        data-parallel degree folded in.  Replicas are built from one
        config (only the PRNG seed and device group differ, and neither
        is a fingerprint axis), so replica 0 speaks for the group."""
        return dict(self.replicas[0].receipt_context(),
                    engine="dp_paged", dp_size=self.dp_size)

    def jit_counters(self) -> dict:
        """Compile-variant snapshot summed over replicas (same shape as
        :meth:`PagedTPUEngine.jit_counters`; per-entry variant counts add
        — each replica compiles its own programs)."""
        out = {"compiles": 0, "cache_misses": 0, "entries": {}}
        for rep in self.replicas:
            row = rep.jit_counters()
            out["compiles"] += row["compiles"]
            out["cache_misses"] += row["cache_misses"]
            for name, n in row["entries"].items():
                out["entries"][name] = out["entries"].get(name, 0) + n
        return out

    def aot_counters(self) -> dict:
        """AOT-cache snapshot merged over replicas (same shape as
        :meth:`PagedTPUEngine.aot_counters`).  Per-process work counters
        (hits/misses/errors/compile seconds) sum; ``entries``/``bytes``
        describe the ONE shared directory every replica's cache instance
        sits on, so they take the max — summing would report the
        directory dp× too large and mis-size REVAL_TPU_AOT_CACHE_MAX_MB
        tuning."""
        rows = [rep.aot_counters() for rep in self.replicas]
        if not any(r.get("enabled") for r in rows):
            return {"enabled": False}
        out: dict = {"enabled": True}
        for row in rows:
            for k, v in row.items():
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    if k in ("entries", "bytes"):
                        out[k] = max(out.get(k, 0), v)
                    else:
                        out[k] = out.get(k, 0) + v
                elif k != "enabled":
                    out.setdefault(k, v)
        return out

    def prefix_cache_counters(self) -> dict:
        """Prefix-cache gauge snapshot summed over replicas (counters ride
        the aggregated ``stats``)."""
        out: dict = {}
        for rep in self.replicas:
            if rep.prefix_cache is None:
                continue
            for k, v in rep.prefix_cache.counters().items():
                out[k] = out.get(k, 0) + v
        return out

    def spec_counters(self) -> dict:
        """Speculative-decoding counters aggregated over replicas (the
        underlying counters ride the merged ``stats`` registry)."""
        return self.stats.spec_counters()

    def generate(self, prompts: list[str], *, max_new_tokens: int = 256,
                 temperature: float = 0.0,
                 stop: list[str] | None = None,
                 top_k: int = 0, top_p: float = 1.0,
                 on_progress=None, return_ids: bool = False,
                 grammar=None):
        if not prompts:
            return ([], []) if return_ids else []
        stop = stop or []
        grammars = PagedTPUEngine._grammar_list(grammar, len(prompts))
        # latency stamps anchor at CALL time, not queue-pull time: a
        # prompt that waits in the shared work queue must show that wait
        # in queue_wait/ttft/e2e, same clock as the serving session
        t_submit = time.perf_counter()
        encoded = [self.replicas[0].encode_clipped(p, max_new_tokens)
                   for p in prompts]
        # LPT order (longest prompt first): with demand-driven pulling the
        # schedule tail is bounded by the LAST pull — starting the big
        # prefills early keeps that tail a short prompt, not a long one
        order = sorted(range(len(prompts)),
                       key=lambda i: len(encoded[i]), reverse=True)
        work = deque(order)             # guarded-by: lock
        lock = threading.Lock()
        # unguarded: replicas write DISJOINT indices (each prompt is pulled
        # by exactly one replica); futures_wait publishes before the read
        out: list[str] = [""] * len(prompts)
        # unguarded: same disjoint-index / futures_wait contract as `out`
        out_ids: list[list[int]] = [[] for _ in prompts]

        # one call-level key set shared by every replica: request i samples
        # from fold_in(call_key, i) wherever it lands, so dp output at
        # temperature > 0 is placement-independent (and equals a single
        # same-seed paged engine run, since replica 0 carries seed+0)
        keys = self.replicas[0].request_keys(len(prompts))
        notify = None
        if on_progress is not None:
            def notify(req, _stop=stop):
                on_progress(req.index, finalize_text(
                    self.tokenizer, req.generated, _stop))

        def run_replica(eng: PagedTPUEngine) -> None:
            reqs: dict[int, _Request] = {}
            st = eng.new_drive_state()
            try:
                while True:
                    pulled: list[int] = []
                    with lock:
                        while work and len(reqs) + len(pulled) < eng.max_slots:
                            pulled.append(work.popleft())
                    for i in pulled:
                        ids = encoded[i]
                        # the replica's persistent radix cache: the first
                        # pull of a template prefills + caches it, every
                        # later pull (this call or the next) rides it
                        seq, node = eng.submit_request(ids, max_new_tokens,
                                                       grammar=grammars[i])
                        reqs[seq] = _Request(
                            index=i, ids=ids, max_new=max_new_tokens,
                            scanner=StopScanner(eng.tokenizer, stop),
                            temp=float(temperature),
                            top_k=int(top_k), top_p=float(top_p),
                            notify=notify, key=keys[i], node=node,
                            t_submit=t_submit,
                            grammar=grammars[i],
                            # automaton state ids are REPLICA-local: the
                            # pulling engine resolves its own start state
                            gstate=(eng.grammar_state(grammars[i])
                                    if grammars[i] else 0))
                    if not reqs:
                        break
                    eng._drive_tick(reqs, st)
                    # done requests are harvested immediately, so `reqs`
                    # only ever holds live ones (the pull bound above)
                    for seq in [s for s, q in reqs.items() if q.done]:
                        req = reqs.pop(seq)
                        out[req.index] = finalize_text(
                            eng.tokenizer, req.generated, stop)
                        out_ids[req.index] = finalize_ids(eng.tokenizer,
                                                          req.generated)
                        eng.stats.prompts += 1
            except Exception:
                for seq, req in reqs.items():
                    if not req.done:    # done seqs were released by _retire
                        eng.release_request(seq, req)
                raise

        futures = [self._pool.submit(run_replica, eng)
                   for eng in self.replicas]
        # wait for EVERY replica before propagating a fault: re-raising
        # early would let a retry drive an engine still owned by a live
        # worker thread (use-after-donate on its cache)
        futures_wait(futures)
        for f in futures:
            f.result()          # propagate replica faults
        if return_ids:
            return out, out_ids
        return out

    def close(self) -> None:
        self._pool.shutdown(wait=True)
        for rep in self.replicas:
            rep.close()
        self.replicas = []
