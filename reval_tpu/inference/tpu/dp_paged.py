"""Data-parallel continuous batching: N paged-engine replicas, one process.

The paged engine's page pool and native scheduler are deliberately
per-replica state (a global pool would serialise every replica's admission
on one lock and put all block tables behind one host thread), so data
parallelism for continuous batching is replica-per-device-group: a v5e-8
runs the flagship models as ``dp=2 × tp=4`` — two independent paged
engines, each sharded over its own 4 chips, fed disjoint prompt shards.

This mirrors how the reference scales: vLLM's continuous batching is
per-process, and ``batch_run.py`` runs several GPU processes side by side
(reference batch_run.py:20-28).  Here the replicas share one Python
process — JAX dispatch releases the GIL while device work runs, so a
thread per replica keeps every device group busy concurrently — and one
model load (weights are device_put per replica group).

Prompts shard round-robin so few-shot batches stay balanced; outputs
reassemble into caller order.  Prefix sharing happens per replica on its
own shard (round-robin preserves the common template in every shard).
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor

import jax

from ...models import load_checkpoint
from ...parallel import make_mesh
from .engine import EngineStats
from .paged_engine import PagedTPUEngine
from .tokenizer import HFTokenizer

__all__ = ["DataParallelPagedEngine"]


class DataParallelPagedEngine:
    def __init__(self, params, cfg, tokenizer, *, dp_size: int,
                 tp_size: int = 1, max_slots: int = 8, page_size: int = 128,
                 max_seq_len: int = 8192, num_pages: int | None = None,
                 seed: int = 0, prefix_sharing: bool = True, devices=None,
                 kv_dtype: str = ""):
        devices = list(devices if devices is not None else jax.devices())
        need = dp_size * tp_size
        if len(devices) < need:
            raise ValueError(f"dp={dp_size} × tp={tp_size} needs {need} "
                             f"devices, have {len(devices)}")
        self.dp_size = dp_size
        self.tokenizer = tokenizer
        self.replicas: list[PagedTPUEngine] = []
        for r in range(dp_size):
            group = devices[r * tp_size:(r + 1) * tp_size]
            # a tp=1 mesh still pins the replica's params/cache to its device
            mesh = make_mesh(tp=tp_size, devices=group)
            self.replicas.append(PagedTPUEngine(
                params, cfg, tokenizer, max_slots=max_slots,
                page_size=page_size, max_seq_len=max_seq_len,
                num_pages=num_pages, mesh=mesh, seed=seed + r,
                prefix_sharing=prefix_sharing, kv_dtype=kv_dtype))
        self._pool = ThreadPoolExecutor(max_workers=dp_size,
                                        thread_name_prefix="dp-paged")

    @classmethod
    def from_pretrained(cls, model_path: str, *, dtype: str = "bfloat16",
                        dp_size: int = 2, tp_size: int = 1,
                        max_slots: int = 8, page_size: int = 128,
                        max_seq_len: int = 8192, num_pages: int | None = None,
                        tokenizer=None, seed: int = 0, kv_dtype: str = "",
                        local_devices_only: bool = False
                        ) -> "DataParallelPagedEngine":
        params, cfg = load_checkpoint(model_path, dtype=dtype)
        if tokenizer is None:
            tokenizer = HFTokenizer(model_path)
        devices = jax.local_devices() if local_devices_only else None
        return cls(params, cfg, tokenizer, dp_size=dp_size, tp_size=tp_size,
                   max_slots=max_slots, page_size=page_size,
                   max_seq_len=max_seq_len, num_pages=num_pages, seed=seed,
                   devices=devices, kv_dtype=kv_dtype)

    @property
    def stats(self) -> EngineStats:
        """Aggregated over replicas (seconds are summed device-time, not
        wall-clock — divide by dp for a wall estimate under full overlap)."""
        agg = EngineStats()
        for rep in self.replicas:
            s = rep.stats
            agg.prompts += s.prompts
            agg.generated_tokens += s.generated_tokens
            agg.prefill_tokens += s.prefill_tokens
            agg.decode_seconds += s.decode_seconds
            agg.prefill_seconds += s.prefill_seconds
        return agg

    def generate(self, prompts: list[str], *, max_new_tokens: int = 256,
                 temperature: float = 0.0,
                 stop: list[str] | None = None, on_progress=None) -> list[str]:
        if not prompts:
            return []
        shards = [prompts[r::self.dp_size] for r in range(self.dp_size)]

        def run(arg):
            r, (replica, shard) = arg
            if not shard:
                return []
            cb = None
            if on_progress is not None:
                # map the replica-local index back to the caller's order;
                # callbacks arrive from dp worker threads concurrently
                def cb(j, text, _r=r):
                    on_progress(_r + j * self.dp_size, text)
            return replica.generate(shard, max_new_tokens=max_new_tokens,
                                    temperature=temperature, stop=stop,
                                    on_progress=cb)

        results = list(self._pool.map(run, enumerate(zip(self.replicas, shards))))
        out: list[str] = [""] * len(prompts)
        for r, shard_out in enumerate(results):
            for j, text in enumerate(shard_out):
                out[r + j * self.dp_size] = text
        return out

    def close(self) -> None:
        self._pool.shutdown(wait=True)
        for rep in self.replicas:
            rep.close()
        self.replicas = []
