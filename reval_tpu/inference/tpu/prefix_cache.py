"""Persistent radix prefix cache over the paged KV pool.

vLLM-style automatic prefix caching for the paged engine: every
page-aligned prompt prefix the engine prefills is registered in a radix
tree keyed by token ids, backed by REF-COUNTED pages in the native pool
(runtime/native/runtime.cpp), and SURVIVES across ``generate()`` calls and
engine entry points.  A later prompt — same call, next fleet repeat, or an
unrelated HTTP request — walks the tree for its longest cached page-aligned
prefix and prefills only the uncovered suffix.  This replaces the old
whole-batch-LCP reservation that was torn down inside each call
(``_reserve_shared_prefix``): multiple distinct prefixes now live per
batch (fused multi-task fleet batches hit per-template nodes), and
single-prompt serve-mode requests share too.

Structure: one node per POOL PAGE (``page_size`` tokens), children keyed by
the next page's token tuple — a radix tree whose edge labels are all the
same length, i.e. a page-granular trie, matching the only reuse unit the
pool has.  Each node owns a native *prefix object* that refcounts the
whole root→node page chain (``alloc_prefix`` / ``alloc_prefix_extend``),
so riders attach the full chain with one ``submit_prefixed`` and releasing
a leaf frees exactly its own page.

Memory policy: insertion is best-effort behind a free-page WATERMARK
(decode admission headroom — cached-but-idle prefixes must never starve
running sequences), and LRU eviction of rider-free leaves runs on demand:
before an insert that would cross the watermark, before preempting a
running sequence on pool exhaustion, and before declaring admission
deadlocked.  Nodes whose prefix an in-flight request rides are pinned
(``riders``) for the request's whole lifetime — a preempted rider keeps
its node alive so re-admission can re-attach the pages.

Single-owner, like the runtime it wraps: one engine drives one cache from
one thread (the dp engine builds one cache per replica).
"""

from __future__ import annotations

from dataclasses import dataclass, field

__all__ = ["RadixPrefixCache", "PrefixNode"]


@dataclass
class PrefixNode:
    """One cached page: ``key`` is this page's token ids (``page_size``
    of them); ``prefix_id`` the native prefix object holding the whole
    root→here chain by refcount."""

    key: tuple
    prefix_id: int
    tok_len: int                       # tokens covered root→here inclusive
    parent: "PrefixNode | None" = None
    children: dict = field(default_factory=dict)
    riders: int = 0                    # in-flight requests riding this node
    tick: int = 0                      # LRU stamp (larger = fresher)

    @property
    def depth_pages(self) -> int:
        return self.tok_len // len(self.key) if self.key else 0


class RadixPrefixCache:
    """See module docstring.  ``stats`` is a zero-arg callable returning
    the engine's live :class:`EngineStats` (engines replace their stats
    object wholesale between bench passes, so the cache must re-resolve
    it per update rather than hold a reference)."""

    def __init__(self, rt, page_size: int, *, watermark: int = 0,
                 stats=None, spill=None):
        self.rt = rt
        self.page = page_size
        self.watermark = watermark
        self._stats = stats if stats is not None else lambda: None
        # KV-tier spill hook (kv_tiers.py): called with the victim node
        # just BEFORE eviction releases its page — the pages are still
        # refcounted, so the engine can read them out.  LRU eviction
        # only: drop_tail rollbacks hold uncommitted garbage KV and
        # clear() is teardown — neither must ever reach a colder tier.
        self._spill = spill
        self.children: dict = {}       # root level: first page tuple → node
        self._tick = 0
        self.nodes = 0
        self.cached_pages = 0

    # -- lookup / insertion ------------------------------------------------
    def match_len(self, ids) -> int:
        """Tokens of ``ids`` covered by cached pages (pure query — no
        stats, no pinning, no insertion).  Capped one token short of the
        prompt so a full hit still leaves the rider its own first token."""
        node = self._walk(ids)
        return node.tok_len if node is not None else 0

    def _walk(self, ids):
        cap = max(0, (len(ids) - 1)) // self.page
        node, children = None, self.children
        for i in range(cap):
            key = tuple(ids[i * self.page:(i + 1) * self.page])
            nxt = children.get(key)
            if nxt is None:
                break
            node, children = nxt, nxt.children
        return node

    def acquire(self, ids) -> tuple[PrefixNode | None, int]:
        """Match + extend the tree for one prompt; pin and return the node
        the request should ride.

        Returns ``(node, new_from)``: ``node`` is the deepest cached node
        covering ``ids`` (pinned — pair with :meth:`unpin` when the
        request finishes), and ``new_from`` the token offset its newly
        inserted pages start at (== ``node.tok_len`` when nothing new was
        inserted).  The CALLER must prefill+commit tokens
        ``[new_from, node.tok_len)`` into the new pages before any rider's
        suffix prefill or decode touches them — within the engine this is
        synchronous, so ordering holds by construction.

        Insertion covers every full page of ``ids[:-1]`` that fits behind
        the free-page watermark (evicting LRU rider-free leaves first);
        under pressure the prefix is cached partially or not at all —
        sharing then degrades gracefully instead of starving decode.
        """
        stats = self._stats()
        if stats is not None:
            stats.prefix_lookup_tokens += len(ids)
        matched = self._walk(ids)
        if matched is not None:
            if stats is not None:
                stats.prefix_hit_tokens += matched.tok_len
            self._touch(matched)
        cap = max(0, (len(ids) - 1)) // self.page
        node = matched
        start = node.tok_len // self.page if node is not None else 0
        new_from = start * self.page
        # the pin travels with the chain head as it grows: _make_room's
        # eviction below must never reap the very node we are extending
        # (a fresh leaf is rider-free until this pin reaches it)
        if node is not None:
            node.riders += 1
        for i in range(start, cap):
            if not self._make_room(1):
                break
            key = tuple(ids[i * self.page:(i + 1) * self.page])
            try:
                if node is None:
                    prefix_id = self.rt.alloc_prefix(1)
                else:
                    prefix_id = self.rt.alloc_prefix_extend(node.prefix_id, 1)
            except ValueError:
                break                    # pool raced us below the watermark
            child = PrefixNode(key=key, prefix_id=prefix_id,
                               tok_len=(i + 1) * self.page, parent=node,
                               riders=1)
            (self.children if node is None else node.children)[key] = child
            if node is not None:
                node.riders -= 1         # hand the pin to the child
            node = child
            self.nodes += 1
            self.cached_pages += 1
            if stats is not None:
                stats.prefix_inserted_pages += 1
            self._touch(node)
        return (node, new_from) if node is not None else (None, 0)

    def unpin(self, node: PrefixNode) -> None:
        assert node.riders > 0, "unpin without a matching acquire"
        node.riders -= 1

    # -- eviction ----------------------------------------------------------
    def _touch(self, node: PrefixNode) -> None:
        """Freshen the whole root→node chain: an ancestor is at least as
        recently useful as the freshest path through it."""
        self._tick += 1
        while node is not None:
            node.tick = self._tick
            node = node.parent

    def _evictable(self):
        out = []
        stack = list(self.children.values())
        while stack:
            n = stack.pop()
            if n.children:
                stack.extend(n.children.values())
            elif n.riders == 0:
                out.append(n)
        return out

    def evict_lru(self, n_pages: int = 1) -> int:
        """Evict least-recently-used rider-free leaves until ``n_pages``
        pool pages were freed (a leaf frees exactly its own page) or no
        candidate remains.  Returns pages freed."""
        freed = 0
        stats = self._stats()
        while freed < n_pages:
            leaves = self._evictable()
            if not leaves:
                break
            victim = min(leaves, key=lambda n: n.tick)
            if self._spill is not None:
                # while the page is still held — the hook dispatches a
                # device-side copy; a failed spill loses tier warmth,
                # never the eviction (the engine counts it)
                self._spill(victim)
            self._drop(victim)
            freed += 1
            if stats is not None:
                stats.prefix_evictions += 1
        return freed

    def _make_room(self, n_pages: int) -> bool:
        """True when ``n_pages`` can be allocated while keeping the
        watermark's worth of free pages for decode; evicts LRU leaves to
        get there."""
        need = n_pages + self.watermark
        if self.rt.free_pages >= need:
            return True
        self.evict_lru(need - self.rt.free_pages)
        return self.rt.free_pages >= need

    def _drop(self, node: PrefixNode) -> None:
        self.rt.release(node.prefix_id)
        siblings = (self.children if node.parent is None
                    else node.parent.children)
        del siblings[node.key]
        self.nodes -= 1
        self.cached_pages -= 1

    def drop_tail(self, node: PrefixNode, down_to: int) -> None:
        """Remove ``node`` and its ancestors newer than ``down_to`` tokens
        — the caller's failed-insert rollback (KV never committed, so the
        nodes must not survive to serve garbage).  Only the chain just
        built by one ``acquire`` may be dropped: within a single-owner
        engine nothing else can ride it yet."""
        while node is not None and node.tok_len > down_to:
            parent = node.parent
            assert not node.children, "drop_tail on a shared chain"
            node.riders = 0
            self._drop(node)
            node = parent

    def clear(self) -> None:
        """Release every cached prefix (engine close / bench cold pass).
        Pinned nodes are released too — callers must only clear with no
        requests in flight."""
        stack = list(self.children.values())
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            self.rt.release(n.prefix_id)
        self.children = {}
        self.nodes = 0
        self.cached_pages = 0

    # -- gauges ------------------------------------------------------------
    @property
    def pinned_pages(self) -> int:
        """Pages on root→node chains some in-flight request rides (an
        upper bound on what eviction cannot touch right now)."""
        pinned = set()
        stack = list(self.children.values())
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            if n.riders > 0:
                m = n
                while m is not None and m.prefix_id not in pinned:
                    pinned.add(m.prefix_id)
                    m = m.parent
        return len(pinned)

    def counters(self) -> dict:
        """Gauge snapshot (counters live on the engine's EngineStats)."""
        return {"cached_pages": self.cached_pages,
                "pinned_pages": self.pinned_pages,
                "nodes": self.nodes}
