"""TPUEngine: batched prefill + chunked decode with stop-string handling.

The generation loop that replaces vLLM for this framework (SURVEY §7 step
4).  Shape discipline (hard part 4) and stop-string semantics (hard part 1)
drive the design:

- **Length bucketing.** Prompts are sorted by token length and packed into
  fixed-size batches; each batch left-pads to a power-of-two bucket, so XLA
  compiles one prefill/decode pair per bucket instead of per shape.
- **Chunked decode.** The token loop runs as a jitted ``lax.scan`` of
  ``CHUNK`` steps; the host only syncs between chunks.  Stop sequences are
  *strings*, not token ids — after each chunk the generated ids are
  detokenised and scanned for the stop string (and EOS), reproducing
  vLLM's post-detokenisation stop semantics without a per-token host
  round-trip.
- **Left-padding** makes every sequence's decode write position identical,
  so KV-cache updates are dynamic slices, not scatters (see models/model.py).
- Finished sequences keep decoding into masked positions until the whole
  batch stops; their text is truncated at the stop match afterwards.

Sharding: params/caches are placed with NamedSharding over a (dp, tp) mesh
when one is provided (see reval_tpu.parallel); jit then partitions the
same functions — there is no separate multi-chip code path.
"""

from __future__ import annotations

import contextlib
import functools
import os
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ...analysis.jitcheck import tracked_jit
from ...models import (
    KVCache,
    ModelConfig,
    decode_step,
    init_kv_cache,
    load_checkpoint,
    prefill,
)
from .sampling import filter_logits, sample_token
from .tokenizer import HFTokenizer

__all__ = ["TPUEngine", "StopScanner"]

CHUNK = 8            # decode steps per host sync
MIN_BUCKET = 64


def profile_trace():
    """``jax.profiler`` capture gated on ``REVAL_TPU_PROFILE=<dir>``
    (SURVEY §5.1: profiling hooks for the decode loop).  Each generate()
    call under the flag writes one trace into the directory; inspect with
    TensorBoard or ``jax.profiler`` tooling.  Without the flag this is a
    no-op nullcontext — zero cost on the hot path."""
    from ...env import env_str

    trace_dir = env_str("REVAL_TPU_PROFILE")
    if not trace_dir:
        return contextlib.nullcontext()
    return jax.profiler.trace(trace_dir)


@functools.lru_cache(maxsize=64)
def _sharded_zeros(shape, dtype, sharding):
    """Memoised jitted zeros-maker: out_shardings places each shard
    directly on its device with no replicated transient; the lru_cache
    keeps one compiled program per (shape, dtype, sharding).  Bounded:
    each entry pins its NamedSharding's mesh (and devices) plus a
    compiled executable, so an unbounded cache would leak meshes from
    closed engines in a long-lived server cycling cache-length buckets."""
    # jit-entry: engine.sharded_zeros bucketed=(shape)
    return jax.jit(lambda: jnp.zeros(shape, dtype), out_shardings=sharding)


def pow2_bucket(n: int, minimum: int = 1) -> int:
    """Smallest power-of-two multiple of ``minimum`` that is >= n."""
    b = minimum
    while b < n:
        b *= 2
    return b


def _bucket(n: int) -> int:
    return pow2_bucket(n, MIN_BUCKET)


def clip_prompt_ids(tokenizer, prompt: str, max_new_tokens: int,
                    max_len: int) -> list[int]:
    """Tokenise one prompt, left-clipping so prompt + generation fits
    ``max_len`` — the single source of the clipping/rejection rule shared
    by the paged engine and the serving mock engine (serve --mock must
    reject exactly what production rejects).  Raises ValueError when the
    token budget alone exceeds the sequence capacity."""
    limit = max_len - max_new_tokens - 1
    if limit < 1:
        raise ValueError(
            f"max_new_tokens={max_new_tokens} leaves no room for a prompt "
            f"within max_seq_len={max_len}")
    ids = tokenizer.encode(prompt)
    if not ids:
        ids = [tokenizer.pad_id]    # empty prompt: one pad token
    if len(ids) > limit:
        ids = ids[-limit:]          # clip from the left, keep the tail
    return ids


def truncate_at_stop(text: str, stop: list[str]) -> str:
    """Cut at the earliest stop-string occurrence (stop excluded) —
    vLLM-compatible post-detokenisation stop semantics."""
    positions = [text.find(s) for s in stop if s in text]
    return text[: min(positions)] if positions else text


def stop_hit(tokenizer, ids: list[int], stop: list[str]) -> bool:
    """Has this generation finished? — EOS token or any stop string in the
    detokenised text (both engines share this one contract)."""
    if tokenizer.eos_id in ids:
        return True
    if not stop:
        return False
    text = tokenizer.decode(ids)
    return any(s in text for s in stop)


class StopScanner:
    """Incremental stop detection with O(chunk) cost per check.

    ``stop_hit`` detokenises the FULL generated id list on every call; at
    CoT budgets (1024 tokens) × 8 slots that is quadratic host work per
    sequence (SURVEY §7 hard part 1 warns about exactly this).  The
    scanner is push-style: callers feed each chunk's NEW token ids and it
    keeps a bounded tail of previous tokens, so a stop string straddling
    a chunk boundary is still seen.  The tail is sized by the longest
    stop string's UTF-8 *byte* length — every BPE/byte-level token
    carries at least one byte, so ``S-1`` bytes of straddle always fit —
    plus a margin for window-edge artifacts.

    Detection only — final truncation still happens in ``finalize_text``
    with one full decode, keeping vLLM post-detokenisation semantics.
    """

    #: extra overlap tokens beyond the longest stop string, absorbing
    #: multi-byte tokens at the window edge and partial-UTF8 artifacts
    MARGIN = 8

    def __init__(self, tokenizer, stop: list[str]):
        self.tokenizer = tokenizer
        self.stop = stop
        self.overlap = (max((len(s.encode("utf-8")) for s in stop), default=0)
                        + self.MARGIN)
        self._tail: list[int] = []

    def reset(self) -> None:
        self._tail = []

    def hit_new(self, new_ids: list[int]) -> bool:
        """Feed the tokens generated since the last call; True = finished."""
        if not new_ids:
            return False
        if self.tokenizer.eos_id in new_ids:
            return True
        if not self.stop:
            return False
        window = self._tail + list(new_ids)
        self._tail = window[-self.overlap:]
        text = self.tokenizer.decode(window)
        return any(s in text for s in self.stop)


def finalize_text(tokenizer, ids: list[int], stop: list[str]) -> str:
    """Generated ids → final text: cut at EOS, then at the earliest stop
    string (vLLM post-detokenisation semantics)."""
    if tokenizer.eos_id in ids:
        ids = ids[: ids.index(tokenizer.eos_id)]
    return truncate_at_stop(tokenizer.decode(ids), stop)


def finalize_ids(tokenizer, ids: list[int]) -> list[int]:
    """Generated ids cut just PAST the EOS (inclusive) — the
    schedule-invariant raw stream behind ``generate(return_ids=True)``.
    Chunked engines legitimately compute tokens beyond EOS (the chunk
    finishes; static batches keep stepping until every row is done), and
    those overrun tails differ by engine/chunking, so they are discarded
    exactly as ``finalize_text`` discards them — but the EOS itself is
    KEPT: "stopped here" vs "kept going with token X" is a real
    divergence the determinism matrix must see (text alone cannot: ids
    outside the byte range decode to nothing)."""
    if tokenizer.eos_id in ids:
        ids = ids[: ids.index(tokenizer.eos_id) + 1]
    return list(ids)


#: bound on the per-template affinity-stat dict (warm snapshots carry
#: it whole, so it must not grow with workload diversity)
TEMPLATE_STATS_CAP = 4096


def bump_template_stats(stats: dict, tag: int, n: int = 1) -> None:
    """Bounded bump of the per-template affinity counters (the template
    mix warm snapshots and the placement view report): past the cap the
    lightest half folds away — heavy templates ARE the signal, and a
    high-diversity workload (every distinct first prompt page is a new
    tag) would otherwise grow the dict, and every drain's snapshot, for
    the life of the replica."""
    stats[tag] = stats.get(tag, 0) + n
    if len(stats) > TEMPLATE_STATS_CAP:
        keep = sorted(stats.items(), key=lambda kv: kv[1],
                      reverse=True)[:TEMPLATE_STATS_CAP // 2]
        stats.clear()
        stats.update(keep)


def restore_template_stats(stats: dict, mapping) -> None:
    """Merge one snapshot's ``template_stats`` document into the live
    dict (bounded, via :func:`bump_template_stats`).  Keys AND counts
    both came off disk: either failing to parse skips just that row — a
    corrupt stat must never abort a restore whose chains already
    replayed."""
    for key, count in (mapping or {}).items():
        try:
            bump_template_stats(stats, int(key), int(count))
        except (TypeError, ValueError):
            continue


#: (attribute, metric name, python type) — the EngineStats counter set.
#: Attribute access keeps the historical dataclass field names (every
#: caller, test, and JSON surface reads ``stats.prompts`` etc.); the
#: VALUES live in the obs registry so ``/metrics``, dp/MultiSession
#: merges, and the fleet snapshot all see one store.
_STAT_FIELDS = (
    ("prompts", "reval_engine_prompts_total", int),
    ("generated_tokens", "reval_engine_generated_tokens_total", int),
    ("prefill_tokens", "reval_engine_prefill_tokens_total", int),
    ("decode_seconds", "reval_engine_decode_seconds_total", float),
    ("prefill_seconds", "reval_engine_prefill_seconds_total", float),
    ("decode_chunks", "reval_engine_decode_chunks_total", int),
    # weight passes: forward executions of the decode program
    ("decode_steps", "reval_engine_decode_steps_total", int),
    # chunks whose fetch rode behind the next dispatch (chunk pipeline)
    ("pipelined_chunks", "reval_engine_pipelined_chunks_total", int),
    # in-place device table patches — page crossings absorbed flush-free
    ("patched_tables", "reval_engine_patched_tables_total", int),
    # persistent radix prefix cache (paged engine; prefix_cache.py):
    ("prefix_hit_tokens", "reval_prefix_hit_tokens_total", int),
    ("prefix_lookup_tokens", "reval_prefix_lookup_tokens_total", int),
    ("prefix_inserted_pages", "reval_prefix_inserted_pages_total", int),
    ("prefix_evictions", "reval_prefix_evictions_total", int),
    # speculative + constrained decoding (reval_tpu/decoding/ + the
    # paged engine's batched verify path):
    ("spec_rounds", "reval_spec_verify_rounds_total", int),
    ("spec_drafted_tokens", "reval_spec_drafted_tokens_total", int),
    ("spec_accepted_tokens", "reval_spec_accepted_tokens_total", int),
    ("spec_rolled_back_tokens", "reval_spec_rolled_back_tokens_total", int),
    ("spec_wedges", "reval_spec_wedges_total", int),
    ("grammar_requests", "reval_grammar_requests_total", int),
    ("grammar_forced_tokens", "reval_grammar_forced_tokens_total", int),
    # hierarchical KV tiering (paged engine; kv_tiers.py):
    ("kvtier_spills", "reval_kvtier_spills_total", int),
    ("kvtier_spill_drops", "reval_kvtier_spill_drops_total", int),
    ("kvtier_spill_errors", "reval_kvtier_spill_errors_total", int),
    ("kvtier_promotions", "reval_kvtier_promotions_total", int),
    ("kvtier_disk_promotions", "reval_kvtier_disk_promotions_total", int),
    ("kvtier_recomputes", "reval_kvtier_recomputes_total", int),
    ("kvtier_integrity_failures",
     "reval_kvtier_integrity_failures_total", int),
    ("kvtier_host_evictions", "reval_kvtier_host_evictions_total", int),
    # ragged continuous batching (paged engine `_tick_ragged`): wave
    # occupancy — useful counts the real (ctx, q) work rows asked for,
    # padded the full b*w rectangle the single dispatch covered; their
    # ratio is the bench ragged block's padded-vs-useful lens
    ("ragged_ticks", "reval_ragged_ticks_total", int),
    ("ragged_useful_tokens", "reval_ragged_useful_tokens_total", int),
    ("ragged_padded_tokens", "reval_ragged_padded_tokens_total", int),
    # serving lifecycle (serving/session.py + serving/server.py):
    ("sheds", "reval_serving_sheds_total", int),
    ("deadline_expired", "reval_serving_deadline_expired_total", int),
    ("watchdog_trips", "reval_serving_watchdog_trips_total", int),
    ("drain_seconds", "reval_serving_drain_seconds_total", float),
)


class EngineStats:
    """Engine counters + latency histograms over one obs registry.

    Historically a plain dataclass of ints/floats; the fields survive as
    properties (read/write/`+=` all work) over
    :class:`~reval_tpu.obs.metrics.MetricsRegistry` counters, which adds
    the histogram side (TTFT/TPOT/e2e/queue-wait distributions via
    :meth:`observe_request`) and registry-level merging for dp replicas
    and ``/metrics``.  ``REVAL_TPU_OBS=0`` (bench ``--no-obs``) disables
    histogram observation only — counters are engine accounting and stay
    on."""

    def __init__(self, registry=None):
        from ...env import env_flag
        from ...obs.metrics import MetricsRegistry

        if registry is None:
            registry = MetricsRegistry(enabled=env_flag("REVAL_TPU_OBS", True))
        self.registry = registry
        for _, metric, _ in _STAT_FIELDS:
            registry.counter(metric)

    def merge(self, other: "EngineStats") -> None:
        """Fold another stats block in: counters sum, histogram buckets
        add, gauges take last (the dp-replica aggregation rule)."""
        self.registry.merge(other.registry)

    @property
    def prefix_hit_rate(self) -> float:
        return (self.prefix_hit_tokens / self.prefix_lookup_tokens
                if self.prefix_lookup_tokens else 0.0)

    def serving_counters(self) -> dict:
        """The lifecycle counter block every surface reports (bench JSON,
        fleet trailer, server drain log, serve smoke) — one definition so
        a future counter cannot be added to three surfaces and silently
        missed on the fourth."""
        return {"sheds": self.sheds,
                "deadline_expired": self.deadline_expired,
                "watchdog_trips": self.watchdog_trips,
                "drain_seconds": round(self.drain_seconds, 3)}

    @property
    def spec_accept_rate(self) -> float:
        return (self.spec_accepted_tokens / self.spec_drafted_tokens
                if self.spec_drafted_tokens else 0.0)

    def spec_counters(self) -> dict:
        """The speculative-decoding counter block — the
        ``serving_counters``/``prefix_counters`` sibling: bench JSON,
        the fleet trailer, and the determinism matrix's spec cells all
        render THIS dict, so the surfaces cannot drift."""
        return {"rounds": self.spec_rounds,
                "drafted_tokens": self.spec_drafted_tokens,
                "accepted_tokens": self.spec_accepted_tokens,
                "accept_rate": round(self.spec_accept_rate, 4),
                "rolled_back_tokens": self.spec_rolled_back_tokens,
                "forced_tokens": self.grammar_forced_tokens,
                "grammar_requests": self.grammar_requests,
                "wedges": self.spec_wedges}

    def prefix_counters(self) -> dict:
        """The prefix-cache counter block, the ``serving_counters``
        sibling: bench JSON and the fleet trailer both render THIS dict
        (they used to format the same four counters independently)."""
        return {"hit_tokens": self.prefix_hit_tokens,
                "hit_rate": round(self.prefix_hit_rate, 4),
                "evictions": self.prefix_evictions,
                "inserted_pages": self.prefix_inserted_pages}

    def kvtier_counters(self) -> dict:
        """The KV-tier counter block (``serving_counters`` sibling):
        bench's ``kv_tier`` output, the loadgen artifact, and `watch`
        render THIS dict.  ``promote_hit_rate`` is promotions over
        promotion attempts (promotions + degraded recomputes)."""
        attempts = self.kvtier_promotions + self.kvtier_recomputes
        from ...obs import metrics as m

        h = self.registry.histogram(m.KVTIER_PROMOTE_SECONDS)
        out = {"spills": self.kvtier_spills,
               "spill_drops": self.kvtier_spill_drops,
               "spill_errors": self.kvtier_spill_errors,
               "promotions": self.kvtier_promotions,
               "disk_promotions": self.kvtier_disk_promotions,
               "recomputes": self.kvtier_recomputes,
               "integrity_failures": self.kvtier_integrity_failures,
               "host_evictions": self.kvtier_host_evictions,
               "promote_hit_rate": round(
                   self.kvtier_promotions / attempts, 4) if attempts
               else 0.0}
        if h.count:
            out["promote_p50_ms"] = round(h.percentile(0.50) * 1e3, 3)
            out["promote_p95_ms"] = round(h.percentile(0.95) * 1e3, 3)
        return out

    # -- latency histograms ------------------------------------------------
    def observe_request(self, req) -> None:
        """Record one retired request's lifecycle stamps (perf_counter
        seconds on the request object: ``t_submit``/``t_admit``/
        ``t_first``/``t_done``) into the latency histograms.  Engines
        call this exactly once per request, at retirement."""
        from ...obs import metrics as m

        reg = self.registry
        reg.counter(m.REQUESTS).add(1)
        t_submit = getattr(req, "t_submit", None)
        if t_submit is None:
            return
        t_done = getattr(req, "t_done", None)
        if t_done is None:
            t_done = time.perf_counter()
        t_admit = getattr(req, "t_admit", None)
        t_first = getattr(req, "t_first", None)
        if t_admit is not None:
            reg.histogram(m.QUEUE_WAIT).observe(max(0.0, t_admit - t_submit))
        if t_first is not None:
            reg.histogram(m.TTFT).observe(max(0.0, t_first - t_submit))
        reg.histogram(m.E2E).observe(max(0.0, t_done - t_submit))
        n = len(getattr(req, "generated", None) or ())
        if t_first is not None and n > 1:
            reg.histogram(m.TPOT).observe(
                max(0.0, (t_done - t_first) / (n - 1)))

    def latency_summary(self) -> dict:
        """Percentile digest of the request histograms — the fleet
        trailer and bench ``latency`` block.  Empty dict when nothing
        was observed (obs disabled, or no requests retired)."""
        from ...obs import metrics as m

        out: dict = {}
        for label, name in (("queue_wait", m.QUEUE_WAIT), ("ttft", m.TTFT),
                            ("tpot", m.TPOT), ("e2e", m.E2E)):
            h = self.registry.histogram(name)
            if h.count:
                out[label] = {"count": h.count,
                              "mean": round(h.sum / h.count, 6),
                              "p50": round(h.percentile(0.50), 6),
                              "p95": round(h.percentile(0.95), 6),
                              "p99": round(h.percentile(0.99), 6)}
        return out


def _stat_property(metric: str, cast) -> property:
    def fget(self):
        return cast(self.registry.counter(metric).value)

    def fset(self, v):
        self.registry.counter(metric).set(v)

    return property(fget, fset)


for _name, _metric, _cast in _STAT_FIELDS:
    setattr(EngineStats, _name, _stat_property(_metric, _cast))
del _name, _metric, _cast


class TPUEngine:
    # Engine-surface gaps (enginezoo pass; ROADMAP item 3 erases them):
    # not-supported: close — no driver thread or pool; generate() leaves nothing running
    # not-supported: submit_request — static whole-batch engine, no request lifecycle
    # not-supported: release_request — static whole-batch engine, no request lifecycle
    # not-supported: new_drive_state — no session drive loop; fleet fuses batches
    # not-supported: encode_clipped — request-level API is session-driver-only
    # not-supported: request_keys — per-request PRNG is a continuous-batching feature
    # not-supported: aot_counters — AOT executable cache wraps paged entries only
    # not-supported: prefix_cache_counters — no radix prefix cache on the static path
    # not-supported: warm_state — nothing to snapshot without a prefix cache
    # not-supported: rewarm — nothing to replay without a prefix cache
    # not-supported: spec_counters — no drafter/verify path on the static whole-batch engine
    # not-supported: grammar_state — constrained decoding rides the paged decode chunk only
    # not-supported: receipt_context — receipts stamp at continuous-session retire; the static whole-batch path has no per-request retire to stamp
    # mesh: axes=(dp)
    def __init__(self, params, cfg: ModelConfig, tokenizer, *, batch_size: int = 8,
                 max_seq_len: int = 8192, mesh=None, seed: int = 0):
        self.cfg = cfg
        self.tokenizer = tokenizer
        self.batch_size = batch_size
        self.max_seq_len = max_seq_len
        self.mesh = mesh
        self.stats = EngineStats()
        self._key = jax.random.PRNGKey(seed)
        self.params = params
        self._input_sharding = None
        self._cache_sharding = None
        self._replicate = None       # set iff the mesh spans processes
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ...parallel import shard_params
            from ...parallel.sharding import kv_cache_spec, resolve_moe_impl

            cfg = self.cfg = resolve_moe_impl(cfg, mesh)
            sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
            dp = sizes.get("dp", 1)
            if batch_size % dp:
                raise ValueError(f"batch_size {batch_size} must divide by dp={dp}")
            self._sp = sizes.get("sp", 1)
            if self._sp > 1 and MIN_BUCKET % self._sp:
                raise ValueError(
                    f"sp={self._sp} must divide the bucket granularity "
                    f"{MIN_BUCKET} (power-of-two sp up to {MIN_BUCKET})")
            self.params = shard_params(params, cfg, mesh)
            self._input_sharding = NamedSharding(mesh, P("dp"))
            # multihost "global" mode: the mesh spans several processes
            # (launchers/tpu_vm_fleet.sh MULTIHOST=global — one model over
            # every host's chips).  Host readbacks then need an explicit
            # replicate step: np.asarray() can only consume arrays that
            # are fully addressable or fully replicated, and dp-sharded
            # token outputs are neither.  The replicate jit is an XLA
            # all-gather over ICI/DCN, a few KB per decode chunk.
            if any(d.process_index != jax.process_index()
                   for d in mesh.devices.flat):
                # jit-entry: engine.replicate bucketed=(shape)
                self._replicate = jax.jit(
                    lambda x: x, out_shardings=NamedSharding(mesh, P()))
            if sizes.get("sp", 1) > 1:
                # sequence parallelism: prefill via ring attention with T
                # sharded over sp; the cache keeps S sp-sharded and decode
                # attention distributes for free (see parallel/sp_prefill)
                from ...parallel.sp_prefill import (
                    sequence_parallel_prefill, sp_kv_cache_spec)

                self._cache_sharding = NamedSharding(
                    mesh, sp_kv_cache_spec(cfg, mesh))
                # jit-entry: engine.sp_prefill bucketed=(rows, tokens)
                sp_prefill = jax.jit(
                    partial(sequence_parallel_prefill, cfg=cfg, mesh=mesh),
                    out_shardings=(None, self._cache_sharding))
            else:
                self._cache_sharding = NamedSharding(mesh, kv_cache_spec(cfg, mesh))
                sp_prefill = None
        else:
            sp_prefill = None
        # Cache-returning entries pin out_shardings to the declared spec
        # on a mesh: XLA's propagation is otherwise free to pick another
        # cache layout (the shardcheck guard caught dp-mesh prefill
        # returning a GSPMD-resharded cache over the declared
        # kv_cache_spec), and every later chunk then pays a silent
        # re-gather back to the operand shardings.
        prefill_kw = ({"out_shardings": (None, self._cache_sharding)}
                      if mesh is not None else {})
        chunk_kw = ({"out_shardings": (None, self._cache_sharding, None)}
                    if mesh is not None else {})
        # compile-variant tracking mirrors the paged engine (budgets =
        # worst-case legitimate bucket counts; see analysis/jitcheck.py)
        # jit-entry: engine.prefill bucketed=(rows, tokens) warmup=16
        self._jit_prefill = tracked_jit(
            "engine.prefill",
            sp_prefill or jax.jit(
                partial(prefill, cfg=cfg, logits_mode="last"),
                **prefill_kw),
            registry=lambda: self.stats.registry, warmup=16)
        # jit-entry: engine.decode_chunk static=(steps, filtered) bucketed=(tokens) warmup=48
        self._jit_decode_chunk = tracked_jit(
            "engine.decode_chunk",
            jax.jit(
                partial(self._decode_chunk, cfg=cfg),
                static_argnames=("steps", "filtered"),
                donate_argnames=("cache",),
                **chunk_kw,
            ),
            registry=lambda: self.stats.registry, warmup=48)
        # runtime mesh discipline (analysis/shardcheck.py): on a mesh,
        # assert the batch inputs stay dp-sharded and the KV cache keeps
        # kv_cache_spec (sp_kv_cache_spec under sp) through every entry
        # — a silently-resharded cache is a mesh-size× chunk-time cliff
        if mesh is not None:
            from ...analysis.shardcheck import ShardGuard

            self._jit_prefill = ShardGuard(
                "engine.prefill", self._jit_prefill,
                registry=lambda: self.stats.registry,
                in_checks={"tokens": self._input_sharding,
                           "pad_len": self._input_sharding,
                           "cache": self._cache_sharding},
                out_checks={1: self._cache_sharding})
            self._jit_decode_chunk = ShardGuard(
                "engine.decode_chunk", self._jit_decode_chunk,
                registry=lambda: self.stats.registry,
                in_checks={2: self._input_sharding,
                           3: self._cache_sharding},
                out_checks={1: self._cache_sharding})
        self._jit_trackers = (self._jit_prefill, self._jit_decode_chunk)

    def jit_counters(self) -> dict:
        """Compile-variant snapshot of the tracked jit entry points —
        same shape as :meth:`PagedTPUEngine.jit_counters` (the serial
        engine path's row in the PERF.md compile-count baseline)."""
        return {"compiles": sum(t.variants for t in self._jit_trackers),
                "cache_misses": sum(t.misses for t in self._jit_trackers),
                "entries": {t.name: t.variants for t in self._jit_trackers}}

    # -- construction ------------------------------------------------------
    @classmethod
    def from_pretrained(cls, model_path: str, *, dtype: str = "bfloat16", tp_size: int = 1,
                        dp_size: int = 1, sp_size: int = 1, batch_size: int = 8,
                        max_seq_len: int = 8192,
                        tokenizer=None, seed: int = 0,
                        local_devices_only: bool = False) -> "TPUEngine":
        """``local_devices_only`` confines the mesh to this host's chips —
        the replicated-engines multihost mode (one full replica per host,
        prompts sharded over DCN by the fleet).  ``sp_size``: shard
        prefill sequences (and the KV cache) over a sequence-parallel
        ring for prompts past one chip's attention working set (all
        families — sliding windows and score softcapping ride the ring
        masks since round 4)."""
        mesh = None
        if tp_size * dp_size * sp_size > 1:
            from ...parallel import make_mesh

            devices = jax.local_devices() if local_devices_only else None
            mesh = make_mesh(tp=tp_size, dp=dp_size, sp=sp_size,
                             devices=devices)
        if mesh is not None and dtype != "int8":
            # shard-direct load (see PagedTPUEngine.from_pretrained)
            from ...models import load_checkpoint_sharded

            params, cfg = load_checkpoint_sharded(model_path, mesh, dtype=dtype)
        else:
            params, cfg = load_checkpoint(model_path, dtype=dtype)
        if tokenizer is None:
            tokenizer = HFTokenizer(model_path)
        return cls(params, cfg, tokenizer, batch_size=batch_size,
                   max_seq_len=max_seq_len, mesh=mesh, seed=seed)

    # -- jitted pieces -----------------------------------------------------
    @staticmethod
    def _decode_chunk(params, first_token, pad_len, cache: KVCache, start_pos,
                      temperature, key, top_k=None, top_p=None, *,
                      cfg: ModelConfig, steps: int, filtered: bool = False):
        """Run ``steps`` decode iterations; returns sampled tokens [B, steps].

        ``filtered`` (static) compiles the top-k/top-p logits filter into
        the chunk; the default program carries no [B, V] sort."""

        def body(carry, _):
            token, cache, pos, key = carry
            logits, cache = decode_step(params, cfg, token, pad_len, cache, pos)
            if filtered:
                logits = filter_logits(logits, top_k, top_p, temperature)
            key, sub = jax.random.split(key)
            nxt = sample_token(logits, temperature, sub)
            return (nxt[:, None], cache, pos + 1, key), nxt

        (last, cache, _, _), toks = jax.lax.scan(
            body, (first_token, cache, start_pos, key), None, length=steps)
        return toks.T, cache, last

    def _next_key(self):
        self._key, sub = jax.random.split(self._key)
        if self._replicate is not None:
            # a key committed to this host's device 0 cannot feed a jit
            # spanning the cross-process mesh; hand jit the host value
            # (identical on every host — same seed, same split sequence)
            return np.asarray(sub)
        return sub

    def _cache_rows(self, b: int) -> int:
        """KV-cache batch rows for a ``b``-row generation batch.  The
        pipelined engine over-allocates scratch rows for fill/drain ticks."""
        return b

    def _init_cache(self, rows: int, length: int) -> KVCache:
        """Fresh zero KV cache, created *born sharded* on a mesh: each
        device materialises only its own shard (jit with out_shardings),
        so the full [L, B, S, H_kv, D] buffer never transits one chip's
        HBM — on a pp mesh that transient could exceed a single stage's
        memory (the whole point of pipelining the layer stack)."""
        dtype = self.params["embed"].dtype
        if self._cache_sharding is None:
            return init_kv_cache(self.cfg, rows, length, dtype=dtype)
        shape = (self.cfg.num_layers, rows, length,
                 self.cfg.num_kv_heads, self.cfg.head_dim)
        zeros = _sharded_zeros(shape, jnp.dtype(dtype), self._cache_sharding)
        return KVCache(zeros(), zeros())

    def _cache_len(self, t: int, max_new: int) -> int:
        """KV-cache sequence length for a ``t``-token bucket.  An
        sp-sharded cache dim must divide evenly over the mesh, so round
        up; the extra slots are past every row's final position and the
        decode mask (``cols <= cur_pos``) never reads them."""
        s = t + max_new
        sp = getattr(self, "_sp", 1)
        return -(-s // sp) * sp

    # -- generation --------------------------------------------------------
    def generate(self, prompts: list[str], *, max_new_tokens: int = 256,
                 temperature: float = 0.0, stop: list[str] | None = None,
                 top_k: int = 0, top_p: float = 1.0,
                 return_ids: bool = False, grammar=None):
        """Generate completions for every prompt (any count); order
        preserved.  ``top_k``/``top_p`` filter the sampling distribution
        (0 / 1.0 = off — the defaults compile no filter into the chunk
        program).  ``return_ids``: also return the raw generated token
        streams (``finalize_ids`` semantics — EOS-cut, pre-stop) as a
        second list, for consumers that must see divergence text hides
        (the determinism matrix).  ``grammar`` is rejected loudly: the
        constraint automaton rides the paged decode chunk only — a
        silent ignore here would score unconstrained generations as
        constrained ones."""
        if grammar:
            raise ValueError(
                "grammar-constrained decoding requires the paged engine "
                "(reval_tpu/decoding/); the static engine has no masked "
                "decode path — drop grammar= or use engine='paged'")
        if not prompts:
            return ([], []) if return_ids else []
        stop = stop or []
        ids = [self.tokenizer.encode(p) for p in prompts]
        order = sorted(range(len(ids)), key=lambda i: len(ids[i]), reverse=True)
        out: list[str | None] = [None] * len(prompts)
        out_ids: list[list[int]] = [[] for _ in prompts]
        with profile_trace():
            for start in range(0, len(order), self.batch_size):
                batch_idx = order[start:start + self.batch_size]
                batch_ids = [ids[i] for i in batch_idx]
                texts, raw = self._generate_batch(batch_ids, max_new_tokens,
                                                  temperature, stop,
                                                  top_k=top_k, top_p=top_p)
                for i, text, row_ids in zip(batch_idx, texts, raw):
                    out[i] = text
                    out_ids[i] = finalize_ids(self.tokenizer, row_ids)
        if return_ids:
            return out, out_ids  # type: ignore[return-value]
        return out  # type: ignore[return-value]

    def _host_read(self, arr) -> np.ndarray:
        """Device tokens → numpy on EVERY host.  On a cross-process mesh
        the dp-sharded output is not addressable here, so replicate first
        (all-gather); every host then takes identical scheduling decisions
        (stop scanning, loop exit) from identical data."""
        if self._replicate is not None:
            arr = self._replicate(arr)
        return np.asarray(arr)

    def _generate_batch(self, batch_ids: list[list[int]], max_new_tokens: int,
                        temperature: float, stop: list[str],
                        top_k: int = 0, top_p: float = 1.0,
                        ) -> tuple[list[str], list[list[int]]]:
        n_real = len(batch_ids)
        # greedy (temp 0) never needs the filter: masking can't change
        # the argmax, and the filtered program pays a [B, V] sort per step
        filtered = (top_k > 0 or top_p < 1.0) and temperature > 0
        kf = np.full(self.batch_size, top_k, np.int32)
        pf = np.full(self.batch_size, top_p, np.float32)
        b = self.batch_size
        pad_id = self.tokenizer.pad_id
        # clip overlong prompts from the left, keeping room to generate
        limit = self.max_seq_len - max_new_tokens - 1
        batch_ids = [seq[-limit:] if len(seq) > limit else seq for seq in batch_ids]
        t = _bucket(max(len(s) for s in batch_ids))
        while len(batch_ids) < b:
            batch_ids.append([pad_id])  # dummy rows pad the batch
        tokens = np.full((b, t), pad_id, dtype=np.int32)
        pad_len = np.zeros(b, dtype=np.int32)
        for row, seq in enumerate(batch_ids):
            tokens[row, t - len(seq):] = seq
            pad_len[row] = t - len(seq)

        cache = self._init_cache(self._cache_rows(b),
                                 self._cache_len(t, max_new_tokens))
        if self._input_sharding is not None:
            # device_put straight from numpy: each process contributes its
            # addressable shards, so this works on a cross-process mesh
            # (every host holds the same full batch in global mode)
            dev_tokens = jax.device_put(tokens, self._input_sharding)
            dev_pad = jax.device_put(pad_len, self._input_sharding)
        else:
            dev_tokens, dev_pad = jnp.asarray(tokens), jnp.asarray(pad_len)
        t0 = time.perf_counter()
        with jax.profiler.TraceAnnotation("reval.prefill"):
            logits, cache = self._jit_prefill(
                self.params, tokens=dev_tokens, pad_len=dev_pad, cache=cache)
            first_logits = logits[:, 0, :]
            if filtered:
                first_logits = filter_logits(first_logits, kf, pf,
                                             np.float32(temperature))
            first = sample_token(first_logits, np.float32(temperature),
                                 self._next_key())
        # the host read is the sync: through the axon tunnel
        # block_until_ready returns before the device has executed, so
        # timing must end on an actual fetch
        first_host = self._host_read(first)[:, None]
        self.stats.prefill_seconds += time.perf_counter() - t0
        self.stats.prefill_tokens += int((t - pad_len).sum())

        generated = np.zeros((b, 0), dtype=np.int32)
        generated = np.concatenate([generated, first_host], axis=1)
        token = first[:, None]
        pos = np.int32(t)   # host value: placeable on any (even cross-
                            # process) device assignment by jit
        # dummy rows (batch padding) are born finished or they would pin
        # the whole batch to the full token budget
        finished = [False] * n_real + [True] * (b - n_real)
        scanners = [StopScanner(self.tokenizer, stop) for _ in range(n_real)]
        for row in range(n_real):
            finished[row] = scanners[row].hit_new([int(first_host[row, 0])])

        t0 = time.perf_counter()
        while generated.shape[1] < max_new_tokens and not all(finished):
            steps = min(CHUNK, max_new_tokens - generated.shape[1])
            with jax.profiler.TraceAnnotation("reval.decode_chunk"):
                toks, cache, token = self._jit_decode_chunk(
                    self.params, token, dev_pad, cache, pos,
                    np.float32(temperature), self._next_key(), kf, pf,
                    steps=steps, filtered=filtered)
            pos = pos + steps
            self.stats.decode_steps += steps
            chunk_host = self._host_read(toks)
            generated = np.concatenate([generated, chunk_host], axis=1)
            for row in range(n_real):
                if not finished[row]:
                    finished[row] = scanners[row].hit_new(chunk_host[row].tolist())
        self.stats.decode_seconds += time.perf_counter() - t0
        self.stats.generated_tokens += int(generated[:n_real].size)
        self.stats.prompts += n_real

        raw = [generated[row].tolist() for row in range(n_real)]
        return ([finalize_text(self.tokenizer, row_ids, stop)
                 for row_ids in raw], raw)
