"""Persistent AOT executable cache: warm restarts skip XLA compilation.

A restarted ``EngineServer`` pays the full jit cost before ``/readyz``
flips — AOT_CERT_r05.json measures 64.7 s for the flagship decode chunk
and 1060.5 s for the 34B north-star program, so every deploy or crash is
minutes of lost capacity.  This module makes the compile a one-time cost
per (program, shape, environment): every :class:`~reval_tpu.analysis.
jitcheck.TrackedJit` variant an engine compiles is serialized to disk
via ``jax.export`` and the NEXT process boot loads the serialized
executable instead of tracing + lowering again.

Layering (all additive — unset ``REVAL_TPU_AOT_CACHE_DIR`` disables the
whole module and engines behave exactly as before):

- :class:`AOTCache` — the directory: fingerprint-keyed entries (one
  ``.json`` meta + one ``.bin`` payload per compile variant), atomic
  tmp+rename writes with the meta as the commit point, sha256 payload
  checksums, a size-bounded LRU GC (``REVAL_TPU_AOT_CACHE_MAX_MB``), and
  the ``reval_aot_*`` counters.  Enabling the cache also points jax's
  own persistent compilation cache at ``<dir>/xla`` so the backend
  compile of a deserialized module is cached too.
- :class:`AotJit` — the per-entry wrapper around a ``TrackedJit``.  Per
  call it runs the tracker's variant accounting (``note_call`` — the
  ``reval_jit_*`` counters stay identical), then dispatches to the
  deserialized executable when the variant is cached, or compiles fresh
  through the underlying jit and serializes the result.  Static args
  are baked into the exported module, so the wrapper strips them when
  dispatching a loaded executable.

**Never a crash.**  Every degraded path — corrupt or truncated payload,
checksum or fingerprint mismatch, an unwritable cache directory, a jax
build that cannot export the program (Mosaic canary) — falls back to a
fresh compile with a typed event (``aot.cache_error`` /
``aot.unsupported``) and a counter; the serving path is never taken
down by its own cache.

**Fingerprint.**  Entries are keyed by a sha256 over the engine's
context (model config, dtypes, kernel backend, mesh, page geometry) plus
the jax/jaxlib versions (:func:`runtime_context`); a payload whose
recorded fingerprint disagrees with the booting engine's is stale — it
degrades to a fresh compile, never a wrong program.

``tools/aot_cache.py`` is the operator CLI (``ls`` / ``verify`` /
``gc``) over the same directory format.
"""

from __future__ import annotations

import functools
import hashlib
import json
import os
import time

from ...env import env_int, env_str
from ...obs import metrics as obs_metrics
from ...obs.logging import log_event

__all__ = ["AOTCache", "AotJit", "cache_from_env", "fingerprint",
           "runtime_context", "kernel_export_skip", "FORMAT"]

FORMAT = "reval-aot-v1"

_MB = 1 << 20

#: age before GC reaps a meta-less payload or leftover tmp file — long
#: enough for a live writer's commit (payload rename → meta rename) to
#: finish, short enough that a crash's debris goes at the next store
_ORPHAN_GRACE_S = 60.0


def runtime_context(**extra) -> dict:
    """The environment half of a cache fingerprint: jax/jaxlib versions
    (an executable serialized by one toolchain must not be fed to
    another) plus whatever engine context the caller adds."""
    import jax

    ctx = {"jax": jax.__version__}
    try:
        import jaxlib

        ctx["jaxlib"] = jaxlib.__version__
    except Exception:       # pragma: no cover — jaxlib always ships with jax
        pass
    ctx.update(extra)
    return ctx


def fingerprint(context: dict) -> str:
    """Canonical sha256 over a context dict (sorted-key JSON, everything
    stringified so dtypes/meshes/config reprs key stably)."""
    blob = json.dumps({str(k): str(v) for k, v in context.items()},
                      sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()


@functools.lru_cache(maxsize=None)
def kernel_export_skip() -> str | None:
    """Capability canary for Pallas-kernel exports, shared with
    tests/test_tpu_lowering.py: both decode kernels transpose a K/V page
    in VMEM (``jnp.swapaxes(k, 0, 1)``), and older jax builds' Mosaic
    TPU lowering has no rule for a (1, 0, 2) transpose — the chip's jax
    does.  Exports a minimal Pallas program using exactly that
    construct; a failure names the ENVIRONMENT gap (the host toolchain
    cannot lower the real kernels either), so kernel-program exports
    report ``unsupported`` instead of raising.  Cached — the probe costs
    seconds; callers that never export kernels never pay it."""
    try:
        import jax
        import jax.export  # noqa: F401 — jax 0.4.x needs the explicit import
    except ImportError as e:    # pragma: no cover — host jax build
        return f"jax.export unavailable on this host ({e})"
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def kern(x_ref, o_ref):
        o_ref[...] = jnp.swapaxes(x_ref[...], 0, 1)

    fn = pl.pallas_call(kern, out_shape=jax.ShapeDtypeStruct(
        (8, 2, 128), jnp.float32))
    try:
        # jit-entry: aot.canary warmup=1
        probe = jax.jit(fn)
        jax.export.export(probe, platforms=["tpu"])(
            jnp.zeros((2, 8, 128), jnp.float32))
        return None
    except Exception as e:  # noqa: BLE001 — any lowering error means the
        # host toolchain, not the kernel, is what cannot lower
        return ("jax.export unavailable for the Pallas kernel exports on "
                "this host: this jax build's Mosaic TPU lowering lacks the "
                f"kernels' baseline (1,0,2) transpose "
                f"({type(e).__name__})")


@functools.lru_cache(maxsize=None)
def _register_tree_serialization() -> None:
    """Register the engine's custom pytree containers with
    ``jax.export`` (serialize/deserialize walks treedefs): KVCache is a
    NamedTuple, PagedKVCache a registered dataclass whose only auxdata
    is its static ``page_size``.  Idempotent (cached); a best-effort
    no-op on jax builds without the registration API — the export then
    reports its own ``unsupported`` verdict."""
    try:
        import jax.export

        from ...models.model import KVCache
        from ...models.paged import PagedKVCache

        jax.export.register_namedtuple_serialization(
            KVCache, serialized_name="reval_tpu.KVCache")
        # auxdata for a registered dataclass is the meta-field tuple —
        # here just (page_size,)
        jax.export.register_pytree_node_serialization(
            PagedKVCache, serialized_name="reval_tpu.PagedKVCache",
            serialize_auxdata=lambda aux: json.dumps(
                [int(v) for v in aux]).encode(),
            deserialize_auxdata=lambda data: tuple(
                json.loads(data.decode())))
    except Exception:   # noqa: BLE001 — registration is an enabler, not
        # a requirement; the export path reports its own verdict
        pass


def _jax_deserialize(payload: bytes, donate_argnums: tuple = ()):
    """The default payload codec: a ``jax.export`` serialized module →
    a callable dispatching the deserialized executable.

    ``donate_argnums`` RE-APPLIES the original jit's buffer donation —
    serialization does not preserve it, and the engines' commit/decode
    programs update the paged KV pool in place through exactly that
    aliasing: without re-donation a warm restart would allocate a fresh
    copy of the whole pool per call and OOM a flagship-sized config
    that boots cold just fine (verified: a donated input survives the
    round trip unless the loader re-declares it)."""
    import jax
    import jax.export

    _register_tree_serialization()
    exported = jax.export.deserialize(bytearray(payload))
    # jit-entry: aot.exec warmup=8
    return jax.jit(exported.call,
                   donate_argnums=tuple(donate_argnums) or None)


def _sig_hash(sig_key) -> str:
    """Stable 16-hex digest of one TrackedJit signature key (leaf shapes
    and dtypes render as plain tuples/strings; the treedef repr is
    structural, so two processes tracing the same call agree)."""
    return hashlib.sha256(repr(sig_key).encode()).hexdigest()[:16]


def _entry_slug(entry: str) -> str:
    return entry.replace(".", "_")


class AOTCache:
    """One persistent executable-cache directory (see module docstring).

    Single-owner like the engines that hold it: one engine drives one
    cache instance from its own threads' serialized call path (the
    session driver); the CLI tool reads the directory out-of-band and
    tolerates concurrent writers through the atomic commit protocol
    (payload first, meta last)."""

    def __init__(self, cache_dir: str, *, max_mb: int | None = None,
                 registry=None):
        self.dir = cache_dir
        self.max_mb = (max_mb if max_mb is not None
                       else env_int("REVAL_TPU_AOT_CACHE_MAX_MB", 2048))
        # zero-arg callable returning the live MetricsRegistry (engines
        # swap stats wholesale between bench passes, same contract as
        # TrackedJit), or None for the internal counters only
        self._registry = registry
        self._disabled_store = False    # sticky after an unwritable dir
        #: process-local counter twin of the reval_aot_* metrics — the
        #: bench ``restart`` block and engine.aot_counters() read these
        #: (reset-proof against EngineStats swaps)
        self.hits = 0
        self.misses = 0
        self.errors = 0
        self.unsupported = 0
        self.compile_s_saved = 0.0
        # (monotonic stamp, bytes) memo for the <dir>/xla walk — see
        # _xla_bytes()
        self._xla_scan = (0.0, 0)
        try:
            os.makedirs(self.dir, exist_ok=True)
        except OSError as exc:
            self._disabled_store = True
            self._error("mkdir", str(self.dir), exc)
        # seed the directory gauges once — a warm boot may never store,
        # and load hits deliberately skip the (walking) refresh
        self._touch_gauges()

    def bind_registry(self, registry) -> None:
        """Point the reval_aot_* counters at an engine's registry (a
        zero-arg callable returning it) so they ride that engine's
        ``/metrics``."""
        self._registry = registry
        # a warm boot may never store: seed the directory gauges once
        # here instead of walking the directory per load hit
        self._touch_gauges()

    def _reg(self):
        reg = self._registry
        return reg() if callable(reg) else reg

    def _count(self, metric: str, n: float = 1) -> None:
        reg = self._reg()
        if reg is not None:
            reg.counter(metric).add(n)

    def _error(self, where: str, detail: str, exc=None) -> None:
        self.errors += 1
        self._count(obs_metrics.AOT_ERRORS)
        log_event("aot.cache_error", level="warning", where=where,
                  detail=detail, exc=exc)

    # -- directory layout ---------------------------------------------------
    def _base(self, entry: str, sig_key, fp: str) -> str:
        # the fingerprint is part of the FILE key: two engine configs
        # with identical call signatures (say xla- and pallas-backed
        # boots alternating over one shared dir) must coexist as
        # separate entries — a fp-free key would make each config's
        # store clobber the other's and every boot of either a cold
        # compile.  The meta's full-fingerprint check stays as defense
        # in depth against prefix collisions and hand-moved files.
        return os.path.join(
            self.dir,
            f"{_entry_slug(entry)}-{fp[:16]}-{_sig_hash(sig_key)}")

    def entries(self) -> list[dict]:
        """Meta rows for every committed entry (a ``.json`` whose
        payload exists), oldest-touched first — the LRU order GC reaps
        in.  Unreadable metas surface as ``{"error": ...}`` rows."""
        rows = []
        try:
            names = sorted(os.listdir(self.dir))
        except OSError:
            return []
        for name in names:
            if not name.endswith(".json"):
                continue
            path = os.path.join(self.dir, name)
            row = {"file": name, "path": path}
            try:
                with open(path) as f:
                    meta = json.load(f)
                if not isinstance(meta, dict):
                    raise ValueError("meta is not a JSON object")
                row.update(meta)
                payload = path[:-5] + ".bin"
                row["payload_present"] = os.path.exists(payload)
                row["mtime"] = os.path.getmtime(path)
            except Exception as exc:    # noqa: BLE001 — an unreadable meta
                # is a report row, never a crash
                row["error"] = repr(exc)
                row.setdefault("mtime", 0.0)
            rows.append(row)
        rows.sort(key=lambda r: r.get("mtime", 0.0))
        return rows

    def _touch_gauges(self, usage: tuple | None = None) -> None:
        reg = self._reg()
        if reg is None:
            return
        n, total = usage if usage is not None else self._usage()
        reg.gauge(obs_metrics.AOT_ENTRIES).set(n)
        reg.gauge(obs_metrics.AOT_BYTES).set(total)

    def _usage(self) -> tuple[int, int]:
        n = total = 0
        try:
            for name in os.listdir(self.dir):
                path = os.path.join(self.dir, name)
                if name.endswith(".json"):
                    n += 1
                if name.endswith((".json", ".bin")):
                    try:
                        total += os.path.getsize(path)
                    except OSError:
                        pass
        except OSError:
            pass
        # jax's own persistent compilation cache lives under <dir>/xla
        # (cache_from_env points it there): it is part of the directory
        # the size bound promises to keep sane, so it counts
        return n, total + self._xla_bytes()

    _XLA_SCAN_TTL_S = 30.0

    def _xla_bytes(self) -> int:
        """Bytes under ``<dir>/xla``, walked at most once per TTL: jax's
        cache holds thousands of files for flagship models and a cold
        boot stores many variants back-to-back — re-walking the tree per
        store would add exactly the IO this module exists to avoid."""
        now = time.monotonic()
        stamp, cached = self._xla_scan
        if stamp and now - stamp < self._XLA_SCAN_TTL_S:
            return cached
        total = 0
        for root, _dirs, names in os.walk(os.path.join(self.dir, "xla")):
            for name in names:
                try:
                    total += os.path.getsize(os.path.join(root, name))
                except OSError:
                    pass
        self._xla_scan = (now, total)
        return total

    # -- load / store -------------------------------------------------------
    def load(self, entry: str, sig_key, fp: str, deserialize=None):
        """The deserialized executable for one variant, or None (cold,
        stale, corrupt — every miss shape is counted + logged, never
        raised).  A hit refreshes the entry's LRU stamp and credits the
        recorded compile cost to ``reval_aot_compile_seconds_saved``.

        ``deserialize`` is the payload codec (bytes → callable/object);
        default is the ``jax.export`` module codec the engines store.
        The mock engine passes its own, so the whole degraded-path state
        machine is exercised host-only through the real cache."""
        base = self._base(entry, sig_key, fp)
        meta_path, payload_path = base + ".json", base + ".bin"
        if not os.path.exists(meta_path):
            self._miss(entry, "cold")
            return None
        try:
            with open(meta_path) as f:
                meta = json.load(f)
            if not isinstance(meta, dict) or meta.get("format") != FORMAT:
                raise ValueError(f"not a {FORMAT} meta")
            if meta.get("fingerprint") != fp:
                self._error("fingerprint", f"{entry}: cached fingerprint "
                            f"{str(meta.get('fingerprint'))[:16]}… does not "
                            f"match this engine's {fp[:16]}…")
                self._miss(entry, "fingerprint_mismatch")
                return None
            with open(payload_path, "rb") as f:
                payload = f.read()
            digest = hashlib.sha256(payload).hexdigest()
            if digest != meta.get("payload_sha256"):
                raise ValueError("payload checksum mismatch (truncated or "
                                 "corrupt write)")
            fn = (deserialize or _jax_deserialize)(payload)
        except Exception as exc:    # noqa: BLE001 — every load failure
            # shape degrades to a fresh compile
            self._error("load", f"{entry}: {type(exc).__name__}", exc)
            self._miss(entry, "load_error")
            return None
        saved = float(meta.get("compile_s") or 0.0)
        self.hits += 1
        self.compile_s_saved += saved
        self._count(obs_metrics.AOT_HITS)
        if saved:
            self._count(obs_metrics.AOT_SAVED_SECONDS, saved)
        log_event("aot.cache_hit", entry=entry, compile_s_saved=round(saved, 3),
                  file=os.path.basename(meta_path))
        try:
            now = time.time()
            os.utime(meta_path, (now, now))     # LRU freshness
        except OSError:
            pass
        # no gauge touch here: a hit changes no sizes, and a warm boot
        # loads many variants back-to-back — bind_registry/gc/store own
        # the (directory-walking) gauge refresh
        return fn

    def _miss(self, entry: str, reason: str) -> None:
        self.misses += 1
        self._count(obs_metrics.AOT_MISSES)
        log_event("aot.cache_miss", entry=entry, reason=reason)

    def note_unsupported(self, entry: str, reason: str) -> None:
        """This jax build cannot export ``entry``'s program (Mosaic
        canary failed, ``jax.export`` absent, or the export itself
        raised) — counted and logged ONCE per entry by the wrapper,
        never raised into the serving path."""
        self.unsupported += 1
        self._count(obs_metrics.AOT_UNSUPPORTED)
        log_event("aot.unsupported", level="warning", entry=entry,
                  reason=reason[:300])

    def store(self, entry: str, sig_key, fp: str, payload: bytes,
              compile_s: float, signature_repr: str = "") -> bool:
        """Commit one serialized executable: payload first, meta last
        (the loader requires the meta, so a torn write is invisible),
        both atomic tmp+rename.  An unwritable directory disables
        further stores for this process (counted + logged once)."""
        if self._disabled_store:
            return False
        base = self._base(entry, sig_key, fp)
        meta = {"format": FORMAT, "entry": entry,
                "fingerprint": fp,
                "signature": signature_repr[:2000],
                "payload_sha256": hashlib.sha256(payload).hexdigest(),
                "payload_bytes": len(payload),
                "compile_s": round(float(compile_s), 3),
                "created_ts": time.strftime("%Y-%m-%dT%H:%M:%S")}
        try:
            with open(base + ".bin.tmp", "wb") as f:
                f.write(payload)
            os.replace(base + ".bin.tmp", base + ".bin")
            with open(base + ".json.tmp", "w") as f:
                json.dump(meta, f)
            os.replace(base + ".json.tmp", base + ".json")
        except OSError as exc:
            self._disabled_store = True
            self._error("store", f"{entry}: cache dir unwritable — "
                        f"disabling stores for this process", exc)
            return False
        self.gc()
        return True

    # -- GC -----------------------------------------------------------------
    def gc(self, max_mb: int | None = None) -> int:
        """Evict least-recently-touched entries until the directory fits
        the size bound.  Returns entries evicted."""
        bound = (max_mb if max_mb is not None else self.max_mb) * _MB
        evicted = 0
        if max_mb is not None:
            # an explicit bound (CLI / tests) expects a FRESH directory
            # view, not the store path's TTL-memoised xla size
            self._xla_scan = (0.0, 0)
        # orphan payloads (a crash inside the payload-first commit
        # window leaves a .bin whose meta never landed) and stale .tmp
        # files count against the bound but are invisible to entries()
        # — left alone, one orphan past the bound would make every
        # store evict the whole live cache and still never fit.  Reap
        # them first; the grace period keeps a concurrent writer's
        # just-renamed payload safe until its meta commits.
        orphans = 0
        now = time.time()
        try:
            names = list(os.listdir(self.dir))
        except OSError:
            names = []
        for name in names:
            path = os.path.join(self.dir, name)
            stale = name.endswith((".bin.tmp", ".json.tmp")) or (
                name.endswith(".bin")
                and not os.path.exists(path[:-4] + ".json"))
            if not stale:
                continue
            try:
                if now - os.path.getmtime(path) > _ORPHAN_GRACE_S:
                    os.remove(path)
                    orphans += 1
            except OSError:
                pass
        n, total = self._usage()
        # reap jax's xla compilation-cache files (oldest first) BEFORE
        # touching AOT entries: a backend re-compile of a deserialized
        # module is far cheaper than re-paying the trace+lower an
        # evicted entry represents
        xla_reaped = 0
        if total > bound:
            xla_files = []
            for root, _dirs, names in os.walk(
                    os.path.join(self.dir, "xla")):
                for name in names:
                    path = os.path.join(root, name)
                    try:
                        xla_files.append((os.path.getmtime(path),
                                          os.path.getsize(path), path))
                    except OSError:
                        pass
            xla_files.sort()
            xla_left = sum(size for _m, size, _p in xla_files)
            for _mtime, size, path in xla_files:
                if total <= bound:
                    break
                try:
                    os.remove(path)
                except OSError:
                    continue
                total -= size
                xla_left -= size
                xla_reaped += 1
            self._xla_scan = (time.monotonic(), max(0, xla_left))
        if total > bound:
            # only now pay the meta-parsing entries() pass — the common
            # under-bound store skips it entirely
            for row in self.entries():
                if total <= bound:
                    break
                meta_path = row["path"]
                payload_path = meta_path[:-5] + ".bin"
                freed = 0
                for path in (meta_path, payload_path):
                    try:
                        freed += os.path.getsize(path)
                        os.remove(path)
                    except OSError:
                        pass
                total -= freed
                evicted += 1
        if evicted or orphans or xla_reaped:
            log_event("aot.gc", evicted=evicted, orphans=orphans,
                      xla_files=xla_reaped,
                      bound_mb=bound // _MB, bytes_now=max(0, total))
        self._touch_gauges((n - evicted, max(0, total)))
        return evicted

    # -- introspection -------------------------------------------------------
    def verify_entry(self, row: dict, deep: bool = False) -> str | None:
        """Integrity verdict for one :meth:`entries` row: None = ok,
        else the problem.  ``deep`` also round-trips the payload through
        ``jax.export.deserialize``."""
        if row.get("error"):
            return f"unreadable meta: {row['error']}"
        if row.get("format") != FORMAT:
            return f"wrong format {row.get('format')!r}"
        payload_path = row["path"][:-5] + ".bin"
        if not row.get("payload_present"):
            return "payload missing"
        try:
            with open(payload_path, "rb") as f:
                payload = f.read()
        except OSError as exc:
            return f"payload unreadable: {exc}"
        if hashlib.sha256(payload).hexdigest() != row.get("payload_sha256"):
            return "payload checksum mismatch"
        if deep:
            try:
                import jax.export

                # same treedef registrations as the load path — without
                # them a fresh CLI process reads every KVCache-carrying
                # payload as broken
                _register_tree_serialization()
                jax.export.deserialize(bytearray(payload))
            except Exception as exc:    # noqa: BLE001 — the verdict IS
                # the point of a deep verify
                return f"payload does not deserialize: {type(exc).__name__}"
        return None

    def counters(self) -> dict:
        """The bench ``restart`` block / ``engine.aot_counters()`` row."""
        n, total = self._usage()
        return {"hits": self.hits, "misses": self.misses,
                "errors": self.errors, "unsupported": self.unsupported,
                "compile_s_saved": round(self.compile_s_saved, 3),
                "entries": n, "bytes": total, "dir": self.dir}


class AotJit:
    """AOT-cache wrapper around one :class:`TrackedJit` entry.

    Call path: run the tracker's variant accounting (``note_call`` — the
    ``reval_jit_*`` counters and the jitcheck sanitizer see exactly the
    calls they would without the cache), then:

    - variant already loaded → dispatch to the deserialized executable;
    - variant on disk → deserialize once, count a hit, dispatch;
    - cold/stale/corrupt → compile fresh through the underlying jit
      (timed), then export + store the serialized module for the next
      process.  An export failure marks the entry ``unsupported`` (once)
      and the wrapper degrades to a plain TrackedJit.

    ``static`` names the entry's static argnames: their values are baked
    into each exported variant, so dispatch to a loaded executable
    strips them from the call.

    ``canary`` is an optional zero-arg capability probe returning a skip
    reason (or None): engines whose programs embed Pallas kernels pass
    :func:`kernel_export_skip`, so a jax build whose Mosaic lowering
    cannot export the kernels reports ``unsupported`` up front — cheap,
    with the environment gap named — instead of paying a doomed export
    per variant.  The degraded entry serves through the plain TrackedJit
    exactly as if the cache were off.
    """

    def __init__(self, tracked, cache: AOTCache, context: dict,
                 static: tuple = (), canary=None, donate: tuple = ()):
        self._tracked = tracked
        self._cache = cache
        self._static = tuple(static)
        self._canary = canary
        #: positional indices (at THIS wrapper's call site) whose buffers
        #: the original jit donates — re-applied to the deserialized
        #: executable, because serialization drops donation and the
        #: engines' in-place KV-pool updates depend on it
        self._donate = tuple(donate)
        self._fp = fingerprint(runtime_context(**context))
        self._loaded: dict = {}         # sig key -> deserialized callable
        self._probed: set = set()       # sig keys already checked on disk
        self._unsupported = False
        #: fresh XLA compiles this process actually paid for this entry —
        #: the drill's "zero compilations of already-cached entries"
        self.fresh_compiles = 0

    # the tracker surface jit_counters()/tests read, unchanged
    @property
    def name(self) -> str:
        return self._tracked.name

    @property
    def warmup(self):
        return self._tracked.warmup

    @property
    def variants(self) -> int:
        return self._tracked.variants

    @property
    def misses(self) -> int:
        return self._tracked.misses

    def _strip_static(self, kwargs: dict) -> dict:
        if not self._static:
            return kwargs
        return {k: v for k, v in kwargs.items() if k not in self._static}

    def __call__(self, *args, **kwargs):
        key = self._tracked.note_call(args, kwargs)
        fn = self._loaded.get(key)
        if fn is not None:
            return fn(*args, **self._strip_static(kwargs))
        if self._unsupported or key in self._probed:
            return self._tracked._fn(*args, **kwargs)
        self._probed.add(key)
        fn = self._cache.load(
            self.name, key, self._fp,
            deserialize=lambda payload: _jax_deserialize(
                payload, donate_argnums=self._donate))
        if fn is not None:
            self._loaded[key] = fn
            return fn(*args, **self._strip_static(kwargs))
        # fresh compile (the first call traces + lowers + runs; its wall
        # is the upper bound of what the next boot's hit will save)
        t0 = time.perf_counter()
        out = self._tracked._fn(*args, **kwargs)
        compile_s = time.perf_counter() - t0
        self.fresh_compiles += 1
        self._export_store(key, args, kwargs, compile_s)
        return out

    def _export_store(self, key, args, kwargs, compile_s: float) -> None:
        if self._cache._disabled_store:
            # the dir already proved unwritable (sticky): skip the
            # export — jax.export on a real program costs compile-scale
            # seconds, and store() would drop the bytes anyway
            return
        if self._canary is not None:
            reason = self._canary()
            if reason is not None:
                # the environment, not this entry, cannot export: report
                # unsupported (counted + logged once) and degrade to the
                # plain TrackedJit — never raise into the serving path
                self._unsupported = True
                self._cache.note_unsupported(self.name, reason)
                return
        try:
            import jax.export

            _register_tree_serialization()
            exported = jax.export.export(self._tracked._fn)(*args, **kwargs)
            payload = bytes(exported.serialize())
        except Exception as exc:    # noqa: BLE001 — a program this jax
            # build cannot export (Mosaic gap, unsupported primitive) is
            # an environment verdict, not a serving fault
            if not self._unsupported:
                self._unsupported = True
                self._cache.note_unsupported(
                    self.name, f"{type(exc).__name__}: {exc}")
            return
        self._cache.store(self.name, key, self._fp, payload, compile_s,
                          signature_repr=repr(key))

    def __getattr__(self, item):
        return getattr(self._tracked, item)


def cache_from_env(registry=None) -> AOTCache | None:
    """The process's AOT cache per ``REVAL_TPU_AOT_CACHE_DIR`` (empty/
    unset disables), with jax's own persistent compilation cache pointed
    at ``<dir>/xla`` so the backend compile of a deserialized module is
    cached across processes too."""
    cache_dir = env_str("REVAL_TPU_AOT_CACHE_DIR", "") or ""
    if not cache_dir:
        return None
    _enable_jax_persistent_cache(os.path.join(cache_dir, "xla"))
    return AOTCache(cache_dir, registry=registry)


@functools.lru_cache(maxsize=None)
def _enable_jax_persistent_cache(xla_dir: str) -> None:
    try:
        os.makedirs(xla_dir, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", xla_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as exc:    # noqa: BLE001 — jax's own cache is a
        # bonus layer; its absence must not disable the AOT cache
        log_event("aot.cache_error", level="warning", where="xla_cache",
                  detail="could not enable jax persistent compilation "
                         "cache", exc=exc)
