"""Tokenizer adapters.

The engine only needs ``encode``/``decode``/``eos_id``/``pad_id``.
:class:`HFTokenizer` wraps a HuggingFace checkpoint's tokenizer;
:class:`ByteTokenizer` is a dependency-free byte-level fallback used by
tests and random-weight benches (no tokenizer files required).
"""

from __future__ import annotations

__all__ = ["HFTokenizer", "ByteTokenizer"]


class HFTokenizer:
    def __init__(self, model_path: str):
        from transformers import AutoTokenizer

        self.tk = AutoTokenizer.from_pretrained(model_path)
        self.eos_id = self.tk.eos_token_id
        self.pad_id = self.tk.pad_token_id if self.tk.pad_token_id is not None else (self.eos_id or 0)

    def encode(self, text: str) -> list[int]:
        return self.tk.encode(text)

    def decode(self, ids: list[int]) -> str:
        return self.tk.decode(ids, skip_special_tokens=True)


class ByteTokenizer:
    """UTF-8 bytes as tokens; ids 0-255 are bytes, 256 BOS, 257 EOS."""

    vocab_size = 258

    def __init__(self):
        self.bos_id = 256
        self.eos_id = 257
        self.pad_id = 0

    def encode(self, text: str) -> list[int]:
        return [self.bos_id] + list(text.encode("utf-8"))

    def decode(self, ids) -> str:
        data = bytes(i for i in ids if 0 <= i < 256)
        return data.decode("utf-8", errors="ignore")
