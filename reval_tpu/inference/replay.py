"""Replay backend: re-serve generations from a prior run's results log.

Deterministic re-scoring without any model — the framework's regression
oracle (reference inference.py:133-168).  Reads the results-store JSONL
(last row is the metrics trailer and is skipped), flattens every
``generation[*].results[*].generated`` string in order, and serves them
one per ``infer`` call; ``'EOF'`` when exhausted.
"""

from __future__ import annotations

import glob
import json
import os

from .base import InferenceBackend, OPENAI_FULL_IDS

__all__ = ["ReplayBackend"]


class ReplayBackend(InferenceBackend):
    def __init__(self, replay_task: str, model_id: str, temp: float = 0.8,
                 prompt_type: str = "direct", replay_time: str | None = None,
                 results_dir: str = "model_generations",
                 replay_results_dir: str | None = None, **kwargs):
        """``replay_results_dir`` reads logs from a different tree than the
        one this run writes to — e.g. re-scoring the reference repo's
        committed logs (read-only) into a local results dir."""
        model_id = OPENAI_FULL_IDS.get(model_id, model_id)
        super().__init__(model_id, temp=temp, prompt_type=prompt_type)
        results_dir = replay_results_dir or results_dir
        base = os.path.join(results_dir, f"{replay_task}@{self.info}")
        # Fallback: reference logs use unsanitised model ids with '/' in the
        # directory name; our writer sanitises.  Accept both.
        candidates = [base, os.path.join(results_dir, f"{replay_task}@{self.info}".replace("/", "_"))]
        path = next((c for c in candidates if glob.glob(f"{c}/*.jsonl")), None)
        if path is None:
            raise FileNotFoundError(f"no replay logs under {candidates}")
        if replay_time is None:
            file = max(glob.glob(f"{path}/*.jsonl"), key=os.path.getctime)
        else:
            matches = glob.glob(f"{path}/{replay_time}.*jsonl") + [f"{path}/{replay_time}.jsonl"]
            file = next((f for f in matches if os.path.exists(f)), None)
            if file is None:
                raise FileNotFoundError(f"no replay log for timestamp {replay_time!r} under {path}")
        self.source_file = file
        self.generations: list[str] = []
        with open(file) as f:
            rows = [json.loads(line) for line in f if line.strip()]
        for row in rows[:-1]:  # last row is the metrics trailer
            for gen in row.get("generation", []):
                recs = self._dedup(gen.get("results", []))
                self.generations.extend(rec.get("generated", "") for rec in recs)
        self.ptr = 0

    @staticmethod
    def _dedup(recs: list[dict]) -> list[dict]:
        """Drop the reference path task's double-appended records: it logs
        each probe twice, a bare {generated,response,expected} then the same
        probe enriched with line/prompt (reference evaluation.py:549,552).
        A bare record whose successor carries the same generation plus a
        strict superset of keys is that duplicate.  Logs written by this
        framework (and the reference's other tasks) have uniform key sets
        per task, so the strict-subset test never fires on them."""
        out = []
        for i, rec in enumerate(recs):
            nxt = recs[i + 1] if i + 1 < len(recs) else None
            if (nxt is not None
                    and rec.get("generated") == nxt.get("generated")
                    and set(rec) < set(nxt)):
                continue
            out.append(rec)
        return out

    def infer_one(self, prompt: str) -> str:
        if self.ptr >= len(self.generations):
            return "EOF"
        resp = self.generations[self.ptr]
        self.ptr += 1
        return resp
