"""Mock + scripted backends: model-free runs of the whole eval loop.

``MockBackend`` answers every prompt with a fixed string (the reference's
``--mock`` flag, evaluation.py:45-47); ``ScriptedBackend`` serves a given
response list in order — the unit-test workhorse for scoring logic.
"""

from __future__ import annotations

from typing import Sequence

from .base import InferenceBackend

__all__ = ["MockBackend", "ScriptedBackend"]


class MockBackend(InferenceBackend):
    def __init__(self, model_id: str = "mock_model", response: str = "mock_model_gen", **kwargs):
        kwargs.setdefault("prompt_type", "direct")
        super().__init__(model_id, **{k: v for k, v in kwargs.items() if k in ("temp", "prompt_type", "max_new_tokens")})
        self.response = response
        self.calls = 0

    @property
    def info(self) -> str:
        # Mock runs are stored under a model-independent name
        # (reference evaluation.py:125-126).
        return f"mock_model_{self.prompt_type}"

    def infer_one(self, prompt: str) -> str:
        self.calls += 1
        return self.response


class ScriptedBackend(InferenceBackend):
    """Serves ``responses`` in order; 'EOF' when exhausted."""

    def __init__(self, responses: Sequence[str], model_id: str = "scripted", **kwargs):
        kwargs.setdefault("prompt_type", "direct")
        super().__init__(model_id, **{k: v for k, v in kwargs.items() if k in ("temp", "prompt_type", "max_new_tokens")})
        self.responses = list(responses)
        self.ptr = 0
        self.prompts_seen: list[str] = []

    def infer_one(self, prompt: str) -> str:
        self.prompts_seen.append(prompt)
        if self.ptr >= len(self.responses):
            return "EOF"
        resp = self.responses[self.ptr]
        self.ptr += 1
        return resp
