"""OpenAI chat-completions backend (reference inference.py:46-73).

Optional dependency: ``openai`` (and ``backoff`` if present for rate-limit
retry; otherwise a small built-in exponential backoff is used).  Reads
``OPENAI_API_KEY`` / ``OPENAI_BASE_URL`` from the environment, honouring a
``.env`` file when python-dotenv is installed.
"""

from __future__ import annotations

import os
import random
import time

from .base import InferenceBackend, OPENAI_FULL_IDS as _FULL_IDS

__all__ = ["OpenAIBackend"]

SYSTEM_PROMPT = (
    "You are an expert at Python programming, code execution, test case generation, and fuzzing."
)


class OpenAIBackend(InferenceBackend):
    def __init__(self, model_id: str = "gpt-3.5", temp: float = 0.8, prompt_type: str = "direct", **kwargs):
        assert model_id in _FULL_IDS, f"use a valid OpenAI model id: {sorted(_FULL_IDS)}"
        super().__init__(_FULL_IDS[model_id], temp=temp, prompt_type=prompt_type)
        if os.path.exists(".env"):
            try:
                from dotenv import load_dotenv

                load_dotenv(".env", override=True)
            except ImportError:
                pass
        from openai import OpenAI  # optional dep; error here is actionable

        self._client = OpenAI(
            api_key=os.environ["OPENAI_API_KEY"],
            base_url=os.environ.get("OPENAI_BASE_URL"),
        )

    def infer_one(self, prompt: str) -> str:
        from openai import RateLimitError

        delay = 1.0
        while True:
            try:
                return self._request(prompt)
            except RateLimitError:
                time.sleep(delay + random.random())
                delay = min(delay * 2, 60.0)

    def _request(self, prompt: str) -> str:
        stream = self._client.chat.completions.create(
            model=self.model_id,
            messages=[
                {"role": "system", "content": SYSTEM_PROMPT},
                {"role": "user", "content": prompt},
            ],
            stream=True,
            temperature=self.temp,
            stop=self.config.stop,
            max_tokens=self.config.max_new_tokens,
        )
        chunks = []
        for chunk in stream:
            content = chunk.choices[0].delta.content
            if content is not None:
                chunks.append(content)
        return "".join(chunks)
