"""Inference backend abstraction.

A backend turns prompts into completions.  Beyond the reference's
single-prompt ``infer`` (inference.py:31), backends here expose
``infer_many`` so the task engine can hand the TPU engine whole batches —
the serial one-prompt-at-a-time harness is what throttles accelerators
(SURVEY §7 hard part 5).  Backends that are inherently serial (replay,
HTTP) just loop.

Dispatch (``create_backend``) mirrors the reference factory
(inference.py:34-44) with the vLLM arms replaced by the in-tree TPU engine:
``replay_task`` → replay; ``'gpt' in model_id`` → OpenAI; ``port`` → HTTP
client; otherwise → TPU.
"""

from __future__ import annotations

from typing import Sequence

__all__ = ["InferenceBackend", "GenerationConfig", "create_backend"]

# Generation budget per prompt style (reference inference.py:25).
MAX_NEW_TOKENS = {"direct": 256, "cot": 1024}

# The universal stop sequence (reference inference.py:65,97,123).
STOP_STRING = "[/ANSWER]"

# Short OpenAI aliases → full model ids (reference inference.py:49-52).
OPENAI_FULL_IDS = {"gpt-3.5": "gpt-3.5-turbo-0125", "gpt-4": "gpt-4-turbo-preview"}


def model_info_from_config(cfg: dict) -> str:
    """The results-directory identity a run with this config would write to.

    Must stay in lockstep with :attr:`InferenceBackend.info` and the mock
    naming in ``TaskRunner`` — consistency/replay lookups depend on it.
    """
    if cfg.get("mock") or cfg.get("custom_mock") or cfg.get("backend") == "mock":
        return f"mock_model_{cfg.get('prompt_type', 'direct')}"
    model_id = OPENAI_FULL_IDS.get(cfg["model_id"], cfg["model_id"])
    return f"{model_id}_{cfg.get('prompt_type', 'direct')}_temp{float(cfg.get('temp', 0.8))}"


class GenerationConfig:
    """Sampling/stopping knobs shared by all backends."""

    def __init__(self, temp: float = 0.8, prompt_type: str = "direct", max_new_tokens: int | None = None):
        self.temp = float(temp)
        self.prompt_type = prompt_type
        self.max_new_tokens = max_new_tokens or MAX_NEW_TOKENS.get(prompt_type, 256)
        self.stop = [STOP_STRING]


class InferenceBackend:
    """Base class: identity + generation config + the infer API."""

    def __init__(self, model_id: str, temp: float = 0.8, prompt_type: str = "direct",
                 max_new_tokens: int | None = None, **_ignored):
        self.model_id = model_id
        self.config = GenerationConfig(temp, prompt_type, max_new_tokens)

    @property
    def temp(self) -> float:
        return self.config.temp

    @property
    def prompt_type(self) -> str:
        return self.config.prompt_type

    @property
    def info(self) -> str:
        """Results-directory identity (reference inference.py:27-29)."""
        return f"{self.model_id}_{self.prompt_type}_temp{self.temp}"

    # -- generation -------------------------------------------------------
    def infer(self, prompt: str) -> str:
        return self.infer_many([prompt])[0]

    def infer_many(self, prompts: Sequence[str]) -> list[str]:
        """Batched generation.  Default: serial loop over :meth:`infer_one`;
        the TPU engine overrides this with true batched decode."""
        return [self.infer_one(p) for p in prompts]

    def infer_one(self, prompt: str) -> str:
        raise NotImplementedError

    def close(self) -> None:
        """Release device/network resources (no-op by default)."""


def create_backend(**kwargs) -> InferenceBackend:
    """Build a backend from config kwargs (the run-config dict).

    Recognised shapes, in priority order:
    - ``replay_task=…``            → :class:`~reval_tpu.inference.replay.ReplayBackend`
    - ``mock=True``/``custom_mock``→ :class:`~reval_tpu.inference.mock.MockBackend`
    - ``model_id`` contains 'gpt'  → :class:`~reval_tpu.inference.openai_backend.OpenAIBackend`
    - ``port=…``                   → :class:`~reval_tpu.inference.client.HTTPClientBackend`
    - otherwise                    → :class:`~reval_tpu.inference.tpu.TPUBackend`
    """
    if kwargs.get("replay_task"):
        from .replay import ReplayBackend

        return ReplayBackend(**kwargs)
    if kwargs.get("mock") or kwargs.get("custom_mock"):
        from .mock import MockBackend

        return MockBackend(**kwargs)
    if "gpt" in kwargs.get("model_id", ""):
        from .openai_backend import OpenAIBackend

        return OpenAIBackend(**kwargs)
    if kwargs.get("port"):
        from .client import HTTPClientBackend

        return HTTPClientBackend(**kwargs)
    from .tpu import TPUBackend

    return TPUBackend(**kwargs)
