"""HTTP client backend: OpenAI-compatible /v1/completions against a local
server (reference inference.py:106-131's vLLM-server client, rebuilt on
stdlib urllib so no SDK is required).

Pairs with ``reval_tpu.serving.server``, which serves the in-process TPU
engine over the same protocol — the split exists so one resident sharded
model can serve many sequential task runs (reference start_server.sh
topology, SURVEY §3.3).

Resilience: construction no longer races the server.  A wait-for-server
handshake polls ``/healthz`` (any HTTP answer counts as "up", so servers
predating the route still pass) until the engine finishes loading/compiling,
and every request afterwards runs under a
:class:`~reval_tpu.resilience.RetryPolicy` — connection resets, timeouts,
5xx responses, and truncated JSON bodies are retried with exponential
backoff instead of killing the launcher.
"""

from __future__ import annotations

import json
import urllib.request

from ..resilience import RetryPolicy, wait_for_server
from .base import InferenceBackend

__all__ = ["HTTPClientBackend"]


class HTTPClientBackend(InferenceBackend):
    def __init__(self, model_id: str, port: int = 3000, host: str = "localhost",
                 mock: bool = False, temp: float = 0.8, prompt_type: str = "direct",
                 retry_policy: RetryPolicy | None = None, retry: dict | None = None,
                 wait_for_server_s: float = 600.0, **kwargs):
        super().__init__(model_id, temp=temp, prompt_type=prompt_type)
        self.base_url = f"http://{host}:{port}/v1"
        # ``retry`` is the config-dict spelling (run configs are JSON);
        # ``retry_policy`` the programmatic one
        self.retry = retry_policy or RetryPolicy(**(retry or {}))
        self._server_model = model_id
        if not mock:
            # Launchers start client and server concurrently; block here
            # until the server answers instead of crashing on the eager
            # /models probe.  The default budget is 10 minutes because the
            # engine really does spend minutes loading + compiling a big
            # checkpoint before it binds the port.
            wait_for_server(lambda: self._request_once("/healthz", timeout=5),
                            timeout=wait_for_server_s,
                            describe=f"server at {self.base_url}")
            models = self._get("/models")
            self._server_model = models["data"][0]["id"]
            print(f"user-side model_id: {model_id}, server-side model_id: {self._server_model}")

    def _request_once(self, route: str, data: bytes | None = None,
                      timeout: float = 30) -> dict:
        req = urllib.request.Request(
            self.base_url + route, data=data,
            headers={"Content-Type": "application/json"} if data else {},
            method="POST" if data is not None else "GET",
        )
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.load(resp)

    def _get(self, route: str) -> dict:
        return self.retry.call(lambda: self._request_once(route))

    def _post(self, route: str, payload: dict, timeout: float = 600) -> dict:
        data = json.dumps(payload).encode()
        return self.retry.call(
            lambda: self._request_once(route, data=data, timeout=timeout))

    def infer_one(self, prompt: str) -> str:
        out = self._post("/completions", {
            "model": self._server_model,
            "prompt": prompt,
            "temperature": self.temp,
            "stop": self.config.stop,
            "max_tokens": self.config.max_new_tokens,
        })
        return out["choices"][0]["text"]

    def infer_many(self, prompts) -> list[str]:
        """The server accepts list prompts (OpenAI protocol) so whole
        batches ride one request and the engine schedules them together."""
        if not prompts:
            return []
        out = self._post("/completions", {
            "model": self._server_model,
            "prompt": list(prompts),
            "temperature": self.temp,
            "stop": self.config.stop,
            "max_tokens": self.config.max_new_tokens,
        })
        choices = sorted(out["choices"], key=lambda c: c.get("index", 0))
        return [c["text"] for c in choices]
