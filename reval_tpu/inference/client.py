"""HTTP client backend: OpenAI-compatible /v1/completions against a local
server (reference inference.py:106-131's vLLM-server client, rebuilt on
stdlib urllib so no SDK is required).

Pairs with ``reval_tpu.serving.server``, which serves the in-process TPU
engine over the same protocol — the split exists so one resident sharded
model can serve many sequential task runs (reference start_server.sh
topology, SURVEY §3.3).
"""

from __future__ import annotations

import json
import urllib.request

from .base import InferenceBackend

__all__ = ["HTTPClientBackend"]


class HTTPClientBackend(InferenceBackend):
    def __init__(self, model_id: str, port: int = 3000, host: str = "localhost",
                 mock: bool = False, temp: float = 0.8, prompt_type: str = "direct", **kwargs):
        super().__init__(model_id, temp=temp, prompt_type=prompt_type)
        self.base_url = f"http://{host}:{port}/v1"
        self._server_model = model_id
        if not mock:
            models = self._get("/models")
            self._server_model = models["data"][0]["id"]
            print(f"user-side model_id: {model_id}, server-side model_id: {self._server_model}")

    def _get(self, route: str) -> dict:
        with urllib.request.urlopen(self.base_url + route, timeout=30) as resp:
            return json.load(resp)

    def _post(self, route: str, payload: dict, timeout: float = 600) -> dict:
        req = urllib.request.Request(
            self.base_url + route,
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.load(resp)

    def infer_one(self, prompt: str) -> str:
        out = self._post("/completions", {
            "model": self._server_model,
            "prompt": prompt,
            "temperature": self.temp,
            "stop": self.config.stop,
            "max_tokens": self.config.max_new_tokens,
        })
        return out["choices"][0]["text"]

    def infer_many(self, prompts) -> list[str]:
        """The server accepts list prompts (OpenAI protocol) so whole
        batches ride one request and the engine schedules them together."""
        if not prompts:
            return []
        out = self._post("/completions", {
            "model": self._server_model,
            "prompt": list(prompts),
            "temperature": self.temp,
            "stop": self.config.stop,
            "max_tokens": self.config.max_new_tokens,
        })
        choices = sorted(out["choices"], key=lambda c: c.get("index", 0))
        return [c["text"] for c in choices]
