"""HTTP client backend: OpenAI-compatible /v1/completions against a local
server (reference inference.py:106-131's vLLM-server client, rebuilt on
stdlib urllib so no SDK is required).

Pairs with ``reval_tpu.serving.server``, which serves the in-process TPU
engine over the same protocol — the split exists so one resident sharded
model can serve many sequential task runs (reference start_server.sh
topology, SURVEY §3.3).

Resilience: construction no longer races the server.  A wait-for-server
handshake polls ``/readyz`` — not just "the port answers" but "the engine
is loaded, the driver is stepping, and the queue has room"; a 503
(draining, wedged, still loading, or ``warming`` — a restarted server
replaying its warm-state snapshot through prefill before readiness
flips) keeps polling, while a 404 from an older server without the
route still counts as up.  Every request
afterwards runs under a :class:`~reval_tpu.resilience.RetryPolicy` —
connection resets, timeouts, 5xx responses, truncated JSON bodies, and
429 load sheds are retried with exponential backoff, honoring the
server's ``Retry-After`` hint when one is sent.

The endpoint may equally be a :class:`~reval_tpu.serving.FleetRouter`:
its ``/readyz`` aggregates the replica set (200 while ANY replica is
ready — "some replicas ready" IS ready, so the handshake completes on a
degraded fleet), its 429/503 sheds carry the same ``Retry-After``
contract through the extra hop, and ``X-Request-Id`` passes through
both directions — one id names the request in this client's retry log,
the router's failover log, and the serving replica's spans.

Deadlines: each completion request carries ``deadline_s`` — this client's
remaining per-request budget (``request_timeout``) — so a server that
cannot finish in time cancels the work engine-side (freeing its batch
slot for live traffic) instead of generating tokens nobody will read.

Request ids: the client MINTS one id per logical request, sends it as
``X-Request-Id``, and keeps it across retries of that request — so the
server's logs/spans and this side's retry log (``(request_id, attempt,
delay)`` via the RetryPolicy ``label``) all name the same request, and a
re-sent attempt is attributable to its original.  The server echoes the
id on every response.
"""

from __future__ import annotations

import json
import urllib.request
import uuid

from ..resilience import RetryPolicy, wait_for_server
from .base import InferenceBackend

__all__ = ["HTTPClientBackend"]

# /readyz statuses that mean "server up, engine not serving yet (loading,
# draining, overloaded)" — the handshake keeps waiting through them
READYZ_WAIT_STATUSES = frozenset({429, 503})


class HTTPClientBackend(InferenceBackend):
    def __init__(self, model_id: str, port: int = 3000, host: str = "localhost",
                 mock: bool = False, temp: float = 0.8, prompt_type: str = "direct",
                 retry_policy: RetryPolicy | None = None, retry: dict | None = None,
                 wait_for_server_s: float = 600.0,
                 request_timeout: float = 600.0, **kwargs):
        super().__init__(model_id, temp=temp, prompt_type=prompt_type)
        self.base_url = f"http://{host}:{port}/v1"
        # ``retry`` is the config-dict spelling (run configs are JSON);
        # ``retry_policy`` the programmatic one
        self.retry = retry_policy or RetryPolicy(**(retry or {}))
        #: per-request wall budget; also sent as the request's
        #: ``deadline_s`` so the server stops working for a caller that
        #: has already given up
        self.request_timeout = float(request_timeout)
        self._server_model = model_id
        #: the most recent verified reproducibility receipt (obs/
        #: receipts.py) — None until a receipted completion lands.  The
        #: fleet journals this per task; ``receipt_fingerprints`` is the
        #: set observed across the backend's lifetime (a fleet run that
        #: failed over between divergent replicas shows >1 entry).
        self.last_receipt: dict | None = None
        self.receipt_fingerprints: set[str] = set()
        if not mock:
            # Launchers start client and server concurrently; block here
            # until the server is READY instead of crashing on the eager
            # /models probe.  The default budget is 10 minutes because the
            # engine really does spend minutes loading + compiling a big
            # checkpoint before readiness flips.
            ready = wait_for_server(
                lambda: self._request_once("/readyz", timeout=5),
                timeout=wait_for_server_s,
                retry_statuses=READYZ_WAIT_STATUSES,
                describe=f"server at {self.base_url}")
            if isinstance(ready, dict) and "replicas_ready" in ready:
                # a fleet router answered: say how degraded the fleet is
                # (the handshake completes on ANY ready replica)
                print(f"router at {self.base_url}: "
                      f"{ready['replicas_ready']}/{ready['replicas_total']} "
                      f"replicas ready")
            models = self._get("/models")
            self._server_model = models["data"][0]["id"]
            print(f"user-side model_id: {model_id}, server-side model_id: {self._server_model}")

    def _request_once(self, route: str, data: bytes | None = None,
                      timeout: float = 30,
                      request_id: str | None = None) -> dict:
        headers = {"Content-Type": "application/json"} if data else {}
        if request_id is not None:
            headers["X-Request-Id"] = request_id
        req = urllib.request.Request(
            self.base_url + route, data=data, headers=headers,
            method="POST" if data is not None else "GET",
        )
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            header = resp.headers.get("X-Reval-Receipt")
            out = json.load(resp)
        if header is not None:
            self._note_receipt(header, out)
        return out

    def _note_receipt(self, header: str, body) -> None:
        """Verify + surface a response's reproducibility receipt: the
        header must parse as a valid ``reval-receipt-v1`` AND agree with
        the body's ``receipt`` field (one generation, two exposures — a
        proxy that rewrote one of them is exactly what this catches).  A
        bad receipt is a loud warning, never a failed completion: the
        text is still the text."""
        from ..obs.logging import log_event
        from ..obs.receipts import parse_receipt

        try:
            receipt = parse_receipt(header)
            embedded = body.get("receipt") if isinstance(body, dict) else None
            if embedded is not None and embedded != receipt:
                raise ValueError("X-Reval-Receipt header disagrees with "
                                 "the body's receipt field")
        except ValueError as exc:
            log_event("client.receipt_invalid", level="warning",
                      error=str(exc))
            return
        self.last_receipt = receipt
        self.receipt_fingerprints.add(receipt["fingerprint"])

    def _get(self, route: str) -> dict:
        rid = uuid.uuid4().hex[:12]
        return self.retry.call(
            lambda: self._request_once(route, request_id=rid),
            label=f"request {rid} (GET {route})")

    def _post(self, route: str, payload: dict,
              timeout: float | None = None) -> dict:
        timeout = self.request_timeout if timeout is None else timeout
        data = json.dumps(payload).encode()
        # ONE id for every retry attempt of this logical request: the
        # server's span/log trail shows the re-sends as the same request
        rid = uuid.uuid4().hex[:12]
        return self.retry.call(
            lambda: self._request_once(route, data=data, timeout=timeout,
                                       request_id=rid),
            label=f"request {rid} (POST {route})")

    def _completion_payload(self, prompt) -> dict:
        return {
            "model": self._server_model,
            "prompt": prompt,
            "temperature": self.temp,
            "stop": self.config.stop,
            "max_tokens": self.config.max_new_tokens,
            # the remaining budget this client will actually wait: past
            # it the server cancels the request engine-side (504) rather
            # than decode into a closed socket
            "deadline_s": self.request_timeout,
        }

    def infer_one(self, prompt: str) -> str:
        out = self._post("/completions", self._completion_payload(prompt))
        return out["choices"][0]["text"]

    def infer_many(self, prompts) -> list[str]:
        """The server accepts list prompts (OpenAI protocol) so whole
        batches ride one request and the engine schedules them together."""
        if not prompts:
            return []
        out = self._post("/completions",
                         self._completion_payload(list(prompts)))
        choices = sorted(out["choices"], key=lambda c: c.get("index", 0))
        return [c["text"] for c in choices]
