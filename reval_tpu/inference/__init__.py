"""Inference backends: TPU engine, OpenAI, HTTP client, replay, mock."""

from .base import InferenceBackend, GenerationConfig, create_backend, STOP_STRING
from .mock import MockBackend, ScriptedBackend
from .replay import ReplayBackend

__all__ = [
    "InferenceBackend",
    "GenerationConfig",
    "MockBackend",
    "ReplayBackend",
    "ScriptedBackend",
    "STOP_STRING",
    "create_backend",
]
