"""Self-healing kernel CI: supervised per-cell benchmarking, an autotune
leaderboard, and a perf instrument that cannot go blind.

The perf trajectory was an instrument-failure story: BENCH rounds 2-5
all report ``tpu-unreachable``, so every chip claim went stale while the
serving stack grew five PRs.  This module adopts the FlashInfer-Bench
loop (PAPERS.md, arxiv 2601.00227): a continuous kernel-benchmark
harness whose instrument treats its OWN failure as a first-class,
recoverable state.

Design, end to end:

- **Variant matrix.**  :func:`default_cells` enumerates kernel cells —
  backend (``xla`` / ``pallas`` / ``pallas_seq``) × dot-tile formulation
  (``swap`` / ``wide``, the in-kernel tiling knob ``REVAL_TPU_KERNEL_DOT``
  selects) × KV pool dtype (``bf16`` / ``int8``) × decode chunk size
  (host-fetch cadence, ``REVAL_TPU_DECODE_CHUNK``).  The timing core
  (:func:`time_cell`) IS ``tools/kernel_bench.py``'s: that CLI is now a
  thin label-map over this module, so the two can't drift.
- **Supervision.**  Every cell runs as a timeout-bounded SUBPROCESS
  (:func:`supervise_cell`): a wedged kernel, a dead tunnel, or a Mosaic
  crash loses one cell, never the round.  The child heartbeats a sidecar
  file; the parent watches it with the bench
  :class:`~reval_tpu.resilience.watchdog.StallWatchdog` PER CELL (stalled
  heartbeat + failed device probes → early kill) plus a hard per-cell
  deadline.  Transient failures retry under the resilience layer's
  :class:`~reval_tpu.resilience.RetryPolicy` with exponential backoff.
- **Degradation.**  A cell that still fails degrades to a STALE-marked
  entry carrying its last-known value and commit (the cell-wise
  extension of ``bench.py``'s ``fail()`` semantics) — never a blind 0.0;
  with no last-known value it is recorded skipped WITH the error.  The
  surviving cells always produce a leaderboard artifact
  (:data:`SCHEMA` = ``reval-kernelbench-v1``, schema self-checked before
  the atomic write, validated on disk by the ``kernelbench`` lint pass).
- **Autotune.**  The winning cell is emitted as a
  ``tools/decide_defaults.py``-compatible serving-config pick
  (``REVAL_TPU_PAGED_BACKEND`` + dot/chunk/kv knobs), and a regression
  gate fails loudly (exit 1, named cell, incumbent-vs-HEAD delta) when
  HEAD regresses the incumbent winner beyond a noise band.
- **Drills.**  ``--chaos-cell wedge|timeout|flaky-device:<cell>``
  (:class:`~reval_tpu.resilience.KernelCellChaos`) makes every
  degradation path exercisable on CPU in tier-1, and
  ``REVAL_TPU_KERNELBENCH_PERTURB=<cell>=<factor>`` seeds a measured
  regression so the gate's exit-1 path is drillable too.

``reval_kernelbench_*`` metrics and ``kernelbench.*`` events ride the
declared registries, and the artifact embeds a registry snapshot, so
``tools/obs_report.py`` (``--kernels``) sees instrument health like any
other subsystem.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import statistics
import subprocess
import sys
import tempfile
import time
from dataclasses import asdict, dataclass

from .env import env_float, env_str
from .obs import metrics as obs_metrics
from .obs.logging import log_event
from .obs.metrics import MetricsRegistry
from .resilience import KernelCellChaos, RetryPolicy
from .resilience.watchdog import StallWatchdog

__all__ = [
    "SCHEMA", "KernelCell", "BenchShape", "default_cells", "build_inputs",
    "time_cell", "child_main", "supervise_cell", "last_known_cell",
    "find_leaderboards", "incumbent_leaderboard", "regression_gate",
    "build_pick", "run_round", "validate_leaderboard", "write_leaderboard",
    "render_leaderboard", "main",
]

SCHEMA = "reval-kernelbench-v1"

#: legacy ``tools/kernel_bench.py`` row label -> (backend, dot, pool);
#: the thin CLI maps its historical variants onto matrix cells so
#: ``kernel_ab.txt`` keeps its exact line format for decide_defaults
LEGACY_LABELS = {
    "grid": ("pallas", "swap", "bf16"),
    "seq": ("pallas_seq", "swap", "bf16"),
    "grid-wide": ("pallas", "wide", "bf16"),
    "seq-wide": ("pallas_seq", "wide", "bf16"),
    "grid-int8": ("pallas", "swap", "int8"),
    "seq-int8": ("pallas_seq", "swap", "int8"),
    "xla": ("xla", None, "bf16"),
}


@dataclass(frozen=True)
class KernelCell:
    """One leaderboard cell: a fully pinned kernel configuration."""

    backend: str            # xla | pallas | pallas_seq | ragged
    dot: str | None         # swap | wide (None for xla: no dot knob)
    pool: str               # bf16 | int8 KV pool dtype
    chunk: int              # decode chunk size (steps per host fetch)

    @property
    def name(self) -> str:
        parts = [self.backend] + ([self.dot] if self.dot else [])
        return "-".join(parts + [self.pool, f"c{self.chunk}"])

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "KernelCell":
        return cls(backend=d["backend"], dot=d.get("dot"), pool=d["pool"],
                   chunk=int(d["chunk"]))


@dataclass
class BenchShape:
    """The decode shape every cell is timed at (the flagship bench
    shape by default; a toy one under ``--tiny``)."""

    slots: int = 32
    ctx: int = 600
    heads: int = 16
    kv_heads: int = 16
    head_dim: int = 128
    page: int = 128
    span: int = 16
    layers: int = 24
    reps: int = 10

    @classmethod
    def tiny(cls) -> "BenchShape":
        return cls(slots=2, ctx=96, span=3, layers=2, reps=3)

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "BenchShape":
        return cls(**{k: int(v) for k, v in d.items()})


def default_cells(tiny: bool = False) -> list[KernelCell]:
    """The declared cell taxonomy.  Tiny keeps one dot mode and the bf16
    pool (CPU interpret mode prices dot variants meaninglessly) but
    crosses every backend with two chunk cadences, so the harness paths
    — not the chip numbers — are what tier-1 certifies."""
    cells: list[KernelCell] = []
    if tiny:
        for backend in ("xla", "pallas", "pallas_seq", "ragged"):
            for chunk in (2, 4):
                dot = None if backend == "xla" else "swap"
                cells.append(KernelCell(backend, dot, "bf16", chunk))
        return cells
    for backend in ("xla", "pallas", "pallas_seq", "ragged"):
        dots = (None,) if backend == "xla" else ("swap", "wide")
        for dot in dots:
            for pool in ("bf16", "int8"):
                for chunk in (8, 32):
                    cells.append(KernelCell(backend, dot, pool, chunk))
    return cells


def _taxonomy_names(tiny: bool) -> set[str]:
    return {c.name for c in default_cells(tiny)}


# -- timing core (child side; ONE implementation, shared with the legacy
#    tools/kernel_bench.py CLI) ---------------------------------------------

def build_inputs(shape: BenchShape, pool: str, seed: int = 0) -> dict:
    """The paged-decode operand set at ``shape``: query, flat K/V page
    pools (bf16 or int8 + f32 scales), block tables, and seq lens."""
    import jax.numpy as jnp
    import numpy as np

    b, h, h_kv, d, p = (shape.slots, shape.heads, shape.kv_heads,
                        shape.head_dim, shape.page)
    need = (shape.ctx + p - 1) // p + 1
    # the table must span every live page or the kernels read garbage ids
    span = max(shape.span, need)
    n_pages = 1 + b * need
    rng = np.random.default_rng(seed)

    q = jnp.asarray(rng.standard_normal((b, h, d)), jnp.bfloat16)
    kp = jnp.asarray(rng.standard_normal((n_pages * p, h_kv, d)), jnp.bfloat16)
    vp = jnp.asarray(rng.standard_normal((n_pages * p, h_kv, d)), jnp.bfloat16)
    out = {"q": q, "k": kp, "v": vp, "k_scales": None, "v_scales": None}
    if pool == "int8":
        out["k"] = (kp * 16).astype(jnp.int8)
        out["v"] = (vp * 16).astype(jnp.int8)
        scales = jnp.full((n_pages * p, h_kv), 1 / 16, jnp.float32)
        out["k_scales"] = out["v_scales"] = scales
    tables = np.zeros((b, span), np.int32)
    for s in range(b):
        for j in range(need):
            tables[s, j] = 1 + s * need + j
    out["tables"] = jnp.asarray(tables)
    out["lens"] = jnp.full((b,), shape.ctx, jnp.int32)
    return out


def _cell_fn(backend: str, dot: str | None):
    """The kernel callable + trace-time kwargs for a cell (direct
    function references — the dispatcher's env/autotune resolution must
    never leak into a cell that pins its own config)."""
    import jax

    from .ops import pallas_attention as pa

    if backend == "xla":
        return pa.paged_decode_attention_xla, {}
    kw = {"dot_mode": dot or "swap",
          "interpret": jax.default_backend() != "tpu"}
    if backend == "ragged":
        import jax.numpy as jnp

        def ragged_decode(q, k, v, tables, lens, **kwargs):
            # the ragged wave kernel at its decode point (W=1): same
            # operand shapes as every other cell, so the leaderboard
            # prices it head-to-head on the one shape all cells share
            out = pa.ragged_paged_attention_pallas(
                q[:, None], k, v, tables, jnp.maximum(lens, 1) - 1,
                jnp.ones_like(lens), **kwargs)
            return out[:, 0]
        return ragged_decode, kw
    fn = (pa.paged_decode_attention_pallas_seq if backend == "pallas_seq"
          else pa.paged_decode_attention_pallas)
    return fn, kw


def time_cell(cell: KernelCell, shape: BenchShape, *, tiny: bool = False,
              heartbeat=None, inputs: dict | None = None) -> dict:
    """Time one cell in-process and return its row observables.

    ``ms_per_step`` is the cost of one decode step (``shape.layers``
    kernel calls), measured by the same N-vs-1 in-jit ``fori_loop``
    cancellation as the historical kernel A/B — timing MUST end on a
    host fetch (through the axon tunnel ``block_until_ready`` returns
    before the device executes), and the fetch+RTT overhead cancels
    between the long and short loops.  The cell's ``chunk`` sets the
    long loop to ``chunk * layers`` calls: one decode chunk's worth of
    kernel work per fetch, so the dispatch amortisation the chunk knob
    trades is what the cell actually prices.
    """
    hb = heartbeat or (lambda *_: None)
    hb("build", 0)
    import jax

    if tiny:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    p = shape.page
    data = inputs if inputs is not None else build_inputs(shape, cell.pool)
    fn, kw = _cell_fn(cell.backend, cell.dot)
    kw = dict(kw, page_size=p)
    quantized = data["k_scales"] is not None
    if quantized:
        kw.update(k_scales=data["k_scales"], v_scales=data["v_scales"])

    q, k, v = data["q"], data["k"], data["v"]
    tables, lens = data["tables"], data["lens"]

    def make_loop(n):
        @jax.jit
        def loop(q, k, v, tables, lens):
            def body(_, acc):
                o = fn(acc.astype(q.dtype), k, v, tables, lens, **kw)
                return o.astype(jnp.float32)
            return jax.lax.fori_loop(0, n, body, q.astype(jnp.float32))
        return loop

    def fetch_time(loop):
        t0 = time.perf_counter()
        np.asarray(loop(q, k, v, tables, lens))
        return time.perf_counter() - t0

    loop_n = max(shape.layers * cell.chunk, 1)
    long_loop, unit_loop = make_loop(loop_n), make_loop(1)
    hb("compile", 0)
    fetch_time(long_loop)
    fetch_time(unit_loop)
    t_n, t_1 = [], []
    for rep in range(shape.reps):
        hb("rep", rep)
        t_n.append(fetch_time(long_loop))
        if loop_n > 1:
            t_1.append(fetch_time(unit_loop))
    if loop_n > 1:
        per_call = ((statistics.median(t_n) - statistics.median(t_1))
                    / (loop_n - 1))
    else:
        per_call = statistics.median(t_n)
    # RTT jitter can swallow a sub-resolution kernel: floor at 1 µs so
    # the GB/s stays finite and the row reads as "fast", never as 0.0
    ms = max(per_call * shape.layers, 1e-6) * 1000
    live_pages = (shape.ctx + p - 1) // p
    elt = 1 if quantized else 2
    gb = (2 * shape.slots * live_pages * p * shape.kv_heads * shape.head_dim
          * elt * shape.layers) / 1e9
    if quantized:
        # the f32 K/V scale arrays are real traffic too — without them
        # the int8 rows understate their GB/s in the artifact that
        # decides the default backend
        gb += (2 * shape.slots * live_pages * p * shape.kv_heads * 4
               * shape.layers) / 1e9
    row = {"cell": cell.name, "ms_per_step": round(ms, 6),
           "gbps": round(gb / (ms / 1000), 3), "reps": shape.reps,
           "loop_n": loop_n, "device": str(jax.devices()[0].device_kind),
           "platform": jax.default_backend()}
    factor = _perturb_factor(cell.name)
    if factor is not None:
        # chaos hook: a seeded measured regression for the gate drill —
        # marked in the row so the artifact can never pose as evidence
        row["ms_per_step"] = round(row["ms_per_step"] * factor, 6)
        row["perturb"] = factor
    return row


def _perturb_factor(cell_name: str) -> float | None:
    spec = env_str("REVAL_TPU_KERNELBENCH_PERTURB", "") or ""
    if "=" not in spec:
        return None
    name, _, factor = spec.partition("=")
    if name.strip() != cell_name:
        return None
    try:
        return float(factor)
    except ValueError:
        return None


# -- child process -----------------------------------------------------------

class _Heartbeat:
    """Tiny progress writer the parent's StallWatchdog samples: any
    content change counts as progress, so a stalled child reads as a
    frozen file and a healthy one as a moving phase/rep/clock tuple."""

    def __init__(self, path: str | None):
        self.path = path

    def __call__(self, phase: str, rep: int) -> None:
        if not self.path:
            return
        try:
            with open(self.path, "w") as f:
                f.write(f"{phase}:{rep}:{time.monotonic():.3f}")
        except OSError:
            pass


def child_main(args) -> int:
    """``--run-cell`` entry: time ONE cell and print one JSON line.
    Exit 0 with a result object, nonzero with an ``{"error": ...}``
    object — the parent classifies nonzero exits as transport-shaped
    (retryable) failures."""
    payload = json.loads(args.run_cell)
    cell = KernelCell.from_dict(payload["cell"])
    shape = BenchShape.from_dict(payload["shape"])
    hb = _Heartbeat(args.heartbeat)
    hb("boot", 0)
    chaos = KernelCellChaos.parse(args.chaos_cell or [])
    try:
        # chaos fires before any jax work: a wedged tunnel dies during
        # device init, not politely mid-measurement
        chaos.apply_in_child(cell.name, args.attempt, heartbeat=hb)
        row = time_cell(cell, shape, tiny=bool(payload.get("tiny")),
                        heartbeat=hb)
        print(json.dumps(row))
        return 0
    except Exception as e:   # structured failure beats a traceback
        print(json.dumps({"cell": cell.name,
                          "error": f"{type(e).__name__}: {e}"}))
        return 7


# -- parent-side supervision -------------------------------------------------

def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _kill(proc) -> None:
    try:
        proc.terminate()
        try:
            proc.wait(timeout=0.5)
        except subprocess.TimeoutExpired:
            proc.kill()         # a wedge drill ignores SIGTERM on purpose
            proc.wait(timeout=5.0)
    except Exception:
        pass


def _run_cell_subprocess(cell: KernelCell, shape: BenchShape, *, tiny: bool,
                         attempt: int, timeout_s: float, stall_s: float,
                         probe_gap_s: float, probe_fails: int, poll_s: float,
                         chaos: KernelCellChaos | None, hb_dir: str) -> dict:
    """One supervised attempt: spawn the cell child, watch its heartbeat
    with the bench StallWatchdog (per CELL, not per round) under a hard
    deadline, and parse its one-line JSON result.  Raises
    ``TimeoutError`` (wedge/deadline) or ``ConnectionError`` (crash) —
    both transport-shaped for the retry policy's classification."""
    hb_path = os.path.join(hb_dir, f"{cell.name}.a{attempt}.hb")
    payload = {"cell": cell.to_dict(), "shape": shape.to_dict(), "tiny": tiny}
    cmd = [sys.executable, "-m", "reval_tpu.kernelbench",
           "--run-cell", json.dumps(payload), "--heartbeat", hb_path,
           "--attempt", str(attempt)]
    if chaos is not None:
        cmd += chaos.to_argv()
    env = dict(os.environ)
    env["PYTHONPATH"] = (_repo_root() + os.pathsep + env["PYTHONPATH"]
                         if env.get("PYTHONPATH") else _repo_root())
    # child output goes to FILES, never PIPEs: a chatty child (Mosaic /
    # libtpu compile warnings run to hundreds of KB) would fill a 64 KB
    # pipe the parent isn't draining, block mid-write, and burn its whole
    # budget looking exactly like a wedge
    out_path, err_path = hb_path + ".out", hb_path + ".err"
    prober = chaos.device_probe_override(cell.name) if chaos else None
    wd = StallWatchdog(stall_s=stall_s, probe_gap_s=probe_gap_s,
                       probe_fails=probe_fails,
                       **({"prober": prober} if prober is not None else {}))
    deadline = time.monotonic() + timeout_s
    try:
        with open(out_path, "w") as out_f, open(err_path, "w") as err_f:
            proc = subprocess.Popen(cmd, stdout=out_f, stderr=err_f,
                                    env=env)
            while True:
                try:
                    proc.wait(timeout=poll_s)
                    break
                except subprocess.TimeoutExpired:
                    pass
                progress = None
                try:
                    with open(hb_path) as f:
                        progress = f.read()
                except OSError:
                    pass
                if time.monotonic() > deadline:
                    _kill(proc)
                    raise TimeoutError(
                        f"cell {cell.name}: exceeded its "
                        f"{timeout_s:.0f}s budget (attempt {attempt})")
                if wd.stalled_and_dead(progress):
                    _kill(proc)
                    raise TimeoutError(
                        f"cell {cell.name}: stall watchdog tripped — no "
                        f"heartbeat progress for >={wd.stall_s:.1f}s and "
                        f"{wd.probe_fails} consecutive device probes "
                        f"failed (attempt {attempt})")
        with open(out_path) as f:
            out = f.read()
        with open(err_path) as f:
            err = f.read()
    finally:
        for path in (hb_path, out_path, err_path):
            try:
                os.unlink(path)
            except OSError:
                pass
    line = (out.strip().splitlines() or ["{}"])[-1]
    try:
        obj = json.loads(line)
    except ValueError:
        obj = {}
    if proc.returncode != 0 or "error" in obj or "ms_per_step" not in obj:
        detail = obj.get("error") or (err.strip()[-400:] or
                                      f"child exited rc={proc.returncode}")
        raise ConnectionError(f"cell {cell.name}: {detail}")
    return obj


def supervise_cell(cell: KernelCell, shape: BenchShape, *, tiny: bool,
                   out_dir: str, hb_dir: str, timeout_s: float,
                   attempts: int, stall_s: float, probe_gap_s: float,
                   probe_fails: int, poll_s: float, retry_delay_s: float,
                   chaos: KernelCellChaos | None,
                   registry: MetricsRegistry, runner=None,
                   sleep=time.sleep) -> dict:
    """Run one cell under retry supervision and return its artifact row:
    ``run`` on success, ``stale`` (last-known value + commit carried)
    when every attempt failed but the cell HAS history, ``skipped`` with
    the error when it has none.  Never raises, never returns 0.0."""
    counters = {"attempts": 0, "retries": 0}

    def on_retry(attempt, exc, delay):
        counters["retries"] += 1
        registry.counter(obs_metrics.KB_RETRIES).add(1)
        log_event("kernelbench.cell_retry", level="warning", cell=cell.name,
                  attempt=attempt + 1, delay_s=round(delay, 3), exc=exc)

    def attempt_fn():
        n = counters["attempts"]
        counters["attempts"] += 1
        fn = runner if runner is not None else _run_cell_subprocess
        return fn(cell, shape, tiny=tiny, attempt=n, timeout_s=timeout_s,
                  stall_s=stall_s, probe_gap_s=probe_gap_s,
                  probe_fails=probe_fails, poll_s=poll_s, chaos=chaos,
                  hb_dir=hb_dir)

    policy = RetryPolicy(max_attempts=max(1, attempts),
                         base_delay=retry_delay_s, max_delay=240.0,
                         multiplier=2.0, jitter=0.25, sleep=sleep)
    try:
        out = policy.call(attempt_fn, on_retry=on_retry)
        row = {"spec": cell.to_dict(), "status": "run", **out}
    except Exception as exc:
        error = f"{type(exc).__name__}: {exc}"
        lk = last_known_cell(cell.name, out_dir, tiny)
        if lk is not None:
            # an unreachable cell is a STALE measurement, not a zero:
            # the explicit marker + carried value/commit keep the
            # leaderboard honest about WHEN each number was real
            row = {"spec": cell.to_dict(), "status": "stale",
                   "error": error, "last_known": lk}
            registry.counter(obs_metrics.KB_STALE).add(1)
            log_event("kernelbench.cell_stale", level="warning",
                      cell=cell.name, error=error,
                      last_known_ms=lk["ms_per_step"],
                      last_known_commit=lk["commit"])
        else:
            row = {"spec": cell.to_dict(), "status": "skipped",
                   "reason": f"no measurement and no last-known value: "
                             f"{error}"}
            registry.counter(obs_metrics.KB_SKIPPED).add(1)
    row["attempts"] = counters["attempts"]
    row["retries"] = counters["retries"]
    if row["status"] == "run":
        registry.counter(obs_metrics.KB_CELLS).add(1)
    return row


# -- artifact history --------------------------------------------------------

def find_leaderboards(out_dir: str) -> list[str]:
    """On-disk leaderboard artifacts, newest first (mtime)."""
    paths = glob.glob(os.path.join(out_dir, "kernelbench-*.json"))
    def _mtime(p):
        try:
            return os.path.getmtime(p)
        except OSError:
            return 0.0
    return sorted(paths, key=_mtime, reverse=True)


def _load_leaderboard(path: str) -> dict | None:
    try:
        with open(path) as f:
            obj = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(obj, dict):
        return None
    # driver-committed records may nest the artifact under "parsed"
    if obj.get("schema") != SCHEMA and isinstance(obj.get("parsed"), dict):
        obj = obj["parsed"]
    return obj if obj.get("schema") == SCHEMA else None


def last_known_cell(name: str, out_dir: str, tiny: bool) -> dict | None:
    """The newest trustworthy measurement of ``name``: a fresh run row
    from a prior artifact, or a prior stale row's carried value (staleness
    chains forward).  Perturbed artifacts are drill debris, never
    evidence; tiny and full histories never cross."""
    for path in find_leaderboards(out_dir):
        obj = _load_leaderboard(path)
        if (obj is None or bool(obj.get("tiny")) != bool(tiny)
                or obj.get("perturb")):
            continue
        row = (obj.get("cells") or {}).get(name)
        if not isinstance(row, dict):
            continue
        if row.get("status") == "run" and row.get("ms_per_step"):
            return {"ms_per_step": row["ms_per_step"],
                    "gbps": row.get("gbps"),
                    "commit": obj.get("commit") or "unknown",
                    "ts": obj.get("ts", ""),
                    "source": os.path.basename(path)}
        if row.get("status") == "stale" and row.get("last_known"):
            return row["last_known"]
    return None


def incumbent_leaderboard(out_dir: str, tiny: bool,
                          explicit: str | None = None
                          ) -> tuple[dict, str] | None:
    """The artifact the regression gate defends: ``explicit`` when
    given, else the newest same-tier artifact with a measured winner.
    Perturbed AND chaos rounds are excluded — a drill whose fastest
    cell was wedged into staleness crowns a slower survivor as winner,
    and defending THAT would let a real regression of the true fastest
    cell through the gate (same rule as decide_defaults/obs_report:
    drill debris is never the bar)."""
    if explicit:
        obj = _load_leaderboard(explicit)
        return (obj, explicit) if obj is not None else None
    for path in find_leaderboards(out_dir):
        obj = _load_leaderboard(path)
        if (obj is None or bool(obj.get("tiny")) != bool(tiny)
                or obj.get("perturb") or obj.get("chaos")):
            continue
        winner = (obj.get("summary") or {}).get("winner")
        if winner and (obj.get("cells", {}).get(winner) or {}).get(
                "ms_per_step"):
            return obj, path
    return None


def regression_gate(incumbent: tuple[dict, str] | None, cells: dict,
                    noise: float) -> dict:
    """Compare HEAD against the incumbent WINNER cell.  Regressed =
    HEAD's fresh measurement of that cell is slower by more than the
    noise band.  A stale/skipped HEAD cell is ``instrument-blind`` (the
    stale marker is already the loud signal — a blind instrument must
    not read as a perf regression, nor as a pass for one)."""
    if incumbent is None:
        return {"status": "no-incumbent"}
    inc_obj, inc_path = incumbent
    winner = (inc_obj.get("summary") or {}).get("winner")
    inc_row = (inc_obj.get("cells") or {}).get(winner) or {}
    inc_ms = inc_row.get("ms_per_step")
    if not winner or not inc_ms:
        return {"status": "no-incumbent"}
    base = {"cell": winner, "incumbent_ms": inc_ms,
            "incumbent_source": os.path.basename(inc_path),
            "incumbent_commit": inc_obj.get("commit") or "unknown",
            "noise_band": noise}
    head = cells.get(winner)
    if head is None:
        return {**base, "status": "cell-gone"}
    if head.get("status") != "run":
        return {**base, "status": "instrument-blind",
                "head_status": head.get("status")}
    head_ms = head["ms_per_step"]
    delta = head_ms / inc_ms - 1.0
    status = "regressed" if delta > noise else "ok"
    return {**base, "status": status, "head_ms": head_ms,
            "delta": round(delta, 4)}


def build_pick(cells: dict, winner: str, source: str) -> dict:
    """The decide_defaults-compatible serving-config pick for the
    winning cell: backend + dot via the autotune keys the dispatcher
    reads, the decode-chunk cadence via ``env``, the kv dtype via
    ``bench_args`` (bench.py's autotune pickup)."""
    spec = cells[winner]["spec"]
    return {
        "REVAL_TPU_PAGED_BACKEND": spec["backend"],
        "REVAL_TPU_KERNEL_DOT": spec.get("dot") or "swap",
        "env": {"REVAL_TPU_DECODE_CHUNK": str(spec["chunk"])},
        "bench_args": ({"kv_dtype": "int8"} if spec["pool"] == "int8"
                       else {}),
        # every cell is timed at the 1.3b direct bench shape; other
        # modes/models keep their own memory-safe defaults
        "scope": {"mode": "direct", "model": "1.3b"},
        "evidence": {"tier": "kernelbench", "source": source,
                     "cell": winner,
                     "ms_per_step": cells[winner]["ms_per_step"]},
    }


def _git_commit() -> str:
    try:
        r = subprocess.run(["git", "-C", _repo_root(), "log", "-1",
                            "--format=%h"], capture_output=True, text=True,
                           timeout=10)
        if r.returncode == 0 and r.stdout.strip():
            return r.stdout.strip()
    except Exception:
        pass
    return "unknown"


# -- the round ---------------------------------------------------------------

def run_round(*, tiny: bool = False, select=None,
              shape: BenchShape | None = None, out_dir: str | None = None,
              chaos: KernelCellChaos | None = None,
              attempts: int | None = None, cell_timeout_s: float | None = None,
              stall_s: float | None = None, probe_gap_s: float | None = None,
              probe_fails: int | None = None, poll_s: float | None = None,
              retry_delay_s: float | None = None, noise: float | None = None,
              incumbent_path: str | None = None,
              registry: MetricsRegistry | None = None, runner=None,
              sleep=time.sleep, progress=None) -> dict:
    """Run the full supervised matrix and return the leaderboard
    artifact.  A degraded cell NEVER aborts the round; ``select``
    narrows which cells EXECUTE without narrowing the report (unselected
    cells record as skipped "not selected", so a filtered run can't pose
    as a full audit — the vanished-cell lint rule stays enforceable)."""
    say = progress or (lambda msg: None)
    shape = shape or (BenchShape.tiny() if tiny else BenchShape())
    out_dir = (out_dir or env_str("REVAL_TPU_KERNELBENCH_DIR")
               or os.path.join(_repo_root(), "tpu_watch"))
    noise = (noise if noise is not None
             else env_float("REVAL_TPU_KERNELBENCH_NOISE", 0.15))
    # tiny supervision budgets keep the tier-1 drill in seconds while
    # the chip defaults survive real compiles and tunnel hiccups
    attempts = attempts if attempts is not None else (2 if tiny else 3)
    cell_timeout_s = cell_timeout_s if cell_timeout_s is not None else (
        60.0 if tiny else 600.0)
    stall_s = stall_s if stall_s is not None else (1.5 if tiny else 420.0)
    probe_gap_s = probe_gap_s if probe_gap_s is not None else (
        0.3 if tiny else 120.0)
    probe_fails = probe_fails if probe_fails is not None else (2 if tiny
                                                               else 3)
    poll_s = poll_s if poll_s is not None else (0.1 if tiny else 1.0)
    retry_delay_s = retry_delay_s if retry_delay_s is not None else (
        0.05 if tiny else 30.0)

    taxonomy = default_cells(tiny)
    names = [c.name for c in taxonomy]
    if chaos is not None:
        # a typo'd cell name would run the whole round clean while still
        # stamping the artifact as a chaos drill — fail loudly instead
        unknown = set(chaos.rules) - set(names)
        if unknown:
            raise ValueError(f"--chaos-cell names unknown cell(s) "
                             f"{sorted(unknown)}; taxonomy: {names}")
    chosen = list(taxonomy)
    skipped_sel: dict[str, KernelCell] = {}
    if select is not None:
        unknown = set(select) - set(names)
        if unknown:
            raise ValueError(f"unknown cell(s) {sorted(unknown)}; "
                             f"taxonomy: {names}")
        chosen = [c for c in taxonomy if c.name in set(select)]
        skipped_sel = {c.name: c for c in taxonomy
                       if c.name not in set(select)}

    reg = registry if registry is not None else MetricsRegistry()
    t0 = time.time()
    hb_dir = tempfile.mkdtemp(prefix="kernelbench-hb-")
    cells: dict[str, dict] = {}
    try:
        for cell in chosen:
            say(f"cell {cell.name}")
            cells[cell.name] = supervise_cell(
                cell, shape, tiny=tiny, out_dir=out_dir, hb_dir=hb_dir,
                timeout_s=cell_timeout_s, attempts=attempts,
                stall_s=stall_s, probe_gap_s=probe_gap_s,
                probe_fails=probe_fails, poll_s=poll_s,
                retry_delay_s=retry_delay_s, chaos=chaos, registry=reg,
                runner=runner, sleep=sleep)
    finally:
        import shutil

        shutil.rmtree(hb_dir, ignore_errors=True)
    for name, cell in skipped_sel.items():
        cells[name] = {"spec": cell.to_dict(), "status": "skipped",
                       "reason": "not selected for this run (--cells)"}
        reg.counter(obs_metrics.KB_SKIPPED).add(1)
    cells = {n: cells[n] for n in names}    # taxonomy order

    fresh = {n: r for n, r in cells.items()
             if r["status"] == "run" and r.get("ms_per_step")}
    winner = (min(fresh, key=lambda n: fresh[n]["ms_per_step"])
              if fresh else None)
    if winner is not None:
        reg.gauge(obs_metrics.KB_BEST_MS).set(fresh[winner]["ms_per_step"])

    gate = regression_gate(
        incumbent_leaderboard(out_dir, tiny, incumbent_path), cells, noise)
    if gate["status"] == "regressed":
        reg.counter(obs_metrics.KB_REGRESSIONS).add(1)
        log_event("kernelbench.regression", level="error",
                  cell=gate["cell"], incumbent_ms=gate["incumbent_ms"],
                  head_ms=gate["head_ms"], delta=gate["delta"],
                  incumbent_commit=gate["incumbent_commit"])

    perturb = {n: r["perturb"] for n, r in cells.items() if r.get("perturb")}
    ts = time.strftime("%Y%m%d-%H%M%S", time.gmtime(t0))
    artifact_name = f"kernelbench-{ts}.json"
    host = next(({"device": r["device"], "platform": r["platform"]}
                 for r in fresh.values()), None)
    artifact = {
        "schema": SCHEMA,
        "created_unix": round(t0, 3),
        "ts": time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(t0)),
        "elapsed_s": round(time.time() - t0, 3),
        "commit": _git_commit(),
        "tiny": bool(tiny),
        "host": host,
        "shape": shape.to_dict(),
        "cells": cells,
        "summary": {
            "cells_run": sum(1 for r in cells.values()
                             if r["status"] == "run"),
            "cells_stale": sum(1 for r in cells.values()
                               if r["status"] == "stale"),
            "cells_skipped": sum(1 for r in cells.values()
                                 if r["status"] == "skipped"),
            "retries": sum(r.get("retries", 0) for r in cells.values()),
            "winner": winner,
            "gate": gate,
        },
        "chaos": chaos.rules if chaos is not None and chaos.rules else None,
        "perturb": perturb or None,
    }
    if winner is not None:
        artifact["pick"] = build_pick(cells, winner, artifact_name)
        log_event("kernelbench.pick", cell=winner,
                  backend=artifact["pick"]["REVAL_TPU_PAGED_BACKEND"],
                  ms_per_step=fresh[winner]["ms_per_step"])
    artifact["metrics"] = reg.snapshot()
    return artifact


def validate_leaderboard(obj: dict, taxonomy: list[KernelCell] | None = None
                         ) -> list[str]:
    """Schema check shared by the ``kernelbench`` lint pass, the CLI's
    pre-write self-check, and the tests.  The invariants the instrument
    lives by: no vanished cells (every taxonomy cell run, stale, or
    skipped WITH a reason), no 0.0 measurements, stale entries carry a
    last-known value + commit."""
    errors: list[str] = []
    if not isinstance(obj, dict):
        return ["leaderboard artifact is not a JSON object"]
    if obj.get("schema") != SCHEMA:
        return [f"schema {obj.get('schema')!r} != expected {SCHEMA!r}"]
    if not isinstance(obj.get("tiny"), bool):
        errors.append("missing 'tiny' flag (tiny and chip histories must "
                      "never cross)")
    cells = obj.get("cells")
    if not isinstance(cells, dict) or not cells:
        return errors + ["no cells in leaderboard"]
    for name, row in sorted(cells.items()):
        status = row.get("status") if isinstance(row, dict) else None
        if status not in ("run", "stale", "skipped"):
            errors.append(f"cell {name}: unknown status {status!r}")
            continue
        if not isinstance(row.get("spec"), dict):
            errors.append(f"cell {name}: missing spec")
        if status == "run":
            if not row.get("ms_per_step") or row["ms_per_step"] <= 0:
                errors.append(f"cell {name}: run cell with no positive "
                              f"ms_per_step (a blind 0.0 is exactly what "
                              f"this schema exists to forbid)")
            if not isinstance(row.get("attempts"), int):
                errors.append(f"cell {name}: run cell missing attempts")
        elif status == "stale":
            lk = row.get("last_known")
            if not isinstance(lk, dict) or not lk.get("ms_per_step"):
                errors.append(f"cell {name}: stale cell without a "
                              f"last-known ms_per_step")
            elif not lk.get("commit"):
                errors.append(f"cell {name}: stale cell's last-known value "
                              f"carries no commit")
            if not row.get("error"):
                errors.append(f"cell {name}: stale cell without the error "
                              f"that degraded it")
            if not isinstance(row.get("retries"), int):
                errors.append(f"cell {name}: stale cell missing its retry "
                              f"count")
        else:
            if not row.get("reason"):
                errors.append(f"cell {name}: skipped without a reason")
    for key in ("summary", "shape"):
        if not isinstance(obj.get(key), dict):
            errors.append(f"missing {key!r} block")
    summary = obj.get("summary") or {}
    winner = summary.get("winner")
    if winner is not None:
        wrow = cells.get(winner)
        if not isinstance(wrow, dict) or wrow.get("status") != "run":
            errors.append(f"summary winner {winner!r} is not a fresh run "
                          f"cell")
        pick = obj.get("pick")
        if not isinstance(pick, dict):
            errors.append("winner present but no serving-config pick")
        elif (isinstance(wrow, dict) and isinstance(wrow.get("spec"), dict)
              and pick.get("REVAL_TPU_PAGED_BACKEND")
              != wrow["spec"].get("backend")):
            errors.append(f"pick backend "
                          f"{pick.get('REVAL_TPU_PAGED_BACKEND')!r} does "
                          f"not match winner cell {winner!r}")
    expected = {c.name for c in (taxonomy if taxonomy is not None
                                 else default_cells(bool(obj.get("tiny"))))}
    for name in sorted(expected - set(cells)):
        errors.append(f"cell {name}: in the declared taxonomy but absent "
                      f"from the leaderboard (cells must be run, stale, or "
                      f"skipped with a reason, never dropped)")
    return errors


def write_leaderboard(artifact: dict, out_dir: str | None = None) -> str:
    """Atomically write ``kernelbench-<ts>.json`` into ``out_dir``
    (default ``REVAL_TPU_KERNELBENCH_DIR``, else ``tpu_watch/``) and
    return the path.  Same-second collisions suffix instead of
    clobbering — a vanished leaderboard reads as a clean round."""
    out_dir = (out_dir or env_str("REVAL_TPU_KERNELBENCH_DIR")
               or os.path.join(_repo_root(), "tpu_watch"))
    os.makedirs(out_dir, exist_ok=True)
    ts = time.strftime("%Y%m%d-%H%M%S", time.gmtime(artifact["created_unix"]))
    path = os.path.join(out_dir, f"kernelbench-{ts}.json")
    n = 1
    while os.path.exists(path):
        path = os.path.join(out_dir, f"kernelbench-{ts}.{n}.json")
        n += 1
    with open(path + ".tmp", "w") as f:
        json.dump(artifact, f, indent=1)
    os.replace(path + ".tmp", path)
    return path


def render_leaderboard(artifact: dict) -> str:
    """The console leaderboard: every cell, freshest-evidence column,
    stale rows explicitly marked with their provenance (a stale value
    must never read as a fresh measurement)."""
    s = artifact["summary"]
    lines = [f"== kernelbench leaderboard @ {artifact['commit']} "
             f"({artifact['ts']}"
             + (", TINY" if artifact.get("tiny") else "") + ") ==", "",
             f"{'cell':<26} {'status':<8} {'ms/step':>10} {'GB/s':>8} "
             f"{'att':>3} {'rty':>3}  evidence"]
    for name, row in artifact["cells"].items():
        mark = " <-- winner" if name == s.get("winner") else ""
        if row["status"] == "run":
            pert = (f" [PERTURBED x{row['perturb']:g}]"
                    if row.get("perturb") else "")
            lines.append(f"{name:<26} {'run':<8} {row['ms_per_step']:>10.3f} "
                         f"{row.get('gbps', 0):>8.1f} "
                         f"{row.get('attempts', 1):>3} "
                         f"{row.get('retries', 0):>3}  fresh{pert}{mark}")
        elif row["status"] == "stale":
            lk = row["last_known"]
            lines.append(f"{name:<26} {'STALE':<8} "
                         f"{lk['ms_per_step']:>10.3f} "
                         f"{(lk.get('gbps') or 0):>8.1f} "
                         f"{row.get('attempts', 0):>3} "
                         f"{row.get('retries', 0):>3}  "
                         f"last known @ {lk['commit']} ({lk['source']}) — "
                         f"{row['error']}")
        else:
            lines.append(f"{name:<26} {'skipped':<8} {'—':>10} {'—':>8} "
                         f"{row.get('attempts', 0):>3} "
                         f"{row.get('retries', 0):>3}  {row['reason']}")
    lines.append("")
    lines.append(f"{s['cells_run']} run · {s['cells_stale']} stale · "
                 f"{s['cells_skipped']} skipped · {s['retries']} retries")
    gate = s["gate"]
    if gate["status"] == "regressed":
        lines.append(f"REGRESSION GATE: cell {gate['cell']} regressed — "
                     f"incumbent {gate['incumbent_ms']:.3f} ms/step "
                     f"(@ {gate['incumbent_commit']}) -> HEAD "
                     f"{gate['head_ms']:.3f} ms/step "
                     f"({gate['delta']:+.1%}, band {gate['noise_band']:.0%})")
    elif gate["status"] == "instrument-blind":
        lines.append(f"gate: instrument blind on incumbent winner "
                     f"{gate['cell']} (HEAD cell is "
                     f"{gate.get('head_status')}) — not a verdict")
    else:
        lines.append(f"gate: {gate['status']}")
    pick = artifact.get("pick")
    if pick:
        lines.append(f"pick: {pick['REVAL_TPU_PAGED_BACKEND']} / "
                     f"{pick['REVAL_TPU_KERNEL_DOT']} / "
                     f"chunk {pick['env']['REVAL_TPU_DECODE_CHUNK']}"
                     + (" / kv int8" if pick['bench_args'].get('kv_dtype')
                        == "int8" else ""))
    return "\n".join(lines)


# -- CLI ---------------------------------------------------------------------

def _note(msg: str) -> None:
    print(f"[kernelbench {time.strftime('%H:%M:%S')}] {msg}",
          file=sys.stderr, flush=True)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="kernelbench",
        description="Self-healing kernel CI: supervised per-cell "
                    "benchmarking + autotune leaderboard.  Exit codes: "
                    "0 round complete (gate ok / no incumbent / "
                    "instrument-blind), 1 regression gate failed, "
                    "2 usage error, 3 nothing measured AND no history "
                    "(instrument dead).")
    ap.add_argument("--tiny", action="store_true",
                    help="toy shape on CPU: certifies the harness paths, "
                         "never a perf number (tiny and chip artifact "
                         "histories never cross)")
    ap.add_argument("--cells", default=None,
                    help="comma-separated cell names to execute; the rest "
                         "are reported skipped ('not selected')")
    ap.add_argument("--chaos-cell", action="append", default=[],
                    metavar="MODE:CELL",
                    help="inject a fault into the named cell: wedge "
                         "(hangs, device probes fail), timeout (runs past "
                         "its budget), flaky-device (first attempt dies, "
                         "retry succeeds); repeatable")
    ap.add_argument("--out-dir", default=None,
                    help="artifact directory (default "
                         "$REVAL_TPU_KERNELBENCH_DIR, else tpu_watch/)")
    ap.add_argument("--incumbent", default=None,
                    help="explicit incumbent artifact for the regression "
                         "gate (default: newest same-tier artifact)")
    ap.add_argument("--noise", type=float, default=None,
                    help="regression noise band (default "
                         "$REVAL_TPU_KERNELBENCH_NOISE, else 0.15)")
    ap.add_argument("--cell-timeout", type=float, default=None,
                    help="hard per-cell budget in seconds (default 600 "
                         "chip / 60 tiny)")
    ap.add_argument("--attempts", type=int, default=None,
                    help="attempts per cell incl. retries (default 3 "
                         "chip / 2 tiny)")
    ap.add_argument("--stall-s", type=float, default=None,
                    help="per-cell stall-watchdog threshold (default 420 "
                         "chip / 1.5 tiny)")
    ap.add_argument("--probe-gap-s", type=float, default=None)
    ap.add_argument("--probe-fails", type=int, default=None)
    ap.add_argument("--reps", type=int, default=None,
                    help="timing reps per cell (default 10 chip / 3 tiny)")
    ap.add_argument("--slots", type=int, default=None)
    ap.add_argument("--ctx", type=int, default=None)
    ap.add_argument("--layers", type=int, default=None)
    # child-mode flags (the supervised per-cell subprocess)
    ap.add_argument("--run-cell", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--heartbeat", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--attempt", type=int, default=0,
                    help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args.run_cell:
        return child_main(args)

    try:
        chaos = KernelCellChaos.parse(args.chaos_cell)
    except ValueError as e:
        ap.error(str(e))
    select = ([s.strip() for s in args.cells.split(",") if s.strip()]
              if args.cells else None)
    shape = BenchShape.tiny() if args.tiny else BenchShape()
    for field in ("slots", "ctx", "layers", "reps"):
        if getattr(args, field) is not None:
            setattr(shape, field, getattr(args, field))

    chip_lock = None
    try:        # serialize with concurrent chip users (runbook vs driver)
        from bench import acquire_chip_lock
        chip_lock = acquire_chip_lock(skip=args.tiny)  # held until exit
    except ImportError:
        pass

    try:
        artifact = run_round(
            tiny=args.tiny, select=select, shape=shape,
            out_dir=args.out_dir, chaos=chaos if chaos.rules else None,
            attempts=args.attempts, cell_timeout_s=args.cell_timeout,
            stall_s=args.stall_s, probe_gap_s=args.probe_gap_s,
            probe_fails=args.probe_fails, noise=args.noise,
            incumbent_path=args.incumbent, progress=_note)
    except ValueError as e:
        print(f"kernelbench: {e}", file=sys.stderr)
        return 2
    errors = validate_leaderboard(artifact)
    if errors:       # the self-check before write, like the determinism CLI
        for err in errors:
            print(f"kernelbench: self-check: {err}", file=sys.stderr)
        return 2
    path = write_leaderboard(artifact, args.out_dir)
    print(render_leaderboard(artifact), file=sys.stderr)
    _note(f"leaderboard written: {path}")

    s = artifact["summary"]
    winner = s["winner"]
    stale_with_value = s["cells_stale"]
    out = {"metric": "kernelbench winner ms/step"
           + (" (TINY-SMOKE-TEST)" if artifact["tiny"] else ""),
           "value": (artifact["cells"][winner]["ms_per_step"]
                     if winner else 0.0),
           "unit": "ms/step", "winner": winner,
           "cells_run": s["cells_run"], "cells_stale": s["cells_stale"],
           "cells_skipped": s["cells_skipped"], "retries": s["retries"],
           "gate": s["gate"]["status"], "commit": artifact["commit"],
           "artifact": os.path.basename(path)}
    if winner is None:
        out["error"] = ("instrument-dead" if stale_with_value == 0
                        else "all-cells-stale")
    print(json.dumps(out))
    if s["gate"]["status"] == "regressed":
        return 1
    if winner is None and stale_with_value == 0:
        return 3
    return 0


if __name__ == "__main__":
    sys.exit(main())
