"""End-to-end task-generation pipelines per dataset family.

Each generator runs the program under the tracer, combines the static
line analysis with the dynamic variable analysis, and emits rows in the
shipped ``DREval_tasks*.jsonl`` / ``DREval_data*.jsonl`` schemas
(reference taskgen.py:290-613; schema documented in SURVEY §2.23).

A probe line must be recommended by **both** analyses: the control-flow
selection (:func:`~reval_tpu.taskgen.blocks.select_probe_lines`) and the
variable selection (:func:`~reval_tpu.taskgen.variables.select_state_probes`)
— reference taskgen.py:334-336,479-481,569-571.  Each selected line carries
the first variable recommended for it, in program order (deterministic,
unlike the reference's set iteration — taskgen.py:547-548).

External dataset loads (HF ``datasets``) and source formatting (``black``)
are optional: the loaders raise a clear error when the package is absent,
and formatting falls back to an AST round-trip.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from pathlib import Path

from ..datasets import MAX_INPUTS, Families
from ..dynamics import CodeSpace, ExecutionTrace, Sandbox
from ..datasets.dreval import ClassEvalHooks, DREvalDataset
from .asserts import parse_assert_statement
from .blocks import select_probe_lines
from .classeval import mask_asserts
from .variables import select_state_probes

__all__ = [
    "TaskGenStats",
    "format_code",
    "probes_for_function",
    "generate_humaneval_classeval",
    "generate_mbpp",
    "generate_mathqa",
    "load_mbpp_rows",
    "load_mathqa_rows",
    "write_jsonl",
]

# MBPP rows whose programs hang, exhaust memory, or need test setup —
# the reference's skip list (taskgen.py:422-424) expressed in DREval ids.
MBPP_SKIP_IDS = frozenset({210, 265, 266, 272, 276, 285, 438, 475, 483, 541, 562})


@dataclass
class TaskGenStats:
    valid: list[tuple[int, int]] = field(default_factory=list)
    empty: list[tuple[int, int]] = field(default_factory=list)
    invalid: list[tuple[int, int]] = field(default_factory=list)

    def summary(self) -> dict:
        return {
            "valid": len(self.valid),
            "empty": len(self.empty),
            "invalid": len(self.invalid),
            "valid_items": len({i for i, _ in self.valid}),
        }


def format_code(code: str, line_length: int = 120) -> str:
    """``black``-format when available, else normalise via AST round-trip."""
    try:
        import black  # type: ignore

        return black.format_str(code, mode=black.Mode(line_length=line_length))
    except ImportError:
        import ast

        return ast.unparse(ast.parse(code)) + "\n"


def probes_for_function(code: str, trace: ExecutionTrace) -> list[dict]:
    """Intersect line and variable analyses into ``{'lineno', 'var'}`` probes."""
    exec_lines = select_probe_lines(code)
    var_probes = select_state_probes(code, trace)
    first_var: dict[int, str] = {}
    for lineno, var in var_probes:
        first_var.setdefault(lineno, var)
    return [
        {"lineno": lineno, "var": first_var[lineno]}
        for lineno in sorted(exec_lines & first_var.keys())
    ]


def _call_repr(entry: str, input_repr: str) -> str:
    """``"(a, b,)"`` input repr → ``entry(a, b)`` call text
    (reference taskgen.py:573 strips the trailing ``,)``)."""
    inner = input_repr.strip()
    if inner.endswith(",)"):
        inner = inner[:-2] + ")"
    return f"{entry}{inner}"


# ---------------------------------------------------------------------------
# HumanEval + ClassEval (regeneration from the shipped data files)
# ---------------------------------------------------------------------------

def generate_humaneval_classeval(
    dataset: DREvalDataset,
    indices: list[int] | None = None,
    *,
    max_inputs: int = MAX_INPUTS,
    sandbox_timeout: float = 120.0,
) -> tuple[list[dict], TaskGenStats]:
    """Rebuild task rows for the HumanEval/ClassEval families from a loaded
    data split (reference ``process_dataset``, taskgen.py:549-608)."""
    stats = TaskGenStats()
    rows: list[dict] = []
    if indices is None:
        indices = sorted(i for i in dataset.by_idx if i <= Families.CLASSEVAL_END)
    for idx in indices:
        item = {"task_id": f"DREval/{idx}", "idx": idx, "tasks": []}
        try:
            if idx <= Families.HUMANEVAL_END:
                _gen_function_item(dataset, idx, item, stats, max_inputs, sandbox_timeout)
            else:
                _gen_class_item(dataset, idx, item, stats, max_inputs, sandbox_timeout)
        except Exception:
            # e.g. programs importing packages absent from this machine;
            # the item is kept with whatever inputs succeeded
            stats.invalid.append((idx, -1))
        rows.append(item)
    return rows, stats


def _gen_function_item(dataset, idx, item, stats, max_inputs, timeout):
    code = dataset.code(idx)
    entry = dataset.entry_point(idx)
    space = CodeSpace()
    space.load_function(entry, code)
    sandbox = Sandbox(space.ns[entry], timeout=timeout)
    for input_idx, input_repr in enumerate(dataset.inputs(idx)):
        if len(item["tasks"]) >= max_inputs:
            break
        args = space.eval_invocation(input_repr)
        _, trace = sandbox.run(*args)
        assert sandbox.status == "ok", f"{sandbox.status} on DREval/{idx} input {input_idx}"
        task = probes_for_function(code, trace)
        if task:
            item["tasks"].append({
                "input_idx": input_idx,
                "task": task,
                "output_pred": f"assert {_call_repr(entry, input_repr)} == ??",
            })
            stats.valid.append((idx, input_idx))
        else:
            stats.empty.append((idx, input_idx))


def _gen_class_item(dataset, idx, item, stats, max_inputs, timeout):
    code = dataset.code(idx)
    cls_name = dataset.entry_point(idx)
    space = CodeSpace()
    space.load_class(cls_name, code)
    test_classes = space.load_test_classes(
        cls_name, code, dataset.test_code(idx),
        ClassEvalHooks.name_pattern, ClassEvalHooks.validation, ClassEvalHooks.postprocess,
    )
    inputs = dataset.inputs(idx)
    assert len(test_classes) == len(inputs), f"test class/input mismatch on DREval/{idx}"
    for input_idx, test_cls in enumerate(test_classes):
        if len(item["tasks"]) >= max_inputs:
            break
        output_pred = mask_asserts(inputs[input_idx])
        if output_pred is None:
            stats.empty.append((idx, input_idx))
            continue
        from ..tasks.base import TaskRunner

        trace, status = TaskRunner.run_class_sandbox(test_cls, timeout)
        assert status == "ok", f"{status} tracing {test_cls.__name__}.dreval_test"
        task = probes_for_function(code, trace)
        if task:
            item["tasks"].append(
                {"input_idx": input_idx, "task": task, "output_pred": output_pred})
            stats.valid.append((idx, input_idx))
        else:
            stats.empty.append((idx, input_idx))


# ---------------------------------------------------------------------------
# MBPP (from raw upstream rows)
# ---------------------------------------------------------------------------

def _repair_and_run(sandbox: Sandbox, space: CodeSpace, input_repr: str):
    """Run with input auto-repair (reference taskgen.py:456-470): a
    ``TypeError`` retries with a 1-tuple'd argument string; an in-program
    exception retries with the whole input wrapped in a list."""
    for attempt in range(3):
        try:
            args = space.eval_invocation(input_repr)
            result, trace = sandbox.run(*args)
        except TypeError:
            # single non-iterable arg: tuple-ify by appending at the END
            # only (the reference rewrites every ')', which corrupts parens
            # inside string literals — taskgen.py:461)
            input_repr = input_repr[:-1] + ",)" if input_repr.endswith(")") else input_repr
            continue
        if "exception" in sandbox.status and attempt == 0:
            input_repr = f"[{input_repr},]"
            continue
        return result, trace, input_repr
    return None, None, input_repr


def generate_mbpp(
    raw_rows: list[dict],
    *,
    start_idx: int = Families.MBPP_START,
    skip_ids: frozenset[int] = MBPP_SKIP_IDS,
    max_inputs: int = MAX_INPUTS,
    sandbox_timeout: float = 120.0,
    fmt: bool = True,
) -> tuple[list[dict], list[dict], TaskGenStats]:
    """Build (tasks_rows, data_rows) from upstream MBPP test-split rows
    (reference ``process_mbpp_dataset``, taskgen.py:413-544)."""
    stats = TaskGenStats()
    tasks_rows: list[dict] = []
    data_rows: list[dict] = []
    for offset, row in enumerate(raw_rows):
        idx = start_idx + offset
        if idx in skip_ids:
            continue
        if row.get("test_setup_code", "").strip():
            continue  # programs needing setup code are out of scope
        code = row["code"].replace("\r\n", "\n")
        if fmt:
            code = format_code(code)
        item = {"task_id": f"DREval/{idx}", "idx": idx, "tasks": []}
        inputs, invocations, outputs, fn_names = [], [], [], []
        for test_idx, assert_stmt in enumerate(row["test_list"]):
            if len(item["tasks"]) >= max_inputs:
                break
            try:
                fn_name, input_repr, _ = parse_assert_statement(assert_stmt)
                invocation = format_code(f"{fn_name}{input_repr}") if fmt else f"{fn_name}{input_repr}"
                space = CodeSpace()
                fn = space.load_function(fn_name, code)
                sandbox = Sandbox(fn, timeout=sandbox_timeout)
                result, trace, input_repr = _repair_and_run(sandbox, space, input_repr)
                assert sandbox.status == "ok", f"{sandbox.status} on DREval/{idx}: {fn_name}{input_repr}"
                # input_idx indexes the *recorded* inputs list so the task
                # engine's inputs[input_idx] lookup always aligns, even when
                # an earlier test case was dropped (the reference keeps the
                # raw test-list index, which can misalign after a skip —
                # taskgen.py:441,473)
                input_idx = len(inputs)
                inputs.append(input_repr)
                fn_names.append(fn_name)
                outputs.append(result)
                invocations.append(invocation)
                task = probes_for_function(code, trace)
                if task:
                    item["tasks"].append({
                        "input_idx": input_idx,
                        "task": task,
                        "output_pred": f"assert {invocation}) == ??",
                    })
                    stats.valid.append((idx, input_idx))
                else:
                    stats.empty.append((idx, input_idx))
            except Exception:
                stats.invalid.append((idx, test_idx))
        if not item["tasks"] or len(set(fn_names)) != 1:
            continue
        data_entry = {
            "task_id": item["task_id"],
            "code": code,
            "entry_point": fn_names[0],
            "inputs": inputs,
            "outputs": outputs,
            "innvocations": invocations,  # (sic) upstream schema, SURVEY §2.23
        }
        try:
            json.dumps(data_entry)
        except (TypeError, ValueError):
            continue  # non-JSON-serialisable outputs
        data_rows.append(data_entry)
        tasks_rows.append(item)
    return tasks_rows, data_rows, stats


# ---------------------------------------------------------------------------
# MathQA (from raw upstream rows)
# ---------------------------------------------------------------------------

def _wrap_mathqa(code: str) -> str:
    """Wrap straight-line MathQA code in ``def main(): …; return answer``
    (reference taskgen.py:283-288)."""
    indented = "\n".join(f"    {line}" for line in code.splitlines())
    return f"def main():\n{indented}\n    return answer\n\nmain()"


def generate_mathqa(
    raw_rows: list[dict],
    *,
    start_idx: int = Families.MATHQA_START,
    sandbox_timeout: float = 120.0,
    fmt: bool = True,
) -> tuple[list[dict], list[dict], TaskGenStats]:
    """Build (tasks_rows, data_rows) from upstream MathQA-Python rows
    (reference ``process_mathqa_dataset``, taskgen.py:290-409).  Each row
    has exactly one input: the nullary ``main()`` invocation."""
    stats = TaskGenStats()
    tasks_rows: list[dict] = []
    data_rows: list[dict] = []
    for row in raw_rows:
        idx = int(row["task_id"]) + start_idx
        code = _wrap_mathqa(row["code"].replace("\r\n", "\n"))
        if fmt:
            code = format_code(code)
        item = {"task_id": f"DREval/{idx}", "idx": idx, "tasks": []}
        try:
            invocation = format_code("main()") if fmt else "main()"
            space = CodeSpace()
            fn = space.load_function("main", code)
            sandbox = Sandbox(fn, timeout=sandbox_timeout)
            result, trace = sandbox.run()
            assert sandbox.status == "ok", f"{sandbox.status} on DREval/{idx}"
            task = probes_for_function(code, trace)
        except Exception:
            stats.invalid.append((idx, 0))
            continue
        if not task:
            stats.empty.append((idx, 0))
            continue
        item["tasks"].append({
            "input_idx": 0,
            "task": task,
            "output_pred": f"assert {invocation}) == ??",
        })
        stats.valid.append((idx, 0))
        data_entry = {
            "task_id": item["task_id"],
            "code": code,
            "entry_point": "main",
            "inputs": [[]],
            "outputs": [result],
            "innvocations": [invocation],
        }
        try:
            json.dumps(data_entry)
        except (TypeError, ValueError):
            continue
        data_rows.append(data_entry)
        tasks_rows.append(item)
    return tasks_rows, data_rows, stats


# ---------------------------------------------------------------------------
# upstream loaders / IO
# ---------------------------------------------------------------------------

def load_mbpp_rows():
    """MBPP test split via HF ``datasets`` (reference taskgen.py:419)."""
    try:
        from datasets import load_dataset  # type: ignore
    except ImportError as e:
        raise RuntimeError(
            "the `datasets` package is required to fetch MBPP; "
            "pass pre-downloaded rows instead") from e
    return list(load_dataset("google-research-datasets/mbpp", "full")["test"])


def load_mathqa_rows():
    """MathQA-Python test split via HF ``datasets`` (reference taskgen.py:296)."""
    try:
        from datasets import load_dataset  # type: ignore
    except ImportError as e:
        raise RuntimeError(
            "the `datasets` package is required to fetch MathQA; "
            "pass pre-downloaded rows instead") from e
    return list(load_dataset("dtruong46me/mathqa-python")["test"])


def write_jsonl(path: str | Path, rows: list[dict]) -> Path:
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    with open(path, "w") as f:
        for row in rows:
            f.write(json.dumps(row) + "\n")
    return path
