"""MBPP test-assert parsing: ``assert f(args) == expected`` → parts.

MBPP ships its test cases as assert statement strings; the generator needs
the callee, the argument tuple text, and the expected-value text
(reference ``parse_assert_statement``, taskgen.py:19,265-278 — a single
regex there; we parse the AST instead so nested parens/strings in the
arguments cannot break the split).
"""

from __future__ import annotations

import ast

__all__ = ["parse_assert_statement"]


def parse_assert_statement(statement: str) -> tuple[str, str, str]:
    """Split one ``assert fn(<args>) == <expected>`` statement.

    Returns ``(fn_name, "(<args>)", "<expected>")``; raises ``ValueError``
    for anything that is not a simple equality assert on a call.
    """
    try:
        tree = ast.parse(statement.strip())
    except SyntaxError as e:
        raise ValueError(f"unparsable assert statement: {statement!r}") from e
    if len(tree.body) != 1 or not isinstance(tree.body[0], ast.Assert):
        raise ValueError(f"not a single assert statement: {statement!r}")
    test = tree.body[0].test
    if not (
        isinstance(test, ast.Compare)
        and len(test.ops) == 1
        and isinstance(test.ops[0], ast.Eq)
        and isinstance(test.left, ast.Call)
        and isinstance(test.left.func, ast.Name)
    ):
        raise ValueError(f"not an `assert fn(...) == expected` form: {statement!r}")
    call = test.left
    args = ", ".join(ast.unparse(a) for a in call.args)
    expected = ast.unparse(test.comparators[0])
    return call.func.id, f"({args})", expected
