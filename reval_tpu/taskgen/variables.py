"""(line, var) probe selection for the state task.

Static + dynamic analysis over one traced execution (reference
``inspect_variable``, taskgen.py:145-240):

- **assignments** contribute their LHS targets, skipping trivially-constant
  RHS values (``a = 0``, ``xs = []`` — reference taskgen.py:77-97) and the
  ``_`` placeholder; augmented assignments always count;
- **returns** contribute returned names (or, for ``return <constant>``, the
  nearest previously-selected variable — reference taskgen.py:194-198);
- **bare expressions** (mutating calls like ``xs.append(1)``) are probed
  dynamically: diff the tracer snapshots before vs after each visit to the
  line — new locals, changed locals, and changed ``self.*`` attributes
  (reference taskgen.py:201-236).

Returns an ordered, de-duplicated list so downstream "first var for a line"
selection is deterministic (the reference iterates a ``set`` and documents
that its output can reshuffle between runs, taskgen.py:547-548).
"""

from __future__ import annotations

import ast

from ..dynamics import ExecutionTrace
from .blocks import is_interesting_stmt, partition_blocks

__all__ = ["select_state_probes"]


def _constant_ish(value: ast.expr | None) -> bool:
    """RHS values too trivial to ask about (reference taskgen.py:77-97)."""
    if isinstance(value, ast.Constant):
        return True
    if isinstance(value, (ast.List, ast.Tuple, ast.Set)):
        return all(isinstance(e, ast.Constant) for e in value.elts)
    if isinstance(value, ast.Dict):
        return len(value.keys) == 0
    return False


def _diff_names(before, after) -> set[str]:
    """Variables that a line's execution created or changed."""
    names: set[str] = set()
    for s1, s2 in zip(before, after):
        l1, l2 = s1.locals, s2.locals
        names |= l2.keys() - l1.keys()
        for name in l1.keys() & l2.keys():
            try:
                if l1[name] != l2[name]:
                    names.add(name)
            except ValueError:
                pass  # ambiguous truthiness (numpy arrays)
        if "self" in l1 and "self" in l2:
            d1 = getattr(l1["self"], "__dict__", {})
            d2 = getattr(l2["self"], "__dict__", {})
            for attr in d1.keys() & d2.keys():
                try:
                    if d1[attr] != d2[attr]:
                        names.add(f"self.{attr}")
                except ValueError:
                    pass
    return names


def _subscript_adhoc(var: str) -> str:
    """Subscripts keyed by a call are unanswerable for the model; probe the
    container instead (reference taskgen.py:134-143 hard-codes the one
    ClassEval instance; we generalise by pattern)."""
    try:
        node = ast.parse(var, mode="eval").body
    except SyntaxError:
        return var
    if isinstance(node, ast.Subscript) and any(
        isinstance(n, ast.Call) for n in ast.walk(node.slice)
    ):
        return ast.unparse(node.value)
    return var


def select_state_probes(code: str, trace: ExecutionTrace) -> list[tuple[int, str]]:
    """Ordered unique ``(1-indexed lineno, var expression)`` probes."""
    probes: list[tuple[int, str]] = []
    seen: set[tuple[int, str]] = set()

    def add(lineno: int, var: str) -> None:
        var = _subscript_adhoc(var)
        if var != "_" and (lineno, var) not in seen:
            seen.add((lineno, var))
            probes.append((lineno, var))

    for block in partition_blocks(code):
        for stmt in block.statements:
            if not is_interesting_stmt(stmt):
                continue
            if isinstance(stmt, ast.Assign):
                if _constant_ish(stmt.value):
                    continue
                for target in stmt.targets:
                    add(stmt.lineno, ast.unparse(target).strip())
            elif isinstance(stmt, ast.AugAssign):
                add(stmt.lineno, ast.unparse(stmt.target).strip())
            elif isinstance(stmt, ast.AnnAssign):
                if _constant_ish(stmt.value):
                    continue
                add(stmt.lineno, ast.unparse(stmt.target).strip())
            elif isinstance(stmt, ast.Return):
                if isinstance(stmt.value, ast.Name):
                    add(stmt.lineno, stmt.value.id)
                elif isinstance(stmt.value, ast.Tuple):
                    for elt in stmt.value.elts:
                        if isinstance(elt, ast.Name):
                            add(stmt.lineno, elt.id)
                elif isinstance(stmt.value, ast.Constant):
                    for lineno, name in reversed(probes):
                        if lineno < stmt.lineno:
                            add(stmt.lineno, name)
                            break
            elif isinstance(stmt, ast.Expr):
                before = trace.states_before(stmt.lineno - 1)
                after = trace.states_after(stmt.lineno - 1)
                for name in sorted(_diff_names(before, after)):
                    if name != "self":
                        add(stmt.lineno, name)
    return probes
