"""Offline task generation: build DREval task/data JSONL from raw benchmarks.

The pipeline replicates the reference generator's semantics
(reference taskgen.py:1-613) with an in-tree control-flow partitioner
(the reference leans on the external ``staticfg`` package plus a monkey
patch, taskgen.py:33-60) and no interactive debugger or per-row prints.

Stages per program:
1. :func:`select_probe_lines` — basic-block analysis picks the lines used by
   the coverage/path tasks (reference ``inspect_execution``, taskgen.py:111-132);
2. ground-truth execution of the program in a :class:`~reval_tpu.dynamics.Sandbox`;
3. :func:`select_state_probes` — static LHS extraction + dynamic trace-diff
   picks ``(line, var)`` probes (reference ``inspect_variable``, taskgen.py:145-240);
4. intersection: only lines that both analyses recommend become tasks
   (reference taskgen.py:334-336,479-481,569-571);
5. an ``output_pred`` assert with the expected value masked to ``??``.
"""

from .blocks import BasicBlock, partition_blocks, select_probe_lines
from .variables import select_state_probes
from .classeval import mask_asserts
from .asserts import parse_assert_statement
from .pipeline import (
    TaskGenStats,
    format_code,
    generate_humaneval_classeval,
    generate_mbpp,
    generate_mathqa,
    load_mbpp_rows,
    load_mathqa_rows,
    probes_for_function,
    write_jsonl,
)

__all__ = [
    "BasicBlock",
    "partition_blocks",
    "select_probe_lines",
    "select_state_probes",
    "mask_asserts",
    "parse_assert_statement",
    "TaskGenStats",
    "format_code",
    "generate_humaneval_classeval",
    "generate_mbpp",
    "generate_mathqa",
    "load_mbpp_rows",
    "load_mathqa_rows",
    "probes_for_function",
    "write_jsonl",
]
