"""ClassEval output-task construction: mask assertions' expected values.

Given a ClassEval per-input test snippet (straight-line unittest assert
calls), replace the expected-value argument of **every** recognised
assertion with the placeholder ``??`` (reference ``inspect_test``,
taskgen.py:242-262 — the shipped data confirms all asserts are masked).
The model is later asked to fill the ``??`` back in, and the completed
statement is executed as the verdict.
"""

from __future__ import annotations

import ast

__all__ = ["mask_asserts", "RECOGNISED_ASSERTS"]

# unittest assert kinds treated as output probes (reference taskgen.py:29-31)
RECOGNISED_ASSERTS = frozenset({
    "assertEqual",
    "assertNotEqual",
    "assertAlmostEqual",
    "assertTrue",
    "assertFalse",
    "assertIsNone",
    "assertIsNotNone",
    "assertIn",
    "assertNotIn",
})


def mask_asserts(test_code: str) -> str | None:
    """Mask the expected value of every recognised assert call with ``??``.

    Returns the transformed source, or ``None`` when the snippet contains
    no recognised assertion (such inputs are skipped by the generator,
    reference taskgen.py:588-590).
    """
    tree = ast.parse(test_code)
    calls: list[ast.Call] = []
    for stmt in tree.body:
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            func = stmt.value.func
            if isinstance(func, ast.Name) and func.id in RECOGNISED_ASSERTS:
                calls.append(stmt.value)
    if not calls:
        return None
    for call in calls:
        # two-arg asserts compare (actual, expected): mask the expected side;
        # one-arg asserts (assertTrue/...) mask their only argument
        idx = 1 if len(call.args) >= 2 else 0
        call.args[idx] = ast.Name(id="??")
    return ast.unparse(tree)
