"""Mini control-flow graph over Python source + probe-line selection.

The reference derives coverage/path probe lines from a CFG built by the
external ``staticfg`` package (reference taskgen.py:62-75, 111-132).  We
build an equivalent graph directly from the ``ast``.  Two properties of
that builder are load-bearing and reproduced here:

- **Block membership.**  Simple statements accumulate into the current
  block; an ``if`` is appended to its predecessor block before branching
  (so the block's last *interesting* statement is the one before the
  test); a loop head sits alone in a guard block; ``return``/``raise``/
  ``break``/``continue`` terminate a block; ``def`` statements are
  appended to the enclosing block and their bodies become separate
  sub-graphs; ``try``/``with``/``class`` bodies flatten into the current
  stream (the reference CFG builder traverses those nodes generically).
- **Iteration order.**  Blocks are yielded in BFS order from the entry,
  with branch/loop *successor* blocks enqueued the moment their parent is
  visited — so an after-loop block is visited before the loop body's inner
  blocks, and unreachable blocks (code after a ``return``) are never
  yielded.  The variable analysis's nearest-previous-variable fallback
  (variables.py) depends on exactly this order.

Selection keeps, per block, the **last** statement of an "interesting"
kind — assignments, returns, non-constant expressions (reference
taskgen.py:22-27, 119-132) — because last-in-block statements make the
next-line task non-trivial (the successor is in another block).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

__all__ = ["BasicBlock", "partition_blocks", "select_probe_lines", "is_interesting_stmt"]

# Statement kinds eligible as probe lines (reference taskgen.py:23-24).
WANTED_STMTS = (ast.Assign, ast.AugAssign, ast.AnnAssign, ast.Return, ast.Expr)
# Bare-expression statements of these kinds are noise, e.g. docstrings
# (reference taskgen.py:27).
EXCLUDED_EXPRS = (ast.Constant,)


def is_interesting_stmt(stmt: ast.stmt) -> bool:
    if not isinstance(stmt, WANTED_STMTS):
        return False
    if isinstance(stmt, ast.Expr) and isinstance(stmt.value, EXCLUDED_EXPRS):
        return False
    return True


@dataclass
class BasicBlock:
    statements: list[ast.stmt] = field(default_factory=list)
    exits: list["BasicBlock"] = field(default_factory=list)

    def last_interesting(self) -> ast.stmt | None:
        for stmt in reversed(self.statements):
            if is_interesting_stmt(stmt):
                return stmt
        return None


class _GraphBuilder:
    """Builds one block graph; function bodies become child builders."""

    def __init__(self):
        self.entry = BasicBlock()
        self.current: BasicBlock = self.entry
        self.children: list[_GraphBuilder] = []
        self._loop_after: list[BasicBlock] = []
        self._loop_guard: list[BasicBlock] = []

    # -- graph bookkeeping -------------------------------------------------
    def _edge(self, src: BasicBlock, dst: BasicBlock) -> None:
        src.exits.append(dst)

    def _start_block(self) -> BasicBlock:
        self.current = BasicBlock()
        return self.current

    # -- traversal ---------------------------------------------------------
    def feed(self, body: list[ast.stmt]) -> None:
        for stmt in body:
            self._feed_stmt(stmt)

    def _feed_stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.current.statements.append(stmt)
            child = _GraphBuilder()
            child.feed(stmt.body)
            self.children.append(child)
        elif isinstance(stmt, ast.ClassDef):
            # class bodies flatten into the enclosing stream (methods still
            # get their own sub-graphs via the branch above)
            self.feed(stmt.body)
        elif isinstance(stmt, ast.If):
            self._feed_if(stmt)
        elif isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            self._feed_loop(stmt)
        elif isinstance(stmt, ast.Try):
            # flattened: body, handler bodies, orelse, finalbody in order
            self.feed(stmt.body)
            for handler in stmt.handlers:
                self.feed(handler.body)
            self.feed(stmt.orelse)
            self.feed(stmt.finalbody)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            self.feed(stmt.body)
        elif isinstance(stmt, (ast.Return, ast.Raise)):
            self.current.statements.append(stmt)
            self._start_block()  # unreachable until linked (dead code stays dead)
        elif isinstance(stmt, ast.Break):
            self.current.statements.append(stmt)
            if self._loop_after:
                self._edge(self.current, self._loop_after[-1])
            self._start_block()
        elif isinstance(stmt, ast.Continue):
            self.current.statements.append(stmt)
            if self._loop_guard:
                self._edge(self.current, self._loop_guard[-1])
            self._start_block()
        else:
            # Assign/AugAssign/AnnAssign/Expr/Assert/Import/Global/Pass/...
            self.current.statements.append(stmt)

    def _feed_if(self, stmt: ast.If) -> None:
        self.current.statements.append(stmt)
        head = self.current
        body_entry = BasicBlock()
        after = BasicBlock()
        self._edge(head, body_entry)           # branch target enqueued first
        if stmt.orelse:
            else_entry = BasicBlock()
            self._edge(head, else_entry)
            self.current = else_entry
            self.feed(stmt.orelse)
            if not self.current.exits:
                self._edge(self.current, after)
        else:
            self._edge(head, after)
        self.current = body_entry
        self.feed(stmt.body)
        if not self.current.exits:
            self._edge(self.current, after)
        self.current = after

    def _feed_loop(self, stmt: ast.While | ast.For | ast.AsyncFor) -> None:
        guard = BasicBlock([stmt])
        self._edge(self.current, guard)
        body_entry = BasicBlock()
        after = BasicBlock()
        self._edge(guard, body_entry)          # body first, then after-loop
        self._edge(guard, after)
        # NOTE: loop `else` bodies are deliberately NOT traversed — the
        # reference's CFG builder ignores them, so their lines never become
        # probes in the shipped datasets; regeneration must match.
        self._loop_guard.append(guard)
        self._loop_after.append(after)
        self.current = body_entry
        self.feed(stmt.body)
        if not self.current.exits:
            self._edge(self.current, guard)    # loop back
        self._loop_guard.pop()
        self._loop_after.pop()
        self.current = after

    # -- ordered iteration -------------------------------------------------
    def ordered_blocks(self) -> list[BasicBlock]:
        """BFS from entry (unreachable blocks pruned), then sub-graphs."""
        out: list[BasicBlock] = []
        seen = {id(self.entry)}
        queue = [self.entry]
        while queue:
            block = queue.pop(0)
            out.append(block)
            for nxt in block.exits:
                if id(nxt) not in seen:
                    seen.add(id(nxt))
                    queue.append(nxt)
        for child in self.children:
            out.extend(child.ordered_blocks())
        return out


def partition_blocks(code: str) -> list[BasicBlock]:
    """Basic blocks of ``code`` in analysis order (empty blocks pruned)."""
    tree = ast.parse(code)
    builder = _GraphBuilder()
    builder.feed(tree.body)
    return [b for b in builder.ordered_blocks() if b.statements]


def select_probe_lines(code: str) -> set[int]:
    """1-indexed lines recommended for the coverage and path tasks: the
    last interesting statement of every basic block."""
    lines: set[int] = set()
    for block in partition_blocks(code):
        stmt = block.last_interesting()
        if stmt is not None:
            lines.add(stmt.lineno)
    return lines
