"""Normalisation ops.

RMSNorm is the llama-family workhorse; computed in float32 regardless of
activation dtype (bf16 accumulation visibly drifts logits over 30+ layers)
and cast back, which XLA fuses into neighbouring ops on TPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["rms_norm"]


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-6,
             offset: float = 0.0) -> jnp.ndarray:
    """``x * w / rms(x)`` with float32 internals.

    ``offset`` supports Gemma's ``(1 + w)`` parameterisation.
    """
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    variance = jnp.mean(xf * xf, axis=-1, keepdims=True)
    normed = xf * jax.lax.rsqrt(variance + eps)
    out = normed * (offset + weight.astype(jnp.float32))
    return out.astype(dtype)
