"""Attention for prefill and decode against a left-padded KV cache.

Layout contract (the whole engine is built around left-padding):
- Sequences are left-padded to the bucket length ``T``; ``pad_len[b]`` is
  the number of pad positions at the front of sequence ``b``.
- The KV cache is ``[B, S_max, H_kv, D]``; prefill writes positions
  ``[0, T)`` (pads included but masked), decode appends at a single shared
  position ``T + step`` for every sequence — left-padding is what makes the
  decode write position uniform, so no scatter is needed.

GQA: query heads are grouped over ``H_kv`` KV heads; scores are computed in
float32 and the softmax is masked before normalisation.

The XLA implementations below compile to fused MXU matmuls and are the
portable path (CPU tests + TPU).  The Pallas ragged/paged decode kernel
(``reval_tpu.ops.pallas_attention``) plugs in behind the same signatures.
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["prefill_attention", "decode_attention", "context_prefill_attention"]

_NEG_INF = -1e30


def _group_queries(q: jnp.ndarray, n_kv_heads: int) -> jnp.ndarray:
    """[B, T, H, D] → [B, T, H_kv, G, D] grouping query heads per KV head."""
    b, t, h, d = q.shape
    return q.reshape(b, t, n_kv_heads, h // n_kv_heads, d)


def prefill_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                      pad_len: jnp.ndarray, scale: float | None = None,
                      window: int | None = None) -> jnp.ndarray:
    """Causal self-attention over one left-padded prefill block.

    q: [B, T, H, D]; k, v: [B, T, H_kv, D]; pad_len: [B] int32.
    ``window``: sliding-window size (Mistral/StarCoder2) — a query attends
    only the most recent ``window`` keys, itself included; None = full
    causal.  Buffer-position distance equals logical distance because both
    ends share the row's pad offset.  Returns [B, T, H, D].
    """
    b, t, h, d = q.shape
    n_kv = k.shape[2]
    scale = scale if scale is not None else d ** -0.5
    qg = _group_queries(q, n_kv).astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    # scores: [B, H_kv, G, T_q, T_k]
    scores = jnp.einsum("bqngd,bknd->bngqk", qg, kf) * scale
    rows = jnp.arange(t)[:, None]       # query positions
    cols = jnp.arange(t)[None, :]       # key positions
    causal = rows >= cols
    if window is not None:
        causal = causal & (rows - cols < window)
    valid_key = cols >= pad_len[:, None, None, None, None]
    mask = causal[None, None, None, :, :] & valid_key
    scores = jnp.where(mask, scores, _NEG_INF)
    probs = jnp.exp(scores - scores.max(axis=-1, keepdims=True))
    probs = probs / probs.sum(axis=-1, keepdims=True)
    out = jnp.einsum("bngqk,bknd->bqngd", probs, vf)
    return out.reshape(b, t, h, d).astype(q.dtype)


def context_prefill_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                              ctx_k: jnp.ndarray, ctx_v: jnp.ndarray,
                              pad_len: jnp.ndarray,
                              scale: float | None = None,
                              window: int | None = None) -> jnp.ndarray:
    """Causal attention for a suffix block that follows a shared context.

    The shared-prefix prefill path: ``ctx_k``/``ctx_v`` ([1, Tc, H_kv, D],
    broadcast over the batch) hold the KV of a prompt prefix common to
    every row; q/k/v ([B, T(_kv), …]) are the left-padded per-row suffixes
    whose sequence positions start at Tc.  Every suffix query attends to
    the whole context plus the causal/unpadded part of its own suffix.
    ``window`` masks keys more than ``window-1`` logical positions behind
    the query (suffix queries sit at logical ``Tc + i - pad``).
    """
    b, t, h, d = q.shape
    n_kv = k.shape[2]
    tc = ctx_k.shape[1]
    scale = scale if scale is not None else d ** -0.5
    qg = _group_queries(q, n_kv).astype(jnp.float32)
    ctx_kf = jnp.broadcast_to(ctx_k, (b, tc, n_kv, d)).astype(jnp.float32)
    ctx_vf = jnp.broadcast_to(ctx_v, (b, tc, n_kv, d)).astype(jnp.float32)
    kf = jnp.concatenate([ctx_kf, k.astype(jnp.float32)], axis=1)
    vf = jnp.concatenate([ctx_vf, v.astype(jnp.float32)], axis=1)
    scores = jnp.einsum("bqngd,bknd->bngqk", qg, kf) * scale
    rows = jnp.arange(t)[:, None]              # suffix query buffer positions
    cols = jnp.arange(t + tc)[None, :]         # key positions: ctx then suffix
    in_ctx = cols < tc
    causal = rows + tc >= cols                 # suffix key j valid if j-tc <= i
    valid_suffix = cols - tc >= pad_len[:, None, None, None, None]
    in_ctx = in_ctx[None, None, None, :, :]
    causal = causal[None, None, None, :, :]
    if window is not None:
        # suffix↔suffix distance is pad-invariant (rows - (cols - tc));
        # ctx keys sit at logical cols, queries at tc + (rows - pad)
        causal = causal & (rows - (cols - tc) < window)[None, None, None, :, :]
        q_logical = tc + rows - pad_len[:, None, None, None, None]
        in_ctx = in_ctx & (q_logical - cols < window)
    mask = in_ctx | (causal & valid_suffix)
    scores = jnp.where(mask, scores, _NEG_INF)
    probs = jnp.exp(scores - scores.max(axis=-1, keepdims=True))
    probs = probs / probs.sum(axis=-1, keepdims=True)
    out = jnp.einsum("bngqk,bknd->bqngd", probs, vf)
    return out.reshape(b, t, h, d).astype(q.dtype)


def decode_attention(q: jnp.ndarray, k_cache: jnp.ndarray, v_cache: jnp.ndarray,
                     pad_len: jnp.ndarray, cur_pos: jnp.ndarray,
                     scale: float | None = None,
                     window: int | None = None) -> jnp.ndarray:
    """One-token attention against the cache.

    q: [B, 1, H, D]; caches: [B, S, H_kv, D]; pad_len: [B]; cur_pos: scalar
    (the position just written, shared across the batch).  Keys are valid in
    ``[pad_len[b], cur_pos]``, windowed to the most recent ``window`` when
    set.  Returns [B, 1, H, D].
    """
    b, _, h, d = q.shape
    s = k_cache.shape[1]
    n_kv = k_cache.shape[2]
    scale = scale if scale is not None else d ** -0.5
    qg = _group_queries(q, n_kv).astype(jnp.float32)          # [B, 1, N, G, D]
    kf = k_cache.astype(jnp.float32)
    vf = v_cache.astype(jnp.float32)
    scores = jnp.einsum("bqngd,bsnd->bngqs", qg, kf) * scale  # [B, N, G, 1, S]
    cols = jnp.arange(s)
    valid = (cols[None, :] >= pad_len[:, None]) & (cols[None, :] <= cur_pos)
    if window is not None:
        valid = valid & (cur_pos - cols[None, :] < window)
    scores = jnp.where(valid[:, None, None, None, :], scores, _NEG_INF)
    probs = jnp.exp(scores - scores.max(axis=-1, keepdims=True))
    probs = probs / probs.sum(axis=-1, keepdims=True)
    out = jnp.einsum("bngqs,bsnd->bqngd", probs, vf)
    return out.reshape(b, 1, h, d).astype(q.dtype)
