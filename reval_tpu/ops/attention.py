"""Attention for prefill and decode against a left-padded KV cache.

Layout contract (the whole engine is built around left-padding):
- Sequences are left-padded to the bucket length ``T``; ``pad_len[b]`` is
  the number of pad positions at the front of sequence ``b``.
- The KV cache is ``[B, S_max, H_kv, D]``; prefill writes positions
  ``[0, T)`` (pads included but masked), decode appends at a single shared
  position ``T + step`` for every sequence — left-padding is what makes the
  decode write position uniform, so no scatter is needed.

GQA: query heads are grouped over ``H_kv`` KV heads; scores are computed in
float32 and the softmax is masked before normalisation.

The XLA implementations below compile to fused MXU matmuls and are the
portable path (CPU tests + TPU).  The Pallas ragged/paged decode kernel
(``reval_tpu.ops.pallas_attention``) plugs in behind the same signatures.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["prefill_attention", "decode_attention", "context_prefill_attention",
           "batched_context_prefill_attention"]

_NEG_INF = -1e30


def _softcap(scores, cap):
    """Gemma-2 logit softcapping: ``cap * tanh(scores / cap)`` on RAW
    (scaled, unmasked) scores; None = no-op."""
    if cap is None:
        return scores
    return cap * jnp.tanh(scores / cap)

#: key-block size for the flash-style blocked path; score blocks beyond
#: this total key length never materialise the full [T_q, T_k] tensor
_KEY_BLOCK = 512


def _group_queries(q: jnp.ndarray, n_kv_heads: int) -> jnp.ndarray:
    """[B, T, H, D] → [B, T, H_kv, G, D] grouping query heads per KV head."""
    b, t, h, d = q.shape
    return q.reshape(b, t, n_kv_heads, h // n_kv_heads, d)


def _blocked_attention(qg, k, v, mask_fn, scale: float,
                       softcap: float | None = None) -> jnp.ndarray:
    """Flash-style exact attention: ``lax.scan`` over key blocks with
    online-softmax accumulators, so the peak score transient is
    [B, N, G, T_q, BLOCK] instead of [..., T_k] — at the 6.7b prefill
    shape that is the difference between ~1 GB and ~¼ GB per layer of
    scratch, which decides whether big-model prefill fits next to the
    page pool (PERF.md).  Numerics are fp32 and EXACT (not an
    approximation); ``mask_fn(cols) → [B, 1, 1, T_q, C]`` supplies
    causal/pad/window validity per key block.
    """
    b, tq, n_kv, g, d = qg.shape
    s = k.shape[1]
    blk = min(_KEY_BLOCK, s)
    n_blocks = (s + blk - 1) // blk
    pad = n_blocks * blk - s
    if pad:
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
    # scan layout: key blocks leading
    kb = jnp.moveaxis(k.reshape(b, n_blocks, blk, n_kv, d), 1, 0)
    vb = jnp.moveaxis(v.reshape(b, n_blocks, blk, n_kv, d), 1, 0)
    starts = jnp.arange(n_blocks, dtype=jnp.int32) * blk

    m0 = jnp.full((b, n_kv, g, tq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, n_kv, g, tq, 1), jnp.float32)
    acc0 = jnp.zeros((b, n_kv, g, tq, d), jnp.float32)

    def body(carry, xs):
        m, l, acc = carry
        kc, vc, start = xs
        cols = start + jnp.arange(blk)
        scores = _softcap(jnp.einsum("bqngd,bknd->bngqk", qg,
                                     kc.astype(jnp.float32)) * scale, softcap)
        valid = mask_fn(cols) & (cols < s)[None, None, None, None, :]
        scores = jnp.where(valid, scores, _NEG_INF)
        m_new = jnp.maximum(m, scores.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(scores - m_new)
        l = alpha * l + p.sum(axis=-1, keepdims=True)
        acc = acc * alpha + jnp.einsum("bngqk,bknd->bngqd", p,
                                       vc.astype(jnp.float32))
        return (m_new, l, acc), None

    (m, l, acc), _ = jax.lax.scan(body, (m0, l0, acc0), (kb, vb, starts))
    out = acc / jnp.maximum(l, 1e-30)
    return jnp.moveaxis(out, -2, 1)          # [B, T_q, N, G, D]


def prefill_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                      pad_len: jnp.ndarray, scale: float | None = None,
                      window=None, softcap: float | None = None) -> jnp.ndarray:
    """Causal self-attention over one left-padded prefill block.

    q: [B, T, H, D]; k, v: [B, T, H_kv, D]; pad_len: [B] int32.
    ``window``: sliding-window size (Mistral/StarCoder2) — a query attends
    only the most recent ``window`` keys, itself included; None = full
    causal.  Buffer-position distance equals logical distance because both
    ends share the row's pad offset.  Returns [B, T, H, D].

    Blocks over keys when T exceeds the key-block size (exact online
    softmax; see ``_blocked_attention``), otherwise one dense fused
    einsum.
    """
    b, t, h, d = q.shape
    n_kv = k.shape[2]
    scale = scale if scale is not None else d ** -0.5
    qg = _group_queries(q, n_kv).astype(jnp.float32)
    rows = jnp.arange(t)[:, None]       # query positions

    def mask_fn(cols):
        """Key-column validity → [B, 1, 1, T, C] (one definition for the
        blocked and dense paths)."""
        causal = rows >= cols[None, :]
        if window is not None:
            causal = causal & (rows - cols[None, :] < window)
        valid_key = cols[None, :] >= pad_len[:, None]
        return (causal[None, None, None, :, :]
                & valid_key[:, None, None, None, :])

    if t > _KEY_BLOCK:
        out = _blocked_attention(qg, k, v, mask_fn, scale, softcap)
        return out.reshape(b, t, h, d).astype(q.dtype)

    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    # scores: [B, H_kv, G, T_q, T_k]
    scores = _softcap(jnp.einsum("bqngd,bknd->bngqk", qg, kf) * scale, softcap)
    scores = jnp.where(mask_fn(jnp.arange(t)), scores, _NEG_INF)
    probs = jnp.exp(scores - scores.max(axis=-1, keepdims=True))
    probs = probs / probs.sum(axis=-1, keepdims=True)
    out = jnp.einsum("bngqk,bknd->bqngd", probs, vf)
    return out.reshape(b, t, h, d).astype(q.dtype)


def context_prefill_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                              ctx_k: jnp.ndarray, ctx_v: jnp.ndarray,
                              pad_len: jnp.ndarray,
                              scale: float | None = None,
                              window=None,
                              softcap: float | None = None) -> jnp.ndarray:
    """Causal attention for a suffix block that follows a shared context.

    The shared-prefix prefill path: ``ctx_k``/``ctx_v`` ([1, Tc, H_kv, D],
    broadcast over the batch) hold the KV of a prompt prefix common to
    every row; q/k/v ([B, T(_kv), …]) are the left-padded per-row suffixes
    whose sequence positions start at Tc.  Every suffix query attends to
    the whole context plus the causal/unpadded part of its own suffix.
    ``window`` masks keys more than ``window-1`` logical positions behind
    the query (suffix queries sit at logical ``Tc + i - pad``).
    """
    b, t, h, d = q.shape
    n_kv = k.shape[2]
    tc = ctx_k.shape[1]
    scale = scale if scale is not None else d ** -0.5
    qg = _group_queries(q, n_kv).astype(jnp.float32)
    rows = jnp.arange(t)[:, None]              # suffix query buffer positions

    def mask_for(cols):
        """Validity of key columns ``cols`` (ctx keys ahead of suffix keys)
        for every query → [B, 1, 1, T, C]."""
        c = cols.shape[0]
        in_ctx = jnp.broadcast_to((cols < tc)[None, :], (t, c))     # [T, C]
        causal = rows + tc >= cols[None, :]                          # [T, C]
        valid_suffix = (cols[None, :] - tc) >= pad_len[:, None]      # [B, C]
        if window is not None:
            # suffix↔suffix distance is pad-invariant (rows - (cols - tc));
            # ctx keys sit at logical cols, queries at tc + (rows - pad)
            causal = causal & (rows - (cols[None, :] - tc) < window)
            q_logical = tc + rows[:, 0][None, :] - pad_len[:, None]  # [B, T]
            in_ctx_b = (in_ctx[None, :, :]
                        & (q_logical[:, :, None] - cols[None, None, :] < window))
        else:
            in_ctx_b = in_ctx[None, :, :]                            # [1|B,T,C]
        mask = in_ctx_b | (causal[None, :, :] & valid_suffix[:, None, :])
        return mask[:, None, None, :, :]

    # concat in the WIDER of the two dtypes: a float32 context next to a
    # bf16 suffix keeps its precision (score math upcasts to f32 anyway)
    cat_t = jnp.result_type(ctx_k.dtype, k.dtype)
    kcat = jnp.concatenate(
        [jnp.broadcast_to(ctx_k, (b, tc, n_kv, d)).astype(cat_t),
         k.astype(cat_t)], axis=1)
    vcat = jnp.concatenate(
        [jnp.broadcast_to(ctx_v, (b, tc, n_kv, d)).astype(cat_t),
         v.astype(cat_t)], axis=1)

    if t + tc > _KEY_BLOCK:
        out = _blocked_attention(qg, kcat, vcat, mask_for, scale, softcap)
        return out.reshape(b, t, h, d).astype(q.dtype)

    kf = kcat.astype(jnp.float32)
    vf = vcat.astype(jnp.float32)
    scores = _softcap(jnp.einsum("bqngd,bknd->bngqk", qg, kf) * scale, softcap)
    mask = mask_for(jnp.arange(t + tc))
    scores = jnp.where(mask, scores, _NEG_INF)
    probs = jnp.exp(scores - scores.max(axis=-1, keepdims=True))
    probs = probs / probs.sum(axis=-1, keepdims=True)
    out = jnp.einsum("bngqk,bknd->bqngd", probs, vf)
    return out.reshape(b, t, h, d).astype(q.dtype)


def batched_context_prefill_attention(q: jnp.ndarray, k: jnp.ndarray,
                                      v: jnp.ndarray,
                                      ctx_k: jnp.ndarray, ctx_v: jnp.ndarray,
                                      ctx_len: jnp.ndarray,
                                      pad_len: jnp.ndarray,
                                      scale: float | None = None,
                                      window=None,
                                      softcap: float | None = None
                                      ) -> jnp.ndarray:
    """Causal attention for suffix blocks that each follow their OWN
    cached context — the multi-prefix generalisation of
    :func:`context_prefill_attention`.

    ``ctx_k``/``ctx_v`` are PER-ROW: ``[B, Tc, H_kv, D]`` where row ``b``'s
    valid context is its first ``ctx_len[b]`` positions (the rest is
    padding from bucketing different prefix lengths together — typically
    gathered trash-page rows, masked here).  Suffix queries sit at logical
    positions ``ctx_len[b] + (i - pad_len[b])``; each attends its whole
    (valid) context plus the causal/unpadded part of its own suffix.
    Identical numerics to ``context_prefill_attention`` when every row
    shares one full-length context — the single-prefix path is the
    ``ctx_len == Tc`` special case.
    """
    b, t, h, d = q.shape
    n_kv = k.shape[2]
    tc = ctx_k.shape[1]
    scale = scale if scale is not None else d ** -0.5
    qg = _group_queries(q, n_kv).astype(jnp.float32)
    rows = jnp.arange(t)[:, None]              # suffix query buffer positions

    def mask_for(cols):
        """Validity of key columns ``cols`` (per-row ctx keys ahead of
        suffix keys) for every query → [B, 1, 1, T, C]."""
        c = cols.shape[0]
        # ctx keys: valid iff inside this row's real context
        in_ctx = (cols[None, :] < ctx_len[:, None]) & (cols < tc)[None, :]
        in_ctx_b = jnp.broadcast_to(in_ctx[:, None, :], (b, t, c))
        sj = cols[None, :] - tc                                  # suffix col
        causal = rows >= (cols - tc)[None, :]                    # [T, C]
        valid_suffix = (sj >= pad_len[:, None]) & (cols >= tc)[None, :]
        if window is not None:
            # suffix↔suffix distance is pad-invariant (rows - sj); ctx
            # keys sit at logical cols, queries at ctx_len + (rows - pad)
            causal = causal & (rows - (cols - tc)[None, :] < window)
            q_logical = (ctx_len[:, None] + rows[:, 0][None, :]
                         - pad_len[:, None])                     # [B, T]
            in_ctx_b = (in_ctx_b
                        & (q_logical[:, :, None] - cols[None, None, :]
                           < window))
        mask = in_ctx_b | (causal[None, :, :] & valid_suffix[:, None, :])
        return mask[:, None, None, :, :]

    cat_t = jnp.result_type(ctx_k.dtype, k.dtype)
    kcat = jnp.concatenate([ctx_k.astype(cat_t), k.astype(cat_t)], axis=1)
    vcat = jnp.concatenate([ctx_v.astype(cat_t), v.astype(cat_t)], axis=1)

    if t + tc > _KEY_BLOCK:
        out = _blocked_attention(qg, kcat, vcat, mask_for, scale, softcap)
        return out.reshape(b, t, h, d).astype(q.dtype)

    kf = kcat.astype(jnp.float32)
    vf = vcat.astype(jnp.float32)
    scores = _softcap(jnp.einsum("bqngd,bknd->bngqk", qg, kf) * scale, softcap)
    mask = mask_for(jnp.arange(t + tc))
    scores = jnp.where(mask, scores, _NEG_INF)
    probs = jnp.exp(scores - scores.max(axis=-1, keepdims=True))
    probs = probs / probs.sum(axis=-1, keepdims=True)
    out = jnp.einsum("bngqk,bknd->bqngd", probs, vf)
    return out.reshape(b, t, h, d).astype(q.dtype)


def decode_attention(q: jnp.ndarray, k_cache: jnp.ndarray, v_cache: jnp.ndarray,
                     pad_len: jnp.ndarray, cur_pos: jnp.ndarray,
                     scale: float | None = None,
                     window=None, softcap: float | None = None) -> jnp.ndarray:
    """One-token attention against the cache.

    q: [B, 1, H, D]; caches: [B, S, H_kv, D]; pad_len: [B]; cur_pos: scalar
    (the position just written, shared across the batch).  Keys are valid in
    ``[pad_len[b], cur_pos]``, windowed to the most recent ``window`` when
    set.  Returns [B, 1, H, D].
    """
    b, _, h, d = q.shape
    s = k_cache.shape[1]
    n_kv = k_cache.shape[2]
    scale = scale if scale is not None else d ** -0.5
    qg = _group_queries(q, n_kv).astype(jnp.float32)          # [B, 1, N, G, D]
    kf = k_cache.astype(jnp.float32)
    vf = v_cache.astype(jnp.float32)
    scores = _softcap(jnp.einsum("bqngd,bsnd->bngqs", qg, kf) * scale,
                      softcap)                                # [B, N, G, 1, S]
    cols = jnp.arange(s)
    valid = (cols[None, :] >= pad_len[:, None]) & (cols[None, :] <= cur_pos)
    if window is not None:
        valid = valid & (cur_pos - cols[None, :] < window)
    scores = jnp.where(valid[:, None, None, None, :], scores, _NEG_INF)
    probs = jnp.exp(scores - scores.max(axis=-1, keepdims=True))
    probs = probs / probs.sum(axis=-1, keepdims=True)
    out = jnp.einsum("bngqs,bsnd->bqngd", probs, vf)
    return out.reshape(b, 1, h, d).astype(q.dtype)
