"""TPU compute ops: XLA-fused implementations + Pallas kernels.

Every op has an XLA (pure jax.numpy/lax) implementation that runs anywhere
(CPU tests, TPU fallback); hot ops additionally ship a Pallas TPU kernel
selected at runtime (see ``attention.py``)."""

from .norms import rms_norm
from .rope import apply_rope, rope_angles
from .attention import prefill_attention, decode_attention

__all__ = [
    "apply_rope",
    "decode_attention",
    "prefill_attention",
    "rms_norm",
    "rope_angles",
]
