"""Pallas TPU kernel: ragged paged-attention for the decode step.

TPU-native replacement for the paged-attention CUDA kernels vLLM supplies
to the reference (reference inference.py:90-95 constructs ``vllm.LLM``;
its CUDA kernels are the vendored-native dependency catalogued in
SURVEY.md §2.9).  The KV cache lives in HBM as fixed-size pages and a
block table maps each sequence to its pages, so sequences of wildly
different lengths share one cache pool with no per-sequence reallocation —
the layout continuous batching needs.

Layout (measured on v5e, see PERF.md and models/paged.py):
- ``k_pages``/``v_pages``: ``[N_pages * P, H_kv, D]`` — token-major and
  flat, the same arrays the decode scatter writes in place.  A page is
  ``P`` consecutive rows, so the kernel views the array as
  ``[N_pages, P, H_kv, D]`` (a free reshape) and one page for *all* kv
  heads is a contiguous block.
- ``block_tables``: ``[B, max_pages]`` int32 page ids (0-padded past the
  end; padding is masked, never read as data).
- ``seq_lens``: ``[B]`` int32 — tokens currently valid per sequence.
- optional ``k_scales``/``v_scales``: ``[N_pages * P, H_kv]`` f32 —
  per-(token, head) symmetric int8 scales when the pool stores int8
  (models/paged.py ``kv_dtype="int8"``): dequantised value =
  ``page_int8 * scale``.  Halves pool bytes and attention DMA.

Kernel shape: grid ``(B, max_pages)`` with the page dimension innermost
and *arbitrary* (sequential), so flash-style online-softmax accumulators
in VMEM scratch carry across pages.  Each grid step processes one page
for EVERY head at once — the per-(head, page) grid of a head-split layout
costs ~H_kv× more grid steps, and TPU grids are sequential per core, so
grid-step overhead is what buries fine-grained kernels.  The block table
and sequence lengths ride in scalar-prefetch SMEM: Pallas reads
``block_tables[b, p]`` inside the BlockSpec index_map to schedule the
HBM→VMEM DMA of the right page ahead of compute — the pipelining the CUDA
kernel does by hand falls out of the grid spec.  Dead pages (beyond the
sequence's length, or wholly outside its sliding window) redirect their
index_map to page 0: consecutive equal block indices skip the re-DMA, so
table padding costs almost nothing.

Everything compiles with ``interpret=True`` on CPU, which is how the unit
tests validate the kernel bit-for-bit against the XLA reference below.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax 0.4.x spells it TPUCompilerParams; newer jax renamed it
_CompilerParams = getattr(pltpu, "CompilerParams", None) or \
    pltpu.TPUCompilerParams

from .attention import _softcap

__all__ = [
    "paged_decode_attention",
    "paged_decode_attention_xla",
    "paged_decode_attention_pallas",
    "paged_decode_attention_pallas_seq",
    "ragged_paged_attention",
    "ragged_paged_attention_xla",
    "ragged_paged_attention_pallas",
    "resolved_paged_backend",
]

_NEG_INF = -1e30

_AUTOTUNE_CACHE: dict = {}


def _autotune_defaults() -> dict:
    """Measured-best kernel config persisted by tools/decide_defaults.py
    (``{repo}/tpu_watch/autotune.json``; override with
    ``REVAL_TPU_AUTOTUNE_FILE``).  Missing/invalid file → {}.  Cached per
    path so the dispatch hot path stats the file once."""
    import json
    import os

    from ..env import env_str

    path = env_str("REVAL_TPU_AUTOTUNE_FILE") or os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "tpu_watch", "autotune.json")
    if path not in _AUTOTUNE_CACHE:
        try:
            with open(path) as f:
                obj = json.load(f)
            _AUTOTUNE_CACHE[path] = {
                k: obj[k] for k in ("REVAL_TPU_PAGED_BACKEND",
                                    "REVAL_TPU_KERNEL_DOT")
                if isinstance(obj.get(k), str)}
        except (OSError, ValueError):
            _AUTOTUNE_CACHE[path] = {}
    return _AUTOTUNE_CACHE[path]


def _scale_rows(s_ph, g: int):
    """[P, H_kv] per-(token, head) scales → a [H, P] multiplier aligned
    with the [H, P] score/prob layout (kv-head scales repeat over the
    g query heads of their group)."""
    return _scale_rows_t(s_ph.T, g)


def _scale_rows_t(s_hp, g: int):
    """Transposed variant: [H_kv, P] scale page → [H, P] multiplier.
    The seq kernel stores scale pages head-major so their HBM→VMEM DMA
    slices end on the lane-aligned P dim (a [.., P, H_kv] layout has a
    sub-lane minor dim that Mosaic's memref slicing rejects: "Slice
    shape along dimension 2 must be aligned to tiling (128)")."""
    t = s_hp[:, None, :]                                   # [H_kv, 1, P]
    return jnp.broadcast_to(
        t, (t.shape[0], g, t.shape[2])).reshape(-1, t.shape[2])


def _group_onehot(h_kv: int, g: int):
    """[H, H_kv, 1] f32 mask: 1 where kv-head j serves query head i
    (j == i // g).  Compile-time constant-foldable iota comparison."""
    hh = jax.lax.broadcasted_iota(jnp.int32, (h_kv * g, h_kv, 1), 0)
    kk = jax.lax.broadcasted_iota(jnp.int32, (h_kv * g, h_kv, 1), 1)
    return (kk == hh // g).astype(jnp.float32)


def _widen_q(q, h_kv: int, g: int):
    """[H, D] → [H, H_kv*D]: each head's query placed at its kv-group's
    block, zeros elsewhere.  Loop-invariant — the seq kernel hoists it
    out of the per-page fori_loop (Mosaic does not reliably hoist from a
    lowered loop body, and per-page op issue is the measured bottleneck)."""
    d = q.shape[-1]
    return (q[:, None, :] * _group_onehot(h_kv, g)).reshape(h_kv * g,
                                                            h_kv * d)


def _page_scores(q, k, scale, softcap, valid, h_kv: int, g: int,
                 ks_hp=None, wide: bool = False):
    """Masked attention scores for one page, ALL heads in one dot.

    q: [H, D] f32 — or, when ``wide``, the PRE-WIDENED [H, H_kv*D] from
    :func:`_widen_q`; k: [P, H_kv, D] f32 (int8 pools: CAST but not
    scaled); valid: [1, P] bool; ks_hp: None or [H, P] per-token
    k-scales from :func:`_scale_rows`.  Returns s: [H, P] f32.

    One dot over the whole page replaces the per-head matvec loop: at
    decode shapes the per-head ops are ~sub-µs each and their fixed issue
    overhead — not bandwidth — dominated the measured step time (23.6 ms
    vs a 8 ms roofline, tpu_watch r4 ablation), so the kernel's job is to
    touch the page with as FEW ops as possible.  The int8 dequant scales
    don't vary along the contracted dim, so they factor out of the dot
    EXACTLY — a [H, P] multiply on the scores replaces a [P, H_kv, D]
    multiply on the keys (128× fewer elements).

    Two dot formulations (``wide`` picks; on-chip A/B decides defaults):
    - batched (default): one kv-head-batched ``dot_general``.  Mosaic
      only lowers batched matmuls whose batch dims are BOTH dim 0
      ("batch dims must be equal" otherwise, and index-1 batches are
      rejected too — probed on a real v5e), so the [P, H_kv, D] page is
      swapped to [H_kv, P, D] in VMEM first — real data movement,
      ~page-sized, on the critical path.
    - wide: fold the head-group one-hot into a widened q
      ([H, H_kv*D], zeros outside each head's kv block), so ONE plain 2D
      matmul against the page's [P, H_kv*D] view yields [H, P] directly
      (h_kv× the MXU FLOPs — decode is bandwidth-bound, the MXU is idle
      anyway).  No transpose, and every reshape keeps the 128-lane minor
      dim aligned (a lane-splitting reshape like [H, P*H_kv] →
      [H, P, H_kv] is an "unsupported shape cast" in Mosaic).
    """
    h = h_kv * g
    if wide:
        p, d = k.shape[0], k.shape[-1]
        s = jax.lax.dot_general(                           # [H, P]
            q, k.reshape(p, h_kv * d), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
    else:
        q3 = q.reshape(h_kv, g, q.shape[-1])               # [H_kv, G, D]
        s = jax.lax.dot_general(                           # [H_kv, G, P]
            q3, jnp.swapaxes(k, 0, 1), (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale
        s = s.reshape(h, -1)                               # [H, P]
    if ks_hp is not None:
        s = s * ks_hp
    s = _softcap(s, softcap)                 # gemma-2 score softcapping
    return jnp.where(valid, s, _NEG_INF)


def _page_values(probs, v, h_kv: int, g: int, wide: bool = False):
    """probs: [H, P] f32, v: [P, H_kv, D] f32 → weighted values [H, D].
    Same two formulations as :func:`_page_scores`."""
    if wide:
        h, p = probs.shape
        d = v.shape[-1]
        ow = jax.lax.dot_general(                          # [H, H_kv*D]
            probs, v.reshape(p, h_kv * d), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return (ow.reshape(h, h_kv, d)                     # aligned split
                * _group_onehot(h_kv, g)).sum(1)           # [H, D]
    p3 = probs.reshape(h_kv, g, probs.shape[-1])           # [H_kv, G, P]
    out = jax.lax.dot_general(                             # [H_kv, G, D]
        p3, jnp.swapaxes(v, 0, 1), (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )
    return out.reshape(h_kv * g, v.shape[-1])              # [H, D]


def _flash_update(s, v, m_ref, l_ref, acc_ref, h_kv: int, g: int,
                  vs_hp=None, wide: bool = False):
    """Fold one page's scores/values into the online-softmax scratch.

    s: [H, P] masked scores; v: [P, H_kv, D] values (int8 pools: CAST but
    not scaled — ``vs_hp`` [H, P] folds the per-token scales into the
    probs instead, exact because scales don't vary along the summed dim).
    m_ref/l_ref are lane-replicated [H, 128]; acc_ref is [H, D]."""
    m_prev = m_ref[:, :1]                         # [H, 1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m_new)               # rescale old sums
    probs = jnp.exp(s - m_new)                    # [H, P]
    l_new = alpha * l_ref[:, :1] + probs.sum(axis=-1, keepdims=True)
    pv = probs if vs_hp is None else probs * vs_hp
    acc_ref[:] = acc_ref[:] * alpha + _page_values(pv, v, h_kv, g, wide)
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)


def _decode_kernel(block_tables_ref, seq_lens_ref, q_ref, k_ref, v_ref,
                   *rest, page_size: int, scale: float, max_pages: int,
                   window: int | None, softcap: float | None,
                   h_kv: int, g: int, quantized: bool, wide: bool):
    if quantized:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, m_ref, l_ref, acc_ref = rest
        ks_ref = vs_ref = None
    b = pl.program_id(0)
    p = pl.program_id(1)

    @pl.when(p == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    seq_len = seq_lens_ref[b]

    # sliding window: the query (logical position seq_len-1) sees keys in
    # [seq_len - window, seq_len); pages wholly before that are skipped —
    # compute for old pages costs nothing extra, and the window page set
    # is what bounds effective attention length for Mistral/StarCoder2
    live = p * page_size < seq_len
    if window is not None:
        live = live & ((p + 1) * page_size > seq_len - window)

    @pl.when(live)
    def _compute():
        cols = jax.lax.broadcasted_iota(jnp.int32, (1, page_size), 1)
        pos = p * page_size + cols                    # [1, P]
        valid = pos < seq_len
        if window is not None:
            valid = valid & (pos >= seq_len - window)
        q = q_ref[0].astype(jnp.float32)                       # [H, D]
        if wide:
            q = _widen_q(q, h_kv, g)                           # [H, H_kv*D]
        k = k_ref[0].astype(jnp.float32)                       # [P, H_kv, D]
        v = v_ref[0].astype(jnp.float32)
        ks_hp = vs_hp = None
        if ks_ref is not None:
            ks_hp = _scale_rows(ks_ref[0], g)
            vs_hp = _scale_rows(vs_ref[0], g)
        s = _page_scores(q, k, scale, softcap, valid, h_kv, g, ks_hp, wide)
        _flash_update(s, v, m_ref, l_ref, acc_ref, h_kv, g, vs_hp, wide)

    @pl.when(p == max_pages - 1)
    def _finalize():
        o_ref[0] = (acc_ref[:] / l_ref[:, :1]).astype(o_ref.dtype)


# jit-entry: ops.paged_attn_pallas static=(page_size, scale, interpret, window, softcap, dot_mode) bucketed=(batch, pages)
@functools.partial(
    jax.jit, static_argnames=("page_size", "scale", "interpret", "window",
                              "softcap", "dot_mode"))
def paged_decode_attention_pallas(q, k_pages, v_pages, block_tables, seq_lens,
                                  *, page_size: int, scale: float | None = None,
                                  interpret: bool = False,
                                  window: int | None = None,
                                  softcap: float | None = None,
                                  k_scales=None, v_scales=None,
                                  dot_mode: str = "swap"):
    """One-token attention against a paged KV cache (Pallas TPU kernel).

    q: [B, H, D]; k_pages/v_pages: [N_pages * P, H_kv, D] (token-major
    flat); block_tables: [B, max_pages] int32; seq_lens: [B] int32 (≥1).
    ``window``: sliding-window size (static; per-model constant) — only
    the most recent ``window`` keys participate.  ``k_scales``/
    ``v_scales``: per-(token, head) f32 scales for int8 pools.
    Returns [B, H, D].
    """
    if dot_mode not in ("swap", "wide"):
        # a typo would silently bench swap under the wide label
        raise ValueError(f"unknown dot_mode {dot_mode!r}; expected swap | wide")
    b, h, d = q.shape
    h_kv = k_pages.shape[1]
    g = h // h_kv
    max_pages = block_tables.shape[1]
    quantized = k_scales is not None
    scale = float(scale if scale is not None else d ** -0.5)
    kp = k_pages.reshape(-1, page_size, h_kv, d)   # [N, P, H_kv, D] view
    vp = v_pages.reshape(-1, page_size, h_kv, d)

    def page_index(b_, p_, bt, sl):
        # dead pages (masked anyway) redirect to page 0: consecutive
        # identical indices skip the HBM→VMEM re-DMA
        alive = p_ * page_size < sl[b_]
        if window is not None:
            alive = alive & ((p_ + 1) * page_size > sl[b_] - window)
        return jnp.where(alive, bt[b_, p_], 0)

    in_specs = [
        pl.BlockSpec((1, h, d), lambda b_, p_, bt, sl: (b_, 0, 0)),
        pl.BlockSpec((1, page_size, h_kv, d),
                     lambda b_, p_, bt, sl: (page_index(b_, p_, bt, sl), 0, 0, 0)),
        pl.BlockSpec((1, page_size, h_kv, d),
                     lambda b_, p_, bt, sl: (page_index(b_, p_, bt, sl), 0, 0, 0)),
    ]
    operands = [q, kp, vp]
    if quantized:
        ksp = k_scales.reshape(-1, page_size, h_kv)
        vsp = v_scales.reshape(-1, page_size, h_kv)
        spec_s = pl.BlockSpec(
            (1, page_size, h_kv),
            lambda b_, p_, bt, sl: (page_index(b_, p_, bt, sl), 0, 0))
        in_specs += [spec_s, spec_s]
        operands += [ksp, vsp]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, max_pages),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, h, d), lambda b_, p_, bt, sl: (b_, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, 128), jnp.float32),   # running max (lane-replicated)
            pltpu.VMEM((h, 128), jnp.float32),   # running denominator
            pltpu.VMEM((h, d), jnp.float32),     # output accumulator
        ],
    )
    kernel = functools.partial(_decode_kernel, page_size=page_size,
                               scale=scale, max_pages=max_pages,
                               window=window, softcap=softcap, h_kv=h_kv,
                               g=g, quantized=quantized,
                               wide=dot_mode == "wide")
    # tile: (8, 128) — f32 native VMEM tiling; head_dim rides the lane
    # dim (the 128-wide scratch rows), page rows ride the sublane dim
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(block_tables, seq_lens, *operands)


def _decode_kernel_seq(block_tables_ref, seq_lens_ref, q_ref, k_hbm, v_hbm,
                       *rest, page_size: int, scale: float,
                       window: int | None, softcap: float | None,
                       h_kv: int, g: int, quantized: bool, wide: bool):
    """One grid step = one WHOLE sequence: a double-buffered in-kernel
    page loop replaces the per-(sequence, page) grid of
    ``_decode_kernel``.

    Why: at decode shapes a page's compute is ~1 µs — the same order as
    TPU grid-step overhead, and most of the [B, max_pages] grid's steps
    are DEAD (table span vs ~5 live pages at the bench shape).  Here the
    grid is just [B]; the kernel walks the sequence's live pages with
    ``make_async_copy`` HBM→VMEM fetches two pages deep, so page p+1
    streams in while page p computes — the hand-rolled version of the
    pipelining BlockSpec index_maps gave the old kernel, minus the
    dead-step overhead.

    The flash accumulators (m, l, acc) live in VMEM *scratch refs*
    mutated by the loop body — loop-carried arrays updated with
    ``.at[].set`` lower to ``scatter``, which Mosaic has no TPU lowering
    for (found the hard way: the r3 version of this kernel only ever ran
    in CPU interpret mode and died on the first real-chip compile)."""
    if quantized:
        (ks_hbm, vs_hbm, o_ref, k_buf, v_buf, ks_buf, vs_buf, sem,
         m_ref, l_ref, acc_ref) = rest
    else:
        o_ref, k_buf, v_buf, sem, m_ref, l_ref, acc_ref = rest
        ks_hbm = vs_hbm = ks_buf = vs_buf = None
    b = pl.program_id(0)
    seq_len = seq_lens_ref[b]
    n_live = (seq_len + page_size - 1) // page_size

    def first_page(b_):
        if window is not None:
            return jnp.maximum((seq_lens_ref[b_] - window) // page_size, 0)
        return jnp.int32(0)

    p0 = first_page(b)

    def dmas(slot, p, b_):
        page = block_tables_ref[b_, p]
        out = [
            pltpu.make_async_copy(k_hbm.at[page], k_buf.at[slot],
                                  sem.at[slot, 0]),
            pltpu.make_async_copy(v_hbm.at[page], v_buf.at[slot],
                                  sem.at[slot, 1]),
        ]
        if quantized:
            out += [
                pltpu.make_async_copy(ks_hbm.at[page], ks_buf.at[slot],
                                      sem.at[slot, 2]),
                pltpu.make_async_copy(vs_hbm.at[page], vs_buf.at[slot],
                                      sem.at[slot, 3]),
            ]
        return out

    # Cross-sequence pipelining: sequence b's first-page DMA was started
    # by the EPILOGUE of grid step b-1 (the DMA queue never drains at a
    # grid-step boundary); only the first grid step starts its own.
    # Start/wait stay balanced: every step waits exactly the pages
    # [p0, n_live) and starts [p0+1, n_live) plus its successor's p0.
    @pl.when(b == 0)
    def _first_seq():
        for d in dmas(p0 % 2, p0, b):
            d.start()

    m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
    l_ref[:] = jnp.zeros_like(l_ref)
    acc_ref[:] = jnp.zeros_like(acc_ref)

    q_seq = q_ref[0].astype(jnp.float32)                       # [H, D]
    if wide:
        q_seq = _widen_q(q_seq, h_kv, g)       # loop-invariant: hoisted

    def body(p, carry):
        slot = p % 2

        @pl.when(p + 1 < n_live)
        def _prefetch():
            for d in dmas((p + 1) % 2, p + 1, b):
                d.start()

        for d in dmas(slot, p, b):
            d.wait()

        cols = jax.lax.broadcasted_iota(jnp.int32, (1, page_size), 1)
        pos = p * page_size + cols                     # [1, P]
        valid = pos < seq_len
        if window is not None:
            valid = valid & (pos >= seq_len - window)

        k = k_buf[slot].astype(jnp.float32)                    # [P, H_kv, D]
        v = v_buf[slot].astype(jnp.float32)
        ks_hp = vs_hp = None
        if quantized:
            ks_hp = _scale_rows_t(ks_buf[slot], g)             # [H_kv, P]
            vs_hp = _scale_rows_t(vs_buf[slot], g)
        s = _page_scores(q_seq, k, scale, softcap, valid, h_kv, g, ks_hp,
                         wide)
        _flash_update(s, v, m_ref, l_ref, acc_ref, h_kv, g, vs_hp, wide)
        return carry

    jax.lax.fori_loop(p0, n_live, body, 0)

    @pl.when(b + 1 < pl.num_programs(0))
    def _next_seq():
        np0 = first_page(b + 1)
        for d in dmas(np0 % 2, np0, b + 1):
            d.start()

    o_ref[0] = (acc_ref[:] / l_ref[:, :1]).astype(o_ref.dtype)


# jit-entry: ops.paged_attn_pallas_seq static=(page_size, scale, interpret, window, softcap, dot_mode) bucketed=(batch, pages)
@functools.partial(
    jax.jit, static_argnames=("page_size", "scale", "interpret", "window",
                              "softcap", "dot_mode"))
def paged_decode_attention_pallas_seq(q, k_pages, v_pages, block_tables,
                                      seq_lens, *, page_size: int,
                                      scale: float | None = None,
                                      interpret: bool = False,
                                      window: int | None = None,
                                      softcap: float | None = None,
                                      k_scales=None, v_scales=None,
                                      dot_mode: str = "swap"):
    """Per-sequence paged decode attention (see ``_decode_kernel_seq``).

    Same contract as :func:`paged_decode_attention_pallas`; the pools stay
    in HBM (``memory_space=ANY``) and the kernel streams live pages only.
    """
    if dot_mode not in ("swap", "wide"):
        # a typo would silently bench swap under the wide label
        raise ValueError(f"unknown dot_mode {dot_mode!r}; expected swap | wide")
    b, h, d = q.shape
    h_kv = k_pages.shape[1]
    g = h // h_kv
    quantized = k_scales is not None
    scale = float(scale if scale is not None else d ** -0.5)
    # the kernel's DMA start/wait chain assumes every sequence owns at
    # least one live page (a zero-len row would orphan the predecessor's
    # prefetched first-page copy — silent corruption, not a crash).  The
    # engine always passes lens >= 1 (idle slots point at the trash
    # page); enforce the contract here so any other caller is safe too —
    # a clamped row attends over one trash-page token and its output is
    # never read.
    seq_lens = jnp.maximum(seq_lens, 1)
    kp = k_pages.reshape(-1, page_size, h_kv, d)   # [N, P, H_kv, D] view
    vp = v_pages.reshape(-1, page_size, h_kv, d)

    any_spec = pl.BlockSpec(memory_space=pl.ANY)
    in_specs = [
        pl.BlockSpec((1, h, d), lambda b_, bt, sl: (b_, 0, 0)),
        any_spec, any_spec,
    ]
    operands = [q, kp, vp]
    scratch = [
        pltpu.VMEM((2, page_size, h_kv, d), k_pages.dtype),
        pltpu.VMEM((2, page_size, h_kv, d), v_pages.dtype),
    ]
    n_sems = 2
    if quantized:
        in_specs += [any_spec, any_spec]
        # head-major [N, H_kv, P] pages: the DMA's minor dim must be the
        # lane-aligned P (see _scale_rows_t); the transpose is a few MB
        # over the whole pool, noise next to the page reads themselves
        operands += [
            k_scales.reshape(-1, page_size, h_kv).transpose(0, 2, 1),
            v_scales.reshape(-1, page_size, h_kv).transpose(0, 2, 1)]
        scratch += [pltpu.VMEM((2, h_kv, page_size), jnp.float32),
                    pltpu.VMEM((2, h_kv, page_size), jnp.float32)]
        n_sems = 4
    scratch.append(pltpu.SemaphoreType.DMA((2, n_sems)))
    scratch += [
        pltpu.VMEM((h, 128), jnp.float32),   # running max (lane-replicated)
        pltpu.VMEM((h, 128), jnp.float32),   # running denominator
        pltpu.VMEM((h, d), jnp.float32),     # output accumulator
    ]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, h, d), lambda b_, bt, sl: (b_, 0, 0)),
        scratch_shapes=scratch,
    )
    kernel = functools.partial(_decode_kernel_seq, page_size=page_size,
                               scale=scale, window=window, softcap=softcap,
                               h_kv=h_kv, g=g, quantized=quantized,
                               wide=dot_mode == "wide")
    # tile: (8, 128) — f32 native VMEM tiling; the double-buffered page
    # scratch keeps head_dim on the lane dim, page rows on the sublane
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(block_tables, seq_lens, *operands)


def paged_decode_attention_xla(q, k_pages, v_pages, block_tables, seq_lens,
                               *, page_size: int, scale: float | None = None,
                               window: int | None = None,
                               softcap: float | None = None,
                               k_scales=None, v_scales=None):
    """Portable XLA reference for :func:`paged_decode_attention_pallas`.

    Gathers each sequence's pages (a leading-dim whole-page gather in the
    token-major layout) into a contiguous [B, S, H_kv, D] view and runs
    masked attention; the unit-test oracle and the CPU execution path.
    """
    b, h, d = q.shape
    h_kv = k_pages.shape[1]
    g = h // h_kv
    max_pages = block_tables.shape[1]
    s_max = max_pages * page_size
    scale = scale if scale is not None else d ** -0.5

    kp = k_pages.reshape(-1, page_size, h_kv, d)   # [N, P, H_kv, D] view
    vp = v_pages.reshape(-1, page_size, h_kv, d)
    k_seq = kp[block_tables].reshape(b, s_max, h_kv, d).astype(jnp.float32)
    v_seq = vp[block_tables].reshape(b, s_max, h_kv, d).astype(jnp.float32)
    if k_scales is not None:
        ksp = k_scales.reshape(-1, page_size, h_kv)
        vsp = v_scales.reshape(-1, page_size, h_kv)
        k_seq = k_seq * ksp[block_tables].reshape(b, s_max, h_kv)[..., None]
        v_seq = v_seq * vsp[block_tables].reshape(b, s_max, h_kv)[..., None]

    qg = q.reshape(b, h_kv, g, d).astype(jnp.float32)
    scores = jnp.einsum("bngd,bsnd->bngs", qg, k_seq) * scale
    scores = _softcap(scores, softcap)
    pos = jnp.arange(s_max)[None, :]
    valid = pos < seq_lens[:, None]
    if window is not None:
        valid = valid & (pos >= seq_lens[:, None] - window)
    scores = jnp.where(valid[:, None, None, :], scores, _NEG_INF)
    probs = jnp.exp(scores - scores.max(axis=-1, keepdims=True))
    probs = probs / probs.sum(axis=-1, keepdims=True)
    out = jnp.einsum("bngs,bsnd->bngd", probs, v_seq)
    return out.reshape(b, h, d).astype(q.dtype)


# -- ragged paged attention: one kernel for prefill, decode, and verify -----
#
# The ragged formulation (PAPERS.md, arxiv 2604.15464) serves a MIXED batch
# in one wave: every row carries a ``(ctx_len, q_len)`` descriptor — a
# decode row is ``q_len=1``, a draft-verify window ``q_len=1+ndraft``, a
# prefill(-chunk) row ``q_len=w`` — and query column ``j`` of row ``b``
# attends kv positions ``< ctx_len[b] + j + 1`` through the page table.
# No per-row gather of pool pages into a dense context buffer, no pow2
# context bucketing: the window's KV is scattered into the pool FIRST
# (models/paged.py ``paged_ragged_step``) and the kernel reads pages.
#
# Columns ``j >= q_len[b]`` are PADDING: their output is unspecified
# (finite, never NaN — ``_NEG_INF`` is a finite sentinel, so an all-masked
# row degrades to a uniform average, not 0/0) and must not be read.
# ``q_lens`` bounds page liveness so a decode row in a wide-window batch
# streams only its own ``ctx+1`` tokens' pages.

def _ragged_fold_q(q, h_kv: int, g: int):
    """[W, H, D] → [W*H, D] virtual heads in KV-HEAD-MAJOR order
    (``vh = kv*(W*g) + w*g + h_in_group``), so the existing swap/wide dot
    helpers see a plain ``(h_kv, W*g)`` head grouping.  The transpose
    keeps the lane-aligned D minor dim (a sublane shuffle, Mosaic-safe)."""
    w, h, d = q.shape
    return q.reshape(w, h_kv, g, d).transpose(1, 0, 2, 3).reshape(w * h, d)


def _ragged_unfold(acc, w: int, h_kv: int, g: int):
    """[W*H, D] kv-head-major virtual heads → [W, H, D]."""
    d = acc.shape[-1]
    return acc.reshape(h_kv, w, g, d).transpose(1, 0, 2, 3).reshape(
        w, h_kv * g, d)


def _ragged_col_iota(w: int, h_kv: int, g: int, page_size: int):
    """[W*H, P] int32: the query COLUMN each virtual-head row belongs to
    (``(vh // g) % w`` under the kv-head-major fold) — the per-row piece
    of the ragged causal mask."""
    vh = jax.lax.broadcasted_iota(jnp.int32, (w * h_kv * g, page_size), 0)
    return (vh // g) % w


def _ragged_kernel(block_tables_ref, ctx_lens_ref, q_lens_ref, q_ref,
                   k_ref, v_ref, *rest, page_size: int, scale: float,
                   max_pages: int, w: int, window: int | None,
                   softcap: float | None, h_kv: int, g: int,
                   quantized: bool, wide: bool):
    """Grid ``(B, max_pages)``, page-innermost-arbitrary like
    ``_decode_kernel`` — but the W query columns of the row's ragged
    window fold into W*H VIRTUAL heads (kv-head-major, see
    ``_ragged_fold_q``), so every per-page dot/flash helper is reused
    verbatim with ``g -> W*g`` and the causal mask varying per virtual
    head instead of per row."""
    if quantized:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, m_ref, l_ref, acc_ref = rest
        ks_ref = vs_ref = None
    b = pl.program_id(0)
    p = pl.program_id(1)

    @pl.when(p == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    ctx_len = ctx_lens_ref[b]
    q_len = q_lens_ref[b]
    # the row's attended span ends at ctx + q_len (its last real query
    # column sees kv positions < ctx + q_len); clamp at 1 so an idle
    # padding row still owns one (masked) live page — l stays > 0
    attn_max = ctx_len + jnp.maximum(jnp.minimum(q_len, w), 1)
    live = p * page_size < attn_max
    if window is not None:
        # the EARLIEST query column's window lower bound is
        # ctx + 1 - window; pages wholly before it are dead for every col
        live = live & ((p + 1) * page_size > ctx_len + 1 - window)

    @pl.when(live)
    def _compute():
        cols = jax.lax.broadcasted_iota(
            jnp.int32, (w * h_kv * g, page_size), 1)
        pos = p * page_size + cols                    # [W*H, P] kv pos
        qcol = _ragged_col_iota(w, h_kv, g, page_size)
        attn_len = ctx_len + qcol + 1                 # ragged causal edge
        valid = pos < attn_len
        if window is not None:
            valid = valid & (pos >= attn_len - window)
        q = _ragged_fold_q(q_ref[0].astype(jnp.float32), h_kv, g)
        if wide:
            q = _widen_q(q, h_kv, w * g)              # [W*H, H_kv*D]
        k = k_ref[0].astype(jnp.float32)              # [P, H_kv, D]
        v = v_ref[0].astype(jnp.float32)
        ks_hp = vs_hp = None
        if ks_ref is not None:
            ks_hp = _scale_rows(ks_ref[0], w * g)
            vs_hp = _scale_rows(vs_ref[0], w * g)
        s = _page_scores(q, k, scale, softcap, valid, h_kv, w * g, ks_hp,
                         wide)
        _flash_update(s, v, m_ref, l_ref, acc_ref, h_kv, w * g, vs_hp,
                      wide)

    @pl.when(p == max_pages - 1)
    def _finalize():
        o_ref[0] = _ragged_unfold(
            acc_ref[:] / l_ref[:, :1], w, h_kv, g).astype(o_ref.dtype)


# jit-entry: ops.ragged_attn_pallas static=(page_size, scale, interpret, window, softcap, dot_mode) bucketed=(batch, q_window, pages)
@functools.partial(
    jax.jit, static_argnames=("page_size", "scale", "interpret", "window",
                              "softcap", "dot_mode"))
def ragged_paged_attention_pallas(q, k_pages, v_pages, block_tables,
                                  ctx_lens, q_lens, *, page_size: int,
                                  scale: float | None = None,
                                  interpret: bool = False,
                                  window: int | None = None,
                                  softcap: float | None = None,
                                  k_scales=None, v_scales=None,
                                  dot_mode: str = "swap"):
    """Ragged paged attention (Pallas TPU kernel): one wave over a mixed
    prefill / decode / verify batch.

    q: [B, W, H, D] — W query columns per row, left-aligned; column j of
    row b is the token at absolute position ``ctx_lens[b] + j`` and
    attends kv positions ``< ctx_lens[b] + j + 1`` through the page
    table.  k_pages/v_pages: [N_pages * P, H_kv, D] token-major flat
    (the window's KV already scattered in — see models/paged.py
    ``paged_ragged_step``); block_tables: [B, max_pages] int32;
    ctx_lens/q_lens: [B] int32 ragged descriptors.  Columns
    ``j >= q_lens[b]`` produce unspecified (finite) output.  Returns
    [B, W, H, D].
    """
    if dot_mode not in ("swap", "wide"):
        # a typo would silently bench swap under the wide label
        raise ValueError(f"unknown dot_mode {dot_mode!r}; expected swap | wide")
    b, w, h, d = q.shape
    h_kv = k_pages.shape[1]
    g = h // h_kv
    max_pages = block_tables.shape[1]
    quantized = k_scales is not None
    scale = float(scale if scale is not None else d ** -0.5)
    kp = k_pages.reshape(-1, page_size, h_kv, d)   # [N, P, H_kv, D] view
    vp = v_pages.reshape(-1, page_size, h_kv, d)

    def page_index(b_, p_, bt, cl, ql):
        # dead pages (beyond the row's ragged span) redirect to page 0:
        # consecutive identical indices skip the HBM→VMEM re-DMA
        amax = cl[b_] + jnp.maximum(jnp.minimum(ql[b_], w), 1)
        alive = p_ * page_size < amax
        if window is not None:
            alive = alive & ((p_ + 1) * page_size > cl[b_] + 1 - window)
        return jnp.where(alive, bt[b_, p_], 0)

    in_specs = [
        pl.BlockSpec((1, w, h, d), lambda b_, p_, bt, cl, ql: (b_, 0, 0, 0)),
        pl.BlockSpec((1, page_size, h_kv, d),
                     lambda b_, p_, bt, cl, ql: (
                         page_index(b_, p_, bt, cl, ql), 0, 0, 0)),
        pl.BlockSpec((1, page_size, h_kv, d),
                     lambda b_, p_, bt, cl, ql: (
                         page_index(b_, p_, bt, cl, ql), 0, 0, 0)),
    ]
    operands = [q, kp, vp]
    if quantized:
        ksp = k_scales.reshape(-1, page_size, h_kv)
        vsp = v_scales.reshape(-1, page_size, h_kv)
        spec_s = pl.BlockSpec(
            (1, page_size, h_kv),
            lambda b_, p_, bt, cl, ql: (
                page_index(b_, p_, bt, cl, ql), 0, 0))
        in_specs += [spec_s, spec_s]
        operands += [ksp, vsp]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b, max_pages),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, w, h, d),
                               lambda b_, p_, bt, cl, ql: (b_, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((w * h, 128), jnp.float32),  # running max (lane-rep)
            pltpu.VMEM((w * h, 128), jnp.float32),  # running denominator
            pltpu.VMEM((w * h, d), jnp.float32),    # output accumulator
        ],
    )
    kernel = functools.partial(_ragged_kernel, page_size=page_size,
                               scale=scale, max_pages=max_pages, w=w,
                               window=window, softcap=softcap, h_kv=h_kv,
                               g=g, quantized=quantized,
                               wide=dot_mode == "wide")
    # tile: (8, 128) — f32 native VMEM tiling; head_dim rides the lane
    # dim (the 128-wide scratch rows), W*H virtual heads ride the sublane
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, w, h, d), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(block_tables, ctx_lens, q_lens, *operands)


def ragged_paged_attention_xla(q, k_pages, v_pages, block_tables,
                               ctx_lens, q_lens, *, page_size: int,
                               scale: float | None = None,
                               window: int | None = None,
                               softcap: float | None = None,
                               k_scales=None, v_scales=None):
    """Portable XLA reference for :func:`ragged_paged_attention_pallas`.

    Whole-page gather into [B, S, H_kv, D] plus a dense [B, W, S] ragged
    causal mask (``pos < ctx + j + 1``); the unit-test oracle and the
    CPU/export execution path.  Same padding-column contract: output at
    ``j >= q_lens[b]`` is unspecified but finite (``q_lens`` is accepted
    for signature parity; the mask needs only ``ctx_lens``).
    """
    del q_lens      # padding cols share the valid-col mask rule; never read
    b, w, h, d = q.shape
    h_kv = k_pages.shape[1]
    g = h // h_kv
    s_max = block_tables.shape[1] * page_size
    scale = scale if scale is not None else d ** -0.5

    kp = k_pages.reshape(-1, page_size, h_kv, d)   # [N, P, H_kv, D] view
    vp = v_pages.reshape(-1, page_size, h_kv, d)
    k_seq = kp[block_tables].reshape(b, s_max, h_kv, d).astype(jnp.float32)
    v_seq = vp[block_tables].reshape(b, s_max, h_kv, d).astype(jnp.float32)
    if k_scales is not None:
        ksp = k_scales.reshape(-1, page_size, h_kv)
        vsp = v_scales.reshape(-1, page_size, h_kv)
        k_seq = k_seq * ksp[block_tables].reshape(b, s_max, h_kv)[..., None]
        v_seq = v_seq * vsp[block_tables].reshape(b, s_max, h_kv)[..., None]

    qg = q.reshape(b, w, h_kv, g, d).astype(jnp.float32)
    scores = jnp.einsum("bwngd,bsnd->bwngs", qg, k_seq) * scale
    scores = _softcap(scores, softcap)
    pos = jnp.arange(s_max)[None, None, :]                     # [1, 1, S]
    attn_len = ctx_lens[:, None] + jnp.arange(w)[None, :] + 1  # [B, W]
    valid = pos < attn_len[:, :, None]
    if window is not None:
        valid = valid & (pos >= attn_len[:, :, None] - window)
    scores = jnp.where(valid[:, :, None, None, :], scores, _NEG_INF)
    probs = jnp.exp(scores - scores.max(axis=-1, keepdims=True))
    probs = probs / probs.sum(axis=-1, keepdims=True)
    out = jnp.einsum("bwngs,bsnd->bwngd", probs, v_seq)
    return out.reshape(b, w, h, d).astype(q.dtype)


def ragged_paged_attention(q, k_pages, v_pages, block_tables, ctx_lens,
                           q_lens, *, page_size: int,
                           scale: float | None = None,
                           window: int | None = None,
                           softcap: float | None = None,
                           k_scales=None, v_scales=None):
    """Backend-dispatching ragged paged attention.

    ``REVAL_TPU_PAGED_BACKEND=ragged`` selects the Pallas kernel
    (interpret mode off-TPU, same ``REVAL_TPU_FORCE_MOSAIC`` escape as
    the decode dispatch); ``ragged_xla`` pins the gather reference —
    the exportable formulation deviceless AOT uses.  Any other resolved
    backend (the engine only calls this when it runs in ragged mode)
    defaults to Pallas-on-TPU / XLA-elsewhere, mirroring
    :func:`paged_decode_attention`'s fallback rule.
    """
    from ..env import env_str

    choice = (env_str("REVAL_TPU_PAGED_BACKEND")
              or _autotune_defaults().get("REVAL_TPU_PAGED_BACKEND"))
    if choice == "ragged_xla":
        use_pallas = False
    elif choice == "ragged":
        use_pallas = True
    else:
        use_pallas = jax.default_backend() == "tpu"
    if not use_pallas:
        return ragged_paged_attention_xla(
            q, k_pages, v_pages, block_tables, ctx_lens, q_lens,
            page_size=page_size, scale=scale, window=window,
            softcap=softcap, k_scales=k_scales, v_scales=v_scales)
    force = (env_str("REVAL_TPU_FORCE_MOSAIC") or "").lower()
    interpret = (jax.default_backend() != "tpu"
                 and force not in ("1", "true"))
    dot = (env_str("REVAL_TPU_KERNEL_DOT")
           or _autotune_defaults().get("REVAL_TPU_KERNEL_DOT") or "swap")
    if dot not in ("swap", "wide"):
        raise ValueError(f"unknown REVAL_TPU_KERNEL_DOT {dot!r}; "
                         "expected swap | wide")
    return ragged_paged_attention_pallas(
        q, k_pages, v_pages, block_tables, ctx_lens, q_lens,
        page_size=page_size, scale=scale, interpret=interpret,
        window=window, softcap=softcap, k_scales=k_scales,
        v_scales=v_scales, dot_mode=dot)


def paged_decode_attention(q, k_pages, v_pages, block_tables, seq_lens,
                           *, page_size: int, scale: float | None = None,
                           window: int | None = None,
                           softcap: float | None = None,
                           k_scales=None, v_scales=None):
    """Backend-dispatching paged decode attention: Pallas on TPU, XLA
    elsewhere (same numerics; the kernel is tested against the XLA path).

    ``REVAL_TPU_PAGED_BACKEND=pallas|pallas_seq|xla`` overrides — the XLA
    gather formulation is what CPU uses; ``pallas_seq`` selects the
    per-sequence streaming kernel (pending on-chip A/B before it becomes
    the TPU default).  ``REVAL_TPU_KERNEL_DOT=swap|wide`` picks the
    in-kernel dot formulation (see :func:`_page_scores`); read at trace
    time, so it binds per compiled program like the backend choice.

    ``REVAL_TPU_FORCE_MOSAIC=1`` forces ``interpret=False`` even when the
    runtime backend is CPU: deviceless AOT compiles for a TPU *topology*
    (tests/test_tpu_aot_compile.py, tools/aot_warm.py) run on a CPU host,
    and keying interpret on ``jax.default_backend()`` would silently
    trace the HLO emulation instead of the Mosaic kernel — compiling a
    program the chip never runs.

    When an env var is UNSET, the persisted autotune decision
    (``tpu_watch/autotune.json``, written by ``tools/decide_defaults.py``
    from recorded on-chip A/B artifacts; path override:
    ``REVAL_TPU_AUTOTUNE_FILE``) supplies the measured-best default — so
    the driver's official bench and any engine user run the winning
    config without a live session flipping constants.
    """
    from ..env import env_str

    choice = (env_str("REVAL_TPU_PAGED_BACKEND")
              or _autotune_defaults().get("REVAL_TPU_PAGED_BACKEND"))
    if choice not in (None, "", "pallas", "pallas_seq", "xla",
                      "ragged", "ragged_xla"):
        # a typo here would silently bench the wrong backend under the
        # right label — fail loudly instead
        raise ValueError(f"unknown REVAL_TPU_PAGED_BACKEND {choice!r}; "
                         "expected pallas | pallas_seq | xla | ragged | "
                         "ragged_xla")
    if choice in ("ragged", "ragged_xla"):
        # ragged mode: ONE kernel owns every attention shape, including
        # the plain decode step (a W=1 ragged window).  The engine passes
        # attn_lens (= seq_lens + 1 past the freshly written token), so
        # the ragged descriptor is ctx = attn_len - 1 with one query col.
        out = ragged_paged_attention(
            q[:, None], k_pages, v_pages, block_tables,
            jnp.maximum(seq_lens, 1) - 1, jnp.ones_like(seq_lens),
            page_size=page_size, scale=scale, window=window,
            softcap=softcap, k_scales=k_scales, v_scales=v_scales)
        return out[:, 0]
    if choice == "pallas_seq":
        fn = paged_decode_attention_pallas_seq
    else:
        use_pallas = (choice == "pallas" if choice
                      else jax.default_backend() == "tpu")
        fn = (paged_decode_attention_pallas if use_pallas
              else paged_decode_attention_xla)
    kw = {}
    if fn is not paged_decode_attention_xla:
        # an explicitly-chosen Pallas kernel off-TPU runs in interpret
        # mode: slow, but it lets the whole engine path execute the real
        # kernel on CPU (end-to-end validation without a chip)
        force = (env_str("REVAL_TPU_FORCE_MOSAIC") or "").lower()
        kw["interpret"] = (jax.default_backend() != "tpu"
                           and force not in ("1", "true"))
        dot = (env_str("REVAL_TPU_KERNEL_DOT")
               or _autotune_defaults().get("REVAL_TPU_KERNEL_DOT") or "swap")
        if dot not in ("swap", "wide"):
            raise ValueError(f"unknown REVAL_TPU_KERNEL_DOT {dot!r}; "
                             "expected swap | wide")
        kw["dot_mode"] = dot
    return fn(q, k_pages, v_pages, block_tables, seq_lens,
              page_size=page_size, scale=scale, window=window,
              softcap=softcap, k_scales=k_scales, v_scales=v_scales, **kw)


def resolved_paged_backend() -> str:
    """The decode-attention backend :func:`paged_decode_attention` will
    actually trace right now — env override, else the persisted autotune
    pick, else pallas-on-TPU/xla-elsewhere.  The AOT executable cache
    keys its fingerprint on this (and only arms the Mosaic export canary
    for pallas programs — an xla-resolved chunk exports anywhere)."""
    from ..env import env_str

    choice = (env_str("REVAL_TPU_PAGED_BACKEND")
              or _autotune_defaults().get("REVAL_TPU_PAGED_BACKEND"))
    if choice in ("pallas", "pallas_seq", "xla", "ragged", "ragged_xla"):
        return choice
    return "pallas" if jax.default_backend() == "tpu" else "xla"


def resolved_kernel_knobs() -> dict:
    """The trace-time kernel knobs that bind per compiled program beyond
    the backend label — dot formulation (``REVAL_TPU_KERNEL_DOT`` or the
    autotune pick) and interpret mode (``REVAL_TPU_FORCE_MOSAIC`` ×
    platform).  The AOT executable cache folds these into its
    fingerprint: under one backend label they change the traced program,
    so a warm restart must not serve an executable traced under
    different knobs.  The xla formulation reads neither — stable
    constants, so xla-resolved programs cache across knob changes."""
    from ..env import env_str

    if resolved_paged_backend() in ("xla", "ragged_xla"):
        return {"dot_mode": "n/a", "interpret": "n/a"}
    force = (env_str("REVAL_TPU_FORCE_MOSAIC") or "").lower()
    return {"dot_mode": (env_str("REVAL_TPU_KERNEL_DOT")
                         or _autotune_defaults().get("REVAL_TPU_KERNEL_DOT")
                         or "swap"),
            "interpret": (jax.default_backend() != "tpu"
                          and force not in ("1", "true"))}
