"""Pallas TPU kernel: ragged paged-attention for the decode step.

TPU-native replacement for the paged-attention CUDA kernels vLLM supplies
to the reference (reference inference.py:90-95 constructs ``vllm.LLM``;
its CUDA kernels are the vendored-native dependency catalogued in
SURVEY.md §2.9).  The KV cache lives in HBM as fixed-size pages and a
block table maps each sequence to its pages, so sequences of wildly
different lengths share one cache pool with no per-sequence reallocation —
the layout continuous batching needs.

Layout (measured on v5e, see PERF.md and models/paged.py):
- ``k_pages``/``v_pages``: ``[N_pages * P, H_kv, D]`` — token-major and
  flat, the same arrays the decode scatter writes in place.  A page is
  ``P`` consecutive rows, so the kernel views the array as
  ``[N_pages, P, H_kv, D]`` (a free reshape) and one page for *all* kv
  heads is a contiguous block.
- ``block_tables``: ``[B, max_pages]`` int32 page ids (0-padded past the
  end; padding is masked, never read as data).
- ``seq_lens``: ``[B]`` int32 — tokens currently valid per sequence.
- optional ``k_scales``/``v_scales``: ``[N_pages * P, H_kv]`` f32 —
  per-(token, head) symmetric int8 scales when the pool stores int8
  (models/paged.py ``kv_dtype="int8"``): dequantised value =
  ``page_int8 * scale``.  Halves pool bytes and attention DMA.

Kernel shape: grid ``(B, max_pages)`` with the page dimension innermost
and *arbitrary* (sequential), so flash-style online-softmax accumulators
in VMEM scratch carry across pages.  Each grid step processes one page
for EVERY head at once — the per-(head, page) grid of a head-split layout
costs ~H_kv× more grid steps, and TPU grids are sequential per core, so
grid-step overhead is what buries fine-grained kernels.  The block table
and sequence lengths ride in scalar-prefetch SMEM: Pallas reads
``block_tables[b, p]`` inside the BlockSpec index_map to schedule the
HBM→VMEM DMA of the right page ahead of compute — the pipelining the CUDA
kernel does by hand falls out of the grid spec.  Dead pages (beyond the
sequence's length, or wholly outside its sliding window) redirect their
index_map to page 0: consecutive equal block indices skip the re-DMA, so
table padding costs almost nothing.

Everything compiles with ``interpret=True`` on CPU, which is how the unit
tests validate the kernel bit-for-bit against the XLA reference below.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .attention import _softcap

__all__ = [
    "paged_decode_attention",
    "paged_decode_attention_xla",
    "paged_decode_attention_pallas",
    "paged_decode_attention_pallas_seq",
]

_NEG_INF = -1e30


def _decode_kernel(block_tables_ref, seq_lens_ref, q_ref, k_ref, v_ref,
                   *rest, page_size: int, scale: float, max_pages: int,
                   window: int | None, softcap: float | None,
                   h_kv: int, g: int, quantized: bool):
    if quantized:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, m_ref, l_ref, acc_ref = rest
        ks_ref = vs_ref = None
    b = pl.program_id(0)
    p = pl.program_id(1)

    @pl.when(p == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    seq_len = seq_lens_ref[b]

    # sliding window: the query (logical position seq_len-1) sees keys in
    # [seq_len - window, seq_len); pages wholly before that are skipped —
    # compute for old pages costs nothing extra, and the window page set
    # is what bounds effective attention length for Mistral/StarCoder2
    live = p * page_size < seq_len
    if window is not None:
        live = live & ((p + 1) * page_size > seq_len - window)

    @pl.when(live)
    def _compute():
        cols = jax.lax.broadcasted_iota(jnp.int32, (1, page_size), 1)
        pos = p * page_size + cols                    # [1, P]
        valid = pos < seq_len
        if window is not None:
            valid = valid & (pos >= seq_len - window)
        # one page for all heads: static loop over kv heads, each a
        # [G, D] x [D, P] matmul (batched matvec has no 2D-matmul form)
        for h in range(h_kv):
            q = q_ref[0, h * g:(h + 1) * g].astype(jnp.float32)    # [G, D]
            k = k_ref[0, :, h].astype(jnp.float32)                 # [P, D]
            v = v_ref[0, :, h].astype(jnp.float32)                 # [P, D]
            if ks_ref is not None:
                k = k * ks_ref[0, :, h][:, None]
                v = v * vs_ref[0, :, h][:, None]
            s = jax.lax.dot_general(                               # [G, P]
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale
            s = _softcap(s, softcap)             # gemma-2 score softcapping
            s = jnp.where(valid, s, _NEG_INF)

            rows = slice(h * g, (h + 1) * g)
            m_prev = m_ref[rows, :1]                      # [G, 1]
            m_cur = jnp.max(s, axis=-1, keepdims=True)    # [G, 1]
            m_new = jnp.maximum(m_prev, m_cur)
            alpha = jnp.exp(m_prev - m_new)               # rescale old sums
            probs = jnp.exp(s - m_new)                    # [G, P]
            l_new = alpha * l_ref[rows, :1] + probs.sum(axis=-1, keepdims=True)
            acc_ref[rows, :] = acc_ref[rows, :] * alpha + jnp.dot(
                probs, v, preferred_element_type=jnp.float32)
            m_ref[rows, :] = jnp.broadcast_to(m_new, (g, m_ref.shape[1]))
            l_ref[rows, :] = jnp.broadcast_to(l_new, (g, l_ref.shape[1]))

    @pl.when(p == max_pages - 1)
    def _finalize():
        o_ref[0] = (acc_ref[:] / l_ref[:, :1]).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("page_size", "scale", "interpret", "window",
                              "softcap"))
def paged_decode_attention_pallas(q, k_pages, v_pages, block_tables, seq_lens,
                                  *, page_size: int, scale: float | None = None,
                                  interpret: bool = False,
                                  window: int | None = None,
                                  softcap: float | None = None,
                                  k_scales=None, v_scales=None):
    """One-token attention against a paged KV cache (Pallas TPU kernel).

    q: [B, H, D]; k_pages/v_pages: [N_pages * P, H_kv, D] (token-major
    flat); block_tables: [B, max_pages] int32; seq_lens: [B] int32 (≥1).
    ``window``: sliding-window size (static; per-model constant) — only
    the most recent ``window`` keys participate.  ``k_scales``/
    ``v_scales``: per-(token, head) f32 scales for int8 pools.
    Returns [B, H, D].
    """
    b, h, d = q.shape
    h_kv = k_pages.shape[1]
    g = h // h_kv
    max_pages = block_tables.shape[1]
    quantized = k_scales is not None
    scale = float(scale if scale is not None else d ** -0.5)
    kp = k_pages.reshape(-1, page_size, h_kv, d)   # [N, P, H_kv, D] view
    vp = v_pages.reshape(-1, page_size, h_kv, d)

    def page_index(b_, p_, bt, sl):
        # dead pages (masked anyway) redirect to page 0: consecutive
        # identical indices skip the HBM→VMEM re-DMA
        alive = p_ * page_size < sl[b_]
        if window is not None:
            alive = alive & ((p_ + 1) * page_size > sl[b_] - window)
        return jnp.where(alive, bt[b_, p_], 0)

    in_specs = [
        pl.BlockSpec((1, h, d), lambda b_, p_, bt, sl: (b_, 0, 0)),
        pl.BlockSpec((1, page_size, h_kv, d),
                     lambda b_, p_, bt, sl: (page_index(b_, p_, bt, sl), 0, 0, 0)),
        pl.BlockSpec((1, page_size, h_kv, d),
                     lambda b_, p_, bt, sl: (page_index(b_, p_, bt, sl), 0, 0, 0)),
    ]
    operands = [q, kp, vp]
    if quantized:
        ksp = k_scales.reshape(-1, page_size, h_kv)
        vsp = v_scales.reshape(-1, page_size, h_kv)
        spec_s = pl.BlockSpec(
            (1, page_size, h_kv),
            lambda b_, p_, bt, sl: (page_index(b_, p_, bt, sl), 0, 0))
        in_specs += [spec_s, spec_s]
        operands += [ksp, vsp]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, max_pages),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, h, d), lambda b_, p_, bt, sl: (b_, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, 128), jnp.float32),   # running max (lane-replicated)
            pltpu.VMEM((h, 128), jnp.float32),   # running denominator
            pltpu.VMEM((h, d), jnp.float32),     # output accumulator
        ],
    )
    kernel = functools.partial(_decode_kernel, page_size=page_size,
                               scale=scale, max_pages=max_pages,
                               window=window, softcap=softcap, h_kv=h_kv,
                               g=g, quantized=quantized)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(block_tables, seq_lens, *operands)


def _decode_kernel_seq(block_tables_ref, seq_lens_ref, q_ref, k_hbm, v_hbm,
                       *rest, page_size: int, scale: float,
                       window: int | None, softcap: float | None,
                       h_kv: int, g: int, quantized: bool):
    """One grid step = one WHOLE sequence: a double-buffered in-kernel
    page loop replaces the per-(sequence, page) grid of
    ``_decode_kernel``.

    Why: at decode shapes the per-page work is a handful of [G, D]x[D, P]
    matvecs (~1-3 us) — the same order as TPU grid-step overhead, so the
    page-granular grid pays ~50% overhead (measured 1442 tok/s vs ~4000
    tok/s HBM roofline at the bench shape, PERF.md).  Here the grid is
    just [B]; the kernel walks the sequence's live pages with
    ``make_async_copy`` HBM→VMEM fetches two pages deep, so page p+1
    streams in while page p computes — the hand-rolled version of the
    pipelining BlockSpec index_maps gave the old kernel, minus the
    dead-step overhead."""
    if quantized:
        ks_hbm, vs_hbm, o_ref, k_buf, v_buf, ks_buf, vs_buf, sem = rest
    else:
        o_ref, k_buf, v_buf, sem = rest
        ks_hbm = vs_hbm = ks_buf = vs_buf = None
    b = pl.program_id(0)
    seq_len = seq_lens_ref[b]
    n_live = (seq_len + page_size - 1) // page_size
    if window is not None:
        p0 = jnp.maximum((seq_len - window) // page_size, 0)
    else:
        p0 = jnp.int32(0)

    def dmas(slot, p):
        page = block_tables_ref[b, p]
        out = [
            pltpu.make_async_copy(k_hbm.at[page], k_buf.at[slot],
                                  sem.at[slot, 0]),
            pltpu.make_async_copy(v_hbm.at[page], v_buf.at[slot],
                                  sem.at[slot, 1]),
        ]
        if quantized:
            out += [
                pltpu.make_async_copy(ks_hbm.at[page], ks_buf.at[slot],
                                      sem.at[slot, 2]),
                pltpu.make_async_copy(vs_hbm.at[page], vs_buf.at[slot],
                                      sem.at[slot, 3]),
            ]
        return out

    for d in dmas(p0 % 2, p0):
        d.start()

    def body(p, carry):
        m, l, acc = carry
        slot = p % 2

        @pl.when(p + 1 < n_live)
        def _prefetch():
            for d in dmas((p + 1) % 2, p + 1):
                d.start()

        for d in dmas(slot, p):
            d.wait()

        cols = jax.lax.broadcasted_iota(jnp.int32, (1, page_size), 1)
        pos = p * page_size + cols                     # [1, P]
        valid = pos < seq_len
        if window is not None:
            valid = valid & (pos >= seq_len - window)

        for h in range(h_kv):
            q = q_ref[0, h * g:(h + 1) * g].astype(jnp.float32)    # [G, D]
            k = k_buf[slot, :, h].astype(jnp.float32)              # [P, D]
            v = v_buf[slot, :, h].astype(jnp.float32)
            if quantized:
                k = k * ks_buf[slot, :, h][:, None]
                v = v * vs_buf[slot, :, h][:, None]
            s = jax.lax.dot_general(                               # [G, P]
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale
            s = _softcap(s, softcap)
            s = jnp.where(valid, s, _NEG_INF)

            rows = slice(h * g, (h + 1) * g)
            m_prev = m[rows]                              # [G, 1]
            m_cur = jnp.max(s, axis=-1, keepdims=True)
            m_new = jnp.maximum(m_prev, m_cur)
            alpha = jnp.exp(m_prev - m_new)
            probs = jnp.exp(s - m_new)                    # [G, P]
            l = l.at[rows].set(alpha * l[rows]
                               + probs.sum(axis=-1, keepdims=True))
            acc = acc.at[rows].set(acc[rows] * alpha + jnp.dot(
                probs, v, preferred_element_type=jnp.float32))
            m = m.at[rows].set(m_new)
        return m, l, acc

    h = h_kv * g
    m0 = jnp.full((h, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((h, 1), jnp.float32)
    acc0 = jnp.zeros((h, q_ref.shape[2]), jnp.float32)
    _, l, acc = jax.lax.fori_loop(p0, n_live, body, (m0, l0, acc0))
    o_ref[0] = (acc / l).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("page_size", "scale", "interpret", "window",
                              "softcap"))
def paged_decode_attention_pallas_seq(q, k_pages, v_pages, block_tables,
                                      seq_lens, *, page_size: int,
                                      scale: float | None = None,
                                      interpret: bool = False,
                                      window: int | None = None,
                                      softcap: float | None = None,
                                      k_scales=None, v_scales=None):
    """Per-sequence paged decode attention (see ``_decode_kernel_seq``).

    Same contract as :func:`paged_decode_attention_pallas`; the pools stay
    in HBM (``memory_space=ANY``) and the kernel streams live pages only.
    """
    b, h, d = q.shape
    h_kv = k_pages.shape[1]
    g = h // h_kv
    quantized = k_scales is not None
    scale = float(scale if scale is not None else d ** -0.5)
    kp = k_pages.reshape(-1, page_size, h_kv, d)   # [N, P, H_kv, D] view
    vp = v_pages.reshape(-1, page_size, h_kv, d)

    any_spec = pl.BlockSpec(memory_space=pl.ANY)
    in_specs = [
        pl.BlockSpec((1, h, d), lambda b_, bt, sl: (b_, 0, 0)),
        any_spec, any_spec,
    ]
    operands = [q, kp, vp]
    scratch = [
        pltpu.VMEM((2, page_size, h_kv, d), k_pages.dtype),
        pltpu.VMEM((2, page_size, h_kv, d), v_pages.dtype),
    ]
    n_sems = 2
    if quantized:
        in_specs += [any_spec, any_spec]
        operands += [k_scales.reshape(-1, page_size, h_kv),
                     v_scales.reshape(-1, page_size, h_kv)]
        scratch += [pltpu.VMEM((2, page_size, h_kv), jnp.float32),
                    pltpu.VMEM((2, page_size, h_kv), jnp.float32)]
        n_sems = 4
    scratch.append(pltpu.SemaphoreType.DMA((2, n_sems)))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, h, d), lambda b_, bt, sl: (b_, 0, 0)),
        scratch_shapes=scratch,
    )
    kernel = functools.partial(_decode_kernel_seq, page_size=page_size,
                               scale=scale, window=window, softcap=softcap,
                               h_kv=h_kv, g=g, quantized=quantized)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(block_tables, seq_lens, *operands)


def paged_decode_attention_xla(q, k_pages, v_pages, block_tables, seq_lens,
                               *, page_size: int, scale: float | None = None,
                               window: int | None = None,
                               softcap: float | None = None,
                               k_scales=None, v_scales=None):
    """Portable XLA reference for :func:`paged_decode_attention_pallas`.

    Gathers each sequence's pages (a leading-dim whole-page gather in the
    token-major layout) into a contiguous [B, S, H_kv, D] view and runs
    masked attention; the unit-test oracle and the CPU execution path.
    """
    b, h, d = q.shape
    h_kv = k_pages.shape[1]
    g = h // h_kv
    max_pages = block_tables.shape[1]
    s_max = max_pages * page_size
    scale = scale if scale is not None else d ** -0.5

    kp = k_pages.reshape(-1, page_size, h_kv, d)   # [N, P, H_kv, D] view
    vp = v_pages.reshape(-1, page_size, h_kv, d)
    k_seq = kp[block_tables].reshape(b, s_max, h_kv, d).astype(jnp.float32)
    v_seq = vp[block_tables].reshape(b, s_max, h_kv, d).astype(jnp.float32)
    if k_scales is not None:
        ksp = k_scales.reshape(-1, page_size, h_kv)
        vsp = v_scales.reshape(-1, page_size, h_kv)
        k_seq = k_seq * ksp[block_tables].reshape(b, s_max, h_kv)[..., None]
        v_seq = v_seq * vsp[block_tables].reshape(b, s_max, h_kv)[..., None]

    qg = q.reshape(b, h_kv, g, d).astype(jnp.float32)
    scores = jnp.einsum("bngd,bsnd->bngs", qg, k_seq) * scale
    scores = _softcap(scores, softcap)
    pos = jnp.arange(s_max)[None, :]
    valid = pos < seq_lens[:, None]
    if window is not None:
        valid = valid & (pos >= seq_lens[:, None] - window)
    scores = jnp.where(valid[:, None, None, :], scores, _NEG_INF)
    probs = jnp.exp(scores - scores.max(axis=-1, keepdims=True))
    probs = probs / probs.sum(axis=-1, keepdims=True)
    out = jnp.einsum("bngs,bsnd->bngd", probs, v_seq)
    return out.reshape(b, h, d).astype(q.dtype)


def paged_decode_attention(q, k_pages, v_pages, block_tables, seq_lens,
                           *, page_size: int, scale: float | None = None,
                           window: int | None = None,
                           softcap: float | None = None,
                           k_scales=None, v_scales=None):
    """Backend-dispatching paged decode attention: Pallas on TPU, XLA
    elsewhere (same numerics; the kernel is tested against the XLA path).

    ``REVAL_TPU_PAGED_BACKEND=pallas|pallas_seq|xla`` overrides — the XLA
    gather formulation is what CPU uses; ``pallas_seq`` selects the
    per-sequence streaming kernel (pending on-chip A/B before it becomes
    the TPU default).
    """
    import os

    choice = os.environ.get("REVAL_TPU_PAGED_BACKEND")
    if choice == "pallas_seq":
        fn = paged_decode_attention_pallas_seq
    else:
        use_pallas = (choice == "pallas" if choice
                      else jax.default_backend() == "tpu")
        fn = (paged_decode_attention_pallas if use_pallas
              else paged_decode_attention_xla)
    kw = {}
    if fn is not paged_decode_attention_xla:
        # an explicitly-chosen Pallas kernel off-TPU runs in interpret
        # mode: slow, but it lets the whole engine path execute the real
        # kernel on CPU (end-to-end validation without a chip)
        kw["interpret"] = jax.default_backend() != "tpu"
    return fn(q, k_pages, v_pages, block_tables, seq_lens,
              page_size=page_size, scale=scale, window=window,
              softcap=softcap, k_scales=k_scales, v_scales=v_scales, **kw)
