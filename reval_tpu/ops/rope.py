"""Rotary position embeddings (RoPE).

Half-split layout (HF llama convention: rotate_half over the feature dim),
angles precomputed per call from positions — positions are data (they
depend on per-sequence padding), so there is no cached table to go stale.
Float32 throughout; bf16 angles noticeably hurt long-context parity.
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["rope_angles", "apply_rope"]


def rope_angles(positions: jnp.ndarray, head_dim: int, theta: float = 10000.0):
    """cos/sin tables for integer ``positions`` [..., T] → ([..., T, D/2])."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    angles = positions.astype(jnp.float32)[..., None] * inv_freq  # [..., T, D/2]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray) -> jnp.ndarray:
    """Rotate ``x`` [B, T, H, D] by per-position angles [B, T, D/2].

    Uses the half-split convention: pairs are (x[..., :D/2], x[..., D/2:]).
    """
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    half = xf.shape[-1] // 2
    x1, x2 = xf[..., :half], xf[..., half:]
    cos = cos[:, :, None, :]  # broadcast over heads
    sin = sin[:, :, None, :]
    rotated = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return rotated.astype(dtype)
