"""Prompt construction from the byte-compatible few-shot templates.

Templates live in ``templates/`` (4 tasks × {direct, cot}); rendering is
plain ``str.format`` over the fields ``{code} {invocation}
{invocation_abbr} {line} {codeline} {var}`` (reference prompt.py:1-9).
Templates are cached after first read.
"""

from __future__ import annotations

from functools import lru_cache
from pathlib import Path

__all__ = ["build_prompt", "build_direct_prompt", "build_cot_prompt", "template_path", "STOP_STRING"]

# The universal generation stop sequence (reference inference.py:65,97,123).
STOP_STRING = "[/ANSWER]"

_TEMPLATE_DIR = Path(__file__).resolve().parent / "templates"

VALID_TASKS = ("coverage", "path", "state", "output")
VALID_STYLES = ("direct", "cot")


def template_path(task: str, style: str) -> Path:
    assert task in VALID_TASKS, f"unknown task {task!r}"
    assert style in VALID_STYLES, f"unknown prompt style {style!r}"
    return _TEMPLATE_DIR / f"{style}_{task}.txt"


@lru_cache(maxsize=None)
def _template(task: str, style: str) -> str:
    return template_path(task, style).read_text()


def build_prompt(task: str, style: str, **fields) -> str:
    return _template(task, style).format(**fields)


def build_direct_prompt(task: str, **fields) -> str:
    return build_prompt(task, "direct", **fields)


def build_cot_prompt(task: str, **fields) -> str:
    return build_prompt(task, "cot", **fields)
