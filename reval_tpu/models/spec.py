"""Greedy speculative decoding over the paged cache: n-gram draft + MXU verify.

Decode is weight-bandwidth-bound (PERF.md: every step re-reads the matmul
weights), so verifying K candidate tokens in ONE model pass makes
accepted tokens nearly free: the weights are read once per verify round
instead of once per token.  DREval generations are exceptionally
draft-friendly — answers echo prompt fragments ("[ANSWER] ... [/ANSWER]",
repeated variable/state lists, CoT traces quoting the program line by
line) — so a prompt-lookup (n-gram) draft needs no draft model at all:
candidates come from the sequence's OWN history (the technique vLLM
ships as prompt-lookup / ngram speculative decoding; the reference never
enables it).

Greedy only, and exactly output-preserving: a candidate is accepted iff
it equals the model's own argmax at that position, and the first
mismatch position contributes the model's argmax as a bonus token — the
emitted sequence is bit-identical to token-by-token greedy decode.

Everything runs ON DEVICE inside the engine's jitted chunk (drafting is
a vectorised bigram search over a device-resident history buffer), so
the host round-trip cost per chunk is unchanged — critical on this
host's tunneled TPU where each dispatch costs ~100 ms (PERF.md).

Cache-write discipline: a verify round writes all K+1 positions' KV into
the pages at ``lens .. lens+K``; only ``m+1`` (matches + bonus) advance
``lens``.  Stale entries beyond the new length are never read (every
attention masks by per-query length) and are overwritten when a later
round reaches those positions — no rollback pass.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .configs import ModelConfig
from .model import _block, _embed, _norm, _unembed
from .paged import (PagedKVCache, _attention_tp_manual, _layer_scales,
                    _quantize_kv)
from ..ops import rope_angles

__all__ = ["paged_verify_step", "draft_ngram", "spec_round"]


def paged_verify_step(params, cfg: ModelConfig, tokens: jnp.ndarray,
                      block_tables: jnp.ndarray, seq_lens: jnp.ndarray,
                      cache: PagedKVCache,
                      mesh=None) -> tuple[jnp.ndarray, PagedKVCache]:
    """K-token step: ``tokens`` [B, K] occupy positions
    ``seq_lens + [0..K)``; returns logits [B, K, V] and the cache with
    all K positions' KV written.

    The per-position causal structure folds into the existing per-row
    paged kernel by flattening K into the batch dim: row ``b*K + j``
    attends with length ``seq_lens[b] + j + 1`` over ``b``'s block table
    — token j sees the cache plus candidates 0..j (their KV is written
    before attention, exactly like the single-token step).
    """
    b, k = tokens.shape
    page = cache.page_size
    h = _embed(params, cfg, tokens)                        # [B, K, D]
    positions = seq_lens[:, None] + jnp.arange(k)[None, :]   # [B, K]
    cos, sin = rope_angles(positions, cfg.head_dim, cfg.rope_theta)
    pages = jnp.take_along_axis(block_tables, positions // page, axis=1)
    flat_pos = (pages * page + positions % page).reshape(-1)  # [B*K]
    attn_lens = (positions + 1).reshape(-1)                   # [B*K]
    tables_rep = jnp.repeat(block_tables, k, axis=0)          # [B*K, P]

    layers = params["layers"]
    new_k, new_v = [], []
    new_ks, new_vs = [], []
    for i in range(cfg.num_layers):
        layer = jax.tree.map(lambda x: x[i], layers)

        def attend(q, kk, vv, i=i):
            ks_i, vs_i = _layer_scales(cache, i)
            kf = kk.reshape(b * k, *kk.shape[2:])
            vf = vv.reshape(b * k, *vv.shape[2:])
            if cache.quantized:
                kq, ks_new = _quantize_kv(kf)
                vq, vs_new = _quantize_kv(vf)
                ki = cache.k[i].at[flat_pos].set(kq)
                vi = cache.v[i].at[flat_pos].set(vq)
                ks_i = ks_i.at[flat_pos].set(ks_new)
                vs_i = vs_i.at[flat_pos].set(vs_new)
                new_ks.append(ks_i)
                new_vs.append(vs_i)
            else:
                ki = cache.k[i].at[flat_pos].set(kf.astype(cache.dtype))
                vi = cache.v[i].at[flat_pos].set(vf.astype(cache.dtype))
            new_k.append(ki)
            new_v.append(vi)
            qf = q.reshape(b * k, *q.shape[2:])
            attn = _attention_tp_manual(
                qf, ki, vi, tables_rep, attn_lens, ks_i, vs_i,
                page=page, cfg=cfg, win=cfg.window_for_layer(i), mesh=mesh)
            return attn.reshape(b, k, *attn.shape[1:])

        h = _block(h, layer, cfg, cos, sin, attend)
    h = _norm(h, params["final_norm_w"], params.get("final_norm_b"), cfg)
    out_cache = PagedKVCache(
        k=tuple(new_k), v=tuple(new_v), page_size=page,
        k_scale=tuple(new_ks) if cache.quantized else None,
        v_scale=tuple(new_vs) if cache.quantized else None)
    return _unembed(params, cfg, h), out_cache


def draft_ngram(hist: jnp.ndarray, n_tok: jnp.ndarray, k: int) -> jnp.ndarray:
    """Prompt-lookup draft: for each row, find the LAST earlier occurrence
    of the trailing bigram in ``hist[: n_tok]`` and propose the ``k``
    tokens that followed it.  No-match rows get an arbitrary (recent)
    window — a useless draft only costs acceptance, never correctness.

    hist: [B, S] token history (prompt + generated so far);
    n_tok: [B] valid lengths.  Returns candidates [B, k].
    """
    b, s = hist.shape
    idx = jnp.arange(s - 1)
    a = jnp.take_along_axis(hist, (n_tok - 2)[:, None], axis=1)   # [B,1]
    bb = jnp.take_along_axis(hist, (n_tok - 1)[:, None], axis=1)
    match = ((hist[:, :-1] == a) & (hist[:, 1:] == bb)
             & (idx[None, :] < (n_tok - 2)[:, None]))             # [B, S-1]
    p = jnp.max(jnp.where(match, idx[None, :], -1), axis=1)       # [B]
    start = jnp.where(p >= 0, p + 2, jnp.maximum(n_tok - k, 0))
    gather = jnp.clip(start[:, None] + jnp.arange(k)[None, :], 0, s - 1)
    return jnp.take_along_axis(hist, gather, axis=1)              # [B, k]


def spec_round(params, cfg: ModelConfig, last_token: jnp.ndarray,
               hist: jnp.ndarray, n_tok: jnp.ndarray,
               block_tables: jnp.ndarray, seq_lens: jnp.ndarray,
               cache: PagedKVCache, k: int, mesh=None):
    """One draft+verify round (greedy).

    last_token [B, 1] is the pending input token (position ``seq_lens``).
    Returns (out_tokens [B, k+1], n_out [B] in 1..k+1, new last_token,
    hist, n_tok, seq_lens, cache) — out_tokens beyond ``n_out`` are
    padding and must be masked by the caller.
    """
    cand = draft_ngram(hist, n_tok, k)                       # [B, k]
    feed = jnp.concatenate([last_token, cand], axis=1)       # [B, k+1]
    logits, cache = paged_verify_step(params, cfg, feed, block_tables,
                                      seq_lens, cache, mesh=mesh)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)   # [B, k+1]
    # greedy[:, j] = model's token AFTER feed[:, j]; candidate j (=feed
    # j+1) is accepted iff it equals greedy[:, j] and all before matched
    ok = cand == greedy[:, :-1]                              # [B, k]
    acc = jnp.cumprod(ok.astype(jnp.int32), axis=1)          # [B, k]
    n_acc = acc.sum(axis=1)                                  # [B] 0..k
    # emitted: accepted candidates then the bonus (model argmax at the
    # first mismatch — or after all k accepts)
    bonus = jnp.take_along_axis(greedy, n_acc[:, None], axis=1)  # [B,1]
    out = jnp.where(jnp.arange(k)[None, :] < n_acc[:, None], cand, 0)
    out = jnp.concatenate([out, jnp.zeros_like(bonus)], axis=1)
    out = out.at[jnp.arange(out.shape[0]), n_acc].set(bonus[:, 0])
    n_out = n_acc + 1                                        # [B] 1..k+1
    # append to history + advance
    pos = n_tok[:, None] + jnp.arange(k + 1)[None, :]
    upd = jnp.where(jnp.arange(k + 1)[None, :] < n_out[:, None], out,
                    jnp.take_along_axis(
                        hist, jnp.clip(pos, 0, hist.shape[1] - 1), axis=1))
    hist = _scatter_rows(hist, jnp.clip(pos, 0, hist.shape[1] - 1), upd)
    n_tok = n_tok + n_out
    seq_lens = seq_lens + n_out
    last = jnp.take_along_axis(out, (n_out - 1)[:, None], axis=1)
    return out, n_out, last, hist, n_tok, seq_lens, cache


def _scatter_rows(buf: jnp.ndarray, cols: jnp.ndarray,
                  vals: jnp.ndarray) -> jnp.ndarray:
    """buf[b, cols[b, j]] = vals[b, j] (batched column scatter)."""
    b = buf.shape[0]
    rows = jnp.repeat(jnp.arange(b)[:, None], cols.shape[1], axis=1)
    return buf.at[rows, cols].set(vals)
