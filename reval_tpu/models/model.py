"""Functional decoder-only transformer (llama / gemma / starcoder2 families).

Design (TPU-first, not a port):
- **Params are a flat pytree of stacked arrays**: every per-layer weight is
  stored as ``[L, ...]`` and the layer loop is a single ``lax.scan`` — one
  layer gets traced/compiled once regardless of depth, and sharding rules
  are written once per weight name.
- **Static family flags** (``ModelConfig``) select norm/MLP/bias variants at
  trace time; there is no Python-level polymorphism inside jit.
- **Left-padded batches** throughout (see ops/attention.py): the KV cache
  decode write position is uniform across the batch, so cache updates are
  ``dynamic_update_slice`` (no scatter).
- Matmuls run in the params' dtype (bf16 on TPU) on the MXU; norms, RoPE
  and attention softmax accumulate in float32.

Weight layout: projections are stored ``[in, out]`` (``x @ w``); the HF
loader transposes torch's ``[out, in]``.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..ops import apply_rope, decode_attention, prefill_attention, rope_angles, rms_norm
from ..ops.attention import (_softcap, batched_context_prefill_attention,
                             context_prefill_attention)
from .configs import ModelConfig

__all__ = ["KVCache", "init_kv_cache", "prefill", "prefill_with_context",
           "decode_step", "logits_for_tokens"]


class KVCache(NamedTuple):
    k: jnp.ndarray  # [L, B, S, H_kv, D]
    v: jnp.ndarray  # [L, B, S, H_kv, D]


def init_kv_cache(cfg: ModelConfig, batch: int, max_seq: int, dtype=jnp.bfloat16) -> KVCache:
    shape = (cfg.num_layers, batch, max_seq, cfg.num_kv_heads, cfg.head_dim)
    return KVCache(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))


def _norm(x, w, b, cfg: ModelConfig):
    if cfg.use_layernorm:
        xf = x.astype(jnp.float32)
        mean = xf.mean(axis=-1, keepdims=True)
        var = ((xf - mean) ** 2).mean(axis=-1, keepdims=True)
        out = (xf - mean) * jax.lax.rsqrt(var + cfg.rms_norm_eps)
        out = out * w.astype(jnp.float32) + b.astype(jnp.float32)
        return out.astype(x.dtype)
    return rms_norm(x, w, cfg.rms_norm_eps, offset=cfg.norm_offset)


def _act(x, cfg: ModelConfig):
    if cfg.hidden_act in ("gelu", "gelu_pytorch_tanh", "gelu_tanh"):
        return jax.nn.gelu(x, approximate=True)
    return jax.nn.silu(x)


def _mm(x, container, name: str):
    """``x @ container[name]`` with transparent weight-only quantization.

    int8 (``<name>_scale`` [out]): the weight converts to the activation
    dtype inside the dot (XLA fuses the convert into the operand load)
    and the per-output-channel scale applies to the product — exact
    w.r.t. the dequantised weight since the scale is constant along the
    contraction dim.

    int4 (``<name>_gscale`` [G, out], models/quant.py group-wise scheme):
    the scale folds into the weight operand — convert + broadcast-
    multiply is an elementwise producer XLA fuses into the dot's operand
    load (the same fusion the int8 convert rides), and the einsum
    contracts BOTH the group and in-group dims in one f32-accumulated
    dot, so there are no per-group partial sums and no [T, G, out]
    intermediate (at 34B prefill shapes such partials would be
    a multi-GB f32 transient)."""
    w = container[name]
    gs = container.get(name + "_gscale")
    if gs is not None:
        n_groups = gs.shape[-2]
        g = w.shape[-2] // n_groups
        xg = x.reshape(*x.shape[:-1], n_groups, g)
        wdq = (w.reshape(n_groups, g, w.shape[-1]).astype(x.dtype)
               * gs.astype(x.dtype)[:, None, :])
        gz = container.get(name + "_gzero")
        if gz is not None:           # asymmetric (AWQ): w = q*s - z*s
            wdq = wdq - gz.astype(x.dtype)[:, None, :]
        out = jnp.einsum("...gi,gio->...o", xg, wdq,
                         preferred_element_type=jnp.float32)
        return out.astype(x.dtype)
    s = container.get(name + "_scale")
    if s is None:
        return x @ w
    return (x @ w.astype(x.dtype)) * s.astype(x.dtype)


def _moe_capacity(s: int, cfg: ModelConfig) -> int:
    """Static per-expert dispatch capacity for ``s`` tokens.

    Default (``moe_capacity_factor is None``): EXACT — an expert can
    receive at most one assignment per token (top-k indices are distinct
    experts), so capacity ``s`` provably fits every assignment; rounded
    up to a multiple of 8 for TPU lane tiling (slots past ``s`` are
    simply never addressed).  ``_moe_mlp_dispatch`` chunks long batches
    so this never exceeds ``MOE_DISPATCH_CHUNK``.

    Lossy opt-in (a float): ``capacity_factor``× the uniform load,
    rounded up to a multiple of 8, floored at ``top_k`` and capped at
    ``s`` — beyond it, skewed routing DROPS assignments.
    """
    if cfg.moe_capacity_factor is None:
        return -(-s // 8) * 8
    uniform = s * cfg.num_experts_per_tok / cfg.num_experts
    cap = int(-(-uniform * cfg.moe_capacity_factor // 1))
    cap = -(-max(cap, cfg.num_experts_per_tok) // 8) * 8
    return min(cap, s)


def _route(xs, layer, cfg: ModelConfig):
    """Router math, HF-mixtral-equivalent: float32 softmax over all
    experts, top-k, renormalised over the selected k."""
    router = xs.astype(jnp.float32) @ layer["router_w"].astype(jnp.float32)
    probs = jax.nn.softmax(router, axis=-1)                    # [S, E] f32
    topv, topi = jax.lax.top_k(probs, cfg.num_experts_per_tok)
    return topv / jnp.sum(topv, axis=-1, keepdims=True), topi


def _expert_w(layer, name: str, dtype):
    """Expert weight stack [E, in, out] in compute dtype; quantized
    stacks dequantise here (transient — the ragged path needs plain
    operands).  int8: per-(expert, out) scale; int4: per-(expert, group,
    out) scale (models/quant.py)."""
    w = layer[name]
    gscale = layer.get(name + "_gscale")
    if gscale is not None:
        from .quant import dequantize_grouped

        return dequantize_grouped(w, gscale, dtype)
    scale = layer.get(name + "_scale")
    if scale is None:
        return w if w.dtype == dtype else w.astype(dtype)
    return w.astype(dtype) * scale[:, None, :].astype(dtype)


def _moe_mlp_ragged(x, layer, cfg: ModelConfig):
    """Exact dropless MoE (the default): sort assignments by expert and
    run the expert FFNs as grouped matmuls via ``lax.ragged_dot``.

    Every token's top-k experts contribute, always — bit-comparable to
    HF/vLLM mixtral, and a token's output never depends on what else is
    in the batch.  Static shapes throughout ([S*K] rows, group sizes are
    data); the MXU sees one ragged-grouped GEMM per projection instead of
    ``E`` small ones.  Not ``ep``-shardable (the row partition is data-
    dependent) — engines switch to the dispatch formulation on ep meshes.
    """
    b, t, d = x.shape
    s = b * t
    e, k = cfg.num_experts, cfg.num_experts_per_tok
    xs = x.reshape(s, d)
    topv, topi = _route(xs, layer, cfg)
    flat_e = topi.reshape(-1)                                  # [S*K]
    order = jnp.argsort(flat_e)
    tok = order // k
    xs_sorted = xs[tok]
    group_sizes = jnp.bincount(flat_e, length=e).astype(jnp.int32)
    wg = _expert_w(layer, "moe_gate_w", xs.dtype)
    wu = _expert_w(layer, "moe_up_w", xs.dtype)
    wd = _expert_w(layer, "moe_down_w", xs.dtype)
    g = jax.lax.ragged_dot(xs_sorted, wg, group_sizes)
    u = jax.lax.ragged_dot(xs_sorted, wu, group_sizes)
    y = jax.lax.ragged_dot(_act(g, cfg) * u, wd, group_sizes)  # [S*K, D]
    w_sorted = topv.reshape(-1)[order]
    out = jnp.zeros((s, d), jnp.float32).at[tok].add(
        y.astype(jnp.float32) * w_sorted[:, None])
    return out.reshape(b, t, d).astype(x.dtype)


# Token-axis chunk for the EXACT dispatch MoE path: bounds the
# [E+1, cap, D] scatter buffer (cap == chunk tokens) on long prefill
# batches.  Routing is per-token and exact capacity admits every
# assignment, so chunking never changes logits.  Lossy mode (explicit
# capacity factor) never chunks: its drop rule is defined over the WHOLE
# batch, and per-chunk capacity would change which assignments drop.
MOE_DISPATCH_CHUNK = 1024


def _moe_mlp_dispatch(x, layer, cfg: ModelConfig):
    """GShard dispatch — the ``ep``-shardable MoE path.

    Assignments scatter into a dense ``[E, cap, D]`` buffer, the expert
    FFNs run as ONE batched einsum over the expert dim (the ``ep`` mesh
    axis shards that dim, see parallel/sharding.py), and results gather
    back per assignment.  By default (``moe_capacity_factor=None``) the
    capacity provably fits every assignment — EXACT, logits match the
    ragged path bit-for-bit semantics under any router skew; batches
    longer than ``MOE_DISPATCH_CHUNK`` dispatch chunk-by-chunk
    (``lax.map``) to bound the buffer.  An explicit float capacity
    factor is the lossy opt-in: assignments past ``cap`` slots are
    DROPPED (combine weight zeroed).  The single-device default is the
    exact ragged path; engines select this one only on ep>1 meshes.
    """
    b, t, d = x.shape
    s = b * t
    xs = x.reshape(s, d)
    c = MOE_DISPATCH_CHUNK
    if cfg.moe_capacity_factor is not None or s <= c:
        out = _dispatch_block(xs, layer, cfg)
    else:
        n = -(-s // c)
        xp = jnp.pad(xs, ((0, n * c - s), (0, 0)))
        out = jax.lax.map(lambda blk: _dispatch_block(blk, layer, cfg),
                          xp.reshape(n, c, d)).reshape(n * c, d)[:s]
    return out.reshape(b, t, d).astype(x.dtype)


def _dispatch_block(xs, layer, cfg: ModelConfig):
    """One dispatch round over ``[S, D]`` tokens (see caller)."""
    s, d = xs.shape
    e, k = cfg.num_experts, cfg.num_experts_per_tok
    topv, topi = _route(xs, layer, cfg)
    cap = _moe_capacity(s, cfg)

    flat_e = topi.reshape(-1)                                  # [S*K]
    onehot = jax.nn.one_hot(flat_e, e, dtype=jnp.int32)
    slot = jnp.take_along_axis(jnp.cumsum(onehot, axis=0) - onehot,
                               flat_e[:, None], axis=1)[:, 0]  # [S*K]
    ok = slot < cap
    eidx = jnp.where(ok, flat_e, e)        # overflow → scratch expert row
    sidx = jnp.minimum(slot, cap - 1)
    src = jnp.repeat(xs, k, axis=0)                            # [S*K, D]
    buf = jnp.zeros((e + 1, cap, d), xs.dtype).at[eidx, sidx].set(src)
    xe = buf[:e]                                               # [E, cap, D]

    def expert_mm(h, name, out_pattern):
        if layer.get(name + "_gscale") is not None:    # int4: transient dequant
            return jnp.einsum(out_pattern, h, _expert_w(layer, name, h.dtype))
        w = layer[name]
        scale = layer.get(name + "_scale")
        y = jnp.einsum(out_pattern, h, w.astype(h.dtype))
        if scale is not None:                  # weight-only int8 experts
            y = y * scale[:, None, :].astype(h.dtype)
        return y

    g = expert_mm(xe, "moe_gate_w", "ecd,edf->ecf")
    u = expert_mm(xe, "moe_up_w", "ecd,edf->ecf")
    y = expert_mm(_act(g, cfg) * u, "moe_down_w", "ecf,efd->ecd")

    ypad = jnp.concatenate([y, jnp.zeros((1, cap, d), y.dtype)], axis=0)
    out_a = ypad[eidx, sidx].astype(jnp.float32)               # [S*K, D]
    w_a = jnp.where(ok, topv.reshape(-1), 0.0)
    out = (out_a * w_a[:, None]).reshape(s, k, d).sum(axis=1)
    return out.astype(xs.dtype)                                # [S, D]


def _mlp(x, layer, cfg: ModelConfig):
    if cfg.num_experts:
        if cfg.moe_impl == "dispatch":
            return _moe_mlp_dispatch(x, layer, cfg)
        return _moe_mlp_ragged(x, layer, cfg)
    if cfg.mlp_gated:
        gate = _mm(x, layer, "gate_w")
        up = _mm(x, layer, "up_w")
        return _mm(_act(gate, cfg) * up, layer, "down_w")
    h = _mm(x, layer, "fc_w")
    if cfg.mlp_bias:
        h = h + layer["fc_b"]
    h = _act(h, cfg)
    out = _mm(h, layer, "proj_w")
    if cfg.mlp_bias:
        out = out + layer["proj_b"]
    return out


def _qkv(x, layer, cfg: ModelConfig):
    b, t, _ = x.shape
    q = _mm(x, layer, "q_w")
    k = _mm(x, layer, "k_w")
    v = _mm(x, layer, "v_w")
    if cfg.attention_bias:
        q, k, v = q + layer["q_b"], k + layer["k_b"], v + layer["v_b"]
    q = q.reshape(b, t, cfg.num_heads, cfg.head_dim)
    k = k.reshape(b, t, cfg.num_kv_heads, cfg.head_dim)
    v = v.reshape(b, t, cfg.num_kv_heads, cfg.head_dim)
    return q, k, v


def _out_proj(attn_out, layer, cfg: ModelConfig):
    b, t = attn_out.shape[:2]
    out = _mm(attn_out.reshape(b, t, cfg.num_heads * cfg.head_dim), layer, "o_w")
    if cfg.attention_bias:
        out = out + layer["o_b"]
    return out


def _block(h, layer, cfg: ModelConfig, cos, sin, attend):
    """One transformer block (norm → qkv → rope → attention → out-proj →
    norm → mlp, pre-norm residuals) — THE block wiring, shared by every
    forward variant (contiguous prefill/decode, paged decode, pipelined
    stages).  ``attend(q, k, v) -> attn_out [B, T, H, D]`` supplies the
    attention and owns any cache read/write (callers stash the rotated
    k/v from inside the callback when they need to commit them)."""
    normed = _norm(h, layer["attn_norm_w"], layer.get("attn_norm_b"), cfg)
    q, k, v = _qkv(normed, layer, cfg)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    attn = attend(q, k, v)
    out = _out_proj(attn, layer, cfg)
    if cfg.use_post_norms:                       # gemma-2 sandwich norms
        out = _norm(out, layer["post_attn_norm_w"], None, cfg)
    h = h + out
    normed = _norm(h, layer["mlp_norm_w"], layer.get("mlp_norm_b"), cfg)
    out = _mlp(normed, layer, cfg)
    if cfg.use_post_norms:
        out = _norm(out, layer["post_mlp_norm_w"], None, cfg)
    return h + out


def _embed(params, cfg: ModelConfig, tokens):
    h = params["embed"][tokens]
    if cfg.embed_scale is not None:
        h = (h.astype(jnp.float32) * cfg.embed_scale).astype(h.dtype)
    return h


def _unembed(params, cfg: ModelConfig, h):
    if cfg.tie_word_embeddings:
        logits = (h @ params["embed"].T).astype(jnp.float32)
    else:
        logits = _mm(h, params, "lm_head").astype(jnp.float32)
    return _softcap(logits, cfg.final_softcap)   # gemma-2 logit softcapping


def prefill(params, cfg: ModelConfig, tokens: jnp.ndarray, pad_len: jnp.ndarray,
            cache: KVCache, logits_mode: str = "all", attend_fn=None,
            constrain=None, collect_hiddens: bool = False):
    """Process a left-padded prompt block [B, T]; fill cache positions
    [0, T); return logits and the updated cache.

    ``logits_mode``: "all" → [B, T, V] (parity tests, scoring); "last" →
    [B, 1, V] for the final position only — generation needs nothing else,
    and skipping the [B, T, V] unembed matmul removes the single largest
    waste in prefill (T× the needed FLOPs into the vocab dimension).

    ``attend_fn(q, k, v, win)`` overrides the attention (the only piece
    that varies across prefill deployments — the sequence-parallel path
    swaps in ring attention); ``win`` is the layer's traced sliding-window
    size (sentinel-big = full causal), threaded so windowed models work
    under any attention override.  ``constrain(h)`` (optional)
    re-annotates the activation sharding after embed and every layer.

    ``collect_hiddens=True`` (fidelity tests only — static flag, so the
    generation path compiles without it) additionally returns the
    pre-final-norm hidden states after every layer, ``[L, B, T, D]``.
    For layers ``l < L-1`` these equal ``transformers``'
    ``output_hidden_states`` entries ``hidden_states[l+1]``; HF's LAST
    entry has the final norm already applied, so the last layer compares
    through the logits instead (see tests/test_bf16_fidelity.py).
    """
    b, t = tokens.shape
    h = _embed(params, cfg, tokens)
    if constrain is not None:
        h = constrain(h)
    positions = jnp.maximum(jnp.arange(t)[None, :] - pad_len[:, None], 0)
    cos, sin = rope_angles(positions, cfg.head_dim, cfg.rope_theta)
    # per-layer windows ride the scan as an [L] array (gemma-2 alternates
    # sliding/global; other models get a uniform value, sentinel-big for
    # none) — a traced window behaves identically in the masks
    wins = cfg.layer_windows_array()

    def default_attend(win):
        def f(q, k, v):
            return prefill_attention(q, k, v, pad_len, scale=cfg.attn_scale,
                                     window=win, softcap=cfg.attn_softcap)
        return f

    def layer_step(h, xs):
        layer, k_slot, v_slot, win = xs
        kv = {}
        inner = ((lambda q, k, v: attend_fn(q, k, v, win))
                 if attend_fn is not None else default_attend(win))

        def attend(q, k, v):
            kv["k"] = jax.lax.dynamic_update_slice(
                k_slot, k.astype(k_slot.dtype), (0, 0, 0, 0))
            kv["v"] = jax.lax.dynamic_update_slice(
                v_slot, v.astype(v_slot.dtype), (0, 0, 0, 0))
            return inner(q, k, v)

        h = _block(h, layer, cfg, cos, sin, attend)
        if constrain is not None:
            h = constrain(h)
        ys = (kv["k"], kv["v"], h) if collect_hiddens else (kv["k"], kv["v"])
        return h, ys

    h, ys = jax.lax.scan(
        layer_step, h, (params["layers"], cache.k, cache.v, wins))
    new_k, new_v = ys[0], ys[1]
    h = _norm(h, params["final_norm_w"], params.get("final_norm_b"), cfg)
    if logits_mode == "last":
        h = h[:, -1:, :]   # left-padding puts every row's final token last
    logits = _unembed(params, cfg, h)
    if collect_hiddens:
        return logits, KVCache(new_k, new_v), ys[2]
    return logits, KVCache(new_k, new_v)


def prefill_with_context(params, cfg: ModelConfig, tokens: jnp.ndarray,
                         pad_len: jnp.ndarray, ctx: KVCache, cache: KVCache,
                         logits_mode: str = "last") -> tuple[jnp.ndarray, KVCache]:
    """Prefill a left-padded suffix block [B, T] that follows a shared
    context whose KV is already computed.

    ``ctx``: KVCache of the common prompt prefix ([L, 1, Tc, H_kv, D],
    broadcast over rows).  Suffix sequence positions start at Tc.  Returns
    logits and the suffix KV (cache positions [0, T) = sequence positions
    [Tc, Tc+T)) — the shared-prefix prefill path: the context is computed
    once per batch instead of once per row (DREval few-shot templates are
    50-72% of every prompt).
    """
    b, t = tokens.shape
    tc = ctx.k.shape[2]
    h = _embed(params, cfg, tokens)
    positions = tc + jnp.maximum(jnp.arange(t)[None, :] - pad_len[:, None], 0)
    cos, sin = rope_angles(positions, cfg.head_dim, cfg.rope_theta)
    wins = cfg.layer_windows_array()

    def layer_step(h, xs):
        layer, ctx_k, ctx_v, k_slot, v_slot, win = xs
        kv = {}

        def attend(q, k, v):
            kv["k"] = jax.lax.dynamic_update_slice(
                k_slot, k.astype(k_slot.dtype), (0, 0, 0, 0))
            kv["v"] = jax.lax.dynamic_update_slice(
                v_slot, v.astype(v_slot.dtype), (0, 0, 0, 0))
            return context_prefill_attention(q, k, v, ctx_k, ctx_v, pad_len,
                                             scale=cfg.attn_scale, window=win,
                                             softcap=cfg.attn_softcap)

        h = _block(h, layer, cfg, cos, sin, attend)
        return h, (kv["k"], kv["v"])

    h, (new_k, new_v) = jax.lax.scan(
        layer_step, h, (params["layers"], ctx.k, ctx.v, cache.k, cache.v, wins))
    h = _norm(h, params["final_norm_w"], params.get("final_norm_b"), cfg)
    if logits_mode == "last":
        h = h[:, -1:, :]
    return _unembed(params, cfg, h), KVCache(new_k, new_v)


def prefill_with_batched_context(params, cfg: ModelConfig,
                                 tokens: jnp.ndarray, pad_len: jnp.ndarray,
                                 ctx_k: jnp.ndarray, ctx_v: jnp.ndarray,
                                 ctx_len: jnp.ndarray, cache: KVCache,
                                 logits_mode: str = "last",
                                 ) -> tuple[jnp.ndarray, KVCache]:
    """Prefill left-padded suffix blocks [B, T] where each row follows its
    OWN cached context — the multi-prefix sibling of
    :func:`prefill_with_context` (which broadcasts ONE context over the
    batch).

    ``ctx_k``/``ctx_v``: ``[L, B, Tc, H_kv, D]`` per-row context KV (rows
    padded past ``ctx_len[b]`` are masked — the paged engine gathers them
    from pool pages, see models/paged.py:gather_prefix_context).  Row
    ``b``'s suffix positions start at ``ctx_len[b]``.  Returns logits and
    the suffix KV (cache positions [0, T) = row sequence positions
    [ctx_len, ctx_len + T)).
    """
    b, t = tokens.shape
    h = _embed(params, cfg, tokens)
    positions = (ctx_len[:, None]
                 + jnp.maximum(jnp.arange(t)[None, :] - pad_len[:, None], 0))
    cos, sin = rope_angles(positions, cfg.head_dim, cfg.rope_theta)
    wins = cfg.layer_windows_array()

    def layer_step(h, xs):
        layer, ck, cv, k_slot, v_slot, win = xs
        kv = {}

        def attend(q, k, v):
            kv["k"] = jax.lax.dynamic_update_slice(
                k_slot, k.astype(k_slot.dtype), (0, 0, 0, 0))
            kv["v"] = jax.lax.dynamic_update_slice(
                v_slot, v.astype(v_slot.dtype), (0, 0, 0, 0))
            return batched_context_prefill_attention(
                q, k, v, ck, cv, ctx_len, pad_len, scale=cfg.attn_scale,
                window=win, softcap=cfg.attn_softcap)

        h = _block(h, layer, cfg, cos, sin, attend)
        return h, (kv["k"], kv["v"])

    h, (new_k, new_v) = jax.lax.scan(
        layer_step, h, (params["layers"], ctx_k, ctx_v, cache.k, cache.v, wins))
    h = _norm(h, params["final_norm_w"], params.get("final_norm_b"), cfg)
    if logits_mode == "last":
        h = h[:, -1:, :]
    return _unembed(params, cfg, h), KVCache(new_k, new_v)


def decode_step(params, cfg: ModelConfig, token: jnp.ndarray, pad_len: jnp.ndarray,
                cache: KVCache, cur_pos: jnp.ndarray) -> tuple[jnp.ndarray, KVCache]:
    """One decode step: token [B, 1] at shared position ``cur_pos``; write
    cache at cur_pos, attend over [pad_len, cur_pos]; logits [B, V].

    The layer loop is UNROLLED, unlike prefill's ``lax.scan``: scanning
    with the cache as xs/ys stacks a fresh output cache every step — a
    full cache copy per token (measured 31 → 17.5 ms/step at B=8, S=1024
    on the 1.3b shape).  Unrolled, the per-layer writes are
    ``dynamic_update_slice`` on the donated buffer and the reads fuse
    into the attention.  Prefill keeps the scan: its whole cache is
    freshly written each call, so the stacked ys ARE the output, and one
    traced layer keeps compile time flat."""
    h = _embed(params, cfg, token)
    positions = jnp.maximum(cur_pos - pad_len, 0)[:, None]
    cos, sin = rope_angles(positions, cfg.head_dim, cfg.rope_theta)

    ck, cv = cache.k, cache.v
    layers = params["layers"]
    for i in range(cfg.num_layers):
        layer = jax.tree.map(lambda x: x[i], layers)

        def attend(q, k, v, i=i):
            nonlocal ck, cv
            ck = jax.lax.dynamic_update_slice(
                ck, k[None].astype(ck.dtype), (i, 0, cur_pos, 0, 0))
            cv = jax.lax.dynamic_update_slice(
                cv, v[None].astype(cv.dtype), (i, 0, cur_pos, 0, 0))
            return decode_attention(q, ck[i], cv[i], pad_len, cur_pos,
                                    scale=cfg.attn_scale,
                                    window=cfg.window_for_layer(i),
                                    softcap=cfg.attn_softcap)

        h = _block(h, layer, cfg, cos, sin, attend)
    h = _norm(h, params["final_norm_w"], params.get("final_norm_b"), cfg)
    return _unembed(params, cfg, h)[:, 0, :], KVCache(ck, cv)


def logits_for_tokens(params, cfg: ModelConfig, tokens: jnp.ndarray) -> jnp.ndarray:
    """Convenience full-sequence forward (no cache) for parity tests."""
    b, t = tokens.shape
    cache = init_kv_cache(cfg, b, t, dtype=params["embed"].dtype)
    logits, _ = prefill(params, cfg, tokens, jnp.zeros(b, jnp.int32), cache)
    return logits
