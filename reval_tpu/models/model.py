"""Functional decoder-only transformer (llama / gemma / starcoder2 families).

Design (TPU-first, not a port):
- **Params are a flat pytree of stacked arrays**: every per-layer weight is
  stored as ``[L, ...]`` and the layer loop is a single ``lax.scan`` — one
  layer gets traced/compiled once regardless of depth, and sharding rules
  are written once per weight name.
- **Static family flags** (``ModelConfig``) select norm/MLP/bias variants at
  trace time; there is no Python-level polymorphism inside jit.
- **Left-padded batches** throughout (see ops/attention.py): the KV cache
  decode write position is uniform across the batch, so cache updates are
  ``dynamic_update_slice`` (no scatter).
- Matmuls run in the params' dtype (bf16 on TPU) on the MXU; norms, RoPE
  and attention softmax accumulate in float32.

Weight layout: projections are stored ``[in, out]`` (``x @ w``); the HF
loader transposes torch's ``[out, in]``.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..ops import apply_rope, decode_attention, prefill_attention, rope_angles, rms_norm
from ..ops.attention import context_prefill_attention
from .configs import ModelConfig

__all__ = ["KVCache", "init_kv_cache", "prefill", "prefill_with_context",
           "decode_step", "logits_for_tokens"]


class KVCache(NamedTuple):
    k: jnp.ndarray  # [L, B, S, H_kv, D]
    v: jnp.ndarray  # [L, B, S, H_kv, D]


def init_kv_cache(cfg: ModelConfig, batch: int, max_seq: int, dtype=jnp.bfloat16) -> KVCache:
    shape = (cfg.num_layers, batch, max_seq, cfg.num_kv_heads, cfg.head_dim)
    return KVCache(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))


def _norm(x, w, b, cfg: ModelConfig):
    if cfg.use_layernorm:
        xf = x.astype(jnp.float32)
        mean = xf.mean(axis=-1, keepdims=True)
        var = ((xf - mean) ** 2).mean(axis=-1, keepdims=True)
        out = (xf - mean) * jax.lax.rsqrt(var + cfg.rms_norm_eps)
        out = out * w.astype(jnp.float32) + b.astype(jnp.float32)
        return out.astype(x.dtype)
    return rms_norm(x, w, cfg.rms_norm_eps, offset=cfg.norm_offset)


def _act(x, cfg: ModelConfig):
    if cfg.hidden_act in ("gelu", "gelu_pytorch_tanh", "gelu_tanh"):
        return jax.nn.gelu(x, approximate=True)
    return jax.nn.silu(x)


def _mm(x, container, name: str):
    """``x @ container[name]`` with transparent weight-only int8: when a
    ``<name>_scale`` leaf rides along (models/quant.py), the int8 weight
    converts to the activation dtype inside the dot (XLA fuses the
    convert into the operand load) and the per-output-channel scale
    applies to the product — exact w.r.t. the dequantised weight since
    the scale is constant along the contraction dim."""
    w = container[name]
    s = container.get(name + "_scale")
    if s is None:
        return x @ w
    return (x @ w.astype(x.dtype)) * s.astype(x.dtype)


def _mlp(x, layer, cfg: ModelConfig):
    if cfg.mlp_gated:
        gate = _mm(x, layer, "gate_w")
        up = _mm(x, layer, "up_w")
        return _mm(_act(gate, cfg) * up, layer, "down_w")
    h = _mm(x, layer, "fc_w")
    if cfg.mlp_bias:
        h = h + layer["fc_b"]
    h = _act(h, cfg)
    out = _mm(h, layer, "proj_w")
    if cfg.mlp_bias:
        out = out + layer["proj_b"]
    return out


def _qkv(x, layer, cfg: ModelConfig):
    b, t, _ = x.shape
    q = _mm(x, layer, "q_w")
    k = _mm(x, layer, "k_w")
    v = _mm(x, layer, "v_w")
    if cfg.attention_bias:
        q, k, v = q + layer["q_b"], k + layer["k_b"], v + layer["v_b"]
    q = q.reshape(b, t, cfg.num_heads, cfg.head_dim)
    k = k.reshape(b, t, cfg.num_kv_heads, cfg.head_dim)
    v = v.reshape(b, t, cfg.num_kv_heads, cfg.head_dim)
    return q, k, v


def _out_proj(attn_out, layer, cfg: ModelConfig):
    b, t = attn_out.shape[:2]
    out = _mm(attn_out.reshape(b, t, cfg.num_heads * cfg.head_dim), layer, "o_w")
    if cfg.attention_bias:
        out = out + layer["o_b"]
    return out


def _embed(params, cfg: ModelConfig, tokens):
    h = params["embed"][tokens]
    if cfg.embed_scale is not None:
        h = (h.astype(jnp.float32) * cfg.embed_scale).astype(h.dtype)
    return h


def _unembed(params, cfg: ModelConfig, h):
    if cfg.tie_word_embeddings:
        return (h @ params["embed"].T).astype(jnp.float32)
    return _mm(h, params, "lm_head").astype(jnp.float32)


def prefill(params, cfg: ModelConfig, tokens: jnp.ndarray, pad_len: jnp.ndarray,
            cache: KVCache, logits_mode: str = "all") -> tuple[jnp.ndarray, KVCache]:
    """Process a left-padded prompt block [B, T]; fill cache positions
    [0, T); return logits and the updated cache.

    ``logits_mode``: "all" → [B, T, V] (parity tests, scoring); "last" →
    [B, 1, V] for the final position only — generation needs nothing else,
    and skipping the [B, T, V] unembed matmul removes the single largest
    waste in prefill (T× the needed FLOPs into the vocab dimension).
    """
    b, t = tokens.shape
    h = _embed(params, cfg, tokens)
    positions = jnp.maximum(jnp.arange(t)[None, :] - pad_len[:, None], 0)
    cos, sin = rope_angles(positions, cfg.head_dim, cfg.rope_theta)

    def layer_step(h, xs):
        layer, k_slot, v_slot = xs
        normed = _norm(h, layer["attn_norm_w"], layer.get("attn_norm_b"), cfg)
        q, k, v = _qkv(normed, layer, cfg)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        new_k = jax.lax.dynamic_update_slice(k_slot, k.astype(k_slot.dtype), (0, 0, 0, 0))
        new_v = jax.lax.dynamic_update_slice(v_slot, v.astype(v_slot.dtype), (0, 0, 0, 0))
        attn = prefill_attention(q, k, v, pad_len, window=cfg.sliding_window)
        h = h + _out_proj(attn, layer, cfg)
        normed = _norm(h, layer["mlp_norm_w"], layer.get("mlp_norm_b"), cfg)
        h = h + _mlp(normed, layer, cfg)
        return h, (new_k, new_v)

    h, (new_k, new_v) = jax.lax.scan(layer_step, h, (params["layers"], cache.k, cache.v))
    h = _norm(h, params["final_norm_w"], params.get("final_norm_b"), cfg)
    if logits_mode == "last":
        h = h[:, -1:, :]   # left-padding puts every row's final token last
    return _unembed(params, cfg, h), KVCache(new_k, new_v)


def prefill_with_context(params, cfg: ModelConfig, tokens: jnp.ndarray,
                         pad_len: jnp.ndarray, ctx: KVCache, cache: KVCache,
                         logits_mode: str = "last") -> tuple[jnp.ndarray, KVCache]:
    """Prefill a left-padded suffix block [B, T] that follows a shared
    context whose KV is already computed.

    ``ctx``: KVCache of the common prompt prefix ([L, 1, Tc, H_kv, D],
    broadcast over rows).  Suffix sequence positions start at Tc.  Returns
    logits and the suffix KV (cache positions [0, T) = sequence positions
    [Tc, Tc+T)) — the shared-prefix prefill path: the context is computed
    once per batch instead of once per row (DREval few-shot templates are
    50-72% of every prompt).
    """
    b, t = tokens.shape
    tc = ctx.k.shape[2]
    h = _embed(params, cfg, tokens)
    positions = tc + jnp.maximum(jnp.arange(t)[None, :] - pad_len[:, None], 0)
    cos, sin = rope_angles(positions, cfg.head_dim, cfg.rope_theta)

    def layer_step(h, xs):
        layer, ctx_k, ctx_v, k_slot, v_slot = xs
        normed = _norm(h, layer["attn_norm_w"], layer.get("attn_norm_b"), cfg)
        q, k, v = _qkv(normed, layer, cfg)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        new_k = jax.lax.dynamic_update_slice(k_slot, k.astype(k_slot.dtype), (0, 0, 0, 0))
        new_v = jax.lax.dynamic_update_slice(v_slot, v.astype(v_slot.dtype), (0, 0, 0, 0))
        attn = context_prefill_attention(q, k, v, ctx_k, ctx_v, pad_len,
                                         window=cfg.sliding_window)
        h = h + _out_proj(attn, layer, cfg)
        normed = _norm(h, layer["mlp_norm_w"], layer.get("mlp_norm_b"), cfg)
        h = h + _mlp(normed, layer, cfg)
        return h, (new_k, new_v)

    h, (new_k, new_v) = jax.lax.scan(
        layer_step, h, (params["layers"], ctx.k, ctx.v, cache.k, cache.v))
    h = _norm(h, params["final_norm_w"], params.get("final_norm_b"), cfg)
    if logits_mode == "last":
        h = h[:, -1:, :]
    return _unembed(params, cfg, h), KVCache(new_k, new_v)


def decode_step(params, cfg: ModelConfig, token: jnp.ndarray, pad_len: jnp.ndarray,
                cache: KVCache, cur_pos: jnp.ndarray) -> tuple[jnp.ndarray, KVCache]:
    """One decode step: token [B, 1] at shared position ``cur_pos``; write
    cache at cur_pos, attend over [pad_len, cur_pos]; logits [B, V].

    The layer loop is UNROLLED, unlike prefill's ``lax.scan``: scanning
    with the cache as xs/ys stacks a fresh output cache every step — a
    full cache copy per token (measured 31 → 17.5 ms/step at B=8, S=1024
    on the 1.3b shape).  Unrolled, the per-layer writes are
    ``dynamic_update_slice`` on the donated buffer and the reads fuse
    into the attention.  Prefill keeps the scan: its whole cache is
    freshly written each call, so the stacked ys ARE the output, and one
    traced layer keeps compile time flat."""
    h = _embed(params, cfg, token)
    positions = jnp.maximum(cur_pos - pad_len, 0)[:, None]
    cos, sin = rope_angles(positions, cfg.head_dim, cfg.rope_theta)

    ck, cv = cache.k, cache.v
    layers = params["layers"]
    for i in range(cfg.num_layers):
        layer = jax.tree.map(lambda x: x[i], layers)
        normed = _norm(h, layer["attn_norm_w"], layer.get("attn_norm_b"), cfg)
        q, k, v = _qkv(normed, layer, cfg)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        ck = jax.lax.dynamic_update_slice(
            ck, k[None].astype(ck.dtype), (i, 0, cur_pos, 0, 0))
        cv = jax.lax.dynamic_update_slice(
            cv, v[None].astype(cv.dtype), (i, 0, cur_pos, 0, 0))
        attn = decode_attention(q, ck[i], cv[i], pad_len, cur_pos,
                                window=cfg.sliding_window)
        h = h + _out_proj(attn, layer, cfg)
        normed = _norm(h, layer["mlp_norm_w"], layer.get("mlp_norm_b"), cfg)
        h = h + _mlp(normed, layer, cfg)
    h = _norm(h, params["final_norm_w"], params.get("final_norm_b"), cfg)
    return _unembed(params, cfg, h)[:, 0, :], KVCache(ck, cv)


def logits_for_tokens(params, cfg: ModelConfig, tokens: jnp.ndarray) -> jnp.ndarray:
    """Convenience full-sequence forward (no cache) for parity tests."""
    b, t = tokens.shape
    cache = init_kv_cache(cfg, b, t, dtype=params["embed"].dtype)
    logits, _ = prefill(params, cfg, tokens, jnp.zeros(b, jnp.int32), cache)
    return logits
