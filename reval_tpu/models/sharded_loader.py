"""Sharded HF checkpoint loading: every device reads ONLY its slice.

``load_checkpoint`` materialises the full params tree on every host and
then ``device_put``s shards — fine up to ~7 B, but a 34 B/70 B checkpoint
(BASELINE.json configs[3]-[4]: DeepSeek-33B on v5e-8, CodeLlama-70B on
v5p-16) would put 70-140 GB through every host's RAM before the mesh ever
sees a byte.  The reference leans on vLLM's per-rank weight loader for
the same problem (SURVEY §7 hard part 6).

TPU-native version: ``jax.make_array_from_callback`` drives the read —
JAX hands the callback the index (a tuple of slices in OUR layout) for
each addressable shard, and the callback pulls exactly that range from
safetensors via ``get_slice`` (no full-tensor read; transposition maps
the range onto HF's ``[out, in]`` storage).  Multi-host falls out: each
process only materialises its own devices' shards, and the resulting
``jax.Array``s are global views over the mesh.

Weight-only int8 (``dtype="int8"``) is NOT supported here: per-channel
scales need a global amax over a dim that tensor parallelism may shard,
so quantize-then-shard must see whole tensors — use ``load_checkpoint``
for int8 (its models fit single-host RAM by construction).
"""

from __future__ import annotations

from pathlib import Path

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding

from .configs import ModelConfig, load_hf_config
from .loader import _DTYPES, _TOP_LEVEL, _ShardedReader, _weight_map, param_template

__all__ = ["load_checkpoint_sharded"]


class _SliceReader(_ShardedReader):
    """Adds ranged reads on top of the by-name shard index."""

    def get_range(self, name: str, idx: tuple[slice, ...],
                  transpose: bool) -> np.ndarray:
        path = self.files[name]
        if path not in self._handles:
            self._handles[path] = self._open(path, framework="numpy")
        sl = self._handles[path].get_slice(name)
        if transpose:
            assert len(idx) == 2, "transpose only applies to 2-D projections"
            out = sl[idx[1], idx[0]]
            return np.asarray(out).T
        return np.asarray(sl[idx])


def _resolve(idx: tuple[slice, ...], shape: tuple[int, ...]) -> tuple[tuple[int, int, int], ...]:
    """Concretise the (possibly open-ended) slices JAX hands the callback
    into (start, stop, step) int tuples — hashable on every supported
    Python (slice objects only hash from 3.12)."""
    return tuple(s.indices(dim) for s, dim in zip(idx, shape))


def _slices(key: tuple[tuple[int, int, int], ...]) -> tuple[slice, ...]:
    return tuple(slice(*t) for t in key)


def load_checkpoint_sharded(model_path: str | Path, mesh: Mesh,
                            dtype: str = "bfloat16",
                            cfg: ModelConfig | None = None,
                            specs_fn=None):
    """Load an HF checkpoint directly into mesh-sharded ``jax.Array``s.

    Returns (params, cfg) like ``load_checkpoint``, but no host ever
    holds more than its own devices' shards (plus replicated leaves).
    ``specs_fn`` overrides the sharding-rule function (default
    ``parallel.sharding.param_specs``; the pipelined engine passes
    ``pp_param_specs`` so each host reads only its stages' layers).
    """
    if dtype == "int8":
        raise ValueError(
            "int8 needs whole-tensor amax before sharding; use "
            "load_checkpoint(dtype='int8') and shard_params instead")
    from ..parallel.sharding import param_specs

    model_path = Path(model_path)
    cfg = cfg or load_hf_config(model_path)
    cfg.dtype = dtype
    target = _DTYPES[dtype]
    reader = _SliceReader(model_path)
    template = param_template(cfg)
    if cfg.tie_word_embeddings or _TOP_LEVEL["lm_head"][0] not in reader:
        template.pop("lm_head", None)
        cfg.tie_word_embeddings = True
    specs = (specs_fn or param_specs)(template, cfg, mesh)
    wmap = _weight_map(cfg)

    def top_leaf(name: str, shape) -> jax.Array:
        hf_name, transpose = _TOP_LEVEL[name]
        sharding = NamedSharding(mesh, specs[name])
        cache: dict = {}

        def cb(idx):
            key = _resolve(idx, shape)
            if key not in cache:
                cache[key] = reader.get_range(hf_name, _slices(key), transpose
                                              ).astype(np.float32).astype(target)
            return cache[key]

        return jax.make_array_from_callback(tuple(shape), sharding, cb)

    def layer_leaf(name: str, shape) -> jax.Array:
        """Stacked [L, ...] leaf assembled from per-layer HF tensors; the
        callback reads exactly the layer range JAX asks for, so a
        ``pp``-sharded layer dim means each host reads only its own
        stages' tensors.  MoE expert stacks
        ([L, E, in, out], ``{e}`` in the template) additionally iterate
        the callback's expert range — an ``ep``-sharded mesh then makes
        each host read only its own experts' tensors."""
        hf_template, transpose = wmap[name]
        sharding = NamedSharding(mesh, specs["layers"][name])
        cache: dict = {}

        def cb(idx):
            key = _resolve(idx, shape)
            if key not in cache:
                layer_rng = range(*key[0])
                if "{e}" in hf_template:
                    parts = [
                        np.stack([reader.get_range(
                            hf_template.format(i=i, e=e),
                            _slices(key[2:]), transpose)
                            for e in range(*key[1])])
                        for i in layer_rng]
                else:
                    parts = [reader.get_range(hf_template.format(i=i),
                                              _slices(key[1:]), transpose)
                             for i in layer_rng]
                cache[key] = np.stack(parts).astype(np.float32).astype(target)
            return cache[key]

        return jax.make_array_from_callback(tuple(shape), sharding, cb)

    params: dict = {"layers": {}}
    for name, shape in template.items():
        if name == "layers":
            for k, shp in shape.items():
                if k not in wmap or wmap[k][0].format(i=0, e=0) not in reader:
                    continue           # optional weight absent (e.g. biases)
                params["layers"][k] = layer_leaf(k, shp)
        else:
            params[name] = top_leaf(name, shape)
    return params, cfg
