"""Sharded HF checkpoint loading: every device reads ONLY its slice.

``load_checkpoint`` materialises the full params tree on every host and
then ``device_put``s shards — fine up to ~7 B, but a 34 B/70 B checkpoint
(BASELINE.json configs[3]-[4]: DeepSeek-33B on v5e-8, CodeLlama-70B on
v5p-16) would put 70-140 GB through every host's RAM before the mesh ever
sees a byte.  The reference leans on vLLM's per-rank weight loader for
the same problem (SURVEY §7 hard part 6).

TPU-native version: ``jax.make_array_from_callback`` drives the read —
JAX hands the callback the index (a tuple of slices in OUR layout) for
each addressable shard, and the callback pulls exactly that range from
safetensors via ``get_slice`` (no full-tensor read; transposition maps
the range onto HF's ``[out, in]`` storage).  Multi-host falls out: each
process only materialises its own devices' shards, and the resulting
``jax.Array``s are global views over the mesh.

Weight-only int8 (``dtype="int8"``) is NOT supported here: per-channel
scales need a global amax over the WHOLE contraction dim, which tensor
parallelism shards — use ``load_checkpoint`` for int8 (its models fit
single-host RAM by construction).

Weight-only int4 (``dtype="int4"``) IS supported — this loader is how
the 34B CoT flagship actually reaches a v5e-8 (PERF.md HBM table; the
full-tree path would put 17 GB bf16 leaves through one device).  int4's
group scales are LOCAL to ``g`` consecutive contraction values, so each
shard quantizes its own slice: the per-leaf group size is chosen to
divide the shard's contraction slice (``_group_size_for(in/tp)``), which
makes group boundaries align with shard boundaries — shard-local
quantization is then bit-identical to quantizing the whole tensor at
that group size.
"""

from __future__ import annotations

from pathlib import Path

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding

from .configs import ModelConfig, load_hf_config
from .loader import _DTYPES, _TOP_LEVEL, _ShardedReader, _weight_map, param_template

__all__ = ["load_checkpoint_sharded"]


class _SliceReader(_ShardedReader):
    """Adds ranged reads on top of the by-name shard index."""

    def get_range(self, name: str, idx: tuple[slice, ...],
                  transpose: bool) -> np.ndarray:
        path = self.files[name]
        if path not in self._handles:
            self._handles[path] = self._open(path, framework="numpy")
        sl = self._handles[path].get_slice(name)
        if transpose:
            assert len(idx) == 2, "transpose only applies to 2-D projections"
            out = sl[idx[1], idx[0]]
            return np.asarray(out).T
        return np.asarray(sl[idx])


def _resolve(idx: tuple[slice, ...], shape: tuple[int, ...]) -> tuple[tuple[int, int, int], ...]:
    """Concretise the (possibly open-ended) slices JAX hands the callback
    into (start, stop, step) int tuples — hashable on every supported
    Python (slice objects only hash from 3.12)."""
    return tuple(s.indices(dim) for s, dim in zip(idx, shape))


def _slices(key: tuple[tuple[int, int, int], ...]) -> tuple[slice, ...]:
    return tuple(slice(*t) for t in key)


# mesh: axes=()
def load_checkpoint_sharded(model_path: str | Path, mesh: Mesh,
                            dtype: str = "bfloat16",
                            cfg: ModelConfig | None = None,
                            specs_fn=None):
    """Load an HF checkpoint directly into mesh-sharded ``jax.Array``s.

    Returns (params, cfg) like ``load_checkpoint``, but no host ever
    holds more than its own devices' shards (plus replicated leaves).
    ``specs_fn`` overrides the sharding-rule function (default
    ``parallel.sharding.param_specs``; the pipelined engine passes
    ``pp_param_specs`` so each host reads only its stages' layers).
    """
    if dtype == "int8":
        raise ValueError(
            "int8 needs whole-tensor amax before sharding; use "
            "load_checkpoint(dtype='int8') and shard_params instead")
    from ..parallel.sharding import param_specs
    from .awq import awq_config, gptq_config

    if awq_config(model_path) or gptq_config(model_path):
        # AWQ/GPTQ tensors (qweight/qzeros/scales packing) have no slice-read
        # path yet: fall back to full-tree ingest + shard.  Host-RAM cost
        # is the UNPACKED int4 tree (ml_dtypes.int4 stores one byte per
        # element: ~34 GB for 34B plus a largest-leaf transient — fits a
        # 100+ GB host, NOT a laptop), still well under the bf16 tree
        # the slice path exists to avoid.  A checkpoint whose fixed
        # group size misaligns with a tp shard gets its gscale/gzero
        # group dim replicated by param_specs' fit() (with a warning)
        # and pays a GSPMD reshard in _mm — correct, slower.
        from .loader import load_checkpoint

        params, cfg = load_checkpoint(model_path, dtype=dtype, cfg=cfg)
        specs = (specs_fn or param_specs)(params, cfg, mesh)
        params = jax.tree_util.tree_map(
            lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh, spec)),
            params, specs, is_leaf=lambda x: not isinstance(x, dict))
        return params, cfg

    int4 = dtype == "int4"
    if int4:
        dtype = "bfloat16"
    model_path = Path(model_path)
    cfg = cfg or load_hf_config(model_path)
    cfg.dtype = dtype
    target = _DTYPES[dtype]
    reader = _SliceReader(model_path)
    template = param_template(cfg)
    if cfg.tie_word_embeddings or _TOP_LEVEL["lm_head"][0] not in reader:
        template.pop("lm_head", None)
        cfg.tie_word_embeddings = True
    wmap = _weight_map(cfg)

    g_eff: dict[str, int] = {}
    if int4:
        # per-leaf group size dividing the shard's contraction slice, so
        # shard-local quantization == whole-tensor quantization at that g
        from .quant import GROUP_SIZE, MATMUL_WEIGHTS, _group_size_for

        sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        pre_specs = (specs_fn or param_specs)(template, cfg, mesh)

        def add_gscales(store: dict, spec_store: dict) -> None:
            for name, shape in list(store.items()):
                if name not in MATMUL_WEIGHTS or len(shape) < 2:
                    continue
                in_dim = len(shape) - 2
                spec = spec_store[name]
                ax = spec[in_dim] if in_dim < len(spec) else None
                shards = sizes.get(ax, 1) if ax else 1
                g = _group_size_for(shape[in_dim] // shards, GROUP_SIZE)
                g_eff[name] = g
                store[name + "_gscale"] = (*shape[:in_dim],
                                           shape[in_dim] // g, shape[-1])

        add_gscales(template["layers"], pre_specs["layers"])
        add_gscales(template, pre_specs)   # top level: lm_head (if untied)
    specs = (specs_fn or param_specs)(template, cfg, mesh)

    def read_block(name: str, key, is_layer: bool) -> np.ndarray:
        """One f32 host block covering ``key`` (weight index space)."""
        if is_layer:
            hf_template, transpose = wmap[name]
            layer_rng = range(*key[0])
            if "{e}" in hf_template:
                parts = [
                    np.stack([reader.get_range(
                        hf_template.format(i=i, e=e),
                        _slices(key[2:]), transpose)
                        for e in range(*key[1])])
                    for i in layer_rng]
            else:
                parts = [reader.get_range(hf_template.format(i=i),
                                          _slices(key[1:]), transpose)
                         for i in layer_rng]
            return np.stack(parts).astype(np.float32)
        hf_name, transpose = _TOP_LEVEL[name]
        return reader.get_range(hf_name, _slices(key),
                                transpose).astype(np.float32)

    def plain_leaf(name: str, shape, spec, is_layer: bool) -> jax.Array:
        """The callback reads exactly the range JAX asks for: a
        ``pp``-sharded layer dim means each host reads only its own
        stages' tensors, an ``ep``-sharded expert dim only its own
        experts'."""
        sharding = NamedSharding(mesh, spec)
        cache: dict = {}

        def cb(idx):
            key = _resolve(idx, shape)
            if key not in cache:
                cache[key] = read_block(name, key, is_layer).astype(target)
            return cache[key]

        return jax.make_array_from_callback(tuple(shape), sharding, cb)

    def quantized_pair(name: str, shape, gshape, wspec, sspec,
                       is_layer: bool) -> tuple[jax.Array, jax.Array]:
        """int4 weight + gscale arrays sharing one read+quantize per
        block: the gscale callback maps its (G-dim) index back onto the
        weight's (in-dim) index, so congruently-sharded leaves hit the
        same cache entry."""
        from .quant import symmetric_int4_grouped_np

        g = g_eff[name]
        in_dim = len(shape) - 2
        qcache: dict = {}

        def block(key):
            if key not in qcache:
                qcache[key] = symmetric_int4_grouped_np(
                    read_block(name, key, is_layer), group_size=g)
            return qcache[key]

        def w_cb(idx):
            return block(_resolve(idx, shape))[0]

        def s_cb(idx):
            skey = list(_resolve(idx, gshape))
            g0, g1, _ = skey[in_dim]
            skey[in_dim] = (g0 * g, g1 * g, 1)
            return block(tuple(skey))[1]

        return (jax.make_array_from_callback(
                    tuple(shape), NamedSharding(mesh, wspec), w_cb),
                jax.make_array_from_callback(
                    tuple(gshape), NamedSharding(mesh, sspec), s_cb))

    def build(store: dict, spec_store: dict, shapes: dict,
              is_layer: bool) -> None:
        for name, shape in shapes.items():
            if name.endswith("_gscale"):
                continue
            if is_layer and (name not in wmap
                             or wmap[name][0].format(i=0, e=0) not in reader):
                continue           # optional weight absent (e.g. biases)
            if name + "_gscale" in shapes:
                store[name], store[name + "_gscale"] = quantized_pair(
                    name, shape, shapes[name + "_gscale"],
                    spec_store[name], spec_store[name + "_gscale"], is_layer)
            else:
                store[name] = plain_leaf(name, shape, spec_store[name],
                                         is_layer)

    params: dict = {"layers": {}}
    build(params["layers"], specs["layers"], template["layers"], True)
    build(params, specs, {k: v for k, v in template.items() if k != "layers"},
          False)
    return params, cfg
