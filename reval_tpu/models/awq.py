"""AWQ pre-quantized checkpoint ingestion.

The reference consumes AWQ/GPTQ checkpoints through vLLM's
``quantization`` kwarg (reference inference.py:93); published 4-bit 34B
checkpoints (the CoT flagship class) ship in AWQ's GEMM format, so the
TPU loader reads it natively and maps it onto this repo's int4 storage
(models/quant.py) — asymmetric, hence the extra ``<name>_gzero`` leaf:

AWQ GEMM tensor layout (per linear ``{module}.{qweight,qzeros,scales}``,
AutoAWQ ``awq/utils/packing_utils.py`` semantics):

- ``qweight`` int32 ``[in, out/8]``: eight unsigned 4-bit columns per
  int32, nibble ``p`` (bit shift ``4p``) holding logical column
  ``AWQ_ORDER[p]`` of its 8-column block;
- ``qzeros`` int32 ``[in/g, out/8]``: zero points, packed identically;
- ``scales`` fp16 ``[in/g, out]``;
- dequantisation: ``w[i, o] = (q[i, o] - z[i//g, o]) * s[i//g, o]``.

Mapping to our storage: ``w_int4 = q - 8`` (recentred into signed s4),
``gscale = s``, ``gzero = (z - 8) * s`` — then
``w = w_int4 * gscale - gzero`` exactly reproduces ``(q - z) * s``, and
``_mm`` folds the subtraction into the same fused weight-operand chain
as the symmetric path (models/model.py).

No network egress on this host, so format compliance is validated by a
synthetic writer (tests/test_awq.py) that packs with the same order map.
"""

from __future__ import annotations

import json
from pathlib import Path

import numpy as np

__all__ = ["AWQ_ORDER", "awq_config", "pack_awq", "unpack_awq",
           "awq_to_leaves", "gptq_config", "gptq_to_leaves",
           "pack_gptq_rows", "unpack_gptq_rows",
           "pack_gptq_cols", "unpack_gptq_cols"]

#: nibble position -> logical column offset within each 8-column block
#:
#: Verification status: the interleave (and the GPTQ "stored zeros are
#: zero-1" v1 convention below) is validated only against this module's
#: own pack_* twins — a synthetic writer built from the same constants.
#: This host has zero network egress, so no tensor actually packed by
#: AutoAWQ/AutoGPTQ has been cross-checked; a wrong nibble order would
#: pass every in-repo test and garble a real published checkpoint.
#: When egress (or a vendored golden fixture) is available, add a
#: one-time cross-check against real AutoAWQ bytes before trusting
#: this path on downloaded checkpoints.  (ADVICE r3.)
AWQ_ORDER = (0, 2, 4, 6, 1, 3, 5, 7)


def awq_config(model_path) -> dict | None:
    """The checkpoint's ``quantization_config`` when it is AWQ-GEMM 4-bit
    (the only variant published 34B checkpoints use); None otherwise."""
    cfg_path = Path(model_path) / "config.json"
    if not cfg_path.exists():
        return None
    qc = json.loads(cfg_path.read_text()).get("quantization_config")
    if not qc or qc.get("quant_method") != "awq":
        return None
    if qc.get("bits", 4) != 4:
        raise ValueError(f"AWQ bits={qc.get('bits')} unsupported (int4 only)")
    if qc.get("version", "gemm").lower() != "gemm":
        # GEMV packs qweight output-major with a different nibble layout —
        # unpacking it with GEMM semantics would be silent garbage for
        # square projections, so refuse loudly
        raise ValueError(f"AWQ version={qc.get('version')!r} unsupported "
                         "(GEMM packing only)")
    return qc


def unpack_awq(packed: np.ndarray, order=AWQ_ORDER) -> np.ndarray:
    """int32 ``[rows, cols/8]`` -> uint8 ``[rows, cols]`` of 4-bit values
    in logical column order.  ``order`` maps nibble position -> logical
    column offset (AWQ's interleave by default; ``range(8)`` gives the
    sequential GPTQ qzeros layout)."""
    rows, pcols = packed.shape
    u = packed.astype(np.uint32)
    out = np.empty((rows, pcols * 8), np.uint8)
    for p, col in enumerate(order):
        out[:, col::8] = ((u >> (4 * p)) & 0xF).astype(np.uint8)
    return out


def pack_awq(vals: np.ndarray, order=AWQ_ORDER) -> np.ndarray:
    """Inverse of :func:`unpack_awq` (the synthetic-checkpoint writer and
    round-trip tests)."""
    rows, cols = vals.shape
    assert cols % 8 == 0
    out = np.zeros((rows, cols // 8), np.uint32)
    for p, col in enumerate(order):
        out |= (vals[:, col::8].astype(np.uint32) & 0xF) << (4 * p)
    return out.astype(np.int32)


def awq_to_leaves(qweight: np.ndarray, qzeros: np.ndarray,
                  scales: np.ndarray):
    """AWQ tensors -> (w int4 [in, out], gscale f32 [G, out],
    gzero f32 [G, out]) in this repo's storage convention."""
    import ml_dtypes

    q = unpack_awq(qweight)                       # [in, out] in 0..15
    z = unpack_awq(qzeros)                        # [G, out] in 0..15
    s = scales.astype(np.float32)                 # [G, out]
    w = (q.astype(np.int8) - 8).astype(ml_dtypes.int4)
    gzero = (z.astype(np.float32) - 8.0) * s
    return w, s, gzero


# -- GPTQ (AutoGPTQ v1 GEMM layout) ----------------------------------------
#
# The other format published 4-bit checkpoints ship in (the reference
# reaches it through vLLM's quantization="gptq").  Differences from AWQ:
# - qweight int32 [in/8, out]: eight 4-bit ROWS per int32, packed
#   sequentially along the IN dim (no order map);
# - qzeros int32 [G, out/8]: packed sequentially along OUT, and stored
#   OFF BY ONE (AutoGPTQ writes z-1): dequant is (q - (z_stored + 1)) * s;
# - scales fp16 [G, out];
# - desc_act=True adds a g_idx permutation of the contraction dim —
#   NOT supported here (rejected loudly): it breaks the contiguous-group
#   invariant the int4 storage and its sharding rules rely on.


def gptq_config(model_path) -> dict | None:
    """The checkpoint's ``quantization_config`` when it is GPTQ 4-bit
    with contiguous groups; None when not GPTQ."""
    cfg_path = Path(model_path) / "config.json"
    if not cfg_path.exists():
        return None
    qc = json.loads(cfg_path.read_text()).get("quantization_config")
    if not qc or qc.get("quant_method") != "gptq":
        return None
    if qc.get("bits", 4) != 4:
        raise ValueError(f"GPTQ bits={qc.get('bits')} unsupported (int4 only)")
    if qc.get("desc_act", False):
        raise ValueError(
            "GPTQ desc_act=True (activation-order g_idx) unsupported — "
            "groups must be contiguous along the contraction dim")
    if qc.get("checkpoint_format", "gptq") != "gptq":
        # gptq_v2 stores TRUE zeros (no -1): loading it with the v1 +1
        # fold would shift every weight one scale step — silent garbage
        raise ValueError(
            f"GPTQ checkpoint_format={qc.get('checkpoint_format')!r} "
            "unsupported (v1 'gptq' zeros-minus-one layout only)")
    return qc


def unpack_gptq_rows(packed: np.ndarray) -> np.ndarray:
    """int32 ``[rows/8, cols]`` -> uint8 ``[rows, cols]``: eight
    sequential 4-bit rows per int32 (GPTQ qweight packing)."""
    prows, cols = packed.shape
    u = packed.astype(np.uint32)
    out = np.empty((prows * 8, cols), np.uint8)
    for p in range(8):
        out[p::8] = ((u >> (4 * p)) & 0xF).astype(np.uint8)
    return out


def unpack_gptq_cols(packed: np.ndarray) -> np.ndarray:
    """int32 ``[rows, cols/8]`` -> uint8 ``[rows, cols]``: eight
    sequential 4-bit columns per int32 (GPTQ qzeros packing — the AWQ
    unpack with an identity order map)."""
    return unpack_awq(packed, order=range(8))


def pack_gptq_rows(vals: np.ndarray) -> np.ndarray:
    rows, cols = vals.shape
    assert rows % 8 == 0
    out = np.zeros((rows // 8, cols), np.uint32)
    for p in range(8):
        out |= (vals[p::8].astype(np.uint32) & 0xF) << (4 * p)
    return out.astype(np.int32)


def pack_gptq_cols(vals: np.ndarray) -> np.ndarray:
    return pack_awq(vals, order=range(8))


def gptq_to_leaves(qweight: np.ndarray, qzeros: np.ndarray,
                   scales: np.ndarray):
    """GPTQ tensors -> (w int4 [in, out], gscale f32 [G, out],
    gzero f32 [G, out]), same storage convention as AWQ: the stored
    zeros' +1 offset folds into gzero so ``w*s - gzero`` reproduces
    ``(q - (z_stored+1)) * s`` exactly."""
    import ml_dtypes

    q = unpack_gptq_rows(qweight)                 # [in, out] in 0..15
    z = unpack_gptq_cols(qzeros).astype(np.float32) + 1.0   # true zeros
    s = scales.astype(np.float32)                 # [G, out]
    w = (q.astype(np.int8) - 8).astype(ml_dtypes.int4)
    gzero = (z - 8.0) * s
    return w, s, gzero
