"""Paged KV cache forward passes (continuous-batching path).

Contrast with the contiguous cache in ``model.py``: there, one batch shares
a rectangular ``[B, S, H, D]`` buffer and every sequence decodes at the same
position (left-padding makes that possible).  Continuous batching breaks
that invariant — each slot holds a different sequence at a different
length — so the cache becomes a pool of fixed-size pages in HBM addressed
through per-sequence block tables (see ``ops/pallas_attention.py`` for the
kernel and layout rationale; SURVEY.md §7 step 4 / hard part 2 for why this
is the throughput lever that replaces vLLM's paged allocator).

Layout (chosen by measurement on a v5e chip — see PERF.md):
- the cache is a **per-layer pytree**: one ``[N_pages * P, H_kv, D]`` array
  per layer per k/v, token-major and flat.  Two properties matter:
  1. the decode write is a scatter whose indices hit the *leading* dim
     (``flat_pos = page * P + offset``), which XLA executes in place on the
     donated buffer.  Any layout that needs mixed basic/advanced indexing
     (a stacked ``[L, ...]`` array, or heads ahead of pages) lowers to
     full-array copies instead — measured 92.8 ms/step vs 11.7 ms/step on
     the 1.3b flagship shape, the difference between copying the whole
     multi-GB pool every token and writing 32 KB;
  2. a page (``P`` consecutive rows) is contiguous, so per-sequence reads
     reshape to ``[N_pages, P, H_kv, D]`` for free and gather whole pages
     along the leading dim — the XLA-friendly gather form.
- the layer loop is **unrolled** (a Python ``for`` at trace time), NOT a
  ``lax.scan``: scanning over the cache as xs/ys stacks fresh output
  buffers every step, which again copies the entire pool per token.

Page 0 is reserved as the **trash page**: table slots past a sequence's
allocation and idle batch slots all point at it, so out-of-range writes
land somewhere harmless and masked reads never see them.  The native
allocator (reval_tpu.runtime) never hands out page 0.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from ..ops import apply_rope, rope_angles
from ..ops.pallas_attention import paged_decode_attention
from .configs import ModelConfig
from .model import _embed, _mlp, _norm, _out_proj, _qkv, _unembed

__all__ = [
    "PagedKVCache",
    "init_paged_cache",
    "paged_decode_step",
    "commit_prefill",
]


@partial(jax.tree_util.register_dataclass,
         data_fields=("k", "v"), meta_fields=("page_size",))
@dataclasses.dataclass
class PagedKVCache:
    """Per-layer flat token-major page pool.

    ``k``/``v``: tuples of ``num_layers`` arrays, each
    ``[N_pages * page_size, H_kv, D]``.  ``page_size`` is static metadata
    (it shapes the flat-index arithmetic inside jit).
    """

    k: tuple
    v: tuple
    page_size: int

    @property
    def num_pages(self) -> int:
        return self.k[0].shape[0] // self.page_size

    @property
    def dtype(self):
        return self.k[0].dtype


def init_paged_cache(cfg: ModelConfig, num_pages: int, page_size: int = 128,
                     dtype=jnp.bfloat16) -> PagedKVCache:
    shape = (num_pages * page_size, cfg.num_kv_heads, cfg.head_dim)
    return PagedKVCache(
        k=tuple(jnp.zeros(shape, dtype) for _ in range(cfg.num_layers)),
        v=tuple(jnp.zeros(shape, dtype) for _ in range(cfg.num_layers)),
        page_size=page_size,
    )


def paged_decode_step(params, cfg: ModelConfig, tokens: jnp.ndarray,
                      block_tables: jnp.ndarray, seq_lens: jnp.ndarray,
                      cache: PagedKVCache) -> tuple[jnp.ndarray, PagedKVCache]:
    """One decode step at per-sequence positions.

    tokens: [B, 1] — next input token per slot; its position is
    ``seq_lens[b]`` (the current length, 0-indexed), so the caller advances
    ``seq_lens`` by one *after* the step.  block_tables: [B, max_pages];
    idle slots should point at the trash page with ``seq_lens == 1``.
    Returns (logits [B, V], updated cache).
    """
    page = cache.page_size
    h = _embed(params, cfg, tokens)
    positions = seq_lens[:, None]                       # [B, 1]
    cos, sin = rope_angles(positions, cfg.head_dim, cfg.rope_theta)
    write_page = jnp.take_along_axis(
        block_tables, (seq_lens // page)[:, None], axis=1)[:, 0]   # [B]
    flat_pos = write_page * page + seq_lens % page                  # [B]
    attn_lens = seq_lens + 1                    # new token attends to itself

    layers = params["layers"]
    new_k, new_v = [], []
    for i in range(cfg.num_layers):
        layer = jax.tree.map(lambda x: x[i], layers)
        normed = _norm(h, layer["attn_norm_w"], layer.get("attn_norm_b"), cfg)
        q, k, v = _qkv(normed, layer, cfg)      # q: [B, 1, H, D]
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        # leading-dim scatter → in-place on the donated buffer
        ki = cache.k[i].at[flat_pos].set(k[:, 0].astype(cache.dtype))
        vi = cache.v[i].at[flat_pos].set(v[:, 0].astype(cache.dtype))
        new_k.append(ki)
        new_v.append(vi)
        attn = paged_decode_attention(
            q[:, 0], ki, vi, block_tables, attn_lens, page_size=page,
            window=cfg.sliding_window)
        h = h + _out_proj(attn[:, None], layer, cfg)
        normed = _norm(h, layer["mlp_norm_w"], layer.get("mlp_norm_b"), cfg)
        h = h + _mlp(normed, layer, cfg)
    h = _norm(h, params["final_norm_w"], params.get("final_norm_b"), cfg)
    return (_unembed(params, cfg, h)[:, 0, :],
            PagedKVCache(k=tuple(new_k), v=tuple(new_v), page_size=page))


def commit_prefill(cache: PagedKVCache, kv: "KVCache", pad_len: jnp.ndarray,
                   prefill_tables: jnp.ndarray) -> PagedKVCache:
    """Copy a left-padded contiguous prefill cache into pages.

    kv: contiguous :class:`~reval_tpu.models.model.KVCache` of shape
    [L, B, T, H_kv, D] (T a multiple of the page size); pad_len: [B];
    prefill_tables: [B, T // P] destination page ids — slots past
    ``ceil(len/P)`` should be the trash page.

    Prefill itself runs through the existing left-padded ``prefill`` (its
    attention is already MXU-shaped); paging only changes where the KV
    lands.  The pad shift folds into the scatter's destination indices —
    row ``b``'s buffer column ``j`` holds sequence position ``j - pad``,
    so it lands at ``table[b, (j-pad)//P]*P + (j-pad)%P`` and padding
    columns land in the trash page — no left-align roll copy of the
    multi-GB KV block first (the roll was half the commit's HBM traffic
    and an OOM at 6.7b scale).
    """
    l, b, t, h_kv, d = kv.k.shape
    p = cache.page_size
    assert t % p == 0, f"prefill bucket {t} not a multiple of page size {p}"

    offs = jnp.arange(t, dtype=jnp.int32)
    rel = offs[None, :] - pad_len[:, None]                 # [B, T]
    relc = jnp.clip(rel, 0, t - 1)
    dest = (jnp.take_along_axis(prefill_tables, relc // p, axis=1) * p
            + relc % p)
    flat_idx = jnp.where(rel >= 0, dest, relc % p)         # pad → trash page 0
    new_k, new_v = [], []
    for i in range(l):
        new_k.append(cache.k[i].at[flat_idx].set(kv.k[i].astype(cache.dtype)))
        new_v.append(cache.v[i].at[flat_idx].set(kv.v[i].astype(cache.dtype)))
    return PagedKVCache(k=tuple(new_k), v=tuple(new_v), page_size=p)
