"""Paged KV cache forward passes (continuous-batching path).

Contrast with the contiguous cache in ``model.py``: there, one batch shares
a rectangular ``[B, S, H, D]`` buffer and every sequence decodes at the same
position (left-padding makes that possible).  Continuous batching breaks
that invariant — each slot holds a different sequence at a different
length — so the cache becomes a pool of fixed-size pages in HBM addressed
through per-sequence block tables (see ``ops/pallas_attention.py`` for the
kernel and layout rationale; SURVEY.md §7 step 4 / hard part 2 for why this
is the throughput lever that replaces vLLM's paged allocator).

Page 0 is reserved as the **trash page**: table slots past a sequence's
allocation and idle batch slots all point at it, so out-of-range writes
land somewhere harmless and masked reads never see them.  The native
allocator (reval_tpu.runtime) never hands out page 0.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..ops import apply_rope, rope_angles
from ..ops.pallas_attention import paged_decode_attention
from .configs import ModelConfig
from .model import _embed, _mlp, _norm, _out_proj, _qkv, _unembed

__all__ = [
    "PagedKVCache",
    "init_paged_cache",
    "paged_decode_step",
    "commit_prefill",
]


class PagedKVCache(NamedTuple):
    k: jnp.ndarray  # [L, H_kv, N_pages, P, D]
    v: jnp.ndarray  # [L, H_kv, N_pages, P, D]

    @property
    def page_size(self) -> int:
        return self.k.shape[3]


def init_paged_cache(cfg: ModelConfig, num_pages: int, page_size: int = 128,
                     dtype=jnp.bfloat16) -> PagedKVCache:
    shape = (cfg.num_layers, cfg.num_kv_heads, num_pages, page_size, cfg.head_dim)
    return PagedKVCache(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))


def paged_decode_step(params, cfg: ModelConfig, tokens: jnp.ndarray,
                      block_tables: jnp.ndarray, seq_lens: jnp.ndarray,
                      cache: PagedKVCache) -> tuple[jnp.ndarray, PagedKVCache]:
    """One decode step at per-sequence positions.

    tokens: [B, 1] — next input token per slot; its position is
    ``seq_lens[b]`` (the current length, 0-indexed), so the caller advances
    ``seq_lens`` by one *after* the step.  block_tables: [B, max_pages];
    idle slots should point at the trash page with ``seq_lens == 1``.
    Returns (logits [B, V], updated cache).
    """
    page = cache.page_size
    h = _embed(params, cfg, tokens)
    positions = seq_lens[:, None]                       # [B, 1]
    cos, sin = rope_angles(positions, cfg.head_dim, cfg.rope_theta)
    write_page = jnp.take_along_axis(
        block_tables, (seq_lens // page)[:, None], axis=1)[:, 0]   # [B]
    write_off = seq_lens % page                                     # [B]
    attn_lens = seq_lens + 1                    # new token attends to itself

    def layer_step(h, xs):
        layer, k_slot, v_slot = xs              # slots: [H_kv, N, P, D]
        normed = _norm(h, layer["attn_norm_w"], layer.get("attn_norm_b"), cfg)
        q, k, v = _qkv(normed, layer, cfg)      # q: [B, 1, H, D]
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        k_new = k[:, 0].astype(k_slot.dtype).transpose(1, 0, 2)  # [H_kv, B, D]
        v_new = v[:, 0].astype(v_slot.dtype).transpose(1, 0, 2)
        k_slot = k_slot.at[:, write_page, write_off].set(k_new)
        v_slot = v_slot.at[:, write_page, write_off].set(v_new)
        attn = paged_decode_attention(
            q[:, 0], k_slot, v_slot, block_tables, attn_lens, page_size=page,
            window=cfg.sliding_window)
        h = h + _out_proj(attn[:, None], layer, cfg)
        normed = _norm(h, layer["mlp_norm_w"], layer.get("mlp_norm_b"), cfg)
        h = h + _mlp(normed, layer, cfg)
        return h, (k_slot, v_slot)

    h, (new_k, new_v) = jax.lax.scan(layer_step, h, (params["layers"], cache.k, cache.v))
    h = _norm(h, params["final_norm_w"], params.get("final_norm_b"), cfg)
    return _unembed(params, cfg, h)[:, 0, :], PagedKVCache(new_k, new_v)


def commit_prefill(cache: PagedKVCache, kv: "KVCache", pad_len: jnp.ndarray,
                   prefill_tables: jnp.ndarray) -> PagedKVCache:
    """Copy a left-padded contiguous prefill cache into pages.

    kv: contiguous :class:`~reval_tpu.models.model.KVCache` of shape
    [L, B, T, H_kv, D] (T a multiple of the page size); pad_len: [B];
    prefill_tables: [B, T // P] destination page ids — slots past
    ``ceil(len/P)`` should be the trash page.

    Prefill itself runs through the existing left-padded ``prefill`` (its
    attention is already MXU-shaped); paging only changes where the KV
    lands, so commit is a roll (left-align) + reshape + one scatter.
    """
    l, b, t, h_kv, d = kv.k.shape
    p = cache.page_size
    assert t % p == 0, f"prefill bucket {t} not a multiple of page size {p}"
    n_pg = t // p

    def align(x, shift):            # [L, T, H_kv, D] rolled left by pad_len
        return jnp.roll(x, -shift, axis=1)

    k_aligned = jax.vmap(align, in_axes=(1, 0), out_axes=1)(kv.k, pad_len)
    v_aligned = jax.vmap(align, in_axes=(1, 0), out_axes=1)(kv.v, pad_len)
    # [L, B, n_pg, P, H_kv, D] → [L, H_kv, B, n_pg, P, D]
    k_paged = k_aligned.reshape(l, b, n_pg, p, h_kv, d).transpose(0, 4, 1, 2, 3, 5)
    v_paged = v_aligned.reshape(l, b, n_pg, p, h_kv, d).transpose(0, 4, 1, 2, 3, 5)
    new_k = cache.k.at[:, :, prefill_tables].set(k_paged.astype(cache.k.dtype))
    new_v = cache.v.at[:, :, prefill_tables].set(v_paged.astype(cache.v.dtype))
    return PagedKVCache(new_k, new_v)
