"""Paged KV cache forward passes (continuous-batching path).

Contrast with the contiguous cache in ``model.py``: there, one batch shares
a rectangular ``[B, S, H, D]`` buffer and every sequence decodes at the same
position (left-padding makes that possible).  Continuous batching breaks
that invariant — each slot holds a different sequence at a different
length — so the cache becomes a pool of fixed-size pages in HBM addressed
through per-sequence block tables (see ``ops/pallas_attention.py`` for the
kernel and layout rationale; SURVEY.md §7 step 4 / hard part 2 for why this
is the throughput lever that replaces vLLM's paged allocator).

Layout (chosen by measurement on a v5e chip — see PERF.md):
- the cache is a **per-layer pytree**: one ``[N_pages * P, H_kv, D]`` array
  per layer per k/v, token-major and flat.  Two properties matter:
  1. the decode write is a scatter whose indices hit the *leading* dim
     (``flat_pos = page * P + offset``), which XLA executes in place on the
     donated buffer.  Any layout that needs mixed basic/advanced indexing
     (a stacked ``[L, ...]`` array, or heads ahead of pages) lowers to
     full-array copies instead — measured 92.8 ms/step vs 11.7 ms/step on
     the 1.3b flagship shape, the difference between copying the whole
     multi-GB pool every token and writing 32 KB;
  2. a page (``P`` consecutive rows) is contiguous, so per-sequence reads
     reshape to ``[N_pages, P, H_kv, D]`` for free and gather whole pages
     along the leading dim — the XLA-friendly gather form.
- the layer loop is **unrolled** (a Python ``for`` at trace time), NOT a
  ``lax.scan``: scanning over the cache as xs/ys stacks fresh output
  buffers every step, which again copies the entire pool per token.
- optional **int8 pool** (``kv_dtype="int8"``): pages store symmetric
  per-(token, head) int8 with f32 scales in sibling ``[N_pages * P,
  H_kv]`` arrays — halves pool HBM and attention read traffic, the
  dominant decode cost at large batch/long context.  Writes quantize the
  fresh K/V vector (one amax over D per head); reads dequantize inside
  the attention kernel.

Page 0 is reserved as the **trash page**: table slots past a sequence's
allocation and idle batch slots all point at it, so out-of-range writes
land somewhere harmless and masked reads never see them.  The native
allocator (reval_tpu.runtime) never hands out page 0.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from ..ops import rope_angles
from ..ops.pallas_attention import (paged_decode_attention,
                                    ragged_paged_attention)
from .configs import ModelConfig
from .model import (_block, _embed, _norm, _unembed,
                    prefill_with_batched_context)

__all__ = [
    "PagedKVCache",
    "init_paged_cache",
    "paged_decode_step",
    "paged_ragged_step",
    "commit_prefill",
    "commit_verify",
    "gather_prefix_context",
    "prefill_with_paged_context",
    "gather_tier_page",
    "promote_tier_page",
]


@partial(jax.tree_util.register_dataclass,
         data_fields=("k", "v", "k_scale", "v_scale"),
         meta_fields=("page_size",))
@dataclasses.dataclass
class PagedKVCache:
    """Per-layer flat token-major page pool.

    ``k``/``v``: tuples of ``num_layers`` arrays, each
    ``[N_pages * page_size, H_kv, D]``.  ``k_scale``/``v_scale``: None
    (float pool) or per-layer ``[N_pages * page_size, H_kv]`` f32 scale
    arrays (int8 pool).  ``page_size`` is static metadata (it shapes the
    flat-index arithmetic inside jit).
    """

    k: tuple
    v: tuple
    page_size: int
    k_scale: tuple | None = None
    v_scale: tuple | None = None

    @property
    def num_pages(self) -> int:
        return self.k[0].shape[0] // self.page_size

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None

    @property
    def dtype(self):
        return self.k[0].dtype


def init_paged_cache(cfg: ModelConfig, num_pages: int, page_size: int = 128,
                     dtype=jnp.bfloat16, kv_dtype: str = "") -> PagedKVCache:
    """``kv_dtype``: "" (store in ``dtype``) or "int8" (quantized pool
    with per-(token, head) scales — half the HBM)."""
    if kv_dtype not in ("", "int8"):
        raise ValueError(f"unknown kv_dtype {kv_dtype!r}; expected '' or 'int8'")
    rows = num_pages * page_size
    shape = (rows, cfg.num_kv_heads, cfg.head_dim)
    quantized = kv_dtype == "int8"
    store = jnp.int8 if quantized else dtype

    def mk_scales():
        # two independent allocations (k and v) so donation stays safe
        return (tuple(jnp.ones((rows, cfg.num_kv_heads), jnp.float32)
                      for _ in range(cfg.num_layers)) if quantized else None)

    return PagedKVCache(
        k=tuple(jnp.zeros(shape, store) for _ in range(cfg.num_layers)),
        v=tuple(jnp.zeros(shape, store) for _ in range(cfg.num_layers)),
        page_size=page_size,
        k_scale=mk_scales(),
        v_scale=mk_scales(),
    )


def _quantize_kv(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """[..., H_kv, D] float → (int8 values, f32 scales [..., H_kv]) —
    the shared symmetric recipe, reduced per (token, head)."""
    from .quant import symmetric_int8

    return symmetric_int8(x, axis=-1)


def _layer_scales(cache: PagedKVCache, i: int):
    if cache.quantized:
        return cache.k_scale[i], cache.v_scale[i]
    return None, None


# mesh: axes=(tp)
def _attention_tp_manual(q2, ki, vi, block_tables, attn_lens, ks_i, vs_i,
                         *, page: int, cfg: ModelConfig, win, mesh):
    """Dispatch paged attention, manually sharded over ``tp`` when a mesh
    is present.

    Mosaic custom calls cannot be GSPMD-auto-partitioned ("Please wrap
    the call in a shard_map" on a real multi-chip compile) — the CPU
    virtual mesh never catches this because interpret mode traces plain
    HLO, which GSPMD happily partitions; the deviceless AOT tier
    (tests/test_tpu_aot_compile.py) did.  Attention is embarrassingly
    parallel over heads, so a partial-manual shard_map over ``tp`` alone
    needs no collectives inside: each shard runs the kernel on its local
    query heads against its local (kv-divisible) or replicated
    (indivisible) KV slice, mirroring exactly the shardings
    ``param_specs``/``paged_cache_spec`` chose for the operands.
    """
    call = partial(paged_decode_attention, page_size=page,
                   scale=cfg.attn_scale, window=win,
                   softcap=cfg.attn_softcap)
    if mesh is None:
        return call(q2, ki, vi, block_tables, attn_lens,
                    k_scales=ks_i, v_scales=vs_i)
    from ..parallel.mesh import mesh_axis_sizes

    if mesh_axis_sizes(mesh).get("tp", 1) == 1:
        return call(q2, ki, vi, block_tables, attn_lens,
                    k_scales=ks_i, v_scales=vs_i)
    from jax.sharding import PartitionSpec as P

    from ..parallel.sharding import _divisible

    div = _divisible(cfg, mesh)
    # q may shard over heads ONLY when the per-shard query heads still
    # line up with their kv groups: either the kv heads shard the same
    # way, or there is a single kv head every query head maps to (MQA).
    # With kv replicated and h_kv > 1, a head-sharded q would make the
    # kernel recompute g from local shapes and pair query heads with the
    # wrong kv heads — silently wrong logits, so fall back to
    # replicated q (param_specs replicates q_w in that case too).
    q_shardable = div["heads"] and (div["kv_heads"] or cfg.num_kv_heads == 1)
    q_spec = P(None, "tp", None) if q_shardable else P(None, None, None)
    kv_spec = P(None, "tp", None) if div["kv_heads"] else P(None, None, None)
    sc_spec = P(None, "tp") if div["kv_heads"] else P(None, None)
    in_specs = [q_spec, kv_spec, kv_spec, P(), P()]
    args = [q2, ki, vi, block_tables, attn_lens]
    if ks_i is not None:
        in_specs += [sc_spec, sc_spec]
        args += [ks_i, vs_i]

    def local(q_, k_, v_, bt_, sl_, *scales):
        ks_, vs_ = scales if scales else (None, None)
        return call(q_, k_, v_, bt_, sl_, k_scales=ks_, v_scales=vs_)

    # Manual over ALL mesh axes (the default), not just {"tp"}: Mosaic
    # rejects custom calls whose manual axes are any strict subset of the
    # mesh's manual axis names, and make_mesh keeps singleton (dp, pp,
    # sp, ep) axes — a partial-manual region over {"tp"} compiles only
    # on single-axis meshes.  The specs place only "tp"; every other
    # axis is replicated (the paged engine is tp-only by contract).
    # check_vma=False: pallas_call's out_shape is a plain ShapeDtypeStruct
    # with no varying-axes metadata, which the vma checker rejects inside
    # a manual region; correctness here is by construction (head-parallel,
    # no cross-shard dataflow).  compat_shard_map handles the 0.4.x
    # spelling (check_rep) — the shim models/paged.py used to carry
    # privately, now shared with the pp/sp ring paths.
    from ..parallel.mesh import compat_shard_map

    # jit-entry: paged.attn_tp_shard bucketed=(rows)
    # mesh: axes=(tp) in=(dynamic) out=(dynamic)
    return compat_shard_map(local, mesh=mesh, in_specs=tuple(in_specs),
                            out_specs=q_spec, check_vma=False)(*args)


def paged_decode_step(params, cfg: ModelConfig, tokens: jnp.ndarray,
                      block_tables: jnp.ndarray, seq_lens: jnp.ndarray,
                      cache: PagedKVCache,
                      mesh=None) -> tuple[jnp.ndarray, PagedKVCache]:
    """One decode step at per-sequence positions.

    tokens: [B, 1] — next input token per slot; its position is
    ``seq_lens[b]`` (the current length, 0-indexed), so the caller advances
    ``seq_lens`` by one *after* the step.  block_tables: [B, max_pages];
    idle slots should point at the trash page with ``seq_lens == 1``.
    ``mesh``: the engine's mesh when tp-sharded (see
    :func:`_attention_tp_manual`).  Returns (logits [B, V], updated cache).
    """
    page = cache.page_size
    h = _embed(params, cfg, tokens)
    positions = seq_lens[:, None]                       # [B, 1]
    cos, sin = rope_angles(positions, cfg.head_dim, cfg.rope_theta)
    write_page = jnp.take_along_axis(
        block_tables, (seq_lens // page)[:, None], axis=1)[:, 0]   # [B]
    flat_pos = write_page * page + seq_lens % page                  # [B]
    attn_lens = seq_lens + 1                    # new token attends to itself

    layers = params["layers"]
    new_k, new_v = [], []
    new_ks, new_vs = [], []
    for i in range(cfg.num_layers):
        layer = jax.tree.map(lambda x: x[i], layers)

        def attend(q, k, v, i=i):
            ks_i, vs_i = _layer_scales(cache, i)
            if cache.quantized:
                kq, ks_new = _quantize_kv(k[:, 0])
                vq, vs_new = _quantize_kv(v[:, 0])
                ki = cache.k[i].at[flat_pos].set(kq)
                vi = cache.v[i].at[flat_pos].set(vq)
                ks_i = ks_i.at[flat_pos].set(ks_new)
                vs_i = vs_i.at[flat_pos].set(vs_new)
                new_ks.append(ks_i)
                new_vs.append(vs_i)
            else:
                # leading-dim scatter → in-place on the donated buffer
                ki = cache.k[i].at[flat_pos].set(k[:, 0].astype(cache.dtype))
                vi = cache.v[i].at[flat_pos].set(v[:, 0].astype(cache.dtype))
            new_k.append(ki)
            new_v.append(vi)
            attn = _attention_tp_manual(
                q[:, 0], ki, vi, block_tables, attn_lens, ks_i, vs_i,
                page=page, cfg=cfg, win=cfg.window_for_layer(i), mesh=mesh)
            return attn[:, None]

        h = _block(h, layer, cfg, cos, sin, attend)
    h = _norm(h, params["final_norm_w"], params.get("final_norm_b"), cfg)
    out_cache = PagedKVCache(
        k=tuple(new_k), v=tuple(new_v), page_size=page,
        k_scale=tuple(new_ks) if cache.quantized else None,
        v_scale=tuple(new_vs) if cache.quantized else None)
    return _unembed(params, cfg, h)[:, 0, :], out_cache


def paged_ragged_step(params, cfg: ModelConfig, tokens: jnp.ndarray,
                      block_tables: jnp.ndarray, ctx_lens: jnp.ndarray,
                      q_lens: jnp.ndarray, cache: PagedKVCache,
                      mesh=None) -> tuple[jnp.ndarray, PagedKVCache]:
    """One ragged window forward over a MIXED batch: the unified shape
    that replaces per-row gathered-context prefill, the decode step, and
    the spec-verify window (ops/pallas_attention.py ragged kernel).

    tokens: [B, W] — row ``b``'s window, left-aligned; column ``j`` is
    the token at absolute position ``ctx_lens[b] + j`` and columns
    ``j >= q_lens[b]`` are padding (their KV lands in the trash page,
    their logits are unspecified).  A decode row is ``q_lens=1``, a
    verify window ``1+ndraft``, a prefill chunk up to ``W``.  Each
    layer scatters the window's KV into the pool FIRST (the same flat
    positions plain decode would write, which keeps ragged KV
    bit-compatible with the incumbent paths), then attends through the
    page table — no dense per-row context gather, no pow2 context
    bucketing.  Returns (logits [B, W, V], updated cache).

    ``mesh`` must be tp=1 (the engine falls back to the incumbent split
    dispatch on tp-sharded meshes — the ragged kernel has no shard_map
    wrapper yet).
    """
    page = cache.page_size
    b, w = tokens.shape
    h = _embed(params, cfg, tokens)
    positions = ctx_lens[:, None] + jnp.arange(w, dtype=jnp.int32)[None, :]
    cos, sin = rope_angles(positions, cfg.head_dim, cfg.rope_theta)
    # window column j lands at the flat position decode would have
    # written token ctx+j to; padding cols land in the trash page rows
    pidx = jnp.clip(positions // page, 0, block_tables.shape[1] - 1)
    dest = jnp.take_along_axis(block_tables, pidx, axis=1) * page \
        + positions % page                                      # [B, W]
    col_valid = jnp.arange(w, dtype=jnp.int32)[None, :] < q_lens[:, None]
    flat_idx = jnp.where(col_valid, dest, positions % page)

    layers = params["layers"]
    new_k, new_v = [], []
    new_ks, new_vs = [], []
    for i in range(cfg.num_layers):
        layer = jax.tree.map(lambda x: x[i], layers)

        def attend(q, k, v, i=i):
            ks_i, vs_i = _layer_scales(cache, i)
            if cache.quantized:
                kq, ks_new = _quantize_kv(k)
                vq, vs_new = _quantize_kv(v)
                ki = cache.k[i].at[flat_idx].set(kq)
                vi = cache.v[i].at[flat_idx].set(vq)
                ks_i = ks_i.at[flat_idx].set(ks_new)
                vs_i = vs_i.at[flat_idx].set(vs_new)
                new_ks.append(ks_i)
                new_vs.append(vs_i)
            else:
                # leading-dim scatter → in-place on the donated buffer
                ki = cache.k[i].at[flat_idx].set(k.astype(cache.dtype))
                vi = cache.v[i].at[flat_idx].set(v.astype(cache.dtype))
            new_k.append(ki)
            new_v.append(vi)
            return ragged_paged_attention(
                q, ki, vi, block_tables, ctx_lens, q_lens,
                page_size=page, scale=cfg.attn_scale,
                window=cfg.window_for_layer(i),
                softcap=cfg.attn_softcap, k_scales=ks_i, v_scales=vs_i)

        h = _block(h, layer, cfg, cos, sin, attend)
    h = _norm(h, params["final_norm_w"], params.get("final_norm_b"), cfg)
    out_cache = PagedKVCache(
        k=tuple(new_k), v=tuple(new_v), page_size=page,
        k_scale=tuple(new_ks) if cache.quantized else None,
        v_scale=tuple(new_vs) if cache.quantized else None)
    return _unembed(params, cfg, h), out_cache


def gather_prefix_context(cache: PagedKVCache, ctx_tables: jnp.ndarray
                          ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Gather per-row prefix KV out of the page pool into contiguous
    context blocks: ``ctx_tables`` [B, N_pre] page ids (trash-page padded
    past each row's real prefix) → ``(k, v)`` each ``[L, B, N_pre * P,
    H_kv, D]`` — the ``ctx_k``/``ctx_v`` operands of
    :func:`~reval_tpu.models.model.prefill_with_batched_context`.

    DEPRECATED as a serving path: :func:`paged_ragged_step` attends
    pool pages directly with no dense gather and owns prefill whenever
    the engine runs the ragged backend.  This stays as the incumbent
    fallback (split-dispatch mode, tp-sharded meshes) and as the
    prefix-insert batch-1 path.

    The gather hits the pool's *leading* (token-major) dim — the
    XLA-friendly whole-page gather form this layout was chosen for (see
    module docstring).  Rows gathered from the trash page hold stale
    bytes; the attention masks them via ``ctx_len``.  Int8 pools
    dequantize through their scales here (the context is read-only —
    nothing writes back).
    """
    p = cache.page_size
    b, npre = ctx_tables.shape
    flat = (ctx_tables[:, :, None] * p
            + jnp.arange(p, dtype=jnp.int32)[None, None, :]).reshape(b, npre * p)

    def gather(pool, scales):
        x = pool[flat]                              # [B, Tc, H_kv, D]
        if scales is not None:
            x = x.astype(jnp.float32) * scales[flat][..., None]
        return x

    ks, vs = [], []
    for i in range(len(cache.k)):
        sk, sv = _layer_scales(cache, i)
        ks.append(gather(cache.k[i], sk))
        vs.append(gather(cache.v[i], sv))
    return jnp.stack(ks), jnp.stack(vs)


def prefill_with_paged_context(params, cfg: ModelConfig, tokens: jnp.ndarray,
                               pad_len: jnp.ndarray, ctx_tables: jnp.ndarray,
                               ctx_len: jnp.ndarray, paged: PagedKVCache,
                               cache: "KVCache", logits_mode: str = "last"):
    """Prefill suffix blocks whose per-row prefix KV lives in pool pages.

    The persistent radix prefix cache's prefill path: each admitted row's
    longest cached prefix is already committed to (refcounted) pages, so
    the suffix attends a context GATHERED from the pool instead of a
    contiguous KV block held by the engine — no second copy of cached
    prefixes ever exists, and different rows ride different prefixes in
    one call.  ``paged`` is read-only here (commit of the suffix KV is a
    separate donated step, as for plain prefill).

    DEPRECATED as a serving path (see :func:`gather_prefix_context`):
    ragged-backend prefill feeds windows through
    :func:`paged_ragged_step` instead.  Kept as the incumbent fallback
    and the spec-verify forward of the split-dispatch mode.
    """
    ctx_k, ctx_v = gather_prefix_context(paged, ctx_tables)
    return prefill_with_batched_context(
        params, cfg, tokens, pad_len, ctx_k, ctx_v, ctx_len, cache,
        logits_mode=logits_mode)


def gather_tier_page(cache: PagedKVCache, page: jnp.ndarray) -> tuple:
    """Slice ONE page's rows out of every pool array — the KV-tier spill
    read (kv_tiers.py).  ``page`` is a [1] int32 page id; returns a flat
    tuple of per-layer ``[P, H_kv, D]`` k then v blocks (then ``[P,
    H_kv]`` k/v scales for an int8 pool) in the tier store's canonical
    block order.

    A dynamic slice on the leading (token-major) dim — the same
    whole-page-contiguous property the gather path rides.  The result
    aliases nothing (a slice is a copy), so the engine releases the pool
    page immediately after dispatch: the later donated pool write cannot
    clobber an in-flight spill because XLA orders both on the device
    stream.
    """
    p = cache.page_size
    start = page[0] * p

    def rows(pool):
        return jax.lax.dynamic_slice_in_dim(pool, start, p, axis=0)

    out = [rows(cache.k[i]) for i in range(len(cache.k))]
    out += [rows(cache.v[i]) for i in range(len(cache.v))]
    if cache.quantized:
        out += [rows(cache.k_scale[i]) for i in range(len(cache.k_scale))]
        out += [rows(cache.v_scale[i]) for i in range(len(cache.v_scale))]
    return tuple(out)


def promote_tier_page(cache: PagedKVCache, page: jnp.ndarray,
                      blocks: tuple) -> PagedKVCache:
    """Scatter one spilled page's blocks back into the pool at ``page``
    (a [1] int32 page id) — the KV-tier promotion write, the exact
    inverse of :func:`gather_tier_page` (same flat block order).

    A leading-dim ``dynamic_update_slice`` on the donated pool — in
    place, like the decode scatter.  The blocks are raw bytes hashed at
    spill time, so a promoted page is bit-identical to what the resident
    page held: promotion can never change an answer.
    """
    p = cache.page_size
    start = page[0] * p
    nl = len(cache.k)

    def put(pool, block):
        return jax.lax.dynamic_update_slice_in_dim(
            pool, block.astype(pool.dtype), start, axis=0)

    new_k = tuple(put(cache.k[i], blocks[i]) for i in range(nl))
    new_v = tuple(put(cache.v[i], blocks[nl + i]) for i in range(nl))
    new_ks = new_vs = None
    if cache.quantized:
        new_ks = tuple(put(cache.k_scale[i], blocks[2 * nl + i])
                       for i in range(nl))
        new_vs = tuple(put(cache.v_scale[i], blocks[3 * nl + i])
                       for i in range(nl))
    return PagedKVCache(k=new_k, v=new_v, page_size=p,
                        k_scale=new_ks, v_scale=new_vs)


def commit_verify(cache: PagedKVCache, kv: "KVCache", tables: jnp.ndarray,
                  start: jnp.ndarray) -> PagedKVCache:
    """Scatter a speculative verify window's KV into pages at absolute
    per-row positions — the mid-page sibling of :func:`commit_prefill`.

    kv: contiguous [L, B, W, H_kv, D] window KV (W = draft window, a
    handful of tokens — NOT page-aligned); tables: [B, span] block
    tables; start: [B] the absolute sequence position of window column
    0 (the row's materialised length).  Column ``j`` lands at
    ``table[b, (start+j)//P]*P + (start+j)%P`` — the same flat position
    the plain decode scatter would have written token ``start+j`` to,
    which is what makes speculative KV bit-compatible with plain
    decode's.  Rejected draft columns land too: they sit past the
    row's accepted length, so attention masks them and the next window
    (or plain decode step) overwrites them in place.  Idle rows point
    their tables at the trash page, exactly like the decode path.
    """
    l, b, w, h_kv, d = kv.k.shape
    p = cache.page_size
    pos = start[:, None] + jnp.arange(w, dtype=jnp.int32)[None, :]   # [B, W]
    dest = jnp.take_along_axis(tables, pos // p, axis=1) * p + pos % p
    new_k, new_v, new_ks, new_vs = [], [], [], []
    for i in range(l):
        if cache.quantized:
            kq, ks = _quantize_kv(kv.k[i])
            vq, vs = _quantize_kv(kv.v[i])
            new_k.append(cache.k[i].at[dest].set(kq))
            new_v.append(cache.v[i].at[dest].set(vq))
            new_ks.append(cache.k_scale[i].at[dest].set(ks))
            new_vs.append(cache.v_scale[i].at[dest].set(vs))
        else:
            new_k.append(cache.k[i].at[dest].set(
                kv.k[i].astype(cache.dtype)))
            new_v.append(cache.v[i].at[dest].set(
                kv.v[i].astype(cache.dtype)))
    return PagedKVCache(
        k=tuple(new_k), v=tuple(new_v), page_size=p,
        k_scale=tuple(new_ks) if cache.quantized else None,
        v_scale=tuple(new_vs) if cache.quantized else None)


def commit_prefill(cache: PagedKVCache, kv: "KVCache", pad_len: jnp.ndarray,
                   prefill_tables: jnp.ndarray) -> PagedKVCache:
    """Copy a left-padded contiguous prefill cache into pages.

    kv: contiguous :class:`~reval_tpu.models.model.KVCache` of shape
    [L, B, T, H_kv, D] (T a multiple of the page size); pad_len: [B];
    prefill_tables: [B, T // P] destination page ids — slots past
    ``ceil(len/P)`` should be the trash page.

    Prefill itself runs through the existing left-padded ``prefill`` (its
    attention is already MXU-shaped); paging only changes where the KV
    lands.  The pad shift folds into the scatter's destination indices —
    row ``b``'s buffer column ``j`` holds sequence position ``j - pad``,
    so it lands at ``table[b, (j-pad)//P]*P + (j-pad)%P`` and padding
    columns land in the trash page — no left-align roll copy of the
    multi-GB KV block first (the roll was half the commit's HBM traffic
    and an OOM at 6.7b scale).  Int8 pools quantize each layer's block
    as it commits.
    """
    l, b, t, h_kv, d = kv.k.shape
    p = cache.page_size
    assert t % p == 0, f"prefill bucket {t} not a multiple of page size {p}"

    offs = jnp.arange(t, dtype=jnp.int32)
    rel = offs[None, :] - pad_len[:, None]                 # [B, T]
    relc = jnp.clip(rel, 0, t - 1)
    dest = (jnp.take_along_axis(prefill_tables, relc // p, axis=1) * p
            + relc % p)
    flat_idx = jnp.where(rel >= 0, dest, relc % p)         # pad → trash page 0
    new_k, new_v, new_ks, new_vs = [], [], [], []
    for i in range(l):
        if cache.quantized:
            kq, ks = _quantize_kv(kv.k[i])
            vq, vs = _quantize_kv(kv.v[i])
            new_k.append(cache.k[i].at[flat_idx].set(kq))
            new_v.append(cache.v[i].at[flat_idx].set(vq))
            new_ks.append(cache.k_scale[i].at[flat_idx].set(ks))
            new_vs.append(cache.v_scale[i].at[flat_idx].set(vs))
        else:
            new_k.append(cache.k[i].at[flat_idx].set(
                kv.k[i].astype(cache.dtype)))
            new_v.append(cache.v[i].at[flat_idx].set(
                kv.v[i].astype(cache.dtype)))
    return PagedKVCache(
        k=tuple(new_k), v=tuple(new_v), page_size=p,
        k_scale=tuple(new_ks) if cache.quantized else None,
        v_scale=tuple(new_vs) if cache.quantized else None)
