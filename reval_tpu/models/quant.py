"""Weight-only int8 quantization (per-output-channel, symmetric).

TPU-native memory lever: autoregressive decode re-reads every matmul
weight each step, so HBM traffic — not FLOPs — bounds decode speed, and
int8 storage halves it versus bf16.  More importantly it changes what
*fits*: deepseek-coder-6.7b is 13.4 GB in bf16 — no room next to a KV
page pool on a 16 GB v5e chip — but 6.7 GB in int8 runs single-chip
(BASELINE.json configs[1]-[2] class models on one chip; the reference
needed an A800 per vLLM worker for the same shapes).

Scheme (the standard weight-only recipe, chosen for XLA friendliness):
- per-output-channel symmetric scales: ``s[o] = max_abs(w[:, o]) / 127``,
  ``w_q = round(w / s)`` stored int8, compute stays bf16 —
  ``(x @ w_q.astype(bf16)) * s`` is exact w.r.t. the dequantised weight
  because the scale is constant along the contraction dim, and XLA fuses
  the int8→bf16 convert into the dot's operand load (no dequantised copy
  is materialised in HBM).
- quantized leaves keep their name; the scale rides next to them as
  ``<name>_scale`` (stacked ``[L, out]`` for layer weights), so the
  sharding rules and checkpoint plumbing see ordinary pytree leaves.
- ``embed`` stays bf16: it is read by token *gather* (one row per token),
  not a matmul — quantizing it saves nothing per step and would cost
  accuracy twice when embeddings are tied.

Activations are untouched (bf16): weight-only int8 on decoder LLMs is
the regime with negligible accuracy cost, and the MXU runs bf16×bf16.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["MATMUL_WEIGHTS", "quantize_params", "quantize_stacked",
           "is_quantized", "symmetric_int4_grouped",
           "symmetric_int4_grouped_np", "dequantize_grouped",
           "dequantize_params", "GROUP_SIZE"]

#: int4 group size along the contraction (input-feature) dim — the
#: AWQ/GPTQ-standard granularity; per-output-channel alone is too coarse
#: for 15 levels.  128 matches the TPU lane tile and divides every
#: llama-family hidden/intermediate size.
GROUP_SIZE = 128

#: matmul weights eligible for int8 storage ([..., in, out] layout);
#: the moe expert stacks are [L, E, in, out] and quantize per (layer,
#: expert, out-channel).  The tiny router stays float (its logits pick
#: experts — rounding there changes routing, not just values).
MATMUL_WEIGHTS = (
    "q_w", "k_w", "v_w", "o_w",
    "gate_w", "up_w", "down_w",
    "fc_w", "proj_w",
    "moe_gate_w", "moe_up_w", "moe_down_w",
    "lm_head",
)


def symmetric_int8(x: jnp.ndarray, axis: int) -> tuple[jnp.ndarray, jnp.ndarray]:
    """THE int8 recipe (one definition for weights and KV): symmetric
    per-slice scales ``amax/127`` reduced over ``axis``, zero slices
    pinned to scale 1, values clipped to ±127.  Returns (int8, f32
    scales with ``axis`` removed)."""
    xf = x.astype(jnp.float32)
    s = jnp.max(jnp.abs(xf), axis=axis) / 127.0
    s = jnp.where(s == 0.0, 1.0, s)
    q = jnp.round(xf / jnp.expand_dims(s, axis))
    q = jnp.clip(q, -127, 127).astype(jnp.int8)
    return q, s


def _quantize_leaf(w: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """[..., in, out] → (int8 weights, f32 scales [..., out])."""
    return symmetric_int8(w, axis=-2)


def _group_size_for(n_in: int, group_size: int) -> int:
    """Largest power-of-two-reduced divisor of ``n_in`` at most
    ``group_size`` (non-standard in-dims fall back gracefully)."""
    g = min(group_size, n_in)
    while n_in % g:
        g //= 2
    return max(g, 1)


def symmetric_int4_grouped(w: jnp.ndarray, group_size: int = GROUP_SIZE
                           ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Group-wise symmetric int4: ``[..., in, out]`` → (int4 weights of
    the SAME shape, f32 scales ``[..., in/g, out]``).

    Scale ``s[.., G, o] = max_abs(w[.., G*g:(G+1)*g, o]) / 7``; XLA's
    native ``s4`` dtype stores two nibbles per byte on TPU, so weight HBM
    is 4× smaller than bf16 (+ scales: f32/g ≈ 0.25 bit/weight at g=128).
    """
    *lead, n_in, n_out = w.shape
    g = _group_size_for(n_in, group_size)
    wf = w.astype(jnp.float32).reshape(*lead, n_in // g, g, n_out)
    s = jnp.max(jnp.abs(wf), axis=-2) / 7.0
    s = jnp.where(s == 0.0, 1.0, s)
    q = jnp.round(wf / s[..., None, :])
    q = jnp.clip(q, -7, 7).astype(jnp.int4).reshape(*lead, n_in, n_out)
    return q, s


def symmetric_int4_grouped_np(w, group_size: int = GROUP_SIZE):
    """Host-side (numpy) twin of :func:`symmetric_int4_grouped` for the
    shard-direct loader: quantizes a checkpoint slice without touching a
    device.  Bit-identical grids given the same ``group_size``."""
    import ml_dtypes
    import numpy as np

    *lead, n_in, n_out = w.shape
    g = _group_size_for(n_in, group_size)
    wf = np.asarray(w, np.float32).reshape(*lead, n_in // g, g, n_out)
    s = np.abs(wf).max(axis=-2) / 7.0
    s = np.where(s == 0.0, 1.0, s)
    q = np.clip(np.round(wf / s[..., None, :]), -7, 7)
    return (q.reshape(*lead, n_in, n_out).astype(ml_dtypes.int4),
            s.astype(np.float32))


def dequantize_grouped(q: jnp.ndarray, gscale: jnp.ndarray, dtype,
                       gzero: jnp.ndarray | None = None) -> jnp.ndarray:
    """int4 ``[..., in, out]`` + scales ``[..., G, out]`` (+ optional
    AWQ-style zero offsets ``gzero`` [..., G, out], already scaled) →
    ``dtype`` weights (a transient — the dense hot path never calls
    this, see ``_mm``'s fused group einsum; expert paths use it per
    layer)."""
    *lead, n_in, n_out = q.shape
    n_groups = gscale.shape[-2]
    g = n_in // n_groups
    wf = q.astype(dtype).reshape(*lead, n_groups, g, n_out)
    wf = wf * gscale[..., None, :].astype(dtype)
    if gzero is not None:
        wf = wf - gzero[..., None, :].astype(dtype)
    return wf.reshape(*lead, n_in, n_out)


def dequantize_params(params: dict, dtype=jnp.float32) -> dict:
    """Inverse of :func:`quantize_params` (int4/``_gscale`` leaves only —
    the test/dryrun oracle): EVERY quantized leaf dequantises, including
    top-level ones like ``lm_head``, so a comparison engine really runs
    the plain-weights path end to end."""
    def deq_store(src: dict) -> dict:
        out: dict = {}
        for name, leaf in src.items():
            if name.endswith(("_gscale", "_gzero")):
                continue
            gs = src.get(name + "_gscale")
            out[name] = (dequantize_grouped(leaf, gs, dtype,
                                            src.get(name + "_gzero"))
                         if gs is not None else leaf)
        return out

    out = deq_store({k: v for k, v in params.items() if k != "layers"})
    out["layers"] = deq_store(params["layers"])
    return out


def quantize_stacked(w: jnp.ndarray, mode: str = "int8", tp: int = 1
                     ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Quantize a stacked ``[L, in, out]`` weight layer-by-layer.

    Quantizing the whole stack materialises fp32 temporaries of the full
    stacked size (5.8 GB for 6.7b's MLP weights) — several alive at once
    under JAX's async dispatch is an instant OOM next to the model.
    Slicing keeps the fp32 transient to one layer."""
    if mode == "int8":
        leaf = _quantize_leaf
    else:
        g = _tp_aligned_group(w.shape[-2], tp)

        def leaf(x):
            return symmetric_int4_grouped(x, group_size=g)
    if w.ndim <= 2:
        return leaf(w)
    parts = [leaf(w[i]) for i in range(w.shape[0])]
    return (jnp.stack([q for q, _ in parts]),
            jnp.stack([s for _, s in parts]))


def _tp_aligned_group(n_in: int, tp: int) -> int:
    """int4 group size whose boundaries align with a ``tp``-way shard of
    the contraction dim: groups then never straddle shards, so the
    sharded ``_mm`` reshape needs no resharding and the gscale's group
    dim divides ``tp``.  Same rule the shard-direct loader applies."""
    if tp > 1 and n_in % tp == 0:
        return _group_size_for(n_in // tp, GROUP_SIZE)
    return _group_size_for(n_in, GROUP_SIZE)


def quantize_into(store: dict, name: str, arr: jnp.ndarray,
                  mode: str = "int8", tp: int = 1) -> None:
    """Store ``arr`` under ``name``, quantizing it when it is a matmul
    weight — the ONE place that defines the storage conventions ``_mm``
    (models/model.py) and the sharding rules (parallel/sharding.py)
    consume: int8 rides a per-out-channel ``<name>_scale`` sibling, int4
    a per-(group, out-channel) ``<name>_gscale``."""
    if name in MATMUL_WEIGHTS:
        if jnp.dtype(arr.dtype) in (jnp.dtype(jnp.int8), jnp.dtype(jnp.int4)):
            # re-quantizing quantized CODES would treat -8..127 integers
            # as float weights and orphan any _gzero sibling _mm still
            # subtracts — silently wrong logits (e.g. an AWQ-loaded tree
            # passed back through quantize_params)
            raise ValueError(
                f"{name} is already quantized ({arr.dtype}) — "
                "quantize_params takes float-weight trees only")
        q, s = quantize_stacked(arr, mode, tp)
        store[name] = q
        store[name + ("_scale" if mode == "int8" else "_gscale")] = s
    else:
        store[name] = arr


def quantize_params(params: dict, mode: str = "int8", tp: int = 1) -> dict:
    """Return a params tree with matmul weights in int8 + ``*_scale``
    (or int4 + ``*_gscale``) leaves.  Norms, biases and the embedding
    stay in their dtype.

    ``tp``: intended tensor-parallel width for params-in-hand int4 use
    (engine construction from an already-loaded tree) — aligns group
    boundaries to shard boundaries like the shard-direct loader does, so
    in-sharded matmuls don't pay a GSPMD reshard every step."""
    out: dict = {}
    for name, value in params.items():
        if name == "layers":
            layers: dict = {}
            for k, v in value.items():
                quantize_into(layers, k, v, mode, tp)
            out["layers"] = layers
        else:
            quantize_into(out, name, value, mode, tp)
    return out


def is_quantized(params: dict) -> bool:
    layers = params.get("layers", {})
    return any(k.endswith(("_scale", "_gscale")) for k in layers)
