"""Weight-only int8 quantization (per-output-channel, symmetric).

TPU-native memory lever: autoregressive decode re-reads every matmul
weight each step, so HBM traffic — not FLOPs — bounds decode speed, and
int8 storage halves it versus bf16.  More importantly it changes what
*fits*: deepseek-coder-6.7b is 13.4 GB in bf16 — no room next to a KV
page pool on a 16 GB v5e chip — but 6.7 GB in int8 runs single-chip
(BASELINE.json configs[1]-[2] class models on one chip; the reference
needed an A800 per vLLM worker for the same shapes).

Scheme (the standard weight-only recipe, chosen for XLA friendliness):
- per-output-channel symmetric scales: ``s[o] = max_abs(w[:, o]) / 127``,
  ``w_q = round(w / s)`` stored int8, compute stays bf16 —
  ``(x @ w_q.astype(bf16)) * s`` is exact w.r.t. the dequantised weight
  because the scale is constant along the contraction dim, and XLA fuses
  the int8→bf16 convert into the dot's operand load (no dequantised copy
  is materialised in HBM).
- quantized leaves keep their name; the scale rides next to them as
  ``<name>_scale`` (stacked ``[L, out]`` for layer weights), so the
  sharding rules and checkpoint plumbing see ordinary pytree leaves.
- ``embed`` stays bf16: it is read by token *gather* (one row per token),
  not a matmul — quantizing it saves nothing per step and would cost
  accuracy twice when embeddings are tied.

Activations are untouched (bf16): weight-only int8 on decoder LLMs is
the regime with negligible accuracy cost, and the MXU runs bf16×bf16.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["MATMUL_WEIGHTS", "quantize_params", "quantize_stacked", "is_quantized"]

#: matmul weights eligible for int8 storage ([..., in, out] layout);
#: the moe expert stacks are [L, E, in, out] and quantize per (layer,
#: expert, out-channel).  The tiny router stays float (its logits pick
#: experts — rounding there changes routing, not just values).
MATMUL_WEIGHTS = (
    "q_w", "k_w", "v_w", "o_w",
    "gate_w", "up_w", "down_w",
    "fc_w", "proj_w",
    "moe_gate_w", "moe_up_w", "moe_down_w",
    "lm_head",
)


def symmetric_int8(x: jnp.ndarray, axis: int) -> tuple[jnp.ndarray, jnp.ndarray]:
    """THE int8 recipe (one definition for weights and KV): symmetric
    per-slice scales ``amax/127`` reduced over ``axis``, zero slices
    pinned to scale 1, values clipped to ±127.  Returns (int8, f32
    scales with ``axis`` removed)."""
    xf = x.astype(jnp.float32)
    s = jnp.max(jnp.abs(xf), axis=axis) / 127.0
    s = jnp.where(s == 0.0, 1.0, s)
    q = jnp.round(xf / jnp.expand_dims(s, axis))
    q = jnp.clip(q, -127, 127).astype(jnp.int8)
    return q, s


def _quantize_leaf(w: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """[..., in, out] → (int8 weights, f32 scales [..., out])."""
    return symmetric_int8(w, axis=-2)


def quantize_stacked(w: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Quantize a stacked ``[L, in, out]`` weight layer-by-layer.

    ``_quantize_leaf`` on the whole stack materialises fp32 temporaries of
    the full stacked size (5.8 GB for 6.7b's MLP weights) — several alive
    at once under JAX's async dispatch is an instant OOM next to the
    model.  Slicing keeps the fp32 transient to one layer."""
    if w.ndim <= 2:
        return _quantize_leaf(w)
    parts = [_quantize_leaf(w[i]) for i in range(w.shape[0])]
    return (jnp.stack([q for q, _ in parts]),
            jnp.stack([s for _, s in parts]))


def quantize_into(store: dict, name: str, arr: jnp.ndarray) -> None:
    """Store ``arr`` under ``name``, quantizing it (int8 + ``<name>_scale``
    sibling) when it is a matmul weight — the ONE place that defines the
    storage convention ``_mm`` (models/model.py) and the sharding rules
    (parallel/sharding.py) consume."""
    if name in MATMUL_WEIGHTS:
        q, s = quantize_stacked(arr)
        store[name] = q
        store[name + "_scale"] = s
    else:
        store[name] = arr


def quantize_params(params: dict) -> dict:
    """Return a params tree with matmul weights in int8 + ``*_scale``
    leaves.  Norms, biases and the embedding stay in their dtype."""
    out: dict = {}
    for name, value in params.items():
        if name == "layers":
            layers: dict = {}
            for k, v in value.items():
                quantize_into(layers, k, v)
            out["layers"] = layers
        else:
            quantize_into(out, name, value)
    return out


def is_quantized(params: dict) -> bool:
    layers = params.get("layers", {})
    return any(k.endswith("_scale") for k in layers)
