"""Model architecture configs + HF ``config.json`` parsing.

One dataclass covers the decoder-only families in the reference model zoo
(model_list.txt): the llama family (Llama/CodeLlama, DeepSeek-Coder,
Mistral, Magicoder), Gemma, and StarCoder2.  Family-specific behaviour is
explicit flags, not subclasses — the forward pass branches on them
statically so jit sees fixed control flow.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from pathlib import Path

__all__ = ["ModelConfig", "load_hf_config"]


@dataclass
class ModelConfig:
    vocab_size: int
    hidden_size: int
    intermediate_size: int
    num_layers: int
    num_heads: int
    num_kv_heads: int
    head_dim: int
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-6
    max_position_embeddings: int = 16384
    tie_word_embeddings: bool = False
    family: str = "llama"          # llama | gemma | starcoder2
    # family flags
    norm_offset: float = 0.0        # gemma: weights stored as (w - 1)
    embed_scale: float | None = None  # gemma: embeddings scaled by sqrt(hidden)
    use_layernorm: bool = False     # starcoder2: LayerNorm (with bias) not RMSNorm
    mlp_gated: bool = True          # starcoder2: plain GELU MLP (c_fc/c_proj)
    attention_bias: bool = False    # starcoder2 uses biases on qkv/o
    mlp_bias: bool = False
    sliding_window: int | None = None  # mistral/starcoder2: attend last W keys
    hidden_act: str = "silu"
    dtype: str = "bfloat16"
    # gemma-2 family flags
    use_post_norms: bool = False     # sandwich norms around attn + mlp outputs
    alt_sliding: bool = False        # sliding window on EVEN layers only
    attn_softcap: float | None = None    # tanh softcap on attention scores
    final_softcap: float | None = None   # tanh softcap on lm logits
    query_scale: float | None = None     # attention scale = query_scale**-0.5
    # mixture-of-experts (mixtral): 0 experts = dense MLP
    num_experts: int = 0
    num_experts_per_tok: int = 2
    # "ragged": exact sort + lax.ragged_dot (dropless, HF-equivalent);
    # "dispatch": GShard dispatch (ep-shardable — engines switch to it
    # automatically on an ep>1 mesh)
    moe_impl: str = "ragged"
    # dispatch slots per expert.  None (default) = EXACT drop-free
    # dispatch: capacity covers every assignment and the dispatch chunks
    # long token batches to bound its buffer — for an evaluation
    # framework, batch-dependent logits are a correctness hazard, so
    # lossy routing must be a loud opt-in.  A float trades exactness for
    # compute: that multiple of the uniform load, assignments beyond it
    # DROP under router skew.
    moe_capacity_factor: float | None = None

    @property
    def q_per_kv(self) -> int:
        return self.num_heads // self.num_kv_heads

    @property
    def attn_scale(self) -> float:
        base = self.query_scale if self.query_scale is not None else self.head_dim
        return float(base) ** -0.5

    def window_for_layer(self, i: int) -> int | None:
        """Static sliding window for layer ``i`` (gemma-2 alternates:
        sliding on even layers, global on odd — HF ``layer_types``)."""
        if self.alt_sliding:
            return self.sliding_window if i % 2 == 0 else None
        return self.sliding_window

    def layer_windows_array(self):
        """[L] int32 window sizes for traced (scan-based) layer loops;
        global layers get a sentinel larger than any position."""
        import jax.numpy as jnp

        big = 1 << 30
        vals = [self.window_for_layer(i) or big for i in range(self.num_layers)]
        return jnp.asarray(vals, jnp.int32)


def load_hf_config(model_path: str | Path) -> ModelConfig:
    """Parse a HuggingFace ``config.json`` into a :class:`ModelConfig`."""
    with open(Path(model_path) / "config.json") as f:
        hf = json.load(f)
    model_type = hf.get("model_type", "llama")
    common = dict(
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        intermediate_size=hf["intermediate_size"],
        num_layers=hf["num_hidden_layers"],
        num_heads=hf["num_attention_heads"],
        num_kv_heads=hf.get("num_key_value_heads", hf["num_attention_heads"]),
        # some configs carry an explicit null head_dim
        head_dim=hf.get("head_dim") or hf["hidden_size"] // hf["num_attention_heads"],
        rope_theta=hf.get("rope_theta", 10000.0),
        max_position_embeddings=hf.get("max_position_embeddings", 16384),
        tie_word_embeddings=hf.get("tie_word_embeddings", False),
        hidden_act=hf.get("hidden_act", hf.get("hidden_activation", "silu")),
        sliding_window=hf.get("sliding_window"),
    )
    if model_type == "mixtral":
        return ModelConfig(
            family="llama", rms_norm_eps=hf.get("rms_norm_eps", 1e-6),
            num_experts=hf["num_local_experts"],
            num_experts_per_tok=hf.get("num_experts_per_tok", 2),
            **common)
    if model_type in ("llama", "mistral", "deepseek"):
        return ModelConfig(family="llama", rms_norm_eps=hf.get("rms_norm_eps", 1e-6), **common)
    if model_type == "gemma":
        return ModelConfig(
            family="gemma",
            rms_norm_eps=hf.get("rms_norm_eps", 1e-6),
            norm_offset=1.0,
            embed_scale=float(hf["hidden_size"]) ** 0.5,
            **{**common, "tie_word_embeddings": True},
        )
    if model_type == "gemma2":
        return ModelConfig(
            family="gemma",
            rms_norm_eps=hf.get("rms_norm_eps", 1e-6),
            norm_offset=1.0,
            embed_scale=float(hf["hidden_size"]) ** 0.5,
            use_post_norms=True,
            alt_sliding=True,
            attn_softcap=hf.get("attn_logit_softcapping"),
            final_softcap=hf.get("final_logit_softcapping"),
            query_scale=hf.get("query_pre_attn_scalar"),
            **{**common, "tie_word_embeddings": True},
        )
    if model_type == "starcoder2":
        return ModelConfig(
            family="starcoder2",
            rms_norm_eps=hf.get("norm_epsilon", 1e-5),
            use_layernorm=True,
            mlp_gated=False,
            attention_bias=hf.get("use_bias", True),
            mlp_bias=hf.get("use_bias", True),
            **common,
        )
    raise ValueError(f"unsupported model_type {model_type!r} (supported: "
                     f"llama/mistral/deepseek, mixtral, gemma, gemma2, "
                     f"starcoder2)")
