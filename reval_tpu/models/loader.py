"""HuggingFace safetensors checkpoints → stacked JAX pytrees.

Per-family weight-name maps (HF llama/gemma/starcoder2 module paths →
our flat stacked layout).  Loading is streaming and layer-wise: each tensor
is read from safetensors, transposed ``[out,in]`` → ``[in,out]`` where it
is a projection, cast to the target dtype, and stacked across layers —
peak host memory is ~one checkpoint shard, and the result can be
``jax.device_put`` with shardings applied (see parallel/sharding.py).

Equivalent of the checkpoint path vLLM performs internally for the
reference (SURVEY §2.11); here it is in-tree and TPU-shaped.
"""

from __future__ import annotations

import json
from pathlib import Path

import jax.numpy as jnp
import numpy as np

from .configs import ModelConfig, load_hf_config

__all__ = ["load_checkpoint", "init_random_params", "init_random_int4", "param_template"]

_DTYPES = {"bfloat16": jnp.bfloat16, "float32": jnp.float32, "float16": jnp.float16}


# our name → (HF template, transpose?)  `{i}` is the layer index.
def _weight_map(cfg: ModelConfig) -> dict:
    if cfg.family in ("llama", "gemma"):
        m = {
            "attn_norm_w": ("model.layers.{i}.input_layernorm.weight", False),
            "q_w": ("model.layers.{i}.self_attn.q_proj.weight", True),
            "k_w": ("model.layers.{i}.self_attn.k_proj.weight", True),
            "v_w": ("model.layers.{i}.self_attn.v_proj.weight", True),
            "o_w": ("model.layers.{i}.self_attn.o_proj.weight", True),
            "mlp_norm_w": ("model.layers.{i}.post_attention_layernorm.weight", False),
            "gate_w": ("model.layers.{i}.mlp.gate_proj.weight", True),
            "up_w": ("model.layers.{i}.mlp.up_proj.weight", True),
            "down_w": ("model.layers.{i}.mlp.down_proj.weight", True),
        }
        if cfg.num_experts:      # mixtral: MoE block replaces the dense MLP
            for dense in ("gate_w", "up_w", "down_w"):
                del m[dense]
            m.update({
                "router_w": ("model.layers.{i}.block_sparse_moe.gate.weight", True),
                # HF names: w1 = gate, w2 = down, w3 = up ({e} = expert idx)
                "moe_gate_w": ("model.layers.{i}.block_sparse_moe.experts.{e}.w1.weight", True),
                "moe_down_w": ("model.layers.{i}.block_sparse_moe.experts.{e}.w2.weight", True),
                "moe_up_w": ("model.layers.{i}.block_sparse_moe.experts.{e}.w3.weight", True),
            })
        if cfg.use_post_norms:   # gemma-2 sandwich norms: HF's
            # post_attention_layernorm is the POST-attn norm (not the MLP
            # pre-norm as in llama); the MLP norms have their own names
            m.update({
                "post_attn_norm_w": ("model.layers.{i}.post_attention_layernorm.weight", False),
                "mlp_norm_w": ("model.layers.{i}.pre_feedforward_layernorm.weight", False),
                "post_mlp_norm_w": ("model.layers.{i}.post_feedforward_layernorm.weight", False),
            })
        return m
    if cfg.family == "starcoder2":
        m = {
            "attn_norm_w": ("model.layers.{i}.input_layernorm.weight", False),
            "attn_norm_b": ("model.layers.{i}.input_layernorm.bias", False),
            "q_w": ("model.layers.{i}.self_attn.q_proj.weight", True),
            "k_w": ("model.layers.{i}.self_attn.k_proj.weight", True),
            "v_w": ("model.layers.{i}.self_attn.v_proj.weight", True),
            "o_w": ("model.layers.{i}.self_attn.o_proj.weight", True),
            "mlp_norm_w": ("model.layers.{i}.post_attention_layernorm.weight", False),
            "mlp_norm_b": ("model.layers.{i}.post_attention_layernorm.bias", False),
            "fc_w": ("model.layers.{i}.mlp.c_fc.weight", True),
            "fc_b": ("model.layers.{i}.mlp.c_fc.bias", False),
            "proj_w": ("model.layers.{i}.mlp.c_proj.weight", True),
            "proj_b": ("model.layers.{i}.mlp.c_proj.bias", False),
        }
        if cfg.attention_bias:
            m.update({
                "q_b": ("model.layers.{i}.self_attn.q_proj.bias", False),
                "k_b": ("model.layers.{i}.self_attn.k_proj.bias", False),
                "v_b": ("model.layers.{i}.self_attn.v_proj.bias", False),
                "o_b": ("model.layers.{i}.self_attn.o_proj.bias", False),
            })
        return m
    raise ValueError(f"no weight map for family {cfg.family}")


_TOP_LEVEL = {
    "embed": ("model.embed_tokens.weight", False),
    "final_norm_w": ("model.norm.weight", False),
    "final_norm_b": ("model.norm.bias", False),       # starcoder2 only
    "lm_head": ("lm_head.weight", True),              # absent when tied
}


class _ShardedReader:
    """Random access over one or many safetensors shards by tensor name."""

    def __init__(self, model_path: Path):
        from safetensors import safe_open

        self._open = safe_open
        index_path = model_path / "model.safetensors.index.json"
        self.files: dict[str, Path] = {}
        if index_path.exists():
            with open(index_path) as f:
                index = json.load(f)
            for tensor, fname in index["weight_map"].items():
                self.files[tensor] = model_path / fname
        else:
            single = model_path / "model.safetensors"
            with safe_open(single, framework="numpy") as f:
                for tensor in f.keys():
                    self.files[tensor] = single
        self._handles: dict[Path, object] = {}

    def __contains__(self, name: str) -> bool:
        return name in self.files

    def get(self, name: str) -> np.ndarray:
        path = self.files[name]
        if path not in self._handles:
            self._handles[path] = self._open(path, framework="numpy")
        tensor = self._handles[path].get_tensor(name)
        # numpy has no bfloat16: safetensors returns a uint16 view via
        # ml_dtypes in recent versions; jnp.asarray handles both.
        return tensor


def load_checkpoint(model_path: str | Path, dtype: str = "bfloat16",
                    cfg: ModelConfig | None = None):
    """Load an HF checkpoint directory into (params pytree, ModelConfig).

    ``dtype="int8"``: bf16 activations with weight-only int8 matmul
    weights (models/quant.py) — halves weight HBM reads and fits ~2×
    the parameters per chip.  ``dtype="int4"``: group-wise weight-only
    int4 (4× smaller weights — CodeLlama-34B in ~17 GB fits a v5e-8
    tp-sharded WITH page-pool headroom, the shape the reference needed
    multi-A800 vLLM tensor parallelism for)."""
    model_path = Path(model_path)
    cfg = cfg or load_hf_config(model_path)
    qmode = dtype if dtype in ("int8", "int4") else None
    if qmode:
        dtype = "bfloat16"
    from .awq import awq_config, awq_to_leaves, gptq_config, gptq_to_leaves

    awq = awq_config(model_path)
    gptq = None if awq else gptq_config(model_path)
    prequant = awq_to_leaves if awq else (gptq_to_leaves if gptq else None)
    if prequant:
        # checkpoint ships pre-quantized int4 (AWQ/GPTQ): ingest as-is —
        # requesting int8/int4 on top is a no-op, the weights already are
        qmode = None
        if cfg.num_experts:
            raise NotImplementedError(
                "pre-quantized MoE checkpoints are not supported — "
                "dense families only")
    cfg.dtype = dtype
    target = _DTYPES[dtype]
    reader = _ShardedReader(model_path)

    def fetch(template: str, transpose: bool, i: int | None = None,
              e: int | None = None):
        name = template if i is None else template.format(i=i, e=e)
        arr = np.asarray(reader.get(name))
        if transpose:
            arr = arr.T
        return arr

    def place(store: dict, name: str, arr: jnp.ndarray) -> None:
        """Store a leaf, quantizing matmul weights leaf-by-leaf — the
        whole-tree quantize-after-load would hold bf16 AND quantized
        copies of the model at once (20 GB for 6.7b: an OOM on a 16 GB
        chip)."""
        from .quant import quantize_into

        if qmode:
            quantize_into(store, name, arr, qmode)
        else:
            store[name] = arr

    def awq_stacked(store: dict, our_name: str, base: str,
                    n: int | None = None) -> None:
        """Read one pre-quantized linear (``base``.{qweight,qzeros,scales},
        AWQ and GPTQ both store [in, out]-major — no transpose) into
        int4 + gscale + gzero leaves; ``n`` stacks across layers."""

        def one(i):
            return prequant(
                np.asarray(reader.get(base.format(i=i) + ".qweight")),
                np.asarray(reader.get(base.format(i=i) + ".qzeros")),
                np.asarray(reader.get(base.format(i=i) + ".scales")))

        parts = [one(i) for i in range(n)] if n is not None else [one(0)]
        stack = (lambda xs: np.stack(xs)) if n is not None else (lambda xs: xs[0])
        store[our_name] = jnp.asarray(stack([p[0] for p in parts]))
        store[our_name + "_gscale"] = jnp.asarray(stack([p[1] for p in parts]))
        store[our_name + "_gzero"] = jnp.asarray(stack([p[2] for p in parts]))

    params: dict = {}
    params["embed"] = jnp.asarray(fetch(*_TOP_LEVEL["embed"]), dtype=target)
    params["final_norm_w"] = jnp.asarray(fetch(*_TOP_LEVEL["final_norm_w"]), dtype=target)
    if _TOP_LEVEL["final_norm_b"][0] in reader:
        params["final_norm_b"] = jnp.asarray(fetch(*_TOP_LEVEL["final_norm_b"]), dtype=target)
    if not cfg.tie_word_embeddings:
        lm_base = _TOP_LEVEL["lm_head"][0].removesuffix(".weight")
        if _TOP_LEVEL["lm_head"][0] in reader:
            place(params, "lm_head",
                  jnp.asarray(fetch(*_TOP_LEVEL["lm_head"]), dtype=target))
        elif prequant and lm_base + ".qweight" in reader:
            awq_stacked(params, "lm_head", lm_base)
        else:
            cfg.tie_word_embeddings = True  # checkpoint ties implicitly

    layers: dict[str, jnp.ndarray] = {}
    for our_name, (template, transpose) in _weight_map(cfg).items():
        base = template.removesuffix(".weight")
        if (prequant and template.endswith(".weight")
                and base.format(i=0) + ".qweight" in reader):
            awq_stacked(layers, our_name, base, cfg.num_layers)
            continue
        if template.format(i=0, e=0) not in reader:
            continue  # optional weight absent in this checkpoint
        if "{e}" in template:   # expert-stacked: [L, E, ...]
            stacked = np.stack([
                np.stack([fetch(template, transpose, i, ei)
                          for ei in range(cfg.num_experts)])
                for i in range(cfg.num_layers)])
        else:
            stacked = np.stack([fetch(template, transpose, i)
                                for i in range(cfg.num_layers)])
        place(layers, our_name, jnp.asarray(stacked, dtype=target))
    params["layers"] = layers
    return params, cfg


def param_template(cfg: ModelConfig) -> dict:
    """Shapes/dtypes of the params pytree (for sharding-rule construction
    and random init) without reading any checkpoint."""
    E, F, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers
    H, HK, D, V = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim, cfg.vocab_size
    layers = {
        "attn_norm_w": (L, E),
        "q_w": (L, E, H * D),
        "k_w": (L, E, HK * D),
        "v_w": (L, E, HK * D),
        "o_w": (L, H * D, E),
        "mlp_norm_w": (L, E),
    }
    if cfg.num_experts:
        layers.update({
            "router_w": (L, E, cfg.num_experts),
            "moe_gate_w": (L, cfg.num_experts, E, F),
            "moe_up_w": (L, cfg.num_experts, E, F),
            "moe_down_w": (L, cfg.num_experts, F, E),
        })
    elif cfg.mlp_gated:
        layers.update({"gate_w": (L, E, F), "up_w": (L, E, F), "down_w": (L, F, E)})
    else:
        layers.update({"fc_w": (L, E, F), "proj_w": (L, F, E)})
        if cfg.mlp_bias:
            layers.update({"fc_b": (L, F), "proj_b": (L, E)})
    if cfg.use_post_norms:
        layers.update({"post_attn_norm_w": (L, E), "post_mlp_norm_w": (L, E)})
    if cfg.use_layernorm:
        layers.update({"attn_norm_b": (L, E), "mlp_norm_b": (L, E)})
    if cfg.attention_bias:
        layers.update({"q_b": (L, H * D), "k_b": (L, HK * D), "v_b": (L, HK * D), "o_b": (L, E)})
    tree = {"embed": (V, E), "final_norm_w": (E,), "layers": layers}
    if cfg.use_layernorm:
        tree["final_norm_b"] = (E,)
    if not cfg.tie_word_embeddings:
        tree["lm_head"] = (E, V)
    return tree


def init_random_params(cfg: ModelConfig, seed: int = 0, dtype: str = "float32",
                       tp: int = 1) -> dict:
    """Random params matching the template — benches and sharding tests run
    real architectures without real checkpoints (this host has no egress).
    ``dtype="int8"``/``"int4"`` quantizes matmul weights leaf-by-leaf as
    they are drawn (models/quant.py), so the float tree is never fully
    resident.  ``tp``: intended tensor-parallel width — aligns int4 group
    boundaries to shard boundaries (same rule as the shard-direct loader),
    so a 34B-class tree can be born int4 AND born shard-aligned."""
    import jax

    qmode = dtype if dtype in ("int8", "int4") else None
    target = _DTYPES["bfloat16" if qmode else dtype]
    template = param_template(cfg)
    key = jax.random.PRNGKey(seed)
    flat: dict = {}

    def init_leaf(path, shape):
        nonlocal key
        key, sub = jax.random.split(key)
        scale = 0.02 if len(shape) > 1 else 1.0
        arr = jax.random.normal(sub, shape, dtype=jnp.float32) * scale
        if path.endswith("norm_w") and not cfg.use_layernorm and cfg.norm_offset == 0.0:
            arr = jnp.ones(shape, jnp.float32)
        return arr.astype(target)

    def place(store, name, shape):
        from .quant import MATMUL_WEIGHTS, quantize_into

        if qmode and name in MATMUL_WEIGHTS and len(shape) >= 3:
            # draw + quantize layer-by-layer: the stacked fp32 draw alone
            # is multi-GB at 6.7b scale (see quant.quantize_stacked)
            parts: dict = {}
            for _ in range(shape[0]):
                tmp: dict = {}
                quantize_into(tmp, name, init_leaf(name, shape[1:]), qmode,
                              tp=tp)
                for k, v in tmp.items():
                    parts.setdefault(k, []).append(v)
            for k, v in parts.items():
                store[k] = jnp.stack(v)
            return
        leaf = init_leaf(name, shape)
        if qmode:
            quantize_into(store, name, leaf, qmode, tp=tp)
        else:
            store[name] = leaf

    for name, value in template.items():
        if name == "layers":
            flat["layers"] = {}
            for k, shape in value.items():
                place(flat["layers"], k, shape)
        else:
            place(flat, name, value)
    return flat


def init_random_int4(cfg: ModelConfig, seed: int = 0, tp: int = 1) -> dict:
    """Random int4 params WITHOUT the float draw-and-quantize pass:
    matmul weights are uniform int4 codes + uniform group scales written
    directly (numpy, ~GB/s), everything else a small normal draw.  Same
    leaf conventions as :func:`quant.quantize_into` (``<name>_gscale``
    siblings, tp-aligned groups), so engines consume the tree unchanged.

    This exists for the 34B north-star dryrun: drawing 34e9 normals
    through jax.random and quantizing them takes the best part of an
    hour on a CPU host, while the resulting VALUES are irrelevant to
    footprint/compile/sharding validation — only sizes, dtypes and group
    geometry matter."""
    import ml_dtypes

    from .quant import MATMUL_WEIGHTS, _tp_aligned_group

    rng = np.random.default_rng(seed)
    template = param_template(cfg)

    def fill(store: dict, name: str, shape: tuple) -> None:
        if name in MATMUL_WEIGHTS and len(shape) >= 2:
            *lead, n_in, n_out = shape
            g = _tp_aligned_group(n_in, tp)
            codes = rng.integers(-7, 8, size=shape, dtype=np.int8)
            store[name] = jnp.asarray(codes.astype(ml_dtypes.int4))
            scales = rng.uniform(0.001, 0.004,
                                 size=(*lead, n_in // g, n_out))
            store[name + "_gscale"] = jnp.asarray(scales.astype(np.float32))
        else:
            arr = rng.standard_normal(shape, dtype=np.float32)
            scale = 0.02 if len(shape) > 1 else 1.0
            if (name.endswith("norm_w") and not cfg.use_layernorm
                    and cfg.norm_offset == 0.0):
                arr = np.ones(shape, np.float32)
                scale = 1.0
            store[name] = jnp.asarray((arr * scale).astype(ml_dtypes.bfloat16))

    flat: dict = {}
    for name, value in template.items():
        if name == "layers":
            flat["layers"] = {}
            for k, shape in value.items():
                fill(flat["layers"], k, shape)
        else:
            fill(flat, name, value)
    return flat
