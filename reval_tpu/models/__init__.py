"""JAX model zoo: one functional decoder, per-family configs + loaders.

Covers the reference model zoo (model_list.txt): llama family (CodeLlama,
DeepSeek-Coder, Mistral, Magicoder), Gemma, StarCoder2."""

from .configs import ModelConfig, load_hf_config
from .loader import (init_random_int4, init_random_params, load_checkpoint,
                     param_template)
from .model import (
    KVCache,
    decode_step,
    init_kv_cache,
    logits_for_tokens,
    prefill,
    prefill_with_batched_context,
    prefill_with_context,
)
from .quant import is_quantized, quantize_params
from .sharded_loader import load_checkpoint_sharded
from .zoo import MODEL_ZOO, ZooEntry, zoo_config, zoo_entry

__all__ = [
    "KVCache",
    "MODEL_ZOO",
    "ModelConfig",
    "ZooEntry",
    "decode_step",
    "init_kv_cache",
    "init_random_int4",
    "init_random_params",
    "is_quantized",
    "load_checkpoint",
    "load_checkpoint_sharded",
    "load_hf_config",
    "logits_for_tokens",
    "param_template",
    "prefill",
    "prefill_with_batched_context",
    "prefill_with_context",
    "quantize_params",
    "zoo_config",
    "zoo_entry",
]
