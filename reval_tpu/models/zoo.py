"""Model zoo: the benchmark's evaluated models + flagship targets.

The reference ships a flat list of HF ids (model_list.txt:1-13); here each
entry also carries the architecture family (all are covered by
:class:`~reval_tpu.models.configs.ModelConfig` flags) and the known model
dimensions, so shape-only work — benchmarking, sharding dry-runs,
compile-cache warming — needs no checkpoint download.
"""

from __future__ import annotations

from dataclasses import dataclass

from .configs import ModelConfig

__all__ = ["ZooEntry", "MODEL_ZOO", "zoo_entry", "zoo_config"]


@dataclass(frozen=True)
class ZooEntry:
    hf_id: str
    family: str                 # llama | gemma | starcoder2  (configs.py)
    n_params: str
    dims: dict                  # ModelConfig kwargs (architecture shape)


def _llama(vocab, hidden, inter, layers, heads, kv_heads=None, head_dim=None,
           rope_theta=10000.0, **extra) -> dict:
    return dict(vocab_size=vocab, hidden_size=hidden, intermediate_size=inter,
                num_layers=layers, num_heads=heads,
                num_kv_heads=kv_heads or heads,
                head_dim=head_dim or hidden // heads,
                rope_theta=rope_theta, **extra)


MODEL_ZOO: dict[str, ZooEntry] = {
    # -- the reference's evaluated models (model_list.txt) ----------------
    "google/gemma-2b-it": ZooEntry(
        "google/gemma-2b-it", "gemma", "2B",
        _llama(256000, 2048, 16384, 18, 8, kv_heads=1, head_dim=256,
               family="gemma", norm_offset=1.0, embed_scale=2048 ** 0.5,
               tie_word_embeddings=True, hidden_act="gelu_pytorch_tanh")),
    "google/gemma-7b-it": ZooEntry(
        "google/gemma-7b-it", "gemma", "7B",
        _llama(256000, 3072, 24576, 28, 16, head_dim=256,
               family="gemma", norm_offset=1.0, embed_scale=3072 ** 0.5,
               tie_word_embeddings=True, hidden_act="gelu_pytorch_tanh")),
    "mistralai/Mistral-7B-Instruct-v0.2": ZooEntry(
        "mistralai/Mistral-7B-Instruct-v0.2", "llama", "7B",
        _llama(32000, 4096, 14336, 32, 32, kv_heads=8, rope_theta=1000000.0)),
    "codellama/CodeLlama-7b-hf": ZooEntry(
        "codellama/CodeLlama-7b-hf", "llama", "7B",
        _llama(32016, 4096, 11008, 32, 32, rope_theta=1000000.0)),
    "codellama/CodeLlama-7b-Instruct-hf": ZooEntry(
        "codellama/CodeLlama-7b-Instruct-hf", "llama", "7B",
        _llama(32016, 4096, 11008, 32, 32, rope_theta=1000000.0)),
    "codellama/CodeLlama-7b-Python-hf": ZooEntry(
        "codellama/CodeLlama-7b-Python-hf", "llama", "7B",
        _llama(32000, 4096, 11008, 32, 32, rope_theta=1000000.0)),
    "codellama/CodeLlama-13b-Instruct-hf": ZooEntry(
        "codellama/CodeLlama-13b-Instruct-hf", "llama", "13B",
        _llama(32016, 5120, 13824, 40, 40, rope_theta=1000000.0)),
    "codellama/CodeLlama-34b-Instruct-hf": ZooEntry(
        "codellama/CodeLlama-34b-Instruct-hf", "llama", "34B",
        _llama(32000, 8192, 22016, 48, 64, kv_heads=8, rope_theta=1000000.0)),
    "bigcode/starcoder2-3b": ZooEntry(
        "bigcode/starcoder2-3b", "starcoder2", "3B",
        _llama(49152, 3072, 12288, 30, 24, kv_heads=2, rope_theta=999999.4,
               family="starcoder2", use_layernorm=True, mlp_gated=False,
               attention_bias=True, mlp_bias=True, hidden_act="gelu_pytorch_tanh",
               rms_norm_eps=1e-5)),
    "bigcode/starcoder2-7b": ZooEntry(
        "bigcode/starcoder2-7b", "starcoder2", "7B",
        _llama(49152, 4608, 18432, 32, 36, kv_heads=4, rope_theta=1000000.0,
               family="starcoder2", use_layernorm=True, mlp_gated=False,
               attention_bias=True, mlp_bias=True, hidden_act="gelu_pytorch_tanh",
               rms_norm_eps=1e-5)),
    "bigcode/starcoder2-15b": ZooEntry(
        "bigcode/starcoder2-15b", "starcoder2", "15B",
        _llama(49152, 6144, 24576, 40, 48, kv_heads=4, rope_theta=100000.0,
               family="starcoder2", use_layernorm=True, mlp_gated=False,
               attention_bias=True, mlp_bias=True, hidden_act="gelu_pytorch_tanh",
               rms_norm_eps=1e-5)),
    "ise-uiuc/Magicoder-CL-7B": ZooEntry(
        "ise-uiuc/Magicoder-CL-7B", "llama", "7B",
        _llama(32001, 4096, 11008, 32, 32, rope_theta=1000000.0)),
    "ise-uiuc/Magicoder-S-CL-7B": ZooEntry(
        "ise-uiuc/Magicoder-S-CL-7B", "llama", "7B",
        _llama(32001, 4096, 11008, 32, 32, rope_theta=1000000.0)),
    # -- flagship/benchmark targets (BASELINE.json configs) ---------------
    "deepseek-ai/deepseek-coder-1.3b-base": ZooEntry(
        "deepseek-ai/deepseek-coder-1.3b-base", "llama", "1.3B",
        _llama(32256, 2048, 5504, 24, 16, rope_theta=100000.0)),
    "deepseek-ai/deepseek-coder-6.7b-base": ZooEntry(
        "deepseek-ai/deepseek-coder-6.7b-base", "llama", "6.7B",
        _llama(32256, 4096, 11008, 32, 32, rope_theta=100000.0)),
    "codellama/CodeLlama-70b-Instruct-hf": ZooEntry(
        "codellama/CodeLlama-70b-Instruct-hf", "llama", "70B",
        _llama(32016, 8192, 28672, 80, 64, kv_heads=8, rope_theta=1000000.0)),
    # beyond the reference list: MoE coding model (expert parallelism target)
    "mistralai/Mixtral-8x7B-Instruct-v0.1": ZooEntry(
        "mistralai/Mixtral-8x7B-Instruct-v0.1", "llama", "8x7B",
        _llama(32000, 4096, 14336, 32, 32, kv_heads=8, rope_theta=1000000.0,
               num_experts=8, num_experts_per_tok=2)),
    # beyond the reference list: gemma-2 (sandwich norms, softcaps,
    # alternating sliding/global attention)
    "google/gemma-2-2b-it": ZooEntry(
        "google/gemma-2-2b-it", "gemma", "2B",
        _llama(256000, 2304, 9216, 26, 8, kv_heads=4, head_dim=256,
               family="gemma", norm_offset=1.0, embed_scale=2304 ** 0.5,
               tie_word_embeddings=True, hidden_act="gelu_pytorch_tanh",
               use_post_norms=True, alt_sliding=True, sliding_window=4096,
               attn_softcap=50.0, final_softcap=30.0, query_scale=256.0)),
    "google/gemma-2-9b-it": ZooEntry(
        "google/gemma-2-9b-it", "gemma", "9B",
        _llama(256000, 3584, 14336, 42, 16, kv_heads=8, head_dim=256,
               family="gemma", norm_offset=1.0, embed_scale=3584 ** 0.5,
               tie_word_embeddings=True, hidden_act="gelu_pytorch_tanh",
               use_post_norms=True, alt_sliding=True, sliding_window=4096,
               attn_softcap=50.0, final_softcap=30.0, query_scale=256.0)),
}

# short aliases (config files accept either)
_ALIASES = {
    "deepseek-coder-1.3b": "deepseek-ai/deepseek-coder-1.3b-base",
    "deepseek-coder-6.7b": "deepseek-ai/deepseek-coder-6.7b-base",
    "codellama-34b": "codellama/CodeLlama-34b-Instruct-hf",
    "codellama-70b": "codellama/CodeLlama-70b-Instruct-hf",
    "mixtral-8x7b": "mistralai/Mixtral-8x7B-Instruct-v0.1",
}


def zoo_entry(name: str) -> ZooEntry:
    name = _ALIASES.get(name, name)
    if name not in MODEL_ZOO:
        raise KeyError(f"unknown zoo model {name!r}; known: {sorted(MODEL_ZOO)}")
    return MODEL_ZOO[name]


def zoo_config(name: str, dtype: str = "bfloat16") -> ModelConfig:
    """Architecture config for a zoo model (no checkpoint needed)."""
    entry = zoo_entry(name)
    return ModelConfig(dtype=dtype, **entry.dims)
