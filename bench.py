"""Throughput benchmark: DREval probes/sec/chip with the in-tree TPU engine.

Runs the *real* evaluation pipeline — coverage-task planning over HumanEval
builds genuine few-shot prompts, the TPU engine generates with the
benchmark's stop string — on a deepseek-coder-1.3b-shaped model with random
bf16 weights (this host has no checkpoint egress; throughput does not
depend on weight values).

Shape realism (round-1 verdict items 1+3):
- prompts tokenised with a **BPE tokenizer trained on the benchmark corpus**
  (realistic ~3-4 chars/token, not byte-level inflation);
- the reference's direct-mode budget of 256 new tokens
  (reference inference.py:25), CoT=1024 via ``--mode cot``;
- serial baseline measured over >= 32 prompts (the reference harness shape:
  one ``Model.infer`` per probe, reference evaluation.py:105-107);
- prefix-sharing A/B on the same prompt set.

Robustness: the TPU tunnel on this host can wedge such that
``jax.devices()`` blocks forever.  Before touching JAX in-process, a
subprocess probe with a hard timeout checks device health, with bounded
retries; on failure the bench emits a STRUCTURED error JSON line
(``"error": "tpu-unreachable"``) instead of a crash traceback, so a wedge
is distinguishable from a code bug.

Prints exactly ONE JSON line:
``{"metric", "value", "unit", "vs_baseline", ...extras}``.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

# per-chip specs by device_kind substring (public spec sheets):
# (key, bf16 peak FLOPs/s, HBM bandwidth bytes/s).  Decode is
# bandwidth-bound, so achieved fraction of the HBM roofline — not MFU —
# is the "is it actually fast?" lens (round-4 verdict item 5).
CHIP_SPECS = [
    ("v6", 918e12, 1640e9),        # Trillium
    ("v5p", 459e12, 2765e9),
    ("v5 lite", 197e12, 819e9),    # v5e reports "TPU v5 lite"
    ("v5e", 197e12, 819e9),
    ("v4", 275e12, 1228e9),
    ("v3", 123e12, 900e9),
    ("v2", 45e12, 700e9),
]
DEFAULT_SPEC = (197e12, 819e9)     # unknown chip: assume v5e


def _chip_spec(device_kind: str) -> tuple[float, float]:
    kind = device_kind.lower()
    for key, flops, bw in CHIP_SPECS:
        if key in kind:
            return flops, bw
    return DEFAULT_SPEC


def peak_flops_for(device_kind: str) -> float:
    return _chip_spec(device_kind)[0]


def hbm_bw_for(device_kind: str) -> float:
    return _chip_spec(device_kind)[1]


# -- pre-flight ------------------------------------------------------------

def probe_devices(timeout_s: int = 60, retries: int = 6, wait_s: int = 60,
                  force_cpu: bool = False, runner=None, sleep=None,
                  ) -> tuple[tuple[int, str, str] | None, str]:
    """(n_devices, device_kind, platform) via a KILLABLE subprocess.

    ``jax.devices()`` in a wedged-tunnel state blocks forever inside the
    backend plugin — in-process timeouts (SIGALRM) are not reliable there,
    so the probe must be a separate process we can kill.  ``force_cpu``
    uses ``jax.config`` (the env var does NOT override this image's site
    hook that pins the TPU plugin).

    Self-heal: the attempts run under the resilience layer's
    :class:`RetryPolicy` — exponential backoff with jitter from
    ``wait_s`` up, capped at four minutes — instead of the old fixed
    one-minute sleep, so a tunnel that wedges for a couple of minutes
    (the common transient, BENCH_r02–r05's blind spot) gets probed again
    PAST its wedge window before the round is declared ``stale``.  A
    wedged probe raises ``TimeoutError`` and a crashed probe
    ``ConnectionError``, both transport-shaped for the policy's
    classification; ``runner``/``sleep`` are injectable so the backoff
    schedule is unit-testable without subprocesses or real waits.
    """
    from reval_tpu.resilience import RetryPolicy

    cpu = ("jax.config.update('jax_platforms', 'cpu'); " if force_cpu else "")
    code = ("import jax; " + cpu + "ds = jax.devices(); "
            "print(len(ds), ds[0].device_kind, ds[0].platform, sep='|')")
    run = runner if runner is not None else subprocess.run

    def attempt() -> tuple[int, str, str]:
        try:
            r = run([sys.executable, "-c", code], capture_output=True,
                    text=True, timeout=timeout_s)
        except subprocess.TimeoutExpired:
            raise TimeoutError("timeout") from None
        line = (r.stdout.strip().splitlines() or [""])[-1]
        if r.returncode == 0 and line.count("|") == 2:
            n, kind, platform = line.split("|")
            return int(n), kind, platform
        # crash, not a wedge: keep the real cause for the error JSON
        # (still retried — a tunnel mid-recovery can crash the plugin)
        raise ConnectionError(f"probe exited rc={r.returncode}: "
                              f"{r.stderr.strip()[-800:]}")

    policy = RetryPolicy(max_attempts=max(1, int(retries)),
                         base_delay=float(wait_s), max_delay=240.0,
                         multiplier=2.0, jitter=0.25,
                         **({"sleep": sleep} if sleep is not None else {}))
    try:
        return policy.call(attempt, label="bench.device-probe"), ""
    except TimeoutError:
        return None, "timeout"
    except ConnectionError as exc:
        return None, str(exc)


def emit(obj: dict) -> None:
    print(json.dumps(obj))


def note(msg: str) -> None:
    """Phase marker on stderr (stdout carries ONLY the final JSON line).

    A wedged-tunnel bench looks identical to a slow compile from outside;
    these markers make `tail bench.err` name the phase it died in."""
    print(f"[bench {time.strftime('%H:%M:%S')}] {msg}",
          file=sys.stderr, flush=True)


def _newest_artifact(extract):
    """Newest (mtime, path, extract(obj)) over the on-disk bench artifacts
    (watcher-captured + official records) where ``extract`` returns
    non-None.  Per-file failures (concurrent watcher rewrites, malformed
    JSON, wrong types) are contained — a scan here must never raise into
    a caller that is trying to salvage an already-measured number."""
    import glob

    root = os.path.dirname(os.path.abspath(__file__))
    paths = (glob.glob(os.path.join(root, "tpu_watch", "*.json"))
             + glob.glob(os.path.join(root, "BENCH_r*.json")))
    best = None
    for path in paths:
        try:
            with open(path) as f:
                obj = json.load(f)
            val = extract(obj) if isinstance(obj, dict) else None
            if val is None:
                continue
            mtime = os.path.getmtime(path)
        except Exception:
            continue
        if best is None or mtime > best[0]:
            best = (mtime, path, val)
    return best


def last_known_good() -> dict | None:
    """Most recent clean bench artifact on disk (watcher-captured or a past
    official record).

    The tunnel on this host wedges for many hours at a time; a
    driver-run bench during a wedge must not go down as 0.0 when the code
    HAS a verified number from the last time a chip answered — so the
    failure JSON carries it (value, metric, device, commit, timestamp)
    alongside the error.

    When an autotune decision exists, its evidence artifact is preferred
    over the merely-newest one: the newest file is often a pinned A/B
    candidate (e.g. the wide dot mode) that lost the decision, and the
    number a rerun under the decided config would reproduce is the
    decision's, not the loser's.  Exception: an OFFICIAL artifact
    (bench_direct/bench_cot/BENCH_r*, which always run the decided
    config) newer than the evidence supersedes it — the decision file
    only tracks decision-set sources, so without this the fallback
    would report a stale A/B number forever after fresher official
    measurements land.  bench_headline.json is NOT official — pinned
    A/B candidates write it too."""
    def _clean(obj):
        if not isinstance(obj, dict):
            return None
        # driver records (BENCH_r*.json) nest the bench line under "parsed"
        if "value" not in obj and isinstance(obj.get("parsed"), dict):
            obj = obj["parsed"]
        return obj if (not obj.get("error") and obj.get("value")
                       and "metric" in obj
                       and "TINY-SMOKE" not in obj["metric"]) else None

    root = os.path.dirname(os.path.abspath(__file__))
    best = None
    try:
        with open(os.path.join(root, "tpu_watch", "autotune.json")) as f:
            src = json.load(f)["evidence"]["source"]
        # kernel-ab-tier decisions cite "kernel_ab.txt:<label>" — no
        # full-pipeline artifact to prefer; fall through to newest
        if src.endswith(".json"):
            epath = os.path.join(root, "tpu_watch", src)
            with open(epath) as f:
                eobj = _clean(json.load(f))
            if eobj:
                best = (os.path.getmtime(epath), epath, eobj)
    except Exception:
        pass
    if best is not None:
        import glob
        official = ([os.path.join(root, "tpu_watch", "bench_direct.json"),
                     os.path.join(root, "tpu_watch", "bench_cot.json")]
                    + glob.glob(os.path.join(root, "BENCH_r*.json")))
        for path in official:
            try:
                with open(path) as f:
                    obj = _clean(json.load(f))
                mtime = os.path.getmtime(path)
            except Exception:
                continue
            if obj and mtime > best[0]:
                best = (mtime, path, obj)
    if best is None:
        best = _newest_artifact(_clean)
    if best is None:
        return None
    mtime, path, obj = best
    out = {"value": obj["value"], "unit": obj.get("unit", ""),
           "metric": obj["metric"], "device": obj.get("device", ""),
           "source": os.path.relpath(path, root),
           "timestamp": time.strftime("%Y-%m-%dT%H:%M:%S",
                                      time.localtime(mtime))}
    try:   # the newest commit not younger than the artifact ≈ measured code
        r = subprocess.run(
            ["git", "-C", root, "log", "-1", "--format=%h",
             f"--until=@{int(mtime)}"],
            capture_output=True, text=True, timeout=10)
        if r.returncode == 0 and r.stdout.strip():
            out["measured_at_commit"] = r.stdout.strip()
    except Exception:
        pass
    return out


def _last_serial_rate(shape: str, mode: str) -> tuple[float, str] | None:
    """Newest COMPARABLE artifact's measured serial-harness rate
    (probes/s/chip) and its source path — the vs_baseline denominator
    when a wedge kills the serial phase but the headline paged number
    survived.  Comparable = same model shape and eval mode in the metric
    label (a cot serial rate is ~4× slower than direct; dividing across
    modes would inflate the speedup) and never a tiny smoke."""
    def extract(obj):
        if "value" not in obj and isinstance(obj.get("parsed"), dict):
            obj = obj["parsed"]
        rate = obj.get("serial_probes_per_sec")
        metric_s = obj.get("metric", "")
        if (not rate or "TINY-SMOKE" in metric_s or shape not in metric_s
                or f", {mode}," not in metric_s):
            return None
        return float(rate)

    best = _newest_artifact(extract)
    if best is None:
        return None
    root = os.path.dirname(os.path.abspath(__file__))
    return best[2], os.path.relpath(best[1], root)


def acquire_chip_lock(max_wait_s: float = 1200.0, skip: bool = False):
    """Advisory exclusive lock serialising chip users (the driver's
    official bench vs an in-flight runbook step: two processes driving
    the tunneled device concurrently tend to wedge it for both).  Waits
    up to ``max_wait_s`` then proceeds anyway — best effort, never a
    deadlock.  Returns the open fd (hold it for process lifetime; the
    lock releases on exit) or None.  ``skip`` (a --tiny CPU smoke)
    returns None without touching the lock file."""
    if skip:
        return None
    try:
        import fcntl

        root = os.path.dirname(os.path.abspath(__file__))
        os.makedirs(os.path.join(root, "tpu_watch"), exist_ok=True)
        f = open(os.path.join(root, "tpu_watch", ".bench.lock"), "w")
        deadline = time.monotonic() + max_wait_s
        waited = False
        while True:
            try:
                fcntl.flock(f, fcntl.LOCK_EX | fcntl.LOCK_NB)
                return f
            except OSError:
                if time.monotonic() > deadline:
                    note("chip lock still held after "
                         f"{max_wait_s:.0f}s — proceeding anyway")
                    return f
                if not waited:
                    note("waiting for a concurrent chip user "
                         "(tpu_watch/.bench.lock)")
                    waited = True
                time.sleep(min(15.0, max(0.1,
                                         deadline - time.monotonic())))
    except Exception:
        return None


# StallWatchdog moved to the resilience layer so the kernel-CI harness
# (reval_tpu/kernelbench.py) arms one PER CELL while the bench keeps its
# per-round instance — one implementation, re-exported here for the
# historical bench.StallWatchdog callers (tests, tools).
from reval_tpu.resilience.watchdog import StallWatchdog  # noqa: E402


def fail(metric: str, error: str, detail: str = "") -> None:
    out = {"metric": metric, "value": 0.0, "unit": "probes/s/chip",
           "vs_baseline": 0.0, "error": error}
    if detail:
        out["detail"] = detail[-2000:]
    try:
        lk = last_known_good()
    except Exception:
        lk = None
    if lk:
        # an unreachable chip is a STALE measurement, not a zero: the
        # explicit marker + the carried value/commit make BENCH_r06+ read
        # as "stale @ last_known" instead of a multi-round blind spot
        out["status"] = "stale"
        out["last_known"] = lk
        out["stale_probes_per_sec"] = lk["value"]
        if lk.get("measured_at_commit"):
            out["stale_commit"] = lk["measured_at_commit"]
    else:
        out["status"] = "failed"
    emit(out)


# -- workload --------------------------------------------------------------

def build_prompts(n_prompts: int, prompt_type: str) -> list[str]:
    """Genuine DREval coverage prompts (few-shot template + program),
    exactly what the scoring pipeline sends the engine."""
    from reval_tpu.tasks import CoverageTask

    items = 2
    while True:
        task = CoverageTask(model=None, prompt_type=prompt_type,
                            dataset="humaneval", mock=True, max_items=items,
                            progress=False)
        _, jobs = task._plan()
        if len(jobs) >= n_prompts or items > 64:
            return [j.prompt for j in jobs][:n_prompts]
        items *= 2


class TrainedBPE:
    """BPE trained on the benchmark corpus at bench start (~1s): realistic
    token counts without checkpoint/tokenizer egress.  GPT-2-style
    byte-level pre-tokenizer so decode round-trips arbitrary text."""

    def __init__(self, corpus: list[str], vocab_size: int = 8192):
        from tokenizers import Tokenizer, decoders, models, pre_tokenizers, trainers

        tok = Tokenizer(models.BPE(unk_token=None))
        tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
        tok.decoder = decoders.ByteLevel()
        trainer = trainers.BpeTrainer(vocab_size=vocab_size,
                                      special_tokens=["<pad>", "<eos>"],
                                      show_progress=False)
        tok.train_from_iterator(corpus, trainer)
        self.tk = tok
        self.vocab_size = tok.get_vocab_size()
        self.pad_id = 0
        self.eos_id = 1

    def encode(self, text: str) -> list[int]:
        return self.tk.encode(text).ids

    def decode(self, ids) -> str:
        known = [int(i) for i in ids if 0 <= int(i) < self.vocab_size]
        return self.tk.decode(known)


def find_hf_tokenizer(explicit: str | None) -> tuple[object, str] | None:
    """(tokenizer, provenance) from a real model tokenizer when one is
    reachable, else None (→ trained-BPE fallback).  Search order: the
    ``--tokenizer`` flag, ``$REVAL_TPU_TOKENIZER``, then any cached HF
    snapshot with a tokenizer.json.  Verdict r3 item 6: the official
    number should be produced by real-model token counts whenever the
    environment has them, and the metric must say which tokenizer fed it
    (stop-string semantics target: reference inference.py:97)."""
    from pathlib import Path

    candidates: list[Path] = []
    if explicit:
        candidates.append(Path(explicit))
    env = os.environ.get("REVAL_TPU_TOKENIZER")
    if env:
        candidates.append(Path(env))
    hub = Path.home() / ".cache" / "huggingface" / "hub"
    if hub.is_dir():
        candidates.extend(sorted(hub.glob("models--*/snapshots/*")))
    for cand in candidates:
        path = cand.parent if cand.name == "tokenizer.json" else cand
        if not (path / "tokenizer.json").exists():
            if explicit and cand is candidates[0]:
                raise FileNotFoundError(
                    f"--tokenizer {explicit}: no tokenizer.json here")
            continue
        from reval_tpu.inference.tpu.tokenizer import HFTokenizer

        return HFTokenizer(str(path)), str(path)
    return None


def flagship(tiny: bool = False, model: str = "1.3b",
             dtype: str = "bfloat16"):
    """Flagship shapes (BASELINE.json configs[0]: deepseek-coder-1.3b;
    the 6.7b sibling runs single-chip via weight-only int8).  ``model``
    also accepts any zoo name/alias (models/zoo.py) for ad-hoc shape
    benches.  ``tiny`` swaps in a toy config for CPU smoke tests of the
    harness."""
    from reval_tpu.models import ModelConfig, init_random_params, zoo_config

    if tiny:
        cfg = ModelConfig(vocab_size=8192, hidden_size=64,
                          intermediate_size=128, num_layers=2, num_heads=4,
                          num_kv_heads=2, head_dim=32)
        return init_random_params(cfg, seed=0, dtype="float32"), cfg
    name = f"deepseek-coder-{model}" if model in ("1.3b", "6.7b") else model
    cfg = zoo_config(name)
    cfg.dtype = "bfloat16"
    params = init_random_params(cfg, seed=0, dtype=dtype)
    return params, cfg


def count_matmul_params(params) -> tuple[int, int]:
    """(count, resident bytes) of params that flow through matmuls each
    decode step (embedding table lookup excluded; lm_head included).
    Bytes come from the leaves as stored — int8 weights and all scales at
    their true footprint; int4 halved, because ``nbytes`` reports 1 byte
    per nibble (ml_dtypes itemsize) while XLA packs s4 two-per-byte on
    TPU, and overstating weight traffic 2x would corrupt the
    bandwidth_util lens this feeds."""
    import jax
    import jax.numpy as jnp

    total = nbytes = 0
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        keys = "/".join(str(p) for p in path)
        if "embed" in keys:
            continue
        total += leaf.size
        nbytes += leaf.nbytes // 2 if leaf.dtype == jnp.int4 else leaf.nbytes
    return total, nbytes


def decode_flops_per_token(cfg, n_matmul: int, avg_ctx: float) -> float:
    """2*N for the matmuls + attention term 4*L*T*H*D (q@K^T and att@V).

    Attention cost scales with QUERY heads (each query head attends over
    the full context; GQA only shrinks the KV cache, not the dot-product
    count)."""
    attn = 4.0 * cfg.num_layers * avg_ctx * cfg.num_heads * cfg.head_dim
    return 2.0 * n_matmul + attn


# -- timed runs ------------------------------------------------------------

def run_paged(params, cfg, tok, prompts, max_new, *, prefix_sharing,
              max_slots=32, max_seq_len=2048, num_pages=None, kv_dtype="",
              progress_path=None, metric="", grammar=None, speculative=None,
              kv_tiering=None):
    from reval_tpu.inference.tpu.engine import EngineStats
    from reval_tpu.inference.tpu.paged_engine import PagedTPUEngine

    t_build0 = time.perf_counter()
    eng = PagedTPUEngine(params, cfg, tok, max_slots=max_slots,
                         max_seq_len=max_seq_len, num_pages=num_pages,
                         prefix_sharing=prefix_sharing, kv_dtype=kv_dtype,
                         speculative=speculative, kv_tiering=kv_tiering)
    build_wall = time.perf_counter() - t_build0
    # warmup = one full identical run: prefill buckets, decode span buckets,
    # and the prefix-LCP shapes all depend on the (prompt set, max_new)
    # pair, so a reduced warmup would leave XLA compiles inside the timed
    # region on a cold compile cache
    # The tunnel can wedge MID-pass (warmup included — it is the longest
    # phase); when the runbook's timeout then kills this process,
    # everything measured so far must not vanish.  A sampler thread
    # snapshots the engine's per-chunk stats into a sidecar JSON every
    # few seconds — a stalled pass still leaves the true decode rate up
    # to the stall (chip_runbook harvests it as <step>.partial.json).
    # No "value" key: last_known_good() must never surface a partial as
    # a clean artifact.
    stop_evt = thr = None
    phase = {"name": "warmup", "t0": time.perf_counter(), "warmup_wall": 0.0}
    if progress_path:
        import threading

        stop_evt = threading.Event()

        wd = StallWatchdog()

        def _sample():
            while not stop_evt.wait(5.0):
                s = eng.stats
                snap = {"partial": True, "phase": phase["name"],
                        "elapsed_s": round(
                            time.perf_counter() - phase["t0"], 2),
                        "warmup_wall_s": round(phase["warmup_wall"], 2),
                        "generated_tokens": s.generated_tokens,
                        "decode_seconds": round(s.decode_seconds, 3),
                        "decode_tok_s": round(
                            s.generated_tokens / s.decode_seconds, 1)
                        if s.decode_seconds > 0 else 0.0,
                        "prefill_tokens": s.prefill_tokens,
                        "decode_chunks": s.decode_chunks,
                        "config": {"slots": max_slots, "kv_dtype": kv_dtype,
                                   "max_new": max_new,
                                   "prompts": len(prompts)},
                        "ts": time.strftime("%Y-%m-%dT%H:%M:%S")}
                try:
                    with open(progress_path + ".tmp", "w") as f:
                        json.dump(snap, f)
                    os.replace(progress_path + ".tmp", progress_path)
                except OSError:
                    pass
                if wd.stalled_and_dead((s.prefill_tokens,
                                        s.generated_tokens,
                                        s.decode_chunks, s.decode_steps)):
                    note("stall watchdog: no progress for "
                         f"{wd.stall_s:.0f}s and {wd.probe_fails} device "
                         "probes failed — tunnel wedged, exiting")
                    # os._exit skips finally/atexit: emit the structured
                    # fail() artifact FIRST, so a tripped watchdog still
                    # records a stale/failed JSON on stdout instead of
                    # leaving only the .partial.json sidecar (ADVICE r5)
                    try:
                        fail(metric or "DREval coverage probes/sec/chip",
                             "stall-watchdog-tripped",
                             f"no engine-stat progress for "
                             f">={wd.stall_s:.0f}s and {wd.probe_fails} "
                             f"consecutive device probes failed during "
                             f"the {phase['name']} phase")
                        sys.stdout.flush()
                    except Exception:
                        pass
                    os._exit(3)

        thr = threading.Thread(target=_sample, daemon=True)
        thr.start()
    note("  paged warmup pass (compiles land here)")
    t0 = time.perf_counter()
    gkw = {"grammar": grammar} if grammar else {}
    try:
        eng.generate(prompts, max_new_tokens=max_new,
                     temperature=0.0, stop=["[/ANSWER]"], **gkw)
        warmup_wall = time.perf_counter() - t0
        # the warmup pass is the COLD prefix-cache pass (fresh engine):
        # its prefill_tokens against the warm timed pass's measures the
        # cross-call prefill collapse directly, with compiles excluded
        # from both token counts
        cold_prefill_tokens = eng.stats.prefill_tokens
        eng.stats = EngineStats()
        # per-entry dispatch counts at the warmup/timed boundary: the
        # timed-pass delta is the ragged block's dispatches-per-tick
        # numerator (TrackedJit.calls survives the stats swap, so the
        # raw totals include warmup by design)
        calls0 = dict(eng.jit_counters().get("calls") or {})
        note(f"  paged timed pass (warmup took {warmup_wall:.1f}s)")
        phase.update(name="timed-pass", t0=time.perf_counter(),
                     warmup_wall=warmup_wall)
        t0 = time.perf_counter()
        outs = eng.generate(prompts, max_new_tokens=max_new, temperature=0.0,
                            stop=["[/ANSWER]"], **gkw)
    finally:
        if stop_evt is not None:
            stop_evt.set()
            thr.join(timeout=2.0)
    wall = time.perf_counter() - t0
    if progress_path:
        # final snapshot marks this pass complete: if the process later
        # dies in an A/B or serial phase, the harvested sidecar must not
        # read as a timed pass that died ~5 s from its last sample
        s = eng.stats
        try:
            with open(progress_path + ".tmp", "w") as f:
                json.dump({"partial": True, "phase": "complete",
                           "wall_s": round(wall, 2),
                           "warmup_wall_s": round(warmup_wall, 2),
                           "generated_tokens": s.generated_tokens,
                           "decode_seconds": round(s.decode_seconds, 3),
                           "decode_tok_s": round(
                               s.generated_tokens / s.decode_seconds, 1)
                           if s.decode_seconds > 0 else 0.0,
                           "config": {"slots": max_slots,
                                      "kv_dtype": kv_dtype,
                                      "max_new": max_new,
                                      "prompts": len(prompts)},
                           "ts": time.strftime("%Y-%m-%dT%H:%M:%S")}, f)
            os.replace(progress_path + ".tmp", progress_path)
        except OSError:
            pass
    assert len(outs) == len(prompts)
    stats = eng.stats
    stats.warmup_wall = warmup_wall
    prefix_cache = None
    if prefix_sharing and eng.prefix_cache is not None:
        # the timed pass ran against the warm cache: its counters ARE the
        # steady-state fleet-repeat numbers.  Same base dict as the fleet
        # trailer (EngineStats.prefix_counters), plus the bench-only
        # cold/warm comparison.
        prefix_cache = {
            **stats.prefix_counters(),
            "cold_prefill_tokens": cold_prefill_tokens,
            "warm_prefill_tokens": stats.prefill_tokens,
            "warm_prefill_reduction": round(
                1 - stats.prefill_tokens / cold_prefill_tokens, 4)
            if cold_prefill_tokens else 0.0,
            **eng.prefix_cache.counters(),
        }
    # compile-variant counts per jit entry point (analysis/jitcheck.py):
    # the bench "jit" block, and the per-path baseline PERF.md pins —
    # cache_misses > 0 means a post-warmup recompile happened in-run
    jit_row = eng.jit_counters()
    jit_row["timed_calls"] = {
        k: v - calls0.get(k, 0)
        for k, v in (jit_row.get("calls") or {}).items()
        if v - calls0.get(k, 0) > 0}
    # warm-restart economics (inference/tpu/aot_cache.py): cache
    # hits/misses + compile seconds the cache skipped this boot, and —
    # when the cache is on — engine-build+warmup wall as the measured
    # restart-to-ready (what a restarted server pays before /readyz;
    # the BENCH_r* trajectory shows the cold→warm collapse once the
    # chip tunnel is back)
    restart_row = eng.aot_counters()
    if restart_row.get("enabled"):
        restart_row["restart_to_ready_s"] = round(build_wall + warmup_wall, 2)
    # KV-tier traffic over both passes (inference/tpu/kv_tiers.py):
    # spills/promotions/recompute fallbacks + promotion latency — {} when
    # tiering is off (--no-kv-tier A/B)
    tier_row = eng.kv_tier_counters()
    # serving provenance (obs/receipts.py): the engine's receipt config
    # fingerprint — the same value every response served by this config
    # would carry — rides the stats object so the bench round's
    # determinism block can pin it (tools/obs_report.py --receipts diffs
    # it across BENCH rounds)
    ctx_fn = getattr(eng, "receipt_context", None)
    if callable(ctx_fn):
        from reval_tpu.obs.receipts import config_fingerprint
        stats.receipt_fingerprint = config_fingerprint(ctx_fn())
    eng.close()
    return wall, stats, prefix_cache, jit_row, restart_row, tier_row


def run_serial(params, cfg, tok, prompts, max_new, *, max_seq_len=4096):
    """The reference harness shape: one prompt at a time, batch of 1."""
    from reval_tpu.inference.tpu.engine import EngineStats, TPUEngine

    eng = TPUEngine(params, cfg, tok, batch_size=1, max_seq_len=max_seq_len)
    # warmup one prompt per pow2 length bucket at the full token budget —
    # that is every (prefill, decode) shape the timed loop will hit
    from reval_tpu.inference.tpu.engine import _bucket

    seen: set[int] = set()
    for p in prompts:
        b = _bucket(len(tok.encode(p)))
        if b not in seen:
            seen.add(b)
            eng.generate([p], max_new_tokens=max_new, temperature=0.0,
                         stop=["[/ANSWER]"])
    eng.stats = EngineStats()
    t0 = time.perf_counter()
    for p in prompts:
        eng.generate([p], max_new_tokens=max_new, temperature=0.0,
                     stop=["[/ANSWER]"])
    return time.perf_counter() - t0, eng.stats


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--mode", choices=["direct", "cot"], default="direct",
                    help="direct: 256 new tokens; cot: 1024 (reference "
                         "inference.py:25 budgets)")
    ap.add_argument("--prompts", type=int, default=32)
    ap.add_argument("--serial-prompts", type=int, default=32,
                    help="prompts for the serial baseline (>=32 per verdict)")
    ap.add_argument("--skip-serial", action="store_true",
                    help="skip the serial baseline (quick iteration)")
    ap.add_argument("--skip-ab", action="store_true",
                    help="skip the prefix-sharing off run")
    ap.add_argument("--no-kv-tier", action="store_true",
                    help="disable hierarchical KV tiering (host-DRAM "
                         "spill of evicted prefix pages) for the A/B — "
                         "the headline keeps tiering at its default")
    ap.add_argument("--no-prefix-cache", action="store_true",
                    help="disable the persistent radix prefix cache for "
                         "the headline run (A/B candidate pinning); the "
                         "default run measures cache-on and emits the "
                         "cache-off comparison as its A/B row")
    ap.add_argument("--slots", type=int, default=None,
                    help="paged-engine decode slots (batch width); default "
                         "32 direct / 24 cot (the cot pool needs the HBM)")
    ap.add_argument("--max-seq-len", type=int, default=2048)
    ap.add_argument("--num-pages", type=int, default=None,
                    help="page pool size; default oversubscribes to the "
                         "measured working set (~10 pages/slot direct, "
                         "~14/slot cot) instead of slots*max_seq_len — "
                         "preemption handles any overflow")
    ap.add_argument("--model", default="1.3b",
                    help="flagship shape: 1.3b (default), 6.7b (forces "
                         "int8 weights — bf16 does not fit a 16 GB chip "
                         "next to the KV pool), or any models/zoo.py "
                         "name/alias for ad-hoc shape benches")
    ap.add_argument("--dtype", choices=["bfloat16", "int8", "int4"], default=None,
                    help="weight storage; int8 = weight-only quantization "
                         "(models/quant.py). Default bf16 (1.3b) / int8 (6.7b)")
    ap.add_argument("--kv-dtype", choices=["", "int8"], default="",
                    help="KV page pool storage; int8 halves pool HBM and "
                         "attention reads (per-token-head scales)")
    ap.add_argument("--tokenizer", default=None,
                    help="path to a real model tokenizer (dir with "
                         "tokenizer.json); default: $REVAL_TPU_TOKENIZER, "
                         "then any cached HF snapshot, then a BPE trained "
                         "on the benchmark corpus")
    ap.add_argument("--tiny", action="store_true",
                    help="toy model + short budgets: CPU smoke test of the "
                         "bench harness itself, NOT a performance number")
    ap.add_argument("--no-obs", action="store_true",
                    help="disable latency-histogram observation "
                         "(REVAL_TPU_OBS=0) — the A/B that prices the "
                         "observability layer's hot-path cost (PERF.md); "
                         "counters stay on (engine accounting needs them)")
    ap.add_argument("--no-ragged", action="store_true",
                    help="skip the ragged continuous-batching A/B (one "
                         "wave per tick vs the chunked incumbent: tok/s "
                         "delta, dispatches/tick, padded-vs-useful wave "
                         "occupancy)")
    ap.add_argument("--no-spec", action="store_true",
                    help="skip the speculative-decoding A/B garnish "
                         "(grammar-constrained probes, spec on vs off)")
    ap.add_argument("--no-determinism", action="store_true",
                    help="skip the determinism slice (the reference-cell "
                         "greedy fingerprint recorded so BENCH history "
                         "detects silent cross-commit drift — "
                         "obs/determinism.py)")
    ap.add_argument("--no-aot-cache", action="store_true",
                    help="leave REVAL_TPU_AOT_CACHE_DIR unset instead of "
                         "defaulting it to tpu_watch/aot_cache on chip "
                         "runs — the default makes every chip round's "
                         "'restart' block record the real cold->warm "
                         "compile collapse (ROADMAP item 4 remainder)")
    ap.add_argument("--no-autotune", action="store_true",
                    help="ignore tpu_watch/autotune.json — REQUIRED for "
                         "A/B candidate runs, which must measure exactly "
                         "their pinned config (a decision feeding back "
                         "into its own candidates oscillates on noise)")
    args = ap.parse_args()

    if args.no_obs:
        # before any engine construction: EngineStats reads it once
        os.environ["REVAL_TPU_OBS"] = "0"

    # Chip rounds persist AOT executables by default so the "restart"
    # block measures the real cold->warm compile collapse round over
    # round (a --tiny smoke must not seed the chip's cache with toy
    # programs; an operator's explicit dir always wins).
    if (not args.tiny and not args.no_aot_cache
            and not os.environ.get("REVAL_TPU_AOT_CACHE_DIR")):
        os.environ["REVAL_TPU_AOT_CACHE_DIR"] = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "tpu_watch",
            "aot_cache")

    chip_lock = acquire_chip_lock(skip=args.tiny)  # held until exit

    # flags left at their defaults adopt the persisted autotune decision
    # (tools/decide_defaults.py: the measured-best bench config from the
    # last tunnel window), so the driver's official run benches the
    # winning configuration without a live session editing constants.
    # Scope-checked: a decision measured on 1.3b/direct must not override
    # the memory-safe defaults of another model or mode (cot's 24 slots /
    # 6.7b's 8 exist because bigger pools don't fit beside the weights).
    if (not args.tiny and not args.no_autotune
            and args.kv_dtype == "" and args.slots is None):
        try:
            with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                   "tpu_watch", "autotune.json")) as f:
                tuned_obj = json.load(f)
            tuned = tuned_obj.get("bench_args") or {}
            scope = tuned_obj.get("scope") or {}
            if (scope.get("mode") == args.mode
                    and scope.get("model") == args.model):
                if tuned.get("kv_dtype") in ("", "int8"):
                    args.kv_dtype = tuned["kv_dtype"]
                if isinstance(tuned.get("slots"), int):
                    args.slots = tuned["slots"]
                if tuned:
                    note("autotune: applying measured-best bench config "
                         f"{tuned}")
        except (OSError, ValueError):
            pass

    from reval_tpu.inference.base import MAX_NEW_TOKENS

    max_new = MAX_NEW_TOKENS[args.mode]   # the budgets the eval path uses
    if args.dtype is None:
        args.dtype = "int8" if args.model == "6.7b" else "bfloat16"
    if args.tiny:
        max_new = 16
        args.prompts = min(args.prompts, 6)
        args.serial_prompts = min(args.serial_prompts, 4)
    label = (f"deepseek-{args.model}" if args.model in ("1.3b", "6.7b")
             else args.model.rsplit("/", 1)[-1])
    shape = ("TINY-SMOKE-TEST fp32" if args.tiny
             else f"{label}-shape "
                  + (args.dtype + "-weights" if args.dtype != "bfloat16" else "bf16"))
    # tiny mode keeps the corpus BPE: a real tokenizer's ids overflow the
    # toy model's 8k vocab
    try:
        hf_tok = None if args.tiny else find_hf_tokenizer(args.tokenizer)
    except Exception as e:   # structured failure beats a bare traceback
        fail(f"DREval coverage probes/sec/chip ({shape}, {args.mode})",
             "tokenizer-load-failed", f"{type(e).__name__}: {e}")
        sys.exit(1)
    tok_label = "hf-tokenizer" if hf_tok else "trained-BPE"
    metric = (f"DREval coverage probes/sec/chip "
              f"({shape}, {args.mode}, {max_new} new tok, "
              f"{tok_label} prompts)")

    note('pre-flight device probe')
    health, probe_error = probe_devices(force_cpu=args.tiny)
    if health is None:
        if probe_error == "timeout":
            fail(metric, "tpu-unreachable",
                 "jax.devices() subprocess probe timed out repeatedly — the "
                 "device tunnel is wedged; re-run when it recovers")
        else:
            fail(metric, "device-probe-failed", probe_error)
        return

    n_chips, device_kind, platform = health
    try:
        import jax

        if args.tiny:
            jax.config.update("jax_platforms", "cpu")
            # a CPU smoke of the harness must not inherit the CHIP's
            # autotuned kernel choice (tpu_watch/autotune.json may pin a
            # Pallas kernel this host's jax can only interpret — or not
            # even build); the XLA path is the CPU backend by design
            os.environ.setdefault("REVAL_TPU_PAGED_BACKEND", "xla")
        jax.config.update("jax_compilation_cache_dir",
                          os.path.expanduser("~/.cache/reval_tpu_xla"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

        note(f'devices ok ({health[1]}); building prompts')
        prompts = build_prompts(args.prompts, args.mode)
        tok = hf_tok[0] if hf_tok else TrainedBPE(prompts)
        params, cfg = flagship(tiny=args.tiny, model=args.model,
                               dtype=args.dtype)
        if hf_tok:
            top = max(max(tok.encode(p)) for p in prompts)
            if top >= cfg.vocab_size:
                raise ValueError(
                    f"tokenizer at {hf_tok[1]} emits id {top} >= model "
                    f"vocab {cfg.vocab_size}; pair --tokenizer with the "
                    f"matching --model zoo shape")
        n_matmul, weight_bytes = count_matmul_params(params)

        # the bench engines run UNSHARDED (no mesh): exactly one chip does
        # the work, so per-chip numbers divide by 1 regardless of how many
        # chips the host exposes
        chips_used = 1
        if args.tiny and args.slots is None:
            args.slots = 4
        if args.tiny and args.max_seq_len == 2048:
            args.max_seq_len = 512
        if args.slots is None:
            if args.model == "6.7b":
                args.slots = 8 if args.mode == "direct" else 6
            else:
                args.slots = 32 if args.mode == "direct" else 24
        num_pages = args.num_pages
        if num_pages is None:
            # size the pool to the workload's real working set (+1 page
            # per seq and a little slack), not slots*max_seq_len — the
            # full-coverage pool for 32 slots x 2048 would not fit next
            # to the weights on a 16 GB chip, and preemption covers any
            # miscount
            from reval_tpu.inference.tpu.paged_engine import PAGE_SIZE as page

            longest = max(len(tok.encode(p)) for p in prompts) + max_new
            per_seq = (longest + page - 1) // page + 1
            per_seq = min(per_seq, args.max_seq_len // page)
            num_pages = 1 + args.slots * per_seq + 16
        note(f'params ready ({args.dtype}); paged warmup+run '
             f'(slots={args.slots}, pages={num_pages})')
        progress = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "tpu_watch", "bench_inflight.json")
        os.makedirs(os.path.dirname(progress), exist_ok=True)
        wall, stats, cache_row, jit_row, restart_row, tier_row = run_paged(
            params, cfg, tok, prompts, max_new,
            prefix_sharing=not args.no_prefix_cache, max_slots=args.slots,
            max_seq_len=args.max_seq_len,
            num_pages=num_pages, kv_dtype=args.kv_dtype,
            progress_path=progress, metric=metric,
            kv_tiering=not args.no_kv_tier)
        probes_per_sec = len(prompts) / wall / chips_used
        tok_per_sec = (stats.generated_tokens / stats.decode_seconds
                       if stats.decode_seconds else 0.0)
        avg_prompt = sum(len(tok.encode(p)) for p in prompts) / len(prompts)
        avg_ctx = avg_prompt + max_new / 2
        mfu = (tok_per_sec * decode_flops_per_token(cfg, n_matmul, avg_ctx)
               / (peak_flops_for(device_kind) * chips_used))

        # decode HBM roofline: each weight pass streams the matmul params
        # once, and each generated token reads its full KV context.  MFU
        # is near-meaningless for bandwidth-bound decode; this fraction
        # answers "actually fast?" directly (round-4 verdict item 5).
        kvb = (1 if args.kv_dtype == "int8"
               else params["embed"].dtype.itemsize)
        kv_per_ctx = 2 * cfg.num_layers * cfg.num_kv_heads * cfg.head_dim * kvb
        if args.kv_dtype == "int8":
            kv_per_ctx += 2 * cfg.num_layers * cfg.num_kv_heads * 4  # f32 scales
        decode_bytes = (stats.decode_steps * weight_bytes
                        + stats.generated_tokens * avg_ctx * kv_per_ctx)
        hbm_gbps = (decode_bytes / stats.decode_seconds / 1e9
                    if stats.decode_seconds else 0.0)
        bandwidth_util = hbm_gbps * 1e9 / hbm_bw_for(device_kind)

        extras = {
            "tokenizer": hf_tok[1] if hf_tok else "trained-bpe(benchmark-corpus)",
            "tokens_per_sec": round(tok_per_sec, 1),
            "mfu": round(mfu, 4),
            "bandwidth_util": round(bandwidth_util, 4),
            "hbm_gbps_achieved": round(hbm_gbps, 1),
            "decode_steps": stats.decode_steps,
            "device": device_kind,
            "platform": platform,
            "chips_used": chips_used,
            "n_chips_available": n_chips,
            "n_prompts": len(prompts),
            "avg_prompt_tokens": round(avg_prompt, 1),
            "max_new_tokens": max_new,
            "prefill_tokens": stats.prefill_tokens,
            "generated_tokens": stats.generated_tokens,
            "prefill_tokens_per_sec": round(
                stats.prefill_tokens / stats.prefill_seconds, 1)
                if stats.prefill_seconds else 0.0,
            "decode_share": round(stats.decode_seconds / wall, 3) if wall else 0.0,
            "wall_seconds": round(wall, 2),
            "warmup_wall_seconds": round(getattr(stats, "warmup_wall", 0.0), 2),
            "pipelined_chunks": getattr(stats, "pipelined_chunks", 0),
            "patched_tables": getattr(stats, "patched_tables", 0),
            # serving lifecycle counters: zero for an in-process bench,
            # nonzero when the same EngineStats rode a serve session
            # (sheds = 429 load sheds, deadline_expired = engine-side
            # request cancels, watchdog_trips = no-progress trips)
            "serving": stats.serving_counters(),
            # per-request latency distributions from the timed pass:
            # TTFT/TPOT/e2e/queue-wait p50/p95/p99 — the SLO lens the
            # serving studies use (empty under --no-obs)
            "latency": stats.latency_summary(),
            # compile-variant counts per tracked jit entry (warmup pass
            # included — compiles land there by design); cache_misses > 0
            # means a POST-warmup recompile fired mid-run, the silent
            # perf cliff the jitcheck sanitizer pins (PERF.md PR-9)
            "jit": jit_row,
            # warm-restart block: AOT executable-cache hits/misses +
            # compile seconds skipped this boot, and restart_to_ready_s
            # (engine build + warmup wall) when the cache is enabled —
            # {"enabled": false} otherwise, so the BENCH_r* trajectory
            # shows exactly when the cold-start win lands (PR-10)
            "restart": restart_row,
        }
        if args.no_obs:
            extras["obs_disabled"] = True
        if cache_row is not None:
            extras["prefix_cache"] = cache_row
        if tier_row:
            # host/disk page counts, spill + promotion counters, the
            # promote hit-rate, recompute fallbacks, and promotion
            # p50/p95 latency (kv_tiers.py; absent under --no-kv-tier)
            extras["kv_tier"] = tier_row

        # The headline number is already measured; the A/B and serial
        # phases are garnish.  Persist it to disk NOW: a wedge in a
        # garnish phase blocks forever (no exception) until the runbook
        # timeout SIGKILLs this process, and the final emit() would never
        # run.  The artifact carries value+metric and no error, so
        # last_known_good() treats it as the clean measurement it is.
        # TPU-only: a --tiny/--force-cpu smoke must never seed the
        # last-known pool with toy numbers.
        if platform == "tpu":
            try:
                headline = os.path.join(
                    os.path.dirname(os.path.abspath(__file__)), "tpu_watch",
                    "bench_headline.json")
                with open(headline + ".tmp", "w") as f:
                    json.dump({"metric": metric,
                               "value": round(probes_per_sec, 3),
                               "unit": "probes/s/chip", "vs_baseline": 0.0,
                               "pre_garnish": True, **extras}, f)
                os.replace(headline + ".tmp", headline)
            except OSError:
                pass

        # A garnish-phase exception must NOT discard the real value into
        # fail()'s last_known path — record the phase error and emit what
        # was measured.
        if not args.skip_ab and not args.no_prefix_cache:
            note(f'paged run done ({round(len(prompts)/wall,2)} probes/s); '
                 'prefix-cache-off A/B')
            try:
                wall_nopre, _, _, _, _, _ = run_paged(params, cfg, tok,
                                                      prompts, max_new,
                                                prefix_sharing=False,
                                                max_slots=args.slots,
                                                max_seq_len=args.max_seq_len,
                                                num_pages=num_pages,
                                                kv_dtype=args.kv_dtype)
                # legacy key (sharing and the cache are one mechanism now)
                extras["prefix_sharing_speedup"] = round(wall_nopre / wall, 3)
                # the --no-prefix-cache A/B row: what this exact run would
                # have measured with the cache disabled
                extras["no_prefix_cache_speedup"] = round(
                    wall_nopre / wall, 3)
            except Exception as e:
                extras["ab_error"] = type(e).__name__
                note(f'prefix-cache A/B failed ({type(e).__name__}); '
                     'keeping the measured headline')

        # Ragged continuous-batching garnish: the identical workload
        # through the other engine mode — when the headline ran the
        # chunked incumbent, the A/B leg pins the ragged one-wave
        # engine (and vice versa when autotune already decided ragged).
        # The block carries the tok/s delta plus the two observables
        # only the ragged engine has: dispatches-per-tick (must be 1.0
        # — the contract the tier-1 test asserts) and padded-vs-useful
        # wave occupancy.  Garnish rules apply.
        if not args.no_ragged:
            note('ragged A/B (one-wave continuous batching vs chunked)')
            try:
                from reval_tpu.ops.pallas_attention import \
                    resolved_paged_backend

                prev = os.environ.get("REVAL_TPU_PAGED_BACKEND")
                flip = resolved_paged_backend() not in ("ragged",
                                                        "ragged_xla")
                if flip:        # headline was the incumbent: pin ragged
                    ab_backend = ("ragged" if platform == "tpu"
                                  else "ragged_xla")
                else:           # headline was ragged: pin the incumbent
                    ab_backend = "pallas" if platform == "tpu" else "xla"
                os.environ["REVAL_TPU_PAGED_BACKEND"] = ab_backend
                try:
                    w_ab, st_ab, _, jit_ab, _, _ = run_paged(
                        params, cfg, tok, prompts, max_new,
                        prefix_sharing=not args.no_prefix_cache,
                        max_slots=args.slots,
                        max_seq_len=args.max_seq_len,
                        num_pages=num_pages, kv_dtype=args.kv_dtype)
                finally:
                    if prev is None:
                        os.environ.pop("REVAL_TPU_PAGED_BACKEND", None)
                    else:
                        os.environ["REVAL_TPU_PAGED_BACKEND"] = prev
                w_r, st_r, jit_r = ((w_ab, st_ab, jit_ab) if flip
                                    else (wall, stats, jit_row))
                w_i, st_i = ((wall, stats) if flip else (w_ab, st_ab))
                ticks = st_r.ragged_ticks
                disp = (jit_r.get("timed_calls") or {}).get(
                    "paged.ragged_step", 0)
                tok_r = (st_r.generated_tokens / st_r.decode_seconds
                         if st_r.decode_seconds else 0.0)
                tok_i = (st_i.generated_tokens / st_i.decode_seconds
                         if st_i.decode_seconds else 0.0)
                extras["ragged"] = {
                    "backend": (ab_backend if flip
                                else resolved_paged_backend()),
                    "ticks": ticks,
                    "dispatches_per_tick": (round(disp / ticks, 3)
                                            if ticks else 0.0),
                    "wave_occupancy": (round(
                        st_r.ragged_useful_tokens
                        / st_r.ragged_padded_tokens, 4)
                        if st_r.ragged_padded_tokens else 0.0),
                    "useful_tokens": st_r.ragged_useful_tokens,
                    "padded_tokens": st_r.ragged_padded_tokens,
                    "tokens_per_sec": round(tok_r, 1),
                    "tokens_per_sec_incumbent": round(tok_i, 1),
                    "tok_s_delta": (round(tok_r / tok_i, 3)
                                    if tok_i else 0.0),
                    "speedup": round(w_i / w_r, 3) if w_r else 0.0,
                }
            except Exception as e:
                extras["ragged_error"] = type(e).__name__
                note(f'ragged A/B failed ({type(e).__name__}); '
                     'keeping the measured headline')

        # Speculative garnish: the same probes decoded under their answer
        # grammar with the self-drafting verify path on, then off — the
        # `speculative` block carries accept-rate and the engine-steps-
        # saved ratio (the probes/sec/chip lever ROADMAP item 2 names).
        # The headline above stays grammar-less and spec-gated-off, so
        # BENCH_r* history remains comparable.  Garnish rules apply.
        if not args.no_spec:
            note('speculative A/B (grammar-constrained, spec on vs off)')
            try:
                sg = "yesno" if args.mode == "direct" else "cot-yesno"
                sp_prompts = prompts[: min(len(prompts), 16)]
                w_on, st_on, _, _, _, _ = run_paged(
                    params, cfg, tok, sp_prompts, max_new,
                    prefix_sharing=not args.no_prefix_cache,
                    max_slots=args.slots, max_seq_len=args.max_seq_len,
                    num_pages=num_pages, kv_dtype=args.kv_dtype,
                    grammar=sg, speculative=True)
                w_off, st_off, _, _, _, _ = run_paged(
                    params, cfg, tok, sp_prompts, max_new,
                    prefix_sharing=not args.no_prefix_cache,
                    max_slots=args.slots, max_seq_len=args.max_seq_len,
                    num_pages=num_pages, kv_dtype=args.kv_dtype,
                    grammar=sg, speculative=False)
                extras["speculative"] = {
                    **st_on.spec_counters(),
                    "grammar": sg,
                    "decode_steps": st_on.decode_steps,
                    "decode_steps_no_spec": st_off.decode_steps,
                    "steps_saved_ratio": round(
                        st_off.decode_steps / st_on.decode_steps, 2)
                    if st_on.decode_steps else 0.0,
                    "no_spec_speedup": round(w_off / w_on, 3)
                    if w_on else 0.0,
                }
            except Exception as e:
                extras["spec_error"] = type(e).__name__
                note(f'speculative A/B failed ({type(e).__name__}); '
                     'keeping the measured headline')

        # Determinism garnish: run the tiny seeded probe slice through
        # reference + static + seq-kernel cells and record the reference
        # cell's greedy-token fingerprint.  The probe model/set is FIXED
        # (independent of bench flags), so the fingerprint only moves
        # when a commit changes numerics — tools/obs_report.py
        # --determinism diffs it across BENCH rounds and names the first
        # round it changed.  Garnish rules apply: a failure here records
        # an error and keeps the measured headline.
        if not args.no_determinism:
            note('determinism slice (reference-cell fingerprint)')
            try:
                from reval_tpu.obs.determinism import bench_block

                extras["determinism"] = bench_block()
                # the headline engine's serving receipt fingerprint
                # (run_paged attached it): obs_report --receipts diffs
                # this across rounds and names the first drifted one
                extras["determinism"]["receipt_fingerprint"] = getattr(
                    stats, "receipt_fingerprint", None)
                if extras["determinism"]["gate_failures"]:
                    note('determinism slice DIVERGED: '
                         + '; '.join(extras["determinism"]["gate_failures"]))
            except Exception as e:
                extras["determinism_error"] = type(e).__name__
                note(f'determinism slice failed ({type(e).__name__}); '
                     'keeping the measured headline')

        vs_baseline = 0.0
        if not args.skip_serial:
            sp = prompts[: args.serial_prompts]
            note(f'serial baseline ({len(sp)} prompts, batch 1)')
            try:
                serial_s, _ = run_serial(params, cfg, tok, sp, max_new)
                serial_per_sec = len(sp) / serial_s / chips_used
                extras["serial_probes_per_sec"] = round(serial_per_sec, 4)
                vs_baseline = probes_per_sec / serial_per_sec
            except Exception as e:
                extras["serial_error"] = type(e).__name__
                lk_serial = _last_serial_rate(shape, args.mode)  # no raise
                if lk_serial:
                    rate, src = lk_serial
                    extras["serial_probes_per_sec_last_known"] = rate
                    extras["serial_last_known_source"] = src
                    vs_baseline = probes_per_sec / rate
                note(f'serial baseline failed ({type(e).__name__}); '
                     'keeping the measured headline')

        emit({"metric": metric, "value": round(probes_per_sec, 3),
              "unit": "probes/s/chip", "vs_baseline": round(vs_baseline, 2),
              **extras})
    except Exception as e:  # structured failure beats a bare traceback
        import traceback

        fail(metric, type(e).__name__, traceback.format_exc())
        sys.exit(1)


if __name__ == "__main__":
    main()
