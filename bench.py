"""Throughput benchmark: DREval probes/sec/chip with the in-tree TPU engine.

Runs the *real* evaluation pipeline — coverage-task planning over HumanEval
builds genuine few-shot prompts, the TPU engine generates with the
benchmark's stop string — on a deepseek-coder-1.3b-shaped model with random
bf16 weights (this host has no checkpoint egress; throughput does not
depend on weight values).

Baseline for ``vs_baseline``: the reference harness prompts serially, one
``Model.infer`` per probe (reference evaluation.py:105-107) — we measure
that same engine forced to batch_size=1 serial decode and report the
speedup of the batched path.  Prints exactly one JSON line.
"""

from __future__ import annotations

import json
import os
import time


def build_prompts(n_items: int = 3) -> list[str]:
    from reval_tpu.tasks import CoverageTask

    task = CoverageTask(model=None, prompt_type="direct", dataset="humaneval",
                        mock=True, max_items=n_items, progress=False)
    _, jobs = task._plan()
    return [j.prompt for j in jobs]


def flagship():
    from reval_tpu.inference.tpu.tokenizer import ByteTokenizer
    from reval_tpu.models import ModelConfig, init_random_params

    cfg = ModelConfig(
        vocab_size=32256, hidden_size=2048, intermediate_size=5504,
        num_layers=24, num_heads=16, num_kv_heads=16, head_dim=128,
        rope_theta=100000.0,
    )
    params = init_random_params(cfg, seed=0, dtype="bfloat16")
    return params, cfg, ByteTokenizer()


def make_engine(batch_size: int):
    """The production path: continuous batching over the paged KV cache
    (Pallas kernel on TPU) driven by the native C++ scheduler."""
    from reval_tpu.inference.tpu.paged_engine import PagedTPUEngine

    params, cfg, tok = flagship()
    return PagedTPUEngine(params, cfg, tok, max_slots=batch_size,
                          max_seq_len=4096)


def make_serial_engine():
    """The reference harness shape: one prompt at a time (reference
    evaluation.py:105-107 infers serially), static batch of 1."""
    from reval_tpu.inference.tpu.engine import TPUEngine

    params, cfg, tok = flagship()
    return TPUEngine(params, cfg, tok, batch_size=1, max_seq_len=4096)


def timed_run(engine, prompts: list[str], max_new_tokens: int) -> float:
    t0 = time.perf_counter()
    outs = engine.generate(prompts, max_new_tokens=max_new_tokens,
                           temperature=0.0, stop=["[/ANSWER]"])
    assert len(outs) == len(prompts)
    return time.perf_counter() - t0


def main() -> None:
    import jax

    # persistent XLA compilation cache: decode/prefill variants compile once
    # per machine, not once per run (jit cache is per-process otherwise)
    jax.config.update("jax_compilation_cache_dir",
                      os.path.expanduser("~/.cache/reval_tpu_xla"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    max_new = 32
    prompts = build_prompts()
    n = len(prompts)

    batched = make_engine(batch_size=8)
    timed_run(batched, prompts[:8], max_new)      # warmup: compile prefill+decode
    batched_s = timed_run(batched, prompts, max_new)
    batched.close()
    del batched                                   # free params + page pool HBM
    import gc

    gc.collect()

    serial = make_serial_engine()
    timed_run(serial, prompts[:1], max_new)       # warmup
    serial_s = timed_run(serial, prompts[: max(4, n // 8)], max_new)
    serial_per = serial_s / max(4, n // 8)

    n_chips = max(1, len(jax.devices()))
    probes_per_sec = n / batched_s / n_chips
    baseline_per_sec = 1.0 / serial_per / n_chips
    print(json.dumps({
        "metric": "DREval coverage probes/sec/chip (deepseek-1.3b-shape bf16, direct, 32 new tok)",
        "value": round(probes_per_sec, 3),
        "unit": "probes/s/chip",
        "vs_baseline": round(probes_per_sec / baseline_per_sec, 2),
    }))


if __name__ == "__main__":
    main()
