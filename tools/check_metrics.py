#!/usr/bin/env python3
"""Metric/event-namespace lint — THIN SHIM over reval-lint.

The checks themselves moved into the lint framework
(``reval_tpu/analysis/metrics_events.py``; ISSUE 6 migrated them so the
repo has one driver and one report format — run ``python
tools/reval_lint.py`` for the whole suite).  This shim keeps the
historical entry points alive:

- ``python tools/check_metrics.py`` still exits non-zero with the same
  per-violation lines;
- ``run_checks(root) -> [str]`` (plus ``_spec``/``_events_spec``) keeps
  the existing bite tests and any external invocation working.
"""

from __future__ import annotations

import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from reval_tpu.analysis.metrics_events import (  # noqa: E402,F401
    _events_spec,
    _metrics_spec as _spec,
    run_checks as _run_checks,
)


def run_checks(root: str = ROOT) -> list[str]:
    """Returns a list of human-readable violations (empty = clean)."""
    return _run_checks(root)


def main() -> int:
    from reval_tpu.analysis.driver import main as lint_main

    # one driver, one report format: delegate to the migrated passes
    return lint_main(["metrics", "events"])


if __name__ == "__main__":
    sys.exit(main())
