#!/usr/bin/env python3
"""Golden-stream registry: record + gate the probe set's token streams.

The committed upgrade gate for serving-time reproducibility
(``reval_tpu/obs/receipts.py`` is the per-response half; this is the
per-commit half).  ``--record`` runs the determinism probe set over the
host-runnable matrix slice and writes the exact greedy token streams —
plus their per-probe receipt digests and each cell's fingerprint — into
the committed ``GOLDEN_STREAMS.json``.  ``--check`` re-runs the same
cells at HEAD and diffs against the registry: any divergence exits 1
naming the cell and the FIRST divergent (probe, token), the same
earliest-token attribution the determinism matrix's parity gate uses.

So an upgrade PR (jax pin bump, kernel rewrite, scheduler change) that
moves greedy outputs CANNOT land silently: the gate names exactly where
the stream broke, and blessing the new behavior is an explicit,
reviewable ``--record`` commit.

The ``goldenstreams`` reval-lint pass validates the committed registry's
schema (digests recompute from the stored streams; a perturb-drill
recording is refused) without running the model, so the <10 s lint bar
holds; this tool is the full gate.

Exit codes: 0 = recorded / HEAD matches golden; 1 = divergence (or a
self-check failure on record); 2 = unrunnable (no registry to check,
bad cells, reference unloadable).

Usage:
    python tools/golden_streams.py --record            # bless HEAD
    python tools/golden_streams.py --check             # gate HEAD
    python tools/golden_streams.py --check --cells paged-xla-fp32-b2
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    mode = ap.add_mutually_exclusive_group(required=True)
    mode.add_argument("--record", action="store_true",
                      help="run the slice and (re)write the committed "
                           "registry — the explicit blessing step")
    mode.add_argument("--check", action="store_true",
                      help="re-run the recorded cells and diff against "
                           "the registry; divergence exits 1 naming the "
                           "cell and first divergent (probe, token)")
    ap.add_argument("--path", default=None,
                    help="registry path (default <repo>/GOLDEN_STREAMS.json)")
    ap.add_argument("--cells", default=None,
                    help="comma-separated cell names (record: which "
                         "cells to bless, default the host-runnable "
                         "bench slice; check: narrow the re-run — "
                         "unlisted recorded cells are still required "
                         "to match when they execute)")
    ap.add_argument("--json", action="store_true",
                    help="print the registry (record) or the verdict "
                         "object (check) to stdout as JSON")
    args = ap.parse_args(argv)

    from reval_tpu.obs.determinism import (GOLDEN_FILE, GOLDEN_SLICE,
                                           golden_doc, golden_gate,
                                           run_matrix, validate_golden)

    path = args.path or os.path.join(_ROOT, GOLDEN_FILE)
    cells = ([c.strip() for c in args.cells.split(",") if c.strip()]
             if args.cells else None)

    if args.record:
        try:
            matrix = run_matrix(select=cells or list(GOLDEN_SLICE))
        except (ValueError, RuntimeError) as e:
            print(f"golden_streams: {e}", file=sys.stderr)
            return 2
        doc = golden_doc(matrix)
        problems = validate_golden(doc)
        if problems:    # e.g. recorded under a leftover perturb drill
            for p in problems:
                print(f"golden_streams: self-check: {p}", file=sys.stderr)
            return 1
        with open(path + ".tmp", "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(path + ".tmp", path)
        if args.json:
            print(json.dumps(doc, indent=1, sort_keys=True))
        print(f"golden_streams: recorded {len(doc['cells'])} cell(s) "
              f"-> {path}")
        return 0

    try:
        with open(path) as f:
            golden = json.load(f)
    except (OSError, ValueError) as e:
        print(f"golden_streams: cannot read registry {path}: {e} "
              f"(run --record first)", file=sys.stderr)
        return 2
    problems = validate_golden(golden)
    if problems:
        for p in problems:
            print(f"golden_streams: bad registry: {p}", file=sys.stderr)
        return 2
    try:
        matrix = run_matrix(select=cells or list(golden["cells"]))
    except (ValueError, RuntimeError) as e:
        print(f"golden_streams: {e}", file=sys.stderr)
        return 2
    failures = golden_gate(golden, matrix)
    if cells:
        # a narrowed re-run records unselected cells as skipped; those
        # are this invocation's choice, not HEAD's divergence
        chosen = set(cells)
        failures = [msg for msg in failures
                    if msg.split(":", 1)[0].removeprefix("cell ").strip()
                    in chosen or not msg.startswith("cell ")]
    if args.json:
        print(json.dumps({"ok": not failures, "registry": path,
                          "cells_checked": sorted(golden["cells"]),
                          "failures": failures}, indent=1))
    if failures:
        print("GOLDEN-STREAM GATE FAILURE:", file=sys.stderr)
        for msg in failures:
            print(f"  {msg}", file=sys.stderr)
        return 1
    print(f"golden_streams: HEAD matches {path} "
          f"({len(golden['cells'])} cell(s))")
    return 0


if __name__ == "__main__":
    sys.exit(main())
