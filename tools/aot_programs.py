"""Single source of truth for the deviceless-AOT program builders.

tests/test_tpu_aot_compile.py (the compile-certificate test tier),
tools/aot_warm.py (compile-cache pre-warming), and tools/aot_certify.py
(the recorded-evidence artifact) all compile THE SAME programs the
runtime dispatches — if each kept its own copy of the shapes, a change
to the engine's bucketing or state layout would drift one of them into
certifying a program the runtime never executes.  Every builder here
returns a ``jax.stages.Compiled`` for a real TPU target, produced on a
chip-free host via ``jax.experimental.topologies``.

Shape contracts mirrored from the engine/bench:
- the engine pow2-buckets the block-table span (paged_engine.pow2_bucket);
  bench prompts (~500 tok) + 256 new land in bucket 8 (direct) and
  + 1024 new in bucket 16 (cot) — packed state rows are ``span + 6``;
- bench.py sizes the page pool as ``1 + slots * per_seq + 16`` with
  per_seq 7 (direct) / 13 (cot);
- prefill row groups bucket to pow2 under the 768 MB byte budget
  (paged_engine.PREFILL_BYTE_BUDGET): 8- and 4-row batches at t=512.
"""

from __future__ import annotations

import os
import sys
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BENCH_SPAN_DIRECT = 8
BENCH_SPAN_COT = 16
PER_SEQ_DIRECT = 7
PER_SEQ_COT = 13


def bench_pool(slots: int, per_seq: int) -> int:
    """bench.py's default page-pool size for a slot count."""
    return 1 + slots * per_seq + 16


def _env_mosaic(backend: str = "pallas") -> None:
    """The dispatcher keys interpret mode on the RUNTIME backend (cpu on
    a chip-free host) — force the Mosaic kernel so these compiles target
    the chip's program, not the HLO emulation."""
    os.environ["REVAL_TPU_PAGED_BACKEND"] = backend
    os.environ["REVAL_TPU_FORCE_MOSAIC"] = "1"


def topology(name: str):
    """Deviceless PJRT TPU topology (raises when libtpu/the topology API
    is unavailable — tests catch and skip)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from jax.experimental import topologies

    return topologies.get_topology_desc(platform="tpu", topology_name=name)


def _replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P())


def _shaped(tree, sharding):
    import jax

    return jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sharding),
        tree)


def _single_device_mesh(topo):
    import numpy as np
    from jax.sharding import Mesh

    return Mesh(np.array(topo.devices[:1]), ("x",))


def flagship_model_parts(mesh, *, num_pages=bench_pool(32, PER_SEQ_DIRECT),
                         kv_dtype="", weights="bf16w"):
    """1.3b-dims (cfg, params, cache) as replicated ShapeDtypeStructs —
    the model half of the EXACT bench default program."""
    import jax
    import jax.numpy as jnp

    from reval_tpu.models import (init_random_params, quantize_params,
                                  zoo_config)
    from reval_tpu.models.paged import init_paged_cache

    cfg = zoo_config("deepseek-coder-1.3b")
    cfg.dtype = "bfloat16"
    rep = _replicated(mesh)

    def make():
        p = init_random_params(cfg, seed=0, dtype="bfloat16")
        return quantize_params(p) if weights == "int8w" else p

    params = _shaped(jax.eval_shape(make), rep)
    cache = _shaped(
        jax.eval_shape(lambda: init_paged_cache(cfg, num_pages=num_pages,
                                                page_size=128,
                                                dtype=jnp.bfloat16,
                                                kv_dtype=kv_dtype)), rep)
    return cfg, params, cache


def compile_flagship_chunk(*, steps=32, slots=32, kv_dtype="",
                           weights="bf16w", per_seq=PER_SEQ_DIRECT,
                           span=BENCH_SPAN_DIRECT, backend="pallas"):
    """The bench decode-chunk program at 1.3b dims → v5e executable."""
    import jax
    import jax.numpy as jnp

    from reval_tpu.inference.tpu.paged_engine import PagedTPUEngine

    _env_mosaic(backend)
    mesh = _single_device_mesh(topology("v5e:2x2"))
    rep = _replicated(mesh)
    cfg, params, cache = flagship_model_parts(
        mesh, num_pages=bench_pool(slots, per_seq), kv_dtype=kv_dtype,
        weights=weights)
    state = jax.ShapeDtypeStruct((slots, span + 6), jnp.int32, sharding=rep)
    samp = jax.ShapeDtypeStruct((slots, 3), jnp.float32, sharding=rep)
    fn = partial(PagedTPUEngine._decode_chunk, cfg=cfg, steps=steps,
                 filtered=False)
    return (jax.jit(fn, donate_argnames=("cache",))
            .lower(params, state, cache, samp).compile())


def _compile_tp8_chunk(cfg, param_shapes, *, steps, slots, num_pages):
    """Shared tp=8 decode-chunk builder: one copy of the mesh/sharding/
    state recipe so the flagship and 34B certified programs cannot drift
    from each other (they differ only in cfg, weight init, and pool
    size)."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    from reval_tpu.inference.tpu.paged_engine import PagedTPUEngine
    from reval_tpu.models.paged import init_paged_cache
    from reval_tpu.parallel.mesh import make_mesh
    from reval_tpu.parallel.sharding import paged_cache_spec, param_specs

    _env_mosaic("pallas")
    topo = topology("v5e:4x2")
    mesh = make_mesh(tp=8, devices=np.array(topo.devices).reshape(8))
    rep = _replicated(mesh)
    specs = param_specs(param_shapes, cfg, mesh)
    params = jax.tree.map(
        lambda s, sp: jax.ShapeDtypeStruct(
            s.shape, s.dtype, sharding=NamedSharding(mesh, sp)),
        param_shapes, specs, is_leaf=lambda x: not isinstance(x, dict))
    cache_sharding = NamedSharding(mesh, paged_cache_spec(cfg, mesh))
    cache = jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(
            s.shape, s.dtype,
            sharding=cache_sharding if len(s.shape) == 3 else rep),
        jax.eval_shape(lambda: init_paged_cache(
            cfg, num_pages=num_pages, page_size=128, dtype=jnp.bfloat16)))
    state = jax.ShapeDtypeStruct((slots, BENCH_SPAN_DIRECT + 6), jnp.int32,
                                 sharding=rep)
    samp = jax.ShapeDtypeStruct((slots, 3), jnp.float32, sharding=rep)
    fn = partial(PagedTPUEngine._decode_chunk, cfg=cfg, steps=steps,
                 filtered=False, mesh=mesh)
    return (jax.jit(fn, donate_argnames=("cache",))
            .lower(params, state, cache, samp).compile())


def compile_tp8_flagship_chunk(*, steps=8, slots=32):
    """The tp=8 multi-chip decode program (GSPMD + the tp-manual Mosaic
    shard_map) → v5e-8 executable."""
    import jax

    from reval_tpu.models import init_random_params, zoo_config

    cfg = zoo_config("deepseek-coder-1.3b")
    cfg.dtype = "bfloat16"
    shapes = jax.eval_shape(
        lambda: init_random_params(cfg, seed=0, dtype="bfloat16"))
    return _compile_tp8_chunk(cfg, shapes, steps=steps, slots=slots,
                              num_pages=bench_pool(slots, PER_SEQ_DIRECT))


def compile_34b_northstar_chunk(*, steps=8, slots=4, num_pages=48):
    """The 34B north-star decode program (CodeLlama-34B, tp=8, int4,
    paged — dryrun_34b_northstar geometry) → v5e-8 executable."""
    import jax

    from reval_tpu.models import init_random_int4, zoo_config

    cfg = zoo_config("codellama/CodeLlama-34b-Instruct-hf")
    cfg.dtype = "bfloat16"
    shapes = jax.eval_shape(lambda: init_random_int4(cfg, seed=0, tp=8))
    return _compile_tp8_chunk(cfg, shapes, steps=steps, slots=slots,
                              num_pages=num_pages)


def setup_70b_pp():
    """(mesh, cfg, params) for the v5p-16 pp=2 x tp=8 CodeLlama-70B
    program (BASELINE configs[4]) at 2 of the 80 layers — compile cares
    about structure and width, not depth."""
    import numpy as np
    import jax
    from jax.sharding import NamedSharding

    from reval_tpu.models import init_random_int4, zoo_config
    from reval_tpu.parallel.mesh import make_mesh
    from reval_tpu.parallel.pipeline import pp_param_specs

    topo = topology("v5p:4x2x2")
    mesh = make_mesh(pp=2, tp=8, devices=np.array(topo.devices).reshape(16))
    cfg = zoo_config("codellama/CodeLlama-70b-Instruct-hf")
    cfg.num_layers = 2
    cfg.dtype = "bfloat16"
    shapes = jax.eval_shape(lambda: init_random_int4(cfg, seed=0, tp=8))
    specs = pp_param_specs(shapes, cfg, mesh)
    params = jax.tree.map(
        lambda s, sp: jax.ShapeDtypeStruct(
            s.shape, s.dtype, sharding=NamedSharding(mesh, sp)),
        shapes, specs, is_leaf=lambda x: not isinstance(x, dict))
    return mesh, cfg, params


def compile_70b_prefill(*, b=4, t=128, mb=2):
    """The 70B GPipe prefill → v5p-16 executable."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from reval_tpu.models.model import KVCache
    from reval_tpu.parallel.pipeline import pipeline_prefill

    mesh, cfg, params = setup_70b_pp()
    rows = b + mb                 # fill/drain scratch rows (pipeline.py)
    cshape = (cfg.num_layers, rows, t, cfg.num_kv_heads, cfg.head_dim)
    csh = NamedSharding(mesh, P("pp"))
    cache = KVCache(
        k=jax.ShapeDtypeStruct(cshape, jnp.bfloat16, sharding=csh),
        v=jax.ShapeDtypeStruct(cshape, jnp.bfloat16, sharding=csh))
    rep = _replicated(mesh)
    tokens = jax.ShapeDtypeStruct((b, t), jnp.int32, sharding=rep)
    pad = jax.ShapeDtypeStruct((b,), jnp.int32, sharding=rep)
    fn = partial(pipeline_prefill, cfg=cfg, mesh=mesh, n_micro=b // mb)
    return (jax.jit(fn)
            .lower(params, tokens=tokens, pad_len=pad, cache=cache)
            .compile())


def compile_70b_decode(*, b=4, t=256, steps=4):
    """The 70B token-ring decode chunk (exact runtime signature, incl.
    the [B] top_k/top_p rows the engine always passes) → v5p-16."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from reval_tpu.inference.tpu.pp_engine import PipelinedTPUEngine
    from reval_tpu.models.model import KVCache

    mesh, cfg, params = setup_70b_pp()
    rows = b + b // 2             # engine's scratch-row convention
    cshape = (cfg.num_layers, rows, t, cfg.num_kv_heads, cfg.head_dim)
    csh = NamedSharding(mesh, P("pp"))
    cache = KVCache(
        k=jax.ShapeDtypeStruct(cshape, jnp.bfloat16, sharding=csh),
        v=jax.ShapeDtypeStruct(cshape, jnp.bfloat16, sharding=csh))
    rep = _replicated(mesh)
    first = jax.ShapeDtypeStruct((b, 1), jnp.int32, sharding=rep)
    pad = jax.ShapeDtypeStruct((b,), jnp.int32, sharding=rep)
    pos = jax.ShapeDtypeStruct((), jnp.int32, sharding=rep)
    temp = jax.ShapeDtypeStruct((), jnp.float32, sharding=rep)
    key = jax.ShapeDtypeStruct((2,), jnp.uint32, sharding=rep)
    kf = jax.ShapeDtypeStruct((b,), jnp.int32, sharding=rep)
    pf = jax.ShapeDtypeStruct((b,), jnp.float32, sharding=rep)
    fn = partial(PipelinedTPUEngine._pp_decode_chunk, cfg=cfg, mesh=mesh,
                 steps=steps, filtered=False)
    return (jax.jit(fn, donate_argnames=("cache",))
            .lower(params, first, pad, cache, pos, temp, key, kf, pf)
            .compile())


def compile_prefill_commit(*, rows, t=512, n_pg=4, weights="bf16w",
                           kv_dtype="", num_pages=None):
    """The paged engine's prefill + page-commit programs → v5e."""
    import jax
    import jax.numpy as jnp

    from reval_tpu.models import init_kv_cache, prefill
    from reval_tpu.models.paged import commit_prefill, init_paged_cache

    _env_mosaic("pallas")
    mesh = _single_device_mesh(topology("v5e:2x2"))
    rep = _replicated(mesh)
    num_pages = num_pages or bench_pool(32, PER_SEQ_DIRECT)
    cfg, params, _ = flagship_model_parts(mesh, weights=weights)
    kv = _shaped(jax.eval_shape(
        lambda: init_kv_cache(cfg, rows, t, dtype=jnp.bfloat16)), rep)
    tokens = jax.ShapeDtypeStruct((rows, t), jnp.int32, sharding=rep)
    pad = jax.ShapeDtypeStruct((rows,), jnp.int32, sharding=rep)
    pre = (jax.jit(partial(prefill, cfg=cfg, logits_mode="last"))
           .lower(params, tokens=tokens, pad_len=pad, cache=kv).compile())
    pool = _shaped(jax.eval_shape(
        lambda: init_paged_cache(cfg, num_pages=num_pages, page_size=128,
                                 dtype=jnp.bfloat16, kv_dtype=kv_dtype)), rep)
    tables = jax.ShapeDtypeStruct((rows, n_pg), jnp.int32, sharding=rep)
    commit = (jax.jit(commit_prefill, donate_argnums=(0,))
              .lower(pool, kv, pad, tables).compile())
    return pre, commit
