"""Emit a chip-free compile certificate for the headline programs.

Runs the shared deviceless XLA:TPU program builders (tools/aot_programs
— the same ones tests/test_tpu_aot_compile.py asserts on) for the
headline configs and records XLA's own memory analysis in one JSON
artifact (``AOT_CERT.json`` by default) — so the evidence that these
programs compile for real TPU targets and fit their chips is a recorded
number, not just a green test name:

- flagship bench decode chunk (deepseek-coder-1.3b, 32 slots) → v5e,
  16 GB fit asserted;
- the 34B north star (CodeLlama-34B, tp=8, weight-only int4, paged
  decode) → v5e-8, per-chip 16 GB fit asserted;
- the 70B configs[4] program (pp=2 x tp=8, int4) GPipe prefill →
  v5p-16.

The artifact is rewritten after every certificate, so a mid-run kill
keeps the certificates already earned (the 34B compile alone is ~10
minutes of XLA time).

Usage: python tools/aot_certify.py [--out AOT_CERT.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools import aot_programs


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="AOT_CERT.json")
    args = ap.parse_args()

    report: dict = {"certificates": []}

    def mem(compiled):
        ma = compiled.memory_analysis()
        live = ma.argument_size_in_bytes + ma.temp_size_in_bytes
        return {
            "args_gib": round(ma.argument_size_in_bytes / 2**30, 3),
            "temp_gib": round(ma.temp_size_in_bytes / 2**30, 3),
            "per_chip_live_gib": round(live / 2**30, 3),
        }

    def cert(name, target, hbm_gib, build):
        t0 = time.time()
        try:
            entry = {"program": name, "target": target, **mem(build())}
            entry["compiled"] = True
            if hbm_gib:
                entry["fits"] = entry["per_chip_live_gib"] <= hbm_gib * 0.9
                entry["chip_hbm_gib"] = hbm_gib
            entry["compile_s"] = round(time.time() - t0, 1)
        except Exception as e:
            entry = {"program": name, "target": target, "compiled": False,
                     "error": f"{type(e).__name__}: {str(e)[:300]}"}
        report["certificates"].append(entry)
        print(json.dumps(entry), flush=True)
        # rewrite after every certificate: a mid-run kill must not discard
        # the ~10-minute compiles already finished
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1)

    cert("flagship bench decode chunk (deepseek-1.3b, 32 slots, 32 steps)",
         "v5e (1 chip)", 16, aot_programs.compile_flagship_chunk)
    cert("34B north star decode (CodeLlama-34B, tp=8, int4, paged)",
         "v5e-8", 16, aot_programs.compile_34b_northstar_chunk)
    cert("70B configs[4] GPipe prefill (pp=2 x tp=8, int4, 2/80 layers)",
         "v5p-16", None, aot_programs.compile_70b_prefill)

    print(f"wrote {args.out}")
    bad = [c for c in report["certificates"]
           if not c.get("compiled") or c.get("fits") is False]
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
