#!/usr/bin/env python3
"""Self-healing kernel CI: supervised per-cell benchmarking + autotune
leaderboard — the ``tools/`` entry point over
``reval_tpu/kernelbench.py`` (one implementation; ``python -m
reval_tpu.kernelbench`` is the same program, and is what the harness
spawns per cell).

    python tools/kernelbench.py                 # chip round, full matrix
    python tools/kernelbench.py --tiny          # CPU harness certification
    python tools/kernelbench.py --tiny \\
        --chaos-cell wedge:pallas-swap-bf16-c2  # degradation drill

Each cell (kernel backend × dot tile formulation × KV pool dtype ×
decode chunk cadence) runs as a timeout-bounded subprocess under the
bench StallWatchdog and RetryPolicy backoff; a wedged cell degrades to a
stale-marked entry carrying its last-known value + commit, never a 0.0
and never an aborted round.  The surviving cells write an atomic
``reval-kernelbench-v1`` leaderboard artifact, the winner emits a
``tools/decide_defaults.py``-compatible serving-config pick, and the
regression gate exits 1 (named cell, incumbent-vs-HEAD delta) when HEAD
regresses the incumbent winner beyond the noise band.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from reval_tpu.kernelbench import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
