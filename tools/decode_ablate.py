"""On-chip decode-step ablation: where does the paged decode millisecond go?

Times the paged engine's jitted decode chunk at a configurable shape and
isolates components by trace-time substitution:

  full        — the production chunk (paged attention + cache writes + mlp
                + sampling)
  no-attn     — paged_decode_attention replaced by identity on q: removes
                the KV page reads (the pool-bandwidth term)
  kv-int8     — same chunk with the int8 page pool (halved pool reads)

Prints ms/step, tok/s, and the HBM roofline estimate (weights + KV reads
at the device's bandwidth) so kernel inefficiency is separable from
bandwidth limits.  Run on a real chip (falls back to CPU for smoke):

    python tools/decode_ablate.py --slots 32 --ctx 600
"""

from __future__ import annotations

import argparse
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

HBM_GBPS = {"v5 lite": 819, "v5e": 819, "v5p": 2765, "v4": 1228, "v6": 1640}


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--slots", type=int, default=32)
    ap.add_argument("--ctx", type=int, default=600, help="tokens already in cache")
    ap.add_argument("--steps", type=int, default=32, help="chunk length")
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--model", default="deepseek-coder-1.3b")
    ap.add_argument("--dtype", choices=["bfloat16", "int8"], default="bfloat16")
    ap.add_argument("--max-seq-len", type=int, default=2048)
    ap.add_argument("--variants", default="core,seq,slots,chunk,page",
                    help="comma list of variant groups to run, in order: "
                         "core (full/no-attn/kv-int8), seq (streaming "
                         "kernel), slots (batch-width sweep), chunk "
                         "(chunk-length sweep), page (page-size sweep).  "
                         "Groups run in the order given, so a timeout or "
                         "tunnel wedge loses the LAST groups — put the "
                         "decision-critical ones first")
    ap.add_argument("--tiny", action="store_true", help="CPU smoke shape")
    args = ap.parse_args()

    from bench import acquire_chip_lock
    chip_lock = acquire_chip_lock(skip=args.tiny)  # held until exit

    import jax
    import jax.numpy as jnp
    import numpy as np

    if args.tiny:
        jax.config.update("jax_platforms", "cpu")

    from functools import partial

    import reval_tpu.models.paged as paged_mod
    from reval_tpu.inference.tpu.paged_engine import PagedTPUEngine
    from reval_tpu.models import ModelConfig, init_random_params, zoo_config

    if args.tiny:
        cfg = ModelConfig(vocab_size=1024, hidden_size=128, intermediate_size=256,
                          num_layers=2, num_heads=4, num_kv_heads=4, head_dim=32)
        params = init_random_params(cfg, seed=0, dtype="float32")
        args.slots, args.ctx, args.steps = 4, 96, 8
    else:
        cfg = zoo_config(args.model)
        cfg.dtype = "bfloat16"
        params = init_random_params(cfg, seed=0, dtype=args.dtype)

    dev = jax.devices()[0]
    print(f"device: {dev.device_kind} | model {args.model} {args.dtype} | "
          f"slots={args.slots} ctx={args.ctx} steps={args.steps}")

    # Bare dispatch round-trip: a trivial jitted op, timed like a chunk
    # (dispatch + block).  On the tunneled chip this IS the per-chunk RPC
    # floor — it separates host/tunnel latency from on-device work.
    # NB: every timed region here ends on np.asarray, not
    # block_until_ready — through the axon tunnel block_until_ready
    # returns before the device has executed; only a host fetch syncs.
    tiny_f = jax.jit(lambda x: x + 1)
    x = jnp.zeros((8,), jnp.int32)
    np.asarray(tiny_f(x))
    rtts = []
    for _ in range(10):
        t0 = time.perf_counter()
        np.asarray(tiny_f(x))
        rtts.append(time.perf_counter() - t0)
    rtt_ms = statistics.median(rtts) * 1000
    print(f"bare jit dispatch round-trip: {rtt_ms:.3f} ms "
          f"(amortised per step at chunk={args.steps}: {rtt_ms/args.steps:.3f} ms)")

    def run_variant(label: str, kv_dtype: str = "", no_attn: bool = False,
                    steps: int | None = None, page: int = 128,
                    backend: str | None = None, slots: int | None = None):
        steps = args.steps if steps is None else steps
        slots = args.slots if slots is None else slots
        orig = paged_mod.paged_decode_attention
        orig_backend = os.environ.get("REVAL_TPU_PAGED_BACKEND")
        if no_attn:
            # signature-agnostic identity: the kernel's kwargs evolve
            paged_mod.paged_decode_attention = lambda q, *a, **kw: q
        if backend:
            os.environ["REVAL_TPU_PAGED_BACKEND"] = backend
        try:
            from reval_tpu.inference.tpu.tokenizer import ByteTokenizer

            # budget covers warm-up + every timed rep (lens advances each)
            need = (args.ctx + steps * (args.reps + 1)) // page + 2
            num_pages = 1 + slots * need
            eng = PagedTPUEngine(params, cfg, ByteTokenizer(),
                                 max_slots=slots, page_size=page,
                                 max_seq_len=args.max_seq_len,
                                 num_pages=num_pages, kv_dtype=kv_dtype)
            b = slots
            span = eng.max_pages_per_seq
            tables = np.zeros((b, span), np.int32)
            for s in range(b):
                for j in range(need):
                    tables[s, j] = 1 + s * need + j
            lens = np.full((b,), args.ctx, np.int32)
            tok = np.ones((b, 1), np.int32)
            # packed state layout: tables | lens | token | PRNG key (2
            # int32 words) | generated-pos (see _decode_chunk)
            keys = eng.request_keys(b)
            pos = np.zeros((b, 1), np.int32)
            state = jnp.asarray(
                np.concatenate([tables, lens[:, None], tok,
                                keys.view(np.int32), pos], axis=1))
            # sampling params ride a [B, 3] stack (temp | top_p | top_k)
            # since the per-request top-k/nucleus change
            temp = jnp.asarray(np.stack(
                [np.zeros(b, np.float32), np.ones(b, np.float32),
                 np.zeros(b, np.float32)], axis=1))

            cache = eng.cache
            # warm compile
            toks, cache, state2 = eng._jit_chunk(eng.params, state, cache,
                                                 temp, steps=steps)
            np.asarray(toks)
            times = []
            st = state2
            for _ in range(args.reps):
                t0 = time.perf_counter()
                toks, cache, st = eng._jit_chunk(eng.params, st, cache,
                                                 temp, steps=steps)
                np.asarray(toks)  # host fetch = the only real sync (tunnel)
                times.append(time.perf_counter() - t0)
            eng.close()
            ms_step = statistics.median(times) / steps * 1000
            print(f"{label:10s} {ms_step:8.3f} ms/step  "
                  f"{b / ms_step * 1000:8.0f} tok/s")
            return ms_step
        finally:
            paged_mod.paged_decode_attention = orig
            if orig_backend is None:
                os.environ.pop("REVAL_TPU_PAGED_BACKEND", None)
            else:
                os.environ["REVAL_TPU_PAGED_BACKEND"] = orig_backend

    results = {}

    def group_core():
        results["full"] = run_variant("full")
        results["no-attn"] = run_variant("no-attn", no_attn=True)
        results["kv-int8"] = run_variant("kv-int8", kv_dtype="int8")

    def group_seq():
        # the per-sequence streaming kernel (ops/pallas_attention.py
        # _decode_kernel_seq): grid [B] + in-kernel double-buffered page
        # DMA vs the per-(seq, page) grid of the default kernel
        run_variant("seq-kernel", backend="pallas_seq")
        run_variant("seqk-kv8", backend="pallas_seq", kv_dtype="int8")

    def group_slots():
        # slots sweep: weight reads amortise over the batch, KV reads
        # scale with it — if no-attn ms/step is ~flat in slots the
        # non-attention path is weight-bound (raise slots for tok/s); if
        # it scales, the per-slot work (sampling, scatter, norms) is the
        # next target.  64-slot pools only fit in HBM as int8 next to
        # the bf16 weights.
        run_variant("kv8@s64", kv_dtype="int8", slots=64)
        run_variant("seqk8@s64", backend="pallas_seq", kv_dtype="int8",
                    slots=64)
        run_variant("noatt8@s64", no_attn=True, kv_dtype="int8", slots=64)
        run_variant("full@s16", slots=16)
        run_variant("noatt@s16", no_attn=True, slots=16)

    def group_chunk():
        # chunk-length sweep: per-chunk dispatch/RPC overhead shows up as
        # the per-step cost falling with longer chunks; on-device
        # inefficiency does not amortise away
        for s in (8, 64):
            if s != args.steps:
                run_variant(f"full@{s}", steps=s)

    def group_page():
        # page-size sweep: the default kernel runs one sequential grid
        # step per (sequence, page) per layer — bigger pages halve the
        # grid-step count at the cost of pool fragmentation; if this
        # moves the needle the bottleneck is grid overhead, not DMA
        # bandwidth
        run_variant("page=256", page=256)
        run_variant("page=512", page=512)

    groups = {"core": group_core, "seq": group_seq, "slots": group_slots,
              "chunk": group_chunk, "page": group_page}
    for name in args.variants.split(","):
        name = name.strip()
        if name not in groups:
            raise SystemExit(f"unknown variant group {name!r}; "
                             f"expected {sorted(groups)}")
        groups[name]()
    full, noattn, kv8 = (results.get("full"), results.get("no-attn"),
                         results.get("kv-int8"))

    # roofline: weight bytes + kv bytes per step at device bandwidth
    wbytes = sum(x.size * x.dtype.itemsize
                 for x in jax.tree_util.tree_leaves(params))
    kv_tok = 2 * cfg.num_layers * cfg.num_kv_heads * cfg.head_dim
    kvbytes = kv_tok * 2 * args.ctx * args.slots     # bf16 pool
    bw = next((v for k, v in HBM_GBPS.items()
               if k in dev.device_kind.lower()), 819) * 1e9
    print(f"\nroofline: weights {wbytes/1e9:.2f} GB + KV {kvbytes/1e9:.2f} GB "
          f"per step @ {bw/1e12:.2f} TB/s = {(wbytes+kvbytes)/bw*1000:.2f} ms/step "
          f"(attention share {kvbytes/(wbytes+kvbytes):.0%})")
    if full is not None and noattn is not None and kv8 is not None:
        print(f"attn cost observed: {full - noattn:.3f} ms/step; "
              f"int8 pool saves {full - kv8:.3f} ms/step")


if __name__ == "__main__":
    main()
