"""Pre-pay TPU compile time before a tunnel window opens.

The deviceless PJRT topology (`jax.experimental.topologies`) produces
real XLA:TPU executables on this host with no chip, and those compiles
land in the persistent compile cache — the same cache
(`$JAX_COMPILATION_CACHE_DIR`, default matching tools/chip_runbook.sh)
the on-chip runbook benches read.  If the runtime cache key matches, a
~19-minute tunnel window spends its time MEASURING instead of
compiling; if it doesn't match, the cost is only host CPU spent here.

Warms the decode-chunk programs of the runbook's decision set at their
exact runtime shapes (deepseek-coder-1.3b dims, spans/steps the engine
buckets to):

    backend {grid, seq} x kv {bf16, int8} x slots {32, 64}
    x steps {8, 32}, plus the int8-weight variant of the default.

Cache mechanics (measured): the persistent-cache KEY for each program is
stable across runs/processes, and entries land in the cache dir — but
the deviceless compile path never READS the cache (every warm re-run
logs `PERSISTENT COMPILATION CACHE MISS` for a key that exists on disk,
then rewrites it byte-identically).  The read path only runs against a
real backend, i.e. exactly the on-chip bench this tool is warming for —
so re-running the tool is idempotent-but-slow, and whether the runtime
hits depends only on its key matching (same module hash + compile
options + platform version).

Usage: python tools/aot_warm.py [--cache-dir DIR] [--quick]
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--cache-dir",
                    default=os.environ.get("JAX_COMPILATION_CACHE_DIR",
                                           "/root/.cache/jax_comp"))
    ap.add_argument("--quick", action="store_true",
                    help="default config only (one backend, bf16, 32 slots)")
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_compilation_cache_dir", args.cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    # the dispatcher keys interpret mode on the RUNTIME backend (cpu on
    # this host) — force the Mosaic kernel or every warmed executable
    # would contain the HLO emulation and never match an on-chip key
    os.environ["REVAL_TPU_FORCE_MOSAIC"] = "1"

    import numpy as np
    import jax.numpy as jnp
    from jax.experimental import topologies
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from reval_tpu.inference.tpu.paged_engine import PagedTPUEngine
    from reval_tpu.models import (init_random_params, quantize_params,
                                  zoo_config)
    from reval_tpu.models.paged import init_paged_cache

    topo = topologies.get_topology_desc(platform="tpu",
                                        topology_name="v5e:2x2")
    mesh = Mesh(np.array(topo.devices[:1]), ("x",))
    rep = NamedSharding(mesh, P())

    def shaped(tree):
        return jax.tree.map(
            lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=rep),
            tree)

    cfg = zoo_config("deepseek-coder-1.3b")
    cfg.dtype = "bfloat16"
    params_bf16 = shaped(jax.eval_shape(
        lambda: init_random_params(cfg, seed=0, dtype="bfloat16")))
    params_int8 = shaped(jax.eval_shape(
        lambda: quantize_params(init_random_params(cfg, seed=0,
                                                   dtype="bfloat16"))))

    def chunk_args(slots, kv_dtype, params, per_seq, span):
        # bench.py default pool: 1 + slots * per_seq + 16
        num_pages = 1 + slots * per_seq + 16
        cache = shaped(jax.eval_shape(
            lambda: init_paged_cache(cfg, num_pages=num_pages, page_size=128,
                                     dtype=jnp.bfloat16, kv_dtype=kv_dtype)))
        state = jax.ShapeDtypeStruct((slots, span + 5), jnp.int32,
                                     sharding=rep)
        sampling = jax.ShapeDtypeStruct((slots, 3), jnp.float32, sharding=rep)
        return params, state, cache, sampling

    # (backend, kv_dtype, slots, weights, per_seq, span): spans/pools are
    # what the engine pow2-buckets to at the bench's prompt lengths —
    # direct (~500 tok + 256 new): per_seq 7, span bucket 8; cot
    # (+1024 new): per_seq 13, span bucket 16
    jobs = [("grid", "", 32, "bf16w", 7, 8)]
    if not args.quick:
        jobs += [
            ("pallas_seq", "", 32, "bf16w", 7, 8),
            ("grid", "int8", 64, "bf16w", 7, 8),
            ("pallas_seq", "int8", 64, "bf16w", 7, 8),
            ("grid", "", 32, "int8w", 7, 8),
            ("grid", "", 24, "bf16w", 13, 16),      # bench --mode cot
            ("grid", "int8", 24, "bf16w", 13, 16),  # cot + int8 kv
        ]

    # prefill + page-commit programs (the other half of a cold bench's
    # compile time).  Bench prompts (~500 tok) bucket to t=512; the 768 MB
    # prefill byte budget caps groups at 7 rows → pow2 row buckets 8 and
    # 4 (the tail group of a 32-prompt admission wave).  The prefill
    # program varies with the weight dtype, the commit program with the
    # pool (size + kv dtype) — warm every distinct combination the
    # decode jobs above will bench.
    def warm_prefill(rows, t, n_pg, params, num_pages, kv_dtype, label):
        from reval_tpu.models import init_kv_cache, prefill
        from reval_tpu.models.paged import commit_prefill

        kv = shaped(jax.eval_shape(
            lambda: init_kv_cache(cfg, rows, t, dtype=jnp.bfloat16)))
        tokens = jax.ShapeDtypeStruct((rows, t), jnp.int32, sharding=rep)
        pad = jax.ShapeDtypeStruct((rows,), jnp.int32, sharding=rep)
        t0 = time.time()
        (jax.jit(partial(prefill, cfg=cfg, logits_mode="last"))
         .lower(params, tokens=tokens, pad_len=pad, cache=kv).compile())
        pool = shaped(jax.eval_shape(
            lambda: init_paged_cache(cfg, num_pages=num_pages, page_size=128,
                                     dtype=jnp.bfloat16, kv_dtype=kv_dtype)))
        tables = jax.ShapeDtypeStruct((rows, n_pg), jnp.int32, sharding=rep)
        (jax.jit(commit_prefill, donate_argnums=(0,))
         .lower(pool, kv, pad, tables).compile())
        print(f"warmed prefill+commit rows={rows} t={t} {label} in "
              f"{time.time() - t0:.0f}s", flush=True)

    failures = 0
    if not args.quick:
        seen: set[tuple] = set()
        for _, kv_dtype, slots, wdtype, per_seq, _ in jobs:
            num_pages = 1 + slots * per_seq + 16
            combo = (wdtype, kv_dtype, num_pages)
            if combo in seen:
                continue
            seen.add(combo)
            params = params_int8 if wdtype == "int8w" else params_bf16
            for rows in (8, 4):
                label = f"{wdtype}/kv={kv_dtype or 'bf16'}/pool{num_pages}"
                try:
                    warm_prefill(rows, 512, 4, params, num_pages, kv_dtype,
                                 label)
                except Exception as e:
                    failures += 1
                    print(f"FAILED prefill rows={rows} {label}: "
                          f"{type(e).__name__}: {str(e)[:200]}", flush=True)

    for backend, kv_dtype, slots, wdtype, per_seq, span in jobs:
        os.environ["REVAL_TPU_PAGED_BACKEND"] = (
            "pallas" if backend == "grid" else backend)
        params = params_int8 if wdtype == "int8w" else params_bf16
        for steps in (8, 32):
            label = f"{backend}/kv={kv_dtype or 'bf16'}/s{slots}/{wdtype}/steps{steps}"
            fn = partial(PagedTPUEngine._decode_chunk, cfg=cfg, steps=steps,
                         filtered=False)
            t0 = time.time()
            try:
                (jax.jit(fn, donate_argnames=("cache",))
                 .lower(*chunk_args(slots, kv_dtype, params, per_seq, span))
                 .compile())
                print(f"warmed {label} in {time.time() - t0:.0f}s", flush=True)
            except Exception as e:
                failures += 1
                print(f"FAILED {label}: {type(e).__name__}: {str(e)[:200]}",
                      flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
