"""Pre-pay TPU compile time before a tunnel window opens.

The deviceless PJRT topology (`jax.experimental.topologies`) produces
real XLA:TPU executables on this host with no chip, and those compiles
land in the persistent compile cache — the same cache
(`$JAX_COMPILATION_CACHE_DIR`, default matching tools/chip_runbook.sh)
the on-chip runbook benches read.  If the runtime cache key matches, a
~19-minute tunnel window spends its time MEASURING instead of
compiling; if it doesn't match, the cost is only host CPU spent here.

Warms the decode-chunk programs of the runbook's decision set at their
exact runtime shapes (deepseek-coder-1.3b dims, spans/steps the engine
buckets to):

    backend {grid, seq} x kv {bf16, int8} x slots {32, 64}
    x steps {8, 32}, plus the int8-weight variant of the default.

Usage: python tools/aot_warm.py [--cache-dir DIR] [--quick]
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--cache-dir",
                    default=os.environ.get("JAX_COMPILATION_CACHE_DIR",
                                           "/root/.cache/jax_comp"))
    ap.add_argument("--quick", action="store_true",
                    help="default config only (one backend, bf16, 32 slots)")
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_compilation_cache_dir", args.cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    # the dispatcher keys interpret mode on the RUNTIME backend (cpu on
    # this host) — force the Mosaic kernel or every warmed executable
    # would contain the HLO emulation and never match an on-chip key
    os.environ["REVAL_TPU_FORCE_MOSAIC"] = "1"

    import numpy as np
    import jax.numpy as jnp
    from jax.experimental import topologies
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from reval_tpu.inference.tpu.paged_engine import PagedTPUEngine
    from reval_tpu.models import (init_random_params, quantize_params,
                                  zoo_config)
    from reval_tpu.models.paged import init_paged_cache

    topo = topologies.get_topology_desc(platform="tpu",
                                        topology_name="v5e:2x2")
    mesh = Mesh(np.array(topo.devices[:1]), ("x",))
    rep = NamedSharding(mesh, P())

    def shaped(tree):
        return jax.tree.map(
            lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=rep),
            tree)

    cfg = zoo_config("deepseek-coder-1.3b")
    cfg.dtype = "bfloat16"
    params_bf16 = shaped(jax.eval_shape(
        lambda: init_random_params(cfg, seed=0, dtype="bfloat16")))
    params_int8 = shaped(jax.eval_shape(
        lambda: quantize_params(init_random_params(cfg, seed=0,
                                                   dtype="bfloat16"))))

    # the engine pow2-buckets the table span; bench prompts (~500 tok) +
    # 256 new land in bucket 8 (paged_engine.pow2_bucket)
    span = 8

    def chunk_args(slots, kv_dtype, params):
        # bench.py default pool: 1 + slots * per_seq + 16, per_seq ~7
        num_pages = 1 + slots * 7 + 16
        cache = shaped(jax.eval_shape(
            lambda: init_paged_cache(cfg, num_pages=num_pages, page_size=128,
                                     dtype=jnp.bfloat16, kv_dtype=kv_dtype)))
        state = jax.ShapeDtypeStruct((slots, span + 5), jnp.int32,
                                     sharding=rep)
        sampling = jax.ShapeDtypeStruct((slots, 3), jnp.float32, sharding=rep)
        return params, state, cache, sampling

    jobs = [("grid", "", 32, "bf16w")]
    if not args.quick:
        jobs += [
            ("pallas_seq", "", 32, "bf16w"),
            ("grid", "int8", 64, "bf16w"),
            ("pallas_seq", "int8", 64, "bf16w"),
            ("grid", "", 32, "int8w"),
        ]

    failures = 0
    for backend, kv_dtype, slots, wdtype in jobs:
        os.environ["REVAL_TPU_PAGED_BACKEND"] = (
            "pallas" if backend == "grid" else backend)
        params = params_int8 if wdtype == "int8w" else params_bf16
        for steps in (8, 32):
            label = f"{backend}/kv={kv_dtype or 'bf16'}/s{slots}/{wdtype}/steps{steps}"
            fn = partial(PagedTPUEngine._decode_chunk, cfg=cfg, steps=steps,
                         filtered=False)
            t0 = time.time()
            try:
                (jax.jit(fn, donate_argnames=("cache",))
                 .lower(*chunk_args(slots, kv_dtype, params)).compile())
                print(f"warmed {label} in {time.time() - t0:.0f}s", flush=True)
            except Exception as e:
                failures += 1
                print(f"FAILED {label}: {type(e).__name__}: {str(e)[:200]}",
                      flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
