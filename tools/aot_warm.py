"""Pre-pay TPU compile time before a tunnel window opens.

The deviceless PJRT topology (`jax.experimental.topologies`) produces
real XLA:TPU executables on this host with no chip, and those compiles
land in the persistent compile cache — the same cache
(`$JAX_COMPILATION_CACHE_DIR`, default matching tools/chip_runbook.sh)
the on-chip runbook benches read.  If the runtime cache key matches, a
~19-minute tunnel window spends its time MEASURING instead of
compiling; if it doesn't match, the cost is only host CPU spent here.

The programs come from tools/aot_programs — the same builders the AOT
test tier asserts on — at the exact runtime shapes of the runbook's
decision set:

    decode: backend {grid, seq} x kv {bf16, int8} x slots {32, 64}
            x steps {8, 32}, the int8-weight variant, and the cot
            (24-slot / span-16) configs;
    prefill+commit: every distinct (weights, kv dtype, pool) those
            decode configs imply, at the 8- and 4-row admission buckets.

Cache mechanics (measured): the persistent-cache KEY for each program is
stable across runs/processes, and entries land in the cache dir — but
the deviceless compile path never READS the cache (every warm re-run
logs `PERSISTENT COMPILATION CACHE MISS` for a key that exists on disk,
then rewrites it byte-identically).  The read path only runs against a
real backend, i.e. exactly the on-chip bench this tool is warming for —
so re-running the tool is idempotent-but-slow, and whether the runtime
hits depends only on its key matching (same module hash + compile
options + platform version).

Usage: python tools/aot_warm.py [--cache-dir DIR] [--quick]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools import aot_programs
from tools.aot_programs import (PER_SEQ_COT, PER_SEQ_DIRECT,
                                BENCH_SPAN_COT, BENCH_SPAN_DIRECT,
                                bench_pool)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--cache-dir",
                    default=os.environ.get("JAX_COMPILATION_CACHE_DIR",
                                           "/root/.cache/jax_comp"))
    ap.add_argument("--quick", action="store_true",
                    help="default config only (one backend, bf16, 32 slots)")
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_compilation_cache_dir", args.cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    # (backend, kv_dtype, slots, weights, per_seq, span, dot_mode)
    jobs = [("pallas", "", 32, "bf16w", PER_SEQ_DIRECT, BENCH_SPAN_DIRECT,
             "swap")]
    if not args.quick:
        jobs += [
            ("pallas_seq", "", 32, "bf16w", PER_SEQ_DIRECT,
             BENCH_SPAN_DIRECT, "swap"),
            # the wide dot-mode candidates (REVAL_TPU_KERNEL_DOT=wide):
            # if the on-chip A/B flips the default, the diagnosis tier's
            # first pass must not pay fresh compiles
            ("pallas", "", 32, "bf16w", PER_SEQ_DIRECT, BENCH_SPAN_DIRECT,
             "wide"),
            ("pallas_seq", "", 32, "bf16w", PER_SEQ_DIRECT,
             BENCH_SPAN_DIRECT, "wide"),
            ("pallas", "int8", 64, "bf16w", PER_SEQ_DIRECT,
             BENCH_SPAN_DIRECT, "swap"),
            ("pallas_seq", "int8", 64, "bf16w", PER_SEQ_DIRECT,
             BENCH_SPAN_DIRECT, "swap"),
            ("pallas", "", 32, "int8w", PER_SEQ_DIRECT, BENCH_SPAN_DIRECT,
             "swap"),
            ("pallas", "", 24, "bf16w", PER_SEQ_COT, BENCH_SPAN_COT, "swap"),
            ("pallas", "int8", 24, "bf16w", PER_SEQ_COT, BENCH_SPAN_COT,
             "swap"),
        ]

    failures = 0

    def run(label, fn, **kw):
        nonlocal failures
        t0 = time.time()
        try:
            fn(**kw)
            print(f"warmed {label} in {time.time() - t0:.0f}s", flush=True)
        except Exception as e:
            failures += 1
            print(f"FAILED {label}: {type(e).__name__}: {str(e)[:200]}",
                  flush=True)

    # prefill + page-commit: every distinct (weights, kv, pool) the
    # decode jobs imply, at both admission-wave row buckets
    if not args.quick:
        seen: set[tuple] = set()
        for _, kv_dtype, slots, wdtype, per_seq, _, _ in jobs:
            combo = (wdtype, kv_dtype, bench_pool(slots, per_seq))
            if combo in seen:
                continue
            seen.add(combo)
            for rows in (8, 4):
                run(f"prefill+commit rows={rows} {wdtype}/"
                    f"kv={kv_dtype or 'bf16'}/pool{combo[2]}",
                    aot_programs.compile_prefill_commit, rows=rows,
                    weights=wdtype, kv_dtype=kv_dtype, num_pages=combo[2])

    for backend, kv_dtype, slots, wdtype, per_seq, span, dot in jobs:
        os.environ["REVAL_TPU_KERNEL_DOT"] = dot   # read at trace time
        for steps in (8, 32):
            run(f"{backend}/kv={kv_dtype or 'bf16'}/s{slots}/{wdtype}"
                f"/steps{steps}/dot={dot}",
                aot_programs.compile_flagship_chunk, steps=steps,
                slots=slots, kv_dtype=kv_dtype, weights=wdtype,
                per_seq=per_seq, span=span, backend=backend)
    os.environ.pop("REVAL_TPU_KERNEL_DOT", None)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
