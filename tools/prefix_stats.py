"""Token-level prefix-overlap report: size the radix prefix cache and
predict its hit rates before burning a chip window.

For each task of a (dataset, prompt_type) workload this measures, on the
GENUINE planned prompts (mock planning — the same few-shot templates and
programs the scoring pipeline sends):

- ``template_tokens``: the task's intra-task LCP (its few-shot template);
- ``template_share``: template tokens / mean prompt tokens — the fraction
  of every prompt's prefill that is pure repetition (PERF.md cites
  50-72% for DREval direct prompts);
- ``distinct_pages``: pages a page-granular radix tree holds after
  inserting every prompt's full page-aligned prefix — the cache's working
  set for one repeat (multiply by the page's KV bytes for HBM);
- ``warm_hit_rate``: fraction of prompt tokens served from cache on a
  repeat of the same prompt set (fleet repeats 2..N) — page-aligned full
  prefixes over total tokens;
- ``cold_hit_rate``: in-batch sharing on the FIRST pass (later prompts
  hitting pages inserted by earlier ones, task-contiguous order).

With ``--json PATH`` it additionally writes a machine-readable
**affinity table** (``reval-affinity-v1``): per task, the character
length of its template prefix and the crc32 affinity key the fleet
router (``reval_tpu router --affinity-table``) would compute for that
template, plus the fleet-wide ``window_chars`` (the shortest template —
one window that fits inside every task's template, so same-task prompts
always share a key).  The same block rides the stdout JSON under
``"affinity"``.

Prints ONE JSON line.  Examples:

    python tools/prefix_stats.py --dataset humaneval --prompt-type direct
    python tools/prefix_stats.py --tiny          # CPU smoke (tiny counts)
    python tools/prefix_stats.py --tiny --json /tmp/affinity.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

TASKS = ("coverage", "path", "state", "output")


def task_prompts(name: str, n: int, dataset: str, prompt_type: str
                 ) -> list[str]:
    from reval_tpu.tasks import TASKS as TASK_CLASSES

    items = 2
    while True:
        task = TASK_CLASSES[name](model=None, prompt_type=prompt_type,
                                  dataset=dataset, mock=True, max_items=items,
                                  progress=False)
        _, jobs = task._plan()
        if len(jobs) >= n or items > 64:
            return [j.prompt for j in jobs][:n]
        items *= 2


def lcp_tokens(encoded: list[list[int]]) -> int:
    if not encoded:
        return 0
    first = encoded[0]
    lcp = min(len(e) for e in encoded)
    for ids in encoded[1:]:
        i, n = 0, min(lcp, len(ids))
        while i < n and ids[i] == first[i]:
            i += 1
        lcp = i
    return lcp


def lcp_chars(prompts: list[str]) -> int:
    """Character-level longest common prefix — the router hashes CHAR
    windows (it sees wire prompts, not token ids)."""
    if not prompts:
        return 0
    first = prompts[0]
    lcp = min(len(p) for p in prompts)
    for p in prompts[1:]:
        i, n = 0, min(lcp, len(p))
        while i < n and p[i] == first[i]:
            i += 1
        lcp = i
    return lcp


def affinity_table(by_task: dict[str, list[str]],
                   floor_chars: int = 16) -> dict:
    """The ``reval-affinity-v1`` hash-ring seed the fleet router loads:
    one window that fits inside EVERY task's template (the minimum
    char-LCP, floored so a degenerate task cannot collapse routing to a
    couple of characters), and each template's crc32 key under that
    window."""
    import zlib

    lcps = {t: lcp_chars(ps) for t, ps in by_task.items() if ps}
    window = max(floor_chars, min(lcps.values())) if lcps else floor_chars
    tasks = {}
    for t, ps in by_task.items():
        if not ps:
            continue
        key = zlib.crc32(ps[0][:window].encode("utf-8", "replace")) & 0xFFFFFFFF
        tasks[t] = {"template_chars": lcps[t], "key": f"{key:08x}"}
    return {"format": "reval-affinity-v1", "window_chars": window,
            "tasks": tasks}


def radix_stats(encoded: list[list[int]], page: int) -> tuple[int, int, int]:
    """Simulate the engine's page-granular radix insertion over the
    prompt stream → (distinct_pages, cold_hit_tokens, warm_hit_tokens).

    cold: tokens a first pass serves from pages earlier prompts in the
    SAME stream inserted; warm: tokens a full repeat of the stream serves
    (every page-aligned prefix already cached)."""
    tree: dict = {}
    distinct = 0
    cold_hits = 0
    warm_hits = 0
    for ids in encoded:
        cap = max(0, len(ids) - 1) // page
        warm_hits += cap * page
        children = tree
        missed = False
        for i in range(cap):
            key = tuple(ids[i * page:(i + 1) * page])
            node = children.get(key)
            if node is None:
                node = children[key] = {}
                distinct += 1
                missed = True
            elif not missed:
                cold_hits += page
            children = node
    return distinct, cold_hits, warm_hits


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--dataset", default="humaneval")
    ap.add_argument("--prompt-type", choices=["direct", "cot"],
                    default="direct")
    ap.add_argument("--per-task", type=int, default=32,
                    help="prompts per task (4 tasks)")
    ap.add_argument("--page-size", type=int, default=128)
    ap.add_argument("--tokenizer", default=None,
                    help="real tokenizer dir (tokenizer.json); default: a "
                         "BPE trained on the prompt corpus, like bench.py")
    ap.add_argument("--tiny", action="store_true",
                    help="tiny counts: CPU smoke of the tool itself")
    ap.add_argument("--json", default=None, metavar="PATH",
                    help="also write the reval-affinity-v1 table (the "
                         "fleet router's hash-ring seed) to PATH")
    args = ap.parse_args()

    per = 4 if args.tiny else args.per_task
    page = args.page_size
    by_task = {t: task_prompts(t, per, args.dataset, args.prompt_type)
               for t in TASKS}
    all_prompts = [p for t in TASKS for p in by_task[t]]

    from bench import TrainedBPE, find_hf_tokenizer

    hf = None if args.tiny else find_hf_tokenizer(args.tokenizer)
    tok = hf[0] if hf else TrainedBPE(all_prompts)

    enc = {t: [tok.encode(p) for p in by_task[t]] for t in TASKS}
    out: dict = {
        "metric": "prefix_overlap",
        "dataset": args.dataset,
        "prompt_type": args.prompt_type,
        "page_size": page,
        "tokenizer": hf[1] if hf else "trained-bpe(benchmark-corpus)",
        "per_task_prompts": per,
    }
    tasks_out = {}
    total_tokens = total_pages = total_cold = total_warm = 0
    for t in TASKS:
        toks = sum(len(e) for e in enc[t])
        lcp = lcp_tokens(enc[t])
        pages, cold, warm = radix_stats(enc[t], page)
        mean = toks / max(len(enc[t]), 1)
        tasks_out[t] = {
            "prompts": len(enc[t]),
            "total_tokens": toks,
            "mean_prompt_tokens": round(mean, 1),
            "template_tokens": lcp,
            "template_share": round(lcp / mean, 4) if mean else 0.0,
            "distinct_pages": pages,
            "cold_hit_rate": round(cold / toks, 4) if toks else 0.0,
            "warm_hit_rate": round(warm / toks, 4) if toks else 0.0,
        }
        total_tokens += toks
        total_pages += pages
        total_cold += cold
        total_warm += warm
    # the fused fleet batch: task-contiguous stream over ALL tasks — the
    # cross-task LCP is ~0, so fused numbers are per-task sums, which is
    # exactly why per-task grouping must feed the radix lookup
    fused_enc = [e for t in TASKS for e in enc[t]]
    out["fused_batch_lcp_tokens"] = lcp_tokens(fused_enc)
    out["tasks"] = tasks_out
    out["cache_working_set_pages"] = total_pages
    out["cold_hit_rate"] = round(total_cold / total_tokens, 4)
    out["warm_hit_rate"] = round(total_warm / total_tokens, 4)
    out["value"] = out["warm_hit_rate"]
    affinity = affinity_table(by_task)
    affinity.update(dataset=args.dataset, prompt_type=args.prompt_type)
    out["affinity"] = affinity
    if args.json:
        with open(args.json, "w") as f:
            json.dump(affinity, f, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
