"""On-chip fleet-fusion demonstration (VERDICT round-2 item 7).

The fleet runner's claim (fleet.py, replacing reference batch_run.py:20-32)
is that concatenating all four tasks' prompts into ONE ``infer_many``
keeps the chip saturated where per-task runs would each pay their own
ragged tail.  This measures exactly that on real hardware: the four DREval
tasks' genuine planned prompts (mock planning — same few-shot templates
and programs the scoring pipeline sends), generated fused vs per-task on
the same resident engine.

Prints ONE JSON line: {"metric": "fleet_fusion_speedup", ...}.

    python tools/fleet_bench.py --per-task 16
    python tools/fleet_bench.py --tiny          # CPU smoke
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def task_prompts(name: str, n: int, prompt_type: str) -> list[str]:
    from reval_tpu.tasks import TASKS

    items = 2
    while True:
        task = TASKS[name](model=None, prompt_type=prompt_type,
                           dataset="humaneval", mock=True, max_items=items,
                           progress=False)
        _, jobs = task._plan()
        if len(jobs) >= n or items > 64:
            return [j.prompt for j in jobs][:n]
        items *= 2


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--per-task", type=int, default=16,
                    help="prompts per task (4 tasks)")
    ap.add_argument("--max-new", type=int, default=256)
    ap.add_argument("--slots", type=int, default=32)
    ap.add_argument("--model", default="1.3b")
    ap.add_argument("--dtype", choices=["bfloat16", "int8", "int4"],
                    default="bfloat16")
    ap.add_argument("--tiny", action="store_true")
    args = ap.parse_args()

    from bench import acquire_chip_lock
    chip_lock = acquire_chip_lock(skip=args.tiny)  # held until exit

    import jax

    if args.tiny:
        jax.config.update("jax_platforms", "cpu")

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from bench import TrainedBPE, flagship

    from reval_tpu.inference.tpu.engine import EngineStats
    from reval_tpu.inference.tpu.paged_engine import PagedTPUEngine
    from reval_tpu.tasks import TASKS  # noqa: F401  (import check)

    names = ("coverage", "path", "state", "output")
    per = 3 if args.tiny else args.per_task
    by_task = {n: task_prompts(n, per, "direct") for n in names}
    all_prompts = [p for n in names for p in by_task[n]]
    params, cfg = flagship(tiny=args.tiny, model=args.model,
                           dtype=args.dtype)
    tok = TrainedBPE(all_prompts)
    max_new = 8 if args.tiny else args.max_new
    slots = 4 if args.tiny else args.slots

    eng = PagedTPUEngine(params, cfg, tok, max_slots=slots,
                         max_seq_len=1024 if args.tiny else 2048)
    stop = ["[/ANSWER]"]

    def timed(prompt_sets):
        # warmup covers every bucket/shape this exact workload hits
        for ps in prompt_sets:
            eng.generate(ps, max_new_tokens=max_new, temperature=0.0,
                         stop=stop)
        eng.stats = EngineStats()
        t0 = time.perf_counter()
        for ps in prompt_sets:
            eng.generate(ps, max_new_tokens=max_new, temperature=0.0,
                         stop=stop)
        return time.perf_counter() - t0

    fused_wall = timed([all_prompts])
    per_task_wall = timed([by_task[n] for n in names])
    eng.close()

    n = len(all_prompts)
    out = {
        "metric": "fleet_fusion_speedup",
        "value": round(per_task_wall / fused_wall, 3),
        "unit": "x",
        "vs_baseline": round(per_task_wall / fused_wall, 3),
        "fused_probes_per_s": round(n / fused_wall, 3),
        "per_task_probes_per_s": round(n / per_task_wall, 3),
        "prompts": n,
        "max_new": max_new,
        "device": jax.devices()[0].device_kind,
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
