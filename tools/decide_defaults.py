"""Decide the paged-attention defaults from recorded on-chip artifacts.

The runbook's decision-set steps (kernel_ab.txt, bench_quick /
bench_direct_seqk / bench_direct_wide) produce the data that picks the
default backend (grid vs seq) and dot formulation (swap vs wide); this
script turns them into a persisted decision so the choice is applied
even when no build session is active during the tunnel window:

- ``tpu_watch/autotune.json`` — consumed by the dispatcher
  (``reval_tpu.ops.pallas_attention.paged_decode_attention``) for any
  env var the caller left unset, so the driver's official ``bench.py``
  run and every engine user get the measured-best config;
- ``tpu_watch/decided_env.sh`` — sourced by ``tools/chip_runbook.sh``
  at the top of each pass, so the diagnosis-tier artifacts (ablate,
  bench_direct, bench_cot, fleet) measure the winning config.

Full-pipeline bench values outrank the kernel-only A/B when both exist:
the kernel microbench ignores interactions (e.g. a dot mode that wins
in isolation but changes XLA's fusion around the kernel).  Between the
two sits the kernel-CI leaderboard (``tools/kernelbench.py``): its
supervised per-cell matrix is richer than ``kernel_ab.txt`` (chunk
cadence + pool dtype axes, stale-awareness) but still kernel-level, so
its pick is used when no full-pipeline artifact exists.  Tiny, chaos,
or perturbed leaderboards are drill debris and never decide anything.
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WATCH = os.path.join(REPO, "tpu_watch")

# (artifact, backend env, dot env, bench args) — bench rows measure the
# full pipeline; bench_args carries config beyond the kernel env (the
# kv8s64 candidate: int8 pool + 64 slots) for bench.py's autotune pickup
BENCH_CONFIGS = [
    ("bench_quick.json", "pallas", "swap", {}),
    ("bench_direct_seqk.json", "pallas_seq", "swap", {}),
    ("bench_direct_wide.json", "pallas", "wide", {}),
    ("bench_direct_kv8s64.json", "pallas", "swap",
     {"kv_dtype": "int8", "slots": 64}),
    # emergency tier: the runbook only measures this when the pallas
    # quick bench failed (e.g. every Mosaic variant rejected by the
    # chip helper) — a working slow backend beats a failing fast one
    ("bench_direct_xlab.json", "xla", "swap", {}),
]
# kernel_ab row label → (backend, dot) — fallback tier
AB_ROWS = {
    "grid": ("pallas", "swap"),
    "seq": ("pallas_seq", "swap"),
    "grid-wide": ("pallas", "wide"),
    "seq-wide": ("pallas_seq", "wide"),
    "xla": ("xla", "swap"),
}


def _bench_value(path: str) -> float | None:
    try:
        with open(path) as f:
            obj = json.load(f)
        if obj.get("error") or not obj.get("value"):
            return None
        return float(obj["value"])
    except Exception:
        return None


def _kernelbench_pick(watch: str) -> dict | None:
    """The newest trustworthy kernel-CI leaderboard's serving-config
    pick (``reval_tpu/kernelbench.py`` writes it pre-validated).  Tiny
    runs (toy CPU shapes), chaos drills, and perturbed gate drills are
    excluded: a cell matrix measured under injected faults or seeded
    regressions must never become the serving default."""
    paths = glob.glob(os.path.join(watch, "kernelbench-*.json"))

    def _mtime(p):
        try:
            return os.path.getmtime(p)
        except OSError:
            return 0.0

    for path in sorted(paths, key=_mtime, reverse=True):
        try:
            with open(path) as f:
                obj = json.load(f)
        except Exception:
            continue
        if (not isinstance(obj, dict)
                or obj.get("schema") != "reval-kernelbench-v1"
                or obj.get("tiny") or obj.get("chaos") or obj.get("perturb")):
            continue
        pick = obj.get("pick")
        if (isinstance(pick, dict) and pick.get("REVAL_TPU_PAGED_BACKEND")
                and pick.get("REVAL_TPU_KERNEL_DOT")):
            return dict(pick)
    return None


def decide(watch: str = WATCH) -> dict | None:
    """(backend, dot, evidence) from the newest artifacts, or None when
    nothing usable has been recorded yet."""
    best = None   # (value, backend, dot, bench_args, source)
    for name, backend, dot, bench_args in BENCH_CONFIGS:
        v = _bench_value(os.path.join(watch, name))
        if v is not None and (best is None or v > best[0]):
            best = (v, backend, dot, bench_args, name)
    if best is None:
        # kernel-CI leaderboard tier: richer than kernel_ab.txt (chunk +
        # pool axes, supervised/stale-aware), still below full-pipeline
        pick = _kernelbench_pick(watch)
        if pick is not None:
            return pick
    if best is not None:
        value, backend, dot, bench_args, source = best
        return {"REVAL_TPU_PAGED_BACKEND": backend,
                "REVAL_TPU_KERNEL_DOT": dot,
                "bench_args": bench_args,
                # every decision-set artifact measures the 1.3b direct
                # config; bench.py only applies bench_args when this
                # scope matches its own run (cot/6.7b have tighter
                # memory-safe defaults a direct-mode win must not widen)
                "scope": {"mode": "direct", "model": "1.3b"},
                "evidence": {"tier": "full-pipeline", "source": source,
                             "probes_per_sec": value}}

    # fallback: kernel-only A/B rows ("label   12.345 ms/step ...")
    ab = os.path.join(watch, "kernel_ab.txt")
    try:
        with open(ab) as f:
            text = f.read()
    except OSError:
        return None
    rows = []
    for label, (backend, dot) in AB_ROWS.items():
        m = re.search(rf"^{re.escape(label)}\s+([0-9.]+) ms/step", text,
                      re.MULTILINE)
        if m:
            rows.append((float(m.group(1)), backend, dot, label))
    if not rows:
        return None
    ms, backend, dot, label = min(rows)
    return {"REVAL_TPU_PAGED_BACKEND": backend,
            "REVAL_TPU_KERNEL_DOT": dot,
            "evidence": {"tier": "kernel-ab", "source": f"kernel_ab.txt:{label}",
                         "ms_per_step": ms}}


def main(argv: list[str] | None = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--watch", default=WATCH,
                    help="artifact directory (default: tpu_watch/)")
    args = ap.parse_args(argv)

    decision = decide(args.watch)
    if decision is None:
        print("no usable artifacts yet; nothing decided")
        return 1
    decision["decided_at"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    os.makedirs(args.watch, exist_ok=True)
    out = os.path.join(args.watch, "autotune.json")
    with open(out + ".tmp", "w") as f:
        json.dump(decision, f, indent=1)
    os.replace(out + ".tmp", out)
    env = os.path.join(args.watch, "decided_env.sh")
    with open(env + ".tmp", "w") as f:
        f.write("# written by tools/decide_defaults.py — measured-best "
                "paged-attention config\n")
        for k in ("REVAL_TPU_PAGED_BACKEND", "REVAL_TPU_KERNEL_DOT"):
            f.write(f"export {k}={decision[k]}\n")
        # extra knobs the evidence pinned (the kernelbench pick carries
        # the measured-best decode-chunk cadence here)
        for k, v in sorted((decision.get("env") or {}).items()):
            f.write(f"export {k}={v}\n")
    os.replace(env + ".tmp", env)
    print(json.dumps(decision))
    return 0


if __name__ == "__main__":
    sys.exit(main())
