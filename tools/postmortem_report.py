#!/usr/bin/env python3
"""Render a postmortem bundle as a human-readable timeline.

Input: a ``postmortem-<ts>.json`` written by the serving stack (watchdog
trip, driver fault, deadline storm, SIGUSR1, SIGTERM drain — see
``reval_tpu/obs/flightrec.py``), or a saved ``GET /debugz`` body (same
schema).  Output, per replica:

- the envelope: reason, timestamps, env/config fingerprint;
- the in-flight request table with lifecycle stamps (who was where when
  it died: submitted / admitted / first token / done ages);
- the recent structured-log tail (errors and warnings first-class);
- the flight-record runway as a step timeline — the last N drive ticks
  with slots, queue, page pool, chunk size, step wall and heartbeat age,
  plus a summary of the stall (the slowest recorded steps).

Usage:
    python tools/postmortem_report.py BUNDLE.json [--records N] [--all]
"""

from __future__ import annotations

import argparse
import json
import sys

#: flight-record columns rendered in the timeline, (header, key, width)
_COLS = (("step", "step", 8), ("running", "running", 7),
         ("queued", "queued", 6), ("free_pg", "free_pages", 7),
         ("cached", "cached_pages", 6), ("pinned", "pinned_pages", 6),
         ("spec", "spec_accepted", 5),
         ("chunk", "chunk_steps", 5), ("step_ms", "step_ms", 9),
         ("hb_ms", "hb_age_ms", 8))


def _fmt(v, width: int) -> str:
    if isinstance(v, float):
        return f"{v:>{width}.2f}"
    return f"{str(v) if v is not None else '—':>{width}}"


def render_flight(records: list[dict], last: int, out: list[str]) -> None:
    if not records:
        out.append("  (no flight records — recorder disabled or no ticks)")
        return
    total = len(records)
    shown = records[-last:] if last else records
    out.append(f"  {total} records retained, showing the last {len(shown)} "
               f"(steps {shown[0].get('step')}..{shown[-1].get('step')})")
    out.append("  " + " ".join(f"{h:>{w}}" for h, _, w in _COLS) + "  seq_ids")
    for rec in shown:
        row = " ".join(_fmt(rec.get(k), w) for _, k, w in _COLS)
        ids = rec.get("seq_ids") or []
        out.append("  " + row + "  " + ",".join(str(i) for i in ids[:8]))
    slow = sorted(records, key=lambda r: r.get("step_ms") or 0)[-3:]
    out.append("  slowest steps: " + "; ".join(
        f"step {r.get('step')} = {r.get('step_ms', 0):.1f}ms"
        for r in reversed(slow)))


def render_requests(requests: list[dict] | None, out: list[str]) -> None:
    if not requests:
        out.append("  (no in-flight engine requests recorded)")
        return
    out.append(f"  {'seq':>5} {'request_id':<18} {'prompt':>7} {'gen':>5} "
               f"{'done':>5} {'age_s':>8} {'admit':>6} {'first':>6}")
    for r in requests:
        out.append(
            f"  {r.get('seq_id', '—'):>5} "
            f"{str(r.get('request_id') or 'n/a'):<18.18} "
            f"{r.get('prompt_tokens', 0):>7} {r.get('generated_tokens', 0):>5} "
            f"{str(bool(r.get('done'))):>5} {r.get('age_s', 0):>8} "
            f"{'yes' if r.get('t_admit') is not None else 'no':>6} "
            f"{'yes' if r.get('t_first') is not None else 'no':>6}")


def render_logs(logs: list[dict] | None, out: list[str]) -> None:
    if not logs:
        out.append("  (no recent log events)")
        return
    for e in logs[-20:]:
        line = (f"  {e.get('ts', '')} [{e.get('level', '?'):>7}] "
                f"{e.get('event', '?')}")
        if e.get("request_id"):
            line += f" rid={e['request_id']}"
        if e.get("error"):
            line += f" error={e['error']}"
        if e.get("fields"):
            line += " " + json.dumps(e["fields"], default=str)
        out.append(line[:160])


def render_replica(bundle: dict, last: int, out: list[str],
                   label: str = "") -> None:
    if label:
        out.append(f"-- replica {label} " + "-" * max(0, 50 - len(label)))
    readiness = bundle.get("readiness")
    if readiness is not None:
        flags = {k: v for k, v in readiness.items() if k != "replicas"}
        out.append(f"readiness: {json.dumps(flags, default=str)}")
    inflight = bundle.get("inflight")
    if inflight is not None:
        out.append(f"in-flight submissions: {len(inflight)}")
        for sub in inflight[:16]:
            out.append(f"  rid={sub.get('request_id') or 'n/a'} "
                       f"prompts={sub.get('prompts')} "
                       f"tokens={sub.get('tokens')} "
                       f"age={sub.get('age_s')}s "
                       f"deadline_in={sub.get('deadline_in_s')}s "
                       f"resolved={sub.get('resolved')}")
    out.append("engine requests:")
    render_requests(bundle.get("requests"), out)
    spans = bundle.get("spans")
    if spans:
        out.append(f"span tail: {spans.get('total', 0)} events recorded, "
                   f"{spans.get('dropped', 0)} dropped")
    out.append("flight records:")
    render_flight(bundle.get("flight") or [], last, out)


def render(bundle: dict, last: int = 40) -> str:
    out: list[str] = []
    out.append(f"== postmortem: {bundle.get('reason', '?')} "
               f"@ {bundle.get('iso', '?')} ==")
    if bundle.get("error"):
        out.append(f"error: {bundle['error']}")
    if bundle.get("model"):
        out.append(f"model: {bundle['model']}"
                   + ("  (draining)" if bundle.get("draining") else ""))
    fp = bundle.get("fingerprint") or {}
    out.append(f"process: pid={fp.get('pid')} python={fp.get('python')} "
               f"jax={fp.get('jax')} platform={fp.get('platform')}")
    if fp.get("env"):
        out.append(f"env: {json.dumps(fp['env'], default=str)}")
    out.append("")
    replicas = bundle.get("replicas")
    if replicas:
        for i, rep in enumerate(replicas):
            render_replica(rep, last, out, label=str(i))
            out.append("")
    else:
        render_replica(bundle, last, out)
        out.append("")
    out.append("recent structured-log events:")
    render_logs(bundle.get("recent_logs"), out)
    return "\n".join(out)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("bundle", help="postmortem-*.json (or a saved /debugz "
                                   "response body)")
    ap.add_argument("--records", type=int, default=40,
                    help="flight-record timeline rows (default 40)")
    ap.add_argument("--all", action="store_true",
                    help="render every retained flight record")
    args = ap.parse_args(argv)
    with open(args.bundle) as f:
        bundle = json.load(f)
    if not isinstance(bundle, dict) or "reason" not in bundle:
        print(f"{args.bundle}: not a postmortem bundle (no 'reason' key)",
              file=sys.stderr)
        return 1
    print(render(bundle, last=0 if args.all else args.records))
    return 0


if __name__ == "__main__":
    sys.exit(main())
