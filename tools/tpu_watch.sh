#!/bin/bash
# Background TPU watcher (round 3, VERDICT item 1).
#
# The axon TPU tunnel on this host wedges for hours at a time
# (jax.devices() blocks forever — see PERF.md measurement log).  This
# loop probes cheaply via a killable subprocess; while the chip answers
# it drives tools/chip_runbook.sh, which captures the full round-3
# measurement suite one idempotent step at a time — so even a short
# tunnel window makes progress, and a long one completes everything.
#
# Artifacts land under tpu_watch/ (see chip_runbook.sh header).
#
# Python sibling: `python -m reval_tpu watch` babysits a SERVING endpoint
# (polls /statusz + /debugz into a refreshing one-screen console —
# throughput, queue depth, page pool, latency percentiles, last faults).
# This script babysits the raw chip; use both on a serving host.
cd /root/repo || exit 1
mkdir -p tpu_watch

probe() {
  timeout 45 python -c "
import jax
ds = jax.devices()
assert ds[0].platform == 'tpu', ds[0].platform
print(ds[0].device_kind)
" >> tpu_watch/probe_detail.log 2>&1
}

while true; do
  ts=$(date +%Y-%m-%dT%H:%M:%S)
  if probe; then
    echo "$ts ALIVE" >> tpu_watch/probe.log
    touch tpu_watch/ALIVE
    bash tools/chip_runbook.sh
    sleep 60
  else
    echo "$ts wedged" >> tpu_watch/probe.log
    rm -f tpu_watch/ALIVE
    # a wedged probe already blocks 45 s; a long sleep on top can eat
    # 4+ minutes of a ~19-minute tunnel window before the runbook starts
    sleep 90
  fi
done
