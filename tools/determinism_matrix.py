#!/usr/bin/env python3
"""Run the cross-backend determinism matrix and publish the parity table.

The CLI front of ``reval_tpu/obs/determinism.py``: runs a fixed, seeded
probe set through every loadable backend×kernel×parallelism×dtype×batch
cell, diffs each against the declared reference cell, and writes

- ``tpu_watch/determinism-<ts>.json`` — the machine-readable matrix
  (schema ``reval-determinism-v1``; linted by the ``detmatrix``
  reval-lint pass so cells can never silently vanish from the report);
- ``tpu_watch/determinism_table.md`` — the rendered parity table
  PARITY.md points at (supersedes its hand-written backend rows).

Exit codes: 0 = all ``bit_identical`` cells agree with the reference;
1 = PARITY GATE FAILURE (a bit-identical cell diverged — the message
names the cell and the first divergent token); 2 = the matrix could not
run (reference unloadable, bad arguments).

Usage:
    python tools/determinism_matrix.py --tiny            # CPU dev host
    python tools/determinism_matrix.py                   # on-chip audit
    python tools/determinism_matrix.py --cells paged-xla-fp32-b2,static-fp32-b2
    python tools/determinism_matrix.py --tiny --json     # matrix to stdout

``--tiny`` pins jax to CPU and exposes 2 virtual host devices (so the
dp=2 cell is loadable) BEFORE jax initialises — the same probe model is
toy-sized either way, so --tiny changes the platform, not the cells.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--tiny", action="store_true",
                    help="CPU smoke: force the cpu platform + 2 virtual "
                         "devices (dp cell stays loadable)")
    ap.add_argument("--cells", default=None,
                    help="comma-separated cell names to execute "
                         "(unselected cells are reported as skipped, "
                         "never dropped); default: all")
    ap.add_argument("--reference", default=None,
                    help="reference cell override "
                         "(env REVAL_TPU_DETERMINISM_REF)")
    ap.add_argument("--max-new", type=int, default=None,
                    help="greedy tokens per probe (default 12)")
    ap.add_argument("--out", default=None,
                    help="artifact directory (default env "
                         "REVAL_TPU_DETERMINISM_DIR, else tpu_watch/)")
    ap.add_argument("--table", default=None,
                    help="rendered markdown table path (default "
                         "<out>/determinism_table.md; 'none' disables)")
    ap.add_argument("--json", action="store_true",
                    help="print the full matrix JSON to stdout")
    args = ap.parse_args(argv)

    if args.tiny:
        # must land before jax initialises a backend
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=2").strip()

    from reval_tpu.obs.determinism import (default_cells, render_table,
                                           run_matrix, validate_matrix,
                                           write_matrix)

    select = ([c.strip() for c in args.cells.split(",") if c.strip()]
              if args.cells else None)
    try:
        matrix = run_matrix(select=select, reference=args.reference,
                            max_new_tokens=args.max_new)
    except (ValueError, RuntimeError) as e:
        print(f"determinism_matrix: {e}", file=sys.stderr)
        return 2

    problems = validate_matrix(matrix, default_cells())
    if problems:    # a malformed artifact must never be written quietly
        for p in problems:
            print(f"determinism_matrix: self-check: {p}", file=sys.stderr)
        return 2

    path = write_matrix(matrix, args.out)
    table = render_table(matrix)
    table_path = args.table
    if table_path != "none":
        table_path = table_path or os.path.join(
            os.path.dirname(path), "determinism_table.md")
        with open(table_path + ".tmp", "w") as f:
            f.write(table)
        os.replace(table_path + ".tmp", table_path)

    if args.json:
        print(json.dumps(matrix, indent=1))
    else:
        print(table, end="")
        print(f"\nmatrix: {path}"
              + (f"\ntable:  {table_path}" if table_path != "none" else ""))

    failures = matrix["summary"]["gate_failures"]
    if failures:
        print("\nPARITY GATE FAILURE:", file=sys.stderr)
        for msg in failures:
            print(f"  {msg}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
