#!/bin/bash
# Round-5 on-chip measurement suite.  Idempotent: each step skips itself
# once its artifact exists, so repeated invocations (the tpu_watch loop
# calls this every time the tunnel is up) resume where the last window
# ended.
#
# Round-5 state: the r3-kernel baselines live in tpu_watch/r3k_*; the
# round-4 batched-head kernels needed an on-chip Mosaic fix (batch dims
# must both be dim 0 — PERF.md round-5 session 1) and grew a second
# A/B-able dot formulation (wide).  kernel_ab runs FIRST because it
# decides the default backend/dot; tools/decide_defaults.py then
# persists the winner (autotune.json + decided_env.sh) so the diagnosis
# tier, the dispatcher, and the driver's official bench all run the
# measured-best config even when no session is active.
cd /root/repo || exit 1
mkdir -p tpu_watch
R=tpu_watch
# spec path removed round 5 (measure-or-cut): stale A/B artifacts from
# older passes must not read as current-round output
rm -f "$R"/bench_direct_spec.json "$R"/bench_cot_spec.json
# apply the measured-best config decided on an earlier pass (see
# tools/decide_defaults.py); decision-set steps that pin their own env
# override per-step
[ -f "$R/decided_env.sh" ] && . "$R/decided_env.sh"
export JAX_COMPILATION_CACHE_DIR="${JAX_COMPILATION_CACHE_DIR:-/root/.cache/jax_comp}"
mkdir -p "$JAX_COMPILATION_CACHE_DIR"
# Persistent AOT executable cache for every chip step (item-4 AOT
# remainder): the first pass in a window pays the compiles and stores
# serialized executables; every later bench boots warm, so the bench
# "restart" block records the real cold->warm compile collapse instead
# of {"enabled": false} forever.  bench.py also defaults this on chip
# runs — the export makes the tools/ steps (ablate, fleet) match.
export REVAL_TPU_AOT_CACHE_DIR="${REVAL_TPU_AOT_CACHE_DIR:-$R/aot_cache}"
mkdir -p "$REVAL_TPU_AOT_CACHE_DIR"

log() { echo "$(date +%Y-%m-%dT%H:%M:%S) $*" >> $R/runbook.log; }

probe_alive() {
  timeout 45 python -c "
import jax
assert jax.devices()[0].platform == 'tpu'
" > /dev/null 2>&1
}

# run <artifact> <timeout_s> <json|txt> <cmd...>
run() {
  local name=$1 to=$2 kind=$3; shift 3
  [ -s "$R/$name" ] && { log "skip $name (done)"; return 0; }
  # the tunnel wedges mid-pass: without this gate every remaining step
  # burns its full timeout against a dead chip before dying
  if ! probe_alive; then
    log "abort pass before $name (tunnel wedged)"
    exit 2
  fi
  log "start $name: $*"
  local t_start=$(date +%s)
  timeout "$to" "$@" > "$R/$name.tmp" 2> "$R/$name.err"
  local rc=$?
  log "end $name rc=$rc"
  # a bench killed mid-pass still measured something: bench.py streams
  # per-chunk stats to bench_inflight.json — keep a copy per step so the
  # evidence survives the next step overwriting it
  if [ $rc -ne 0 ] && [ -f "$R/bench_inflight.json" ] \
     && [ "$(stat -c %Y "$R/bench_inflight.json")" -ge "$t_start" ]; then
    cp "$R/bench_inflight.json" "$R/$name.partial.json"
    log "saved $name.partial.json (mid-pass stats)"
  fi
  if [ $rc -eq 0 ]; then
    if [ "$kind" = json ]; then
      grep -q '"value"' "$R/$name.tmp" && ! grep -q '"error"' "$R/$name.tmp" \
        && mv "$R/$name.tmp" "$R/$name" && return 0
      log "reject $name (no clean value JSON)"
      return 1
    fi
    mv "$R/$name.tmp" "$R/$name"
    return 0
  fi
  return $rc
}

# -- decision set first: a ~19-minute tunnel window must capture enough
#    to pick the default (kernel backend, kv dtype, slot width) ---------
# Decision-set steps pin EVERY config axis explicitly (backend, dot,
# --no-autotune): a sourced decided_env.sh or persisted autotune.json
# must never leak into the A/B rows, or decide_defaults would label
# measurements with configs they did not run (self-reinforcing loop).
# 1. kernel-only A/B (5 variants incl. the wide dot mode; int8 rows are
#    diagnosis and run later), ~4-6 min
run kernel_ab.txt        1500 txt  python tools/kernel_bench.py --slots 32 --ctx 600 --no-int8
# 2. full pipeline on the baseline default config
run bench_quick.json     1200 json env REVAL_TPU_PAGED_BACKEND=pallas REVAL_TPU_KERNEL_DOT=swap python bench.py --no-autotune --skip-serial --skip-ab --prompts 32
# 3. the candidate default configs
run bench_direct_seqk.json 2400 json env REVAL_TPU_PAGED_BACKEND=pallas_seq REVAL_TPU_KERNEL_DOT=swap python bench.py --no-autotune --skip-serial --skip-ab
run bench_direct_wide.json 2400 json env REVAL_TPU_PAGED_BACKEND=pallas REVAL_TPU_KERNEL_DOT=wide python bench.py --no-autotune --skip-serial --skip-ab
# 3b. emergency tier: only when the pallas quick bench has no artifact
#     (e.g. the chip helper rejects every Mosaic variant) — a working
#     XLA-backend number beats a round of failure JSONs
if [ ! -s "$R/bench_quick.json" ]; then
  run bench_direct_xlab.json 2400 json env REVAL_TPU_PAGED_BACKEND=xla REVAL_TPU_KERNEL_DOT=swap python bench.py --no-autotune --skip-serial --skip-ab
fi
# 4. persist the winning (backend, dot-mode) so the diagnosis tier below,
#    the dispatcher's autotune fallback, and the driver's official bench
#    all run the measured-best config (idempotent: re-decides each pass
#    from whatever artifacts exist)
python tools/decide_defaults.py >> $R/runbook.log 2>&1 && . "$R/decided_env.sh"
# A decision CHANGE invalidates the diagnosis tier: those artifacts
# inherit the decided config, and the idempotent skip would otherwise
# freeze headline numbers measured under a superseded (e.g. emergency
# xla) decision forever.  Decision-set artifacts pin their own env and
# stay.  The fingerprint covers bench_args too (kv dtype, slot width):
# a kv8s64 win keeps backend/dot but changes what bench.py's autotune
# pickup runs, which must also invalidate the official rows.
FP="${REVAL_TPU_PAGED_BACKEND:-pallas}/${REVAL_TPU_KERNEL_DOT:-swap}/$(
  python -c "
import json, sys
try:
    a = json.load(open('$R/autotune.json')).get('bench_args', {})
except Exception:
    a = {}
print(json.dumps(a, sort_keys=True))" 2>/dev/null || echo '{}')"
if [ -f "$R/diagnosis_config.txt" ] && [ "$(cat "$R/diagnosis_config.txt")" != "$FP" ]; then
  log "decision changed ($(cat "$R/diagnosis_config.txt") -> $FP): invalidating diagnosis artifacts"
  rm -f "$R"/ablate.txt "$R"/ablate2.txt "$R"/bench_direct.json \
        "$R"/bench_cot.json "$R"/bench_direct_int8.json \
        "$R"/bench_cot_kv8.json "$R"/fleet.json \
        "$R"/bench_direct_int4.json \
        "$R"/bench_direct_nopipe.json
fi
echo "$FP" > "$R/diagnosis_config.txt"
# -- diagnosis + official numbers --------------------------------------
# official numbers FIRST (round-5 verdict wants a fresh direct headline
# and a cot row; a 40-min ablation must not eat a short window first)
run bench_direct.json    2400 json python bench.py
run bench_cot.json       3600 json python bench.py --mode cot
# Self-healing kernel CI (ROADMAP item 4): the supervised per-cell
# leaderboard — a wedged cell degrades to a stale-marked entry instead
# of killing the round, the winner persists a decide_defaults-compatible
# pick (picked up by step-4's re-decide next pass), and the regression
# gate exits 1 (step stays uncommitted, retried next window) when HEAD
# regresses the incumbent winner.  The timestamped reval-kernelbench-v1
# artifact lands in tpu_watch/ regardless.
run kernelbench.json     2400 json python tools/kernelbench.py
# int8 pool halves KV reads AND lets 64 slots fit -> weight reads amortise
# over 2x the batch.  Retried here (not in the decision set): its first
# attempt stalled 8 min in as the tunnel died (09:17 pass), and an
# unproven candidate must not eat a fresh window before the official
# rows.  If it lands a winner, the next pass's decide re-flips the
# default and invalidates the diagnosis artifacts (designed mechanism).
run bench_direct_kv8s64.json 1800 json env REVAL_TPU_PAGED_BACKEND=pallas REVAL_TPU_KERNEL_DOT=swap python bench.py --no-autotune --kv-dtype int8 --slots 64 --skip-serial --skip-ab
# chunk-pipeline A/B: bench_direct.json above runs with the pipeline ON
# (default); this row is the same decided config with it OFF — the delta
# is the measured per-chunk host cost the pipeline hides
run bench_direct_nopipe.json 2400 json env REVAL_TPU_PIPELINE=0 python bench.py --skip-serial --skip-ab
run ablate.txt           2400 txt  python tools/decode_ablate.py --slots 32 --ctx 600 --variants core,seq,slots
run kernel_ab_int8.txt   1200 txt  python tools/kernel_bench.py --slots 32 --ctx 600 --only-int8
# 5. dtype / feature A-Bs on the new kernel
run bench_direct_int8.json 2400 json python bench.py --dtype int8 --skip-serial --skip-ab
run bench_cot_kv8.json   3600 json python bench.py --mode cot --kv-dtype int8 --skip-serial --skip-ab
run fleet.json           2400 json python tools/fleet_bench.py
run bench_direct_int4.json 2400 json python bench.py --dtype int4 --skip-serial --skip-ab
run ablate2.txt          1800 txt  python tools/decode_ablate.py --slots 32 --ctx 600 --variants chunk,page
run ablate_int8.txt      1800 txt  python tools/decode_ablate.py --slots 32 --ctx 600 --dtype int8 --variants core,seq
log "runbook pass complete"
