#!/usr/bin/env python
"""Operator CLI over the persistent AOT executable cache.

Out-of-band inspection/pruning of the directory the engines populate
(``reval_tpu/inference/tpu/aot_cache.py`` — fingerprint-keyed serialized
executables, one ``.json`` meta + one ``.bin`` payload per compile
variant):

    python tools/aot_cache.py ls     [--dir D] [--json]
    python tools/aot_cache.py verify [--dir D] [--deep] [--json]
    python tools/aot_cache.py gc     [--dir D] [--max-mb N] [--json]

- ``ls``     — every committed entry: program name, payload bytes, the
  compile seconds a hit saves, fingerprint prefix, age.
- ``verify`` — integrity verdicts per entry (meta parses, payload
  present, sha256 matches; ``--deep`` also round-trips the payload
  through ``jax.export.deserialize``).  Exit 1 when anything is broken —
  broken entries are safe (the loader degrades to a fresh compile), but
  an operator pruning disk wants to know.
- ``gc``     — evict least-recently-used entries until the directory
  fits ``--max-mb`` (default ``REVAL_TPU_AOT_CACHE_MAX_MB``).

Reads tolerate a concurrently writing engine: the commit protocol is
payload-first + atomic meta rename, so a half-written entry shows up as
"payload missing"/unreadable at worst, never as a torn load.

``--json`` emits one machine-readable document (round-tripped in
tests/test_warm_restart.py).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from reval_tpu.env import env_str  # noqa: E402
from reval_tpu.inference.tpu.aot_cache import AOTCache  # noqa: E402


def _open_cache(args) -> AOTCache | None:
    cache_dir = args.dir or env_str("REVAL_TPU_AOT_CACHE_DIR", "") or ""
    if not cache_dir:
        print("error: no cache directory (--dir or REVAL_TPU_AOT_CACHE_DIR)",
              file=sys.stderr)
        return None
    if not os.path.isdir(cache_dir):
        print(f"error: {cache_dir} is not a directory", file=sys.stderr)
        return None
    return AOTCache(cache_dir, max_mb=args.max_mb)


def _row(entry: dict, now: float) -> dict:
    return {"file": entry.get("file"),
            "entry": entry.get("entry"),
            "payload_bytes": entry.get("payload_bytes"),
            "compile_s": entry.get("compile_s"),
            "fingerprint": str(entry.get("fingerprint") or "")[:16],
            "age_s": round(max(0.0, now - float(entry.get("mtime") or 0)), 1),
            **({"error": entry["error"]} if entry.get("error") else {})}


def cmd_ls(cache: AOTCache, args) -> int:
    now = time.time()
    rows = [_row(e, now) for e in cache.entries()]
    _, total = cache._usage()
    doc = {"command": "ls", "dir": cache.dir, "entries": rows,
           "total_bytes": total}
    if args.json:
        print(json.dumps(doc))
        return 0
    print(f"AOT cache {cache.dir}: {len(rows)} entries, "
          f"{total / (1 << 20):.1f} MB")
    for r in rows:
        mark = f"  [{r['error']}]" if r.get("error") else ""
        print(f"  {str(r['entry']):<28} {str(r['payload_bytes']):>10}B "
              f"compile {r['compile_s']}s  age {r['age_s']}s  "
              f"fp {r['fingerprint']}…{mark}")
    return 0


def cmd_verify(cache: AOTCache, args) -> int:
    now = time.time()
    rows = []
    bad = 0
    for entry in cache.entries():
        verdict = cache.verify_entry(entry, deep=args.deep)
        row = _row(entry, now)
        row["ok"] = verdict is None
        if verdict is not None:
            bad += 1
            row["problem"] = verdict
        rows.append(row)
    doc = {"command": "verify", "dir": cache.dir, "deep": bool(args.deep),
           "entries": rows, "checked": len(rows), "broken": bad}
    if args.json:
        print(json.dumps(doc))
    else:
        print(f"AOT cache {cache.dir}: {len(rows)} checked, {bad} broken")
        for r in rows:
            status = "ok" if r["ok"] else f"BROKEN: {r['problem']}"
            print(f"  {str(r['entry']):<28} {status}")
    return 1 if bad else 0


def cmd_gc(cache: AOTCache, args) -> int:
    evicted = cache.gc(args.max_mb)
    n, total = cache._usage()
    doc = {"command": "gc", "dir": cache.dir, "evicted": evicted,
           "entries_left": n, "total_bytes": total,
           "bound_mb": args.max_mb if args.max_mb is not None
           else cache.max_mb}
    if args.json:
        print(json.dumps(doc))
    else:
        print(f"AOT cache {cache.dir}: evicted {evicted}, "
              f"{n} entries / {total / (1 << 20):.1f} MB left "
              f"(bound {doc['bound_mb']} MB)")
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="tools/aot_cache.py",
        description="Inspect / verify / prune the persistent AOT "
                    "executable cache")
    parser.add_argument("command", choices=("ls", "verify", "gc"))
    parser.add_argument("--dir", default=None,
                        help="cache directory (default "
                             "REVAL_TPU_AOT_CACHE_DIR)")
    parser.add_argument("--max-mb", type=int, default=None,
                        help="gc size bound in MB (default "
                             "REVAL_TPU_AOT_CACHE_MAX_MB)")
    parser.add_argument("--deep", action="store_true",
                        help="verify: also round-trip payloads through "
                             "jax.export.deserialize")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable output")
    args = parser.parse_args(argv)
    cache = _open_cache(args)
    if cache is None:
        return 2
    return {"ls": cmd_ls, "verify": cmd_verify, "gc": cmd_gc}[args.command](
        cache, args)


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:     # `ls | head` closing stdout is not an error
        os._exit(0)
