#!/usr/bin/env python3
"""Render a metrics snapshot (or diff two) as a console report.

Input: JSON files carrying a registry snapshot — either a raw
``MetricsRegistry.snapshot()`` dict, the fleet's
``<results_dir>/fleet_metrics.json`` (snapshot under ``"metrics"``), or
a ``/statusz`` response body (same nesting).  With two files the report
is the DELTA: counters subtract, histogram bucket counts subtract, and
the percentiles are recomputed from the bucket deltas — i.e. the
distribution of exactly the requests that happened between the two
scrapes, which is how you price a scheduler change without restarting
the server.

With ``--determinism`` the inputs are BENCH round artifacts (or
determinism matrix files) in chronological order: the report lists each
round's reference-cell greedy fingerprint + diverged-cell count and
names the FIRST round whose fingerprint changed — the cross-commit
silent-drift detector (obs/determinism.py writes the block, bench.py
embeds it every round).

Usage:
    python tools/obs_report.py SNAP.json [SNAP2.json]
    python tools/obs_report.py --determinism BENCH_r*.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from reval_tpu.obs.metrics import snapshot_percentile  # noqa: E402


def load_snapshot(path: str) -> dict:
    with open(path) as f:
        obj = json.load(f)
    for key in ("metrics",):    # fleet_metrics.json / statusz nesting
        if key in obj and isinstance(obj[key], dict):
            obj = obj[key]
    if not any(k in obj for k in ("counters", "gauges", "histograms")):
        raise ValueError(f"{path}: not a metrics snapshot (no counters/"
                         f"gauges/histograms key)")
    return {"counters": obj.get("counters", {}),
            "gauges": obj.get("gauges", {}),
            "histograms": obj.get("histograms", {})}


def diff_snapshots(a: dict, b: dict) -> dict:
    """``b - a`` (a taken first).  Gauges keep b's value (a gauge is a
    level, not a flow — diffing it would report nonsense)."""
    counters = {k: round(b["counters"].get(k, 0) - a["counters"].get(k, 0), 6)
                for k in sorted(set(a["counters"]) | set(b["counters"]))}
    hists = {}
    for name in sorted(set(a["histograms"]) | set(b["histograms"])):
        ha = a["histograms"].get(name)
        hb = b["histograms"].get(name)
        if hb is None:
            # present in a, gone in b: the process restarted between the
            # scrapes — rendering a's old totals as a positive "delta"
            # would be a lie, so the series is dropped (the counters
            # section still shows the restart as negative deltas)
            continue
        if ha is None:
            hists[name] = hb      # appeared between scrapes: a is zero
            continue
        if [x[0] for x in ha["buckets"]] != [x[0] for x in hb["buckets"]]:
            raise ValueError(f"{name}: bucket bounds differ between files")
        hists[name] = {
            "buckets": [[bb, cb - ca] for (bb, cb), (_, ca)
                        in zip(hb["buckets"], ha["buckets"])],
            "inf": hb.get("inf", 0) - ha.get("inf", 0),
            "sum": hb["sum"] - ha["sum"],
            "count": hb["count"] - ha["count"]}
    return {"counters": counters, "gauges": dict(b["gauges"]),
            "histograms": hists}


def percentile(hist: dict, q: float) -> float:
    """THE estimator (obs.metrics.snapshot_percentile, itself over
    percentile_from_buckets — shared with Histogram.percentile and the
    `reval_tpu watch` console, so a diff report, a live scrape, and the
    watch screen can never disagree)."""
    return snapshot_percentile(hist, q)


def _fmt_secs(v: float) -> str:
    if v >= 1.0:
        return f"{v:8.3f}s "
    return f"{v * 1e3:8.3f}ms"


def render(snap: dict, title: str) -> str:
    lines = [f"== obs report: {title} ==", ""]
    hists = {k: v for k, v in snap["histograms"].items() if v and v["count"]}
    if hists:
        lines.append(f"{'histogram':<40} {'count':>8} {'mean':>10} "
                     f"{'p50':>10} {'p95':>10} {'p99':>10}")
        for name, h in sorted(hists.items()):
            mean = h["sum"] / h["count"]
            lines.append(
                f"{name:<40} {h['count']:>8} {_fmt_secs(mean):>10} "
                f"{_fmt_secs(percentile(h, .50)):>10} "
                f"{_fmt_secs(percentile(h, .95)):>10} "
                f"{_fmt_secs(percentile(h, .99)):>10}")
        lines.append("")
    counters = {k: v for k, v in snap["counters"].items() if v}
    if counters:
        lines.append(f"{'counter':<48} {'value':>14}")
        for name, v in sorted(counters.items()):
            out = f"{v:.3f}" if isinstance(v, float) and v != int(v) else int(v)
            lines.append(f"{name:<48} {out:>14}")
        lines.append("")
    if snap["gauges"]:
        lines.append(f"{'gauge':<48} {'value':>14}")
        for name, v in sorted(snap["gauges"].items()):
            lines.append(f"{name:<48} {v:>14}")
        lines.append("")
    if len(lines) == 2:
        lines.append("(empty snapshot: no non-zero metrics)")
    return "\n".join(lines)


def determinism_block(path: str) -> dict | None:
    """The determinism block of one artifact: a BENCH round's embedded
    ``determinism`` dict, or a matrix file's own reference-cell row
    (both carry the same keys this report reads)."""
    from reval_tpu.obs.determinism import SCHEMA

    with open(path) as f:
        obj = json.load(f)
    if not isinstance(obj, dict):   # a stray array/string artifact must
        # degrade to one unreadable row, not kill the whole report
        raise ValueError("not a JSON object")
    det = obj.get("determinism")
    if isinstance(det, dict):
        return det
    if obj.get("schema") == SCHEMA:     # a raw matrix artifact
        ref = obj["reference"]
        return {"reference": ref,
                "fingerprint": obj["cells"][ref].get("fingerprint"),
                "cells_run": obj["summary"]["cells_run"],
                "cells_diverged": obj["summary"]["cells_diverged"],
                "gate_failures": obj["summary"].get("gate_failures", [])}
    return None


def render_determinism(paths: list[str]) -> str:
    """The cross-round drift report: one row per artifact, the first
    fingerprint CHANGE named loudly (that is the commit range where the
    numerics moved)."""
    lines = ["== determinism drift across rounds ==", "",
             f"{'round':<28} {'reference cell':<24} {'fingerprint':<18} "
             f"{'cells':>5} {'diverged':>8}"]
    prev: tuple[str, str] | None = None     # (path, fingerprint)
    first_change: str | None = None
    for path in paths:
        name = os.path.basename(path)
        try:
            det = determinism_block(path)
        except (OSError, ValueError, KeyError) as e:
            lines.append(f"{name:<28} (unreadable: {type(e).__name__})")
            continue
        if det is None or not det.get("fingerprint"):
            lines.append(f"{name:<28} (no determinism block)")
            continue
        fp = det["fingerprint"]
        changed = prev is not None and fp != prev[1]
        mark = "  <-- fingerprint CHANGED" if changed else ""
        if det.get("perturb"):      # a chaos-hook run is not evidence
            mark += f"  [PERTURBED: {det['perturb']}]"
        if changed and first_change is None:
            first_change = (f"first drift: {name} (was {prev[1]} in "
                            f"{os.path.basename(prev[0])}, now {fp})")
        lines.append(f"{name:<28} {det.get('reference', '?'):<24} "
                     f"{fp:<18} {det.get('cells_run', '?'):>5} "
                     f"{det.get('cells_diverged', '?'):>8}{mark}")
        for msg in det.get("gate_failures") or ():
            lines.append(f"{'':<28}   gate: {msg}")
        prev = (path, fp)
    lines.append("")
    lines.append(first_change if first_change
                 else "no fingerprint drift across these rounds")
    return "\n".join(lines)


def receipts_block(path: str) -> dict | None:
    """One artifact's serving-provenance receipt facts: a BENCH round's
    ``determinism.receipt_fingerprint`` (run_paged attaches the headline
    engine's receipt config fingerprint; the block's stream fingerprint
    rides along as the digest column), or a fleet/loadgen artifact's
    ``receipts`` trailer (fingerprint set observed across the run)."""
    with open(path) as f:
        obj = json.load(f)
    if not isinstance(obj, dict):
        raise ValueError("not a JSON object")
    det = obj.get("determinism")
    if isinstance(det, dict) and det.get("receipt_fingerprint"):
        return {"fingerprint": det["receipt_fingerprint"],
                "digest": det.get("fingerprint"),
                "perturb": det.get("perturb")}
    rec = obj.get("receipts")
    if isinstance(rec, dict) and rec.get("fingerprints"):
        fps = [str(f) for f in rec["fingerprints"]]
        return {"fingerprint": fps[0] if len(fps) == 1 else None,
                "fingerprints": fps, "digest": None,
                "perturb": obj.get("perturb") or None}
    return None


def render_receipts(paths: list[str]) -> str:
    """Receipt provenance across rounds (chronological order): one row
    per artifact with the serving config fingerprint and the stream
    digest, the FIRST round either drifted named loudly — the same
    first-change contract as --determinism, but over the RECEIPT axes
    (a config fingerprint move means the serving configuration itself
    changed; a digest move at a stable fingerprint means the numerics
    moved under an unchanged config)."""
    lines = ["== receipt provenance across rounds ==", "",
             f"{'round':<28} {'config fingerprint':<18} {'digest':<18}"]
    prev: tuple[str, dict] | None = None
    first_drift: str | None = None
    for path in paths:
        name = os.path.basename(path)
        try:
            block = receipts_block(path)
        except (OSError, ValueError) as e:
            lines.append(f"{name:<28} (unreadable: {type(e).__name__})")
            continue
        if block is None:
            lines.append(f"{name:<28} (no receipt block)")
            continue
        fp = block.get("fingerprint")
        fps = block.get("fingerprints")
        digest = block.get("digest")
        drifted = []
        # a perturb-drill round is debris, not evidence: marked, never
        # compared, and never the next round's comparison bar
        drill = bool(block.get("perturb"))
        if prev is not None and not drill:
            p = prev[1]
            if fp and p.get("fingerprint") and fp != p["fingerprint"]:
                drifted.append("fingerprint")
            if digest and p.get("digest") and digest != p["digest"]:
                drifted.append("digest")
        mark = ""
        if fps and len(fps) > 1:
            mark += f"  SKEW: {len(fps)} fleet fingerprints"
        if drifted:
            mark += f"  <-- {' + '.join(drifted)} DRIFTED"
        if drill:
            mark += f"  [PERTURBED: {block['perturb']}]"
        if drifted and first_drift is None:
            first_drift = (f"first drift: {name} ({', '.join(drifted)} "
                           f"moved vs {os.path.basename(prev[0])})")
        fp_txt = fp or (f"({len(fps)} skewed)" if fps else "?")
        lines.append(f"{name:<28} {fp_txt:<18} {digest or '—':<18}{mark}")
        if not drill:
            prev = (path, block)
    lines.append("")
    lines.append(first_drift if first_drift
                 else "no receipt drift across these rounds")
    return "\n".join(lines)


def speculative_block(path: str) -> dict | None:
    """One artifact's ``speculative`` block: a BENCH round's embedded
    dict (bench.py A/B garnish) or a fleet_metrics.json trailer."""
    with open(path) as f:
        obj = json.load(f)
    if not isinstance(obj, dict):
        raise ValueError("not a JSON object")
    block = obj.get("speculative")
    return block if isinstance(block, dict) else None


def render_speculative(paths: list[str]) -> str:
    """Accept-rate (and steps-saved) trajectory across rounds: one row
    per artifact, per-round deltas against the previous round — how the
    drafting economics move commit to commit."""
    lines = ["== speculative decoding across rounds ==", "",
             f"{'round':<28} {'accept':>7} {'Δ':>7} {'drafted':>8} "
             f"{'accepted':>8} {'steps_saved':>11} {'wedges':>6}"]
    prev: float | None = None
    for path in paths:
        name = os.path.basename(path)
        try:
            block = speculative_block(path)
        except (OSError, ValueError) as e:
            lines.append(f"{name:<28} (unreadable: {type(e).__name__})")
            continue
        if block is None:
            lines.append(f"{name:<28} (no speculative block)")
            continue
        rate = float(block.get("accept_rate") or 0.0)
        delta = "" if prev is None else f"{rate - prev:+.3f}"
        ratio = block.get("steps_saved_ratio")
        lines.append(
            f"{name:<28} {rate:>7.3f} {delta:>7} "
            f"{block.get('drafted_tokens', '?'):>8} "
            f"{block.get('accepted_tokens', '?'):>8} "
            f"{(f'{ratio:.2f}x' if ratio is not None else '?'):>11} "
            f"{block.get('wedges', 0):>6}")
        prev = rate
    return "\n".join(lines)


def kernels_block(path: str) -> dict | None:
    """One kernel-CI leaderboard artifact (``reval-kernelbench-v1``,
    possibly nested under a driver record's ``"parsed"``)."""
    with open(path) as f:
        obj = json.load(f)
    if not isinstance(obj, dict):
        raise ValueError("not a JSON object")
    if (obj.get("schema") != "reval-kernelbench-v1"
            and isinstance(obj.get("parsed"), dict)):
        obj = obj["parsed"]
    if obj.get("schema") != "reval-kernelbench-v1":
        return None
    return obj


def render_kernels(paths: list[str], noise: float = 0.05) -> str:
    """The kernel-CI trajectory across leaderboard rounds (chronological
    order): one row per artifact, per-cell regressions vs the previous
    round's FRESH values, and the first regressed cell named loudly —
    the same first-change contract as --determinism.  Stale cells are
    flagged explicitly with their provenance: a stale cell must never
    render as a fresh measurement, and fresh-vs-stale pairs are never
    compared (a blind instrument is not a perf delta)."""
    lines = ["== kernel-CI leaderboard across rounds ==", "",
             f"{'round':<30} {'winner':<26} {'ms/step':>9} "
             f"{'run':>4} {'stale':>5} {'skip':>4} {'rty':>4}  gate"]
    # one baseline PER TIER: a --tiny smoke interleaved between two chip
    # rounds must not silently eat the chip baseline (the tier check
    # would skip the comparison and a real chip regression would read
    # as "no regression")
    prevs: dict[bool, tuple[str, dict]] = {}
    first_regress: str | None = None
    for path in paths:
        name = os.path.basename(path)
        try:
            obj = kernels_block(path)
        except (OSError, ValueError) as e:
            lines.append(f"{name:<30} (unreadable: {type(e).__name__})")
            continue
        if obj is None:
            lines.append(f"{name:<30} (no kernelbench leaderboard)")
            continue
        s = obj.get("summary", {})
        cells = obj.get("cells", {})
        winner = s.get("winner")
        winner_ms = (cells.get(winner, {}).get("ms_per_step")
                     if winner else None)
        marks = []
        if obj.get("tiny"):
            marks.append("[TINY]")
        # drill rounds (injected faults / seeded regressions) are marked
        # and never compared: chaos debris must not read as a perf move
        drill = bool(obj.get("perturb") or obj.get("chaos"))
        if obj.get("perturb"):
            marks.append(f"[PERTURBED: {', '.join(sorted(obj['perturb']))}]")
        if obj.get("chaos"):
            marks.append("[CHAOS DRILL]")
        regressed = []
        prev = prevs.get(bool(obj.get("tiny")))
        if prev is not None and not drill:
            pcells = prev[1].get("cells", {})
            for cname in sorted(cells):
                now, was = cells[cname], pcells.get(cname, {})
                if (now.get("status") == "run" and was.get("status") == "run"
                        and was.get("ms_per_step")
                        and now["ms_per_step"]
                        > was["ms_per_step"] * (1 + noise)):
                    regressed.append(cname)
        gate = (s.get("gate") or {}).get("status", "?")
        lines.append(
            f"{name:<30} {(winner or '—'):<26} "
            f"{(f'{winner_ms:.3f}' if winner_ms else '—'):>9} "
            f"{s.get('cells_run', '?'):>4} {s.get('cells_stale', '?'):>5} "
            f"{s.get('cells_skipped', '?'):>4} {s.get('retries', '?'):>4}  "
            f"{gate}"
            + (" " + " ".join(marks) if marks else "")
            + (f"  <-- regressed: {', '.join(regressed)}" if regressed
               else ""))
        if regressed and first_regress is None:
            first_regress = (f"first regression: {name} "
                             f"({', '.join(regressed)} vs "
                             f"{os.path.basename(prev[0])})")
        for cname, row in sorted(cells.items()):
            if row.get("status") == "stale":
                lk = row.get("last_known") or {}
                lines.append(
                    f"{'':<30}   STALE {cname}: last known "
                    f"{lk.get('ms_per_step', '?')} ms/step @ "
                    f"{lk.get('commit', '?')} ({lk.get('source', '?')}) — "
                    f"{row.get('retries', 0)} retries, "
                    f"{row.get('error', '?')}")
        if not drill:       # drill rounds never become the comparison bar
            prevs[bool(obj.get("tiny"))] = (path, obj)
    lines.append("")
    lines.append(first_regress if first_regress
                 else "no per-cell regression across these rounds")
    return "\n".join(lines)


def slo_block(path: str) -> dict | None:
    """One artifact's goodput/SLO-attainment block: a ``tools/loadgen.py``
    artifact (``reval-loadgen-v1`` — goodput + slo sections), or any
    artifact (a BENCH round, say) embedding an ``"slo"`` dict with
    ``goodput_ratio``/``attainment`` keys."""
    with open(path) as f:
        obj = json.load(f)
    if not isinstance(obj, dict):
        raise ValueError("not a JSON object")
    if obj.get("format") == "reval-loadgen-v1":
        return {"goodput_ratio": obj.get("goodput", {}).get("ratio"),
                "attainment": obj.get("slo", {}).get("attainment", {}),
                "lost": obj.get("counts", {}).get("lost"),
                "worst_bad_window_s":
                    obj.get("recovery", {}).get("worst_bad_window_s")}
    block = obj.get("slo")
    if isinstance(block, dict) and ("goodput_ratio" in block
                                    or "attainment" in block):
        return {"goodput_ratio": block.get("goodput_ratio"),
                "attainment": block.get("attainment", {}),
                "lost": block.get("lost"),
                "worst_bad_window_s": block.get("worst_bad_window_s")}
    return None


def render_slo(paths: list[str]) -> str:
    """Goodput / SLO-attainment trajectory across loadgen artifacts or
    BENCH rounds (chronological order): one row per artifact, and the
    FIRST round whose goodput ratio or any attainment metric regressed
    named loudly — the same first-change contract as --determinism."""
    lines = ["== goodput / SLO attainment across rounds ==", "",
             f"{'round':<28} {'goodput':>8} {'Δ':>8} {'attainment':<28} "
             f"{'lost':>5} {'worst_window':>12}"]
    prev: tuple[str, dict] | None = None
    first_regress: str | None = None
    for path in paths:
        name = os.path.basename(path)
        try:
            block = slo_block(path)
        except (OSError, ValueError) as e:
            lines.append(f"{name:<28} (unreadable: {type(e).__name__})")
            continue
        if block is None:
            lines.append(f"{name:<28} (no slo block)")
            continue
        ratio = block.get("goodput_ratio")
        att = block.get("attainment") or {}
        att_txt = " ".join(f"{k}={v:.3f}" for k, v in sorted(att.items())
                           if isinstance(v, (int, float))) or "—"
        delta = ""
        regressed = []
        if prev is not None:
            p = prev[1]
            if isinstance(ratio, (int, float)) \
                    and isinstance(p.get("goodput_ratio"), (int, float)):
                delta = f"{ratio - p['goodput_ratio']:+.3f}"
                if ratio < p["goodput_ratio"] - 1e-9:
                    regressed.append("goodput")
            for key, value in sorted((p.get("attainment") or {}).items()):
                now = att.get(key)
                if (isinstance(now, (int, float))
                        and isinstance(value, (int, float))
                        and now < value - 1e-9):
                    regressed.append(key)
        mark = f"  <-- regressed: {', '.join(regressed)}" if regressed else ""
        if regressed and first_regress is None:
            first_regress = (f"first regression: {name} "
                             f"({', '.join(regressed)} vs "
                             f"{os.path.basename(prev[0])})")
        window = block.get("worst_bad_window_s")
        lines.append(
            f"{name:<28} "
            f"{(f'{ratio:.3f}' if isinstance(ratio, (int, float)) else '?'):>8} "
            f"{delta:>8} {att_txt:<28} "
            f"{(block.get('lost') if block.get('lost') is not None else '?'):>5} "
            f"{(f'{window:g}s' if isinstance(window, (int, float)) else '?'):>12}"
            f"{mark}")
        prev = (path, block)
    lines.append("")
    lines.append(first_regress if first_regress
                 else "no goodput/attainment regression across these rounds")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("snapshot", nargs="+",
                    help="metrics snapshot JSON (registry snapshot, "
                         "fleet_metrics.json, or a /statusz body); with "
                         "--determinism/--speculative/--slo: artifacts in "
                         "chronological order")
    ap.add_argument("--determinism", action="store_true",
                    help="report reference-cell fingerprint drift across "
                         "BENCH rounds instead of metric snapshots")
    ap.add_argument("--speculative", action="store_true",
                    help="report speculative-decoding accept-rate deltas "
                         "across BENCH rounds instead of metric snapshots")
    ap.add_argument("--slo", action="store_true",
                    help="report goodput/SLO-attainment deltas across "
                         "loadgen artifacts (or BENCH rounds embedding an "
                         "slo block), naming the first regression")
    ap.add_argument("--kernels", action="store_true",
                    help="report the kernel-CI leaderboard trajectory "
                         "across kernelbench artifacts: per-cell "
                         "regressions (first one named), stale cells "
                         "flagged with provenance")
    ap.add_argument("--receipts", action="store_true",
                    help="report receipt config-fingerprint / stream-"
                         "digest drift across BENCH rounds (or fleet/"
                         "loadgen artifacts carrying a receipts "
                         "trailer), naming the first drifted round")
    args = ap.parse_args(argv)
    if sum((args.determinism, args.speculative, args.slo,
            args.kernels, args.receipts)) > 1:
        ap.error("--determinism, --speculative, --slo, --kernels, and "
                 "--receipts are mutually exclusive")
    if args.kernels:
        print(render_kernels(args.snapshot))
        return 0
    if args.receipts:
        print(render_receipts(args.snapshot))
        return 0
    if args.determinism:
        print(render_determinism(args.snapshot))
        return 0
    if args.speculative:
        print(render_speculative(args.snapshot))
        return 0
    if args.slo:
        print(render_slo(args.snapshot))
        return 0
    if len(args.snapshot) > 2:
        ap.error("snapshot mode takes one file (render) or two (delta)")
    a = load_snapshot(args.snapshot[0])
    if len(args.snapshot) == 1:
        print(render(a, args.snapshot[0]))
        return 0
    b = load_snapshot(args.snapshot[1])
    print(render(diff_snapshots(a, b),
                 f"{args.snapshot[1]} - {args.snapshot[0]}"))
    return 0


if __name__ == "__main__":
    sys.exit(main())
