#!/usr/bin/env python3
"""Render a metrics snapshot (or diff two) as a console report.

Input: JSON files carrying a registry snapshot — either a raw
``MetricsRegistry.snapshot()`` dict, the fleet's
``<results_dir>/fleet_metrics.json`` (snapshot under ``"metrics"``), or
a ``/statusz`` response body (same nesting).  With two files the report
is the DELTA: counters subtract, histogram bucket counts subtract, and
the percentiles are recomputed from the bucket deltas — i.e. the
distribution of exactly the requests that happened between the two
scrapes, which is how you price a scheduler change without restarting
the server.

Usage:
    python tools/obs_report.py SNAP.json [SNAP2.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from reval_tpu.obs.metrics import snapshot_percentile  # noqa: E402


def load_snapshot(path: str) -> dict:
    with open(path) as f:
        obj = json.load(f)
    for key in ("metrics",):    # fleet_metrics.json / statusz nesting
        if key in obj and isinstance(obj[key], dict):
            obj = obj[key]
    if not any(k in obj for k in ("counters", "gauges", "histograms")):
        raise ValueError(f"{path}: not a metrics snapshot (no counters/"
                         f"gauges/histograms key)")
    return {"counters": obj.get("counters", {}),
            "gauges": obj.get("gauges", {}),
            "histograms": obj.get("histograms", {})}


def diff_snapshots(a: dict, b: dict) -> dict:
    """``b - a`` (a taken first).  Gauges keep b's value (a gauge is a
    level, not a flow — diffing it would report nonsense)."""
    counters = {k: round(b["counters"].get(k, 0) - a["counters"].get(k, 0), 6)
                for k in sorted(set(a["counters"]) | set(b["counters"]))}
    hists = {}
    for name in sorted(set(a["histograms"]) | set(b["histograms"])):
        ha = a["histograms"].get(name)
        hb = b["histograms"].get(name)
        if hb is None:
            # present in a, gone in b: the process restarted between the
            # scrapes — rendering a's old totals as a positive "delta"
            # would be a lie, so the series is dropped (the counters
            # section still shows the restart as negative deltas)
            continue
        if ha is None:
            hists[name] = hb      # appeared between scrapes: a is zero
            continue
        if [x[0] for x in ha["buckets"]] != [x[0] for x in hb["buckets"]]:
            raise ValueError(f"{name}: bucket bounds differ between files")
        hists[name] = {
            "buckets": [[bb, cb - ca] for (bb, cb), (_, ca)
                        in zip(hb["buckets"], ha["buckets"])],
            "inf": hb.get("inf", 0) - ha.get("inf", 0),
            "sum": hb["sum"] - ha["sum"],
            "count": hb["count"] - ha["count"]}
    return {"counters": counters, "gauges": dict(b["gauges"]),
            "histograms": hists}


def percentile(hist: dict, q: float) -> float:
    """THE estimator (obs.metrics.snapshot_percentile, itself over
    percentile_from_buckets — shared with Histogram.percentile and the
    `reval_tpu watch` console, so a diff report, a live scrape, and the
    watch screen can never disagree)."""
    return snapshot_percentile(hist, q)


def _fmt_secs(v: float) -> str:
    if v >= 1.0:
        return f"{v:8.3f}s "
    return f"{v * 1e3:8.3f}ms"


def render(snap: dict, title: str) -> str:
    lines = [f"== obs report: {title} ==", ""]
    hists = {k: v for k, v in snap["histograms"].items() if v and v["count"]}
    if hists:
        lines.append(f"{'histogram':<40} {'count':>8} {'mean':>10} "
                     f"{'p50':>10} {'p95':>10} {'p99':>10}")
        for name, h in sorted(hists.items()):
            mean = h["sum"] / h["count"]
            lines.append(
                f"{name:<40} {h['count']:>8} {_fmt_secs(mean):>10} "
                f"{_fmt_secs(percentile(h, .50)):>10} "
                f"{_fmt_secs(percentile(h, .95)):>10} "
                f"{_fmt_secs(percentile(h, .99)):>10}")
        lines.append("")
    counters = {k: v for k, v in snap["counters"].items() if v}
    if counters:
        lines.append(f"{'counter':<48} {'value':>14}")
        for name, v in sorted(counters.items()):
            out = f"{v:.3f}" if isinstance(v, float) and v != int(v) else int(v)
            lines.append(f"{name:<48} {out:>14}")
        lines.append("")
    if snap["gauges"]:
        lines.append(f"{'gauge':<48} {'value':>14}")
        for name, v in sorted(snap["gauges"].items()):
            lines.append(f"{name:<48} {v:>14}")
        lines.append("")
    if len(lines) == 2:
        lines.append("(empty snapshot: no non-zero metrics)")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("snapshot", help="metrics snapshot JSON (registry "
                                     "snapshot, fleet_metrics.json, or a "
                                     "/statusz body)")
    ap.add_argument("snapshot_b", nargs="?", default=None,
                    help="second snapshot: report the DELTA (b - a), "
                         "percentiles recomputed from bucket deltas")
    args = ap.parse_args(argv)
    a = load_snapshot(args.snapshot)
    if args.snapshot_b is None:
        print(render(a, args.snapshot))
        return 0
    b = load_snapshot(args.snapshot_b)
    print(render(diff_snapshots(a, b),
                 f"{args.snapshot_b} - {args.snapshot}"))
    return 0


if __name__ == "__main__":
    sys.exit(main())
