#!/usr/bin/env python
"""Scoring-parity oracle: replay the reference's committed run logs through
this framework's pipeline and check every metric reproduces.

The reference repo ships gemma-1-2b-it MBPP logs for coverage/path/state at
direct/cot x temp {0.0, 0.8} (see BASELINE.md; the metrics trailer is each
log's last JSONL row).  Those generations were produced by the reference's
harness (reference evaluation.py run loop + inference.py vLLM backend);
re-serving them via ReplayBackend and re-scoring with THIS pipeline tests,
end to end: prompt planning order and probe counts, answer postprocessing,
ground-truth execution (tracer + queries), and the metric math.  Any
mismatch to 4 decimals is a scoring-parity bug.

Usage:
    python tools/parity_replay.py [--reference DIR] [--dataset mbpp]
Exit code 0 = all rows reproduce.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# every committed (task, prompt_type, temp) combination in the reference
REFERENCE_RUNS = [
    ("coverage", "direct", 0.0), ("coverage", "direct", 0.8),
    ("coverage", "cot", 0.0), ("coverage", "cot", 0.8),
    ("path", "direct", 0.0), ("path", "direct", 0.8),
    ("path", "cot", 0.0), ("path", "cot", 0.8),
    ("state", "direct", 0.0), ("state", "direct", 0.8),
]
MODEL_ID = "google/gemma-1-2b-it"
# reference state logs also exist for cot; include them
REFERENCE_RUNS += [("state", "cot", 0.0), ("state", "cot", 0.8)]


def reference_trailer(source_file: str) -> dict:
    with open(source_file) as f:
        rows = [json.loads(line) for line in f if line.strip()]
    return rows[-1]


def valid_cases_file(task: str, reference_dir: str, dataset: str) -> str | None:
    """The reference's committed MBPP runs score ONLY tot-validated test
    cases (coverage 1009 / path 414 / state 469 of the full probe set);
    the case lists live next to the tot logs (reference
    evaluation.py:1153-1160's hard-coded paths point at these files)."""
    hits = glob.glob(os.path.join(
        reference_dir, f"{task}@{MODEL_ID}_tot",
        f"*.valid_test_cases.{dataset}.json"))
    return hits[0] if hits else None


def replay_one(task: str, prompt_type: str, temp: float, reference_dir: str,
               dataset: str, out_dir: str) -> tuple[dict, dict] | None:
    """(our metrics, reference trailer), or None if the log is absent."""
    from reval_tpu.inference.replay import ReplayBackend
    from reval_tpu.tasks import TASKS

    try:
        backend = ReplayBackend(replay_task=task, model_id=MODEL_ID,
                                temp=temp, prompt_type=prompt_type,
                                results_dir=reference_dir)
    except FileNotFoundError:
        return None
    runner = TASKS[task](model=backend, prompt_type=prompt_type,
                         dataset=dataset, results_dir=out_dir,
                         progress=False,
                         valid_test_cases_path=valid_cases_file(
                             task, reference_dir, dataset))
    ours = runner.run()
    return ours, reference_trailer(backend.source_file)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--reference",
                    default="/root/reference/model_generations")
    ap.add_argument("--dataset", default="mbpp")
    ap.add_argument("--places", type=int, default=4)
    args = ap.parse_args()

    if not glob.glob(os.path.join(args.reference, "*@*")):
        print(f"no reference logs under {args.reference}")
        return 2

    failures = 0
    with tempfile.TemporaryDirectory() as tmp:
        for task, prompt_type, temp in REFERENCE_RUNS:
            got = replay_one(task, prompt_type, temp, args.reference,
                             args.dataset, tmp)
            if got is None:
                print(f"SKIP  {task:<9} {prompt_type:<6} t={temp}: no log")
                continue
            ours, ref = got
            keys = sorted(set(ours) & set(ref))
            bad = [k for k in keys
                   if round(float(ours[k]), args.places)
                   != round(float(ref[k]), args.places)]
            status = "FAIL" if bad else "ok"
            failures += bool(bad)
            detail = " ".join(f"{k}={ours[k]:{'.4f' if isinstance(ours[k], float) else ''}}"
                              for k in keys)
            print(f"{status:<5} {task:<9} {prompt_type:<6} t={temp}: {detail}"
                  + (f"   MISMATCH on {bad}: ref "
                     + " ".join(f"{k}={ref[k]}" for k in bad) if bad else ""))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
