#!/usr/bin/env python3
"""Open-loop fleet load generator: realistic traffic against the router.

Every chaos drill before this one fed the fleet a handful of hand-fed
prompts; this tool replays REval-shaped traffic at
thousands-of-users scale the way the serving studies measure it
(PAPERS.md, arxiv 2511.17593): **open loop**.  Arrival times are drawn
up front from a seeded process — Poisson, or a diurnal curve with the
peak mid-run — and every request fires AT its arrival time regardless
of how the fleet is doing.  A slow fleet therefore shows up as missed
deadlines and shed requests (the honest signal), never as a generator
that politely slowed down (the closed-loop lie).  The concurrency
ceiling (``REVAL_TPU_LOADGEN_CONCURRENCY``) bounds client sockets;
arrivals past it queue client-side with their wait counted against
their own latency, never re-timed.

**Workload.**  Requests carry per-tenant mixes: each tenant has a
weight (its share of arrivals), a deadline, and a per-task prompt pool.
``--workload reval`` samples GENUINE planned prompts per REval
dataset×prompt_type task (``tools/prefix_stats.py``'s mock planning —
the same few-shot templates the scoring pipeline sends), so requests
ride the router's prefix-affinity keys and exercise cache-warm routing;
``--workload synthetic`` builds long per-(tenant, task) template
prefixes with unique probe suffixes — same routing shape, zero planning
cost (the tier-1 drills use it).  Same seed → bit-identical schedule
AND prompt stream.

**Artifact** (``reval-loadgen-v1``, one JSON object; ``--out`` writes
it, stdout always carries it):

- ``goodput``: completions that met their own deadline, as counts and a
  ratio over ALL generated requests (a lost prompt is goodput's
  denominator too);
- ``slo``: declared targets, attainment (fraction of completions within
  each target), and client-side e2e percentiles next to the fleet-side
  TTFT/TPOT percentiles diffed from the router's federated ``/metrics``
  over exactly this run;
- ``counts``: shed (429) observations, failovers/ejections (router
  counter deltas), transport retries, lost prompts (retry/deadline
  budget exhausted — each also logs ``loadgen.lost``);
- ``timeline``: per-bucket arrivals/completions/good/sheds/lost plus
  ``worst_bad_window_s`` — the longest consecutive stretch of buckets
  containing a miss or loss, i.e. the recovery window the chaos drill
  bounds;
- ``tenants``: the same accounting per tenant;
- ``receipts``: the fleet's receipt config-fingerprint set observed
  over the run (``/statusz`` at start and end) — ``converged`` false
  means the traffic spanned divergent serving configs.

Usage::

    python tools/loadgen.py --target 127.0.0.1:3100 --process diurnal \
        --trough-rate 5 --peak-rate 50 --duration 60 --seed 7 \
        --tenants alpha:3,beta:1 --slo-e2e 2.0 --out loadgen.json
"""

from __future__ import annotations

import argparse
import json
import math
import os
import random
import sys
import threading
import time
import urllib.error
import urllib.request
from dataclasses import dataclass, field

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from reval_tpu.env import env_int  # noqa: E402
from reval_tpu.obs import metrics as obs_metrics  # noqa: E402
from reval_tpu.obs.logging import log_event  # noqa: E402
from reval_tpu.obs.metrics import (  # noqa: E402
    parse_prometheus, scrape_delta_histogram, snapshot_fraction_le)
from reval_tpu.resilience.retry import (  # noqa: E402
    RetryPolicy, retryable_error)
from reval_tpu.serving.autoscaler import p99_from_scrapes  # noqa: E402
from reval_tpu.serving.router import parse_tenant_weights  # noqa: E402,F401
# (re-exported: the tenant-weights grammar is THE router's, parsed once)

FORMAT = "reval-loadgen-v1"

TASKS = ("coverage", "path", "state", "output")


# ---------------------------------------------------------------------------
# Arrival processes (seeded, bit-reproducible)
# ---------------------------------------------------------------------------

def poisson_arrivals(rate_per_s: float, duration_s: float,
                     rng: random.Random) -> list[float]:
    """Homogeneous Poisson arrival offsets in ``[0, duration_s)`` —
    exponential inter-arrivals, exactly as many as the process yields."""
    out: list[float] = []
    t = 0.0
    if rate_per_s <= 0:
        return out
    while True:
        t += rng.expovariate(rate_per_s)
        if t >= duration_s:
            return out
        out.append(t)


def diurnal_rate(t: float, trough_per_s: float, peak_per_s: float,
                 period_s: float) -> float:
    """The instantaneous diurnal rate: a raised-cosine day with the
    trough at t=0 and the peak at ``period_s / 2`` (one default period
    = one run = the peak lands mid-run, where the drill strikes)."""
    phase = 0.5 * (1.0 - math.cos(2.0 * math.pi * t / period_s))
    return trough_per_s + (peak_per_s - trough_per_s) * phase


def diurnal_arrivals(trough_per_s: float, peak_per_s: float,
                     duration_s: float, rng: random.Random,
                     period_s: float | None = None) -> list[float]:
    """Inhomogeneous Poisson arrivals under :func:`diurnal_rate`, by
    thinning against the peak envelope — seeded and bit-reproducible."""
    period = period_s if period_s else duration_s
    peak = max(peak_per_s, trough_per_s, 1e-9)
    out: list[float] = []
    t = 0.0
    while True:
        t += rng.expovariate(peak)
        if t >= duration_s:
            return out
        if rng.random() * peak <= diurnal_rate(t, trough_per_s,
                                               peak_per_s, period):
            out.append(t)


# ---------------------------------------------------------------------------
# Workload: per-tenant request mixes
# ---------------------------------------------------------------------------

@dataclass
class TenantSpec:
    """One tenant's mix: a weight (share of arrivals), an SLO deadline,
    and a per-task prompt pool.  ``probe_suffix`` appends a unique
    probe tail per request (synthetic pools are single templates — the
    suffix keeps prompts distinct while the template prefix still
    carries the router affinity key)."""

    name: str
    weight: float = 1.0
    deadline_s: float = 30.0
    max_tokens: int = 48
    pools: dict = field(default_factory=dict)   # task -> [prompt, ...]
    probe_suffix: bool = True


@dataclass
class PlannedRequest:
    at_s: float
    tenant: str
    prompt: str
    deadline_s: float
    max_tokens: int
    seq: int


def synthetic_tenants(weights: dict[str, float], *,
                      deadline_s: float = 30.0, max_tokens: int = 48,
                      template_chars: int = 600) -> list[TenantSpec]:
    """Synthetic per-(tenant, task) few-shot templates: long shared
    prefixes (well past any affinity window) so consistent-hash routing
    and replica prefix caches are exercised without REval planning."""
    tenants = []
    for name, weight in weights.items():
        pools = {}
        for task in TASKS:
            unit = f"[{task}::{name}] few-shot exemplar | "
            reps = max(1, math.ceil(template_chars / len(unit)))
            pools[task] = [unit * reps]
        tenants.append(TenantSpec(name=name, weight=float(weight),
                                  deadline_s=deadline_s,
                                  max_tokens=max_tokens, pools=pools))
    return tenants


def reval_tenants(weights: dict[str, float], *, dataset: str = "humaneval",
                  prompt_type: str = "direct", per_task: int = 4,
                  deadline_s: float = 30.0,
                  max_tokens: int = 48) -> list[TenantSpec]:
    """GENUINE REval dataset×prompt_type request shapes: every tenant
    samples the same mock-planned prompt pools ``tools/prefix_stats.py``
    measures (and whose affinity table seeds the router), so loadgen
    traffic rides the exact template prefixes production scoring
    sends."""
    from prefix_stats import task_prompts

    pools = {task: task_prompts(task, per_task, dataset, prompt_type)
             for task in TASKS}
    return [TenantSpec(name=name, weight=float(weight),
                       deadline_s=deadline_s, max_tokens=max_tokens,
                       pools=dict(pools), probe_suffix=False)
            for name, weight in weights.items()]


def build_workload(arrivals: list[float], tenants: list[TenantSpec],
                   rng: random.Random) -> list[PlannedRequest]:
    """Assign each arrival a tenant (weighted), a task, and a prompt —
    all drawn from ``rng``, so one seed fixes the whole request
    stream."""
    if not tenants:
        raise ValueError("at least one tenant is required")
    total_w = sum(t.weight for t in tenants)
    out: list[PlannedRequest] = []
    for seq, at_s in enumerate(arrivals):
        pick = rng.random() * total_w
        acc = 0.0
        tenant = tenants[-1]
        for t in tenants:
            acc += t.weight
            if pick <= acc:
                tenant = t
                break
        task = rng.choice(sorted(tenant.pools))
        prompt = rng.choice(tenant.pools[task])
        if tenant.probe_suffix:
            prompt = f"{prompt}probe {seq} of {tenant.name}"
        out.append(PlannedRequest(at_s=at_s, tenant=tenant.name,
                                  prompt=prompt,
                                  deadline_s=tenant.deadline_s,
                                  max_tokens=tenant.max_tokens, seq=seq))
    return out


# ---------------------------------------------------------------------------
# The open-loop runner
# ---------------------------------------------------------------------------

class OpenLoopRunner:
    """Fire a planned request stream at its arrival times against one
    ``/v1/completions`` endpoint (router or single server) and account
    every request to a terminal outcome — ``completed`` (with its
    deadline verdict) or ``lost`` (retry/deadline budget exhausted).
    The ledger is complete by construction: the artifact refuses to
    render until every scheduled arrival has an outcome."""

    def __init__(self, target: str, requests: list[PlannedRequest], *,
                 concurrency: int | None = None,
                 slo_e2e_s: float | None = None,
                 slo_ttft_s: float | None = None,
                 slo_tpot_s: float | None = None,
                 timeline_bucket_s: float = 1.0,
                 retry: RetryPolicy | None = None):
        self.target = target if ":" in str(target) else f"127.0.0.1:{target}"
        self.base_url = f"http://{self.target}"
        self.requests = sorted(requests, key=lambda r: (r.at_s, r.seq))
        concurrency = (concurrency if concurrency is not None
                       else env_int("REVAL_TPU_LOADGEN_CONCURRENCY", 256))
        self._gate = threading.Semaphore(max(1, int(concurrency)))
        self.concurrency = max(1, int(concurrency))
        self.slo = {"e2e_s": slo_e2e_s, "ttft_s": slo_ttft_s,
                    "tpot_s": slo_tpot_s}
        self.timeline_bucket_s = float(timeline_bucket_s)
        self._retry = retry or RetryPolicy(max_attempts=64, base_delay=0.05,
                                           max_delay=1.0, jitter=0.25)
        self._lock = threading.Lock()
        self._records: list[dict] = []      # guarded-by: _lock
        self._sheds = 0                     # guarded-by: _lock
        self._retries = 0                   # guarded-by: _lock

    # -- one request's lifecycle -------------------------------------------
    def _post_once(self, req: PlannedRequest, remaining_s: float) -> str:
        body = json.dumps({
            "prompt": req.prompt, "max_tokens": req.max_tokens,
            "temperature": 0.0, "tenant": req.tenant,
            "deadline_s": round(max(0.05, remaining_s), 3)}).encode()
        http_req = urllib.request.Request(
            self.base_url + "/v1/completions", data=body,
            headers={"Content-Type": "application/json",
                     "X-Request-Id": f"loadgen-{req.seq}"})
        with urllib.request.urlopen(http_req,
                                    timeout=max(1.0, remaining_s + 5)) as r:
            json.loads(r.read())
        return "ok"

    def _fire(self, req: PlannedRequest, t0: float) -> None:
        sched = t0 + req.at_s
        deadline = sched + req.deadline_s
        attempts = 0
        sheds = 0
        outcome = "lost"
        reason = None
        with self._gate:
            while True:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    reason = reason or "deadline before attempt"
                    break
                attempts += 1
                try:
                    self._post_once(req, remaining)
                    outcome = "completed"
                    break
                except urllib.error.HTTPError as exc:
                    exc.read()
                    if exc.code == 429:
                        sheds += 1
                    if exc.code == 504:
                        reason = "deadline_exceeded (504)"
                        break
                    if not retryable_error(exc):
                        reason = f"HTTP {exc.code}"
                        break
                    delay = self._retry.delay_for(min(attempts - 1, 8), exc)
                except Exception as exc:   # noqa: BLE001 — transport death
                    # during a replica kill is the drill's normal weather
                    if not retryable_error(exc):
                        reason = repr(exc)
                        break
                    delay = self._retry.delay_for(min(attempts - 1, 8))
                time.sleep(min(delay, max(0.0, deadline
                                          - time.perf_counter())))
        done = time.perf_counter()
        e2e = done - sched
        rec = {"seq": req.seq, "tenant": req.tenant, "at_s": req.at_s,
               "outcome": outcome, "e2e_s": round(e2e, 4),
               "good": outcome == "completed" and e2e <= req.deadline_s,
               "attempts": attempts, "sheds": sheds,
               "done_at_s": round(done - t0, 4)}
        if outcome != "completed":
            rec["reason"] = reason
            log_event("loadgen.lost", level="warning", seq=req.seq,
                      tenant=req.tenant, attempts=attempts, reason=reason)
        with self._lock:
            self._records.append(rec)
            self._sheds += sheds
            self._retries += max(0, attempts - 1)

    # -- the run ------------------------------------------------------------
    def _scrape(self) -> dict | None:
        try:
            with urllib.request.urlopen(self.base_url + "/metrics",
                                        timeout=10) as r:
                return parse_prometheus(r.read().decode())
        except Exception:   # noqa: BLE001 — a single server without
            # /metrics federation still gets the client-side artifact
            return None

    def _scrape_fingerprints(self) -> dict[str, list[str]]:
        """The receipt config-fingerprint set visible at /statusz right
        now (obs/receipts.py): a router body carries the fleet map
        (fingerprint -> ready replica ids), a single server's readiness
        carries its own.  {} when the target has no provenance — the
        artifact simply omits the receipts block."""
        try:
            with urllib.request.urlopen(self.base_url + "/statusz",
                                        timeout=10) as r:
                status = json.loads(r.read())
        except Exception:   # noqa: BLE001 — same weather as _scrape
            return {}
        fps = status.get("fingerprints")
        if isinstance(fps, dict) and fps:
            return {str(fp): sorted(str(x) for x in ids)
                    for fp, ids in fps.items()}
        readiness = status.get("readiness") or {}
        fp = readiness.get("fingerprint")
        if fp:
            return {str(fp): [str(readiness.get("engine_id") or "engine")]}
        return {}

    def run(self) -> dict:
        log_event("loadgen.start", target=self.target,
                  requests=len(self.requests),
                  concurrency=self.concurrency)
        before = self._scrape()
        fps_before = self._scrape_fingerprints()
        t0 = time.perf_counter()
        threads = []
        for req in self.requests:
            wait = t0 + req.at_s - time.perf_counter()
            if wait > 0:
                # the dispatcher sleeps to the ARRIVAL schedule only —
                # completions never push arrivals (open loop)
                time.sleep(wait)
            th = threading.Thread(target=self._fire, args=(req, t0),
                                  daemon=True, name=f"loadgen-{req.seq}")
            th.start()
            threads.append(th)
        # every worker self-terminates at its own deadline; the join
        # bound derives from the LATEST one (+ slack for a final retry
        # sleep/socket timeout), never a fixed constant a user-supplied
        # --deadline could legitimately exceed
        join_until = t0 + max(r.at_s + r.deadline_s
                              for r in self.requests) + 60.0
        for th in threads:
            th.join(timeout=max(0.1, join_until - time.perf_counter()))
        after = self._scrape()
        artifact = self._artifact(before, after,
                                  time.perf_counter() - t0)
        # serving provenance: the union of fingerprints seen at start
        # and end of the run.  >1 fingerprint means this run's traffic
        # spanned divergent serving configs — its numbers are not one
        # config's numbers (obs_report --receipts flags it as SKEW).
        fp_map: dict[str, set] = {}
        for snap in (fps_before, self._scrape_fingerprints()):
            for fp, ids in snap.items():
                fp_map.setdefault(fp, set()).update(ids)
        if fp_map:
            artifact["receipts"] = {
                "fingerprints": sorted(fp_map),
                "converged": len(fp_map) <= 1,
                "replicas": {fp: sorted(ids)
                             for fp, ids in sorted(fp_map.items())}}
        log_event("loadgen.done", target=self.target,
                  requests=len(self.requests),
                  lost=artifact["counts"]["lost"],
                  goodput_ratio=artifact["goodput"]["ratio"])
        return artifact

    # -- artifact assembly --------------------------------------------------
    @staticmethod
    def _pctl(sorted_vals: list[float], q: float) -> float:
        if not sorted_vals:
            return 0.0
        i = min(len(sorted_vals) - 1,
                max(0, math.ceil(q * len(sorted_vals)) - 1))
        return sorted_vals[i]

    def _fleet_block(self, before: dict | None,
                     after: dict | None) -> dict | None:
        if not after:
            return None

        def delta(name: str) -> float:
            return max(0.0, after.get(name, 0.0)
                       - (before or {}).get(name, 0.0))

        def pct(name: str) -> dict:
            return {f"p{int(q * 100)}":
                    round(p99_from_scrapes(after, before, name, q), 4)
                    for q in (0.50, 0.95, 0.99)}

        def frac_le(name: str, threshold: float | None) -> float | None:
            if threshold is None:
                return None
            # THE shared cumulative→delta assembly + attainment estimator
            hist = scrape_delta_histogram(after, before, name)
            if hist is None:
                return None
            return round(snapshot_fraction_le(hist, threshold), 4)

        return {"ttft": pct(obs_metrics.TTFT),
                "tpot": pct(obs_metrics.TPOT),
                "ttft_attainment": frac_le(obs_metrics.TTFT,
                                           self.slo["ttft_s"]),
                "tpot_attainment": frac_le(obs_metrics.TPOT,
                                           self.slo["tpot_s"]),
                "failovers": int(delta(obs_metrics.ROUTER_FAILOVERS)),
                "ejections": int(delta(obs_metrics.ROUTER_EJECTIONS)),
                "router_sheds": int(delta(obs_metrics.ROUTER_SHEDS)),
                "goodput_total": int(delta(obs_metrics.ROUTER_GOODPUT)),
                "slo_miss_total": int(delta(obs_metrics.ROUTER_SLO_MISS))}

    @staticmethod
    def _kvtier_block(before: dict | None, after: dict | None) -> dict | None:
        """KV-tier traffic over the run from scraped ``reval_kvtier_*``
        deltas (inference/tpu/kv_tiers.py); None when the target has no
        tier store (mock engine, tiering off, no /metrics)."""
        if not after:
            return None

        def delta(name: str) -> int:
            return int(max(0.0, after.get(name, 0.0)
                           - (before or {}).get(name, 0.0)))

        spills = delta(obs_metrics.KVTIER_SPILLS)
        promotions = delta(obs_metrics.KVTIER_PROMOTIONS)
        recomputes = delta(obs_metrics.KVTIER_RECOMPUTES)
        if not (spills or promotions or recomputes):
            return None
        attempts = promotions + recomputes
        return {"spills": spills,
                "spill_drops": delta(obs_metrics.KVTIER_SPILL_DROPS),
                "promotions": promotions,
                "disk_promotions": delta(obs_metrics.KVTIER_DISK_PROMOTIONS),
                "recomputes": recomputes,
                "integrity_failures": delta(
                    obs_metrics.KVTIER_INTEGRITY_FAILURES),
                "promote_hit_rate": round(promotions / attempts, 4)
                if attempts else 0.0}

    def _artifact(self, before: dict | None, after: dict | None,
                  wall_s: float) -> dict:
        with self._lock:
            records = sorted(self._records, key=lambda r: r["seq"])
            sheds, retries = self._sheds, self._retries
        if len(records) != len(self.requests):
            # the ledger-complete invariant: every scheduled arrival gets
            # a terminal outcome.  A worker outliving the derived join
            # bound (a hung socket past every deadline) is recorded as
            # LOST with an explicit reason — degrading to a truthful
            # artifact, never a crash that discards the collected run
            seen = {r["seq"] for r in records}
            for req in self.requests:
                if req.seq in seen:
                    continue
                log_event("loadgen.lost", level="warning", seq=req.seq,
                          tenant=req.tenant, attempts=0,
                          reason="worker outlived the join bound")
                records.append({
                    "seq": req.seq, "tenant": req.tenant,
                    "at_s": req.at_s, "outcome": "lost",
                    "e2e_s": round(wall_s - req.at_s, 4), "good": False,
                    "attempts": 0, "sheds": 0,
                    "done_at_s": round(wall_s, 4),
                    "reason": "worker outlived the join bound"})
            records.sort(key=lambda r: r["seq"])
        completed = [r for r in records if r["outcome"] == "completed"]
        good = [r for r in completed if r["good"]]
        lost = [r for r in records if r["outcome"] != "completed"]
        e2e_sorted = sorted(r["e2e_s"] for r in completed)
        n = len(records)

        bucket = self.timeline_bucket_s
        n_buckets = max(1, math.ceil((max((r["done_at_s"]
                                           for r in records), default=1.0)
                                      + 1e-9) / bucket))
        timeline = [{"t": round(i * bucket, 3), "arrivals": 0,
                     "completions": 0, "good": 0, "sheds": 0, "lost": 0}
                    for i in range(n_buckets)]
        for r in records:
            arr = min(n_buckets - 1, int(r["at_s"] / bucket))
            timeline[arr]["arrivals"] += 1
            timeline[arr]["sheds"] += r["sheds"]
            if r["outcome"] == "completed":
                done_b = min(n_buckets - 1, int(r["done_at_s"] / bucket))
                timeline[done_b]["completions"] += 1
                if r["good"]:
                    timeline[done_b]["good"] += 1
            else:
                timeline[arr]["lost"] += 1
        # a "bad" bucket saw a late completion or a lost arrival
        bad = [(row["completions"] - row["good"]) + row["lost"] > 0
               for row in timeline]
        worst = cur = 0
        for flag in bad:
            cur = cur + 1 if flag else 0
            worst = max(worst, cur)

        per_tenant: dict[str, dict] = {}
        for r in records:
            row = per_tenant.setdefault(
                r["tenant"], {"requests": 0, "completed": 0, "good": 0,
                              "lost": 0, "sheds": 0, "e2e": []})
            row["requests"] += 1
            row["sheds"] += r["sheds"]
            if r["outcome"] == "completed":
                row["completed"] += 1
                row["good"] += int(r["good"])
                row["e2e"].append(r["e2e_s"])
            else:
                row["lost"] += 1
        kv_tier = self._kvtier_block(before, after)
        total_completed = max(1, len(completed))
        tenants_out = {}
        for name, row in sorted(per_tenant.items()):
            e2e = sorted(row.pop("e2e"))
            row["e2e_p95_s"] = round(self._pctl(e2e, 0.95), 4)
            row["goodput_ratio"] = round(row["good"]
                                         / max(1, row["requests"]), 4)
            row["shed_rate"] = round(row["sheds"]
                                     / max(1, row["requests"]), 4)
            if kv_tier:
                # engine-side tier counters carry no tenant label (page
                # chains are shared state), so the per-tenant split is an
                # ESTIMATE weighted by completed-request share — marked
                # _est so nobody reads it as an exact attribution
                share = row["completed"] / total_completed
                row["kv_tier_est"] = {
                    "promotions_est": round(kv_tier["promotions"] * share, 1),
                    "recomputes_est": round(kv_tier["recomputes"] * share, 1),
                    "promote_hit_rate": kv_tier["promote_hit_rate"]}
            tenants_out[name] = row

        e2e_target = self.slo["e2e_s"]
        slo_block = {
            "targets": {k: v for k, v in self.slo.items() if v is not None},
            "attainment": {},
            "latency": {"e2e": {
                "p50": round(self._pctl(e2e_sorted, 0.50), 4),
                "p95": round(self._pctl(e2e_sorted, 0.95), 4),
                "p99": round(self._pctl(e2e_sorted, 0.99), 4)}}}
        if e2e_target is not None and completed:
            slo_block["attainment"]["e2e"] = round(
                sum(1 for r in completed if r["e2e_s"] <= e2e_target)
                / len(completed), 4)
        fleet = self._fleet_block(before, after)
        if fleet:
            slo_block["latency"]["ttft"] = fleet.pop("ttft")
            slo_block["latency"]["tpot"] = fleet.pop("tpot")
            for key in ("ttft", "tpot"):
                att = fleet.pop(f"{key}_attainment")
                if att is not None:
                    slo_block["attainment"][key] = att
        return {
            "format": FORMAT, "target": self.target,
            "requests": n, "wall_s": round(wall_s, 3),
            "concurrency": self.concurrency,
            "timeline_bucket_s": bucket,
            "goodput": {"completed": len(completed), "good": len(good),
                        "lost": len(lost),
                        "ratio": round(len(good) / max(1, n), 4)},
            "slo": slo_block,
            "counts": {"shed_429": sheds, "retries": retries,
                       "lost": len(lost), **(fleet or {})},
            **({"kv_tier": kv_tier} if kv_tier else {}),
            "tenants": tenants_out,
            "timeline": timeline,
            "recovery": {"worst_bad_window_s": round(worst * bucket, 3),
                         "bad_buckets": sum(bad)},
            "ledger_complete": True,
        }


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--target", default="127.0.0.1:3100",
                    help="router (or single server) host:port")
    ap.add_argument("--process", choices=["poisson", "diurnal"],
                    default="poisson")
    ap.add_argument("--rate", type=float, default=10.0,
                    help="poisson arrival rate, req/s")
    ap.add_argument("--trough-rate", type=float, default=2.0,
                    help="diurnal trough rate, req/s")
    ap.add_argument("--peak-rate", type=float, default=20.0,
                    help="diurnal peak rate, req/s (peak lands mid-run)")
    ap.add_argument("--period", type=float, default=None,
                    help="diurnal period seconds (default: the run "
                         "duration — one cycle)")
    ap.add_argument("--duration", type=float, default=30.0)
    ap.add_argument("--seed", type=int, default=None,
                    help="default env REVAL_TPU_LOADGEN_SEED or 0")
    ap.add_argument("--tenants", default="alpha:3,beta:1",
                    help="name:weight,... tenant mix")
    ap.add_argument("--workload", choices=["synthetic", "reval"],
                    default="reval",
                    help="reval = genuine mock-planned prompts per "
                         "dataset×prompt_type task; synthetic = long "
                         "template prefixes, zero planning cost")
    ap.add_argument("--dataset", default="humaneval")
    ap.add_argument("--prompt-type", choices=["direct", "cot"],
                    default="direct")
    ap.add_argument("--per-task", type=int, default=4,
                    help="reval workload: prompts sampled per task")
    ap.add_argument("--deadline", type=float, default=30.0,
                    help="per-request deadline seconds (the goodput bar)")
    ap.add_argument("--max-tokens", type=int, default=48)
    ap.add_argument("--concurrency", type=int, default=None,
                    help="in-flight ceiling (default env "
                         "REVAL_TPU_LOADGEN_CONCURRENCY or 256)")
    ap.add_argument("--slo-e2e", type=float, default=None,
                    help="e2e SLO target seconds (attainment reported)")
    ap.add_argument("--slo-ttft", type=float, default=None)
    ap.add_argument("--slo-tpot", type=float, default=None)
    ap.add_argument("--timeline-bucket-s", type=float, default=1.0,
                    help="timeline bucket width (60 = per-minute)")
    ap.add_argument("--out", default=None, metavar="PATH",
                    help="also write the artifact JSON here")
    args = ap.parse_args(argv)

    seed = (args.seed if args.seed is not None
            else env_int("REVAL_TPU_LOADGEN_SEED", 0))
    rng = random.Random(seed)
    if args.process == "poisson":
        arrivals = poisson_arrivals(args.rate, args.duration, rng)
    else:
        arrivals = diurnal_arrivals(args.trough_rate, args.peak_rate,
                                    args.duration, rng,
                                    period_s=args.period)
    weights = parse_tenant_weights(args.tenants)
    if args.workload == "reval":
        tenants = reval_tenants(weights, dataset=args.dataset,
                                prompt_type=args.prompt_type,
                                per_task=args.per_task,
                                deadline_s=args.deadline,
                                max_tokens=args.max_tokens)
    else:
        tenants = synthetic_tenants(weights, deadline_s=args.deadline,
                                    max_tokens=args.max_tokens)
    requests = build_workload(arrivals, tenants, rng)
    runner = OpenLoopRunner(args.target, requests,
                            concurrency=args.concurrency,
                            slo_e2e_s=args.slo_e2e,
                            slo_ttft_s=args.slo_ttft,
                            slo_tpot_s=args.slo_tpot,
                            timeline_bucket_s=args.timeline_bucket_s)
    artifact = runner.run()
    artifact["seed"] = seed
    artifact["process"] = args.process
    artifact["workload"] = args.workload
    if args.out:
        with open(args.out, "w") as f:
            json.dump(artifact, f, indent=1)
    print(json.dumps(artifact))
    return 0 if artifact["counts"]["lost"] == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
