"""Standalone paged-attention kernel A/B at the bench decode shape.

Times ONLY the attention kernel (not the full decode step) for each
backend × pool-dtype combination, at the flagship bench shape, plus the
XLA gather formulation as a sanity floor.  Runs in ~2 minutes on a chip
— small enough to fit a short tunnel window and decide the default
backend (``REVAL_TPU_PAGED_BACKEND``) from data.

    python tools/kernel_bench.py --slots 32 --ctx 600 --layers 24

``--layers`` repeats the kernel per timed iteration to amortise
dispatch the way a real decode step does (one call per layer).

This CLI is a THIN front over the kernel-CI harness's variant provider
(``reval_tpu/kernelbench.py``): the historical row labels map onto
matrix cells and the timing core is shared, so the quick A/B and the
supervised leaderboard (``tools/kernelbench.py``) can never drift.  The
output line format is unchanged — ``tools/decide_defaults.py`` still
parses ``kernel_ab.txt`` rows verbatim.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--slots", type=int, default=32)
    ap.add_argument("--ctx", type=int, default=600)
    ap.add_argument("--heads", type=int, default=16)
    ap.add_argument("--kv-heads", type=int, default=16)
    ap.add_argument("--head-dim", type=int, default=128)
    ap.add_argument("--page", type=int, default=128)
    ap.add_argument("--span", type=int, default=16, help="block-table span")
    ap.add_argument("--layers", type=int, default=24)
    ap.add_argument("--reps", type=int, default=10)
    ap.add_argument("--no-int8", action="store_true",
                    help="skip the int8-pool rows (pure diagnosis — the "
                         "kv8s64 full-pipeline bench decides the kv dtype; "
                         "saves ~4 min of compiles in a short window)")
    ap.add_argument("--only-int8", action="store_true",
                    help="run ONLY the int8-pool rows (the deferred half of "
                         "a --no-int8 pass; the bf16/xla rows are already "
                         "in kernel_ab.txt and need not be re-measured)")
    ap.add_argument("--tiny", action="store_true", help="CPU smoke")
    args = ap.parse_args()
    if args.no_int8 and args.only_int8:
        ap.error("--no-int8 and --only-int8 are mutually exclusive "
                 "(together they skip every variant)")

    from bench import acquire_chip_lock
    chip_lock = acquire_chip_lock(skip=args.tiny)  # held until exit

    import jax

    from reval_tpu.kernelbench import (LEGACY_LABELS, BenchShape, KernelCell,
                                       build_inputs, time_cell)

    if args.tiny:
        jax.config.update("jax_platforms", "cpu")
        args.slots, args.ctx, args.layers, args.span = 2, 96, 2, 3

    shape = BenchShape(slots=args.slots, ctx=args.ctx, heads=args.heads,
                       kv_heads=args.kv_heads, head_dim=args.head_dim,
                       page=args.page, span=args.span, layers=args.layers,
                       reps=args.reps)
    dev = jax.devices()[0]
    print(f"device: {dev.device_kind} | B={shape.slots} "
          f"H={shape.heads}/{shape.kv_heads} D={shape.head_dim} "
          f"ctx={shape.ctx} page={shape.page} span={shape.span} "
          f"layers={shape.layers}")

    # operand sets are shared across same-pool rows (one build per dtype)
    inputs = {"bf16": None, "int8": None}

    ok_count = 0

    def variant(label: str) -> None:
        nonlocal ok_count
        backend, dot, pool = LEGACY_LABELS[label]
        # chunk=1 preserves the historical timing exactly: the long loop
        # is ``layers`` kernel calls vs one, per-step = per_call * layers
        cell = KernelCell(backend=backend, dot=dot, pool=pool, chunk=1)
        if inputs[pool] is None:
            inputs[pool] = build_inputs(shape, pool)
        try:
            row = time_cell(cell, shape, inputs=inputs[pool])
            print(f"{label:14s} {row['ms_per_step']:8.3f} ms/step   "
                  f"{row['gbps']:6.1f} GB/s effective")
            ok_count += 1
        except Exception as e:
            print(f"{label:14s} FAILED: {type(e).__name__}: {str(e)[:120]}")

    if not args.only_int8:
        for label in ("grid", "seq", "grid-wide", "seq-wide"):
            variant(label)
    if not args.no_int8:
        for label in ("grid-int8", "seq-int8"):
            variant(label)
    if not args.tiny and not args.only_int8:
        variant("xla")

    if ok_count == 0:
        # nothing measured (wedged tunnel / driver fault): exit nonzero so
        # the runbook's skip-if-exists logic retries instead of committing
        # an artifact with zero data points
        sys.exit(1)


if __name__ == "__main__":
    main()
