"""Standalone paged-attention kernel A/B at the bench decode shape.

Times ONLY the attention kernel (not the full decode step) for each
backend × pool-dtype combination, at the flagship bench shape, plus the
XLA gather formulation as a sanity floor.  Runs in ~2 minutes on a chip
— small enough to fit a short tunnel window and decide the default
backend (``REVAL_TPU_PAGED_BACKEND``) from data.

    python tools/kernel_bench.py --slots 32 --ctx 600 --layers 24

``--layers`` repeats the kernel per timed iteration to amortise
dispatch the way a real decode step does (one call per layer).
"""

from __future__ import annotations

import argparse
import os
import statistics
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--slots", type=int, default=32)
    ap.add_argument("--ctx", type=int, default=600)
    ap.add_argument("--heads", type=int, default=16)
    ap.add_argument("--kv-heads", type=int, default=16)
    ap.add_argument("--head-dim", type=int, default=128)
    ap.add_argument("--page", type=int, default=128)
    ap.add_argument("--span", type=int, default=16, help="block-table span")
    ap.add_argument("--layers", type=int, default=24)
    ap.add_argument("--reps", type=int, default=10)
    ap.add_argument("--no-int8", action="store_true",
                    help="skip the int8-pool rows (pure diagnosis — the "
                         "kv8s64 full-pipeline bench decides the kv dtype; "
                         "saves ~4 min of compiles in a short window)")
    ap.add_argument("--only-int8", action="store_true",
                    help="run ONLY the int8-pool rows (the deferred half of "
                         "a --no-int8 pass; the bf16/xla rows are already "
                         "in kernel_ab.txt and need not be re-measured)")
    ap.add_argument("--tiny", action="store_true", help="CPU smoke")
    args = ap.parse_args()
    if args.no_int8 and args.only_int8:
        ap.error("--no-int8 and --only-int8 are mutually exclusive "
                 "(together they skip every variant)")

    from bench import acquire_chip_lock
    chip_lock = acquire_chip_lock(skip=args.tiny)  # held until exit

    import jax
    import jax.numpy as jnp
    import numpy as np

    if args.tiny:
        jax.config.update("jax_platforms", "cpu")
        args.slots, args.ctx, args.layers, args.span = 2, 96, 2, 3

    from reval_tpu.ops import pallas_attention as pa

    b, h, h_kv, d, p = (args.slots, args.heads, args.kv_heads,
                        args.head_dim, args.page)
    need = (args.ctx + p - 1) // p + 1
    # the table must span every live page or the kernels read garbage ids
    args.span = max(args.span, need)
    n_pages = 1 + b * need
    rng = np.random.default_rng(0)

    q = jnp.asarray(rng.standard_normal((b, h, d)), jnp.bfloat16)
    kp = jnp.asarray(rng.standard_normal((n_pages * p, h_kv, d)), jnp.bfloat16)
    vp = jnp.asarray(rng.standard_normal((n_pages * p, h_kv, d)), jnp.bfloat16)
    kp8 = vp8 = ks = None
    if not args.no_int8:
        kp8 = (kp * 16).astype(jnp.int8)
        vp8 = (vp * 16).astype(jnp.int8)
        ks = jnp.full((n_pages * p, h_kv), 1 / 16, jnp.float32)
    tables = np.zeros((b, args.span), np.int32)
    for s in range(b):
        for j in range(need):
            tables[s, j] = 1 + s * need + j
    tables = jnp.asarray(tables)
    lens = jnp.full((b,), args.ctx, jnp.int32)

    dev = jax.devices()[0]
    print(f"device: {dev.device_kind} | B={b} H={h}/{h_kv} D={d} "
          f"ctx={args.ctx} page={p} span={args.span} layers={args.layers}")

    interp = jax.default_backend() != "tpu"

    ok_count = 0

    def variant(label, fn, k, v, scales=False):
        nonlocal ok_count
        kw = dict(page_size=p)
        if scales:
            kw.update(k_scales=ks, v_scales=ks)
        if fn is not pa.paged_decode_attention_xla:
            kw["interpret"] = interp

        # Timing MUST end on a host fetch: through the axon tunnel
        # ``block_until_ready`` returns before the device has executed
        # (measured: a 100-call loop "completed" in 30 µs, then took >2
        # minutes to materialise), so only np.asarray of the result is a
        # sync point.  The fetch+RTT overhead is cancelled by timing an
        # N-layer in-jit loop against a 1-layer one: per-call =
        # (T_N - T_1) / (N - 1).
        def make_loop(n):
            @jax.jit
            def loop(q, k, v, tables, lens):
                def body(_, acc):
                    o = fn(acc.astype(q.dtype), k, v, tables, lens, **kw)
                    return o.astype(jnp.float32)
                return jax.lax.fori_loop(0, n, body, q.astype(jnp.float32))
            return loop

        def fetch_time(loop):
            t0 = time.perf_counter()
            np.asarray(loop(q, k, v, tables, lens))
            return time.perf_counter() - t0

        try:
            loop_n, loop_1 = make_loop(args.layers), make_loop(1)
            fetch_time(loop_n)          # compile
            fetch_time(loop_1)          # compile
            t_n = [fetch_time(loop_n) for _ in range(args.reps)]
            if args.layers > 1:
                t_1 = [fetch_time(loop_1) for _ in range(args.reps)]
                per_call = ((statistics.median(t_n) - statistics.median(t_1))
                            / (args.layers - 1))
            else:       # single layer: overhead can't be cancelled
                per_call = statistics.median(t_n)
            # RTT jitter can swallow a sub-resolution kernel: floor at 1 µs
            # so the GB/s print stays finite and the row reads as "fast",
            # not FAILED
            ms = max(per_call * args.layers, 1e-6) * 1000
            # bytes actually touched: live pages (K+V) per sequence per layer
            live_pages = (args.ctx + p - 1) // p
            elt = 1 if scales else 2
            gb = (2 * b * live_pages * p * h_kv * d * elt * args.layers) / 1e9
            if scales:
                # the f32 K/V scale arrays are real traffic too — without
                # them the int8 rows understate their GB/s in the very
                # artifact that decides the default backend
                gb += (2 * b * live_pages * p * h_kv * 4 * args.layers) / 1e9
            print(f"{label:14s} {ms:8.3f} ms/step   {gb / (ms / 1000):6.1f} GB/s "
                  f"effective")
            ok_count += 1
        except Exception as e:
            print(f"{label:14s} FAILED: {type(e).__name__}: {str(e)[:120]}")

    if not args.only_int8:
        variant("grid", pa.paged_decode_attention_pallas, kp, vp)
        variant("seq", pa.paged_decode_attention_pallas_seq, kp, vp)
        variant("grid-wide", partial(pa.paged_decode_attention_pallas,
                                     dot_mode="wide"), kp, vp)
        variant("seq-wide", partial(pa.paged_decode_attention_pallas_seq,
                                    dot_mode="wide"), kp, vp)
    if not args.no_int8:
        variant("grid-int8", pa.paged_decode_attention_pallas, kp8, vp8,
                scales=True)
        variant("seq-int8", pa.paged_decode_attention_pallas_seq, kp8, vp8,
                scales=True)
    if not args.tiny and not args.only_int8:
        variant("xla", pa.paged_decode_attention_xla, kp, vp)

    if ok_count == 0:
        # nothing measured (wedged tunnel / driver fault): exit nonzero so
        # the runbook's skip-if-exists logic retries instead of committing
        # an artifact with zero data points
        sys.exit(1)


if __name__ == "__main__":
    main()
