#!/usr/bin/env python3
"""reval-lint CLI: the repo's codebase-native static analysis suite.

Thin launcher over :mod:`reval_tpu.analysis.driver` — the passes are:

- ``locks``        lock-discipline / race detector (``# guarded-by:``)
- ``hotpath``      no blocking/allocating calls in ``# hot-path`` functions
- ``jit``          every jax.jit/shard_map ctor declares ``# jit-entry:``
                   (static args, bucketed axes, warmup budget); no
                   traced-value Python branching in annotated bodies
- ``hostsync``     no implicit device->host syncs in hot-path regions or
                   jit-entry bodies (``# host-sync: <why>`` at the few
                   deliberate fetches)
- ``tilecontract`` every ``pallas_call`` in ops/ declares
                   ``# tile: (sublane, lane)``; resolvable BlockSpec/VMEM
                   dims are lane/sublane-aligned
- ``errors``       serving layer raises only the serving/errors.py taxonomy
- ``env``          REVAL_TPU_* reads go through reval_tpu/env.py::ENV
- ``metrics``      METRICS spec <-> README <-> literals (ex check_metrics)
- ``events``       EVENTS spec <-> call sites <-> README (ex check_metrics)
- ``detmatrix``    determinism-matrix artifacts conform to the schema

Usage::

    python tools/reval_lint.py              # all passes, this repo
    python tools/reval_lint.py locks env    # a subset
    python tools/reval_lint.py --root DIR   # a planted tree (tests)

Exit status 1 on any unsuppressed violation; suppressions
(``# lint: allow(<pass>) — <reason>``) are counted and reported.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from reval_tpu.analysis.driver import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
