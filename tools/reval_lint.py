#!/usr/bin/env python3
"""reval-lint CLI: the repo's codebase-native static analysis suite.

Thin launcher over :mod:`reval_tpu.analysis.driver` — the passes are:

- ``locks``        lock-discipline / race detector (``# guarded-by:``)
- ``hotpath``      no blocking/allocating calls in ``# hot-path`` functions
- ``jit``          every jax.jit/shard_map ctor declares ``# jit-entry:``
                   (static args, bucketed axes, warmup budget); no
                   traced-value Python branching in annotated bodies
- ``hostsync``     no implicit device->host syncs in hot-path regions or
                   jit-entry bodies (``# host-sync: <why>`` at the few
                   deliberate fetches)
- ``tilecontract`` every ``pallas_call`` in ops/ declares
                   ``# tile: (sublane, lane)``; resolvable BlockSpec/VMEM
                   dims are lane/sublane-aligned
- ``mesh``         every Mesh/NamedSharding/PartitionSpec/shard_map ctor
                   in parallel/, models/, inference/tpu/ is covered by a
                   ``# mesh: axes=(..) in=(..) out=(..) via=(..)``
                   contract; axes resolve against parallel/mesh.py::AXES;
                   shard_map specs round-trip; collectives name a
                   contract axis
- ``reshard``      with_sharding_constraint needs ``# reshard: <why>``;
                   device_put / zero-arg PartitionSpec in hot-path/jit
                   regions too
- ``enginezoo``    every engine class implements/delegates/reasons away
                   each declared surface member; orphan public methods
                   flagged; ENGINE_SURFACE.md parity matrix kept fresh
- ``errors``       serving layer raises only the serving/errors.py taxonomy
- ``env``          REVAL_TPU_* reads go through reval_tpu/env.py::ENV
- ``metrics``      METRICS spec <-> README <-> literals (ex check_metrics)
- ``events``       EVENTS spec <-> call sites <-> README (ex check_metrics)
- ``detmatrix``    determinism-matrix artifacts conform to the schema

Usage::

    python tools/reval_lint.py              # all passes, this repo
    python tools/reval_lint.py locks env    # a subset
    python tools/reval_lint.py --root DIR   # a planted tree (tests)
    python tools/reval_lint.py --json       # machine-readable report
    python tools/reval_lint.py --changed-only   # git-diff-scoped output
    python tools/reval_lint.py --write-engine-matrix   # ENGINE_SURFACE.md

Exit codes: 0 clean, 1 any unsuppressed violation, 2 unrunnable
(unknown pass, --changed-only outside git).  Suppressions
(``# lint: allow(<pass>) — <reason>``) are counted and reported;
zombie suppressions (pass ran, nothing found at the site) are
violations themselves.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from reval_tpu.analysis.driver import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
