#!/usr/bin/env python3
"""reval-lint CLI: the repo's codebase-native static analysis suite.

Thin launcher over :mod:`reval_tpu.analysis.driver` — the passes are:

- ``locks``   lock-discipline / race detector (``# guarded-by:``)
- ``hotpath`` no blocking/allocating calls in ``# hot-path`` functions
- ``errors``  serving layer raises only the serving/errors.py taxonomy
- ``env``     REVAL_TPU_* reads go through reval_tpu/env.py::ENV
- ``metrics`` METRICS spec <-> README <-> literals (ex check_metrics)
- ``events``  EVENTS spec <-> call sites <-> README (ex check_metrics)

Usage::

    python tools/reval_lint.py              # all passes, this repo
    python tools/reval_lint.py locks env    # a subset
    python tools/reval_lint.py --root DIR   # a planted tree (tests)

Exit status 1 on any unsuppressed violation; suppressions
(``# lint: allow(<pass>) — <reason>``) are counted and reported.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from reval_tpu.analysis.driver import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
