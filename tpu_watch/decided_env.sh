# written by tools/decide_defaults.py — measured-best paged-attention config
export REVAL_TPU_PAGED_BACKEND=pallas_seq
export REVAL_TPU_KERNEL_DOT=swap
