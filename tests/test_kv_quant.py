"""Int8 KV page pool (models/paged.py ``kv_dtype="int8"``): quantized
attention parity (XLA + Pallas interpret), decode-step parity against the
float pool, commit roundtrip, and engine integration."""

import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # noqa: E402

from reval_tpu.models import ModelConfig, init_kv_cache, init_random_params, prefill
from reval_tpu.models.paged import (
    _quantize_kv,
    commit_prefill,
    init_paged_cache,
    paged_decode_step,
)
from reval_tpu.ops.pallas_attention import (
    paged_decode_attention_pallas,
    paged_decode_attention_pallas_seq,
    paged_decode_attention_xla,
)

KERNELS = [paged_decode_attention_pallas, paged_decode_attention_pallas_seq]
KERNEL_IDS = ["page-grid", "per-seq"]

PAGE = 128


def small_cfg():
    return ModelConfig(vocab_size=256, hidden_size=64, intermediate_size=128,
                       num_layers=2, num_heads=4, num_kv_heads=2, head_dim=128)


def make_quantized_paged(seed=0, b=3, h=8, h_kv=4, d=128, n_pages=12,
                         max_pages=3):
    """Float pages + their int8/scale form, so tests can compare the
    quantized attention against the float path on the SAME values."""
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((b, h, d)), jnp.float32)
    kf = jnp.asarray(rng.standard_normal((n_pages * PAGE, h_kv, d)), jnp.float32)
    vf = jnp.asarray(rng.standard_normal((n_pages * PAGE, h_kv, d)), jnp.float32)
    kq, ks = _quantize_kv(kf)
    vq, vs = _quantize_kv(vf)
    tables = jnp.asarray(
        rng.permutation(n_pages)[: b * max_pages].reshape(b, max_pages),
        jnp.int32)
    lens = jnp.asarray(rng.integers(1, max_pages * PAGE, size=b), jnp.int32)
    return q, kf, vf, kq, ks, vq, vs, tables, lens


def test_quantize_kv_roundtrip():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((5, 4, 64)) * 3, jnp.float32)
    q, s = _quantize_kv(x)
    assert q.dtype == jnp.int8 and s.shape == (5, 4)
    deq = np.asarray(q, np.float32) * np.asarray(s)[..., None]
    err = np.abs(deq - np.asarray(x))
    assert err.max() <= 0.5 * np.asarray(s).max() + 1e-6


def test_quantized_xla_matches_dequantized_float():
    q, kf, vf, kq, ks, vq, vs, tables, lens = make_quantized_paged()
    deq_k = kq.astype(jnp.float32) * ks[..., None]
    deq_v = vq.astype(jnp.float32) * vs[..., None]
    ref = paged_decode_attention_xla(q, deq_k, deq_v, tables, lens,
                                     page_size=PAGE)
    got = paged_decode_attention_xla(q, kq, vq, tables, lens, page_size=PAGE,
                                     k_scales=ks, v_scales=vs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    # and it tracks the ORIGINAL float values closely (int8 noise only)
    base = paged_decode_attention_xla(q, kf, vf, tables, lens, page_size=PAGE)
    np.testing.assert_allclose(np.asarray(got), np.asarray(base),
                               rtol=0.1, atol=0.05)


@pytest.mark.parametrize("kernel", KERNELS, ids=KERNEL_IDS)
def test_quantized_pallas_matches_xla(kernel):
    q, kf, vf, kq, ks, vq, vs, tables, lens = make_quantized_paged(seed=1)
    ref = paged_decode_attention_xla(q, kq, vq, tables, lens, page_size=PAGE,
                                     k_scales=ks, v_scales=vs)
    got = kernel(q, kq, vq, tables, lens, page_size=PAGE, interpret=True,
                 k_scales=ks, v_scales=vs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("window", [64, 200])
@pytest.mark.parametrize("kernel", KERNELS, ids=KERNEL_IDS)
def test_quantized_windowed_pallas_matches_xla(kernel, window):
    q, kf, vf, kq, ks, vq, vs, tables, lens = make_quantized_paged(seed=2)
    ref = paged_decode_attention_xla(q, kq, vq, tables, lens, page_size=PAGE,
                                     window=window, k_scales=ks, v_scales=vs)
    got = kernel(q, kq, vq, tables, lens, page_size=PAGE, interpret=True,
                 window=window, k_scales=ks, v_scales=vs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_commit_roundtrip_int8():
    """commit → gather+dequant reproduces the committed KV to int8 noise."""
    cfg = small_cfg()
    rng = np.random.default_rng(3)
    b, t = 2, PAGE
    kv = init_kv_cache(cfg, b, t, dtype=jnp.float32)
    kv = type(kv)(jnp.asarray(rng.standard_normal(kv.k.shape), jnp.float32),
                  jnp.asarray(rng.standard_normal(kv.v.shape), jnp.float32))
    pad_len = jnp.asarray([7, 60], jnp.int32)
    cache = init_paged_cache(cfg, num_pages=3, page_size=PAGE,
                             dtype=jnp.float32, kv_dtype="int8")
    tables = jnp.asarray([[1], [2]], jnp.int32)
    cache = commit_prefill(cache, kv, pad_len, tables)
    for row in range(b):
        pad = int(pad_len[row])
        n_valid = t - pad
        page = int(tables[row, 0])
        got = (np.asarray(cache.k[0][page * PAGE: page * PAGE + n_valid],
                          np.float32)
               * np.asarray(cache.k_scale[0][page * PAGE: page * PAGE + n_valid])[..., None])
        want = np.asarray(kv.k[0, row, pad:], np.float32)
        np.testing.assert_allclose(got, want, rtol=0.05, atol=0.05)


def test_paged_decode_step_int8_tracks_float():
    """Full decode steps over an int8 pool stay close to the float pool."""
    cfg = small_cfg()
    params = init_random_params(cfg, seed=0, dtype="float32")
    rng = np.random.default_rng(4)
    b, t = 2, PAGE
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (b, t)), jnp.int32)
    pad_len = jnp.asarray([5, 100], jnp.int32)
    cache = init_kv_cache(cfg, b, t, dtype=jnp.float32)
    logits, cache = prefill(params, cfg, tokens, pad_len, cache)

    tables = jnp.asarray([[1, 2], [3, 4]], jnp.int32)
    pools = {}
    for kv_dtype in ("", "int8"):
        pc = init_paged_cache(cfg, num_pages=5, page_size=PAGE,
                              dtype=jnp.float32, kv_dtype=kv_dtype)
        pools[kv_dtype] = commit_prefill(pc, cache, pad_len, tables[:, :1])

    lens = t - pad_len
    nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)[:, None]
    for _ in range(3):
        ref, pools[""] = paged_decode_step(params, cfg, nxt, tables, lens,
                                           pools[""])
        got, pools["int8"] = paged_decode_step(params, cfg, nxt, tables, lens,
                                               pools["int8"])
        # logits drift is bounded by int8 KV noise; the decoded ARGMAX
        # (what generation consumes) must agree here
        assert (np.asarray(got).argmax(-1) == np.asarray(ref).argmax(-1)).all()
        denom = np.abs(np.asarray(ref)).max()
        assert np.abs(np.asarray(got) - np.asarray(ref)).max() / denom < 0.1
        nxt = jnp.argmax(ref, axis=-1).astype(jnp.int32)[:, None]
        lens = lens + 1


def test_engine_generates_with_int8_kv():
    from reval_tpu.inference.tpu.paged_engine import PagedTPUEngine
    from reval_tpu.inference.tpu.tokenizer import ByteTokenizer

    cfg = small_cfg()
    params = init_random_params(cfg, seed=5, dtype="float32")
    eng = PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=2,
                         page_size=128, max_seq_len=512, kv_dtype="int8")
    outs = eng.generate(["def f():", "x = 1 +"], max_new_tokens=8,
                        temperature=0.0)
    eng.close()
    assert len(outs) == 2 and all(isinstance(o, str) for o in outs)


def test_engine_int8_kv_with_prefix_sharing():
    """Shared-prefix path + int8 pool: prefix pages quantize on commit and
    riders read them through the scales."""
    from reval_tpu.inference.tpu.paged_engine import PagedTPUEngine
    from reval_tpu.inference.tpu.tokenizer import ByteTokenizer

    cfg = small_cfg()
    params = init_random_params(cfg, seed=6, dtype="float32")
    shared = "#" * 300                      # > one page of common prefix
    prompts = [shared + " def a():", shared + " def b():"]
    outs = {}
    for kv_dtype in ("", "int8"):
        eng = PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=2,
                             page_size=128, max_seq_len=1024,
                             kv_dtype=kv_dtype, prefix_sharing=True)
        outs[kv_dtype] = eng.generate(prompts, max_new_tokens=8,
                                      temperature=0.0)
        eng.close()
    # int8 KV noise may flip a low-margin argmax on random weights, but
    # the outputs must be well-formed and the same shape
    assert len(outs["int8"]) == 2
    assert all(isinstance(o, str) for o in outs["int8"])

@pytest.mark.parametrize("kernel", KERNELS, ids=KERNEL_IDS)
def test_quantized_softcap_pallas_matches_xla(kernel):
    """Scales x softcap TOGETHER: the kernels fold the k-scales into the
    scores BEFORE softcapping (tanh(s*ks/cap) != tanh(s/cap)*ks), so this
    combination locks the ordering the int8 fold relies on — neither the
    scales-only nor softcap-only tests would catch a reorder."""
    q, kf, vf, kq, ks, vq, vs, tables, lens = make_quantized_paged(seed=4)
    ref = paged_decode_attention_xla(q, kq, vq, tables, lens, page_size=PAGE,
                                     softcap=20.0, k_scales=ks, v_scales=vs)
    got = kernel(q, kq, vq, tables, lens, page_size=PAGE, interpret=True,
                 softcap=20.0, k_scales=ks, v_scales=vs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
