"""Golden tests for answer post-processing + equality (SURVEY §7 step 1:
'golden tests for every _postprocess and State._eq branch')."""

import numpy as np
import pytest

from reval_tpu.dynamics import Nil
from reval_tpu.tasks.answers import (
    output_penalty,
    pad_output_answer,
    parse_coverage_answer,
    parse_output_answer,
    parse_path_answer,
    parse_state_answer,
    path_answer_to_lines,
    state_answers_equal,
    strip_answer_tags,
)


class TestStripTags:
    def test_full_tags(self):
        assert strip_answer_tags("junk [ANSWER] YES [/ANSWER] more") == "YES"

    def test_truncated_closing_tag(self):
        assert strip_answer_tags("[ANSWER]NO[/ANSWER") == "NO"

    def test_no_tags_passthrough(self):
        assert strip_answer_tags("  YES  ") == "  YES  "


class TestCoverage:
    @pytest.mark.parametrize("resp,want", [
        ("YES", True),
        ("NO", False),
        ("yes", True),
        ("[ANSWER]YES[/ANSWER]", True),
        ("[ANSWER]\nNO\n[/ANSWER]", False),
        ("", False),                       # empty → NO
        ("MAYBE", False),                  # ambiguous (neither) → NO
        ("YESNO", False),                  # head truncation: 'YES' in 'YES', 'NO' not in 'YES' → True? see below
        ("Not sure", False),
    ])
    def test_basic(self, resp, want):
        if resp == "YESNO":
            # first-3-chars rule: head 'YES' → yes wins
            assert parse_coverage_answer(resp) is True
        else:
            assert parse_coverage_answer(resp) is want

    def test_head_truncation_rule(self):
        # only the first 3 chars are scanned: 'NO WAIT YES' → NO
        assert parse_coverage_answer("NO WAIT YES") is False
        assert parse_coverage_answer("YES BUT NO") is True

    def test_cot_incomplete(self):
        assert parse_coverage_answer("thinking...", "cot") is False
        assert parse_coverage_answer("[THOUGHT]x[/THOUGHT][ANSWER]YES[/ANSWER]", "cot") is True


class TestPath:
    def test_int_sentinels(self):
        assert parse_path_answer("") == -2
        assert parse_path_answer("-1") == -1
        assert parse_path_answer("no thought", "cot") == -2

    def test_code_line_answer(self):
        assert parse_path_answer("[ANSWER]    return x\nextra[/ANSWER]") == "return x"

    def test_line_mapping(self):
        codelines = ["def f(x):", "    if x:", "        return x", "    return x"]
        assert path_answer_to_lines("return x", codelines) == [3, 4]
        assert path_answer_to_lines("nonexistent", codelines) == [-2]
        assert path_answer_to_lines(-1, codelines) == [-1]
        assert path_answer_to_lines(-2, codelines) == [-2]


class TestStateParsing:
    def test_simple_pairs(self):
        assert parse_state_answer("5; int") == (5, int)
        assert parse_state_answer("'abc'; str") == ("abc", str)
        assert parse_state_answer("[1, 2]; list") == ([1, 2], list)
        assert parse_state_answer("3.5; float") == (3.5, float)

    def test_nil_answers(self):
        assert parse_state_answer("Nil") is Nil
        assert parse_state_answer("nil") is Nil
        assert parse_state_answer("[Nil]") is Nil
        assert parse_state_answer("Nil; Nil") is Nil

    def test_no_semicolon_is_error(self):
        assert parse_state_answer("just text") == "ERROR"

    def test_class_unwrap_and_generics(self):
        assert parse_state_answer("5; <class 'int'>") == (5, int)
        assert parse_state_answer("[1]; list[int]") == ([1], list)

    def test_aliases(self):
        assert parse_state_answer("'x'; string") == ("x", str)
        assert parse_state_answer("7; integer") == (7, int)

    def test_tuple_detection(self):
        assert parse_state_answer("(1, 2); (int, int)") == ((1, 2), tuple)

    def test_unquoted_string_fallback(self):
        assert parse_state_answer("hello world; str") == ("hello world", str)

    def test_unicode_quotes(self):
        assert parse_state_answer("‘ab’; str") == ("ab", str)

    def test_none_cases(self):
        assert parse_state_answer("None; NoneType") == (None, type(None))
        assert parse_state_answer("None; int") == (None, type(None))

    def test_ndarray(self):
        val, typ = parse_state_answer("[1, 2]; numpy.ndarray")
        assert typ is np.ndarray and np.array_equal(val, np.array([1, 2]))

    def test_datetime(self):
        import datetime

        val, typ = parse_state_answer("2024-01-02; datetime.datetime")
        assert typ is datetime.datetime and val.year == 2024

    def test_semicolon_in_value(self):
        # rfind: the LAST semicolon splits value from type
        assert parse_state_answer("'a;b'; str") == ("a;b", str)

    def test_cot_incomplete(self):
        assert parse_state_answer("5; int", "cot") == "ERROR"

    def test_garbage_type(self):
        assert parse_state_answer("5; no_such_type_xyz") == "ERROR"


class TestStateEquality:
    def test_nil_cases(self):
        assert state_answers_equal(Nil, Nil)
        assert not state_answers_equal(Nil, [1])
        assert not state_answers_equal((1, int), Nil)

    def test_type_mismatch(self):
        assert not state_answers_equal((1, int), ["1"])     # actual is str
        assert not state_answers_equal(("1", int), [1])     # val/type conflict

    def test_float_tolerance(self):
        assert state_answers_equal((0.30000001, float), [0.3])
        assert not state_answers_equal((0.31, float), [0.3])

    def test_membership(self):
        assert state_answers_equal((2, int), [1, 2, 3])
        assert not state_answers_equal((9, int), [1, 2, 3])

    def test_ndarray(self):
        a = np.array([1.0, 2.0])
        assert state_answers_equal((a, np.ndarray), [np.array([1.0, 2.0])])
        assert not state_answers_equal((a, np.ndarray), [np.array([3.0, 4.0])])

    def test_bool_vs_int_distinct(self):
        assert not state_answers_equal((True, bool), [1])


class TestOutput:
    def test_parse(self):
        assert parse_output_answer("[ANSWER]assert f(1) == 2[/ANSWER]") == "assert f(1) == 2"
        assert parse_output_answer("x", "cot") == "ERROR"

    def test_pad(self):
        given = "a = A(3)\nassertEqual(a.f(2), ??)\nassertEqual(a.f(4), ??)"
        short = "assertEqual(a.f(2), 5)\nassertEqual(a.f(4), 7)"
        padded = pad_output_answer(short, given)
        assert padded.split("\n")[0] == "a = A(3)"
        assert len(padded.split("\n")) == 3
        assert pad_output_answer("ERROR", given) == "assert False"

    def test_penalty(self):
        given = "assert f(1) == ??"
        assert output_penalty("assert True", given)
        assert output_penalty("x = 1", given)          # fewer asserts
        assert not output_penalty("assert f(1) == 2", given)
        assert output_penalty("assertTrue(True)", given)
