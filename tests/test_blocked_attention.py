"""The flash-style blocked prefill attention (ops/attention.py) must be
bit-comparable to the dense formulation — same masks, fp32 online softmax
is exact, only the loop order differs."""

import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # noqa: E402

from reval_tpu.ops import attention


def _dense(fn, *args, **kw):
    """Run ``fn`` with the block threshold lifted → dense path."""
    saved = attention._KEY_BLOCK
    attention._KEY_BLOCK = 1 << 30
    try:
        return fn(*args, **kw)
    finally:
        attention._KEY_BLOCK = saved


def rand(rng, *shape):
    return jnp.asarray(rng.standard_normal(shape), jnp.float32)


@pytest.mark.parametrize("window", [None, 100])
def test_blocked_prefill_matches_dense(window):
    rng = np.random.default_rng(0)
    b, t, h, h_kv, d = 2, 1024, 4, 2, 32     # t > _KEY_BLOCK → blocked
    q = rand(rng, b, t, h, d)
    k = rand(rng, b, t, h_kv, d)
    v = rand(rng, b, t, h_kv, d)
    pad = jnp.asarray([0, 700], jnp.int32)
    got = attention.prefill_attention(q, k, v, pad, window=window)
    ref = _dense(attention.prefill_attention, q, k, v, pad, window=window)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_blocked_prefill_nonmultiple_block():
    """Key length not a multiple of the block size: padding keys must be
    masked, not attended."""
    rng = np.random.default_rng(1)
    saved = attention._KEY_BLOCK
    attention._KEY_BLOCK = 100                # 384 keys → 4 blocks, 16 pad
    try:
        b, t, h, h_kv, d = 1, 384, 2, 2, 16
        q = rand(rng, b, t, h, d)
        k = rand(rng, b, t, h_kv, d)
        v = rand(rng, b, t, h_kv, d)
        pad = jnp.asarray([5], jnp.int32)
        got = attention.prefill_attention(q, k, v, pad)
        ref = _dense(attention.prefill_attention, q, k, v, pad)
        # pad-query rows (j < pad) have NO valid keys; both paths emit
        # meaningless values there that nothing downstream reads — compare
        # the real rows
        np.testing.assert_allclose(np.asarray(got)[:, 5:],
                                   np.asarray(ref)[:, 5:],
                                   rtol=2e-5, atol=2e-5)
    finally:
        attention._KEY_BLOCK = saved


@pytest.mark.parametrize("window", [None, 150])
def test_blocked_context_prefill_matches_dense(window):
    rng = np.random.default_rng(2)
    b, t, tc, h, h_kv, d = 2, 320, 256, 4, 2, 32   # t+tc > 512 → blocked
    q = rand(rng, b, t, h, d)
    k = rand(rng, b, t, h_kv, d)
    v = rand(rng, b, t, h_kv, d)
    ctx_k = rand(rng, 1, tc, h_kv, d)
    ctx_v = rand(rng, 1, tc, h_kv, d)
    pad = jnp.asarray([0, 77], jnp.int32)
    got = attention.context_prefill_attention(q, k, v, ctx_k, ctx_v, pad,
                                              window=window)
    ref = _dense(attention.context_prefill_attention, q, k, v, ctx_k, ctx_v,
                 pad, window=window)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_long_prefill_through_model_matches_short_path():
    """End-to-end: a >512-token prompt prefilled through the model gives
    the same last-token logits as the same tokens right-aligned into a
    longer dense computation run per-row."""
    from reval_tpu.models import ModelConfig, init_kv_cache, init_random_params, prefill

    cfg = ModelConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                      num_layers=2, num_heads=2, num_kv_heads=2, head_dim=16)
    params = init_random_params(cfg, seed=3, dtype="float32")
    rng = np.random.default_rng(3)
    t = 640                                   # > _KEY_BLOCK
    tokens = jnp.asarray(rng.integers(0, 64, (1, t)), jnp.int32)
    pad = jnp.zeros(1, jnp.int32)
    cache = init_kv_cache(cfg, 1, t, dtype=jnp.float32)
    logits_blocked, _ = prefill(params, cfg, tokens, pad, cache)
    logits_dense, _ = _dense(prefill, params, cfg, tokens, pad,
                             init_kv_cache(cfg, 1, t, dtype=jnp.float32))
    np.testing.assert_allclose(np.asarray(logits_blocked[:, -1]),
                               np.asarray(logits_dense[:, -1]),
                               rtol=2e-4, atol=2e-4)