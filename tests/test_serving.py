"""EngineServer ↔ HTTPClientBackend: the in-tree server topology (reference
start_server.sh + vLLM api_server equivalent) round-tripped over real HTTP
on an ephemeral port."""

import json
import urllib.request

import pytest

pytestmark = pytest.mark.slow  # noqa: E402

from reval_tpu.inference.client import HTTPClientBackend
from reval_tpu.serving import EngineServer


@pytest.fixture
def echo_server():
    calls = []

    def generate(prompts, *, max_tokens, temperature, stop):
        calls.append({"prompts": list(prompts), "max_tokens": max_tokens,
                      "temperature": temperature, "stop": stop})
        return [f"echo:{p[:10]}" for p in prompts]

    server = EngineServer(generate, model_id="tiny-echo", port=0).start()
    yield server, calls
    server.shutdown()


def test_models_route_and_client_handshake(echo_server):
    server, _ = echo_server
    client = HTTPClientBackend(model_id="local-name", port=server.port,
                               temp=0.0, prompt_type="direct")
    # the client adopts the server-side model id (reference inference.py:110-113)
    assert client._server_model == "tiny-echo"


def test_batch_rides_one_request(echo_server):
    server, calls = echo_server
    client = HTTPClientBackend(model_id="m", port=server.port, temp=0.0,
                               prompt_type="direct")
    prompts = ["prompt one", "prompt two", "prompt three"]
    outs = client.infer_many(prompts)
    assert outs == [f"echo:{p[:10]}" for p in prompts]
    batch_calls = [c for c in calls if len(c["prompts"]) == 3]
    assert len(batch_calls) == 1                 # one HTTP round trip
    call = batch_calls[0]
    # direct prompts: 256 max tokens, [/ANSWER] stop (reference inference.py:25,65)
    assert call["max_tokens"] == 256
    assert call["stop"] == ["[/ANSWER]"]
    assert call["temperature"] == 0.0


def test_single_prompt_and_unknown_route(echo_server):
    server, _ = echo_server
    client = HTTPClientBackend(model_id="m", port=server.port, temp=0.8,
                               prompt_type="cot")
    assert client.infer_one("hello world") == "echo:hello worl"
    with pytest.raises(urllib.error.HTTPError):
        urllib.request.urlopen(f"http://localhost:{server.port}/v1/nope")


def test_protocol_error_is_400_not_crash(echo_server):
    server, _ = echo_server
    req = urllib.request.Request(
        f"http://localhost:{server.port}/v1/completions",
        data=b'{"max_tokens": "not-an-int"}',
        headers={"Content-Type": "application/json"}, method="POST")
    with pytest.raises(urllib.error.HTTPError) as err:
        urllib.request.urlopen(req)
    assert err.value.code == 400
    # server still alive afterwards
    with urllib.request.urlopen(
            f"http://localhost:{server.port}/v1/models") as resp:
        assert json.load(resp)["data"][0]["id"] == "tiny-echo"


def test_real_engine_behind_server():
    """Tiny random model served end-to-end: server output must equal the
    engine called directly."""
    from reval_tpu.inference.tpu.engine import TPUEngine
    from reval_tpu.inference.tpu.tokenizer import ByteTokenizer
    from reval_tpu.models import ModelConfig, init_random_params
    from reval_tpu.serving.server import _engine_generate_fn

    cfg = ModelConfig(vocab_size=320, hidden_size=64, intermediate_size=128,
                      num_layers=2, num_heads=4, num_kv_heads=2, head_dim=128)
    params = init_random_params(cfg, seed=0, dtype="float32")
    engine = TPUEngine(params, cfg, ByteTokenizer(), batch_size=2,
                       max_seq_len=512)
    # 256 new tokens = the direct-prompt GenerationConfig the client uses
    direct = engine.generate(["def f(x):", "x = 1"], max_new_tokens=256,
                             temperature=0.0, stop=["[/ANSWER]"])
    server = EngineServer(_engine_generate_fn(engine), model_id="tiny", port=0).start()
    try:
        client = HTTPClientBackend(model_id="tiny", port=server.port,
                                   temp=0.0, prompt_type="direct")
        served = client.infer_many(["def f(x):", "x = 1"])
    finally:
        server.shutdown()
    assert served == direct


def test_engine_fault_returns_500():
    """Internal generate failures are server errors (500), not client
    errors — only malformed requests get 400 (advisor finding).  The body
    carries a stable code + request id, never the raw exception text
    (that stays in the server log)."""
    import json as _json
    import urllib.error
    import urllib.request

    from reval_tpu.serving.server import EngineServer

    def boom(prompts, **kw):
        raise RuntimeError("device fell over")

    srv = EngineServer(boom, model_id="m", port=0).start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/v1/completions",
            data=_json.dumps({"prompt": "x"}).encode(),
            headers={"Content-Type": "application/json"})
        try:
            urllib.request.urlopen(req, timeout=10)
            assert False, "expected HTTPError"
        except urllib.error.HTTPError as e:
            assert e.code == 500
            raw = e.read().decode()
            body = _json.loads(raw)
            assert body["error"]["code"] == "internal_error"
            assert body["error"]["request_id"]
            assert "device fell over" not in raw     # no leaked internals
    finally:
        srv.shutdown()


def _sse_events(resp):
    """Parse a Server-Sent-Events body into its data payloads."""
    import json as _json

    events = []
    for raw in resp.read().decode().split("\n\n"):
        raw = raw.strip()
        if not raw.startswith("data: "):
            continue
        payload = raw[len("data: "):]
        events.append("[DONE]" if payload == "[DONE]" else _json.loads(payload))
    return events


def test_streaming_sse_deltas_assemble_to_final_text():
    """stream=true yields per-chunk deltas that concatenate to the final
    text, then [DONE] (the protocol reference inference.py:115-131 speaks)."""
    import json as _json
    import urllib.request

    from reval_tpu.serving.server import EngineServer

    def fake_generate(prompts, *, max_tokens, temperature, stop,
                      on_progress=None):
        finals = []
        for i, _ in enumerate(prompts):
            text = f"answer-{i} [ANSWER] YES"
            if on_progress is not None:
                for cut in (8, 15, len(text)):
                    on_progress(i, text[:cut])
            finals.append(text)
        return finals

    srv = EngineServer(fake_generate, model_id="m", port=0).start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/v1/completions",
            data=_json.dumps({"prompt": ["a", "b"], "stream": True}).encode(),
            headers={"Content-Type": "application/json"})
        events = _sse_events(urllib.request.urlopen(req, timeout=30))
    finally:
        srv.shutdown()
    assert events[-1] == "[DONE]"
    texts = {0: "", 1: ""}
    finished = set()
    for ev in events[:-1]:
        choice = ev["choices"][0]
        texts[choice["index"]] += choice["text"]
        if choice["finish_reason"] == "stop":
            finished.add(choice["index"])
    assert texts == {0: "answer-0 [ANSWER] YES", 1: "answer-1 [ANSWER] YES"}
    assert finished == {0, 1}
    assert len(events) > 3        # actually incremental, not one blob


def test_streaming_from_real_paged_engine():
    """End to end: the paged engine's on_progress hook drives SSE and the
    streamed text equals the buffered result."""
    import json as _json
    import urllib.request

    from reval_tpu.inference.tpu.paged_engine import PagedTPUEngine
    from reval_tpu.inference.tpu.tokenizer import ByteTokenizer
    from reval_tpu.models import ModelConfig, init_random_params
    from reval_tpu.serving.server import EngineServer, _engine_generate_fn

    cfg = ModelConfig(vocab_size=256, hidden_size=64, intermediate_size=128,
                      num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16)
    params = init_random_params(cfg, seed=0, dtype="float32")
    engine = PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=2,
                            page_size=128, max_seq_len=256)
    want = engine.generate(["def f(x):"], max_new_tokens=48, temperature=0.0)
    srv = EngineServer(_engine_generate_fn(engine), model_id="tiny",
                       port=0).start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/v1/completions",
            data=_json.dumps({"prompt": "def f(x):", "stream": True,
                              "max_tokens": 48,
                              "temperature": 0.0}).encode(),
            headers={"Content-Type": "application/json"})
        events = _sse_events(urllib.request.urlopen(req, timeout=120))
    finally:
        srv.shutdown()
    assert events[-1] == "[DONE]"
    text = "".join(ev["choices"][0]["text"] for ev in events[:-1])
    assert text == want[0]
    assert len(events) >= 3       # several chunk boundaries fired


def test_hold_stop_prefix():
    from reval_tpu.serving.server import _hold_stop_prefix

    stop = ["[/ANSWER]"]
    assert _hold_stop_prefix("YES [", stop) == "YES "
    assert _hold_stop_prefix("YES [/ANSWE", stop) == "YES "
    assert _hold_stop_prefix("YES", stop) == "YES"         # no stop tail
    assert _hold_stop_prefix("a [x", stop) == "a [x"   # "[x" isn't a prefix
    assert _hold_stop_prefix("text", []) == "text"


def test_streaming_never_leaks_stop_prefix():
    """A chunk boundary mid-stop-string must not stream the partial stop
    and later retract it: the accumulated stream equals the final text
    and the finish event still arrives (review finding)."""
    import json as _json
    import urllib.request

    from reval_tpu.serving.server import EngineServer

    def fake_generate(prompts, *, max_tokens, temperature, stop,
                      on_progress=None):
        # chunk 1 ends mid-stop ("[/ANS"); chunk 2 completes the stop and
        # finalize truncates back to "YES "
        on_progress(0, "YES [/ANS")
        on_progress(0, "YES ")
        return ["YES "]

    srv = EngineServer(fake_generate, model_id="m", port=0).start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/v1/completions",
            data=_json.dumps({"prompt": "p", "stream": True,
                              "stop": ["[/ANSWER]"]}).encode(),
            headers={"Content-Type": "application/json"})
        events = _sse_events(urllib.request.urlopen(req, timeout=30))
    finally:
        srv.shutdown()
    assert events[-1] == "[DONE]"
    text = "".join(ev["choices"][0]["text"] for ev in events[:-1])
    assert text == "YES "                      # no "[/ANS" ever on the wire
    assert any(ev["choices"][0]["finish_reason"] == "stop"
               for ev in events[:-1])


def test_warmup_engine_compiles_and_serves():
    """warmup_engine runs the hot generation programs (short + long
    prompt, a full decode chunk) and the engine still serves normally."""
    from reval_tpu.inference.tpu.engine import TPUEngine
    from reval_tpu.inference.tpu.tokenizer import ByteTokenizer
    from reval_tpu.models import ModelConfig, init_random_params
    from reval_tpu.serving import warmup_engine

    cfg = ModelConfig(vocab_size=320, hidden_size=64, intermediate_size=128,
                      num_layers=2, num_heads=4, num_kv_heads=2, head_dim=32)
    params = init_random_params(cfg, seed=1, dtype="float32")
    engine = TPUEngine(params, cfg, ByteTokenizer(), batch_size=2,
                       max_seq_len=2048)
    secs = warmup_engine(engine)
    assert secs > 0
    outs = engine.generate(["def f(x):"], max_new_tokens=8, temperature=0.0)
    assert len(outs) == 1 and isinstance(outs[0], str)
