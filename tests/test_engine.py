"""TPUEngine generation-loop tests on a tiny random model (CPU, float32)."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # noqa: E402

import jax.numpy as jnp

from reval_tpu.inference.tpu.engine import TPUEngine, _bucket, truncate_at_stop
from reval_tpu.inference.tpu.tokenizer import ByteTokenizer
from reval_tpu.models import ModelConfig, init_random_params


@pytest.fixture(scope="module")
def engine():
    cfg = ModelConfig(
        vocab_size=ByteTokenizer.vocab_size, hidden_size=64, intermediate_size=128,
        num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
    )
    params = init_random_params(cfg, seed=0, dtype="float32")
    return TPUEngine(params, cfg, ByteTokenizer(), batch_size=4, max_seq_len=512)


class TestBucketing:
    def test_bucket_sizes(self):
        assert _bucket(1) == 64
        assert _bucket(64) == 64
        assert _bucket(65) == 128
        assert _bucket(1000) == 1024


class TestTruncate:
    def test_earliest_stop_wins(self):
        assert truncate_at_stop("abc[/ANSWER]def", ["[/ANSWER]"]) == "abc"
        assert truncate_at_stop("a STOP b HALT", ["HALT", "STOP"]) == "a "
        assert truncate_at_stop("no stops here", ["[/ANSWER]"]) == "no stops here"


class TestGeneration:
    def test_counts_and_budget(self, engine):
        outs = engine.generate(["hello", "world!"], max_new_tokens=12)
        assert len(outs) == 2
        assert all(isinstance(o, str) for o in outs)
        # byte tokenizer: ≤ 1 char per token
        assert all(len(o) <= 12 for o in outs)

    def test_order_preserved_across_batches(self, engine):
        # 6 prompts over batch_size=4 → two batches, sorted by length inside
        prompts = ["a" * n for n in (5, 90, 17, 33, 2, 70)]
        outs = engine.generate(prompts, max_new_tokens=4)
        assert len(outs) == 6
        # regenerate one-by-one; greedy must match the batched run
        for i in (0, 1, 4):
            solo = engine.generate([prompts[i]], max_new_tokens=4)[0]
            assert solo == outs[i], f"prompt {i} differs batched vs solo"

    def test_greedy_deterministic(self, engine):
        a = engine.generate(["determinism"], max_new_tokens=8)
        b = engine.generate(["determinism"], max_new_tokens=8)
        assert a == b

    def test_sampling_respects_seed_stream(self, engine):
        outs = engine.generate(["x"], max_new_tokens=8, temperature=1.0)
        assert len(outs[0]) <= 8

    def test_stats_accumulate(self, engine):
        before = engine.stats.prompts
        engine.generate(["count me"], max_new_tokens=2)
        assert engine.stats.prompts == before + 1
        assert engine.stats.generated_tokens > 0

    def test_empty_prompt_list(self, engine):
        assert engine.generate([], max_new_tokens=4) == []

    def test_long_prompt_clipped(self, engine):
        long_prompt = "y" * 600  # > max_seq_len - max_new_tokens
        outs = engine.generate([long_prompt], max_new_tokens=8)
        assert len(outs) == 1


class TestStopStrings:
    def test_stop_string_truncates(self, engine):
        """Force the stop text into the decode stream via a tokenizer shim."""

        class EchoTokenizer(ByteTokenizer):
            def decode(self, ids) -> str:
                # pretend the model emitted the stop string after 3 tokens
                base = super().decode(ids)
                return base[:3] + "[/ANSWER]" + base[3:] if len(base) > 3 else base

        shim = TPUEngine(engine.params, engine.cfg, EchoTokenizer(), batch_size=4,
                         max_seq_len=512)
        outs = shim.generate(["q"], max_new_tokens=64, stop=["[/ANSWER]"])
        assert outs[0].endswith("") and "[/ANSWER]" not in outs[0]
        assert len(outs[0]) == 3
        # early stop: far fewer than 64 tokens were generated
        assert shim.stats.generated_tokens < 64


class TestStopScanner:
    """Incremental stop detection must see exactly what a full decode sees,
    at O(chunk) cost — including stop strings straddling chunk boundaries."""

    def _scan_chunked(self, text: str, stop: list[str], chunk: int) -> bool:
        from reval_tpu.inference.tpu.engine import StopScanner

        tok = ByteTokenizer()
        ids = [i for i in tok.encode(text) if i != tok.bos_id]
        sc = StopScanner(tok, stop)
        hit = False
        for i in range(0, len(ids), chunk):
            hit = hit or sc.hit_new(ids[i: i + chunk])
        return hit

    def test_straddle_across_chunk_boundary(self):
        # "[/ANSWER]" split across every possible chunk-edge offset
        stop = "[/ANSWER]"
        for pad in range(1, 17):
            text = "x" * pad + stop + "tail"
            assert self._scan_chunked(text, [stop], chunk=8), pad

    def test_no_false_positive(self):
        assert not self._scan_chunked("[/ANSWE" + "R" * 0 + " nope]", ["[/ANSWER]"], 8)
        assert not self._scan_chunked("plain text " * 20, ["[/ANSWER]"], 8)

    def test_matches_full_rescan_on_random_splits(self):
        from reval_tpu.inference.tpu.engine import StopScanner, stop_hit

        tok = ByteTokenizer()
        rng = np.random.RandomState(0)
        for trial in range(50):
            n = int(rng.randint(5, 120))
            body = "".join(chr(int(c)) for c in rng.randint(97, 123, n))
            if trial % 3 == 0:
                pos = int(rng.randint(0, n))
                body = body[:pos] + "[/ANSWER]" + body[pos:]
            ids = [i for i in tok.encode(body) if i != tok.bos_id]
            sc = StopScanner(tok, ["[/ANSWER]"])
            hit = False
            i = 0
            while i < len(ids):
                step = int(rng.randint(1, 12))
                hit = hit or sc.hit_new(ids[i:i + step])
                i += step
            assert hit == stop_hit(tok, ids, ["[/ANSWER]"]), body

    def test_eos_detected_in_chunk(self):
        from reval_tpu.inference.tpu.engine import StopScanner

        tok = ByteTokenizer()
        sc = StopScanner(tok, [])
        assert not sc.hit_new([65, 66, 67])
        assert sc.hit_new([68, tok.eos_id])

    def test_multibyte_stop_straddles_window(self):
        """The overlap window is sized in stop-string BYTES: a multi-byte
        (e.g. Cyrillic) stop split one byte before its end must still hit."""
        stop = "СТОПСТОП"                        # 8 chars, 16 UTF-8 bytes
        for pad in range(1, 20):
            text = "x" * pad + stop + "tail"
            assert self._scan_chunked(text, [stop], chunk=8), pad

    def test_scan_cost_is_bounded(self):
        """The scanner must not re-decode the whole history every chunk."""
        from reval_tpu.inference.tpu.engine import StopScanner

        class CountingTok(ByteTokenizer):
            decoded_tokens = 0

            def decode(self, ids):
                CountingTok.decoded_tokens += len(ids)
                return super().decode(ids)

        tok = CountingTok()
        sc = StopScanner(tok, ["[/ANSWER]"])
        for _ in range(128):                     # 128 chunks of 8 tokens
            sc.hit_new([120] * 8)
        # full-rescan cost would be ~128*129/2*8 ≈ 66k; windowed is ~128*(8+17)
        assert CountingTok.decoded_tokens < 5000
