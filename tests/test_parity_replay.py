"""Scoring parity against the reference's committed run logs.

The reference ships gemma-1-2b-it MBPP logs with known metric trailers
(BASELINE.md lists all rows).  Replaying their generations through THIS
pipeline must reproduce every metric — the strongest end-to-end oracle for
prompt-planning order, probe counts, answer postprocessing, ground-truth
execution, and metric math (reference evaluation.py:239-261 coverage,
:429-432 path, :645-682 state).

The full 12-row sweep lives in tools/parity_replay.py; here a
representative row per task keeps suite time bounded.  Skipped when the
reference tree is not present.
"""

import glob
import os
import subprocess
import sys

import pytest

REFERENCE = "/root/reference/model_generations"
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.skipif(
    not glob.glob(os.path.join(REFERENCE, "*@*")),
    reason="reference run logs not available")


@pytest.mark.parametrize("task,prompt_type,temp,expect", [
    ("coverage", "direct", 0.0,
     {"total": 1009, "acc": 0.8672, "f1": 0.9286, "prec": 0.8780, "rec": 0.9853}),
    ("path", "cot", 0.0, {"total": 414, "acc": 0.0217, "correct": 9}),
    ("state", "direct", 0.0, {"total": 469, "acc": 0.4243, "correct": 199}),
])
def test_reference_metrics_reproduce(task, prompt_type, temp, expect, tmp_path):
    sys.path.insert(0, REPO)
    from tools.parity_replay import replay_one

    got = replay_one(task, prompt_type, temp, REFERENCE, "mbpp", str(tmp_path))
    assert got is not None, "reference log disappeared mid-run?"
    ours, ref = got
    for key, want in expect.items():
        assert round(float(ours[key]), 4) == want, (key, ours, ref)
        # and the reference trailer itself agrees with BASELINE.md
        assert round(float(ref[key]), 4) == want, (key, ref)


def test_full_sweep_cli_smoke():
    """The tool must at least import+arg-parse standalone (full sweep is a
    manual/CI-nightly run: `python tools/parity_replay.py`)."""
    r = subprocess.run([sys.executable, os.path.join(REPO, "tools", "parity_replay.py"),
                        "--reference", "/nonexistent"],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 2
    assert "no reference logs" in r.stdout


@pytest.mark.slow
def test_full_12_row_sweep_reproduces():
    """Round-4 verdict item 9: ALL committed reference trailers reproduce,
    not just the 3 representative rows above — the full sweep (coverage/
    path/output x direct/cot x temps, state direct+cot) as one slow-tier
    gate whenever the reference tree is present."""
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "parity_replay.py"),
         "--reference", REFERENCE],
        capture_output=True, text=True, timeout=1800, cwd=REPO)
    assert r.returncode == 0, f"stdout:\n{r.stdout[-4000:]}\nstderr:\n{r.stderr[-2000:]}"
    # the tool prints one "ok" line per replayed row; every committed row
    # must replay (a SKIP would silently shrink the oracle)
    lines = r.stdout.splitlines()
    ok = sum(1 for l in lines if l.startswith("ok"))
    skipped = [l for l in lines if l.startswith("SKIP")]
    assert ok >= 12 and not skipped, r.stdout[-4000:]
