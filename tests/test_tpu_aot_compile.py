"""Chip-free FULL XLA:TPU compilation of the real programs.

``tests/test_tpu_lowering.py`` (jax.export) runs the Pallas→Mosaic
lowering pass only; this tier goes all the way: a deviceless PJRT TPU
topology (``jax.experimental.topologies``) lets XLA produce the actual
TPU executable on any host — Mosaic codegen, VMEM allocation, GSPMD
partitioning and collective lowering for real chip targets — catching
the class of failures export cannot (kernel scratch that doesn't fit
VMEM, window scheduling, SPMD partitioning of the collectives the
multi-chip engines rely on).  Execution and timing still need silicon;
everything up to that runs here.

The flagship case compiles the EXACT bench decode-chunk program at
deepseek-coder-1.3b dims and asserts XLA's own memory analysis fits a
16 GB v5e next to the page pool — the strongest chip-free form of the
"does the bench config actually fit" claim.  Inputs are
ShapeDtypeStructs (no host weight materialisation), so the 1.3b compile
costs seconds of RAM, not gigabytes.
"""

from __future__ import annotations

from functools import partial

import numpy as np
import pytest

pytestmark = pytest.mark.slow

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _topology(name: str):
    from jax.experimental import topologies

    try:
        return topologies.get_topology_desc(platform="tpu",
                                            topology_name=name)
    except Exception as e:  # libtpu or the topology API unavailable
        pytest.skip(f"deviceless TPU topology {name!r} unavailable: {e}")


def _replicated(mesh: Mesh):
    return NamedSharding(mesh, P())


def _shaped(tree, sharding):
    """Map a pytree of arrays/ShapeDtypeStructs to sharded ShapeDtypeStructs."""
    return jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sharding),
        tree)


B, PAGE, NPAGES, SPAN, D = 4, 128, 24, 6, 128


def _kernel_operands(mesh, h, h_kv, store_dtype=jnp.bfloat16):
    rep = _replicated(mesh)
    q = jax.ShapeDtypeStruct((B, h, D), jnp.bfloat16, sharding=rep)
    kp = jax.ShapeDtypeStruct((NPAGES * PAGE, h_kv, D), store_dtype,
                              sharding=rep)
    bt = jax.ShapeDtypeStruct((B, SPAN), jnp.int32, sharding=rep)
    sl = jax.ShapeDtypeStruct((B,), jnp.int32, sharding=rep)
    return q, kp, bt, sl


@pytest.mark.parametrize("backend", ["pallas", "pallas_seq"])
@pytest.mark.parametrize("h,h_kv", [(16, 16), (16, 4)])
def test_kernel_aot_compiles_v5e(backend, h, h_kv):
    from reval_tpu.ops.pallas_attention import (
        paged_decode_attention_pallas, paged_decode_attention_pallas_seq)

    kernel = (paged_decode_attention_pallas if backend == "pallas"
              else paged_decode_attention_pallas_seq)
    topo = _topology("v5e:2x2")
    mesh = Mesh(np.array(topo.devices[:1]), ("x",))
    q, kp, bt, sl = _kernel_operands(mesh, h, h_kv)

    def f(q, kp, vp, bt, sl):
        return kernel(q, kp, vp, bt, sl, page_size=PAGE)

    compiled = jax.jit(f).lower(q, kp, kp, bt, sl).compile()
    assert compiled.memory_analysis().temp_size_in_bytes >= 0


@pytest.mark.parametrize("backend", ["pallas", "pallas_seq"])
def test_kernel_int8_pool_aot_compiles_v5e(backend):
    from reval_tpu.ops.pallas_attention import (
        paged_decode_attention_pallas, paged_decode_attention_pallas_seq)

    kernel = (paged_decode_attention_pallas if backend == "pallas"
              else paged_decode_attention_pallas_seq)
    topo = _topology("v5e:2x2")
    mesh = Mesh(np.array(topo.devices[:1]), ("x",))
    rep = _replicated(mesh)
    h, h_kv = 16, 4
    q, kp, bt, sl = _kernel_operands(mesh, h, h_kv, store_dtype=jnp.int8)
    sc = jax.ShapeDtypeStruct((NPAGES * PAGE, h_kv), jnp.float32, sharding=rep)

    def f(q, kp, vp, bt, sl, ks, vs):
        return kernel(q, kp, vp, bt, sl, page_size=PAGE,
                      k_scales=ks, v_scales=vs)

    compiled = jax.jit(f).lower(q, kp, kp, bt, sl, sc, sc).compile()
    assert compiled.memory_analysis().temp_size_in_bytes >= 0


def _flagship_model_parts(mesh, *, num_pages=241, kv_dtype=""):
    """1.3b-dims (cfg, params, cache) as replicated ShapeDtypeStructs —
    the model half of the EXACT bench default program (bench.py sizes
    the pool the same way)."""
    from reval_tpu.models import init_random_params, zoo_config
    from reval_tpu.models.paged import init_paged_cache

    cfg = zoo_config("deepseek-coder-1.3b")
    cfg.dtype = "bfloat16"
    rep = _replicated(mesh)
    params = _shaped(
        jax.eval_shape(lambda: init_random_params(cfg, seed=0,
                                                  dtype="bfloat16")), rep)
    cache = _shaped(
        jax.eval_shape(lambda: init_paged_cache(cfg, num_pages=num_pages,
                                                page_size=128,
                                                dtype=jnp.bfloat16,
                                                kv_dtype=kv_dtype)), rep)
    return cfg, params, cache


# the engine pow2-buckets the table span (paged_engine.pow2_bucket);
# bench prompts (~500 tok) + 256 new land in bucket 8 — span 7 would
# compile a program the runtime never executes
BENCH_SPAN = 8


def _flagship_chunk_args(mesh, *, slots=32, num_pages=241, kv_dtype=""):
    """The EXACT bench default decode-chunk operands at 1.3b dims."""
    cfg, params, cache = _flagship_model_parts(mesh, num_pages=num_pages,
                                               kv_dtype=kv_dtype)
    rep = _replicated(mesh)
    state = jax.ShapeDtypeStruct((slots, BENCH_SPAN + 5), jnp.int32,
                                 sharding=rep)
    sampling = jax.ShapeDtypeStruct((slots, 3), jnp.float32, sharding=rep)
    return cfg, params, state, cache, sampling


def test_flagship_decode_chunk_compiles_and_fits_v5e(monkeypatch):
    """The bench's hot program (32 decode steps, 32 slots, grid kernel)
    fully compiles for a v5e and — by XLA's own memory analysis, cache
    donated exactly as the engine donates it — fits the 16 GB chip."""
    from reval_tpu.inference.tpu.paged_engine import PagedTPUEngine

    monkeypatch.setenv("REVAL_TPU_PAGED_BACKEND", "pallas")
    # the dispatcher keys interpret on the RUNTIME backend (cpu here);
    # force the Mosaic kernel so this compiles the chip's program, not
    # the HLO emulation
    monkeypatch.setenv("REVAL_TPU_FORCE_MOSAIC", "1")
    topo = _topology("v5e:2x2")
    mesh = Mesh(np.array(topo.devices[:1]), ("x",))
    cfg, params, state, cache, sampling = _flagship_chunk_args(mesh)
    fn = partial(PagedTPUEngine._decode_chunk, cfg=cfg, steps=32,
                 filtered=False)
    compiled = (jax.jit(fn, donate_argnames=("cache",))
                .lower(params, state, cache, sampling).compile())
    ma = compiled.memory_analysis()
    live = ma.argument_size_in_bytes + ma.temp_size_in_bytes
    # donated cache aliases the output pool, so args+temps is the
    # footprint; 10% headroom mirrors the dryrun fits assertions
    assert live <= 16 * 1024**3 * 0.9, f"{live / 2**30:.2f} GiB"


def test_tp8_sharded_decode_chunk_compiles_v5e8(monkeypatch):
    """The tp=8 multi-chip decode program — GSPMD partitioning plus the
    all-reduces the tp engine relies on — compiles for a real 8-chip
    v5e target (the v5e-8 flagship shape, BASELINE configs[3])."""
    from reval_tpu.inference.tpu.paged_engine import PagedTPUEngine
    from reval_tpu.models import init_random_params, zoo_config
    from reval_tpu.models.paged import init_paged_cache
    from reval_tpu.parallel.sharding import paged_cache_spec, param_specs

    monkeypatch.setenv("REVAL_TPU_PAGED_BACKEND", "pallas")
    monkeypatch.setenv("REVAL_TPU_FORCE_MOSAIC", "1")
    topo = _topology("v5e:4x2")
    mesh = Mesh(np.array(topo.devices).reshape(8), ("tp",))
    rep = _replicated(mesh)

    cfg = zoo_config("deepseek-coder-1.3b")
    cfg.dtype = "bfloat16"
    specs = param_specs(
        jax.eval_shape(lambda: init_random_params(cfg, seed=0,
                                                  dtype="bfloat16")),
        cfg, mesh)
    params = jax.tree.map(
        lambda s, sp: jax.ShapeDtypeStruct(
            s.shape, s.dtype, sharding=NamedSharding(mesh, sp)),
        jax.eval_shape(lambda: init_random_params(cfg, seed=0,
                                                  dtype="bfloat16")),
        specs, is_leaf=lambda x: not isinstance(x, dict))
    cache_sharding = NamedSharding(mesh, paged_cache_spec(cfg, mesh))
    cache = jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(
            s.shape, s.dtype,
            sharding=cache_sharding if len(s.shape) == 3 else rep),
        jax.eval_shape(lambda: init_paged_cache(cfg, num_pages=241,
                                                page_size=128,
                                                dtype=jnp.bfloat16)))
    span, slots = 8, 32
    state = jax.ShapeDtypeStruct((slots, span + 5), jnp.int32, sharding=rep)
    sampling = jax.ShapeDtypeStruct((slots, 3), jnp.float32, sharding=rep)
    # mesh=... engages the tp-manual shard_map around the Mosaic kernel,
    # exactly as the engine's _jit_chunk partial does — without it GSPMD
    # must auto-partition the custom call and the real-chip compile fails
    fn = partial(PagedTPUEngine._decode_chunk, cfg=cfg, steps=8,
                 filtered=False, mesh=mesh)
    compiled = (jax.jit(fn, donate_argnames=("cache",))
                .lower(params, state, cache, sampling).compile())
    ma = compiled.memory_analysis()
    live = ma.argument_size_in_bytes + ma.temp_size_in_bytes
    # per-chip: weights/8 (~0.34 GB) + pool/8 + replicated state
    assert live <= 16 * 1024**3 * 0.9, f"{live / 2**30:.2f} GiB"


def test_ring_attention_sp8_compiles_v5e8():
    """Ring attention (sp=8 sequence parallelism): the ppermute ring must
    lower to real TPU collectives, not just run on the CPU mesh."""
    from reval_tpu.parallel import ring_attention_sharded
    from reval_tpu.parallel.mesh import make_mesh

    topo = _topology("v5e:4x2")
    mesh = make_mesh(sp=8, devices=np.array(topo.devices).reshape(8))
    sharded = NamedSharding(mesh, P(None, "sp"))
    q = jax.ShapeDtypeStruct((2, 16 * 8, 8, 64), jnp.bfloat16,
                             sharding=sharded)
    compiled = (jax.jit(partial(ring_attention_sharded, mesh=mesh))
                .lower(q, q, q).compile())
    assert compiled.memory_analysis().temp_size_in_bytes >= 0


@pytest.mark.parametrize("backend", ["pallas", "pallas_seq"])
def test_kernel_window_softcap_aot_compiles_v5e(backend):
    """gemma-2's sliding window + score softcap variants, through real
    Mosaic codegen (the export tier covers lowering only)."""
    from reval_tpu.ops.pallas_attention import (
        paged_decode_attention_pallas, paged_decode_attention_pallas_seq)

    kernel = (paged_decode_attention_pallas if backend == "pallas"
              else paged_decode_attention_pallas_seq)
    topo = _topology("v5e:2x2")
    mesh = Mesh(np.array(topo.devices[:1]), ("x",))
    q, kp, bt, sl = _kernel_operands(mesh, 16, 4)

    def f(q, kp, vp, bt, sl):
        return kernel(q, kp, vp, bt, sl, page_size=PAGE,
                      window=4096, softcap=50.0)

    compiled = jax.jit(f).lower(q, kp, kp, bt, sl).compile()
    assert compiled.memory_analysis().temp_size_in_bytes >= 0


def test_spec_chunk_compiles_v5e(monkeypatch):
    """The speculative draft+verify chunk program: its chip viability
    must be proven before any tunnel window runs the spec A/B
    (measure-or-cut, round-4 verdict item 3)."""
    from reval_tpu.inference.tpu.paged_engine import PagedTPUEngine

    monkeypatch.setenv("REVAL_TPU_PAGED_BACKEND", "pallas")
    monkeypatch.setenv("REVAL_TPU_FORCE_MOSAIC", "1")
    topo = _topology("v5e:2x2")
    mesh = Mesh(np.array(topo.devices[:1]), ("x",))
    rep = _replicated(mesh)
    cfg, params, cache = _flagship_model_parts(mesh)
    b, k = 32, 4
    hist_len = 2048                       # max_pages_per_seq * page_size
    last = jax.ShapeDtypeStruct((b, 1), jnp.int32, sharding=rep)
    hist = jax.ShapeDtypeStruct((b, hist_len), jnp.int32, sharding=rep)
    n_tok = jax.ShapeDtypeStruct((b,), jnp.int32, sharding=rep)
    tables = jax.ShapeDtypeStruct((b, BENCH_SPAN), jnp.int32, sharding=rep)
    lens = jax.ShapeDtypeStruct((b,), jnp.int32, sharding=rep)
    fn = partial(PagedTPUEngine._spec_chunk, cfg=cfg, rounds=8, k=k)
    compiled = (jax.jit(fn, donate_argnames=("cache",))
                .lower(params, last, hist, n_tok, tables, lens, cache)
                .compile())
    assert compiled.memory_analysis().temp_size_in_bytes >= 0


def test_34b_northstar_decode_compiles_and_fits_v5e8(monkeypatch):
    """The ACTUAL north-star program (CodeLlama-34B, tp=8, weight-only
    int4, paged decode — BASELINE configs[2]) compiled for a real 8-chip
    v5e target, with XLA's own per-chip memory analysis asserting it
    fits 16 GB.  The strongest chip-free form of the north-star claim:
    everything short of execution."""
    from reval_tpu.inference.tpu.paged_engine import PagedTPUEngine
    from reval_tpu.models import init_random_int4, zoo_config
    from reval_tpu.models.paged import init_paged_cache
    from reval_tpu.parallel.mesh import make_mesh
    from reval_tpu.parallel.sharding import paged_cache_spec, param_specs

    monkeypatch.setenv("REVAL_TPU_PAGED_BACKEND", "pallas")
    monkeypatch.setenv("REVAL_TPU_FORCE_MOSAIC", "1")
    topo = _topology("v5e:4x2")
    mesh = make_mesh(tp=8, devices=np.array(topo.devices).reshape(8))
    rep = _replicated(mesh)

    cfg = zoo_config("codellama/CodeLlama-34b-Instruct-hf")
    cfg.dtype = "bfloat16"
    shapes = jax.eval_shape(lambda: init_random_int4(cfg, seed=0, tp=8))
    specs = param_specs(shapes, cfg, mesh)
    params = jax.tree.map(
        lambda s, sp: jax.ShapeDtypeStruct(
            s.shape, s.dtype, sharding=NamedSharding(mesh, sp)),
        shapes, specs, is_leaf=lambda x: not isinstance(x, dict))
    cache_sharding = NamedSharding(mesh, paged_cache_spec(cfg, mesh))
    cache = jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(
            s.shape, s.dtype,
            sharding=cache_sharding if len(s.shape) == 3 else rep),
        jax.eval_shape(lambda: init_paged_cache(cfg, num_pages=48,
                                                page_size=128,
                                                dtype=jnp.bfloat16)))
    span, slots = 8, 4            # dryrun_34b_northstar geometry
    state = jax.ShapeDtypeStruct((slots, span + 5), jnp.int32, sharding=rep)
    sampling = jax.ShapeDtypeStruct((slots, 3), jnp.float32, sharding=rep)
    fn = partial(PagedTPUEngine._decode_chunk, cfg=cfg, steps=8,
                 filtered=False, mesh=mesh)
    compiled = (jax.jit(fn, donate_argnames=("cache",))
                .lower(params, state, cache, sampling).compile())
    ma = compiled.memory_analysis()
    live = ma.argument_size_in_bytes + ma.temp_size_in_bytes
    # XLA stores s4 packed on TPU, so this is the true per-chip resident
    # footprint of the int4 north star next to its page pool
    assert live <= 16 * 1024**3 * 0.9, f"{live / 2**30:.2f} GiB"


def _70b_pp_setup():
    """(mesh, cfg, params) for the v5p-16 pp=2 x tp=8 CodeLlama-70B
    program (BASELINE configs[4]) — shared by the prefill and decode
    compile tests so both certify the same sharding recipe."""
    from reval_tpu.models import init_random_int4, zoo_config
    from reval_tpu.parallel.mesh import make_mesh
    from reval_tpu.parallel.pipeline import pp_param_specs

    topo = _topology("v5p:4x2x2")
    mesh = make_mesh(pp=2, tp=8, devices=np.array(topo.devices).reshape(16))
    cfg = zoo_config("codellama/CodeLlama-70b-Instruct-hf")
    cfg.num_layers = 2
    cfg.dtype = "bfloat16"
    shapes = jax.eval_shape(lambda: init_random_int4(cfg, seed=0, tp=8))
    specs = pp_param_specs(shapes, cfg, mesh)
    params = jax.tree.map(
        lambda s, sp: jax.ShapeDtypeStruct(
            s.shape, s.dtype, sharding=NamedSharding(mesh, sp)),
        shapes, specs, is_leaf=lambda x: not isinstance(x, dict))
    return mesh, cfg, params


def test_70b_pp_tp_prefill_compiles_v5p16():
    """BASELINE configs[4]: the pipeline (pp=2 x tp=8) GPipe prefill at
    CodeLlama-70B widths (2 of 80 layers — compile cares about structure
    and width, not depth) compiles for a 16-device v5p target, including
    the shard_map collectives and int4 weight stacks."""
    from reval_tpu.models import init_random_int4, zoo_config
    from reval_tpu.models.model import KVCache
    from reval_tpu.parallel.pipeline import pipeline_prefill

    mesh, cfg, params = _70b_pp_setup()

    b, t, mb = 4, 128, 2
    n_micro = b // mb
    rows = b + mb                 # fill/drain scratch rows (pipeline.py)
    cache_shape = (cfg.num_layers, rows, t, cfg.num_kv_heads, cfg.head_dim)
    cache_sharding = NamedSharding(mesh, P("pp"))
    cache = KVCache(
        k=jax.ShapeDtypeStruct(cache_shape, jnp.bfloat16,
                               sharding=cache_sharding),
        v=jax.ShapeDtypeStruct(cache_shape, jnp.bfloat16,
                               sharding=cache_sharding))
    rep = NamedSharding(mesh, P())
    tokens = jax.ShapeDtypeStruct((b, t), jnp.int32, sharding=rep)
    pad = jax.ShapeDtypeStruct((b,), jnp.int32, sharding=rep)
    fn = partial(pipeline_prefill, cfg=cfg, mesh=mesh, n_micro=n_micro)
    compiled = jax.jit(fn).lower(params, tokens=tokens, pad_len=pad,
                                 cache=cache).compile()
    assert compiled.memory_analysis().temp_size_in_bytes >= 0


def test_70b_pp_tp_decode_compiles_v5p16():
    """The 70B token-ring DECODE chunk (the half of the pp path the
    prefill test above doesn't cover) compiles for the v5p-16 target."""
    from reval_tpu.inference.tpu.pp_engine import PipelinedTPUEngine
    from reval_tpu.models.model import KVCache

    mesh, cfg, params = _70b_pp_setup()

    b, t = 4, 256
    rows = b + b // 2             # engine's scratch-row convention
    cache_shape = (cfg.num_layers, rows, t, cfg.num_kv_heads, cfg.head_dim)
    cache_sharding = NamedSharding(mesh, P("pp"))
    cache = KVCache(
        k=jax.ShapeDtypeStruct(cache_shape, jnp.bfloat16,
                               sharding=cache_sharding),
        v=jax.ShapeDtypeStruct(cache_shape, jnp.bfloat16,
                               sharding=cache_sharding))
    rep = NamedSharding(mesh, P())
    first = jax.ShapeDtypeStruct((b, 1), jnp.int32, sharding=rep)
    pad = jax.ShapeDtypeStruct((b,), jnp.int32, sharding=rep)
    pos = jax.ShapeDtypeStruct((), jnp.int32, sharding=rep)   # scalar bucket pos
    temp = jax.ShapeDtypeStruct((), jnp.float32, sharding=rep)
    key = jax.ShapeDtypeStruct((2,), jnp.uint32, sharding=rep)
    # the engine ALWAYS passes [B] top_k/top_p arrays (engine.py
    # _generate_batch) — omitting them would certify an executable with
    # two fewer parameters than the one the runtime dispatches
    kf = jax.ShapeDtypeStruct((b,), jnp.int32, sharding=rep)
    pf = jax.ShapeDtypeStruct((b,), jnp.float32, sharding=rep)
    fn = partial(PipelinedTPUEngine._pp_decode_chunk, cfg=cfg, mesh=mesh,
                 steps=4, filtered=False)
    compiled = (jax.jit(fn, donate_argnames=("cache",))
                .lower(params, first, pad, cache, pos, temp, key, kf, pf)
                .compile())
    assert compiled.memory_analysis().temp_size_in_bytes >= 0
