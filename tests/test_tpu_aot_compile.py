"""Chip-free FULL XLA:TPU compilation of the real programs.

``tests/test_tpu_lowering.py`` (jax.export) runs the Pallas→Mosaic
lowering pass only; this tier goes all the way: a deviceless PJRT TPU
topology (``jax.experimental.topologies``) lets XLA produce the actual
TPU executable on any host — Mosaic codegen, VMEM allocation, GSPMD
partitioning and collective lowering for real chip targets — catching
the class of failures export cannot (kernel scratch that doesn't fit
VMEM, window scheduling, SPMD partitioning of the collectives the
multi-chip engines rely on).  Execution and timing still need silicon;
everything up to that runs here.

The engine/bench programs come from ``tools/aot_programs`` — the SAME
builders ``tools/aot_warm.py`` (compile-cache pre-warming) and
``tools/aot_certify.py`` (the recorded-evidence artifact) use, so the
shapes asserted here are the shapes warmed and certified.  Inputs are
ShapeDtypeStructs (no host weight materialisation), so the 1.3b/34B
compiles cost seconds of RAM, not gigabytes.
"""

from __future__ import annotations

import os
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools import aot_programs


def _topology(name: str):
    try:
        return aot_programs.topology(name)
    except Exception as e:  # libtpu or the topology API unavailable
        pytest.skip(f"deviceless TPU topology {name!r} unavailable: {e}")


@pytest.fixture(autouse=True)
def _restore_backend_env():
    """The shared builders set REVAL_TPU_PAGED_BACKEND / FORCE_MOSAIC
    process-wide (their standalone-tool semantics); scope that to each
    test so a later CPU test doesn't dispatch Mosaic uninterpreted."""
    keys = ("REVAL_TPU_PAGED_BACKEND", "REVAL_TPU_FORCE_MOSAIC")
    saved = {k: os.environ.get(k) for k in keys}
    yield
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


def _build(builder, probe: str = "v5e:2x2", **kw):
    """Run a shared program builder, skipping (not failing) when the
    deviceless topology itself is unavailable on this host.  ``probe``
    must name the topology the builder actually requests — probing v5e
    for a v5p-target builder would fail instead of skip on hosts whose
    libtpu resolves one family but not the other."""
    _topology(probe)
    return builder(**kw)


# -- raw kernels (structure variants not covered by the engine programs) ----

B, PAGE, NPAGES, SPAN, D = 4, 128, 24, 6, 128


def _kernel_operands(mesh, h, h_kv, store_dtype=jnp.bfloat16):
    rep = aot_programs._replicated(mesh)
    q = jax.ShapeDtypeStruct((B, h, D), jnp.bfloat16, sharding=rep)
    kp = jax.ShapeDtypeStruct((NPAGES * PAGE, h_kv, D), store_dtype,
                              sharding=rep)
    bt = jax.ShapeDtypeStruct((B, SPAN), jnp.int32, sharding=rep)
    sl = jax.ShapeDtypeStruct((B,), jnp.int32, sharding=rep)
    return q, kp, bt, sl


def _kernel_for(backend):
    from reval_tpu.ops.pallas_attention import (
        paged_decode_attention_pallas, paged_decode_attention_pallas_seq)

    return (paged_decode_attention_pallas if backend == "pallas"
            else paged_decode_attention_pallas_seq)


@pytest.mark.parametrize("dot_mode", ["swap", "wide"])
@pytest.mark.parametrize("backend", ["pallas", "pallas_seq"])
@pytest.mark.parametrize("h,h_kv", [(16, 16), (16, 4)])
def test_kernel_aot_compiles_v5e(backend, h, h_kv, dot_mode):
    kernel = _kernel_for(backend)
    topo = _topology("v5e:2x2")
    mesh = Mesh(np.array(topo.devices[:1]), ("x",))
    q, kp, bt, sl = _kernel_operands(mesh, h, h_kv)

    def f(q, kp, vp, bt, sl):
        return kernel(q, kp, vp, bt, sl, page_size=PAGE, dot_mode=dot_mode)

    compiled = jax.jit(f).lower(q, kp, kp, bt, sl).compile()
    assert compiled.memory_analysis().temp_size_in_bytes >= 0


@pytest.mark.parametrize("backend", ["pallas", "pallas_seq"])
def test_kernel_int8_pool_aot_compiles_v5e(backend):
    kernel = _kernel_for(backend)
    topo = _topology("v5e:2x2")
    mesh = Mesh(np.array(topo.devices[:1]), ("x",))
    rep = aot_programs._replicated(mesh)
    h, h_kv = 16, 4
    q, kp, bt, sl = _kernel_operands(mesh, h, h_kv, store_dtype=jnp.int8)
    sc = jax.ShapeDtypeStruct((NPAGES * PAGE, h_kv), jnp.float32, sharding=rep)

    def f(q, kp, vp, bt, sl, ks, vs):
        return kernel(q, kp, vp, bt, sl, page_size=PAGE,
                      k_scales=ks, v_scales=vs)

    compiled = jax.jit(f).lower(q, kp, kp, bt, sl, sc, sc).compile()
    assert compiled.memory_analysis().temp_size_in_bytes >= 0


@pytest.mark.parametrize("backend", ["pallas", "pallas_seq"])
def test_kernel_window_softcap_aot_compiles_v5e(backend):
    """gemma-2's sliding window + score softcap variants, through real
    Mosaic codegen (the export tier covers lowering only)."""
    kernel = _kernel_for(backend)
    topo = _topology("v5e:2x2")
    mesh = Mesh(np.array(topo.devices[:1]), ("x",))
    q, kp, bt, sl = _kernel_operands(mesh, 16, 4)

    def f(q, kp, vp, bt, sl):
        return kernel(q, kp, vp, bt, sl, page_size=PAGE,
                      window=4096, softcap=50.0)

    compiled = jax.jit(f).lower(q, kp, kp, bt, sl).compile()
    assert compiled.memory_analysis().temp_size_in_bytes >= 0


# -- engine/bench programs (shared builders) --------------------------------

def test_flagship_decode_chunk_compiles_and_fits_v5e():
    """The bench's hot program (32 decode steps, 32 slots, grid kernel)
    fully compiles for a v5e and — by XLA's own memory analysis, cache
    donated exactly as the engine donates it — fits the 16 GB chip."""
    compiled = _build(aot_programs.compile_flagship_chunk)
    ma = compiled.memory_analysis()
    live = ma.argument_size_in_bytes + ma.temp_size_in_bytes
    # donated cache aliases the output pool, so args+temps is the
    # footprint; 10% headroom mirrors the dryrun fits assertions
    assert live <= 16 * 1024**3 * 0.9, f"{live / 2**30:.2f} GiB"


def test_tp8_sharded_decode_chunk_compiles_v5e8():
    """The tp=8 multi-chip decode program — GSPMD partitioning plus the
    tp-manual Mosaic shard_map the tp engine relies on — compiles for a
    real 8-chip v5e target (the v5e-8 flagship shape)."""
    compiled = _build(aot_programs.compile_tp8_flagship_chunk)
    ma = compiled.memory_analysis()
    live = ma.argument_size_in_bytes + ma.temp_size_in_bytes
    assert live <= 16 * 1024**3 * 0.9, f"{live / 2**30:.2f} GiB"


def test_34b_northstar_decode_compiles_and_fits_v5e8():
    """The ACTUAL north-star program (CodeLlama-34B, tp=8, weight-only
    int4, paged decode — BASELINE configs[2]) compiled for a real 8-chip
    v5e target, with XLA's own per-chip memory analysis asserting it
    fits 16 GB.  The strongest chip-free form of the north-star claim:
    everything short of execution."""
    compiled = _build(aot_programs.compile_34b_northstar_chunk)
    ma = compiled.memory_analysis()
    live = ma.argument_size_in_bytes + ma.temp_size_in_bytes
    # XLA stores s4 packed on TPU, so this is the true per-chip resident
    # footprint of the int4 north star next to its page pool
    assert live <= 16 * 1024**3 * 0.9, f"{live / 2**30:.2f} GiB"


def test_ring_attention_sp8_compiles_v5e8():
    """Ring attention (sp=8 sequence parallelism): the ppermute ring must
    lower to real TPU collectives, not just run on the CPU mesh."""
    from functools import partial

    from jax.sharding import NamedSharding, PartitionSpec as P

    from reval_tpu.parallel import ring_attention_sharded
    from reval_tpu.parallel.mesh import make_mesh

    topo = _topology("v5e:4x2")
    mesh = make_mesh(sp=8, devices=np.array(topo.devices).reshape(8))
    sharded = NamedSharding(mesh, P(None, "sp"))
    q = jax.ShapeDtypeStruct((2, 16 * 8, 8, 64), jnp.bfloat16,
                             sharding=sharded)
    compiled = (jax.jit(partial(ring_attention_sharded, mesh=mesh))
                .lower(q, q, q).compile())
    assert compiled.memory_analysis().temp_size_in_bytes >= 0


def test_70b_pp_tp_prefill_compiles_v5p16():
    """BASELINE configs[4]: the pipeline (pp=2 x tp=8) GPipe prefill at
    CodeLlama-70B widths compiles for a 16-device v5p target, including
    the shard_map collectives and int4 weight stacks."""
    compiled = _build(aot_programs.compile_70b_prefill, probe="v5p:4x2x2")
    assert compiled.memory_analysis().temp_size_in_bytes >= 0


def test_70b_pp_tp_decode_compiles_v5p16():
    """The 70B token-ring DECODE chunk (the half of the pp path the
    prefill test above doesn't cover), with the exact runtime signature
    (the engine always passes [B] top_k/top_p rows)."""
    compiled = _build(aot_programs.compile_70b_decode, probe="v5p:4x2x2")
    assert compiled.memory_analysis().temp_size_in_bytes >= 0


def test_prefill_commit_programs_compile_v5e():
    """The paged engine's prefill + page-commit programs at the bench's
    admission-wave row buckets."""
    pre, commit = _build(aot_programs.compile_prefill_commit, rows=4)
    assert pre.memory_analysis().temp_size_in_bytes >= 0
    assert commit.memory_analysis().temp_size_in_bytes >= 0
